"""Chaos smoke: drive one in-process worker through every fault-injection
point and assert it ends healthy with zero lost envelopes.

    JAX_PLATFORMS=cpu python tools/chaos_smoke.py            # all scenarios
    JAX_PLATFORMS=cpu python tools/chaos_smoke.py drop_submit sigterm_drain

Each scenario stands up a fresh FakeHive + Worker (echo jobs — no model
weights, no compile), arms exactly one failure via chiaswarm_tpu.faults,
and checks the lifecycle contract the fault-tolerance layer promises:
every accepted job's envelope is eventually DELIVERED to the hive or
SPOOLED on disk, and the worker's /healthz view ends "ok". Exit code =
number of failed scenarios. tests/test_chaos_smoke.py runs the same
scenarios under pytest so CI exercises every injection point.
"""

from __future__ import annotations

import asyncio
import contextlib
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from chiaswarm_tpu import faults  # noqa: E402
from chiaswarm_tpu.chips.allocator import SliceAllocator  # noqa: E402
from chiaswarm_tpu.settings import Settings  # noqa: E402
from chiaswarm_tpu.worker import Worker  # noqa: E402
from tests.fake_hive import FakeHive  # noqa: E402


@contextlib.contextmanager
def fast_mode():
    """Shrink the production cadences so a scenario runs in seconds."""
    import chiaswarm_tpu.outbox as ob
    import chiaswarm_tpu.worker as wm

    saved = (wm.POLL_SECONDS, wm.ERROR_BACKOFF_SECONDS,
             ob.BACKOFF_BASE_S, ob.BACKOFF_CAP_S)
    wm.POLL_SECONDS, wm.ERROR_BACKOFF_SECONDS = 0.05, 0.2
    ob.BACKOFF_BASE_S, ob.BACKOFF_CAP_S = 0.02, 0.1
    try:
        yield
    finally:
        (wm.POLL_SECONDS, wm.ERROR_BACKOFF_SECONDS,
         ob.BACKOFF_BASE_S, ob.BACKOFF_CAP_S) = saved


def _echo(job_id: str) -> dict:
    return {"id": job_id, "workflow": "echo", "model_name": "none",
            "prompt": job_id}


def _settings(**overrides) -> Settings:
    base = dict(sdaas_token="chaos", worker_name="chaos-worker",
                metrics_port=0)
    base.update(overrides)
    return Settings(**base)


async def _spin(predicate, timeout_s: float = 30.0, step: float = 0.02) -> bool:
    deadline = asyncio.get_running_loop().time() + timeout_s
    while asyncio.get_running_loop().time() < deadline:
        if predicate():
            return True
        await asyncio.sleep(step)
    return predicate()


class ScenarioFailure(AssertionError):
    pass


def _check(condition, detail: str) -> None:
    if not condition:
        raise ScenarioFailure(detail)


# --- scenarios -------------------------------------------------------------


async def scenario_drop_submit() -> str:
    """Submit drop x3 (worker side): outbox retries until the hive ACKs."""
    faults.configure("drop_submit=3")
    hive = await FakeHive().start()
    hive.add_job(_echo("chaos-drop"))
    w = Worker(settings=_settings(),
               allocator=SliceAllocator(chips_per_job=0), hive_uri=hive.uri)
    runner = asyncio.create_task(w.run())
    try:
        results = await hive.wait_for_results(1, timeout=30.0)
        _check(results[0]["id"] == "chaos-drop", "wrong envelope delivered")
        _check(await _spin(lambda: w.outbox.depth == 0),
               f"outbox not drained (depth {w.outbox.depth})")
        _check(faults.get_plan().fired("drop_submit") == 3,
               "injection did not fire 3 times")
        _check(w._health()["status"] == "ok", "worker not healthy at end")
    finally:
        w.stop()
        await asyncio.wait_for(runner, 10)
        await hive.stop()
    return "delivered after 3 injected submit drops"


async def scenario_hive_connection_drop() -> str:
    """Connection severed hive-side x2: same zero-loss contract."""
    faults.configure("")
    hive = await FakeHive().start()
    hive.drop_results_times = 2
    hive.slow_results_s = 0.05  # latency on top of the drops
    hive.add_job(_echo("chaos-sever"))
    w = Worker(settings=_settings(),
               allocator=SliceAllocator(chips_per_job=0), hive_uri=hive.uri)
    runner = asyncio.create_task(w.run())
    try:
        results = await hive.wait_for_results(1, timeout=30.0)
        _check(results[0]["id"] == "chaos-sever", "wrong envelope delivered")
        _check(await _spin(lambda: w.outbox.depth == 0),
               "outbox not drained")
        _check(w._health()["status"] == "ok", "worker not healthy at end")
    finally:
        w.stop()
        await asyncio.wait_for(runner, 10)
        await hive.stop()
    return "delivered through 2 severed hive connections"


async def scenario_hang_watchdog() -> str:
    """Hang-in-denoise: watchdog envelope at the deadline, slice
    quarantined, probed, and back in service — no restart."""
    faults.configure("hang_denoise=1", hang_timeout_s=60.0)
    hive = await FakeHive().start()
    hive.add_job(_echo("chaos-hang"))
    w = Worker(
        settings=_settings(job_deadline_s=0.4, job_deadline_compile_scale=1.0,
                           quarantine_probe_grace_s=10.0),
        allocator=SliceAllocator(chips_per_job=0), hive_uri=hive.uri)
    runner = asyncio.create_task(w.run())
    try:
        results = await hive.wait_for_results(1, timeout=30.0)
        _check("watchdog" in results[0]["pipeline_config"].get("error", ""),
               "expected the watchdog's transient-error envelope")
        _check(not results[0].get("fatal_error"),
               "watchdog envelope must stay transient (resubmittable)")
        _check(w.allocator.quarantined_count == 1, "slice not quarantined")
        _check(w._health()["status"] == "degraded",
               "healthz must report the quarantine")
        faults.get_plan().release_hangs()
        _check(await _spin(lambda: w.allocator.quarantined_count == 0),
               "slice never reinstated after the hang cleared")
        hive.add_job(_echo("chaos-after"))
        await hive.wait_for_results(2, timeout=30.0)
        _check(await _spin(lambda: w._health()["status"] == "ok"),
               "worker not healthy after recovery")
    finally:
        w.stop()
        await asyncio.wait_for(runner, 10)
        await hive.stop()
    return "watchdog expiry -> quarantine -> probe -> back in service"


async def scenario_kill_before_ack() -> str:
    """Crash between hive ack and outbox unlink; a second worker
    generation redelivers from the spool."""
    faults.configure("kill_before_ack=1")
    hive = await FakeHive().start()
    hive.add_job(_echo("chaos-ack"))
    settings = _settings()
    w1 = Worker(settings=settings,
                allocator=SliceAllocator(chips_per_job=0), hive_uri=hive.uri)
    runner = asyncio.create_task(w1.run())
    try:
        await hive.wait_for_results(1, timeout=30.0)
        _check(w1.outbox.depth == 1,
               "envelope must stay spooled through the simulated crash")
    finally:
        w1.stop()
        await asyncio.wait_for(runner, 10)

    faults.configure("")
    hive.results.clear()
    w2 = Worker(settings=settings,
                allocator=SliceAllocator(chips_per_job=0), hive_uri=hive.uri)
    runner = asyncio.create_task(w2.run())
    try:
        results = await hive.wait_for_results(1, timeout=30.0)
        _check(results[0]["id"] == "chaos-ack", "redelivery lost the job id")
        _check(await _spin(lambda: w2.outbox.depth == 0),
               "spool entry not unlinked after the real ack")
        _check(w2._health()["status"] == "ok", "worker not healthy at end")
    finally:
        w2.stop()
        await asyncio.wait_for(runner, 10)
        await hive.stop()
    return "crash-before-ack redelivered by the next worker generation"


async def scenario_sigterm_drain() -> str:
    """stop(drain=True) with a job mid-execution: the pass finishes, the
    outbox flushes, the worker exits on its own."""
    faults.configure("hang_denoise=1", hang_timeout_s=60.0)
    hive = await FakeHive().start()
    hive.add_job(_echo("chaos-drain"))
    w = Worker(settings=_settings(job_deadline_s=0.0, drain_deadline_s=30.0),
               allocator=SliceAllocator(chips_per_job=0), hive_uri=hive.uri)
    runner = asyncio.create_task(w.run())
    try:
        plan = faults.get_plan()
        _check(await _spin(lambda: plan.hanging == 1),
               "job never started executing")
        w.stop(drain=True)  # what the SIGTERM handler calls
        await asyncio.sleep(0.3)
        _check(not runner.done(), "worker must drain, not die, mid-job")
        _check(hive.results == [], "nothing should be delivered yet")
        plan.release_hangs()
        await asyncio.wait_for(runner, 30.0)
        _check([r["id"] for r in hive.results] == ["chaos-drain"],
               "in-flight job lost across the drain")
        _check(w.outbox.depth == 0, "outbox not flushed before exit")
    finally:
        if not runner.done():
            w.stop()
            await asyncio.wait_for(runner, 10)
        await hive.stop()
    return "drain finished the in-flight job and flushed the outbox"


async def scenario_hive_lease_takeover() -> str:
    """Hive-side fault tolerance (the real coordinator, not the fake):
    worker 1 takes a lease and dies mid-job; the hive's reaper expires
    the lease and re-queues, and worker 2 completes the SAME job."""
    from chiaswarm_tpu import telemetry
    from chiaswarm_tpu.hive_server import LocalSwarm
    from chiaswarm_tpu.settings import Settings

    faults.configure("hang_denoise=1", hang_timeout_s=120.0)
    expired = telemetry.REGISTRY.get(
        "swarm_hive_leases_expired_total") or telemetry.counter(
        "swarm_hive_leases_expired_total", "")
    expired_before = expired.value()
    settings = Settings(sdaas_token="chaos", hive_port=0, metrics_port=0,
                        hive_lease_deadline_s=1.0, hive_max_redeliveries=2)
    swarm = LocalSwarm(n_workers=1, chips_per_job=0, settings=settings)
    plan = faults.get_plan()
    async with swarm:
        job_id = await swarm.submit(_echo("chaos-takeover"))
        _check(await _spin(lambda: plan.hanging == 1),
               "worker 1 never started the job")
        # worker 1 dies mid-lease, the job unfinished
        await swarm.stop_worker(swarm.workers[0])
        faults.configure("")  # worker 2 must run clean
        _check(await _spin(lambda: expired.value() > expired_before, 15.0),
               "hive never expired the dead worker's lease")
        swarm.add_worker("chaos-second-worker")
        status = await swarm.wait_done(job_id, timeout=30.0)
        _check(status["completed_by"] == "chaos-second-worker",
               f"job finished by {status['completed_by']}, not the "
               "takeover worker")
        _check(status["attempts"] >= 2,
               "job should record the redelivery attempt")
        plan.release_hangs()  # unstick worker 1's orphaned thread
    return "dead worker's lease expired; second worker completed the job"


async def scenario_gang_member_lost() -> str:
    """Gang dispatch under failure (ISSUE 9): a worker takes a 4-job
    GANG in one /work reply and dies mid-denoise holding all four
    leases. Lease expiry must redeliver every member (possibly as
    singles — a gang is a dispatch-time grouping, not a lifecycle), a
    second worker must complete all four, every job settles EXACTLY
    once, and each trace timeline is gap-free across the loss."""
    from chiaswarm_tpu import telemetry
    from chiaswarm_tpu.hive_server import LocalSwarm
    from chiaswarm_tpu.hive_server.trace import build_trace, trace_missing
    from chiaswarm_tpu.settings import Settings

    def gang_job(i: int) -> dict:
        return {"id": f"chaos-gang-{i}", "workflow": "txt2img",
                "model_name": "stabilityai/stable-diffusion-2-1",
                "prompt": f"gang member {i}", "seed": 7000 + i,
                "height": 64, "width": 64, "num_inference_steps": 2,
                "parameters": {"test_tiny_model": True}}

    # worker 1 hangs at the denoise entry (before any compile), so the
    # scenario's only real pipeline work is worker 2's clean gang pass
    faults.configure("hang_denoise=1", hang_timeout_s=120.0)
    results_ok = telemetry.REGISTRY.get(
        "swarm_hive_results_total") or telemetry.counter(
        "swarm_hive_results_total", "", ("status",))
    ok_before = results_ok.value(status="ok")
    settings = Settings(sdaas_token="chaos", hive_port=0, metrics_port=0,
                        hive_lease_deadline_s=1.0, hive_max_redeliveries=3,
                        hive_max_jobs_per_poll=8, hive_gang_max=8)
    swarm = LocalSwarm(n_workers=0, chips_per_job=0, settings=settings)
    plan = faults.get_plan()
    async with swarm:
        # all four queued BEFORE the first worker exists: its first poll
        # deterministically receives them as ONE gang
        ids = [await swarm.submit(gang_job(i)) for i in range(4)]
        swarm.add_worker("chaos-gang-worker-1")
        _check(await _spin(lambda: plan.hanging == 1),
               "worker 1 never started the gang")
        records = [swarm.hive.queue.records[j] for j in ids]
        dispatches = [e for r in records for e in r.timeline
                      if e.get("event") == "dispatch"]
        _check(len(dispatches) == 4
               and all(e.get("gang_size") == 4 for e in dispatches)
               and len({e.get("gang") for e in dispatches}) == 1,
               f"jobs were not dispatched as one 4-gang: {dispatches}")
        # the gang holder dies mid-denoise with all 4 leases
        await swarm.stop_worker(swarm.workers[0])
        faults.configure("")  # the takeover worker runs clean
        _check(await _spin(
            lambda: all(r.state == "queued" for r in records), 15.0),
            "lease expiry never redelivered every gang member")
        # the 1 s deadline existed to expire the DEAD holder fast; the
        # takeover worker legitimately pays a cold tiny-model compile
        # (tens of seconds), which must not read as a second loss
        swarm.hive.leases.deadline_s = 600.0
        swarm.add_worker("chaos-gang-worker-2")
        for job_id in ids:
            status = await swarm.wait_done(job_id, timeout=240.0)
            _check(status["status"] == "done",
                   f"gang member {job_id} lost across the worker death")
            _check(status["attempts"] >= 2,
                   f"{job_id} should record the redelivery attempt")
        # exactly-once settle: one ok ACK per member, and the late
        # worker-1 envelopes (it died before producing any) never land
        _check(results_ok.value(status="ok") == ok_before + 4,
               "members did not settle exactly once")
        for job_id in ids:
            trace = build_trace(swarm.hive.queue.records[job_id],
                                swarm.hive.queue.clock.wall())
            missing = trace_missing(trace)
            _check(not missing,
                   f"{job_id} timeline incomplete: {missing}")
            kinds = [e["event"] for e in trace["events"]]
            _check(kinds.count("settle") == 1
                   and kinds.count("redeliver") == 1,
                   f"{job_id} timeline duplicated/lost events: {kinds}")
        plan.release_hangs()  # unstick worker 1's orphaned thread
    return ("4-job gang redelivered after its holder died mid-denoise; "
            "all members settled exactly once with gap-free traces")


async def scenario_cancel_mid_denoise() -> str:
    """End-to-end cancellation (ISSUE 10): a worker holds a 4-job GANG
    mid-denoise (hang_denoise pins it at the pass entry); the submitter
    cancels ONE member. The cancel-only heartbeat poll delivers the
    revocation to the busy worker, the chunked denoise drops the row at
    its first chunk boundary, the remaining three members complete with
    correct outputs, the slice is reclaimed, swarm_hive_results_total
    proves exactly-once settle (ok delta == 3, zero for the cancelled
    member), and every timeline is trace_missing-clean."""
    import os

    from chiaswarm_tpu import cancel as cancel_mod
    from chiaswarm_tpu import telemetry
    from chiaswarm_tpu.hive_server import LocalSwarm
    from chiaswarm_tpu.hive_server.trace import build_trace, trace_missing
    from chiaswarm_tpu.settings import Settings

    def gang_job(i: int) -> dict:
        return {"id": f"chaos-cancel-{i}", "workflow": "txt2img",
                "model_name": "stabilityai/stable-diffusion-2-1",
                "prompt": f"cancel member {i}", "seed": 8000 + i,
                "height": 64, "width": 64, "num_inference_steps": 2,
                "parameters": {"test_tiny_model": True}}

    faults.configure("hang_denoise=1", hang_timeout_s=120.0)
    results_ok = telemetry.REGISTRY.get(
        "swarm_hive_results_total") or telemetry.counter(
        "swarm_hive_results_total", "", ("status",))
    ok_before = results_ok.value(status="ok")
    cancelled_disp_before = results_ok.value(status="cancelled")
    # chunk the denoise so the cancel lands at a chunk boundary, not
    # after the full pass (the pipeline reads the knob per pass via
    # load_settings, so the env override reaches in-process workers)
    os.environ["CHIASWARM_DENOISE_CHUNK_STEPS"] = "1"
    settings = Settings(sdaas_token="chaos", hive_port=0, metrics_port=0,
                        hive_lease_deadline_s=600.0,
                        hive_max_jobs_per_poll=8, hive_gang_max=8,
                        denoise_chunk_steps=1)
    swarm = LocalSwarm(n_workers=0, chips_per_job=0, settings=settings)
    plan = faults.get_plan()
    try:
        async with swarm:
            ids = [await swarm.submit(gang_job(i)) for i in range(4)]
            worker = swarm.add_worker("chaos-cancel-worker")
            _check(await _spin(lambda: plan.hanging == 1),
                   "worker never started the gang")
            # cancel ONE member while the gang is mid-denoise
            victim = ids[1]
            ack = await swarm.cancel(victim)
            _check(ack["cancelled"] is True and ack["status"] == "cancelled",
                   f"cancel not acknowledged: {ack}")
            _check(swarm.hive.leases.get(victim) is None,
                   "hive did not revoke the victim's lease")
            # the cancel-only heartbeat must reach the BUSY worker (its
            # only slice is executing, yet it keeps polling) and mark
            # the executing row's cancel token
            _check(await _spin(lambda: cancel_mod.cancelled(victim), 15.0),
                   "revocation never reached the executing worker")
            plan.release_hangs()
            # survivors complete; the victim's row was dropped at the
            # first chunk boundary and no envelope was ever produced
            for job_id in ids:
                if job_id == victim:
                    continue
                status = await swarm.wait_done(job_id, timeout=240.0)
                _check(status["status"] == "done",
                       f"surviving member {job_id} did not complete")
                _check(status["result"] is not None,
                       f"surviving member {job_id} has no result")
            victim_status = await swarm.job_status(victim)
            _check(victim_status["status"] == "cancelled",
                   f"victim ended {victim_status['status']}, not cancelled")
            # exactly-once settle: 3 ok ACKs, and the victim NEVER
            # settled (no late envelope — the row was dropped, and the
            # disposition counter stays untouched)
            _check(results_ok.value(status="ok") == ok_before + 3,
                   "surviving members did not settle exactly once")
            _check(results_ok.value(
                       status="cancelled") == cancelled_disp_before,
                   "a cancelled-member envelope reached the hive")
            # the slice is reclaimed: the worker serves a fresh job
            _check(await _spin(
                lambda: worker.allocator.has_free_slice(), 30.0),
                "slice never freed after the cancelled pass")
            follow_up = await swarm.submit(gang_job(9))
            status = await swarm.wait_done(follow_up, timeout=240.0)
            _check(status["status"] == "done",
                   "follow-up job failed on the reclaimed slice")
            # timelines: survivors are complete end-to-end; the victim's
            # terminal event is its cancel, WAL-durable
            for job_id in ids:
                record = swarm.hive.queue.records[job_id]
                trace = build_trace(record, swarm.hive.queue.clock.wall())
                if job_id == victim:
                    _check(trace["events"][-1]["event"] == "cancel"
                           and trace["open"] is False,
                           f"victim timeline not cancel-terminal: "
                           f"{[e['event'] for e in trace['events']]}")
                else:
                    missing = trace_missing(trace)
                    _check(not missing,
                           f"{job_id} timeline incomplete: {missing}")
            _check(worker.outbox.depth == 0,
                   "outbox should hold nothing for a dropped row")
    finally:
        os.environ.pop("CHIASWARM_DENOISE_CHUNK_STEPS", None)
        plan.release_hangs()
    return ("gang member cancelled mid-denoise: row dropped at a chunk "
            "boundary, 3 batchmates settled exactly once, slice reclaimed")


async def scenario_hive_crash_recovery() -> str:
    """Hive durability (ISSUE 6 acceptance): a hive subprocess holding
    one QUEUED and one LEASED job is killed with SIGKILL; a restart over
    the same $SDAAS_ROOT replays the WAL to the pre-crash state, the
    dead lessee's lease expires, and a pristine worker completes BOTH
    jobs — zero lost."""
    import json
    import os
    import socket
    import subprocess

    import aiohttp

    faults.configure("")
    token = "chaos"
    repo = str(pathlib.Path(__file__).resolve().parent.parent)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ, SDAAS_TOKEN=token,
               CHIASWARM_HIVE_PORT=str(port),
               CHIASWARM_HIVE_LEASE_DEADLINE_S="1.0",
               PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
    uri = f"http://127.0.0.1:{port}"
    headers = {"Authorization": f"Bearer {token}",
               "Content-type": "application/json"}

    def spawn() -> subprocess.Popen:
        return subprocess.Popen(
            [sys.executable, "-m", "chiaswarm_tpu.hive_server"],
            cwd=repo, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)

    async def wait_up(session) -> bool:
        for _ in range(200):
            try:
                async with session.get(f"{uri}/healthz") as r:
                    if r.status in (200, 503):
                        return True
            except aiohttp.ClientError:
                pass
            await asyncio.sleep(0.1)
        return False

    procs = [spawn()]
    w = runner = None
    try:
        async with aiohttp.ClientSession() as session:

            async def submit(job: dict) -> str:
                async with session.post(f"{uri}/api/jobs",
                                        data=json.dumps(job),
                                        headers=headers) as r:
                    _check(r.status == 200, f"submit failed: {r.status}")
                    return (await r.json())["id"]

            async def status(job_id: str) -> dict:
                async with session.get(f"{uri}/api/jobs/{job_id}",
                                       headers=headers) as r:
                    _check(r.status == 200,
                           f"job {job_id} lost across the restart "
                           f"(HTTP {r.status})")
                    return await r.json()

            _check(await wait_up(session),
                   "hive subprocess never answered /healthz")
            leased_id = await submit(_echo("chaos-crash-leased"))
            queued_id = await submit(_echo("chaos-crash-queued"))
            # a doomed worker takes ONE lease (budget 1), then dies with
            # the hive — neither ever gets to report anything
            async with session.get(
                    f"{uri}/api/work",
                    params={"worker_version": "0.1.0",
                            "worker_name": "doomed-w"},
                    headers=headers) as r:
                jobs = (await r.json())["jobs"]
            _check([j["id"] for j in jobs] == [leased_id],
                   f"expected exactly the first job leased, got {jobs}")

            procs[0].kill()  # SIGKILL: no drain, no atexit, no flush
            procs[0].wait()
            procs.append(spawn())  # same $SDAAS_ROOT, same port
            _check(await wait_up(session),
                   "restarted hive never answered /healthz")

            st = await status(leased_id)
            _check(st["status"] in ("leased", "queued"),
                   f"leased job recovered as {st['status']}")
            _check(st["worker"] == "doomed-w",
                   "recovered lease lost its lessee attribution")
            _check((await status(queued_id))["status"] == "queued",
                   "queued job not recovered as queued")

            # a pristine worker against the restarted hive: the dead
            # lessee's recovered lease expires (fresh 1s deadline) and
            # both jobs complete
            w = Worker(settings=_settings(),
                       allocator=SliceAllocator(chips_per_job=0),
                       hive_uri=f"{uri}/api")
            runner = asyncio.create_task(w.run())

            deadline = asyncio.get_running_loop().time() + 30.0
            finals = {}
            while len(finals) < 2:
                _check(asyncio.get_running_loop().time() < deadline,
                       f"jobs not completed after restart: {finals}")
                for job_id in (queued_id, leased_id):
                    if job_id not in finals:
                        st = await status(job_id)
                        _check(st["status"] != "failed",
                               f"job {job_id} failed: {st['error']}")
                        if st["status"] == "done":
                            finals[job_id] = st
                await asyncio.sleep(0.1)
            _check(finals[leased_id]["completed_by"] == "chaos-worker",
                   "leased job not completed by the takeover worker")
            _check(finals[leased_id]["attempts"] >= 2,
                   "redelivery attempt not recorded across the restart")

            # ISSUE 8: the redelivered job answers with ONE complete
            # timeline spanning the SIGKILL — both dispatch attempts,
            # the redelivery, the settle, nothing duplicated
            from chiaswarm_tpu.hive_server.trace import trace_missing

            async with session.get(f"{uri}/api/jobs/{leased_id}/trace",
                                   headers=headers) as r:
                _check(r.status == 200,
                       f"trace endpoint answered {r.status}")
                trace = await r.json()
            missing = trace_missing(trace)
            _check(not missing,
                   f"timeline incomplete across SIGKILL: {missing}")
            kinds = [e["event"] for e in trace["events"]]
            _check(kinds.count("redeliver") == 1
                   and kinds.count("settle") == 1,
                   f"timeline duplicated/lost events: {kinds}")
    finally:
        if w is not None:
            w.stop()
        if runner is not None:
            await asyncio.wait_for(
                asyncio.gather(runner, return_exceptions=True), 10)
        for proc in procs:
            proc.kill()
            proc.wait()
    return ("SIGKILL'd hive recovered 2 jobs from the WAL; the leased one "
            "was redelivered to a pristine worker")


async def scenario_usage_survives_restart() -> str:
    """Fleet accounting (ISSUE 11 acceptance): N jobs settle across two
    tenants; the hive is SIGKILLed and restarted over the same
    $SDAAS_ROOT; the per-tenant ledger (GET /api/usage) must come back
    BIT-IDENTICAL from the WAL replay — and identical again on a
    promoted standby that replicated the same stream. The ledger is
    derived from the journaled records, so this pins that derivation
    end to end."""
    import dataclasses
    import json
    import os
    import socket
    import subprocess

    import aiohttp

    from chiaswarm_tpu.hive_server.replication import StandbyHive

    faults.configure("")
    token = "chaos"
    repo = str(pathlib.Path(__file__).resolve().parent.parent)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ, SDAAS_TOKEN=token,
               CHIASWARM_HIVE_PORT=str(port),
               PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
    uri = f"http://127.0.0.1:{port}"
    headers = {"Authorization": f"Bearer {token}",
               "Content-type": "application/json"}

    def spawn() -> subprocess.Popen:
        return subprocess.Popen(
            [sys.executable, "-m", "chiaswarm_tpu.hive_server"],
            cwd=repo, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)

    async def wait_up(session) -> bool:
        for _ in range(200):
            try:
                async with session.get(f"{uri}/healthz") as r:
                    if r.status in (200, 503):
                        return True
            except aiohttp.ClientError:
                pass
            await asyncio.sleep(0.1)
        return False

    procs = [spawn()]
    w = runner = standby = None
    try:
        async with aiohttp.ClientSession() as session:
            _check(await wait_up(session),
                   "hive subprocess never answered /healthz")
            jobs = [dict(_echo(f"chaos-usage-{i}"),
                         tenant="tenant-a" if i % 2 == 0 else "tenant-b")
                    for i in range(4)]
            for job in jobs:
                async with session.post(f"{uri}/api/jobs",
                                        data=json.dumps(job),
                                        headers=headers) as r:
                    _check(r.status == 200, f"submit failed: {r.status}")

            # a real worker settles all four (its envelopes carry the
            # stage timings the ledger attributes from)
            w = Worker(settings=_settings(),
                       allocator=SliceAllocator(chips_per_job=0),
                       hive_uri=f"{uri}/api")
            runner = asyncio.create_task(w.run())

            async def all_done() -> bool:
                for job in jobs:
                    async with session.get(
                            f"{uri}/api/jobs/{job['id']}",
                            headers=headers) as r:
                        if r.status != 200 or (
                                await r.json())["status"] != "done":
                            return False
                return True

            deadline = asyncio.get_running_loop().time() + 30.0
            while not await all_done():
                _check(asyncio.get_running_loop().time() < deadline,
                       "jobs never settled before the crash")
                await asyncio.sleep(0.1)
            w.stop()
            await asyncio.wait_for(
                asyncio.gather(runner, return_exceptions=True), 10)
            w = runner = None

            async def usage() -> dict:
                async with session.get(f"{uri}/api/usage",
                                       headers=headers) as r:
                    _check(r.status == 200, f"/api/usage -> {r.status}")
                    return await r.json()

            before = await usage()
            _check(before["tenants"].get("tenant-a", {}).get("jobs") == 2
                   and before["tenants"].get("tenant-b", {}).get("jobs") == 2,
                   f"pre-crash ledger wrong: {before['tenants']}")
            _check(before["totals"]["chip_seconds"] > 0,
                   "pre-crash ledger attributed zero chip-seconds")
            _check(before["totals"]["fallback_jobs"] == 0,
                   "real envelopes must not take the fallback path")

            procs[0].kill()  # SIGKILL: no drain, no flush
            procs[0].wait()
            procs.append(spawn())  # same $SDAAS_ROOT, same port
            _check(await wait_up(session),
                   "restarted hive never answered /healthz")
            after = await usage()
            _check(after["tenants"] == before["tenants"],
                   f"per-tenant ledger drifted across SIGKILL recovery:\n"
                   f"  before: {before['tenants']}\n"
                   f"  after:  {after['tenants']}")
            _check(after["totals"] == before["totals"],
                   "ledger totals drifted across SIGKILL recovery")

            # a standby replicating the restarted primary's WAL stream
            # must derive the very same ledger — and keep it once
            # promoted over the (killed) primary
            standby = StandbyHive(
                dataclasses.replace(
                    _settings(), hive_port=0,
                    hive_wal_dir="wal_usage_standby"),
                primary_uri=uri, port=0)
            await standby.server.start()
            await standby.sync_once()
            procs[1].kill()
            procs[1].wait()
            await standby.promote()
            async with session.get(f"{standby.api_uri}/usage",
                                   headers=headers) as r:
                _check(r.status == 200,
                       f"promoted standby /api/usage -> {r.status}")
                promoted = await r.json()
            _check(promoted["tenants"] == before["tenants"],
                   f"promoted standby's ledger drifted:\n"
                   f"  primary:  {before['tenants']}\n"
                   f"  promoted: {promoted['tenants']}")
            _check(promoted["totals"] == before["totals"],
                   "promoted standby's ledger totals drifted")
    finally:
        if w is not None:
            w.stop()
        if runner is not None:
            await asyncio.wait_for(
                asyncio.gather(runner, return_exceptions=True), 10)
        if standby is not None:
            await standby.stop()
        for proc in procs:
            proc.kill()
            proc.wait()
    return ("per-tenant ledger bit-identical across a hive SIGKILL "
            "restart AND on a promoted standby (4 jobs, 2 tenants)")


async def scenario_hive_failover() -> str:
    """Hive replication (ISSUE 7 acceptance): the primary dies mid-lease
    with queued jobs; the WAL-shipped standby health-checks it dead and
    promotes itself; a pristine worker fails over and completes EVERY
    job — zero lost."""
    from chiaswarm_tpu.hive_server import LocalSwarm
    from chiaswarm_tpu.settings import Settings

    faults.configure("hang_denoise=1", hang_timeout_s=120.0)
    settings = Settings(
        sdaas_token="chaos", hive_port=0, metrics_port=0,
        hive_lease_deadline_s=1.0, hive_max_redeliveries=3,
        hive_failover_grace_s=0.5, hive_replication_poll_s=0.05,
        hive_wal_dir="wal_failover")  # isolated from other scenarios
    swarm = LocalSwarm(n_workers=1, chips_per_job=0, settings=settings,
                       standby=True)
    plan = faults.get_plan()
    async with swarm:
        ids = [await swarm.submit(_echo(f"chaos-fo-{i}")) for i in range(3)]
        # worker 1 leases one job and hangs in it — 'mid-lease'
        _check(await _spin(lambda: plan.hanging == 1),
               "worker 1 never started a job")
        # the standby must hold the whole backlog before the crash
        _check(await _spin(lambda: all(
            j in swarm.standby.server.queue.records for j in ids), 10.0),
            "standby never replicated the backlog")
        await swarm.stop_worker(swarm.workers[0])
        faults.configure("")  # the takeover worker runs clean
        await swarm.kill_primary()
        _check(await _spin(lambda: swarm.standby.promoted, 20.0),
               "standby never promoted itself after the primary died")
        _check(swarm.standby.server.epoch >= 1,
               "promotion did not bump the fencing epoch")
        takeover = swarm.add_worker("chaos-failover-worker")
        for job_id in ids:
            status = await swarm.wait_done(job_id, timeout=30.0)
            _check(status["status"] == "done",
                   f"job {job_id} lost across the failover")
        _check(takeover.hive.failovers >= 1,
               "takeover worker never pinned away from the dead primary")
        plan.release_hangs()  # unstick worker 1's orphaned thread
    return ("primary killed mid-lease; standby promoted at epoch "
            f"{swarm.standby.server.epoch}; all {len(ids)} jobs completed")


async def scenario_hive_split_brain_fenced() -> str:
    """Split-brain fencing: the deposed primary is revived from its own
    WAL still believing it holds the lease; a worker that has seen the
    promoted hive's epoch POSTs its result there first — the stale-epoch
    ACK is refused with a 409, the client fails over, and the job is
    settled EXACTLY once (on the promoted hive)."""
    import dataclasses
    import json

    import aiohttp

    from chiaswarm_tpu import telemetry
    from chiaswarm_tpu.hive import HiveClient
    from chiaswarm_tpu.hive_server import HiveServer
    from chiaswarm_tpu.hive_server.replication import StandbyHive
    from chiaswarm_tpu.settings import Settings

    faults.configure("")
    base = Settings(sdaas_token="chaos", hive_port=0, metrics_port=0,
                    hive_wal_dir="wal_splitbrain_p")
    stale = telemetry.REGISTRY.get(
        "swarm_hive_stale_epoch_total") or telemetry.counter(
        "swarm_hive_stale_epoch_total", "")
    stale_before = stale.value()
    primary = await HiveServer(base, port=0).start()
    primary_port = primary.port
    primary_api = primary.api_uri
    standby = StandbyHive(
        dataclasses.replace(base, hive_wal_dir="wal_splitbrain_s"),
        primary_uri=primary.uri, port=0)
    await standby.server.start()
    revived = None
    clients = []
    headers = {"Authorization": "Bearer chaos",
               "Content-type": "application/json"}
    try:
        async with aiohttp.ClientSession() as session:
            async with session.post(
                    f"{primary_api}/jobs",
                    data=json.dumps(_echo("chaos-splitbrain")),
                    headers=headers) as r:
                _check(r.status == 200, f"submit failed: {r.status}")
            async with session.get(
                    f"{primary_api}/work",
                    params={"worker_version": "0.1.0",
                            "worker_name": "doomed-w"},
                    headers=headers) as r:
                jobs = (await r.json())["jobs"]
            _check([j["id"] for j in jobs] == ["chaos-splitbrain"],
                   "the worker never leased the job")
        await standby.sync_once()
        _check(standby.server.queue.records[
            "chaos-splitbrain"].state == "leased",
            "standby did not replicate the lease")
        # the primary 'dies'; the standby promotes (epoch 1)
        await primary.stop()
        await standby.promote()
        # the worker polls, fails over to the promoted hive, and learns
        # the new epoch from its answer headers
        worker_settings = Settings(sdaas_token="chaos",
                                   worker_name="doomed-w",
                                   hive_failover_errors=1)
        poller = HiveClient(worker_settings,
                            [f"http://127.0.0.1:{primary_port}/api",
                             standby.api_uri])
        clients.append(poller)
        try:
            await poller.ask_for_work({"chips": 1})
        except Exception:
            pass  # dead primary: transport error advances the pin
        await poller.ask_for_work({"chips": 1})
        _check(poller.epoch >= 1,
               "worker never learned the promoted hive's epoch")
        # the deposed primary is revived over its own WAL, epoch 0,
        # still believing it holds the lease
        revived = await HiveServer(base, port=primary_port).start()
        _check(revived.epoch == 0, "revived primary epoch should be 0")
        _check(revived.queue.records["chaos-splitbrain"].state == "leased",
               "revived primary lost its pre-crash lease state")
        # the same worker process delivers its result; its endpoint list
        # starts at the revived primary (a fresh delivery client models
        # the outbox redelivery path hitting the old pin first)
        deliverer = HiveClient(worker_settings,
                               [f"http://127.0.0.1:{primary_port}/api",
                                standby.api_uri])
        deliverer.epoch = poller.epoch  # one process, one epoch view
        clients.append(deliverer)
        envelope = {"id": "chaos-splitbrain", "artifacts": {},
                    "nsfw": False, "worker_version": "0.1.0",
                    "pipeline_config": {}, "worker_name": "doomed-w"}
        ack = await deliverer.submit_result(envelope)
        _check(isinstance(ack, dict), "delivery never ACKed")
        _check(stale.value() > stale_before,
               "the stale-epoch refusal was never observed")
        _check(deliverer.failovers >= 1,
               "the delivery client never failed over off the deposed "
               "primary")
        _check(revived.queue.records["chaos-splitbrain"].state == "leased",
               "DOUBLE-SETTLE: the deposed primary accepted the stale ACK")
        _check(standby.server.queue.records[
            "chaos-splitbrain"].state == "done",
            "the promoted hive never settled the job")
    finally:
        for client in clients:
            await client.close()
        if revived is not None:
            await revived.stop()
        await standby.stop()
        await primary.stop()
    return ("deposed primary refused the stale-epoch ACK (409); the job "
            "settled exactly once on the promoted hive")


async def scenario_resume_after_worker_kill() -> str:
    """Preemption tolerance end to end (ISSUE 18 acceptance): worker 1
    runs a chunked, checkpoint-armed denoise and dies mid-pass PAST a
    shipped checkpoint (hang_after_checkpoint pins its executor thread
    right after the upload; the worker is then stopped without drain —
    the hive-visible signature of a SIGKILL). The hive itself is then
    SIGKILLed and restarted over the same $SDAAS_ROOT, so the checkpoint
    must survive via WAL replay + spool. A second worker receives the
    redelivery WITH the `resume` offer, rehydrates at step >= K, finishes
    only the remaining steps, and settles EXACTLY once with a gap-free
    trace timeline and the `resumed` billing stamp."""
    import json
    import os
    import socket
    import subprocess

    import aiohttp

    from chiaswarm_tpu import telemetry
    from chiaswarm_tpu.hive_server.trace import trace_missing

    STEPS, CKPT_EVERY = 6, 2
    faults.configure("hang_after_checkpoint=1", hang_timeout_s=600.0)
    resumed_metric = telemetry.REGISTRY.get(
        "swarm_resume_total") or telemetry.counter(
        "swarm_resume_total", "", ("outcome",))
    resumed_before = resumed_metric.value(outcome="resumed")
    token = "chaos"
    repo = str(pathlib.Path(__file__).resolve().parent.parent)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    base_env = dict(os.environ, SDAAS_TOKEN=token,
                    CHIASWARM_HIVE_PORT=str(port),
                    PYTHONPATH=repo + os.pathsep
                    + os.environ.get("PYTHONPATH", ""))
    uri = f"http://127.0.0.1:{port}"
    headers = {"Authorization": f"Bearer {token}",
               "Content-type": "application/json"}

    def spawn(lease_deadline_s: str) -> subprocess.Popen:
        env = dict(base_env,
                   CHIASWARM_HIVE_LEASE_DEADLINE_S=lease_deadline_s,
                   CHIASWARM_HIVE_MAX_REDELIVERIES="5")
        return subprocess.Popen(
            [sys.executable, "-m", "chiaswarm_tpu.hive_server"],
            cwd=repo, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)

    async def wait_up(session) -> bool:
        for _ in range(200):
            try:
                async with session.get(f"{uri}/healthz") as r:
                    if r.status in (200, 503):
                        return True
            except aiohttp.ClientError:
                pass
            await asyncio.sleep(0.1)
        return False

    def worker_settings(name: str) -> Settings:
        # chunk every step, checkpoint every 2 chunks -> first durable
        # checkpoint at step K=2 of 6; the env twin reaches the pipeline,
        # which reads the chunk knob per pass via load_settings
        return _settings(worker_name=name, denoise_chunk_steps=1,
                         checkpoint_every_chunks=CKPT_EVERY)

    # the first worker's lease must survive its cold tiny-model compile
    # (tens of seconds); redelivery speed only matters after the restart
    os.environ["CHIASWARM_DENOISE_CHUNK_STEPS"] = "1"
    procs = [spawn("600.0")]
    w1 = w2 = runner1 = runner2 = None
    plan = faults.get_plan()
    try:
        async with aiohttp.ClientSession() as session:
            _check(await wait_up(session),
                   "hive subprocess never answered /healthz")
            job = {"id": "chaos-resume", "workflow": "txt2img",
                   "model_name": "stabilityai/stable-diffusion-2-1",
                   "prompt": "preempted mid-denoise", "seed": 9100,
                   "height": 64, "width": 64,
                   "num_inference_steps": STEPS,
                   "parameters": {"test_tiny_model": True}}
            async with session.post(f"{uri}/api/jobs",
                                    data=json.dumps(job),
                                    headers=headers) as r:
                _check(r.status == 200, f"submit failed: {r.status}")

            w1 = Worker(settings=worker_settings("chaos-ckpt-w1"),
                        allocator=SliceAllocator(chips_per_job=0),
                        hive_uri=f"{uri}/api")
            runner1 = asyncio.create_task(w1.run())

            async def trace_events() -> list[dict]:
                async with session.get(f"{uri}/api/jobs/chaos-resume/trace",
                                       headers=headers) as r:
                    if r.status != 200:
                        return []
                    return (await r.json()).get("events", [])

            async def checkpoint_durable() -> bool:
                return any(e["event"] == "checkpoint"
                           for e in await trace_events())

            deadline = asyncio.get_running_loop().time() + 240.0
            while not (await checkpoint_durable() and plan.hanging == 1):
                _check(asyncio.get_running_loop().time() < deadline,
                       "worker 1 never shipped a checkpoint (fired="
                       f"{plan.fired('hang_after_checkpoint')})")
                await asyncio.sleep(0.1)

            # worker 1 'dies' holding the lease: stopped without drain,
            # its denoise thread pinned mid-pass — from the hive's side
            # this is a SIGKILL (no result, no release, lease orphaned)
            w1.stop()
            await asyncio.wait_for(
                asyncio.gather(runner1, return_exceptions=True), 10)
            runner1 = None

            procs[0].kill()  # SIGKILL: no drain, no flush
            procs[0].wait()
            # restart over the same $SDAAS_ROOT with a short deadline so
            # the recovered (dead) lease expires promptly
            procs.append(spawn("3.0"))
            _check(await wait_up(session),
                   "restarted hive never answered /healthz")
            _check(await checkpoint_durable(),
                   "checkpoint event lost across the hive SIGKILL (WAL)")

            # worker 2 rehydrates and finishes only the remaining steps
            w2 = Worker(settings=worker_settings("chaos-ckpt-w2"),
                        allocator=SliceAllocator(chips_per_job=0),
                        hive_uri=f"{uri}/api")
            runner2 = asyncio.create_task(w2.run())

            status = {}
            deadline = asyncio.get_running_loop().time() + 240.0
            while status.get("status") != "done":
                _check(asyncio.get_running_loop().time() < deadline,
                       f"job never settled after the restart: {status}")
                _check(status.get("status") != "failed",
                       f"job failed: {status.get('error')}")
                async with session.get(f"{uri}/api/jobs/chaos-resume",
                                       headers=headers) as r:
                    _check(r.status == 200,
                           f"job lost across the restart ({r.status})")
                    status = await r.json()
                await asyncio.sleep(0.1)

            _check(status["completed_by"] == "chaos-ckpt-w2",
                   f"finished by {status['completed_by']}, not worker 2")
            _check(status["attempts"] >= 2,
                   "the redelivery attempt was not recorded")
            resumed = status["result"]["pipeline_config"].get("resumed")
            _check(resumed is not None,
                   "resumed billing stamp missing from the envelope")
            _check(resumed["from_step"] >= CKPT_EVERY,
                   f"resumed from step {resumed['from_step']}, before the "
                   f"checkpointed step {CKPT_EVERY}")
            _check(resumed["from_step"] + resumed["recomputed_steps"]
                   == STEPS, f"billing stamp inconsistent: {resumed}")
            _check(resumed_metric.value(
                       outcome="resumed") == resumed_before + 1,
                   "worker 2 never counted a rehydrated pass")

            # exactly-once settle with a gap-free timeline spanning the
            # worker death, the hive SIGKILL, and the resume
            async with session.get(f"{uri}/api/jobs/chaos-resume/trace",
                                   headers=headers) as r:
                _check(r.status == 200, f"trace answered {r.status}")
                trace = await r.json()
            missing = trace_missing(trace)
            _check(not missing, f"timeline incomplete: {missing}")
            kinds = [e["event"] for e in trace["events"]]
            _check(kinds.count("settle") == 1,
                   f"job did not settle exactly once: {kinds}")
            _check(kinds.count("checkpoint") >= 1
                   and kinds.count("resume_offer") >= 1
                   and kinds.count("redeliver") >= 1,
                   f"checkpoint/resume events missing from: {kinds}")
    finally:
        os.environ.pop("CHIASWARM_DENOISE_CHUNK_STEPS", None)
        for worker, runner in ((w1, runner1), (w2, runner2)):
            if worker is not None:
                worker.stop()
            if runner is not None:
                await asyncio.wait_for(
                    asyncio.gather(runner, return_exceptions=True), 10)
        for proc in procs:
            proc.kill()
            proc.wait()
        plan.release_hangs()  # unstick worker 1's orphaned thread
    return (f"worker killed past checkpoint K={CKPT_EVERY}; hive SIGKILL "
            f"survived; second worker resumed from step "
            f"{resumed['from_step']} and settled exactly once")


async def scenario_dag_survives_restart() -> str:
    """Stage-graph durability (ISSUE 20 acceptance): the hive is
    SIGKILL'd BETWEEN two stage settles of one workflow. WAL replay
    (ev_dag + the stage-job records) must restore the graph — edges
    intact, stage 0 done with its spooled handoff still fetchable,
    stage 1 admitted and pending — and a fresh stage-capable worker
    must complete the remaining stage EXACTLY once, leaving the parent
    trace gap-free across the crash."""
    import base64
    import hashlib
    import json
    import os
    import socket
    import subprocess

    import aiohttp

    from chiaswarm_tpu.hive_server.trace import trace_missing

    faults.configure("")
    token = "chaos"
    repo = str(pathlib.Path(__file__).resolve().parent.parent)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ, SDAAS_TOKEN=token,
               CHIASWARM_HIVE_PORT=str(port),
               # the pre-crash lease belongs to a SYNTHETIC worker that
               # settles by hand — a short deadline would race its settle
               CHIASWARM_HIVE_LEASE_DEADLINE_S="600.0",
               PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
    uri = f"http://127.0.0.1:{port}"
    headers = {"Authorization": f"Bearer {token}",
               "Content-type": "application/json"}

    def spawn() -> subprocess.Popen:
        return subprocess.Popen(
            [sys.executable, "-m", "chiaswarm_tpu.hive_server"],
            cwd=repo, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)

    async def wait_up(session) -> bool:
        for _ in range(200):
            try:
                async with session.get(f"{uri}/healthz") as r:
                    if r.status in (200, 503):
                        return True
            except aiohttp.ClientError:
                pass
            await asyncio.sleep(0.1)
        return False

    procs = [spawn()]
    w = runner = None
    try:
        async with aiohttp.ClientSession() as session:
            _check(await wait_up(session),
                   "hive subprocess never answered /healthz")
            # an explicit 2-stage echo chain: no model weights, and both
            # stages are host ("postprocess") work a chip-less worker
            # serves — the scenario is about the GRAPH, not the pipeline
            workflow = {"id": "chaos-dag", "stages": [
                {"workflow": "echo", "model_name": "none",
                 "prompt": "stage zero"},
                {"workflow": "echo", "model_name": "none",
                 "prompt": "stage one"},
            ]}
            async with session.post(f"{uri}/api/workflows",
                                    data=json.dumps(workflow),
                                    headers=headers) as r:
                _check(r.status == 200, f"workflow submit -> {r.status}")
                ack = await r.json()
            _check([s["status"] for s in ack["stages"]]
                   == ["queued", "blocked"],
                   f"expansion did not gate stage 1 on stage 0: {ack}")
            s0_id = ack["stages"][0]["id"]

            # a synthetic stage-capable worker settles stage 0 BY HAND:
            # deterministic — nobody is around to take stage 1 when the
            # settle unblocks it, so the SIGKILL lands exactly between
            # the two stage settles
            async with session.get(
                    f"{uri}/api/work",
                    params={"worker_version": "0.1.0",
                            "worker_name": "dag-doomed-w",
                            "stages": "encode,denoise,decode,postprocess"},
                    headers=headers) as r:
                jobs = (await r.json())["jobs"]
            _check([j["id"] for j in jobs] == [s0_id],
                   f"expected exactly stage 0 handed out, got "
                   f"{[j.get('id') for j in jobs]}")
            _check(jobs[0]["trace"].get("stage")
                   == {"workflow_id": "chaos-dag", "stage": "postprocess",
                       "index": 0},
                   f"stage-job trace lacks graph coordinates: "
                   f"{jobs[0].get('trace')}")
            handoff_bytes = b"chaos dag stage zero output"
            envelope = {
                "id": s0_id,
                "artifacts": {"primary": {
                    "blob": base64.b64encode(handoff_bytes).decode("ascii"),
                    "content_type": "text/plain"}},
                "nsfw": False, "worker_version": "0.1.0",
                "pipeline_config": {"timings": {"job_s": 0.25}},
                "worker_name": "dag-doomed-w"}
            async with session.post(f"{uri}/api/results",
                                    data=json.dumps(envelope),
                                    headers=headers) as r:
                _check(r.status == 200, f"stage 0 settle -> {r.status}")

            async def wf_status() -> dict:
                async with session.get(f"{uri}/api/workflows/chaos-dag",
                                       headers=headers) as r:
                    _check(r.status == 200,
                           f"workflow lost (HTTP {r.status})")
                    return await r.json()

            st = await wf_status()
            _check([s["status"] for s in st["stages"]]
                   == ["done", "queued"],
                   f"settle did not unblock stage 1: {st['stages']}")

            procs[0].kill()  # SIGKILL: no drain, no atexit, no flush
            procs[0].wait()
            procs.append(spawn())  # same $SDAAS_ROOT, same port
            _check(await wait_up(session),
                   "restarted hive never answered /healthz")

            st = await wf_status()
            _check(st["status"] == "running"
                   and [s["status"] for s in st["stages"]]
                   == ["done", "queued"],
                   f"WAL replay lost the graph state: {st}")

            # a fresh chip-less worker (stage lane only) completes the
            # recovered ready stage off the spooled handoff — proving
            # the edges AND the content-addressed artifact survived
            w = Worker(settings=_settings(worker_name="chaos-dag-w2"),
                       allocator=SliceAllocator(chips_per_job=0),
                       hive_uri=f"{uri}/api")
            runner = asyncio.create_task(w.run())
            deadline = asyncio.get_running_loop().time() + 30.0
            while (st := await wf_status())["status"] != "done":
                _check(st["status"] == "running",
                       f"workflow ended {st['status']} after the restart")
                _check(asyncio.get_running_loop().time() < deadline,
                       f"workflow never finished after the restart: {st}")
                await asyncio.sleep(0.1)
            _check([s["status"] for s in st["stages"]] == ["done", "done"],
                   f"stage states wrong at the end: {st['stages']}")
            _check(st["stages"][1]["worker"] == "chaos-dag-w2",
                   "recovered stage not completed by the fresh worker")
            _check(st["usage"]["jobs"] == 2,
                   f"parent usage lost a stage: {st['usage']}")
            primary = st["result"]["artifacts"]["primary"]
            _check("blob" not in primary and primary.get("href"),
                   f"final result not spool-referenced: {primary}")
            async with session.get(f"{uri}{primary['href']}",
                                   headers=headers) as r:
                _check(r.status == 200, f"final artifact -> {r.status}")
                blob = await r.read()
            _check(hashlib.sha256(blob).hexdigest() == primary["sha256"],
                   "final artifact bytes drifted from their digest")

            # the parent trace spans the SIGKILL gap-free, every stage
            # settled exactly once, and the settle->admit seam is
            # attributed as the stage handoff it is
            async with session.get(f"{uri}/api/workflows/chaos-dag/trace",
                                   headers=headers) as r:
                _check(r.status == 200, f"workflow trace -> {r.status}")
                trace = await r.json()
            missing = trace_missing(trace)
            _check(not missing,
                   f"parent trace incomplete across SIGKILL: {missing}")
            kinds = [e["event"] for e in trace["events"]]
            _check(kinds.count("settle") == 2,
                   f"stages did not settle exactly once: {kinds}")
            _check(trace["workflow"] is True and trace["open"] is False,
                   f"parent trace not closed: {trace['status']}")
            _check(any(g["attribution"] == "stage_handoff"
                       for g in trace["gaps"]),
                   f"settle->admit seam not attributed: {trace['gaps']}")
    finally:
        if w is not None:
            w.stop()
        if runner is not None:
            await asyncio.wait_for(
                asyncio.gather(runner, return_exceptions=True), 10)
        for proc in procs:
            proc.kill()
            proc.wait()
    return ("workflow graph survived a hive SIGKILL between stage "
            "settles; a fresh worker finished the recovered stage off "
            "the spooled handoff, exactly once, gap-free")


SCENARIOS = {
    "drop_submit": scenario_drop_submit,
    "hive_connection_drop": scenario_hive_connection_drop,
    "hang_watchdog": scenario_hang_watchdog,
    "kill_before_ack": scenario_kill_before_ack,
    "sigterm_drain": scenario_sigterm_drain,
    "hive_lease_takeover": scenario_hive_lease_takeover,
    "gang_member_lost": scenario_gang_member_lost,
    "cancel_mid_denoise": scenario_cancel_mid_denoise,
    "hive_crash_recovery": scenario_hive_crash_recovery,
    "usage_survives_restart": scenario_usage_survives_restart,
    "hive_failover": scenario_hive_failover,
    "hive_split_brain_fenced": scenario_hive_split_brain_fenced,
    "resume_after_worker_kill": scenario_resume_after_worker_kill,
    "dag_survives_restart": scenario_dag_survives_restart,
}


def run_scenario(name: str) -> tuple[bool, str]:
    """One scenario under the fast cadences; (ok, detail). Always disarms
    the global fault plan afterwards."""
    try:
        with fast_mode():
            detail = asyncio.run(SCENARIOS[name]())
        return True, detail
    except ScenarioFailure as e:
        return False, str(e)
    except Exception as e:  # noqa: BLE001 — a crash is a failed scenario
        return False, f"{type(e).__name__}: {e}"
    finally:
        faults.configure("")


def main(argv: list[str] | None = None) -> int:
    import os
    import tempfile

    names = (argv if argv else sys.argv[1:]) or list(SCENARIOS)
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        print(f"unknown scenario(s): {unknown}; have {list(SCENARIOS)}")
        return len(unknown)
    failed = 0
    for name in names:
        # fresh root PER SCENARIO (not per run): persisted worker state —
        # the fencing epoch file above all — must not leak between
        # scenarios, which the CLI accepts in ANY order (a failover
        # scenario's epoch-1 file would 409 a later scenario's fresh
        # epoch-0 hive as 'deposed')
        with tempfile.TemporaryDirectory(prefix="chaos-sdaas-") as root:
            os.environ["SDAAS_ROOT"] = root  # isolate from ~/.sdaas
            ok, detail = run_scenario(name)
        print(f"  {name}: {'ok' if ok else 'FAILED'} — {detail}")
        failed += 0 if ok else 1
    print(f"chaos: {len(names) - failed}/{len(names)} scenarios ok")
    return failed


if __name__ == "__main__":
    sys.exit(main())
