#!/usr/bin/env python
"""Fetch a worker's /metrics and print the placement/batching table.

Two modes (mirroring tools/metrics_dump.py):

  python tools/placement_stats.py --url http://127.0.0.1:8061
      Scrape a LIVE worker's telemetry endpoint (Settings.metrics_port /
      CHIASWARM_METRICS_PORT) and print its dispatch-board placement
      outcomes (`swarm_placement_total{outcome}` -> affinity hit rate,
      steals, cold loads) and batch flush reasons
      (`swarm_batch_flush_total{reason}`, including "preempt").

  python tools/placement_stats.py
      No worker required: drive the REAL placement path in process — a
      2-slice SliceAllocator + BatchScheduler dispatch board through a
      cold -> affinity -> steal claim sequence (pipeline loads emulated
      via the residency map, exactly what registry builds record) — then
      print the same table from the process-local registry. Set
      JAX_PLATFORMS=cpu to keep it off a TPU relay.

What the table answers: is residency routing working (high affinity hit
rate at steady state), how often slices steal foreign groups instead of
idling, and how often interactive jobs preempted lingering groups.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

# reuse the battle-tested Prometheus exposition parser
try:
    from metrics_dump import fetch, parse_metrics
except ImportError:  # direct script invocation: tools/ not on sys.path
    import pathlib

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))
    from metrics_dump import fetch, parse_metrics

PLACEMENT_METRIC = "swarm_placement_total"
FLUSH_METRIC = "swarm_batch_flush_total"
OUTCOMES = ("affinity", "steal", "cold")


def placement_summary(samples: list[tuple[str, dict, float]]) -> dict:
    """Exposition samples -> {outcome counts, affinity_hit_rate, steals,
    flush reasons}."""
    outcomes = {o: 0 for o in OUTCOMES}
    flushes: dict[str, int] = {}
    for name, labels, value in samples:
        if name == PLACEMENT_METRIC and labels.get("outcome") in outcomes:
            outcomes[labels["outcome"]] = int(value)
        elif name == FLUSH_METRIC and "reason" in labels:
            flushes[labels["reason"]] = int(value)
    claimed = sum(outcomes.values())
    return {
        "placements": outcomes,
        "claimed": claimed,
        "affinity_hit_rate": (
            round(outcomes["affinity"] / claimed, 3) if claimed else None
        ),
        "steals": outcomes["steal"],
        "flushes": dict(sorted(flushes.items())),
    }


def render(summary: dict) -> str:
    if not summary["claimed"]:
        return "(no placements recorded yet — has a work item dispatched?)"
    lines = [
        f"{'outcome':<10} {'count':>7}",
        "-" * 18,
    ]
    for outcome in OUTCOMES:
        lines.append(f"{outcome:<10} {summary['placements'][outcome]:>7}")
    lines.append("-" * 18)
    lines.append(f"{'claimed':<10} {summary['claimed']:>7}")
    rate = summary["affinity_hit_rate"]
    lines.append(f"affinity_hit_rate: {rate if rate is not None else '-'}")
    lines.append(f"steals: {summary['steals']}")
    if summary["flushes"]:
        lines.append("")
        lines.append(f"{'flush reason':<12} {'count':>7}")
        lines.append("-" * 20)
        for reason, count in summary["flushes"].items():
            lines.append(f"{reason:<12} {count:>7}")
    return "\n".join(lines)


async def _inprocess_claims() -> list[str]:
    """Drive the real dispatch board through cold -> affinity -> steal on
    a 2-slice allocator; returns the claim outcome sequence."""
    from chiaswarm_tpu.batching import BatchScheduler
    from chiaswarm_tpu.chips import allocator as alloc_mod
    from chiaswarm_tpu.chips.allocator import SliceAllocator

    import jax

    # known-empty residency so the cold -> affinity -> steal choreography
    # is deterministic even in a process that already served jobs
    alloc_mod.reset_residency()
    devices = jax.devices()
    # two slices even on a single-device host: the smoke exercises claim
    # mechanics only, never executes on the slices
    if len(devices) >= 2:
        alloc = SliceAllocator(devices=devices[: len(devices) // 2 * 2],
                               chips_per_job=len(devices) // 2)
    else:
        alloc = SliceAllocator(devices=devices * 2, chips_per_job=1)
    sched = BatchScheduler(linger_s=0.005, max_coalesce=8,
                           free_slices=lambda: alloc.free_count)
    alloc.add_free_listener(sched.notify)

    def job(i: int, steps: int = 2) -> dict:
        return {"id": f"stats-{i}", "workflow": "txt2img",
                "model_name": "test/tiny-sd", "prompt": f"probe {i}",
                "height": 64, "width": 64, "num_inference_steps": steps,
                "parameters": {}}

    outcomes = []
    await sched.put(job(0))
    _, cs, outcome = await asyncio.wait_for(sched.claim(alloc), 5.0)
    outcomes.append(outcome)
    alloc_mod.note_resident("test/tiny-sd", cs.slice_id)  # the load event
    alloc.release(cs)

    await sched.put(job(1))
    _, held, outcome = await asyncio.wait_for(sched.claim(alloc), 5.0)
    outcomes.append(outcome)

    await sched.put(job(2, steps=3))  # home busy -> idle slice steals
    _, cs3, outcome = await asyncio.wait_for(sched.claim(alloc), 5.0)
    outcomes.append(outcome)
    alloc.release(held)
    alloc.release(cs3)
    return outcomes


def run_inprocess() -> str:
    from chiaswarm_tpu.telemetry import REGISTRY

    outcomes = asyncio.run(_inprocess_claims())
    print(f"claim sequence: {' -> '.join(outcomes)}")
    return REGISTRY.render()


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="placement_stats", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument(
        "--url", default=None,
        help="live worker telemetry base URL (e.g. http://127.0.0.1:8061); "
             "omit to run the in-process placement smoke instead")
    parser.add_argument(
        "--raw", action="store_true",
        help="also dump the raw /metrics exposition text")
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="print the summary as one JSON object instead of a table")
    args = parser.parse_args(argv)

    if args.url:
        text = fetch(args.url, "/metrics")
    else:
        text = run_inprocess()

    if args.raw:
        print(text)
    summary = placement_summary(parse_metrics(text))
    if args.as_json:
        print(json.dumps(summary, indent=1))
    else:
        print(render(summary))
    return 0 if summary["claimed"] else 1


if __name__ == "__main__":
    sys.exit(main())
