#!/usr/bin/env python
"""Inspect (and requeue) a worker's durable result outbox.

The outbox (chiaswarm_tpu/outbox.py) is the worker's write-ahead spool:
every finished job's envelope sits under ``$SDAAS_ROOT/outbox/`` until
the hive ACKs it, and a permanent 4xx refusal parks it aside
(``*.json.parked``) instead of dropping it. This is the ops counterpart:

  python tools/outbox_inspect.py
      Table of every spooled and parked envelope: job id, state, age,
      recorded delivery retries, size, and the park reason if any.

  python tools/outbox_inspect.py --json
      The same rows as one JSON document (for scripts/alerts).

  python tools/outbox_inspect.py --requeue <job-id>
  python tools/outbox_inspect.py --requeue all
      Move parked envelope(s) back into the delivery spool; the next
      worker start redelivers them (at-least-once — the hive dedupes by
      job id). Typical use: envelopes parked by a deposed primary's
      refusals, after the swarm has failed over to a hive that will
      accept them.

Reads the same settings the worker does (``$SDAAS_ROOT``,
``Settings.outbox_dir`` / ``CHIASWARM_OUTBOX_DIR``); ``--dir`` overrides.
Contract-tested by tests/test_outbox_inspect.py (quick tier).
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from chiaswarm_tpu.outbox import Outbox  # noqa: E402
from chiaswarm_tpu.settings import load_settings, resolve_path  # noqa: E402


def outbox_dir(override: str | None = None) -> pathlib.Path:
    if override:
        return pathlib.Path(override)
    settings = load_settings()
    return resolve_path(getattr(settings, "outbox_dir", "outbox"))


def inspect_rows(directory: pathlib.Path) -> list[dict]:
    """One row per envelope on disk, parked last, oldest first within
    each state. Unreadable files still get a row — an operator must see
    them, not lose them to a parse error."""
    rows: list[dict] = []
    now = time.time()
    for path in sorted(directory.glob("*.json")) + sorted(
            directory.glob("*.json.parked")):
        parked = path.name.endswith(".parked")
        row = {
            "state": "parked" if parked else "spooled",
            "path": str(path),
            "size_bytes": None,
            "job_id": "?",
            "age_s": None,
            "retries": 0,
            "park_reason": None,
        }
        try:
            row["size_bytes"] = path.stat().st_size
            row["age_s"] = round(now - path.stat().st_mtime, 1)
        except OSError:
            pass
        try:
            payload = json.loads(path.read_text())
            result = payload.get("result") or {}
            row["job_id"] = str(result.get("id", "?"))
            spooled_at = payload.get("spooled_at")
            if spooled_at:
                row["age_s"] = round(now - float(spooled_at), 1)
            row["retries"] = int(payload.get("retries", 0) or 0)
            row["park_reason"] = payload.get("park_reason")
        except (OSError, ValueError, TypeError):
            row["state"] = "unreadable"
        rows.append(row)
    state_rank = {"spooled": 0, "parked": 1, "unreadable": 2}
    rows.sort(key=lambda r: (state_rank.get(r["state"], 3),
                             -(r["age_s"] or 0)))
    return rows


def render_table(rows: list[dict]) -> str:
    if not rows:
        return "outbox empty: every delivered envelope was ACKed and unlinked"
    header = f"{'job id':<28} {'state':<10} {'age':>9} {'retries':>7} {'size':>9}  reason"
    lines = [header, "-" * len(header)]
    for r in rows:
        age = f"{r['age_s']:.0f}s" if r["age_s"] is not None else "?"
        size = f"{r['size_bytes']}B" if r["size_bytes"] is not None else "?"
        reason = (r["park_reason"] or "")[:60]
        lines.append(
            f"{r['job_id']:<28} {r['state']:<10} {age:>9} "
            f"{r['retries']:>7} {size:>9}  {reason}")
    parked = sum(1 for r in rows if r["state"] == "parked")
    lines.append(
        f"{len(rows)} envelope(s) on disk, {parked} parked "
        "(requeue with --requeue <job-id> | all)")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--dir", default=None,
                        help="outbox directory (default: "
                             "$SDAAS_ROOT/<Settings.outbox_dir>)")
    parser.add_argument("--json", action="store_true",
                        help="emit rows as JSON instead of a table")
    parser.add_argument("--requeue", metavar="JOB_ID", default=None,
                        help="move a parked envelope (or 'all') back "
                             "into the delivery spool")
    args = parser.parse_args(argv)

    directory = outbox_dir(args.dir)
    if not directory.is_dir():
        print(f"no outbox at {directory} (worker never spooled anything)")
        return 0

    if args.requeue is not None:
        box = Outbox(directory)
        target = None if args.requeue == "all" else args.requeue
        restored = box.requeue_parked(target)
        if not restored:
            print(f"nothing to requeue for {args.requeue!r} "
                  f"(see the table below)")
        for path in restored:
            print(f"requeued {path.name} — the next worker start "
                  "redelivers it")

    rows = inspect_rows(directory)
    if args.json:
        print(json.dumps({"outbox_dir": str(directory), "entries": rows},
                         indent=2))
    else:
        print(render_table(rows))
    return 0


if __name__ == "__main__":
    sys.exit(main())
