#!/bin/bash
# Round-5 watcher: poll the axon chip; the moment it answers, run the
# LADDERED bench exclusively (banks rows to /tmp/bench_ladder_r05.json as
# each completes), then any extra sweep legs from /tmp/bench_sweep.sh.
cd /root/repo
LOG=/tmp/bench_watch5.log
for i in $(seq 1 300); do
  echo "[watch] probe $i $(date +%T)" >> $LOG
  if timeout 120 python -c "import jax; print(jax.devices())" >> $LOG 2>&1; then
    echo "[watch] chip up at $(date +%T); starting laddered bench" >> $LOG
    BENCH_TPU_PROBE_TIMEOUT=240 BENCH_TPU_PROBE_ATTEMPTS=1 BENCH_CONFIGS=full \
      BENCH_LADDER_FILE=/tmp/bench_ladder_r05.json \
      timeout 10800 python bench.py > /tmp/bench_r05.json 2> /tmp/bench_r05.err
    echo "[watch] ladder rc=$? at $(date +%T)" >> $LOG
    if [ -f /tmp/bench_sweep.sh ]; then
      echo "[watch] running sweep at $(date +%T)" >> $LOG
      bash /tmp/bench_sweep.sh >> $LOG 2>&1
      echo "[watch] sweep rc=$? at $(date +%T)" >> $LOG
    fi
    exit 0
  fi
  sleep 120
done
echo "[watch] chip never recovered" >> $LOG
exit 1
