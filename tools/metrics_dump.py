#!/usr/bin/env python
"""Fetch a worker's /metrics and print the per-stage latency table.

Three modes (combinable):

  python tools/metrics_dump.py --url http://127.0.0.1:8061
      Scrape a LIVE worker's telemetry endpoint (Settings.metrics_port /
      CHIASWARM_METRICS_PORT) and print its stage breakdown + health.

  python tools/metrics_dump.py --hive http://127.0.0.1:9511
      Scrape a LIVE hive coordinator and print its dispatch-outcome,
      shed/admission, and lease/result tables plus per-class
      queue-wait / dispatch-to-settle quantiles — the hive half of the
      same picture, renderable next to the worker stage table.

  python tools/metrics_dump.py
      No worker required: run one hermetic tiny-model txt2img smoke job
      IN PROCESS through the real serving path (format_args -> ChipSet ->
      jitted denoise+decode), then print the stage table from the
      process-local registry. Uses the ambient JAX backend (set
      JAX_PLATFORMS=cpu to keep it off a TPU relay).

The tables are computed from the histogram/counter series (count / mean /
approx p50 / p90 from the cumulative buckets), so what it prints is
exactly what a Prometheus scrape would see.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import re
import sys
import urllib.request

STAGE_METRIC = "swarm_job_stage_seconds"

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+(?P<value>[^\s]+)$"
)
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


_ESCAPES = {'"': '"', "n": "\n", "\\": "\\"}


def _unescape(v: str) -> str:
    # single pass: ordered str.replace would corrupt values where a
    # doubled backslash precedes an 'n' (e.g. 'C:\\new')
    return re.sub(r"\\(.)", lambda m: _ESCAPES.get(m.group(1), m.group(0)),
                  v)


def parse_metrics(text: str) -> list[tuple[str, dict, float]]:
    """Prometheus text -> [(metric_name, labels, value)]."""
    samples = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        labels = {
            k: _unescape(v) for k, v in _LABEL_RE.findall(m.group("labels") or "")
        }
        raw = m.group("value")
        value = float("inf") if raw == "+Inf" else float(raw)
        samples.append((m.group("name"), labels, value))
    return samples


def _quantile_from_buckets(buckets: list[tuple[float, float]], count: float,
                           q: float) -> float | None:
    """Approximate quantile from cumulative (le, count) pairs — the bucket
    upper bound where the cumulative count first crosses q*count (what
    Prometheus' histogram_quantile reports, minus interpolation)."""
    if count <= 0:
        return None
    target = q * count
    for le, cum in sorted(buckets, key=lambda b: b[0]):
        if cum >= target:
            return le
    return None


def stage_rows(samples: list[tuple[str, dict, float]]) -> list[dict]:
    """Aggregate the stage histogram series into per-stage table rows."""
    by_stage: dict[str, dict] = {}
    for name, labels, value in samples:
        if not name.startswith(STAGE_METRIC):
            continue
        stage = labels.get("stage", "?")
        s = by_stage.setdefault(stage, {"buckets": [], "sum": 0.0, "count": 0.0})
        if name == f"{STAGE_METRIC}_bucket":
            le = labels.get("le", "+Inf")
            s["buckets"].append(
                (float("inf") if le == "+Inf" else float(le), value))
        elif name == f"{STAGE_METRIC}_sum":
            s["sum"] = value
        elif name == f"{STAGE_METRIC}_count":
            s["count"] = value
    rows = []
    for stage, s in sorted(by_stage.items()):
        n = s["count"]
        rows.append({
            "stage": stage,
            "count": int(n),
            "mean_s": (s["sum"] / n) if n else None,
            "p50_le_s": _quantile_from_buckets(s["buckets"], n, 0.5),
            "p90_le_s": _quantile_from_buckets(s["buckets"], n, 0.9),
            "total_s": s["sum"],
        })
    return rows


def _fmt_seconds(v) -> str:
    if v is None:
        return "-"
    if v == float("inf"):
        return "+Inf"
    return f"{v:.3f}"


def render_table(rows: list[dict]) -> str:
    if not rows:
        return "(no job stages recorded yet — has a job run?)"

    fmt = _fmt_seconds
    header = f"{'stage':<14} {'count':>6} {'mean_s':>9} " \
             f"{'p50<=s':>9} {'p90<=s':>9} {'total_s':>9}"
    lines = [header, "-" * len(header)]
    for r in rows:
        lines.append(
            f"{r['stage']:<14} {r['count']:>6} {fmt(r['mean_s']):>9} "
            f"{fmt(r['p50_le_s']):>9} {fmt(r['p90_le_s']):>9} "
            f"{fmt(r['total_s']):>9}"
        )
    return "\n".join(lines)


def fetch(url: str, path: str) -> str:
    with urllib.request.urlopen(f"{url.rstrip('/')}{path}", timeout=10) as r:
        return r.read().decode("utf-8")


# --- hive-side tables (--hive) ---------------------------------------------

HIVE_CLASSES = ("interactive", "default", "batch")


def _label_counts(samples, name: str, label: str) -> dict[str, float]:
    return {labels[label]: value for metric, labels, value in samples
            if metric == name and label in labels}


def _gauge_value(samples, name: str) -> float | None:
    for metric, _labels, value in samples:
        if metric == name:
            return value
    return None


def _class_quantiles(samples, name: str) -> list[dict]:
    """Per-class p50/p95 rows from a {class}-labeled hive histogram."""
    rows = []
    for cls in HIVE_CLASSES:
        buckets, count = [], 0.0
        for metric, labels, value in samples:
            if labels.get("class") != cls:
                continue
            if metric == f"{name}_bucket":
                le = labels.get("le", "+Inf")
                buckets.append(
                    (float("inf") if le == "+Inf" else float(le), value))
            elif metric == f"{name}_count":
                count = value
        if count:
            rows.append({
                "class": cls, "count": int(count),
                "p50_le_s": _quantile_from_buckets(buckets, count, 0.5),
                "p95_le_s": _quantile_from_buckets(buckets, count, 0.95),
            })
    return rows


def _gang_summary(samples) -> dict:
    """Gang-size histogram -> {gangs, jobs, p50, p95} (ISSUE 9)."""
    buckets, count, total = [], 0.0, 0.0
    for metric, labels, value in samples:
        if metric == "swarm_hive_gang_size_bucket":
            le = labels.get("le", "+Inf")
            buckets.append(
                (float("inf") if le == "+Inf" else float(le), value))
        elif metric == "swarm_hive_gang_size_count":
            count = value
        elif metric == "swarm_hive_gang_size_sum":
            total = value
    return {
        "gangs": int(count),
        "jobs": int(total),
        "size_p50": _quantile_from_buckets(buckets, count, 0.5),
        "size_p95": _quantile_from_buckets(buckets, count, 0.95),
    }


def _tenant_summary(samples) -> dict:
    """Per-tenant usage gauges -> {tenant: {chip_seconds, rows,
    petaflops}}, sorted by chip-seconds (the hive already folded
    past-top-K tenants into "other", so cardinality here is bounded by
    construction)."""
    chip = _label_counts(
        samples, "swarm_hive_tenant_chip_seconds_total", "tenant")
    rows = _label_counts(samples, "swarm_hive_tenant_rows_total", "tenant")
    flops = _label_counts(samples, "swarm_hive_tenant_flops_total", "tenant")
    return {
        tenant: {"chip_seconds": chip[tenant],
                 "rows": int(rows.get(tenant, 0)),
                 # cost plane (ISSUE 17): "petaflops served" next to the
                 # chip-seconds it was served in
                 "petaflops": round(flops.get(tenant, 0.0) / 1e15, 6)}
        for tenant in sorted(chip, key=lambda t: (-chip[t], t))
    }


def _slo_summary(samples) -> dict:
    """SLO gauges -> per-class fast/slow burn + worst compliance."""
    compliance = _label_counts(
        samples, "swarm_hive_slo_compliance", "class")
    burns: dict[str, dict[str, float]] = {}
    for metric, labels, value in samples:
        if metric != "swarm_hive_slo_burn_rate":
            continue
        cls, window = labels.get("class"), labels.get("window")
        if cls and window:
            burns.setdefault(cls, {})[window] = value
    return {
        cls: {
            "fast_burn": burns.get(cls, {}).get("fast", 0.0),
            "slow_burn": burns.get(cls, {}).get("slow", 0.0),
            "compliance": compliance.get(cls),
        }
        for cls in sorted(set(burns) | set(compliance))
    }


def dag_summary(samples) -> dict | None:
    """Stage-graph serving summary (ISSUE 20): workflow population by
    aggregate state, the ready depth (stage-jobs admitted but not yet
    settled), per-stage lifecycle outcomes, and per-stage queue-wait
    quantiles (admit -> first dispatch). None when the hive never
    tracked a workflow — classic single-stage fleets render nothing."""
    stages: dict[str, dict[str, int]] = {}
    for metric, labels, value in samples:
        if metric == "swarm_hive_dag_stages_total" \
                and "stage" in labels and "outcome" in labels:
            stages.setdefault(labels["stage"], {})[labels["outcome"]] = \
                int(value)
    workflows = {k: int(v) for k, v in sorted(_label_counts(
        samples, "swarm_hive_dag_workflows", "state").items())}
    ready = _gauge_value(samples, "swarm_hive_dag_ready_depth")
    if not stages and not any(workflows.values()) and ready is None:
        return None
    waits = []
    for stage in sorted(stages):
        buckets, count = [], 0.0
        for metric, labels, value in samples:
            if labels.get("stage") != stage:
                continue
            if metric == "swarm_hive_dag_stage_queue_wait_seconds_bucket":
                le = labels.get("le", "+Inf")
                buckets.append(
                    (float("inf") if le == "+Inf" else float(le), value))
            elif metric == "swarm_hive_dag_stage_queue_wait_seconds_count":
                count = value
        if count:
            waits.append({
                "stage": stage, "count": int(count),
                "p50_le_s": _quantile_from_buckets(buckets, count, 0.5),
                "p95_le_s": _quantile_from_buckets(buckets, count, 0.95),
            })
    return {
        "workflows": workflows,
        "ready_depth": int(ready or 0),
        "stages": {s: dict(sorted(o.items()))
                   for s, o in sorted(stages.items())},
        "stage_queue_wait": waits,
    }


def hive_summary(samples) -> dict:
    """Exposition samples -> the hive-side dispatch/shed/lease view."""
    return {
        # stage-graph serving (ISSUE 20)
        "dag": dag_summary(samples),
        # fleet observability plane (ISSUE 11)
        "tenants": _tenant_summary(samples),
        "slo": _slo_summary(samples),
        "usage_fallback": next(
            (int(v) for m, _, v in samples
             if m == "swarm_hive_usage_fallback_total"), 0),
        "outliers": sorted(
            labels["worker"] for m, labels, v in samples
            if m == "swarm_hive_worker_outlier" and v >= 1
            and "worker" in labels),
        "dispatch": {k: int(v) for k, v in sorted(_label_counts(
            samples, "swarm_hive_dispatch_total", "outcome").items())},
        "gang": _gang_summary(samples),
        "submitted": {k: int(v) for k, v in sorted(_label_counts(
            samples, "swarm_hive_jobs_submitted_total", "class").items())},
        "shed": {k: int(v) for k, v in sorted(_label_counts(
            samples, "swarm_hive_shed_total", "class").items())},
        "queue_depth": {k: int(v) for k, v in sorted(_label_counts(
            samples, "swarm_hive_queue_depth", "class").items())},
        "results": {k: int(v) for k, v in sorted(_label_counts(
            samples, "swarm_hive_results_total", "status").items())},
        "leases_active": next(
            (int(v) for m, _, v in samples
             if m == "swarm_hive_leases_active"), 0),
        "leases_expired": next(
            (int(v) for m, _, v in samples
             if m == "swarm_hive_leases_expired_total"), 0),
        "jobs_failed": next(
            (int(v) for m, _, v in samples
             if m == "swarm_hive_jobs_failed_total"), 0),
        # cancellation & deadlines (ISSUE 10)
        "cancelled": {k: int(v) for k, v in sorted(_label_counts(
            samples, "swarm_hive_cancelled_total", "stage").items())},
        "expired": next(
            (int(v) for m, _, v in samples
             if m == "swarm_hive_expired_total"), 0),
        "cancel_revocations_pending": next(
            (int(v) for m, _, v in samples
             if m == "swarm_hive_cancel_revocations_pending"), 0),
        "queue_wait": _class_quantiles(
            samples, "swarm_hive_queue_wait_seconds"),
        "dispatch_to_settle": _class_quantiles(
            samples, "swarm_hive_dispatch_to_settle_seconds"),
        # preemption tolerance (ISSUE 18): mid-pass checkpoint blobs,
        # progressive-preview artifacts, resume offers on redelivery
        "partials": {
            "checkpoints": {k: int(v) for k, v in sorted(_label_counts(
                samples, "swarm_hive_checkpoints_total",
                "outcome").items())},
            "previews": {k: int(v) for k, v in sorted(_label_counts(
                samples, "swarm_hive_previews_total", "outcome").items())},
            "resume_offers": next(
                (int(v) for m, _, v in samples
                 if m == "swarm_hive_resume_offers_total"), 0),
        },
    }


def render_hive_tables(summary: dict) -> str:
    fmt = _fmt_seconds
    lines = ["hive dispatch outcomes"]
    if summary["dispatch"]:
        for outcome, n in summary["dispatch"].items():
            lines.append(f"  {outcome:<10} {n:>8}")
    else:
        lines.append("  (no dispatches yet)")

    gang = summary.get("gang") or {}
    if gang.get("gangs"):
        # gang rate = jobs that left pre-batched over all DELIVERED jobs
        # ("hold" is a deferral, not a delivery); sizes are job COUNTS,
        # not seconds — integer buckets, +Inf = past the largest bucket
        def fmt_size(v):
            if v is None:
                return "-"
            return ">16" if v == float("inf") else str(int(v))

        delivered = sum(n for o, n in summary["dispatch"].items()
                        if o != "hold") or 1
        lines.append(
            f"hive gangs    count={gang['gangs']} jobs={gang['jobs']} "
            f"rate={min(gang['jobs'] / delivered, 1.0):.2f} "
            f"size p50<={fmt_size(gang['size_p50'])} "
            f"p95<={fmt_size(gang['size_p95'])}")

    lines.append("hive admission by class "
                 "(queued now / admitted / shed 429)")
    classes = sorted(set(summary["submitted"]) | set(summary["shed"])
                     | set(summary["queue_depth"]))
    for cls in classes or ["-"]:
        lines.append(
            f"  {cls:<12} {summary['queue_depth'].get(cls, 0):>6} "
            f"{summary['submitted'].get(cls, 0):>9} "
            f"{summary['shed'].get(cls, 0):>6}")

    lines.append(
        f"hive leases   active={summary['leases_active']} "
        f"expired={summary['leases_expired']} "
        f"failed={summary['jobs_failed']}")
    if (summary.get("cancelled") or summary.get("expired")
            or summary.get("cancel_revocations_pending")):
        cancelled = summary.get("cancelled") or {}
        lines.append(
            "hive cancels  "
            + " ".join(f"{s}={n}" for s, n in cancelled.items())
            + (" " if cancelled else "")
            + f"expired={summary.get('expired', 0)} "
            f"pending_revocations="
            f"{summary.get('cancel_revocations_pending', 0)}")
    if summary["results"]:
        lines.append("hive results  " + " ".join(
            f"{s}={n}" for s, n in summary["results"].items()))
    partials = summary.get("partials") or {}
    if (partials.get("checkpoints") or partials.get("previews")
            or partials.get("resume_offers")):
        bits = []
        if partials.get("checkpoints"):
            bits.append("checkpoints " + " ".join(
                f"{o}={n}" for o, n in partials["checkpoints"].items()))
        if partials.get("previews"):
            bits.append("previews " + " ".join(
                f"{o}={n}" for o, n in partials["previews"].items()))
        bits.append(f"resume_offers={partials.get('resume_offers', 0)}")
        lines.append("hive partials " + "  ".join(bits))

    # stage-graph serving (ISSUE 20): workflow population, ready depth,
    # and per-stage outcomes + queue-wait quantiles — absent entirely on
    # fleets that never submitted a workflow
    dag = summary.get("dag")
    if dag:
        wf = dag["workflows"]
        lines.append(
            "hive dag      "
            + " ".join(f"{s}={wf.get(s, 0)}"
                       for s in ("running", "done", "failed", "cancelled"))
            + f" ready_depth={dag['ready_depth']}")
        if dag["stages"]:
            lines.append("hive dag stages (lifecycle outcomes)")
            for stage, outcomes in dag["stages"].items():
                lines.append(
                    f"  {stage:<12} "
                    + " ".join(f"{o}={n}" for o, n in outcomes.items()))
        if dag["stage_queue_wait"]:
            lines.append("hive dag stage wait (admit -> first dispatch)")
            for r in dag["stage_queue_wait"]:
                lines.append(
                    f"  {r['stage']:<12} n={r['count']:<6} "
                    f"p50<={fmt(r['p50_le_s'])} p95<={fmt(r['p95_le_s'])}")

    for key, title in (("queue_wait", "hive queue wait"),
                       ("dispatch_to_settle", "hive dispatch->settle")):
        rows = summary[key]
        if not rows:
            continue
        lines.append(f"{title} (per class)")
        for r in rows:
            lines.append(
                f"  {r['class']:<12} n={r['count']:<6} "
                f"p50<={fmt(r['p50_le_s'])} p95<={fmt(r['p95_le_s'])}")

    # fleet observability plane (ISSUE 11): who consumed the chips, is
    # each class inside its objective, who is dragging the fleet
    tenants = summary.get("tenants") or {}
    if tenants:
        lines.append("hive tenants  (chip_s / rows / Pflops; past-top-K "
                     "folded into 'other')")
        for tenant, t in tenants.items():
            lines.append(
                f"  {tenant:<16} {t['chip_seconds']:>10.3f} "
                f"{t['rows']:>6} {t.get('petaflops', 0.0):>10.6f}")
        if summary.get("usage_fallback"):
            lines.append(
                f"  (usage fallback settles: {summary['usage_fallback']})")
    slo = summary.get("slo") or {}
    if slo:
        lines.append("hive slo      (burn rate: 1.0 = budget spent "
                     "exactly; fast window pages)")
        for cls, view in slo.items():
            comp = view.get("compliance")
            lines.append(
                f"  {cls:<12} fast={view['fast_burn']:.2f} "
                f"slow={view['slow_burn']:.2f} "
                f"compliance={'-' if comp is None else f'{comp:.2f}'}")
    if summary.get("outliers"):
        lines.append("hive outliers " + " ".join(summary["outliers"]))
    return "\n".join(lines)


def embed_cache_line(samples) -> str | None:
    """Worker-side prompt-embedding cache summary (ISSUE 9), rendered
    under the stage table; None when no lookup ever happened (cache
    disabled, or no encode ran)."""
    events = _label_counts(samples, "swarm_embed_cache_total", "event")
    hits, misses = events.get("hit", 0.0), events.get("miss", 0.0)
    total = hits + misses
    if total <= 0:
        return None
    return (f"embed cache    hit={int(hits)} miss={int(misses)} "
            f"hit_rate={hits / total:.2f}")


def lora_summary(samples) -> dict | None:
    """Adapter-serving summary (ISSUE 13): image rows by execution mode
    (delta = runtime per-row low-rank deltas on the resident base tree,
    merged = full merged-tree fallback, none = adapter-free), plus the
    factor cache's hit rate and residency. None when no SD pass ever
    ran AND no adapter was ever resolved."""
    rows = _label_counts(samples, "swarm_lora_rows_total", "mode")
    events = _label_counts(samples, "swarm_lora_cache_total", "event")
    hits, misses = events.get("hit", 0.0), events.get("miss", 0.0)
    lookups = hits + misses
    operand = _label_counts(
        samples, "swarm_lora_operand_cache_total", "event")
    ohits, omisses = operand.get("hit", 0.0), operand.get("miss", 0.0)
    olookups = ohits + omisses
    if not rows and lookups <= 0 and olookups <= 0:
        return None
    adapter_rows = rows.get("delta", 0.0) + rows.get("merged", 0.0)
    summary = {
        "rows": {k: int(v) for k, v in sorted(rows.items())},
        "adapter_rows": int(adapter_rows),
        "delta_rate": (round(rows.get("delta", 0.0) / adapter_rows, 4)
                       if adapter_rows else None),
        "cache": {
            "hits": int(hits),
            "misses": int(misses),
            "hit_rate": round(hits / lookups, 4) if lookups else None,
            "bytes": int(_gauge_value(
                samples, "swarm_lora_cache_bytes") or 0),
            "entries": int(_gauge_value(
                samples, "swarm_lora_cache_entries") or 0),
        },
    }
    if olookups > 0:
        # stacked-operand residency (ISSUE 16): steady-state repeat
        # gangs should drive hit_rate -> 1.0 with the working set's
        # device footprint held in `bytes`; absent entirely on fleets
        # that never consulted the operand cache
        summary["operand_cache"] = {
            "hits": int(ohits),
            "misses": int(omisses),
            "hit_rate": round(ohits / olookups, 4),
            "bytes": int(_gauge_value(
                samples, "swarm_lora_operand_cache_bytes") or 0),
            "entries": int(_gauge_value(
                samples, "swarm_lora_operand_cache_entries") or 0),
        }
    return summary


def lora_line(samples) -> str | None:
    """Human-readable twin of lora_summary."""
    summary = lora_summary(samples)
    if summary is None:
        return None
    rows = summary["rows"]
    cache = summary["cache"]
    parts = [f"adapters       rows "
             + " ".join(f"{k}={v}" for k, v in rows.items())]
    if cache["hits"] or cache["misses"]:
        parts.append(
            f"cache hit_rate={cache['hit_rate']:.2f} "
            f"entries={cache['entries']} "
            f"bytes={cache['bytes']}")
    operand = summary.get("operand_cache")
    if operand is not None:
        parts.append(
            f"operands hit_rate={operand['hit_rate']:.2f} "
            f"entries={operand['entries']} "
            f"resident_bytes={operand['bytes']}")
    return " ".join(parts)


def geometry_summary(samples) -> dict | None:
    """Per-geometry pass counts (swarm_sharded_passes_total, ISSUE 12):
    how many denoise passes ran replicated (data-parallel coalescing
    view) vs sharded (tensorN/seqN interactive view). None when no pass
    ever ran."""
    passes = _label_counts(samples, "swarm_sharded_passes_total", "geometry")
    if not passes:
        return None
    total = sum(passes.values())
    sharded = sum(v for k, v in passes.items() if k != "replicated")
    return {
        "passes": {k: int(v) for k, v in sorted(passes.items())},
        "total": int(total),
        "sharded": int(sharded),
        "sharded_rate": round(sharded / total, 4) if total else 0.0,
    }


def geometry_line(samples) -> str | None:
    """Human-readable twin of geometry_summary."""
    summary = geometry_summary(samples)
    if summary is None:
        return None
    counts = " ".join(
        f"{k}={v}" for k, v in summary["passes"].items())
    return (f"slice geometry {counts} "
            f"sharded_rate={summary['sharded_rate']:.2f}")


def cost_summary(samples) -> dict | None:
    """Serving-path cost plane (ISSUE 17): analytic UNet FLOPs served
    per model, latest MFU per model/geometry (absent on accelerators
    with no peak-FLOPs table entry — CPU always), the analytic-vs-XLA
    divergence ratio, and live compiled programs per model. None when
    no denoise pass ever stamped a cost."""
    flops = _label_counts(samples, "swarm_pass_flops_total", "model")
    if not flops:
        return None
    mfu = {
        f"{labels['model']}/{labels['geometry']}": round(v, 4)
        for m, labels, v in samples
        if m == "swarm_pass_mfu" and "model" in labels
        and "geometry" in labels
    }
    return {
        "pass_flops": {k: int(v) for k, v in sorted(flops.items())},
        "mfu": dict(sorted(mfu.items())),
        "divergence": {
            k: round(v, 4) for k, v in sorted(_label_counts(
                samples, "swarm_flops_divergence_ratio", "model").items())},
        "programs_live": {k: int(v) for k, v in sorted(_label_counts(
            samples, "swarm_programs_live", "model").items())},
    }


def cost_line(samples) -> str | None:
    """Human-readable twin of cost_summary."""
    summary = cost_summary(samples)
    if summary is None:
        return None
    tflops = " ".join(
        f"{model}={flops / 1e12:.3f}"
        for model, flops in summary["pass_flops"].items())
    parts = [f"cost           tflops {tflops}"]
    if summary["mfu"]:
        parts.append("mfu " + " ".join(
            f"{k}={v:.3f}" for k, v in summary["mfu"].items()))
    if summary["divergence"]:
        parts.append("xla_divergence " + " ".join(
            f"{k}={v:.2f}" for k, v in summary["divergence"].items()))
    live = sum(summary["programs_live"].values())
    if live:
        parts.append(f"programs_live={live}")
    return " ".join(parts)


def resume_summary(samples) -> dict | None:
    """Preemption-tolerance summary (ISSUE 18): mid-pass checkpoints
    shipped at chunk boundaries, preview frames decoded, and redelivered
    passes that resumed from a checkpoint instead of recomputing. None
    when the feature never engaged (checkpoint_every_chunks = 0, or no
    chunked pass ever ran)."""
    ckpts = _label_counts(samples, "swarm_checkpoints_total", "outcome")
    previews = _label_counts(samples, "swarm_previews_total", "outcome")
    resumes = _label_counts(samples, "swarm_resume_total", "outcome")
    if not ckpts and not previews and not resumes:
        return None
    return {
        "checkpoints": {k: int(v) for k, v in sorted(ckpts.items())},
        "previews": {k: int(v) for k, v in sorted(previews.items())},
        "resumes": {k: int(v) for k, v in sorted(resumes.items())},
    }


def resume_line(samples) -> str | None:
    """Human-readable twin of resume_summary."""
    summary = resume_summary(samples)
    if summary is None:
        return None
    parts = []
    for key in ("checkpoints", "previews", "resumes"):
        if summary[key]:
            parts.append(f"{key} " + " ".join(
                f"{o}={n}" for o, n in summary[key].items()))
    return "resume         " + "  ".join(parts)


async def _run_smoke_job() -> None:
    """One tiny-model txt2img job through the REAL worker path (the same
    code a hive job takes minus the HTTP hop), populating the stage spans."""
    from chiaswarm_tpu.chips.allocator import SliceAllocator
    from chiaswarm_tpu.job_arguments import format_args
    from chiaswarm_tpu.settings import load_settings

    job = {
        "id": "metrics-dump-smoke",
        "workflow": "txt2img",
        "model_name": "stabilityai/stable-diffusion-2-1",
        "prompt": "a red cube on a table",
        "height": 64,
        "width": 64,
        "num_inference_steps": 2,
        "parameters": {"test_tiny_model": True},
    }
    settings = load_settings()
    allocator = SliceAllocator(chips_per_job=0)
    chipset = await allocator.acquire()
    try:
        func, kwargs = await format_args(job, settings, chipset.identifier())
        kwargs.pop("id", None)
        chipset(func, **kwargs)
    finally:
        allocator.release(chipset)


def run_inprocess() -> str:
    """Run the smoke job and return the process-local registry rendering."""
    from chiaswarm_tpu.telemetry import REGISTRY

    asyncio.run(_run_smoke_job())
    return REGISTRY.render()


def _jsonable(value):
    """JSON-safe twin of a summary structure: bucket bounds and
    quantiles can be float('inf'), which json.dumps would emit as the
    non-standard `Infinity` literal — render them as the exposition
    format's own "+Inf" spelling instead."""
    if isinstance(value, float) and value == float("inf"):
        return "+Inf"
    if isinstance(value, dict):
        return {k: _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    return value


def embed_cache_summary(samples) -> dict | None:
    """The machine-readable twin of embed_cache_line."""
    events = _label_counts(samples, "swarm_embed_cache_total", "event")
    hits, misses = events.get("hit", 0.0), events.get("miss", 0.0)
    total = hits + misses
    if total <= 0:
        return None
    return {"hits": int(hits), "misses": int(misses),
            "hit_rate": round(hits / total, 4)}


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="metrics_dump", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument(
        "--url", default=None,
        help="live worker telemetry base URL (e.g. http://127.0.0.1:8061); "
             "omit to run one in-process smoke job instead")
    parser.add_argument(
        "--hive", default=None,
        help="live hive base URL (e.g. http://127.0.0.1:9511): also print "
             "the hive-side dispatch/shed/lease tables")
    parser.add_argument(
        "--raw", action="store_true",
        help="also dump the raw /metrics exposition text")
    parser.add_argument(
        "--json", action="store_true",
        help="emit ONE machine-readable JSON object — the twin of every "
             "table this run would render — instead of the tables, so CI "
             "and bench tooling consume structured data, not screen text")
    args = parser.parse_args(argv)
    payload: dict = {}

    if args.hive:
        hive_text = fetch(args.hive, "/metrics")
        if args.raw and not args.json:
            print(hive_text)
        summary = hive_summary(parse_metrics(hive_text))
        payload["hive"] = summary
        if not args.json:
            print(render_hive_tables(summary))
            print()
        if not args.url:
            # hive-only mode: no worker scrape, no in-process smoke job
            if args.json:
                print(json.dumps(_jsonable(payload)))
            return 0

    health = None
    if args.url:
        text = fetch(args.url, "/metrics")
        try:
            health = json.loads(fetch(args.url, "/healthz"))
            if not args.json:
                print(f"healthz: {json.dumps(health, indent=1)}")
        except Exception as e:  # the table is still worth printing
            if not args.json:
                print(f"healthz unavailable: {e}")
    else:
        if not args.json:
            print("no --url given: running one in-process tiny smoke job "
                  "(this compiles a tiny pipeline; ~a minute on CPU)")
        text = run_inprocess()

    if args.raw and not args.json:
        print(text)
    samples = parse_metrics(text)
    rows = stage_rows(samples)
    payload["worker"] = {
        "stages": rows,
        "embed_cache": embed_cache_summary(samples),
        "lora": lora_summary(samples),
        "geometry": geometry_summary(samples),
        "cost": cost_summary(samples),
        "resume": resume_summary(samples),
        "healthz": health,
    }
    if args.json:
        print(json.dumps(_jsonable(payload)))
    else:
        print(render_table(rows))
        embed = embed_cache_line(samples)
        if embed:
            print(embed)
        adapters = lora_line(samples)
        if adapters:
            print(adapters)
        geometry = geometry_line(samples)
        if geometry:
            print(geometry)
        cost = cost_line(samples)
        if cost:
            print(cost)
        resume = resume_line(samples)
        if resume:
            print(resume)
    return 0 if rows else 1


if __name__ == "__main__":
    sys.exit(main())
