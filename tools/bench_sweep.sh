#!/bin/bash
# Perf sweep legs, run serially after the main ladder banked its rows.
# Each leg is a direct `--row sdxl` child (sole tenant), best-effort.
cd /root/repo
echo "[sweep] noflash $(date +%T)"
CHIASWARM_DISABLE_FLASH=1 timeout 2700 \
  python bench.py --row sdxl > /tmp/bench_noflash.json 2> /tmp/bench_noflash.err
echo "[sweep] noflash rc=$?"
for B in 2 8; do
  echo "[sweep] batch$B $(date +%T)"
  BENCH_BATCH=$B timeout 2700 \
    python bench.py --row sdxl > /tmp/bench_b$B.json 2> /tmp/bench_b$B.err
  echo "[sweep] batch$B rc=$?"
done
echo "[sweep] nofusedgn $(date +%T)"
CHIASWARM_DISABLE_FUSED_GN=1 timeout 2700 \
  python bench.py --row sdxl > /tmp/bench_nofusedgn.json 2> /tmp/bench_nofusedgn.err
echo "[sweep] nofusedgn rc=$?"
echo "[sweep] bigfusedgn $(date +%T)"
CHIASWARM_FUSED_GN_MAX_BYTES=25165824 timeout 2700 \
  python bench.py --row sdxl > /tmp/bench_bigfusedgn.json 2> /tmp/bench_bigfusedgn.err
echo "[sweep] bigfusedgn rc=$?"
for BQ in 256 1024; do
  echo "[sweep] flashq$BQ $(date +%T)"
  CHIASWARM_FLASH_BLOCK_Q=$BQ CHIASWARM_FLASH_BLOCK_K=$BQ timeout 2700 \
    python bench.py --row sdxl > /tmp/bench_fq$BQ.json 2> /tmp/bench_fq$BQ.err
  echo "[sweep] flashq$BQ rc=$?"
done
echo "[sweep] flux-streamed $(date +%T)"
timeout 3600 python bench.py --row flux > /tmp/bench_flux.json 2> /tmp/bench_flux.err
echo "[sweep] flux rc=$?"
echo "[sweep] flux-streamed-int8 $(date +%T)"
SDAAS_FLUX_STREAM_INT8=1 timeout 3600 \
  python bench.py --row flux > /tmp/bench_flux_int8.json 2> /tmp/bench_flux_int8.err
echo "[sweep] flux-int8 rc=$?"
echo "[sweep] profiled $(date +%T)"
BENCH_PROFILE_DIR=/tmp/bench_trace_r05 timeout 2700 \
  python bench.py --row sdxl > /tmp/bench_profiled.json 2> /tmp/bench_profiled.err
echo "[sweep] profiled rc=$?"
