#!/usr/bin/env python
"""swarm_top: a live fleet console for one hive + N workers.

Scrapes the hive's and each worker's `/metrics` (Prometheus text) and
`/healthz` (JSON) — nothing else, no jax, no chiaswarm imports — and
renders one refreshing frame answering the operator's standing
questions: how deep is the queue by class, where is every slice and
what is warm on it, how are dispatch outcomes and shedding trending,
is the outbox/WAL backing up, which tenants are consuming the
chip-seconds, is each class inside its SLO (burn rate over the fast and
slow windows), which worker is the fleet straggler, and what do stage
latencies look like RIGHT NOW (p50/p95 over the delta between
refreshes, not over the process's whole life).

  python tools/swarm_top.py --hive http://127.0.0.1:9511 \
      --worker http://127.0.0.1:8061 --worker http://10.0.0.2:8061

  python tools/swarm_top.py --hive http://127.0.0.1:9511 --once
      One snapshot (cumulative quantiles), no screen control — the
      contract-test / scripting mode.

Counters render as totals plus per-second rates since the previous
frame; histograms as approximate p50/p95 from the cumulative-bucket
DELTA between frames (what changed in the last interval), falling back
to cumulative in --once mode. Endpoints that refuse or time out render
as unreachable instead of killing the loop — mid-failover is exactly
when the console matters most.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time

try:
    from metrics_dump import _quantile_from_buckets, parse_metrics
except ImportError:  # direct script invocation: tools/ not on sys.path
    import pathlib

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))
    from metrics_dump import _quantile_from_buckets, parse_metrics

STAGE_METRIC = "swarm_job_stage_seconds"
HIVE_WAIT_METRIC = "swarm_hive_queue_wait_seconds"
HIVE_D2S_METRIC = "swarm_hive_dispatch_to_settle_seconds"
JOB_CLASSES = ("interactive", "default", "batch")


# --- scrape -----------------------------------------------------------------


class Snapshot:
    """One endpoint's state at one instant: parsed samples + healthz."""

    def __init__(self, url: str, samples=None, health=None, error=None):
        self.url = url
        self.taken = time.monotonic()
        self.samples = samples or []
        self.health = health or {}
        self.error = error

    def counters(self, name: str, label: str) -> dict[str, float]:
        """{label value: count} across a counter's series."""
        out: dict[str, float] = {}
        for metric, labels, value in self.samples:
            if metric == name and label in labels:
                out[labels[label]] = value
        return out

    def gauge(self, name: str, **match) -> float | None:
        for metric, labels, value in self.samples:
            if metric == name and all(
                    labels.get(k) == v for k, v in match.items()):
                return value
        return None

    def histogram(self, name: str, **match):
        """{le: cumulative count} for one histogram series."""
        buckets: dict[float, float] = {}
        for metric, labels, value in self.samples:
            if metric != f"{name}_bucket":
                continue
            if not all(labels.get(k) == v for k, v in match.items()):
                continue
            le = labels.get("le", "+Inf")
            buckets[float("inf") if le == "+Inf" else float(le)] = value
        return buckets


async def scrape(session, url: str) -> Snapshot:
    base = url.rstrip("/")
    try:
        async with session.get(f"{base}/metrics") as resp:
            if resp.status != 200:
                # a proxy 502 / wrong port must read as unreachable, not
                # as an empty-but-healthy frame (--once exits 1 on it)
                return Snapshot(
                    base, error=f"/metrics answered HTTP {resp.status}")
            samples = parse_metrics(await resp.text())
        health = {}
        try:
            async with session.get(f"{base}/healthz") as resp:
                health = await resp.json()
        except Exception:
            pass  # metrics without healthz still renders most rows
        return Snapshot(base, samples, health)
    except Exception as e:
        return Snapshot(base, error=f"{type(e).__name__}: {e}")


# --- deltas -----------------------------------------------------------------


def rate(cur: float | None, prev: float | None, dt: float) -> str:
    if cur is None or prev is None or dt <= 0 or cur < prev:
        return ""
    return f" (+{(cur - prev) / dt:.1f}/s)"


def quantile_from_buckets(buckets: dict[float, float],
                          q: float) -> float | None:
    """Approximate quantile over a {le: cumulative count} map — thin
    adapter over metrics_dump's crossing logic, so both tools stay in
    agreement about quantile semantics."""
    if not buckets:
        return None
    total = buckets.get(float("inf"), max(buckets.values()))
    if total <= 0:
        return None
    return _quantile_from_buckets(list(buckets.items()), total, q)


def bucket_delta(cur: dict[float, float],
                 prev: dict[float, float] | None) -> dict[float, float]:
    """Per-bound count delta between two cumulative scrapes; negative
    deltas (a restarted process) fall back to the current counts."""
    if not prev:
        return cur
    delta = {le: cur[le] - prev.get(le, 0.0) for le in cur}
    if any(v < 0 for v in delta.values()):
        return cur
    return delta


def fmt_s(v: float | None) -> str:
    if v is None:
        return "-"
    if v == float("inf"):
        return "+Inf"
    return f"{v:g}s"


# --- rendering --------------------------------------------------------------


def render_hive(cur: Snapshot, prev: Snapshot | None) -> list[str]:
    lines = [f"HIVE {cur.url}"]
    if cur.error:
        lines.append(f"  unreachable: {cur.error}")
        return lines
    h = cur.health
    dt = (cur.taken - prev.taken) if prev else 0.0
    live = int(cur.gauge("swarm_hive_workers_live") or 0)
    lines[0] += (f"  role={h.get('role', '?')} epoch={h.get('epoch', '?')}"
                 f" status={h.get('status', '?')} workers_live={live}")
    for reason in h.get("degraded_reasons") or []:
        lines.append(f"  ! {reason}")

    depths = []
    for cls in JOB_CLASSES:
        depth = int(cur.gauge("swarm_hive_queue_depth",
                              **{"class": cls}) or 0)
        depths.append(f"{cls}={depth}")
    lines.append(
        f"  queue     {' '.join(depths)}  "
        f"leases={int(h.get('leases_active', 0))}")

    # stage-graph serving (ISSUE 20): workflow population by aggregate
    # state + ready depth on one line, then per-stage lifecycle outcomes
    # with queue-wait quantiles over the last interval
    wf = h.get("workflows") or {}
    dag_stages: dict[str, dict[str, float]] = {}
    for metric, labels, value in cur.samples:
        if metric == "swarm_hive_dag_stages_total" and "stage" in labels:
            dag_stages.setdefault(labels["stage"], {})[
                labels.get("outcome", "?")] = value
    if wf.get("total") or dag_stages:
        ready = int(cur.gauge("swarm_hive_dag_ready_depth")
                    or wf.get("ready_stages", 0) or 0)
        lines.append(
            f"  workflows total={int(wf.get('total', 0))} "
            f"running={int(wf.get('running', 0))} "
            f"done={int(wf.get('done', 0))} "
            f"failed={int(wf.get('failed', 0))} "
            f"cancelled={int(wf.get('cancelled', 0))} "
            f"ready_stages={ready}")
        parts = []
        for stage in sorted(dag_stages):
            outcomes = " ".join(
                f"{o}={int(n)}"
                for o, n in sorted(dag_stages[stage].items()))
            buckets = bucket_delta(
                cur.histogram("swarm_hive_dag_stage_queue_wait_seconds",
                              stage=stage),
                prev.histogram("swarm_hive_dag_stage_queue_wait_seconds",
                               stage=stage) if prev else None)
            p50 = quantile_from_buckets(buckets, 0.5)
            wait = "" if p50 is None else f" wait p50<={fmt_s(p50)}"
            parts.append(f"{stage}[{outcomes}{wait}]")
        if parts:
            lines.append("  dag       " + " ".join(parts))

    dispatch = cur.counters("swarm_hive_dispatch_total", "outcome")
    pdispatch = prev.counters(
        "swarm_hive_dispatch_total", "outcome") if prev else {}
    lines.append("  dispatch  " + (" ".join(
        f"{o}={int(n)}{rate(n, pdispatch.get(o), dt)}"
        for o, n in sorted(dispatch.items())) or "(none yet)"))

    # gang-scheduled dispatch (ISSUE 9): how often jobs leave pre-batched
    # and how big the groups run (size quantiles over the last interval)
    gang_buckets = bucket_delta(
        cur.histogram("swarm_hive_gang_size"),
        prev.histogram("swarm_hive_gang_size") if prev else None)
    gangs_total = cur.gauge("swarm_hive_gang_size_count")
    gang_jobs = cur.gauge("swarm_hive_gang_size_sum")
    if gangs_total:
        p50 = quantile_from_buckets(gang_buckets, 0.5)
        p95 = quantile_from_buckets(gang_buckets, 0.95)
        # "hold" is a deferral, not a delivery — keep it out of the base
        total_jobs = sum(n for o, n in dispatch.items() if o != "hold") or 1
        lines.append(
            f"  gang      gangs={int(gangs_total)} "
            f"jobs={int(gang_jobs or 0)} "
            f"rate={min((gang_jobs or 0) / total_jobs, 1.0):.2f} "
            f"size p50<={'-' if p50 is None else int(p50)} "
            f"p95<={'-' if p95 is None else int(p95)}")

    shed = cur.counters("swarm_hive_shed_total", "class")
    pshed = prev.counters("swarm_hive_shed_total", "class") if prev else {}
    if shed:
        lines.append("  shed      " + " ".join(
            f"{c}={int(n)}{rate(n, pshed.get(c), dt)}"
            for c, n in sorted(shed.items())))
    # cancellation & deadlines (ISSUE 10): revoked jobs by the stage the
    # cancel caught them in, TTL expiries, and lease revocations still
    # waiting for their lessee's next poll
    cancelled = cur.counters("swarm_hive_cancelled_total", "stage")
    expired = cur.gauge("swarm_hive_expired_total")
    pending_rev = cur.gauge("swarm_hive_cancel_revocations_pending")
    if cancelled or expired or pending_rev:
        pcancelled = prev.counters(
            "swarm_hive_cancelled_total", "stage") if prev else {}
        parts = [f"{s}={int(n)}{rate(n, pcancelled.get(s), dt)}"
                 for s, n in sorted(cancelled.items())]
        parts.append(f"expired={int(expired or 0)}")
        parts.append(f"pending_revocations={int(pending_rev or 0)}")
        lines.append("  cancel    " + " ".join(parts))
    results = cur.counters("swarm_hive_results_total", "status")
    if results:
        lines.append("  results   " + " ".join(
            f"{s}={int(n)}" for s, n in sorted(results.items())))

    # preemption plane (ISSUE 18): durable mid-pass checkpoint blobs,
    # progressive-preview artifacts, and resume offers extended to
    # capable workers on redelivery
    ckpts = cur.counters("swarm_hive_checkpoints_total", "outcome")
    previews = cur.counters("swarm_hive_previews_total", "outcome")
    offers = cur.gauge("swarm_hive_resume_offers_total")
    if ckpts or previews or offers:
        parts = []
        if ckpts:
            parts.append("checkpoints " + " ".join(
                f"{o}={int(n)}" for o, n in sorted(ckpts.items())))
        if previews:
            parts.append("previews " + " ".join(
                f"{o}={int(n)}" for o, n in sorted(previews.items())))
        parts.append(f"resume_offers={int(offers or 0)}")
        lines.append("  partials  " + "  ".join(parts))

    # fleet observability plane (ISSUE 11): top-K tenants by
    # chip-seconds (the hive folds the rest into 'other'), per-class SLO
    # compliance + burn rate, and the worst straggler worker
    tenant_chip = cur.counters("swarm_hive_tenant_chip_seconds_total",
                               "tenant")
    tenant_rows = cur.counters("swarm_hive_tenant_rows_total", "tenant")
    # cost plane (ISSUE 17): petaflops served alongside the
    # chip-seconds they were served in
    tenant_flops = cur.counters("swarm_hive_tenant_flops_total", "tenant")
    if tenant_chip:
        ranked = sorted(tenant_chip.items(), key=lambda kv: (-kv[1], kv[0]))
        lines.append("  tenants   " + " ".join(
            f"{t}={chip:.1f}s/{int(tenant_rows.get(t, 0))}r"
            + (f"/{tenant_flops[t] / 1e15:.4f}Pf"
               if t in tenant_flops else "")
            for t, chip in ranked))
    slo = h.get("slo") or {}
    if slo:
        parts = []
        for cls in JOB_CLASSES:
            view = slo.get(cls)
            if not view:
                continue
            verdict = "BURNING" if view.get("breaching") else "ok"
            parts.append(
                f"{cls} burn={view.get('fast_burn', 0):.2f}/"
                f"{view.get('slow_burn', 0):.2f} "
                f"comp={view.get('compliance', 1):.2f} {verdict}")
        if parts:
            lines.append("  slo       " + "  ".join(parts))
    outliers = cur.counters("swarm_hive_worker_outlier", "worker")
    flagged = sorted(w for w, v in outliers.items() if v >= 1)
    if flagged:
        stages = h.get("stragglers") or {}
        worst = flagged[0]
        lines.append(
            f"  straggler {' '.join(flagged)}"
            + (f" (stages: {','.join(stages.get(worst) or [])})"
               if stages.get(worst) else ""))

    wal = h.get("wal") or {}
    if wal:
        lines.append(
            f"  wal       appends_since_compact="
            f"{wal.get('appends_since_compact', '?')} "
            f"torn={wal.get('torn_lines', '?')} "
            f"replayed={wal.get('replayed_events', '?')}")
    rep = h.get("replication") or {}
    if rep:
        lines.append(
            f"  replica   rs={rep.get('rs_applied', '?')}"
            f"/{rep.get('rs_primary_tip', '?')} "
            f"last_sync={rep.get('last_sync_age_s', '?')}s")

    for name, label in ((HIVE_WAIT_METRIC, "queue_wait"),
                        (HIVE_D2S_METRIC, "disp->settle")):
        parts = []
        for cls in JOB_CLASSES:
            buckets = bucket_delta(
                cur.histogram(name, **{"class": cls}),
                prev.histogram(name, **{"class": cls}) if prev else None)
            p50 = quantile_from_buckets(buckets, 0.5)
            if p50 is None:
                continue
            p95 = quantile_from_buckets(buckets, 0.95)
            parts.append(f"{cls} p50<={fmt_s(p50)} p95<={fmt_s(p95)}")
        if parts:
            lines.append(f"  {label:<9} " + "  ".join(parts))
    return lines


def render_worker(cur: Snapshot, prev: Snapshot | None) -> list[str]:
    lines = [f"WORKER {cur.url}"]
    if cur.error:
        lines.append(f"  unreachable: {cur.error}")
        return lines
    h = cur.health
    outbox = h.get("outbox") or {}
    age = h.get("last_poll_age_s")
    lines[0] += (f"  status={h.get('status', '?')}"
                 f" in_flight={h.get('jobs_in_flight', '?')}"
                 f" outbox={outbox.get('depth', '?')}"
                 f" last_poll={'-' if age is None else f'{age:g}s'}")
    for reason in h.get("degraded_reasons") or []:
        lines.append(f"  ! {reason}")
    hive = h.get("hive") or {}
    if hive:
        lines.append(
            f"  hive      {hive.get('active_endpoint', '?')} "
            f"failovers={hive.get('failovers', '?')} "
            f"epoch={hive.get('epoch', '?')}")
    for s in h.get("slices") or []:
        resident = ",".join(s.get("resident") or []) or "-"
        busy = "busy" if s.get("busy") else "idle"
        # mesh view of the slice's most recent pass (ISSUE 12): batch
        # traffic shows dataN·tensor1·seq1, a sharded interactive pass
        # flips tensor/seq up for its duration
        geometry = s.get("geometry") or "-"
        lines.append(
            f"  slice {s.get('slice_id', '?')}   {busy:<5} "
            f"{s.get('state', '?'):<12} {geometry:<22} "
            f"resident: {resident}")

    # prompt-embedding cache (ISSUE 9): per-row hit rate — at scale the
    # shared "" negative alone should hold this well above zero
    embed = cur.counters("swarm_embed_cache_total", "event")
    hits, misses = embed.get("hit", 0.0), embed.get("miss", 0.0)
    if hits + misses > 0:
        dt = (cur.taken - prev.taken) if prev else 0.0
        pembed = prev.counters(
            "swarm_embed_cache_total", "event") if prev else {}
        lines.append(
            f"  embed     hit={int(hits)}"
            f"{rate(hits, pembed.get('hit'), dt)} miss={int(misses)} "
            f"hit_rate={hits / (hits + misses):.2f}")

    # adapter serving (ISSUE 13): rows by execution mode (delta = the
    # runtime per-row path, merged = the fallback full-tree copy) plus
    # the factor cache's residency and hit rate — and (ISSUE 16) the
    # stacked-operand cache's steady-state hit rate + device bytes
    # resident, the zero-upload signal
    lrows = cur.counters("swarm_lora_rows_total", "mode")
    lcache = cur.counters("swarm_lora_cache_total", "event")
    lhits, lmisses = lcache.get("hit", 0.0), lcache.get("miss", 0.0)
    opcache = cur.counters("swarm_lora_operand_cache_total", "event")
    ohits, omisses = opcache.get("hit", 0.0), opcache.get("miss", 0.0)
    adapter_rows = lrows.get("delta", 0.0) + lrows.get("merged", 0.0)
    if adapter_rows > 0 or lhits + lmisses > 0 or ohits + omisses > 0:
        entries = cur.gauge("swarm_lora_cache_entries") or 0
        cache_bit = ""
        if lhits + lmisses > 0:
            cache_bit = (f" cache_hit_rate={lhits / (lhits + lmisses):.2f} "
                         f"factors={int(entries)}")
        operand_bit = ""
        if ohits + omisses > 0:
            resident_mb = (cur.gauge("swarm_lora_operand_cache_bytes")
                           or 0) / (1 << 20)
            operand_bit = (
                f" operand_hit_rate={ohits / (ohits + omisses):.2f} "
                f"resident={resident_mb:.0f}MB")
        lines.append(
            f"  adapters  delta={int(lrows.get('delta', 0))} "
            f"merged={int(lrows.get('merged', 0))} "
            f"plain={int(lrows.get('none', 0))}{cache_bit}{operand_bit}")

    # serving-path cost plane (ISSUE 17): analytic TFLOPs served per
    # model with the achieved fleet rate over the last interval, MFU
    # where the chip has a peak-FLOPs table entry (never on CPU), and
    # the compiled-program ledger's live population
    pass_flops = cur.counters("swarm_pass_flops_total", "model")
    if pass_flops:
        dt = (cur.taken - prev.taken) if prev else 0.0
        pflops = prev.counters(
            "swarm_pass_flops_total", "model") if prev else {}
        bits = []
        for m, v in sorted(pass_flops.items()):
            bit = f"{m}={v / 1e12:.2f}T"
            pv = pflops.get(m)
            if pv is not None and dt > 0 and v >= pv:
                bit += f"(+{(v - pv) / dt / 1e12:.2f}T/s)"
            bits.append(bit)
        mfu = {f"{labels['model']}/{labels['geometry']}": v
               for metric, labels, v in cur.samples
               if metric == "swarm_pass_mfu"
               and "model" in labels and "geometry" in labels}
        mfu_bit = ""
        if mfu:
            mfu_bit = " mfu " + " ".join(
                f"{k}={v:.2f}" for k, v in sorted(mfu.items()))
        live = sum(cur.counters("swarm_programs_live", "model").values())
        lines.append(
            f"  cost      {' '.join(bits)}{mfu_bit} programs={int(live)}")

    # preemption tolerance (ISSUE 18): mid-pass checkpoints shipped at
    # chunk boundaries, preview frames decoded, and redelivered passes
    # that actually resumed from a checkpoint instead of recomputing
    ckpts = cur.counters("swarm_checkpoints_total", "outcome")
    previews = cur.counters("swarm_previews_total", "outcome")
    resumes = cur.counters("swarm_resume_total", "outcome")
    if ckpts or previews or resumes:
        dt = (cur.taken - prev.taken) if prev else 0.0
        pck = prev.counters(
            "swarm_checkpoints_total", "outcome") if prev else {}
        shipped = ckpts.get("shipped", 0.0)
        parts = [f"checkpoints={int(shipped)}"
                 f"{rate(shipped, pck.get('shipped'), dt)}"]
        for outcome in ("oversize", "error"):
            if ckpts.get(outcome):
                parts.append(f"{outcome}={int(ckpts[outcome])}")
        parts.append(f"previews={int(previews.get('shipped', 0))}")
        parts.append(f"resumed={int(resumes.get('resumed', 0))}")
        degraded = (resumes.get("fetch_failed", 0.0)
                    + resumes.get("unpack_failed", 0.0))
        if degraded:
            parts.append(f"resume_degraded={int(degraded)}")
        lines.append("  resume    " + " ".join(parts))

    # per-stage latency over the last interval (cumulative in --once)
    stages: dict[str, dict[float, float]] = {}
    for metric, labels, value in cur.samples:
        if metric == f"{STAGE_METRIC}_bucket" and "stage" in labels:
            le = labels.get("le", "+Inf")
            stages.setdefault(labels["stage"], {})[
                float("inf") if le == "+Inf" else float(le)] = value
    parts = []
    for stage in sorted(stages):
        buckets = bucket_delta(
            stages[stage],
            prev.histogram(STAGE_METRIC, stage=stage) if prev else None)
        if not any(buckets.values()):
            continue  # no samples this interval
        p50 = quantile_from_buckets(buckets, 0.5)
        p95 = quantile_from_buckets(buckets, 0.95)
        parts.append(f"{stage} p50<={fmt_s(p50)} p95<={fmt_s(p95)}")
    if parts:
        lines.append("  stages    " + "  ".join(parts))
    return lines


def render_frame(hive: Snapshot | None, workers: list[Snapshot],
                 prev_hive: Snapshot | None,
                 prev_workers: dict[str, Snapshot],
                 interval: float | None) -> str:
    header = time.strftime("swarm_top  %H:%M:%S")
    if interval:
        header += f"  (refresh {interval:g}s; stage quantiles are per-interval)"
    blocks = [header]
    if hive is not None:
        blocks.append("\n".join(render_hive(hive, prev_hive)))
    for snap in workers:
        blocks.append("\n".join(
            render_worker(snap, prev_workers.get(snap.url))))
    return "\n\n".join(blocks)


# --- main loop --------------------------------------------------------------


async def run(args) -> int:
    import aiohttp

    prev_hive: Snapshot | None = None
    prev_workers: dict[str, Snapshot] = {}
    timeout = aiohttp.ClientTimeout(total=args.timeout)
    async with aiohttp.ClientSession(timeout=timeout) as session:
        while True:
            tasks = []
            if args.hive:
                tasks.append(scrape(session, args.hive))
            tasks.extend(scrape(session, w) for w in args.worker)
            snaps = await asyncio.gather(*tasks)
            hive = snaps[0] if args.hive else None
            workers = list(snaps[1 if args.hive else 0:])
            frame = render_frame(
                hive, workers, prev_hive, prev_workers,
                None if args.once else args.interval)
            if not args.once and sys.stdout.isatty():
                sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
            if args.json:
                # after the clear, or live mode would wipe it instantly
                print(json.dumps({
                    "hive": None if hive is None else
                    {"url": hive.url, "health": hive.health,
                     "error": hive.error},
                    "workers": [{"url": w.url, "health": w.health,
                                 "error": w.error} for w in workers],
                }))
            print(frame, flush=True)
            if args.once:
                ok = (hive is None or hive.error is None) and all(
                    w.error is None for w in workers)
                return 0 if ok else 1
            prev_hive, prev_workers = hive, {w.url: w for w in workers}
            await asyncio.sleep(args.interval)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="swarm_top", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument(
        "--hive", default=None,
        help="hive base URL (e.g. http://127.0.0.1:9511)")
    parser.add_argument(
        "--worker", action="append", default=[],
        help="worker telemetry base URL (repeatable; "
             "Settings.metrics_port, default :8061)")
    parser.add_argument(
        "--interval", type=float, default=2.0,
        help="refresh cadence in seconds (default 2)")
    parser.add_argument(
        "--once", action="store_true",
        help="render one snapshot and exit (scripting/CI mode)")
    parser.add_argument(
        "--json", action="store_true",
        help="also emit one machine-readable JSON line per frame")
    parser.add_argument(
        "--timeout", type=float, default=5.0,
        help="per-endpoint scrape timeout in seconds")
    args = parser.parse_args(argv)
    if not args.hive and not args.worker:
        parser.error("nothing to watch: pass --hive and/or --worker")
    try:
        return asyncio.run(run(args))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
