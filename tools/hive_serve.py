"""Run the embedded hive coordinator (chiaswarm_tpu/hive_server/).

    python tools/hive_serve.py                       # settings defaults
    python tools/hive_serve.py --port 9511 --host 0.0.0.0
    python tools/hive_serve.py --lease-deadline 120 --queue-limit 64

Workers need no changes: a stock worker with `sdaas_uri` pointing at
this process (the defaults already line up on one host — port 9511)
polls `/api/work`, executes, and POSTs `/api/results` exactly as it
would against the production hive. Submit jobs with:

    curl -X POST http://127.0.0.1:9511/api/jobs \
         -H "Authorization: Bearer $SDAAS_TOKEN" \
         -d '{"workflow": "txt2img", "model_name": "...", \
              "prompt": "...", "priority": "interactive"}'

then watch `GET /api/jobs/<id>`; `/metrics` and `/healthz` serve the
hive-side catalog (swarm_hive_queue_depth, swarm_hive_dispatch_total,
swarm_hive_leases_expired_total, ...). The server imports no jax — it
runs fine on a CPU-only coordinator host.
"""

from __future__ import annotations

import argparse
import asyncio
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from chiaswarm_tpu.hive_server.app import serve  # noqa: E402
from chiaswarm_tpu.settings import load_settings  # noqa: E402


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--host", default=None,
                        help="bind address (default: Settings.hive_host)")
    parser.add_argument("--port", type=int, default=None,
                        help="bind port (default: Settings.hive_port; "
                             "0 = ephemeral)")
    parser.add_argument("--lease-deadline", type=float, default=None,
                        metavar="S", help="override hive_lease_deadline_s")
    parser.add_argument("--queue-limit", type=int, default=None,
                        help="override hive_queue_depth_limit")
    parser.add_argument("--standby-of", default=None, metavar="URI",
                        help="run as a WAL-shipped standby of this "
                             "primary (overrides hive_standby_of; "
                             "replicates + auto-promotes on failover)")
    args = parser.parse_args(argv)

    settings = load_settings()
    if args.lease_deadline is not None:
        settings.hive_lease_deadline_s = args.lease_deadline
    if args.queue_limit is not None:
        settings.hive_queue_depth_limit = args.queue_limit
    if args.standby_of is not None:
        settings.hive_standby_of = args.standby_of
    try:
        asyncio.run(serve(settings, host=args.host, port=args.port))
    except KeyboardInterrupt:
        print("hive stopped")
    return 0


if __name__ == "__main__":
    sys.exit(main())
