# TPU worker image. The reference built on pytorch/cuda11.7 and bind-mounted
# the HF cache (reference Dockerfile:26-37); here the base is a plain Python
# image with jax[tpu] from the libtpu release channel, and the converted-
# weights model root plus the XLA compilation cache are the volumes.
FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
        ffmpeg libgl1 libglib2.0-0 \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY pyproject.toml README.md ./
COPY chiaswarm_tpu ./chiaswarm_tpu
# golden-image manifest (chiaswarm-tpu-golden --check against pinned hashes)
COPY goldens ./goldens

RUN pip install --no-cache-dir -e ".[media,download]" \
    && pip install --no-cache-dir "jax[tpu]" \
         -f https://storage.googleapis.com/jax-releases/libtpu_releases.html

# settings.json + logs; converted model weights; persistent XLA cache
VOLUME ["/root/.sdaas"]
ENV SDAAS_ROOT=/root/.sdaas

# first run: chiaswarm-tpu-init --download (prefetch + convert + check)
CMD ["chiaswarm-tpu-worker"]
