"""Device-resident adapter operand stacks: zero-upload steady state.

ISSUE 16. PR 13 made adapter identity *data* (zero-padded A/B stacks
indexed by per-row slot ids), but every coalesced pass still re-ran the
host-side numpy assembly and re-uploaded the stacks (`build_operands`
ends in `jnp.asarray`) — at rank cap with full slots that is hundreds
of MB of host→device transfer per pass, paid even when the SAME gang of
adapters repeats forever. This module keeps the already-stacked,
already-device-placed operands resident in a byte-capped process-wide
LRU keyed by the full recipe that produced them:

    (model name, ordered adapter-key tuple, operand signature
     (slot bucket, rank bucket, module-path set), dtype, geometry view)

Scale is deliberately absent from the key: ``alpha/rank`` is folded into
the A stack host-side (adapter-intrinsic, scale-independent) and
``lora_scale`` rides the tiny per-row gain vector, so the same adapter
at two scales is ONE resident stack, not two uploads.

Coherence: the raw-factor LRU (lora_cache.py) is the source of truth
for adapter bytes. This cache registers an invalidation hook there —
evicting or replacing a factor entry drops every operand entry derived
from it, so a re-resolved adapter with different weights can never keep
serving stale device arrays.

Eviction explicitly frees the device buffers (``.delete()`` on every
jax array in the entry) instead of waiting for the GC: the whole point
of the byte cap is bounding HBM, so reclaim must be immediate (swarmlint
SW007).

Sized by ``Settings.lora_operand_cache_mb``
(``CHIASWARM_LORA_OPERAND_CACHE_MB``; 0 disables — passes still run,
they just re-assemble and re-upload like PR 13 did).

Import-time jax-free: the hive server imports the package tree and must
not drag in jax. Thread-safe: slice executor threads consult it
concurrently.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from . import lora_cache, telemetry
from .coalesce import wire_adapter_ref

_EVENTS = telemetry.counter(
    "swarm_lora_operand_cache_total",
    "Device-resident operand-stack cache lookups by outcome (miss = the "
    "stacks were re-assembled host-side and re-uploaded)",
    ("event",),
)
_BYTES = telemetry.gauge(
    "swarm_lora_operand_cache_bytes",
    "Bytes of stacked adapter operands currently resident on device "
    "(bounded by Settings.lora_operand_cache_mb)")
_ENTRIES = telemetry.gauge(
    "swarm_lora_operand_cache_entries",
    "Distinct operand-stack recipes resident in the operand cache")


def ref_of_key(akey: tuple) -> str:
    """Factor-cache adapter key (ref, weight_name, subfolder) -> the
    canonical wire ref workers advertise on /work. Delegates to
    coalesce.wire_adapter_ref so the advertisement and the hive's
    canonical_adapter_ref(job) — computed from the RAW job before the
    worker's loras.resolve_lora normalization rewrote the fields —
    spell the same adapter identically."""
    ref, name, sub = (tuple(akey) + (None, None, None))[:3]
    return wire_adapter_ref(ref, name, sub)


def _free(value) -> None:
    """Release device buffers held by an evicted entry, recursively.
    numpy leaves have no .delete(); already-deleted jax buffers raise —
    both are fine, the entry is unreachable either way."""
    if isinstance(value, dict):
        for leaf in value.values():
            _free(leaf)
    elif isinstance(value, (list, tuple)):
        for leaf in value:
            _free(leaf)
    else:
        delete = getattr(value, "delete", None)
        if callable(delete):
            try:
                delete()
            except Exception:
                pass


class LoraOperandCache:
    """Byte-capped LRU of device-resident operand stacks."""

    def __init__(self, max_bytes: int):
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._entries: OrderedDict[tuple, tuple] = OrderedDict()
        self._bytes = 0

    def lookup(self, key: tuple):
        """The cached (value, nbytes) for `key`, or None. Counts the
        hit; the caller counts the miss once assembly succeeds."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                _EVENTS.inc(event="hit")
            return entry

    def put(self, key: tuple, value, nbytes: int) -> None:
        _EVENTS.inc(event="miss")
        if nbytes > self.max_bytes:
            return  # one giant recipe must not wipe the whole cache
        freed = []
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
                freed.append(old[0])
            self._entries[key] = (value, int(nbytes))
            self._bytes += int(nbytes)
            while self._bytes > self.max_bytes and self._entries:
                _, entry = self._entries.popitem(last=False)
                self._bytes -= entry[1]
                freed.append(entry[0])
            _BYTES.set(self._bytes)
            _ENTRIES.set(len(self._entries))
        for value in freed:
            _free(value)

    def invalidate_where(self, pred) -> int:
        """Drop (and free) every entry whose key satisfies `pred`;
        returns how many were dropped."""
        freed = []
        with self._lock:
            doomed = [k for k in self._entries if pred(k)]
            for key in doomed:
                value, nbytes = self._entries.pop(key)
                self._bytes -= nbytes
                freed.append(value)
            _BYTES.set(self._bytes)
            _ENTRIES.set(len(self._entries))
        for value in freed:
            _free(value)
        return len(freed)

    def resident_adapter_refs(self) -> list[str]:
        """Canonical refs of every adapter with a resident operand
        stack, most-recently-used last (the /work advertisement)."""
        with self._lock:
            seen: dict[str, None] = {}
            for key in self._entries:
                for akey in key[1]:
                    seen[ref_of_key(akey)] = None
            return list(seen)

    @property
    def resident_bytes(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._entries)


_CACHE: LoraOperandCache | None = None
_CONFIGURED = False
_LOCK = threading.Lock()


def get_cache() -> LoraOperandCache | None:
    """The process-wide cache, sized from Settings.lora_operand_cache_mb
    on first use; None when disabled (0)."""
    global _CACHE, _CONFIGURED
    with _LOCK:
        if not _CONFIGURED:
            from .settings import load_settings

            try:
                mb = int(getattr(
                    load_settings(), "lora_operand_cache_mb", 0))
            except Exception:  # the cache is an optimization, never fatal
                mb = 0
            _CACHE = LoraOperandCache(mb * 1024 * 1024) if mb > 0 else None
            _CONFIGURED = True
        return _CACHE


def configure(max_bytes: int | None) -> LoraOperandCache | None:
    """Explicitly (re)size the process-wide cache — tests and benches;
    None or <= 0 disables. The old cache's device buffers are freed."""
    global _CACHE, _CONFIGURED
    with _LOCK:
        old = _CACHE
        _CACHE = (LoraOperandCache(int(max_bytes))
                  if max_bytes and int(max_bytes) > 0 else None)
        _CONFIGURED = True
        _BYTES.set(0)
        _ENTRIES.set(0)
    if old is not None:
        old.invalidate_where(lambda key: True)
    return _CACHE


def reset() -> None:
    """Forget the configured cache (next get_cache() re-reads Settings),
    freeing whatever it held."""
    global _CACHE, _CONFIGURED
    with _LOCK:
        old = _CACHE
        _CACHE = None
        _CONFIGURED = False
    if old is not None:
        old.invalidate_where(lambda key: True)


def invalidate_adapter(akey: tuple) -> None:
    """Drop every operand entry derived from factor-cache key `akey`."""
    cache = _CACHE
    if cache is not None:
        cache.invalidate_where(lambda key: akey in key[1])


def invalidate_model(model_name: str) -> None:
    """Drop every operand entry for `model_name` (pipeline release:
    the mesh the stacks were placed on is going away)."""
    cache = _CACHE
    if cache is not None:
        cache.invalidate_where(lambda key: key[0] == model_name)


def resident_adapter_refs() -> list[str]:
    """Canonical refs resident in the live cache (empty when disabled
    or unconfigured — advertising nothing is always safe)."""
    cache = _CACHE
    return cache.resident_adapter_refs() if cache is not None else []


def _on_factor_invalidate(akey) -> None:
    """Factor-cache coherence hook: a factor entry was evicted or
    replaced (akey) or the factor cache was reconfigured (None)."""
    cache = _CACHE
    if cache is None:
        return
    if akey is None:
        cache.invalidate_where(lambda key: True)
    else:
        cache.invalidate_where(lambda key: akey in key[1])


lora_cache.on_invalidate(_on_factor_invalidate)
