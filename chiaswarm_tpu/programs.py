"""Compiled-program ledger: every jit site, accounted.

ISSUE 17. The pipeline compiles programs at two kinds of site — the
constructor's aux programs (text encode, TE-delta encode, VAE encode,
latent 2x) and the per-bucket denoise variants flowing through
``SDPipeline._program`` (fused, prep, chunk, decode; geometry and
adapter-signature suffixed) — and until now the only visibility into
that population was a hit/miss counter. This module wraps each jitted
program in a thin instrumented callable that:

- times the FIRST call (trace + XLA compile + execute — the compile
  cost an operator actually pays at that site);
- captures XLA's own ``cost_analysis()`` (flops, bytes accessed) from
  the lowered module and ``memory_analysis()`` (argument / output /
  temp / generated-code bytes) from the compiled executable, both
  best-effort — an analysis API missing on some backend records an
  error string, never breaks serving;
- cross-checks the analytic FLOP denominator (models/flops.py) against
  XLA's count when the call site supplies its analytic figure, feeding
  ``swarm_flops_divergence_ratio{model}`` via costs.note_divergence;
- tracks the eviction lifecycle: ``_trim_program_caches`` calls
  ``clear_cache()`` on LRU-evicted programs, which marks the entry
  evicted here (the ledger keeps a bounded tail of evicted entries so
  /debug/programs shows churn, not just survivors).

Served at worker ``GET /debug/programs`` via ``snapshot()``.

Import-time jax-free: the ledger wraps callables it is handed and only
ever touches jax objects the pipeline already created.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

from . import costs, telemetry

# entries kept, live + evicted (popitem LRU below): big enough that a
# program_cache_max=64 pipeline's full churn history fits, small enough
# that a pathological retrace storm cannot grow the ledger unboundedly
MAX_ENTRIES = 512

_LIVE = telemetry.gauge(
    "swarm_programs_live",
    "Compiled XLA programs currently registered live in the program "
    "ledger (constructor aux programs + denoise variants), per model",
    ("model",),
)


class ProgramEntry:
    """One jit site's ledger row (mutable; snapshot() serialises it)."""

    __slots__ = ("model", "kind", "key", "state", "calls", "compile_s",
                 "analytic_flops", "xla", "memory", "divergence", "error",
                 "registered_at")

    def __init__(self, model: str, kind: str, key):
        self.model = model
        self.kind = kind
        self.key = repr(key) if key is not None else ""
        self.state = "registered"  # -> live (first call) -> evicted
        self.calls = 0
        self.compile_s = None
        self.analytic_flops = (None)
        self.xla = None  # {"flops", "bytes_accessed"} from cost_analysis
        self.memory = None  # byte breakdown from memory_analysis
        self.divergence = None  # xla_flops / analytic_flops
        self.error = None
        self.registered_at = time.time()

    def as_dict(self) -> dict:
        return {
            "model": self.model,
            "kind": self.kind,
            "key": self.key,
            "state": self.state,
            "calls": self.calls,
            "compile_s": (None if self.compile_s is None
                          else round(self.compile_s, 3)),
            "analytic_flops": self.analytic_flops,
            "xla": self.xla,
            "memory": self.memory,
            "divergence": (None if self.divergence is None
                           else round(self.divergence, 4)),
            "error": self.error,
        }


_LOCK = threading.Lock()
_LEDGER: OrderedDict[int, ProgramEntry] = OrderedDict()
_next_id = 0


def _flops_of(analysis) -> float | None:
    """The 'flops' figure from a cost_analysis() result, which jax
    returns as a dict (Lowered) or a 1-element list of dicts
    (Compiled) depending on version and stage."""
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    if isinstance(analysis, dict):
        v = analysis.get("flops")
        if isinstance(v, (int, float)) and v >= 0:
            return float(v)
    return None


def _capture(entry: ProgramEntry, fn, args, kwargs):
    """Best-effort XLA analysis of the program, using the exact
    arguments its first call traced with. Returns the AOT-compiled
    executable when one was produced — the wrapper executes through it,
    so the analysed compile IS the serving compile (the jit path would
    not share it and the site would pay XLA twice). Everything is
    guarded: the ledger corroborates, it must never fail a pass."""
    try:
        lowered = fn.lower(*args, **kwargs)
    except Exception as e:  # non-loweable wrapper, backend quirk, ...
        entry.error = f"lower: {type(e).__name__}: {e}"
        return None
    try:
        analysis = lowered.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        flops = _flops_of(analysis)
        entry.xla = {
            "flops": flops,
            "bytes_accessed": analysis.get("bytes accessed")
            if isinstance(analysis, dict) else None,
        }
    except Exception as e:
        entry.error = f"cost_analysis: {type(e).__name__}: {e}"
    compiled = None
    try:
        compiled = lowered.compile()
        stats = compiled.memory_analysis()
        if stats is not None:
            arg_b = int(getattr(stats, "argument_size_in_bytes", 0) or 0)
            out_b = int(getattr(stats, "output_size_in_bytes", 0) or 0)
            tmp_b = int(getattr(stats, "temp_size_in_bytes", 0) or 0)
            code_b = int(getattr(
                stats, "generated_code_size_in_bytes", 0) or 0)
            entry.memory = {
                "argument_bytes": arg_b,
                "output_bytes": out_b,
                "temp_bytes": tmp_b,
                "generated_code_bytes": code_b,
                # what the executable pins at once: arguments + outputs
                # + scratch (an upper bound; XLA may alias)
                "peak_bytes": arg_b + out_b + tmp_b,
            }
    except Exception as e:
        entry.error = f"memory_analysis: {type(e).__name__}: {e}"
    xla_flops = entry.xla.get("flops") if entry.xla else None
    if entry.analytic_flops and xla_flops:
        entry.divergence = costs.note_divergence(
            entry.model, entry.analytic_flops, xla_flops)
    return compiled


class InstrumentedProgram:
    """Thin callable wrapper around one jitted program. The first call
    lowers, analyses and AOT-compiles, then executes through that same
    executable — one XLA compile total, exactly like the bare jit path
    (the jit cache and the AOT path do NOT share executables, so
    analyse-then-call-jit would compile everything twice). Argument
    signatures the AOT executable rejects (the jit path is laxer) fall
    back to the jitted callable, permanently for that site. Exposes
    ``clear_cache`` so the pipeline's LRU eviction (and its executable
    freeing) passes straight through — marking the ledger entry evicted
    and dropping the held executable on the way."""

    __slots__ = ("_fn", "_entry", "_compiled")

    def __init__(self, fn, entry: ProgramEntry):
        self._fn = fn
        self._entry = entry
        self._compiled = None

    def __call__(self, *args, **kwargs):
        entry = self._entry
        if entry.calls == 0:
            t0 = time.perf_counter()
            compiled = _capture(entry, self._fn, args, kwargs)
            out = _SENTINEL = object()
            if compiled is not None:
                try:
                    out = compiled(*args, **kwargs)
                    self._compiled = compiled
                except (TypeError, ValueError):
                    pass  # AOT signature stricter than jit: use jit path
            if out is _SENTINEL:
                out = self._fn(*args, **kwargs)
            entry.compile_s = time.perf_counter() - t0
            entry.calls += 1
            entry.state = "live"
            return out
        entry.calls += 1
        compiled = self._compiled
        if compiled is not None:
            try:
                return compiled(*args, **kwargs)
            except (TypeError, ValueError):
                self._compiled = None  # arg drift: hand back to jit cache
        return self._fn(*args, **kwargs)

    def clear_cache(self) -> None:
        entry = self._entry
        self._compiled = None
        if entry.state != "evicted":
            entry.state = "evicted"
            _refresh_live()
        clear = getattr(self._fn, "clear_cache", None)
        if callable(clear):
            clear()

    def lower(self, *args, **kwargs):
        return self._fn.lower(*args, **kwargs)

    def __getattr__(self, name):
        # a drop-in must expose whatever else the jitted callable does
        # (trace inspection, test recorders, future jax surface)
        return getattr(self._fn, name)


def _refresh_live() -> None:
    """Re-export the per-model live gauge (caller need not hold _LOCK —
    a slightly stale count beats a deadlock)."""
    counts: dict[str, int] = {}
    with _LOCK:
        for entry in _LEDGER.values():
            if entry.state != "evicted":
                counts[entry.model] = counts.get(entry.model, 0) + 1
        models = {e.model for e in _LEDGER.values()}
    for model in models:
        _LIVE.set(counts.get(model, 0), model=model)


def instrument(fn, *, model: str, kind: str, key=None,
               analytic_flops: float | None = None):
    """Register one jit site and return its instrumented wrapper (a
    drop-in for the jitted callable). ``analytic_flops`` — supplied by
    call sites that know their program's analytic UNet FLOP count —
    arms the divergence cross-check."""
    global _next_id
    entry = ProgramEntry(model, kind, key)
    if analytic_flops and analytic_flops > 0:
        entry.analytic_flops = float(analytic_flops)
    with _LOCK:
        _LEDGER[_next_id] = entry
        _next_id += 1
        while len(_LEDGER) > MAX_ENTRIES:
            _LEDGER.popitem(last=False)
    _refresh_live()
    return InstrumentedProgram(fn, entry)


def snapshot() -> dict:
    """The GET /debug/programs payload: every ledger entry (live ones
    first, registration order within each state) plus roll-up counts
    and the per-model worst divergence."""
    with _LOCK:
        entries = [e.as_dict() for e in _LEDGER.values()]
    entries.sort(key=lambda e: (e["state"] == "evicted",))
    live = sum(1 for e in entries if e["state"] != "evicted")
    divergence: dict[str, float] = {}
    for e in entries:
        d = e.get("divergence")
        if d is None:
            continue
        model = e["model"]
        prior = divergence.get(model)
        if prior is None or abs(d - 1.0) > abs(prior - 1.0):
            divergence[model] = d
    return {
        "programs": entries,
        "live": live,
        "evicted": len(entries) - live,
        "divergence": divergence,
    }


def resident_code_bytes() -> dict:
    """Memory-census provider: generated-code bytes of live programs
    (XLA's own figure where the backend reports one — 0 on CPU) plus
    the live-entry count, so /debug/memory totals the program LRUs next
    to the data caches."""
    with _LOCK:
        live = [e for e in _LEDGER.values() if e.state != "evicted"]
    code = sum((e.memory or {}).get("generated_code_bytes", 0) or 0
               for e in live)
    return {"bytes": int(code), "entries": len(live)}


def reset() -> None:
    """Drop every ledger entry (tests)."""
    with _LOCK:
        _LEDGER.clear()
    _refresh_live()
