"""Video IO: PIL frames <-> mp4/webm/gif, download with caps.

Reference parity: swarm/toolbox/video_helpers.py:53-111 (cv2 writers, gif
via diffusers' util, first-frame thumbnail) and swarm/video/pix2pix.py:
84-116,148-183 (30 MiB download cap, <=100 frame split). moviepy isn't in
this image, so resizing happens via PIL before encode instead of a
subprocess ffmpeg pass.
"""

from __future__ import annotations

import io
import os
import tempfile

import numpy as np
from PIL import Image

MAX_VIDEO_BYTES = 30 * 1024 * 1024  # reference swarm/video/pix2pix.py:95-98
MAX_FRAMES = 100  # reference swarm/video/pix2pix.py:40


def _cv2():
    import cv2

    return cv2


def frames_to_video_buffer(frames: list[Image.Image], fps: int = 8,
                           content_type: str = "video/mp4") -> io.BytesIO:
    """Encode PIL frames into an mp4 (mp4v) or webm (VP90) buffer via cv2.

    cv2 writers need a real file path; encode through a temp file.
    """
    cv2 = _cv2()
    if content_type == "video/webm":
        fourcc, suffix = cv2.VideoWriter_fourcc(*"VP90"), ".webm"
    else:
        fourcc, suffix = cv2.VideoWriter_fourcc(*"mp4v"), ".mp4"

    w, h = frames[0].size
    fd, path = tempfile.mkstemp(suffix=suffix)
    os.close(fd)
    try:
        writer = cv2.VideoWriter(path, fourcc, fps, (w, h))
        try:
            for frame in frames:
                arr = np.asarray(frame.convert("RGB"))
                writer.write(cv2.cvtColor(arr, cv2.COLOR_RGB2BGR))
        finally:
            writer.release()
        with open(path, "rb") as f:
            return io.BytesIO(f.read())
    finally:
        os.unlink(path)


def frames_to_gif_buffer(frames: list[Image.Image], fps: int = 8) -> io.BytesIO:
    buffer = io.BytesIO()
    frames[0].save(
        buffer, format="GIF", save_all=True, append_images=frames[1:],
        duration=max(1, int(1000 / fps)), loop=0,
    )
    buffer.seek(0)
    return buffer


def export_frames(frames: list[Image.Image], content_type: str, fps: int = 8):
    """-> (video buffer, actual content_type). Falls back to GIF when cv2
    can't encode the requested container."""
    if content_type == "image/gif":
        return frames_to_gif_buffer(frames, fps), content_type
    try:
        return frames_to_video_buffer(frames, fps, content_type), content_type
    except Exception:
        return frames_to_gif_buffer(frames, fps), "image/gif"


def first_frame_thumbnail(frames: list[Image.Image]) -> io.BytesIO:
    thumb = frames[0].convert("RGB").copy()
    thumb.thumbnail((100, 100))
    buffer = io.BytesIO()
    thumb.save(buffer, format="JPEG")
    buffer.seek(0)
    return buffer


def download_video(url: str, max_bytes: int = MAX_VIDEO_BYTES) -> str:
    """Stream a remote video to a temp file, enforcing the size cap."""
    import requests

    response = requests.get(url, stream=True, timeout=30)
    response.raise_for_status()
    length = response.headers.get("content-length")
    if length and int(length) > max_bytes:
        raise ValueError(f"video exceeds the {max_bytes >> 20} MiB limit")

    fd, path = tempfile.mkstemp(suffix=".mp4")
    size = 0
    with os.fdopen(fd, "wb") as f:
        for chunk in response.iter_content(chunk_size=1 << 16):
            size += len(chunk)
            if size > max_bytes:
                os.unlink(path)
                raise ValueError(f"video exceeds the {max_bytes >> 20} MiB limit")
            f.write(chunk)
    return path


def split_video_frames(path: str, max_frames: int = MAX_FRAMES,
                       max_size: int = 512) -> tuple[list[Image.Image], float]:
    """-> (<=max_frames PIL frames downscaled to <=max_size, source fps)."""
    cv2 = _cv2()
    capture = cv2.VideoCapture(path)
    fps = capture.get(cv2.CAP_PROP_FPS) or 8.0
    frames = []
    try:
        while len(frames) < max_frames:
            ok, frame = capture.read()
            if not ok:
                break
            img = Image.fromarray(cv2.cvtColor(frame, cv2.COLOR_BGR2RGB))
            if max(img.size) > max_size:
                scale = max_size / max(img.size)
                img = img.resize(
                    (max(64, int(img.width * scale) // 8 * 8),
                     max(64, int(img.height * scale) // 8 * 8)),
                    Image.LANCZOS,
                )
            frames.append(img)
    finally:
        capture.release()
    if not frames:
        raise ValueError(f"could not decode any frames from {path}")
    return frames, float(fps)
