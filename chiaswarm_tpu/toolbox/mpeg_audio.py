"""Pure-numpy MPEG-1/2 Audio Layer I encoder — mp3-family artifacts offline.

The reference converts wav -> mp3 with pydub/ffmpeg and returns content
type ``audio/mpeg`` (reference swarm/audio/audioldm.py:17,30-34 and
swarm/audio/bark.py:12,32-34). Neither ffmpeg nor any mp3 library is in
this image, so the rebuild carries its own MPEG audio encoder: MPEG-1 /
MPEG-2-LSF **Layer I** (ISO 11172-3 / 13818-3), which shares the
``audio/mpeg`` stream format and decodes in the same players (mpg123,
ffmpeg, VLC, SDL_mixer) while being implementable — and *verifiable* —
offline. Layer III needs large normative Huffman tables that cannot be
reproduced from first principles without the spec text; Layer I is fully
determined by the polyphase filterbank + uniform midtread quantizers.

Every normative constant here was recovered **by black-box measurement
against a real decoder** (pygame's bundled libmpg123, driven over ctypes
— see tests/mpg123_ref.py):

- The 512-tap synthesis window ``_D``: crafted single-impulse frames per
  subband give the 32 synthesis impulse responses, which factor exactly
  as ``S[k][n] = D[n] * cos((2k+1)(n+16) pi/64)`` with D on a 2^-16 grid
  — i.e. the ISO table itself, recovered to the last bit. (Positions
  n = 16 mod 64 have a vanishing cosine, so D there is unconstrained /
  irrelevant; they are stored as 0.)
- Dequantization: ``value = scf * 2/(2^nb - 1) * (code - (2^(nb-1)-1))``
  — measured linear over every code for nb = 2..4, zero code verified.
- Scalefactors: index i -> ``2 * 2^(-i/3)`` — measured ratios match to
  1e-6 (ISO table B.1).

The analysis filterbank is the time-matched adjoint of the measured
synthesis (windows S/32 on a 32-sample hop); the encoder->libmpg123
roundtrip measures > 80 dB SNR unquantized, so the pair is
near-perfect-reconstruction against real decoders, not just in theory.

Encoding is vectorised numpy (one matmul per frame batch for the
filterbank); a 10 s clip encodes in well under a second on the worker
host, off the TPU path entirely.
"""

from __future__ import annotations

import io

import numpy as np

# ISO 11172-3 synthesis window x 2^16 (recovered by measurement, see
# module docstring). Zeros at n = 0 and n = 16 mod 64 are positions where
# the cosine modulation vanishes.
_D_TABLE = [
    0, -1, -1, -1, -1, -1, -1, -2,
    -2, -2, -2, -3, -3, -4, -4, -5,
    0, -6, -7, -7, -8, -9, -10, -11,
    -13, -14, -16, -17, -19, -21, -24, -26,
    -29, -31, -35, -38, -41, -45, -49, -53,
    -58, -63, -68, -73, -79, -85, -91, -97,
    -104, -111, -117, -125, -132, -139, -147, -154,
    -161, -169, -176, -183, -190, -196, -202, -208,
    -213, -218, -222, -225, -227, -228, -228, -227,
    -224, -221, -215, -208, -200, -189, -177, -163,
    0, -127, -106, -83, -57, -29, 2, 36,
    72, 111, 153, 197, 244, 294, 347, 401,
    459, 519, 581, 645, 711, 779, 848, 919,
    991, 1064, 1137, 1210, 1283, 1356, 1428, 1498,
    1567, 1634, 1698, 1759, 1817, 1870, 1919, 1962,
    2001, 2032, 2057, 2075, 2085, 2087, 2080, 2063,
    2037, 2000, 1952, 1893, 1822, 1739, 1644, 1535,
    1414, 1280, 1131, 970, 794, 605, 402, 185,
    0, -288, -545, -814, -1095, -1388, -1692, -2006,
    -2330, -2663, -3004, -3351, -3705, -4063, -4425, -4788,
    -5153, -5517, -5879, -6237, -6589, -6935, -7271, -7597,
    -7910, -8209, -8491, -8755, -8998, -9219, -9416, -9585,
    -9727, -9838, -9916, -9959, -9966, -9935, -9863, -9750,
    -9592, -9389, -9139, -8840, -8492, -8092, -7640, -7134,
    -6574, -5959, -5288, -4561, -3776, -2935, -2037, -1082,
    -70, 998, 2122, 3300, 4533, 5818, 7154, 8540,
    0, 11455, 12980, 14548, 16155, 17799, 19478, 21189,
    22929, 24694, 26482, 28289, 30112, 31947, 33791, 35640,
    37489, 39336, 41176, 43006, 44821, 46617, 48390, 50137,
    51853, 53534, 55178, 56778, 58333, 59838, 61289, 62684,
    64019, 65290, 66494, 67629, 68692, 69679, 70590, 71420,
    72169, 72835, 73415, 73908, 74313, 74630, 74856, 74992,
    75038, 74992, 74856, 74630, 74313, 73908, 73415, 72835,
    72169, 71420, 70590, 69679, 68692, 67629, 66494, 65290,
    0, 62684, 61289, 59838, 58333, 56778, 55178, 53534,
    51853, 50137, 48390, 46617, 44821, 43006, 41176, 39336,
    37489, 35640, 33791, 31947, 30112, 28289, 26482, 24694,
    22929, 21189, 19478, 17799, 16155, 14548, 12980, 11455,
    9975, 8540, 7154, 5818, 4533, 3300, 2122, 998,
    -70, -1082, -2037, -2935, -3776, -4561, -5288, -5959,
    -6574, -7134, -7640, -8092, -8492, -8840, -9139, -9389,
    -9592, -9750, -9863, -9935, -9966, -9959, -9916, -9838,
    0, -9585, -9416, -9219, -8998, -8755, -8491, -8209,
    -7910, -7597, -7271, -6935, -6589, -6237, -5879, -5517,
    -5153, -4788, -4425, -4063, -3705, -3351, -3004, -2663,
    -2330, -2006, -1692, -1388, -1095, -814, -545, -288,
    -45, 185, 402, 605, 794, 970, 1131, 1280,
    1414, 1535, 1644, 1739, 1822, 1893, 1952, 2000,
    2037, 2063, 2080, 2087, 2085, 2075, 2057, 2032,
    2001, 1962, 1919, 1870, 1817, 1759, 1698, 1634,
    0, 1498, 1428, 1356, 1283, 1210, 1137, 1064,
    991, 919, 848, 779, 711, 645, 581, 519,
    459, 401, 347, 294, 244, 197, 153, 111,
    72, 36, 2, -29, -57, -83, -106, -127,
    -146, -163, -177, -189, -200, -208, -215, -221,
    -224, -227, -228, -228, -227, -225, -222, -218,
    -213, -208, -202, -196, -190, -183, -176, -169,
    -161, -154, -147, -139, -132, -125, -117, -111,
    0, -97, -91, -85, -79, -73, -68, -63,
    -58, -53, -49, -45, -41, -38, -35, -31,
    -29, -26, -24, -21, -19, -17, -16, -14,
    -13, -11, -10, -9, -8, -7, -7, -6,
    -5, -5, -4, -4, -3, -3, -2, -2,
    -2, -2, -1, -1, -1, -1, -1, -1,
]

# Layer I bitrate tables, kbps (index 1..14; 0 = free, 15 = forbidden)
_BITRATES_V1 = [0, 32, 64, 96, 128, 160, 192, 224,
                256, 288, 320, 352, 384, 416, 448]
_BITRATES_V2 = [0, 32, 48, 56, 64, 80, 96, 112,
                128, 144, 160, 176, 192, 224, 256]
# sampling-rate index by version: header fs bits -> Hz
_RATES_V1 = {44100: 0, 48000: 1, 32000: 2}
_RATES_V2 = {22050: 0, 24000: 1, 16000: 2}

_SCF = 2.0 * 2.0 ** (-np.arange(63) / 3.0)  # ISO table B.1

_FRAME_SAMPLES = 384  # Layer I: 12 subband samples x 32 subbands


def _filterbank_matrices() -> tuple[np.ndarray, np.ndarray]:
    """(analysis [32,512], synthesis [32,512]) from the measured window."""
    d = np.asarray(_D_TABLE, np.float64) / 65536.0
    n = np.arange(512)
    k = np.arange(32)
    cos = np.cos((2 * k[:, None] + 1) * (n[None, :] + 16) * np.pi / 64)
    synth = d[None, :] * cos
    return synth / 32.0, synth


_ANALYSIS, _SYNTHESIS = _filterbank_matrices()

# Alignment of the analysis hop grid against the decoder's synthesis
# phase, found by maximising the measured roundtrip SNR (84.6 dB on white
# noise): the encoder consumes input delayed by 19 samples relative to
# the hop grid used below.
_PHASE = 19


class _BitWriter:
    def __init__(self) -> None:
        self.buf = bytearray()
        self.acc = 0
        self.n = 0

    def put(self, value: int, nbits: int) -> None:
        self.acc = (self.acc << nbits) | (value & ((1 << nbits) - 1))
        self.n += nbits
        while self.n >= 8:
            self.n -= 8
            self.buf.append((self.acc >> self.n) & 0xFF)

    def pad_to(self, nbytes: int) -> bytes:
        if self.n:
            self.buf.append((self.acc << (8 - self.n)) & 0xFF)
            self.acc = 0
            self.n = 0
        assert len(self.buf) <= nbytes, (len(self.buf), nbytes)
        return bytes(self.buf) + b"\x00" * (nbytes - len(self.buf))


def _pick_bitrate(rate: int, bitrate_kbps: int | None) -> tuple[int, int, list]:
    """-> (version_bits, bitrate_index, bitrate_table)."""
    if rate in _RATES_V1:
        version, table = 3, _BITRATES_V1
    elif rate in _RATES_V2:
        version, table = 2, _BITRATES_V2
    else:
        raise ValueError(
            f"unsupported MPEG audio rate {rate}; "
            f"supported: {sorted(_RATES_V1) + sorted(_RATES_V2)}"
        )
    if bitrate_kbps is None:
        # ~10 coded bits per PCM sample: measured 43 dB SNR at 8 bits,
        # ~60 dB at 10 on program material; Layer I has no Huffman stage
        # so it buys quality with rate
        want = 10 * rate // 1000
        candidates = [b for b in table[1:] if b >= want]
        bitrate_kbps = candidates[0] if candidates else table[-1]
    if bitrate_kbps not in table[1:]:
        raise ValueError(f"bitrate {bitrate_kbps} not in Layer I table {table[1:]}")
    return version, table.index(bitrate_kbps), table


def _analyze(pcm: np.ndarray) -> np.ndarray:
    """PCM [n] -> subband samples [T, 32] on a 32-sample hop."""
    x = np.concatenate([np.zeros(512 - 32 + _PHASE), pcm.astype(np.float64)])
    t = len(x) // 32
    hops = np.lib.stride_tricks.sliding_window_view(x, 512)[::32]
    hops = hops[: min(t, len(hops))]
    return hops @ _ANALYSIS.T


def _allocate(scaled_peaks: np.ndarray, budget_bits: int) -> np.ndarray:
    """Greedy MNR-driven bit allocation for one frame.

    `scaled_peaks` [32]: per-subband peak magnitude. Repeatedly grant
    bits to the subband whose quantization noise is worst relative to
    its signal level (6.02 dB per bit); starting a subband costs
    12*2 sample bits + 6 scalefactor bits, each further bit costs 12.
    """
    smr = 20.0 * np.log10(np.maximum(scaled_peaks, 1e-10))
    nb = np.zeros(32, np.int64)
    # silent subbands never get bits; threshold ~ -96 dBFS
    active = smr > -96.0
    while True:
        mnr = np.where(nb > 0, 6.02 * nb - smr, -smr - 0.0)
        mnr = np.where(active & (nb < 15), mnr, np.inf)
        sb = int(np.argmin(mnr))
        if not np.isfinite(mnr[sb]):
            break
        cost = 30 if nb[sb] == 0 else 12
        if budget_bits < cost:
            break
        nb[sb] += 2 if nb[sb] == 0 else 1
        budget_bits -= cost
    return nb


def encode_layer1(
    pcm: np.ndarray, rate: int, bitrate_kbps: int | None = None
) -> bytes:
    """float PCM in [-1, 1] (mono [n] or [n, ch] downmixed) -> MPEG Layer I.

    Returns a self-contained ``audio/mpeg`` elementary stream.
    """
    pcm = np.asarray(pcm, np.float64)
    if pcm.ndim == 2:
        pcm = pcm.mean(axis=1)
    peak = np.max(np.abs(pcm)) if pcm.size else 0.0
    if peak > 1.0:
        pcm = pcm / peak
    version, br_idx, table = _pick_bitrate(rate, bitrate_kbps)
    fs_idx = (_RATES_V1 if version == 3 else _RATES_V2)[rate]
    bitrate = table[br_idx] * 1000

    # pad so every frame is full
    nframes = (len(pcm) + _FRAME_SAMPLES - 1) // _FRAME_SAMPLES
    pcm = np.concatenate([pcm, np.zeros(nframes * _FRAME_SAMPLES - len(pcm))])
    sub = _analyze(pcm)  # [T, 32]
    sub = sub[: nframes * 12].reshape(nframes, 12, 32)

    # Layer I frame length is slots = floor(12*bitrate/fs) (+1 when the
    # padding bit is set); the standard accumulator decides padding so the
    # average rate is exact (only 44.1/22.05 kHz ever need it). The header
    # padding bit MUST match the emitted length or decoders lose sync.
    base_slots, frac = divmod(12 * bitrate, rate)
    out = io.BytesIO()
    acc = 0
    for f in range(nframes):
        acc += frac
        padding = 1 if acc >= rate else 0
        acc -= rate * padding
        frame_bits = (base_slots + padding) * 32
        frame = _encode_frame(
            sub[f], version, br_idx, fs_idx, padding, frame_bits
        )
        out.write(frame)
    return out.getvalue()


def _encode_frame(
    sub: np.ndarray, version: int, br_idx: int, fs_idx: int,
    padding: int, frame_bits: int,
) -> bytes:
    peaks = np.abs(sub).max(axis=0)  # [32]
    # smallest scalefactor still >= peak (the table is descending, so:
    # count entries >= peak, take the last of them — picking the next
    # SMALLER scf instead clips the loudest samples by up to 2^(1/3))
    ge = np.searchsorted(-_SCF, -np.maximum(peaks, 1e-10), side="right")
    scf_idx = np.clip(ge - 1, 0, 62)

    header_bits = 32
    alloc_bits = 32 * 4
    budget = frame_bits - header_bits - alloc_bits
    # allocate by RAW level: scf-normalized peaks are all ~1, which would
    # flatten the SMR and spread bits uniformly over noise-floor subbands
    nb = _allocate(peaks, budget)

    w = _BitWriter()
    w.put(0x7FF, 11)
    w.put(version, 2)      # 3 = MPEG-1, 2 = MPEG-2 LSF
    w.put(3, 2)            # Layer I
    w.put(1, 1)            # no CRC
    w.put(br_idx, 4)
    w.put(fs_idx, 2)
    w.put(padding, 1)
    w.put(0, 1)            # private
    w.put(3, 2)            # single channel
    w.put(0, 2)            # mode extension
    w.put(0, 1)            # copyright
    w.put(1, 1)            # original
    w.put(0, 2)            # no emphasis

    for sb in range(32):
        w.put(int(nb[sb]) - 1 if nb[sb] else 0, 4)
    for sb in range(32):
        if nb[sb]:
            w.put(int(scf_idx[sb]), 6)
    # quantize: code = round(x / (scf * 2/(2^nb-1))) + (2^(nb-1)-1)
    codes = np.zeros((12, 32), np.int64)
    for sb in range(32):
        if not nb[sb]:
            continue
        steps = (1 << int(nb[sb])) - 1
        step = _SCF[scf_idx[sb]] * 2.0 / steps
        zero = (1 << (int(nb[sb]) - 1)) - 1
        q = np.round(sub[:, sb] / step).astype(np.int64) + zero
        codes[:, sb] = np.clip(q, 0, steps)
    for s in range(12):
        for sb in range(32):
            if nb[sb]:
                w.put(int(codes[s, sb]), int(nb[sb]))
    return w.pad_to(frame_bits // 8)


def encode_mpeg_buffer(
    pcm: np.ndarray, rate: int, bitrate_kbps: int | None = None
) -> io.BytesIO:
    """Encoder entry for the audio pipelines: BytesIO of an audio/mpeg
    stream, rewound, mirroring wav_to_buffer's contract."""
    buf = io.BytesIO(encode_layer1(pcm, rate, bitrate_kbps))
    buf.seek(0)
    return buf


SUPPORTED_RATES = tuple(sorted(_RATES_V1) + sorted(_RATES_V2))
