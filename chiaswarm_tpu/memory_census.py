"""Fleet memory census: every byte-capped store, one ledger.

ISSUE 17. The repo grew byte-capped stores one PR at a time — the
embedding cache, the LoRA factor and device-operand caches, the
compiled-program LRUs, the hive's artifact spool, the worker's outbox,
the WAL — each with its own gauge, none with a unified answer to "how
many bytes is this process actually holding, and how close is the chip
to its HBM ceiling?". This module is that answer:

- a registry of named byte providers (``register``): module-level
  stores register pull-providers here at census time; instance-scoped
  stores (outbox, artifact spool, WAL) push-register from their
  constructors. Each provider returns at least ``{"bytes": int}`` plus
  whatever detail it wants surfaced;
- ``census()``: the ``GET /debug/memory`` payload — every store's
  bytes (exported as ``swarm_memory_store_bytes{store}``), the grand
  total, per-device HBM occupancy from ``device.memory_stats()``
  (chips/device.hbm_census), and the fleet's worst-device headroom
  ratio;
- ``device_headroom()``: the cheap headroom probe the worker's
  ``/healthz`` consults — below ``Settings.memory_headroom_degraded``
  the worker reports degraded, so an orchestrator sees an
  HBM-squeezed slice before the next big pass OOMs it.

Import-time jax-free (SW001): the accelerator side lives behind a lazy
chips.device import that only executes on worker call paths; providers
that fail (a store torn down mid-scrape) record an error detail, never
break the endpoint.
"""

from __future__ import annotations

import threading

from . import telemetry

_STORE_BYTES = telemetry.gauge(
    "swarm_memory_store_bytes",
    "Resident bytes per byte-capped store (embed cache, LoRA factor / "
    "operand caches, program ledger, outbox, artifact spool, WAL), "
    "refreshed on each /debug/memory census",
    ("store",),
)
_HBM_USED = telemetry.gauge(
    "swarm_device_hbm_used_bytes",
    "Bytes in use on each local device (device.memory_stats), refreshed "
    "on each /debug/memory census",
    ("device",),
)
_HBM_LIMIT = telemetry.gauge(
    "swarm_device_hbm_limit_bytes",
    "Per-device memory limit (device.memory_stats bytes_limit, falling "
    "back to the chips/device HBM table)",
    ("device",),
)
_HEADROOM = telemetry.gauge(
    "swarm_memory_headroom_ratio",
    "Worst-device free-HBM fraction (1 - used/limit); drives the "
    "low-headroom /healthz degradation (memory_headroom_degraded)",
)

_LOCK = threading.Lock()
_PROVIDERS: dict[str, object] = {}


def register(store: str, provider) -> None:
    """Register (or replace) the byte provider for `store`. Providers
    are callables returning ``{"bytes": int, ...detail}``. Instance
    stores re-register on construction — last instance wins, which is
    the live one."""
    with _LOCK:
        _PROVIDERS[str(store)] = provider


def unregister(store: str) -> None:
    with _LOCK:
        _PROVIDERS.pop(str(store), None)


def _cache_provider(get_cache):
    """Provider over the module-level cache pattern (embed_cache /
    lora_cache / lora_operands): resident bytes + entry count, 0 when
    the cache is disabled."""
    def provider() -> dict:
        cache = get_cache()
        if cache is None:
            return {"bytes": 0, "entries": 0, "enabled": False}
        return {"bytes": int(cache.resident_bytes), "entries": len(cache),
                "cap_bytes": int(getattr(cache, "max_bytes", 0))}
    return provider


def _builtin_providers() -> dict:
    """The process-wide stores every census can pull without anyone
    registering them (lazy imports: a hive-only process that never
    touched the worker stores still censuses cleanly)."""
    from . import embed_cache, lora_cache, lora_operands, programs

    return {
        "embed_cache": _cache_provider(embed_cache.get_cache),
        "lora_factor_cache": _cache_provider(lora_cache.get_cache),
        "lora_operand_cache": _cache_provider(lora_operands.get_cache),
        "program_ledger": programs.resident_code_bytes,
    }


def census() -> dict:
    """The GET /debug/memory payload: per-store bytes (gauges refreshed
    as a side effect), the total, per-device HBM occupancy, and the
    worst-device headroom."""
    with _LOCK:
        providers = dict(_PROVIDERS)
    for name, provider in _builtin_providers().items():
        providers.setdefault(name, provider)
    stores: dict[str, dict] = {}
    total = 0
    for name in sorted(providers):
        try:
            detail = providers[name]() or {}
        except Exception as e:  # a torn-down store must not 500 the census
            detail = {"bytes": 0, "error": f"{type(e).__name__}: {e}"}
        nbytes = detail.get("bytes")
        nbytes = int(nbytes) if isinstance(nbytes, (int, float)) else 0
        detail["bytes"] = nbytes
        _STORE_BYTES.set(nbytes, store=name)
        total += nbytes
        stores[name] = detail
    payload = {"stores": stores, "total_bytes": total}
    devices = _device_census()
    if devices is not None:
        payload["devices"] = devices
        headrooms = [d["headroom_ratio"] for d in devices
                     if d.get("headroom_ratio") is not None]
        if headrooms:
            payload["headroom_ratio"] = min(headrooms)
            _HEADROOM.set(payload["headroom_ratio"])
    return payload


def _device_census() -> list[dict] | None:
    """Per-device HBM view (worker processes only — returns None where
    no accelerator runtime is importable)."""
    try:
        from .chips.device import hbm_census
    except Exception:
        return None
    try:
        devices = hbm_census()
    except Exception:
        return None
    for d in devices:
        label = d.get("device", "?")
        used, limit = d.get("bytes_in_use"), d.get("bytes_limit")
        if isinstance(used, int):
            _HBM_USED.set(used, device=label)
        if isinstance(limit, int) and limit > 0:
            _HBM_LIMIT.set(limit, device=label)
            if isinstance(used, int):
                d["headroom_ratio"] = round(max(1.0 - used / limit, 0.0), 4)
        d.setdefault("headroom_ratio", None)
    return devices


def device_headroom() -> float | None:
    """Worst-device free-HBM fraction, or None when no device reports a
    limit (CPU smoke). Cheap enough for every /healthz probe; exports
    the headroom gauge as a side effect."""
    devices = _device_census()
    if not devices:
        return None
    headrooms = [d["headroom_ratio"] for d in devices
                 if d.get("headroom_ratio") is not None]
    if not headrooms:
        return None
    worst = min(headrooms)
    _HEADROOM.set(worst)
    return worst
