"""Durable result outbox: a finished job's envelope survives anything.

A denoise pass costs seconds-to-minutes of accelerator time; the round-6
`result_worker` threw that work away on the first failed upload (caught,
logged, dropped) and a worker restart lost everything still queued. This
module makes delivery a write-ahead contract instead:

- every result envelope is SPOOLED to disk (atomic tmp+rename JSON under
  ``$SDAAS_ROOT/outbox/``) before the first upload attempt;
- the upload loop retries transient failures with capped exponential
  backoff + jitter (``backoff_delay``); a permanent hive refusal (4xx)
  PARKS the entry — renamed aside, out of the retry loop, still on disk;
- the spool file is unlinked ONLY on hive ACK;
- on worker start, ``recover()`` re-enqueues every spooled entry from the
  previous process (parked ones included — the hive may accept now), so
  delivery is at-least-once across restarts and the hive dedupes by job
  id as it always has for resubmitted work.

Depth / oldest-age / retry counters feed /metrics and /healthz
(``saturated`` flips the worker's health to degraded so an orchestrator
can see a hive-side delivery stall before the disk fills).
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import logging
import os
import random
import re
import time
from pathlib import Path

from . import telemetry

logger = logging.getLogger(__name__)

# capped exponential backoff between delivery attempts for one entry;
# module-level so tests (and the chaos harness) can shrink them
BACKOFF_BASE_S = 0.5
BACKOFF_CAP_S = 30.0

_DEPTH = telemetry.gauge(
    "swarm_outbox_depth", "Result envelopes spooled on disk awaiting hive ACK")
_OLDEST = telemetry.gauge(
    "swarm_outbox_oldest_age_seconds",
    "Age of the oldest spooled result envelope (0 when empty)")
_SPOOLED = telemetry.counter(
    "swarm_outbox_spooled_total", "Result envelopes written to the outbox")
_DELIVERED = telemetry.counter(
    "swarm_outbox_delivered_total",
    "Result envelopes unlinked after a hive ACK")
_RETRIES = telemetry.counter(
    "swarm_outbox_retries_total",
    "Delivery attempts retried after a transient failure")
_PARKED = telemetry.counter(
    "swarm_outbox_parked_total",
    "Envelopes parked after a permanent hive refusal (kept on disk)")
_RECOVERED = telemetry.counter(
    "swarm_outbox_recovered_total",
    "Envelopes re-enqueued from a previous process's spool")

_SAFE_ID = re.compile(r"[^A-Za-z0-9._-]+")


def backoff_delay(retries: int, base: float | None = None,
                  cap: float | None = None) -> float:
    """Delay before attempt `retries`+1: exponential, capped, with jitter
    in [ceiling/2, ceiling] so a fleet retrying the same hive outage does
    not re-POST in lockstep."""
    base = BACKOFF_BASE_S if base is None else base
    cap = BACKOFF_CAP_S if cap is None else cap
    ceiling = min(cap, base * (2 ** max(int(retries) - 1, 0)))
    return random.uniform(ceiling / 2, ceiling)


@dataclasses.dataclass
class OutboxEntry:
    result: dict
    job_id: str
    path: Path | None  # None = spool write failed; in-memory only
    spooled_at: float
    retries: int = 0
    parked: bool = False


class Outbox:
    def __init__(self, directory: str | Path, max_entries: int = 512):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.max_entries = int(max_entries)
        self._seq = itertools.count()
        # fleet memory census (ISSUE 17): the spool directory's resident
        # bytes next to the in-HBM stores; last-constructed outbox wins
        from . import memory_census

        memory_census.register("outbox", self.resident_bytes)

    def resident_bytes(self) -> dict:
        """Census provider: spooled envelope bytes on disk (delivery
        spool + parked), plus the file count."""
        files = self._files()
        total = 0
        for path in files:
            try:
                total += path.stat().st_size
            except OSError:
                continue
        return {"bytes": total, "entries": len(files)}

    # --- spool lifecycle ---

    def spool(self, result: dict) -> OutboxEntry:
        """Persist one envelope before its first upload attempt. A failed
        write (full disk, bad mount) degrades to an in-memory entry — the
        job is still delivered this process, just not restart-durable —
        and is logged loudly rather than failing the job."""
        job_id = str(result.get("id", "unknown"))
        now = time.time()
        name = (f"{time.time_ns():020d}-{next(self._seq):04d}-"
                f"{_SAFE_ID.sub('_', job_id)[:80]}.json")
        path: Path | None = self.directory / name
        try:
            payload = json.dumps({"spooled_at": now, "result": result})
            tmp = path.with_name(path.name + ".tmp")
            tmp.write_text(payload)
            os.replace(tmp, path)
        except OSError:
            logger.exception(
                "outbox spool failed for %s; delivery is NOT restart-durable "
                "for this envelope", job_id)
            path = None
        _SPOOLED.inc()
        entry = OutboxEntry(result, job_id, path, now)
        self.refresh_gauges()
        return entry

    def delivered(self, entry: OutboxEntry) -> None:
        """Hive ACKed: the spool file may finally go away."""
        if entry.path is not None:
            try:
                entry.path.unlink(missing_ok=True)
            except OSError:
                logger.warning("could not unlink delivered entry %s",
                               entry.path)
        _DELIVERED.inc()
        self.refresh_gauges()

    def park(self, entry: OutboxEntry, reason: str | None = None) -> None:
        """Permanent hive refusal: take the entry out of the in-process
        retry loop but KEEP it on disk (renamed aside). recover() picks
        parked entries up on the next start — never a silent drop. The
        payload is rewritten with the delivery history (retries, when and
        why it parked) so `tools/outbox_inspect.py` can show an operator
        what happened without the process that knew."""
        entry.parked = True
        if entry.path is not None and not entry.path.name.endswith(".parked"):
            try:
                payload = json.dumps({
                    "spooled_at": entry.spooled_at,
                    "parked_at": time.time(),
                    "retries": entry.retries,
                    "park_reason": reason,
                    "result": entry.result,
                })
                tmp = entry.path.with_name(entry.path.name + ".tmp")
                tmp.write_text(payload)
                os.replace(tmp, entry.path)
                parked = entry.path.with_name(entry.path.name + ".parked")
                os.replace(entry.path, parked)
                entry.path = parked
            except OSError:
                logger.warning("could not park entry %s", entry.path)
        _PARKED.inc()
        self.refresh_gauges()

    def requeue_parked(self, job_id: str | None = None) -> list[Path]:
        """Move parked envelopes back into the delivery spool (strip the
        `.parked` suffix) so the next `recover()` — a worker restart —
        retries them against a hive that may accept them now (e.g. after
        a failover to a fresh primary). `job_id` picks one envelope;
        None requeues every parked one. Returns the restored paths; the
        ops entry point is `tools/outbox_inspect.py --requeue`."""
        restored: list[Path] = []
        for path in sorted(self.directory.glob("*.json.parked")):
            if job_id is not None:
                try:
                    payload = json.loads(path.read_text())
                    result = payload.get("result") or {}
                except (OSError, ValueError):
                    continue
                if str(result.get("id")) != str(job_id):
                    continue
            target = path.with_name(path.name[: -len(".parked")])
            try:
                os.replace(path, target)
                restored.append(target)
            except OSError:
                logger.warning("could not requeue parked entry %s", path)
        self.refresh_gauges()
        return restored

    def recover(self) -> list[OutboxEntry]:
        """Entries spooled by a previous process, oldest first. Unreadable
        files are left in place and logged — an operator can still recover
        the artifacts by hand."""
        entries = []
        for path in self._files():
            try:
                payload = json.loads(path.read_text())
                result = payload["result"]
            except (OSError, ValueError, KeyError, TypeError):
                logger.exception(
                    "unreadable outbox entry %s; leaving it on disk", path)
                continue
            entries.append(OutboxEntry(
                result,
                str(result.get("id", "unknown")),
                path,
                float(payload.get("spooled_at", time.time())),
                retries=int(payload.get("retries", 0) or 0),
                parked=path.name.endswith(".parked"),
            ))
            _RECOVERED.inc()
        entries.sort(key=lambda e: (e.spooled_at, str(e.path)))
        self.refresh_gauges()
        return entries

    def note_retry(self) -> None:
        _RETRIES.inc()

    # --- state for healthz / metrics ---

    def _files(self) -> list[Path]:
        try:
            return sorted(self.directory.glob("*.json")) + sorted(
                self.directory.glob("*.json.parked"))
        except OSError:
            return []

    @property
    def depth(self) -> int:
        return len(self._files())

    def oldest_age_s(self) -> float | None:
        ages = []
        for path in self._files():
            try:
                ages.append(time.time() - path.stat().st_mtime)
            except OSError:
                continue
        return max(ages) if ages else None

    @property
    def saturated(self) -> bool:
        return self.max_entries > 0 and self.depth >= self.max_entries

    def refresh_gauges(self) -> None:
        files = self._files()
        _DEPTH.set(len(files))
        oldest = 0.0
        for path in files:
            try:
                oldest = max(oldest, time.time() - path.stat().st_mtime)
            except OSError:
                continue
        _OLDEST.set(round(oldest, 1))
