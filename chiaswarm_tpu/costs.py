"""Serving-path cost plane: per-pass FLOPs, achieved TFLOP/s, MFU.

ISSUE 17. The roofline contract (0.33 img/s/chip ~= 70% UNet MFU) rested
on an analytic FLOP denominator (models/flops.py) that surfaced only in
bench.py — the serving path billed tenants in chip-seconds with no idea
how many FLOPs it served or what MFU a pass achieved. This module is the
shared cost vocabulary for both:

- ``PEAK_TFLOPS`` / ``peak_tflops(device)``: the per-chip peak dense
  bf16 table, hoisted out of bench.py (which imports it back), with the
  same ``BENCH_PEAK_TFLOPS`` env override. A platform with no entry
  (CPU smoke, an unknown TPU generation) yields None — MFU then reports
  ``null`` while FLOPs are still counted, so the cost plane degrades to
  pure work accounting instead of lying.
- ``pass_cost`` / ``job_cost``: the ``pipeline_config.cost`` stamp the
  pipeline attaches to every envelope (solo, batched, sharded, chunked
  — all four run through the two stamping sites in
  pipelines/stable_diffusion.py). ``flops`` is the JOB's own integer
  FLOP count (so the hive ledger's per-tenant sums equal the sum of
  envelope stamps exactly); the pass-level figures (achieved TFLOP/s
  over the denoise span, MFU) are shared by every envelope of a
  coalesced pass, like ``embed_cache``.
- ``note_divergence``: the analytic-vs-XLA cross-check fed by the
  compiled-program ledger (programs.py) — every first call of a denoise
  program compares models/flops.py against XLA's own cost_analysis()
  and publishes the ratio, closing the "denominator is uncorroborated"
  gap without waiting for a TPU window.

Import-time jax-free (telemetry only): the hive-side tools and the
bench subprocess parser read these stamps without an accelerator
runtime.
"""

from __future__ import annotations

import os

from . import telemetry

# peak dense bf16 TFLOP/s per chip, by device kind prefix (the MFU
# denominator's denominator). Hoisted from bench.py; extend it when a
# new TPU generation lands — an unknown kind reports MFU null, never a
# made-up ratio.
PEAK_TFLOPS = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,
    "TPU v5p": 459.0,
    "TPU v5": 459.0,
    "TPU v6 lite": 918.0,
}

_PASS_FLOPS = telemetry.counter(
    "swarm_pass_flops_total",
    "Analytic UNet FLOPs served by completed denoise passes, per model "
    "(models/flops.py; the serving-path twin of the bench's MFU "
    "denominator)",
    ("model",),
)
_PASS_MFU = telemetry.gauge(
    "swarm_pass_mfu",
    "Model FLOPs utilisation of the most recent denoise pass, per model "
    "and mesh geometry (analytic UNet FLOPs over the denoise+decode "
    "span against the slice's aggregate peak; absent on platforms with "
    "no peak-TFLOPs entry)",
    ("model", "geometry"),
)
_DIVERGENCE = telemetry.gauge(
    "swarm_flops_divergence_ratio",
    "XLA cost_analysis FLOPs over the analytic models/flops.py count "
    "for the most recently compiled denoise program, per model (~1.0 = "
    "the MFU denominator is corroborated; XLA counts the whole program "
    "— scheduler + decode included — so a small overshoot is expected)",
    ("model",),
)


def peak_tflops(device) -> float | None:
    """Per-chip peak dense bf16 TFLOP/s for `device` (anything with a
    ``device_kind``), or None when the platform has no table entry.
    ``BENCH_PEAK_TFLOPS`` overrides — the knob the TPU bench windows
    already use to pin a denominator."""
    override = os.environ.get("BENCH_PEAK_TFLOPS")
    if override:
        return float(override)
    kind = getattr(device, "device_kind", "") or ""
    for prefix, tf in PEAK_TFLOPS.items():
        if kind.startswith(prefix):
            return tf
    return None


def pass_cost(*, model: str, pass_flops: float, denoise_s: float | None,
              chips: int, device=None, geometry: str = "replicated") -> dict:
    """One denoise pass's cost figures, counted into the pass metrics.
    Called once per PASS (a coalesced pass calls it once for the whole
    group); per-envelope stamps derive from it via ``job_cost``.

    ``denoise_s`` is the envelope's ``denoise_decode_s`` span; a span
    too short to measure (rounds to 0 on toy configs) reports achieved
    TFLOP/s and MFU as None rather than dividing by zero."""
    flops = int(round(max(float(pass_flops), 0.0)))
    chips = max(int(chips or 1), 1)
    peak = peak_tflops(device) if device is not None else None
    achieved = None
    if denoise_s and denoise_s > 0:
        achieved = flops / float(denoise_s) / 1e12
    mfu = None
    if achieved is not None and peak:
        mfu = round(achieved / (peak * chips), 4)
    if flops > 0:
        _PASS_FLOPS.inc(flops, model=model)
    if mfu is not None:
        _PASS_MFU.set(mfu, model=model, geometry=geometry)
    return {
        "pass_flops": flops,
        "denoise_s": denoise_s,
        "tflops_per_s": None if achieved is None else round(achieved, 4),
        "chips": chips,
        "peak_tflops_per_chip": peak,
        "mfu": mfu,
    }


def job_cost(pass_figures: dict, job_flops: float) -> dict:
    """The per-envelope ``pipeline_config.cost`` stamp: the job's OWN
    integer FLOPs first (what the tenant ledger sums — envelope sums and
    hive totals must agree exactly), then the shared pass figures."""
    return {"flops": int(round(max(float(job_flops), 0.0))), **pass_figures}


def note_divergence(model: str, analytic_flops: float,
                    xla_flops: float) -> float | None:
    """Publish the XLA/analytic FLOP ratio for one compiled program.
    Returns the ratio (None when either side is unusable — a missing
    cost model must read as "uncorroborated", not as divergence 0)."""
    try:
        analytic = float(analytic_flops)
        xla = float(xla_flops)
    except (TypeError, ValueError):
        return None
    if analytic <= 0 or xla <= 0:
        return None
    ratio = xla / analytic
    _DIVERGENCE.set(round(ratio, 4), model=model)
    return ratio
