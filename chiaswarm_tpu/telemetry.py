"""Process-wide telemetry: metrics registry, per-job trace spans, local HTTP.

The reference swarm emits nothing but a flat rotating log file
(swarm/log_setup.py); there is no way to see where a job's wall clock goes
or how well the batching layer packs rows. Diffusion-serving work
(SwiftDiffusion arXiv:2407.02031, SD-Acc arXiv:2507.01309) is driven by
exactly the per-stage latency breakdown this module provides. Design:

- a tiny, stdlib-only metrics registry (`Counter`, `Gauge`, `Histogram`
  with fixed buckets) rendering the Prometheus text exposition format —
  deliberately NOT a prometheus_client dependency: the worker image must
  not grow a runtime dep for what is ~200 lines of dict arithmetic;
- a `Span` / `trace_job` context-manager API that stamps per-stage wall
  time into BOTH the process-wide `swarm_job_stage_seconds{stage=...}`
  histogram and the per-job `timings` dict that rides the result envelope
  (`pipeline_config`), so the hive and the local scrape see the same
  numbers from the same measurement;
- an aiohttp app (`GET /metrics`, `GET /healthz`) the worker starts next
  to its jax.profiler server. `Settings.metrics_port` / the
  `CHIASWARM_METRICS_PORT` env knob picks the port; 0 disables the server
  (instrumentation itself is dict ops and stays on).

Everything is thread-safe: spans fire from slice executor threads while
the asyncio loop scrapes.
"""

from __future__ import annotations

import bisect
import contextvars
import threading
import time

# per-job stage timings land here; label value = stage name
STAGE_METRIC = "swarm_job_stage_seconds"
_STAGE_HELP = "Per-job wall-clock seconds by lifecycle stage"

# generic latency buckets: 5 ms poll hops up to 10-minute SDXL compiles
DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)

# the job id of the currently-executing job, for log correlation
# (log_setup.JsonFormatter reads it); set by trace_job / worker threads
current_job_id: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "chiaswarm_job_id", default=None
)


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label(v) -> str:
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace('"', '\\"')
    )


def _escape_help(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _sample_line(name: str, labelnames, labelvalues, value: float,
                 extra: tuple[str, str] | None = None) -> str:
    """One exposition line; labels render in DECLARED order (stable), with
    an optional trailing (name, value) pair — histograms put `le` last."""
    pairs = list(zip(labelnames, labelvalues))
    if extra is not None:
        pairs.append(extra)
    if pairs:
        lbl = ",".join(f'{n}="{_escape_label(v)}"' for n, v in pairs)
        return f"{name}{{{lbl}}} {_fmt_value(value)}"
    return f"{name} {_fmt_value(value)}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = "", labelnames=()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._values: dict[tuple, object] = {}

    def _key(self, labels: dict) -> tuple:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name} takes labels {self.labelnames}, got "
                f"{tuple(labels)}"
            )
        return tuple(str(labels[n]) for n in self.labelnames)

    def remove(self, **labels) -> None:
        """Drop one label combination's series. Bounded-cardinality
        surfaces (per-tenant usage gauges folded to top-K, per-worker
        outlier flags pruned with the directory) retire label values
        here instead of exposing stale series forever."""
        with self._lock:
            self._values.pop(self._key(labels), None)

    def samples(self) -> list[str]:
        raise NotImplementedError

    def render(self) -> str:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {_escape_help(self.help)}")
        lines.append(f"# TYPE {self.name} {self.kind}")
        lines.extend(self.samples())
        return "\n".join(lines)


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._values.get(self._key(labels), 0.0))

    def total(self) -> float:
        """Sum across every label combination (heartbeat snapshots)."""
        with self._lock:
            return float(sum(self._values.values()))

    def samples(self) -> list[str]:
        with self._lock:
            items = sorted(self._values.items())
        return [
            _sample_line(self.name, self.labelnames, key, v)
            for key, v in items
        ]


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._values.get(self._key(labels), 0.0))

    def samples(self) -> list[str]:
        with self._lock:
            items = sorted(self._values.items())
        return [
            _sample_line(self.name, self.labelnames, key, v)
            for key, v in items
        ]


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help: str = "", labelnames=(),
                 buckets=DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames)
        bounds = sorted(float(b) for b in buckets)
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets = tuple(bounds)

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        v = float(value)
        with self._lock:
            state = self._values.get(key)
            if state is None:
                # per-bound counts + overflow slot, running sum, count
                state = [[0] * (len(self.buckets) + 1), 0.0, 0]
                self._values[key] = state
            state[0][bisect.bisect_left(self.buckets, v)] += 1
            state[1] += v
            state[2] += 1

    def count(self, **labels) -> int:
        with self._lock:
            state = self._values.get(self._key(labels))
            return int(state[2]) if state else 0

    def sum(self, **labels) -> float:
        with self._lock:
            state = self._values.get(self._key(labels))
            return float(state[1]) if state else 0.0

    def label_values(self, labelname: str) -> list[str]:
        """Distinct observed values of one label (e.g. every stage seen)."""
        idx = self.labelnames.index(labelname)
        with self._lock:
            return sorted({key[idx] for key in self._values})

    def samples(self) -> list[str]:
        with self._lock:
            items = sorted(
                (key, [list(s[0]), s[1], s[2]])
                for key, s in self._values.items()
            )
        lines = []
        for key, (counts, total, n) in items:
            cumulative = 0
            for bound, c in zip(self.buckets, counts):
                cumulative += c
                lines.append(_sample_line(
                    f"{self.name}_bucket", self.labelnames, key, cumulative,
                    extra=("le", _fmt_value(bound)),
                ))
            lines.append(_sample_line(
                f"{self.name}_bucket", self.labelnames, key, n,
                extra=("le", "+Inf"),
            ))
            lines.append(_sample_line(
                f"{self.name}_sum", self.labelnames, key, total))
            lines.append(_sample_line(
                f"{self.name}_count", self.labelnames, key, n))
        return lines


class Registry:
    """Get-or-create metric container; one module-level instance serves the
    whole process (slice executor threads + asyncio loop)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def _get_or_create(self, cls, name, help, labelnames, **kw) -> _Metric:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls) or existing.labelnames != tuple(
                    labelnames
                ):
                    raise ValueError(
                        f"metric {name} already registered with a different "
                        "type or label set"
                    )
                return existing
            metric = cls(name, help, labelnames, **kw)
            self._metrics[name] = metric
            return metric

    def counter(self, name, help="", labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(),
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets)

    def get(self, name) -> _Metric | None:
        with self._lock:
            return self._metrics.get(name)

    def render(self) -> str:
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
        return "\n".join(m.render() for m in metrics) + "\n"


REGISTRY = Registry()


def counter(name, help="", labelnames=()) -> Counter:
    return REGISTRY.counter(name, help, labelnames)


def gauge(name, help="", labelnames=()) -> Gauge:
    return REGISTRY.gauge(name, help, labelnames)


def histogram(name, help="", labelnames=(), buckets=DEFAULT_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, help, labelnames, buckets=buckets)


# --- spans -----------------------------------------------------------------


def observe_stage(stage: str, seconds: float, registry: Registry | None = None
                  ) -> None:
    (registry or REGISTRY).histogram(
        STAGE_METRIC, _STAGE_HELP, ("stage",)
    ).observe(seconds, stage=stage)


class Span:
    """Times one stage of a job; on exit the elapsed wall clock lands in
    the stage histogram AND (when a timings dict is given) in
    `timings[key or f"{stage}_s"]` rounded the way the existing envelope
    timings are. Records on exception too — a failed denoise still spent
    the time."""

    def __init__(self, stage: str, timings: dict | None = None, *,
                 key: str | None = None, registry: Registry | None = None):
        self.stage = stage
        self.timings = timings
        self.key = key or f"{stage}_s"
        self.registry = registry
        self.elapsed: float | None = None

    def __enter__(self) -> "Span":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.elapsed = time.perf_counter() - self._t0
        observe_stage(self.stage, self.elapsed, self.registry)
        if self.timings is not None:
            self.timings[self.key] = round(self.elapsed, 3)


class JobTrace:
    """Per-job trace: a context manager that pins `current_job_id` for log
    correlation and hands out `stage()` spans all writing into one shared
    timings dict (the one that ends up in the job's pipeline_config)."""

    def __init__(self, job_id: str | None = None, timings: dict | None = None,
                 registry: Registry | None = None):
        self.job_id = job_id
        self.timings = timings if timings is not None else {}
        self.registry = registry
        self._token = None

    def __enter__(self) -> "JobTrace":
        if self.job_id is not None:
            self._token = current_job_id.set(str(self.job_id))
        return self

    def __exit__(self, *exc) -> None:
        if self._token is not None:
            current_job_id.reset(self._token)
            self._token = None

    def stage(self, stage: str, key: str | None = None) -> Span:
        return Span(stage, self.timings, key=key, registry=self.registry)

    def record(self, stage: str, seconds: float, key: str | None = None
               ) -> None:
        """A stage measured elsewhere (e.g. queue wait stamped by the
        scheduler) joins the same histogram + timings dict."""
        observe_stage(stage, seconds, self.registry)
        self.timings[key or f"{stage}_s"] = round(seconds, 3)


def trace_job(job_id: str | None = None, timings: dict | None = None,
              registry: Registry | None = None) -> JobTrace:
    return JobTrace(job_id, timings, registry)


# --- HTTP exposition -------------------------------------------------------


# profiler captures may not stack and a runaway duration would pin the
# trace machinery for the whole window — bound one capture hard
PROFILE_MAX_SECONDS = 120.0


def build_metrics_app(registry: Registry | None = None, health=None,
                      profile=None, token: str = "", programs=None,
                      memory=None):
    """aiohttp app with GET /metrics (Prometheus text) and GET /healthz
    (JSON from the caller's `health()` snapshot; a payload carrying
    `status` != "ok" answers 503 so probes can act on it). aiohttp is
    imported lazily — the registry itself must stay dependency-free.

    `profile` (optional) is an async callable `(seconds) -> dict` wired
    to POST /debug/profile?seconds=N — the worker passes its on-demand
    jax.profiler capture (writes a perfetto trace under
    $SDAAS_ROOT/profiles/). The callable raising PermissionError maps to
    403 (the Settings.profiler_capture gate), RuntimeError to 409 (a
    capture already running); no callable, no route. Unlike the
    read-only GETs, /debug/profile MUTATES (pins an executor thread,
    writes prompt-exposing traces to disk), so when `token` is set it
    requires the same bearer auth the hive APIs use — a worker whose
    metrics_host is widened off loopback must not expose an anonymous
    write endpoint (empty token = dev mode, matching the hive).

    `programs` / `memory` (optional, ISSUE 17) are sync callables
    returning JSON-ready dicts, wired to GET /debug/programs (the
    compiled-program ledger, programs.snapshot) and GET /debug/memory
    (the fleet byte census, memory_census.census). Read-only like
    /metrics; no callable, no route."""
    from aiohttp import web

    reg = registry or REGISTRY

    async def metrics(_request):
        return web.Response(
            text=reg.render(),
            headers={"Content-Type": "text/plain; version=0.0.4; charset=utf-8"},
        )

    async def healthz(_request):
        payload = {"status": "ok"}
        if health is not None:
            try:
                payload.update(health() or {})
            except Exception as e:  # a broken probe must still answer
                return web.json_response(
                    {"status": "error", "error": f"{type(e).__name__}: {e}"},
                    status=503,
                )
        status = 200 if payload.get("status") == "ok" else 503
        return web.json_response(payload, status=status)

    async def debug_profile(request):
        if token and request.headers.get(
                "Authorization", "") != f"Bearer {token}":
            return web.json_response(
                {"message": "unauthorized"}, status=401)
        try:
            seconds = float(request.query.get("seconds", "2"))
        except ValueError:
            return web.json_response(
                {"message": "seconds must be a number"}, status=400)
        if not 0 < seconds <= PROFILE_MAX_SECONDS:
            return web.json_response(
                {"message": f"seconds must be in (0, "
                            f"{PROFILE_MAX_SECONDS:g}]"}, status=400)
        try:
            detail = await profile(seconds)
        except PermissionError as e:
            return web.json_response({"message": str(e)}, status=403)
        except RuntimeError as e:
            return web.json_response({"message": str(e)}, status=409)
        except Exception as e:  # profiling must never kill the app
            return web.json_response(
                {"message": f"{type(e).__name__}: {e}"}, status=500)
        return web.json_response({"status": "ok", **(detail or {})})

    def debug_snapshot(provider):
        async def handler(_request):
            try:
                payload = provider() or {}
            except Exception as e:  # a broken ledger must not kill the app
                return web.json_response(
                    {"message": f"{type(e).__name__}: {e}"}, status=500)
            return web.json_response(payload)
        return handler

    app = web.Application()
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/healthz", healthz)
    if profile is not None:
        app.router.add_post("/debug/profile", debug_profile)
    if programs is not None:
        app.router.add_get("/debug/programs", debug_snapshot(programs))
    if memory is not None:
        app.router.add_get("/debug/memory", debug_snapshot(memory))
    return app


async def start_metrics_server(port: int, registry: Registry | None = None,
                               health=None, host: str = "127.0.0.1",
                               profile=None, token: str = "",
                               programs=None, memory=None):
    """Bind the telemetry app; returns the AppRunner (caller cleans up) or
    None when port is falsy (CHIASWARM_METRICS_PORT=0 opt-out)."""
    if not port:
        return None
    from aiohttp import web

    runner = web.AppRunner(
        build_metrics_app(registry, health, profile, token,
                          programs=programs, memory=memory))
    await runner.setup()
    await web.TCPSite(runner, host, int(port)).start()
    return runner
