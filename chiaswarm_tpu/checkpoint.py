"""Denoise checkpoint blobs: the wire format of a preempted pass.

A checkpoint-armed chunked denoise (ISSUE 18) ships its live state at
chunk boundaries — current latents, the scheduler-state leaves, and the
step index — so a redelivered job rehydrates at step K on another worker
instead of recomputing the whole pass. Everything else a resume needs
(conditioning, per-step RNG, guidance) recomputes deterministically from
the redelivered job arguments, so the blob stays tens-to-hundreds of KB.

The format is deliberately self-contained and numpy-version-stable:
an 8-byte magic, a little-endian u32 header length, a JSON header
describing every array (name, dtype, shape), then the arrays' raw bytes
concatenated in header order. ``np.savez`` is avoided on purpose — the
scheduler state may carry ``bfloat16`` leaves, which numpy only
round-trips via pickle; here the dtype travels by NAME and is resolved
through ml_dtypes when numpy alone cannot.

A ``program signature`` pins compatibility: a resume offer is honored
only when the redelivered job resolves to the same (model, bucket key,
dtype, geometry) the checkpoint was cut under — otherwise the latents
would be fed to a program with a different meaning of "step K" and the
pass silently diverges. Signature mismatch degrades to a full recompute,
never an error.
"""

from __future__ import annotations

import hashlib
import json
import struct

import numpy as np

MAGIC = b"CSWCKPT1"
FORMAT_VERSION = 1


def program_signature(model_name: str, key, dtype, geo=None) -> str:
    """Stable short id for the compiled-program family a checkpoint
    belongs to. Built from the same ingredients the pipeline's program
    bucket key uses, so two passes share a signature exactly when their
    chunk programs are interchangeable."""
    raw = repr((str(model_name), key, str(dtype), tuple(geo) if geo else None))
    return hashlib.sha256(raw.encode()).hexdigest()[:16]


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # bfloat16 & friends live in ml_dtypes (a jax dependency)
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def pack(step: int, latents, state_leaves, signature: str) -> bytes:
    """Serialize one checkpoint. `latents` and each entry of
    `state_leaves` must be array-likes (device arrays are gathered by
    np.asarray); order of the leaves is the pytree flatten order, which
    the resuming pipeline re-derives from a fresh prep pass."""
    arrays: list[tuple[str, np.ndarray]] = [("latents", np.asarray(latents))]
    for i, leaf in enumerate(state_leaves):
        arrays.append((f"leaf{i}", np.asarray(leaf)))
    header = {
        "v": FORMAT_VERSION,
        "step": int(step),
        "signature": str(signature),
        "leaves": len(state_leaves),
        "arrays": [
            {"name": name, "dtype": str(a.dtype), "shape": list(a.shape)}
            for name, a in arrays
        ],
    }
    head = json.dumps(header, separators=(",", ":")).encode()
    parts = [MAGIC, struct.pack("<I", len(head)), head]
    for _, a in arrays:
        parts.append(np.ascontiguousarray(a).tobytes())
    return b"".join(parts)


def unpack(blob: bytes) -> dict:
    """Parse a checkpoint blob back into host arrays. Raises ValueError
    on anything malformed — callers treat that as "no checkpoint" and
    run the full pass."""
    if len(blob) < len(MAGIC) + 4 or blob[: len(MAGIC)] != MAGIC:
        raise ValueError("not a checkpoint blob")
    head_len = struct.unpack_from("<I", blob, len(MAGIC))[0]
    start = len(MAGIC) + 4
    try:
        header = json.loads(blob[start:start + head_len].decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ValueError(f"corrupt checkpoint header: {e}") from e
    if header.get("v") != FORMAT_VERSION:
        raise ValueError(f"unsupported checkpoint version {header.get('v')}")
    offset = start + head_len
    out: dict[str, np.ndarray] = {}
    for spec in header.get("arrays", []):
        dtype = _np_dtype(str(spec["dtype"]))
        shape = tuple(int(d) for d in spec["shape"])
        count = int(np.prod(shape, dtype=np.int64)) if shape else 1
        nbytes = count * dtype.itemsize
        chunk = blob[offset:offset + nbytes]
        if len(chunk) != nbytes:
            raise ValueError("truncated checkpoint blob")
        out[str(spec["name"])] = np.frombuffer(
            chunk, dtype=dtype).reshape(shape).copy()
        offset += nbytes
    if "latents" not in out:
        raise ValueError("checkpoint blob has no latents")
    leaves = [out[f"leaf{i}"] for i in range(int(header.get("leaves", 0)))]
    return {
        "step": int(header.get("step", 0)),
        "signature": str(header.get("signature", "")),
        "latents": out["latents"],
        "state_leaves": leaves,
    }
