"""Weight-availability policy: production jobs fail loudly, tests stay hermetic.

Reference behavior: every callback calls `from_pretrained` against the HF
cache and crashes with a library error when the model was never downloaded
(swarm/diffusion/diffusion_func.py:103); operators prefetch via
`python -m swarm.initialize --download` (swarm/initialize.py:68-100).

Round-1 review (VERDICT weak #3) found our fallback silently served images
from deterministic *random* weights. Policy now:

- `test/*` and `*tiny*` model names: random init is the point (hermetic
  CPU tests, `test_tiny_model` jobs) — always allowed.
- anything else: missing weights raise `MissingWeightsError`, a ValueError
  subclass, so the worker marks the job envelope `fatal_error: true`
  (worker.py:178-180) and the hive does not resubmit.
- benchmarks / bring-up can opt in explicitly with `allow_random_init=True`
  (perf does not depend on weight values).
"""

from __future__ import annotations

from pathlib import Path


class MissingWeightsError(ValueError):
    """Model weights are not present on this worker (fatal job error)."""


def is_test_model(model_name: str) -> bool:
    name = model_name.lower()
    return name.startswith("test/") or "tiny" in name


def random_init_permitted(model_name: str, allow_random_init: bool) -> bool:
    return allow_random_init or is_test_model(model_name)


def require_weights_present(
    model_name: str,
    model_dir: Path | None,
    allow_random_init: bool,
    component: str = "model",
    hint: str | None = None,
) -> bool:
    """Gate a missing-weights fallback.

    Returns True when the caller may proceed with random init; raises
    MissingWeightsError when this is a production model whose weights are
    simply absent. `hint` overrides the default remediation text (families
    with no conversion path must not prescribe a dead-end `--download`).
    """
    if random_init_permitted(model_name, allow_random_init):
        return True
    where = f" (looked in {model_dir})" if model_dir is not None else ""
    if hint is None:
        hint = (
            "Prefetch them with `python -m chiaswarm_tpu.initialize "
            "--download` or place converted safetensors under the model root."
        )
    raise MissingWeightsError(
        f"{component} weights for '{model_name}' are not present on this "
        f"worker{where}. {hint}"
    )


def model_dir_for(model_name: str):
    """The downloaded checkpoint dir under the model root, or None — the
    one resolution every pipeline family shares."""
    from pathlib import Path

    from .settings import load_settings

    d = Path(load_settings().model_root_dir).expanduser() / model_name
    return d if d.is_dir() else None


# Families the worker can schedule but cannot serve with real weights yet
# (no conversion path). Single source of truth: `initialize --check` skips
# them and the worker's capability advertisement surfaces them so a
# capability-aware hive can stop sending jobs this worker can never run
# (VERDICT r03 weak #7).
# every family the registry serves now has a real-weight conversion path;
# the mechanism stays so a future family can gate honestly again
UNCONVERTED_FAMILY_KEYWORDS: tuple[str, ...] = ()


# the adapter AnimateDiff jobs get unless the job names one (reference
# tx2vid.py:26-36 hard-codes the same default). Lives here — not in
# pipelines/video.py — so the download CLI can read it without importing
# the jax model stack.
DEFAULT_MOTION_ADAPTER = "guoyww/animatediff-motion-adapter-v1-5-2"
