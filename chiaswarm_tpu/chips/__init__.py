from .device import ChipSet
from .allocator import SliceAllocator

__all__ = ["ChipSet", "SliceAllocator"]
