"""ChipSet: the TPU analog of the reference's one-CUDA-device abstraction.

Where reference swarm/gpu/device.py:6-53 wraps one `cuda:{i}` device with a
busy mutex and a per-job seeded torch.Generator, a ChipSet wraps a *set* of
TPU chips as a `jax.sharding.Mesh` (so one job can be batch-parallel across
its slice), seeds via `jax.random.key`, and reports chip/HBM capability for
work advertisement. The 8 GB VRAM floor (:8-11) has no TPU analog — HBM per
chip is fixed by the platform — so capability is advertised rather than gated.
"""

from __future__ import annotations

import logging
import random
import threading
import time

import jax
from jax.sharding import Mesh

from .. import faults, telemetry

logger = logging.getLogger(__name__)

# wall clock under a slice's busy lock, solo vs coalesced pass — with
# swarm_job_stage_seconds (compile/denoise split stamped by the pipeline)
# this separates "slice occupied" from "slice computing usefully"
_EXECUTE_SECONDS = telemetry.histogram(
    "swarm_slice_execute_seconds",
    "Wall-clock seconds one job (or coalesced pass) held a chip slice",
    ("kind",),
)

# the mesh view the slice's LAST pass ran under, one series per axis
# (ISSUE 12): data = coalescing rows / CFG pair, tensor = Megatron-style
# kernel sharding, seq = ring-attention blocks. A slice serving batch
# traffic sits at tensor=1; an interactive sharded pass flips tensor>1
# for its duration — the gauge is how an operator sees the class-aware
# geometry actually switching.
_SLICE_GEOMETRY = telemetry.gauge(
    "swarm_slice_geometry",
    "Mesh degree of the slice's most recent pass, per axis "
    "(data | tensor | seq)",
    ("slice", "axis"),
)

# Known HBM per chip (GiB) by device kind; fallback is queried or 16.
_HBM_GB = {
    "TPU v2": 8,
    "TPU v3": 16,
    "TPU v4": 32,
    "TPU v5 lite": 16,
    "TPU v5": 95,
    "TPU v5p": 95,
    "TPU v6 lite": 32,
    "cpu": 4,
}


def hbm_gb_of(device) -> int:
    kind = getattr(device, "device_kind", "cpu")
    for prefix, gb in _HBM_GB.items():
        if kind.startswith(prefix):
            return gb
    try:
        stats = device.memory_stats()
        return int(stats["bytes_limit"] / (1 << 30))
    except Exception:
        return 16


def hbm_census() -> list[dict]:
    """Per-local-device memory view for the fleet census (ISSUE 17,
    memory_census.py): whatever ``device.memory_stats()`` reports —
    TPU runtimes give bytes_in_use / bytes_limit / peak_bytes_in_use,
    the CPU backend an allocator subset or nothing — normalised to ints
    with the HBM table as the limit fallback, so /debug/memory always
    has a per-chip row even where the runtime is silent."""
    import jax

    out = []
    for device in jax.local_devices():
        stats = {}
        try:
            stats = device.memory_stats() or {}
        except Exception:
            stats = {}
        limit = stats.get("bytes_limit")
        if not isinstance(limit, int) or limit <= 0:
            limit = None
            kind = getattr(device, "device_kind", "cpu")
            for prefix, gb in _HBM_GB.items():
                if kind.startswith(prefix) and prefix != "cpu":
                    # the table is authoritative for known TPU kinds;
                    # a CPU "limit" would fake headroom where none is
                    # enforced
                    limit = gb << 30
                    break
        row = {
            "device": f"{device.platform}:{device.id}",
            "kind": getattr(device, "device_kind", device.platform),
            "bytes_in_use": stats.get("bytes_in_use")
            if isinstance(stats.get("bytes_in_use"), int) else None,
            "bytes_limit": limit,
            "peak_bytes_in_use": stats.get("peak_bytes_in_use")
            if isinstance(stats.get("peak_bytes_in_use"), int) else None,
        }
        out.append(row)
    return out


class ChipSet:
    """A fixed subset of local accelerator chips, meshed for one job at a time.

    The mesh is [data, tensor, seq] (scaling-book axis convention): pipelines
    shard the image batch (and CFG pair) over ``data``, Megatron-style
    attention/MLP kernels over ``tensor`` (parallel/tensor.py partition
    rules), and ring-attention sequence blocks over ``seq``. Degrees default
    to 1, so a plain ChipSet behaves exactly like the round-1 data-only mesh.
    """

    def __init__(self, devices: list, slice_id: int = 0, tensor: int = 1,
                 seq: int = 1):
        if not devices:
            raise ValueError("ChipSet requires at least one device")
        if tensor < 1 or seq < 1:
            raise ValueError(f"parallel degrees must be >= 1, got {tensor=} {seq=}")
        if len(devices) % (tensor * seq) != 0:
            raise ValueError(
                f"tensor*seq={tensor * seq} does not divide "
                f"slice size {len(devices)}"
            )
        self.devices = list(devices)
        self.slice_id = slice_id
        self.tensor = tensor
        self.seq = seq
        self._mutex = threading.Lock()
        # geometry of the most recent pass (healthz / swarm_top column);
        # starts at the construction-time default
        self.last_geometry: tuple[int, int, int] = (
            len(devices) // (tensor * seq), tensor, seq)

    # --- identity / capability (reference swarm/gpu/device.py:17-27) ---

    @property
    def platform(self) -> str:
        return self.devices[0].platform

    @property
    def busy(self) -> bool:
        """A job currently holds this slice (healthz per-slice state)."""
        return self._mutex.locked()

    def identifier(self) -> str:
        ids = ",".join(str(d.id) for d in self.devices)
        return f"{self.platform}:{ids}"

    def name(self) -> str:
        return getattr(self.devices[0], "device_kind", self.platform)

    def descriptor(self) -> str:
        return f"{self.identifier()}:{self.name()}"

    def chip_count(self) -> int:
        return len(self.devices)

    def hbm_bytes(self) -> int:
        return sum(hbm_gb_of(d) for d in self.devices) << 30

    def memory(self) -> int:
        # legacy `memory` capability key (reference swarm/hive.py:19)
        return self.hbm_bytes()

    def capabilities(self) -> dict:
        return {
            # legacy keys a reference hive understands
            "memory": self.memory(),
            "gpu": self.name(),
            # TPU-native keys
            "chips": self.chip_count(),
            "hbm_gb": self.hbm_bytes() >> 30,
            "topology": f"{self.platform}x{self.chip_count()}",
        }

    def resident_models(self) -> list[str]:
        """Models whose residency entry (allocator residency map, fed by
        registry load + pipeline compile events) points at this slice —
        the warm state the dispatch board routes same-model groups to."""
        from .allocator import models_resident_on

        return models_resident_on(self.slice_id)

    def smoke_probe(self) -> bool:
        """Quarantine-recovery probe (worker watchdog): one tiny matmul on
        every chip of the slice, synchronously. True = the slice computes
        and may return to the allocator; False (busy, or any device error)
        = it stays quarantined."""
        if not self._mutex.acquire(blocking=False):
            return False
        try:
            import jax.numpy as jnp

            for d in self.devices:
                x = jax.device_put(jnp.eye(8, dtype=jnp.float32), d)
                jnp.matmul(x, x).block_until_ready()
            return True
        except Exception:
            logger.exception("smoke probe failed on %s", self.identifier())
            return False
        finally:
            self._mutex.release()

    # --- geometry (ISSUE 12: one slice, two views) ---

    @property
    def shard_capable(self) -> bool:
        """Whether this slice can run one job as a sharded program at
        all: more than one chip to spread attention heads / sequence
        blocks over. The worker ANDs this with Settings.shard_interactive
        before advertising `shard_capable` on /work polls."""
        return len(self.devices) > 1

    def resolve_geometry(self, tensor: int | None = None,
                         seq: int | None = None) -> tuple[int, int] | None:
        """Validate a requested (tensor, seq) view over THIS slice's
        chips; None when it cannot mesh (doesn't divide the chip count).
        tensor=0/None means "auto": the largest power-of-two degree that
        leaves a data axis of at least the CFG pair (2), so a batch-1
        interactive job still shards its uncond/cond rows over `data`
        while attention heads spread over `tensor`."""
        n = len(self.devices)
        seq = int(seq or 1)
        if seq < 1 or n % seq:
            return None
        if tensor:
            tensor = int(tensor)
            if tensor < 1 or n % (tensor * seq):
                return None
            return tensor, seq
        # auto: chips / (2 * seq), floored to a power of two >= 1
        room = n // (2 * seq)
        tensor = 1
        while tensor * 2 <= room and n % (tensor * 2 * seq) == 0:
            tensor *= 2
        return tensor, seq

    def note_geometry(self, data: int, tensor: int, seq: int) -> None:
        """Record the mesh view a pass is running under (called by the
        pipeline at dispatch): feeds the swarm_slice_geometry gauge and
        the healthz/swarm_top geometry column."""
        self.last_geometry = (int(data), int(tensor), int(seq))
        label = str(self.slice_id)
        _SLICE_GEOMETRY.set(data, slice=label, axis="data")
        _SLICE_GEOMETRY.set(tensor, slice=label, axis="tensor")
        _SLICE_GEOMETRY.set(seq, slice=label, axis="seq")

    def geometry_str(self) -> str:
        d, t, s = self.last_geometry
        return f"data{d}·tensor{t}·seq{s}"

    # --- execution ---

    def mesh(self, tensor: int | None = None, seq: int | None = None) -> Mesh:
        """The slice's device mesh — by default the construction-time
        [data, tensor, seq] view; pass `tensor`/`seq` to carve the SAME
        chips into a different geometry (the elastic view ISSUE 12 adds:
        a sharded interactive pass and a data-parallel coalesced pass
        run over identical hardware)."""
        from ..parallel.mesh import make_mesh

        return make_mesh(
            self.devices,
            tensor=self.tensor if tensor is None else tensor,
            seq=self.seq if seq is None else seq,
        )

    def __call__(self, func, **kwargs):
        """Run one job on this slice under the busy lock.

        Mirrors reference swarm/gpu/device.py:29-50: pops model_name, draws a
        seed when the job didn't pin one, injects the RNG, and stamps the
        seed into the returned pipeline_config. Here the RNG is a counter-
        based `jax.random.key` (deterministic across chip counts) and the
        callback also receives this ChipSet for mesh placement.
        """
        if not self._mutex.acquire(blocking=False):
            logger.error("ChipSet %s is busy but got invoked.", self.identifier())
            raise Exception("busy")
        try:
            # fault-injection point: a hung compile/denoise holds the busy
            # lock exactly like the real failure would (faults.py)
            faults.hang("hang_denoise")
            model_name = kwargs.pop("model_name")
            seed = kwargs.pop("seed", None)
            if seed is None:
                seed = random.getrandbits(63)

            kwargs["rng"] = jax.random.key(seed)
            kwargs["chipset"] = self

            started = time.perf_counter()
            artifacts, pipeline_config = func(self.identifier(), model_name, **kwargs)
            elapsed = time.perf_counter() - started
            _EXECUTE_SECONDS.observe(elapsed, kind="solo")
            pipeline_config["seed"] = seed
            # per-job timing breadcrumb (reference has none; SURVEY §5 asks for it)
            pipeline_config.setdefault("timings", {})["job_s"] = round(
                elapsed, 3
            )
            return artifacts, pipeline_config
        finally:
            self._mutex.release()

    def run_batched(self, func, requests: list[dict]):
        """Run a coalesced group of jobs on this slice under the busy lock.

        The batch analog of __call__: draws (or honors) a seed PER JOB,
        injects each job's own counter-based RNG plus this ChipSet, and
        stamps each returned pipeline_config with its job's seed — so a
        coalesced job's images depend only on its own seed, never on its
        batchmates (the batched path's noise stream is its own, distinct
        from the single-job path's draws for the same seed).

        `func(identifier, requests)` must return one (artifacts,
        pipeline_config) pair per request, in order.
        """
        if not self._mutex.acquire(blocking=False):
            logger.error("ChipSet %s is busy but got invoked.", self.identifier())
            raise Exception("busy")
        try:
            # fault-injection points: hang (watchdog path) and a coalesced
            # OOM raised before any request kwarg is mutated, so the
            # worker's per-job fallback reruns the group unchanged
            faults.hang("hang_denoise")
            faults.fire("oom_batched", exc=RuntimeError(
                "RESOURCE_EXHAUSTED: injected OOM (fault oom_batched)"))
            seeds = []
            for kw in requests:
                seed = kw.pop("seed", None)
                if seed is None:
                    seed = random.getrandbits(63)
                seeds.append(seed)
                kw["rng"] = jax.random.key(seed)
                kw["chipset"] = self

            started = time.perf_counter()
            results = func(self.identifier(), requests)
            if len(results) != len(requests):
                raise RuntimeError(
                    f"batched callback returned {len(results)} envelopes "
                    f"for {len(requests)} jobs"
                )
            _EXECUTE_SECONDS.observe(
                time.perf_counter() - started, kind="batched")
            elapsed = round(time.perf_counter() - started, 3)
            for (artifacts, pipeline_config), seed in zip(results, seeds):
                pipeline_config["seed"] = seed
                timings = pipeline_config.setdefault("timings", {})
                # the pass was shared: job_s is the group's wall clock
                timings["job_s"] = elapsed
            return results
        finally:
            self._mutex.release()
