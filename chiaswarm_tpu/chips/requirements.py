"""Per-model capacity requirements: the TPU-native replacement for the
reference's memory-pressure knobs.

Reference behavior replaced: swarm/diffusion/diffusion_func.py:134-146
(VAE slicing/tiling, attention slicing, model/sequential CPU offload) —
CUDA-side degradation hacks that trade 2-10x latency for VRAM. On TPU the
policy is explicit capacity accounting instead (SURVEY §2.6 row
'memory-pressure fallbacks'):

- every model family carries a parameter-footprint estimate and a
  per-image activation estimate;
- a job that cannot fit at the requested batch is capped to the batch
  that fits (recorded in pipeline_config, never silent);
- a model whose parameters alone exceed the slice's HBM is a fatal job
  error naming the chip count it needs — the operator scales the slice
  (tensor parallelism) instead of thrashing host offload.

Numbers are engineering estimates in bf16 serving dtype, anchored on
measured fits (SDXL batch 4 @ 1024^2 runs on one 16 GB v5e chip with
~2 GB/image of transient headroom — bench_r02).
"""

from __future__ import annotations

from ..models.configs import model_family

# static parameter + resident-state footprint, GiB (bf16, incl. text/vae)
FAMILY_PARAMS_GB: dict[str, float] = {
    "sd15": 1.8,
    "sd21": 2.1,
    "sdxl": 8.0,
    "sdxl_refiner": 7.2,
    # measured from the real flux-dev geometry via eval_shape in
    # tests/test_flux_tp.py (12B MMDiT + 4.7B T5-XXL, bf16)
    "flux": 31.4,
    "kandinsky": 6.0,  # prior + decoder + CLIP-bigG text tower
    "kandinsky3": 16.0,  # 3B UNet + FLAN-T5-XXL encoder
    "cascade": 11.0,  # stage C 3.6B + stage B 1.5B + text tower
    "deepfloyd_if": 18.0,  # IF-I XL + T5-XXL encoder
}

# transient activations per image in the fused denoise+decode program,
# GiB at a 1024^2 canvas; scales with canvas area
FAMILY_ACT_GB_PER_IMAGE: dict[str, float] = {
    "sd15": 1.0,
    "sd21": 1.1,
    "sdxl": 2.0,
    "sdxl_refiner": 1.8,
    "flux": 2.5,
    "kandinsky": 1.2,
    "kandinsky3": 2.2,
    "cascade": 1.5,
    "deepfloyd_if": 1.5,
}

# native serving canvas per family (everything else serves 1024)
_FAMILY_CANVAS: dict[str, int] = {
    "sd15": 512,
    "sd21": 768,
    "kandinsky": 512,  # K2.x decoder default (pipelines/kandinsky.py)
}

_DEFAULT_PARAMS_GB = 2.0
_DEFAULT_ACT_GB = 1.0


def _family_key(model_name: str) -> str:
    """Capacity bucket — model_family()'s catch-all is 'sd15', so the
    non-SD families that every capacity table keys on resolve by name
    FIRST (a Kandinsky charged as a 1.8 GB SD model would defeat the
    gate)."""
    name = model_name.lower()
    if "flux" in name:
        return "flux"
    if "kandinsky-3" in name or "kandinsky3" in name:
        return "kandinsky3"
    if "kandinsky" in name:
        return "kandinsky"
    if "cascade" in name:
        return "cascade"
    if name.startswith("deepfloyd/"):
        return "deepfloyd_if"
    return model_family(model_name)


def _area_scale(height: int, width: int | None = None) -> float:
    width = height if width is None else width
    return max((height * width) / (1024.0 * 1024.0), 0.05)


def required_hbm_gb(model_name: str, batch: int, size: int,
                    width: int | None = None) -> float:
    """Estimated HBM for `batch` images at size x (width or size)."""
    fam = _family_key(model_name)
    params = FAMILY_PARAMS_GB.get(fam, _DEFAULT_PARAMS_GB)
    act = FAMILY_ACT_GB_PER_IMAGE.get(fam, _DEFAULT_ACT_GB)
    return params + batch * act * _area_scale(size, width)


def default_canvas(model_name: str) -> int:
    """The family's native serving canvas (the gate's estimate when a job
    names no dims — it must match what the pipeline will actually serve,
    in both directions: 1024 for a 512-native family over-caps batches,
    512 for a 1024-native family admits OOMs)."""
    return _FAMILY_CANVAS.get(_family_key(model_name), 1024)


def min_chips(model_name: str, hbm_gb_per_chip: float, size: int = 1024,
              width: int | None = None) -> int:
    """TP shards needed so the per-chip parameter cut + one image at this
    canvas fits."""
    fam = _family_key(model_name)
    params = FAMILY_PARAMS_GB.get(fam, _DEFAULT_PARAMS_GB)
    act = FAMILY_ACT_GB_PER_IMAGE.get(fam, _DEFAULT_ACT_GB)
    one_image = act * _area_scale(size, width)
    n = 1
    while params / n + one_image > hbm_gb_per_chip and n < 64:
        n *= 2
    return n


# Flux weight streaming (the TPU analog of the reference's sequential CPU
# offload, swarm/job_arguments.py:209-218): the 12B MMDiT pages through the
# chip block-by-block from host RAM, so only the resident tail (T5-XXL
# 9.4 GB + CLIP/VAE/head/final ~0.8) plus two ~0.8 GB double-buffered
# block transfers must fit alongside activations.
FLUX_STREAM_RESIDENT_GB = 12.0


def flux_stream_fit(chipset, batch: int, size: int,
                    width: int | None = None) -> int:
    """Largest batch a single-chip slice serves with flux weight
    streaming; 0 when even the resident tail + one image doesn't fit.
    Streaming v1 targets exactly the small-worker gap: one-chip slices,
    tensor=1 (multi-chip slices shard the resident model instead)."""
    if chipset is None or chipset.platform != "tpu":
        return batch
    if chipset.chip_count() != 1 or max(getattr(chipset, "tensor", 1), 1) > 1:
        return 0
    per_chip_hbm = chipset.hbm_bytes() / (1 << 30)
    act = FAMILY_ACT_GB_PER_IMAGE["flux"]
    free = per_chip_hbm - FLUX_STREAM_RESIDENT_GB
    per_image = act * _area_scale(size, width)
    if free < per_image:
        return 0
    return min(batch, int(free / per_image))


def streaming_enabled() -> bool:
    # load_settings already degrades to defaults on a missing/corrupt
    # file; anything it does raise (e.g. a malformed env override) must
    # propagate — silently forcing streaming ON would override an
    # operator's explicit flux_streaming: false
    from ..settings import load_settings

    return bool(load_settings().flux_streaming)


def flux_admissible(chipset, batch: int, size: int,
                    width: int | None = None,
                    model_name: str = "black-forest-labs/FLUX.1-dev",
                    ) -> tuple[int, str]:
    """The ONE flux admission rule (resident fit, else streaming fit) —
    shared by check_capacity, the worker's flux_runnable advertisement,
    and FluxPipeline's auto-streaming detection, so the hive's placement
    decision, the job gate, and the pipeline's actual mode cannot drift.

    Returns (admissible batch, mode) where mode is "resident",
    "streaming", or "refuse" (batch 0)."""
    resident = fit_batch(chipset, model_name, batch, size, width)
    if resident:
        return resident, "resident"
    if streaming_enabled():
        streamed = flux_stream_fit(chipset, batch, size, width)
        if streamed:
            return streamed, "streaming"
    return 0, "refuse"


def fit_batch(chipset, model_name: str, batch: int, size: int,
              width: int | None = None) -> int:
    """Largest batch (<= requested) this slice fits; 0 = model doesn't fit.

    Accounting is PER CHIP: with tensor=1 the parameter tree replicates
    onto every chip, so a model bigger than one chip's HBM fails no matter
    how many data-parallel chips the slice has. Non-accelerator slices
    (CPU tests) always fit — the host heap is not HBM.
    """
    from ..weights import is_test_model

    if chipset is None or chipset.platform != "tpu":
        return batch
    if is_test_model(model_name):
        # tiny stand-ins are a few MB regardless of the family whose
        # architecture they mimic — the family footprint table is wrong
        # for them by three orders of magnitude
        return batch
    per_chip_hbm = chipset.hbm_bytes() / (1 << 30) / max(chipset.chip_count(), 1)
    # Closed form (the batch arrives unvalidated from the wire — a loop
    # decrementing from 1e9 would stall the worker): the busiest data
    # shard holds ceil(batch/data) images, so the largest admissible
    # batch is floor(free / per_image) * data.
    fam = _family_key(model_name)
    params = FAMILY_PARAMS_GB.get(fam, _DEFAULT_PARAMS_GB)
    act = FAMILY_ACT_GB_PER_IMAGE.get(fam, _DEFAULT_ACT_GB)
    tensor = max(getattr(chipset, "tensor", 1), 1)
    seq = max(getattr(chipset, "seq", 1), 1)
    data = max(chipset.chip_count() // (tensor * seq), 1)
    free = per_chip_hbm - params / tensor
    per_image = act * _area_scale(size, width)
    if free < per_image:
        return 0
    return min(batch, int(free / per_image) * data)


def _pow2_floor(n: int) -> int:
    """Largest power of two <= n (n >= 1)."""
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


def coalesce_rows_limit(chipset, model_name: str, size: int,
                        width: int | None = None,
                        ceiling: int = 256) -> int:
    """Most images one coalesced cross-job batch may hold on this slice.

    The batching scheduler (batching.py) sizes its groups with this BEFORE
    dispatch so a coalesced batch arrives already admissible — the batched
    path caps groups, it never rejects one (each member job passed the
    single-job gate on its own). Non-accelerator slices return the
    ceiling: the host heap is not HBM.

    The budget is a power-of-two BUCKET boundary, not the raw fit:
    run_batched pads the admitted row count up to pad_bucket(rows) AFTER
    admission, so a raw budget of (say) 5 would admit a 5-row group that
    executes an 8-row padded pass and OOMs before the per-job fallback
    (the ROADMAP pad-vs-admission item). Capping at pow2_floor(fit) makes
    every admissible group's PADDED pass fit too.
    """
    allowed = fit_batch(chipset, model_name, ceiling, size, width)
    # a 0 here means the MODEL doesn't fit — that's the single-job gate's
    # fatal error to raise with its remediation text, not a grouping
    # concern; never let the probe block grouping below one job
    return _pow2_floor(allowed) if allowed >= 1 else 1


def coalesced_fit(chipset, model_name: str, total_rows: int, size: int,
                  width: int | None = None) -> int:
    """Admit a coalesced batch of total_rows images: returns the capped
    row budget for ONE denoise pass (the executor splits the request list
    into passes of at most this many rows). Raises only when even one
    image cannot fit — the same fatal contract as check_capacity, which
    each member job already cleared individually.

    Like coalesce_rows_limit, the budget accounts for padding: a pass of
    r rows executes as pad_bucket(r) rows, so the per-pass budget is the
    largest power of two within the raw fit — any chunk at or under it
    pads to at most the budget itself."""
    total_rows = max(int(total_rows), 1)
    # probe the slice's RAW capacity (independent of the request size so
    # the pow2 budget is a property of the slice, not of this group)
    fit = check_capacity(
        chipset, model_name, max(total_rows, 256), size, width)
    return min(total_rows, _pow2_floor(fit))


def check_capacity(chipset, model_name: str, batch: int, size: int,
                   width: int | None = None) -> int:
    """-> allowed batch, or raise a fatal job error naming the fix."""
    if _family_key(model_name) == "flux":
        allowed, _ = flux_admissible(chipset, batch, size, width, model_name)
    else:
        allowed = fit_batch(chipset, model_name, batch, size, width)
    if allowed == 0:
        hbm_gb = chipset.hbm_bytes() / (1 << 30)
        per_chip = hbm_gb / max(chipset.chip_count(), 1)
        fam = _family_key(model_name)
        act = FAMILY_ACT_GB_PER_IMAGE.get(fam, _DEFAULT_ACT_GB)
        one_image = act * _area_scale(size, width)
        base = (
            f"{model_name} does not fit on this {chipset.chip_count()}-chip "
            f"slice ({hbm_gb:.0f} GB HBM, tensor="
            f"{max(getattr(chipset, 'tensor', 1), 1)}): it needs about "
            f"{required_hbm_gb(model_name, 1, size, width):.0f} GB at this "
            f"canvas. "
        )
        if one_image >= per_chip:
            # activations don't shard over tensor: no degree can save this
            raise ValueError(
                base + "One image's activations alone exceed a chip's HBM "
                "at this canvas — reduce the canvas or serve from "
                "higher-HBM chips."
            )
        need = min_chips(model_name, per_chip, size, width)
        raise ValueError(
            base + f"Serve it from a slice with tensor parallelism >= "
            f"{need} (chips shard the parameters; data-parallel chips "
            f"each hold a full copy)."
        )
    return allowed
