"""Chip-slice allocator: jobs sized in chips, placed on free sub-meshes.

The reference shipped a dead `swarm/gpu/device_pool.py` (never imported) and
used a bare semaphore sized to the GPU count instead (swarm/worker.py:195-196)
— with the bug that work advertisement always used the *last* device's
capabilities (swarm/worker.py:45-62). This allocator is that idea done right:

- local chips are partitioned into fixed disjoint slices of `chips_per_job`
  (0 = one slice spanning every chip);
- `acquire()` waits for any free slice; `release()` returns it;
- `capabilities()` aggregates over the whole pool so advertisement reflects
  what the worker can actually take, not one arbitrary device.

Slices are disjoint device subsets so concurrent jobs never contend for a
chip; each slice compiles its own programs (XLA caches are per-process, so
same-shaped jobs on different slices share the compiled executable).
"""

from __future__ import annotations

import asyncio

import jax

from .device import ChipSet


class SliceAllocator:
    def __init__(self, devices: list | None = None, chips_per_job: int = 0,
                 tensor_parallelism: int = 1, sequence_parallelism: int = 1):
        if devices is None:
            devices = jax.devices()
        if not devices:
            raise Exception("No accelerator devices present. Quitting.")

        n = chips_per_job if chips_per_job > 0 else len(devices)
        if len(devices) % n != 0:
            raise ValueError(
                f"chips_per_job={n} does not divide device count {len(devices)}"
            )

        self.slices = [
            ChipSet(devices[i : i + n], slice_id=i // n,
                    tensor=tensor_parallelism, seq=sequence_parallelism)
            for i in range(0, len(devices), n)
        ]
        self._free: asyncio.Queue[ChipSet] = asyncio.Queue()
        # membership mirrors of the free queue and of handed-out slices:
        # every path that could re-enqueue a slice (release after a job,
        # reinstate after a quarantine probe) funnels through _put_free,
        # so no interleaving of watchdog and worker can double-free one
        self._free_ids: set[int] = set()
        self._leased: set[int] = set()
        self._quarantined: set[int] = set()
        for s in self.slices:
            self._put_free(s)

    def __len__(self) -> int:
        return len(self.slices)

    def _put_free(self, chipset: ChipSet) -> None:
        if chipset.slice_id in self._free_ids:
            return
        self._free_ids.add(chipset.slice_id)
        self._free.put_nowait(chipset)

    @property
    def free_count(self) -> int:
        return self._free.qsize()

    def has_free_slice(self) -> bool:
        return not self._free.empty()

    async def acquire(self) -> ChipSet:
        chipset = await self._free.get()
        self._free_ids.discard(chipset.slice_id)
        self._leased.add(chipset.slice_id)
        return chipset

    def release(self, chipset: ChipSet) -> None:
        self._leased.discard(chipset.slice_id)
        if chipset.slice_id in self._quarantined:
            # the watchdog took this slice out of service mid-job; only a
            # passed smoke probe (reinstate) returns it to the free queue
            return
        self._put_free(chipset)

    # --- quarantine (worker watchdog) ---

    def quarantine(self, chipset: ChipSet) -> None:
        """Take a slice out of service: it will not be handed to jobs and
        release() becomes a no-op for it. Idempotent."""
        self._quarantined.add(chipset.slice_id)

    def reinstate(self, chipset: ChipSet) -> None:
        """Clear a slice's quarantine (smoke probe passed). If a worker
        still holds the slice for the rest of its batch, only the flag
        clears — that worker's release() re-enqueues it; otherwise it goes
        back to the free queue here. No-op when never quarantined."""
        if chipset.slice_id not in self._quarantined:
            return
        self._quarantined.discard(chipset.slice_id)
        if chipset.slice_id in self._leased:
            return
        self._put_free(chipset)

    def is_quarantined(self, chipset: ChipSet) -> bool:
        return chipset.slice_id in self._quarantined

    @property
    def quarantined_count(self) -> int:
        return len(self._quarantined)

    def capabilities(self) -> dict:
        """Pool-wide capability advertisement for /work polling.

        Quarantined slices are excluded — advertised capacity shrinks
        while a slice is out of service, so a capability-aware hive stops
        placing work this worker cannot take."""
        per_slice = self.slices[0].capabilities()
        active = [s for s in self.slices
                  if s.slice_id not in self._quarantined]
        total_chips = sum(s.chip_count() for s in active)
        return {
            "memory": per_slice["memory"],
            "gpu": per_slice["gpu"],
            "chips": total_chips,
            "hbm_gb": sum(s.hbm_bytes() for s in active) >> 30,
            "topology": f"{self.slices[0].platform}x{total_chips}"
            + (f"({len(active)}x{per_slice['chips']})" if len(active) > 1 else ""),
            "slices": len(active),
        }
