"""Chip-slice allocator: jobs sized in chips, placed on free sub-meshes.

The reference shipped a dead `swarm/gpu/device_pool.py` (never imported) and
used a bare semaphore sized to the GPU count instead (swarm/worker.py:195-196)
— with the bug that work advertisement always used the *last* device's
capabilities (swarm/worker.py:45-62). This allocator is that idea done right:

- local chips are partitioned into fixed disjoint slices of `chips_per_job`
  (0 = one slice spanning every chip);
- `acquire()` waits for any free slice; `release()` returns it;
- `capabilities()` aggregates over the whole pool so advertisement reflects
  what the worker can actually take, not one arbitrary device.

Slices are disjoint device subsets so concurrent jobs never contend for a
chip; each slice compiles its own programs (XLA caches are per-process, so
same-shaped jobs on different slices share the compiled executable).
"""

from __future__ import annotations

import asyncio

import jax

from .device import ChipSet


class SliceAllocator:
    def __init__(self, devices: list | None = None, chips_per_job: int = 0,
                 tensor_parallelism: int = 1, sequence_parallelism: int = 1):
        if devices is None:
            devices = jax.devices()
        if not devices:
            raise Exception("No accelerator devices present. Quitting.")

        n = chips_per_job if chips_per_job > 0 else len(devices)
        if len(devices) % n != 0:
            raise ValueError(
                f"chips_per_job={n} does not divide device count {len(devices)}"
            )

        self.slices = [
            ChipSet(devices[i : i + n], slice_id=i // n,
                    tensor=tensor_parallelism, seq=sequence_parallelism)
            for i in range(0, len(devices), n)
        ]
        self._free: asyncio.Queue[ChipSet] = asyncio.Queue()
        for s in self.slices:
            self._free.put_nowait(s)

    def __len__(self) -> int:
        return len(self.slices)

    @property
    def free_count(self) -> int:
        return self._free.qsize()

    def has_free_slice(self) -> bool:
        return not self._free.empty()

    async def acquire(self) -> ChipSet:
        return await self._free.get()

    def release(self, chipset: ChipSet) -> None:
        self._free.put_nowait(chipset)

    def capabilities(self) -> dict:
        """Pool-wide capability advertisement for /work polling."""
        per_slice = self.slices[0].capabilities()
        total_chips = sum(s.chip_count() for s in self.slices)
        return {
            "memory": per_slice["memory"],
            "gpu": per_slice["gpu"],
            "chips": total_chips,
            "hbm_gb": sum(s.hbm_bytes() for s in self.slices) >> 30,
            "topology": f"{self.slices[0].platform}x{total_chips}"
            + (f"({len(self.slices)}x{per_slice['chips']})" if len(self.slices) > 1 else ""),
            "slices": len(self.slices),
        }
