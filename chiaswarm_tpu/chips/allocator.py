"""Chip-slice allocator: jobs sized in chips, placed on free sub-meshes.

The reference shipped a dead `swarm/gpu/device_pool.py` (never imported) and
used a bare semaphore sized to the GPU count instead (swarm/worker.py:195-196)
— with the bug that work advertisement always used the *last* device's
capabilities (swarm/worker.py:45-62). This allocator is that idea done right:

- local chips are partitioned into fixed disjoint slices of `chips_per_job`
  (0 = one slice spanning every chip);
- `acquire()` waits for any free slice; `release()` returns it;
- `capabilities()` aggregates over the whole pool so advertisement reflects
  what the worker can actually take, not one arbitrary device.

Slices are disjoint device subsets so concurrent jobs never contend for a
chip; each slice compiles its own programs (XLA caches are per-process, so
same-shaped jobs on different slices share the compiled executable).

Placement (round 8): the allocator also carries the MODEL RESIDENCY map —
which slice last loaded/compiled each model (fed by registry builds and
SDPipeline compile events). `acquire_for(model)` is the placement-aware
acquire: it hands out the slice where the model is already warm when that
slice is free ("affinity"), prefers a residency-unclaimed slice for a model
with no home ("cold"), and otherwise takes any free slice rather than
idling ("steal" — the model's home is busy, so recompiling elsewhere beats
waiting; the ROADMAP cross-slice-stealing item). Residency is process-global
(models are resident per process+slice, and pipelines don't hold an
allocator reference), guarded by a lock because pipeline builds run on
executor threads.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Callable

import jax

from .device import ChipSet

# model name -> slice_id of the slice where it was last loaded/compiled.
# A slice can be home to many models (registry keeps an LRU of resident
# pipelines per slice); a model has ONE home — the most recent load wins,
# which is exactly the copy worth routing to.
_RESIDENCY: dict[str, int] = {}
_RESIDENCY_LOCK = threading.Lock()


def note_resident(model_name: str, slice_id: int) -> None:
    """Record a load/compile event: `model_name` is now warm on slice
    `slice_id`. Called by registry.get_pipeline after a build and by
    SDPipeline on denoise-program compiles (recency refresh)."""
    if not model_name:
        return
    with _RESIDENCY_LOCK:
        _RESIDENCY[str(model_name)] = int(slice_id)


def clear_resident(model_name: str, slice_id: int | None = None) -> None:
    """Drop a residency entry (registry eviction). With `slice_id`, only
    clears when the entry still points at that slice — a fresher load on
    another slice must not be erased by a stale eviction."""
    with _RESIDENCY_LOCK:
        current = _RESIDENCY.get(model_name)
        if current is None:
            return
        if slice_id is None or current == int(slice_id):
            del _RESIDENCY[model_name]


def resident_slice(model_name) -> int | None:
    """Slice id where this model is warm, or None (never loaded)."""
    if not model_name:
        return None
    with _RESIDENCY_LOCK:
        return _RESIDENCY.get(str(model_name))


def residency_snapshot() -> dict[str, int]:
    with _RESIDENCY_LOCK:
        return dict(_RESIDENCY)


def models_resident_on(slice_id: int) -> list[str]:
    """Models whose residency entry points at this slice (healthz view)."""
    with _RESIDENCY_LOCK:
        return sorted(m for m, s in _RESIDENCY.items() if s == int(slice_id))


def reset_residency() -> None:
    """Tests only: forget every residency entry."""
    with _RESIDENCY_LOCK:
        _RESIDENCY.clear()


class SliceAllocator:
    def __init__(self, devices: list | None = None, chips_per_job: int = 0,
                 tensor_parallelism: int = 1, sequence_parallelism: int = 1):
        if devices is None:
            devices = jax.devices()
        if not devices:
            raise Exception("No accelerator devices present. Quitting.")

        n = chips_per_job if chips_per_job > 0 else len(devices)
        if len(devices) % n != 0:
            raise ValueError(
                f"chips_per_job={n} does not divide device count {len(devices)}"
            )

        self.slices = [
            ChipSet(devices[i : i + n], slice_id=i // n,
                    tensor=tensor_parallelism, seq=sequence_parallelism)
            for i in range(0, len(devices), n)
        ]
        self._free: asyncio.Queue[ChipSet] = asyncio.Queue()
        # membership mirrors of the free queue and of handed-out slices:
        # every path that could re-enqueue a slice (release after a job,
        # reinstate after a quarantine probe) funnels through _put_free,
        # so no interleaving of watchdog and worker can double-free one
        self._free_ids: set[int] = set()
        self._leased: set[int] = set()
        self._quarantined: set[int] = set()
        # fired (best-effort) whenever a slice re-enters the free queue so
        # a placement claim blocked on "group ready but no slice free" can
        # re-match without polling (worker wires the dispatch board here)
        self._free_listeners: list[Callable[[], None]] = []
        for s in self.slices:
            self._put_free(s)

    def __len__(self) -> int:
        return len(self.slices)

    def add_free_listener(self, callback: Callable[[], None]) -> None:
        self._free_listeners.append(callback)

    def _put_free(self, chipset: ChipSet) -> None:
        if chipset.slice_id in self._free_ids:
            return
        self._free_ids.add(chipset.slice_id)
        self._free.put_nowait(chipset)
        for cb in self._free_listeners:
            try:
                cb()
            except Exception:  # a notification must never wedge a release
                pass

    @property
    def free_count(self) -> int:
        return self._free.qsize()

    def free_slice_ids(self) -> set[int]:
        return set(self._free_ids)

    def has_free_slice(self) -> bool:
        return not self._free.empty()

    async def acquire(self) -> ChipSet:
        chipset = await self._free.get()
        self._free_ids.discard(chipset.slice_id)
        self._leased.add(chipset.slice_id)
        return chipset

    def try_acquire(self, slice_id: int | None = None) -> ChipSet | None:
        """Non-blocking acquire of a SPECIFIC free slice (or any, when
        slice_id is None). Returns None when the wanted slice (or, with
        None, every slice) is not in the free pool — leased, or evicted
        by quarantine(). Synchronous on purpose: the placement match runs
        check-and-take without an await point, so concurrent slice
        workers cannot race it."""
        target = self._take_from_free(slice_id)
        if target is not None:
            self._leased.add(target.slice_id)
        return target

    def _take_from_free(self, slice_id: int | None) -> ChipSet | None:
        """Pop one slice out of the free queue (a specific one, or the
        FIFO head) without leasing it; non-targets keep their order."""
        kept: list[ChipSet] = []
        target: ChipSet | None = None
        while True:
            try:
                c = self._free.get_nowait()
            except asyncio.QueueEmpty:
                break
            if target is None and (slice_id is None or c.slice_id == slice_id):
                target = c
            else:
                kept.append(c)
        for c in kept:  # preserve FIFO order for plain acquire()
            self._free.put_nowait(c)
        if target is not None:
            self._free_ids.discard(target.slice_id)
        return target

    def acquire_for(self, model_name) -> tuple[ChipSet, str] | None:
        """Placement-aware acquire: the best free slice for `model_name`,
        plus the placement outcome — "affinity" (its home slice was free),
        "cold" (no home anywhere; prefers a slice that is nobody's home so
        later same-model traffic doesn't evict another model's warmth), or
        "steal" (home exists but is busy/quarantined; any free slice beats
        idling — cross-slice batch stealing). None when no slice is free.
        """
        home = resident_slice(model_name)
        if home is not None and home not in self._quarantined:
            chipset = self.try_acquire(home)
            if chipset is not None:
                return chipset, "affinity"
        if not self._free_ids:
            return None
        outcome = "cold" if home is None else "steal"
        occupied = set(residency_snapshot().values())
        preferred = sorted(self._free_ids - occupied) or sorted(self._free_ids)
        for sid in preferred:
            chipset = self.try_acquire(sid)
            if chipset is not None:
                return chipset, outcome
        return None

    def release(self, chipset: ChipSet) -> None:
        self._leased.discard(chipset.slice_id)
        if chipset.slice_id in self._quarantined:
            # the watchdog took this slice out of service mid-job; only a
            # passed smoke probe (reinstate) returns it to the free queue
            return
        self._put_free(chipset)

    # --- quarantine (worker watchdog) ---

    def quarantine(self, chipset: ChipSet) -> None:
        """Take a slice out of service: it will not be handed to jobs and
        release() becomes a no-op for it. Idempotent. A slice sitting in
        the free pool is evicted too — no acquire path (plain, specific,
        or placement) may hand out a quarantined slice."""
        self._quarantined.add(chipset.slice_id)
        if chipset.slice_id in self._free_ids:
            self._take_from_free(chipset.slice_id)

    def reinstate(self, chipset: ChipSet) -> None:
        """Clear a slice's quarantine (smoke probe passed). If a worker
        still holds the slice for the rest of its batch, only the flag
        clears — that worker's release() re-enqueues it; otherwise it goes
        back to the free queue here. No-op when never quarantined."""
        if chipset.slice_id not in self._quarantined:
            return
        self._quarantined.discard(chipset.slice_id)
        if chipset.slice_id in self._leased:
            return
        self._put_free(chipset)

    def is_quarantined(self, chipset: ChipSet) -> bool:
        return chipset.slice_id in self._quarantined

    @property
    def quarantined_count(self) -> int:
        return len(self._quarantined)

    def capabilities(self) -> dict:
        """Pool-wide capability advertisement for /work polling.

        Quarantined slices are excluded — advertised capacity shrinks
        while a slice is out of service, so a capability-aware hive stops
        placing work this worker cannot take."""
        per_slice = self.slices[0].capabilities()
        active = [s for s in self.slices
                  if s.slice_id not in self._quarantined]
        total_chips = sum(s.chip_count() for s in active)
        return {
            "memory": per_slice["memory"],
            "gpu": per_slice["gpu"],
            "chips": total_chips,
            "hbm_gb": sum(s.hbm_bytes() for s in active) >> 30,
            "topology": f"{self.slices[0].platform}x{total_chips}"
            + (f"({len(active)}x{per_slice['chips']})" if len(active) > 1 else ""),
            "slices": len(active),
        }
