"""Cross-job micro-batching: coalesce compatible hive jobs per slice.

The round-5 worker maps one hive job to one chip slice (worker.py
slice_worker), so a batch-1 SDXL job leaves most of a slice's MXU idle
even while the queue holds more jobs for the *same resident model and
shape bucket* — the under-utilization request-batching serving systems
(SwiftDiffusion, arXiv:2407.02031) attack. This module is the batching
layer between the poll loop and the slice workers:

- `coalesce_key(job)` (now in the jax-free shared module coalesce.py,
  re-exported here, because the HIVE uses the same key to gang-schedule)
  buckets a raw hive job by everything that must be IDENTICAL for two
  jobs to share one jitted denoise+decode invocation: (model, family,
  canvas, steps, scheduler, guidance mode, workflow — plain txt2img, or
  img2img with per-request start images at a shared explicit canvas and
  strength). Jobs that carry per-job structure the batched program can't
  express — masks, ControlNet, LoRA, chained stages — key to None and
  take the existing single-job path unchanged.
- `BatchScheduler` holds compatible jobs for a short linger window
  (Settings.batch_linger_ms) so batchmates arriving in the same poll
  burst coalesce, then releases the group to the DISPATCH BOARD as ONE
  work item. Jobs that arrive PRE-BATCHED from a gang-scheduling hive
  (trace.gang on the wire, ISSUE 9) skip the linger entirely via
  `put_gang()` — the hive already did the waiting — flushing as one
  group with reason "gang". Groups cap at Settings.max_coalesce jobs and at the slice's
  capacity limit in images (rows_limit, wired to
  chips/requirements.fit_batch by the worker), so a coalesced batch is
  always admissible without rejection.
- The dispatch board is the placement layer (round 8): released work
  items sit on the board until an idle slice claims one via `claim()`,
  which matches groups to slices by MODEL RESIDENCY (chips/allocator.py
  residency map) — a group goes to the slice where its model is already
  warm ("affinity"), a first-load group prefers a residency-unclaimed
  slice ("cold"), and a group whose home slice is busy is STOLEN by any
  idle slice rather than lingering (the ROADMAP cross-slice-stealing
  item). Interactive groups always claim first. Outcomes are counted in
  `swarm_placement_total{outcome}`.

Batching is an optimization, not a semantic change to what the hive
gets back: every job keeps its own id, prompt, nsfw flags, and result
envelope, and a coalesced job's images depend only on its OWN seed,
never on its batchmates. One honest caveat: the batched program draws
its per-row noise differently from the legacy single-job path, so a
seed-pinned job renders a different (equally valid) image coalesced
than solo — `batched_with` in pipeline_config records which path ran.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable

from . import telemetry
# the compatibility vocabulary moved to the jax-free shared module
# (coalesce.py) so the hive's gang scheduler and this worker-side layer
# can never disagree about what coalesces; re-exported here because five
# PRs of call sites (and tests) import them from batching
from .coalesce import (  # noqa: F401  (re-exports)
    DEFAULT_GUIDANCE,
    DEFAULT_SCHEDULER,
    DEFAULT_STEPS,
    DEFAULT_STRENGTH,
    adapter_ref,
    coalesce_key,
    is_interactive,
    job_rows,
    placement_model,
)

logger = logging.getLogger(__name__)

# why a work item left the scheduler: "solo" (unbatchable / coalescing
# off), "linger" (timer expired), "size" (hit max_coalesce), "rows" (hit
# the slice's image capacity), "slots" (hit the distinct-adapter cap,
# ISSUE 13), "priority" (interactive fast-path),
# "preempt" (an interactive job in a DIFFERENT group flushed this one —
# slice contention, see put()), "gang" (pre-batched by the hive's gang
# scheduler — no linger, see put_gang()), "shutdown" (flush_all)
_FLUSHES = telemetry.counter(
    "swarm_batch_flush_total",
    "Work items released by the batch scheduler, by flush reason",
    ("reason",),
)
_GROUP_JOBS = telemetry.histogram(
    "swarm_batch_group_jobs",
    "Jobs per released work item (coalesce factor; 1 = solo dispatch)",
    buckets=(1, 2, 3, 4, 6, 8, 12, 16),
)
_GROUP_ROWS = telemetry.histogram(
    "swarm_batch_group_rows",
    "Images per released coalesced group",
    buckets=(1, 2, 4, 8, 16, 32),
)
_LINGER_WAIT = telemetry.histogram(
    "swarm_batch_linger_wait_seconds",
    "Open time of a coalescing group from first job to flush",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0),
)
# the tentpole metric: where each claimed work item landed relative to
# its model's warm state. affinity = the resident slice took it; steal =
# the resident slice was busy and an idle slice took it anyway; cold =
# the model was resident nowhere (first load / non-pipeline work)
_PLACEMENT = telemetry.counter(
    "swarm_placement_total",
    "Dispatch-board claims by placement outcome (affinity | steal | cold)",
    ("outcome",),
)

class BatchScheduler:
    """Linger-window grouping between the poll loop and the slice workers'
    dispatch board.

    put() admits raw hive jobs; released work items are LISTS of jobs —
    a singleton for unbatchable jobs (immediately), a coalesced group
    for compatible ones (after the linger window, or sooner when the
    group hits max_coalesce jobs or the slice's capacity in images).
    Slice workers consume via claim() (placement-aware, residency
    routing + stealing) or the plain FIFO get(). task_done() mirrors
    asyncio.Queue so the worker's poll gating (full()) keeps bounding
    in-flight work.
    """

    def __init__(self, linger_s: float = 0.05, max_coalesce: int = 8,
                 maxsize: int = 0, ready_maxsize: int = 0,
                 rows_limit: Callable[[dict], int | None] | None = None,
                 free_slices: Callable[[], int] | None = None,
                 lora_slots: int = 8):
        self.linger_s = max(float(linger_s), 0.0)
        self.max_coalesce = int(max_coalesce)
        # most DISTINCT adapters one group may carry (ISSUE 13): the
        # batched program stacks one factor slot per adapter, so the
        # grouping layer must respect the same cap run_batched enforces
        self.lora_slots = max(int(lora_slots), 1)
        self.maxsize = int(maxsize)
        self.ready_maxsize = int(ready_maxsize)
        self.rows_limit = rows_limit
        # free-slice probe for the interactive preemption rule; None means
        # "unknown" and is treated as contended (preempt — latency first)
        self.free_slices = free_slices
        # the dispatch board: released work items awaiting a slice, oldest
        # first. Each entry: {"jobs", "model", "interactive"}
        self._board: list[dict] = []
        self._change = asyncio.Event()
        # key -> {"jobs": [...], "rows": int, "cap": int|None, "timer": handle}
        self._pending: dict[tuple, dict] = {}
        self._outstanding = 0
        self._ready_jobs = 0  # jobs released to the board, not yet claimed
        # row (image) twins of the job counters, for the capability
        # advertisement: the hive's gang budget is row-denominated, and a
        # job with num_images_per_prompt=4 occupies 4 rows of a slice's
        # coalescing appetite, not 1
        self._ready_rows = 0
        self._executing_rows = 0  # rows claimed off the board, not done
        self._closed = False  # drain mode: nothing lingers anymore

    # --- queue-compatible surface for the worker loop ---

    def full(self) -> bool:
        """Poll gating. Two bounds, so coalescing's extra headroom never
        turns into hoarding of work other swarm members could take:
        - ready_maxsize bounds jobs already RELEASED to the board (the
          round-5 work-queue bound — unbatchable singletons land here
          immediately, so mixed traffic backs polls off exactly as
          before);
        - maxsize bounds total in-flight jobs, giving only the jobs
          LINGERING in open groups the extended coalescing allowance.
        """
        if self.ready_maxsize > 0 and self._ready_jobs >= self.ready_maxsize:
            return True
        return self.maxsize > 0 and self._outstanding >= self.maxsize

    def task_done(self, job: dict | None = None) -> None:
        """One job finished executing. Pass the job dict so the row
        accounting can subtract its true image count (a no-arg call keeps
        the old signature and assumes one row)."""
        self._outstanding -= 1
        self._executing_rows = max(
            self._executing_rows - (job_rows(job) if job is not None else 1),
            0)

    @property
    def pending_jobs(self) -> int:
        """Jobs lingering in open groups (not yet released to the board)."""
        return sum(len(g["jobs"]) for g in self._pending.values())

    @property
    def ready_jobs(self) -> int:
        """Jobs released to the dispatch board but not yet claimed."""
        return self._ready_jobs

    @property
    def outstanding_jobs(self) -> int:
        """All in-flight jobs: lingering + ready + executing."""
        return self._outstanding

    @property
    def outstanding_rows(self) -> int:
        """All in-flight IMAGE ROWS: lingering + ready + executing. This
        is what the worker advertises as `queue_depth` on /work polls —
        the hive's gang budget is row-denominated, and counting jobs
        instead would let a gang reply oversubscribe a slice that is
        mid-coalesce on multi-image jobs."""
        pending_rows = sum(g["rows"] for g in self._pending.values())
        return pending_rows + self._ready_rows + self._executing_rows

    def notify(self) -> None:
        """Wake claim()/get() waiters to re-match (fired on every board
        publish, and wired by the worker to SliceAllocator slice-free
        events so a claim blocked on 'work ready, no slice free' resumes
        the moment a slice returns)."""
        ev, self._change = self._change, asyncio.Event()
        ev.set()

    async def _wait_change(self) -> None:
        # grab the CURRENT event synchronously: callers check their
        # condition and call this with no await in between, so a notify()
        # racing the check can't be lost (single-threaded event loop)
        await self._change.wait()

    async def get(self) -> list[dict]:
        """Plain FIFO pop of the oldest work item (tests/tools; the worker
        uses the placement-aware claim())."""
        while not self._board:
            await self._wait_change()
        entry = self._board.pop(0)
        self._ready_jobs -= len(entry["jobs"])
        self._ready_rows -= entry["rows"]
        self._executing_rows += entry["rows"]
        return entry["jobs"]

    async def claim(self, allocator) -> tuple[list[dict], object, str]:
        """Placement-aware dispatch: wait until a work item AND a free
        slice exist, then match them — returns (jobs, chipset, outcome)
        with the chipset already acquired from `allocator`.

        Match policy, in order (oldest entry first within each rule):
        1. interactive work claims first, wherever it lands;
        2. a group whose model's home slice is free goes HOME (affinity);
        3. a group with no home anywhere takes a free slice, preferring
           one that is nobody's home (cold);
        4. otherwise the oldest group's home is busy: any idle slice
           steals it rather than idling (cross-slice batch stealing).
        The check-and-acquire section is synchronous, so concurrent slice
        workers cannot double-claim an entry or a slice.
        """
        while True:
            if self._board and allocator.has_free_slice():
                match = self._match(allocator)
                if match is not None:
                    return match
            await self._wait_change()

    def _match(self, allocator):
        from .chips.allocator import resident_slice

        def take(idx: int, chipset, outcome: str):
            entry = self._board.pop(idx)
            self._ready_jobs -= len(entry["jobs"])
            self._ready_rows -= entry["rows"]
            self._executing_rows += entry["rows"]
            _PLACEMENT.inc(outcome=outcome)
            return entry["jobs"], chipset, outcome

        # rule 1: interactive first
        for i, entry in enumerate(self._board):
            if entry["interactive"]:
                acquired = allocator.acquire_for(entry["model"])
                if acquired is None:
                    return None
                return take(i, *acquired)
        # rule 2: any entry whose home slice is free goes home
        free_ids = allocator.free_slice_ids()
        for i, entry in enumerate(self._board):
            home = resident_slice(entry["model"])
            if home is not None and home in free_ids:
                chipset = allocator.try_acquire(home)
                if chipset is not None:
                    return take(i, chipset, "affinity")
        # rule 3: oldest homeless entry takes a fresh slice
        for i, entry in enumerate(self._board):
            if resident_slice(entry["model"]) is None:
                acquired = allocator.acquire_for(entry["model"])
                if acquired is None:
                    return None
                return take(i, *acquired)
        # rule 4: every entry's home is busy — steal for the oldest
        acquired = allocator.acquire_for(self._board[0]["model"])
        if acquired is None:
            return None
        return take(0, *acquired)

    def _release(self, jobs: list[dict]) -> None:
        rows = sum(job_rows(j) for j in jobs)
        self._ready_jobs += len(jobs)
        self._ready_rows += rows
        self._board.append({
            "jobs": jobs,
            "rows": rows,
            "model": placement_model(jobs[0]),
            "interactive": any(is_interactive(j) for j in jobs),
        })
        self.notify()

    async def put(self, job: dict) -> None:
        self._outstanding += 1
        if self._closed or self.max_coalesce <= 1 or self.linger_s <= 0:
            self._release_solo(job)
            return
        key = coalesce_key(job)
        if key is None:
            if is_interactive(job):
                self._preempt_lingerers()
            self._release_solo(job)
            return

        rows = job_rows(job)
        adapter = adapter_ref(job)
        group = self._pending.get(key)
        if group is not None and group["cap"] is not None \
                and group["rows"] + rows > group["cap"]:
            # this job would push the group past what the slice fits in
            # one pass — release the full group now, start a fresh one
            self._flush(key, reason="rows")
            group = None
        if (group is not None and adapter is not None
                and adapter not in group["adapters"]
                and len(group["adapters"]) >= self.lora_slots):
            # a new DISTINCT adapter past the stacked-slot cap: release
            # the full group, start a fresh one (ISSUE 13)
            self._flush(key, reason="slots")
            group = None
        if group is None:
            cap = None
            if self.rows_limit is not None:
                try:
                    cap = self.rows_limit(job)
                except Exception:  # capacity probe is advisory, never fatal
                    logger.exception("rows_limit probe failed")
            loop = asyncio.get_running_loop()
            group = {"jobs": [], "rows": 0, "cap": cap, "adapters": set(),
                     "opened": time.monotonic()}
            group["timer"] = loop.call_later(self.linger_s, self._flush, key)
            self._pending[key] = group
        group["jobs"].append(job)
        group["rows"] += rows
        if adapter is not None:
            group["adapters"].add(adapter)
        if is_interactive(job):
            # priority fast-path: an interactive job takes its whole group
            # with it NOW — batchmates already lingering ride along (they
            # only get faster), nobody waits on the timer
            self._flush(key, reason="priority")
            self._preempt_lingerers()
        elif len(group["jobs"]) >= self.max_coalesce:
            self._flush(key, reason="size")
        elif group["cap"] is not None and group["rows"] >= group["cap"]:
            self._flush(key, reason="rows")

    async def put_gang(self, jobs: list[dict]) -> None:
        """Admit a hive-pre-batched gang (jobs sharing one `trace.gang`
        id on the wire): flush immediately as one group with reason
        "gang" — the hive already did the waiting, so a linger window
        here would only add latency. Degrades gracefully: members whose
        key disagrees (or is None — the hive and worker should agree,
        but the worker's view is authoritative for its own slice) fall
        back to the normal put() path, and a gang larger than one
        slice's capacity splits into admissible chunks."""
        if len(jobs) <= 1 or self._closed or self.max_coalesce <= 1:
            for job in jobs:
                await self.put(job)
            return
        solos: list[dict] = []
        by_key: dict[tuple, list[dict]] = {}
        for job in jobs:
            key = coalesce_key(job)
            if key is None:
                solos.append(job)
            else:
                by_key.setdefault(key, []).append(job)
        for members in by_key.values():
            cap = None
            if self.rows_limit is not None:
                try:
                    cap = self.rows_limit(members[0])
                except Exception:  # capacity probe is advisory, never fatal
                    logger.exception("rows_limit probe failed")
            chunk: list[dict] = []
            rows = 0
            adapters: set[str] = set()
            for job in members:
                r = job_rows(job)
                a = adapter_ref(job)
                if chunk and (len(chunk) >= self.max_coalesce
                              or (cap is not None and rows + r > cap)
                              or (a is not None and a not in adapters
                                  and len(adapters) >= self.lora_slots)):
                    self._release_gang(chunk, rows)
                    chunk, rows, adapters = [], 0, set()
                chunk.append(job)
                rows += r
                if a is not None:
                    adapters.add(a)
            if chunk:
                self._release_gang(chunk, rows)
        for job in solos:
            await self.put(job)
        if any(is_interactive(j) for j in jobs):
            # same latency-first rule as put(): an interactive gang on a
            # contended worker must not queue behind linger-timer luck
            self._preempt_lingerers()

    def _release_gang(self, jobs: list[dict], rows: int) -> None:
        self._outstanding += len(jobs)
        _FLUSHES.inc(reason="gang")
        _GROUP_JOBS.observe(len(jobs))
        _GROUP_ROWS.observe(rows)
        _LINGER_WAIT.observe(0.0)
        for job in jobs:
            if isinstance(job.get("trace"), dict):
                job["trace"]["lingered_s"] = 0.0
                job["trace"]["coalesced_with"] = len(jobs) - 1
        if len(jobs) > 1:
            logger.info("hive gang of %d jobs (%d images) for %s",
                        len(jobs), rows, jobs[0].get("model_name"))
        self._release(jobs)

    def _preempt_lingerers(self) -> None:
        """Interactive preemption ACROSS groups (ROADMAP): when an
        interactive job dispatches while slices are contended (at most one
        free), any group still lingering would contend for that slice the
        moment its timer fires — and linger-timer luck must not decide who
        goes first. Flushing them now (reason "preempt") puts every
        contender on the dispatch board, where claim() serves the
        interactive group first, then the preempted groups in age order.
        With multiple free slices nothing blocks, so lingering continues.
        (Callers flush the interactive job's own group before this runs,
        so _pending holds only the OTHER groups.)
        """
        if not self._pending:
            return
        contended = True
        if self.free_slices is not None:
            try:
                contended = int(self.free_slices()) <= 1
            except Exception:  # probe is advisory; stay latency-first
                contended = True
        if not contended:
            return
        for other in list(self._pending):
            self._flush(other, reason="preempt")

    def _release_solo(self, job: dict) -> None:
        _FLUSHES.inc(reason="solo")
        _GROUP_JOBS.observe(1)
        self._release([job])

    def _flush(self, key: tuple, reason: str = "linger") -> None:
        group = self._pending.pop(key, None)
        if group is None:  # timer fired after a size-triggered flush
            return
        group["timer"].cancel()
        _FLUSHES.inc(reason=reason)
        _GROUP_JOBS.observe(len(group["jobs"]))
        _GROUP_ROWS.observe(group["rows"])
        lingered = time.monotonic() - group["opened"]
        _LINGER_WAIT.observe(lingered)
        # split the linger window out of the worker-side queue_wait in
        # each job's trace context (ISSUE 8): "waiting for batchmates"
        # and "waiting for a slice" are different tuning knobs
        # (batch_linger_ms vs capacity), and the job's end-to-end
        # timeline should attribute them separately
        for job in group["jobs"]:
            if isinstance(job.get("trace"), dict):
                job["trace"]["lingered_s"] = round(lingered, 3)
                job["trace"]["coalesced_with"] = len(group["jobs"]) - 1
        if len(group["jobs"]) > 1:
            logger.info(
                "coalesced %d jobs (%d images) for %s [%s]",
                len(group["jobs"]), group["rows"], key[0], reason,
            )
        self._release(group["jobs"])

    def cancel(self, job_id: str) -> bool:
        """Drop a job the hive cancelled while it was still HELD here —
        lingering in an open group or released to the dispatch board but
        not yet claimed by a slice. Returns True when found (the caller
        produces no envelope for it: the hive tombstoned the job, the
        worker simply never runs it). A job already claimed/executing is
        NOT here — that is the cancel registry's half (cancel.py), probed
        by the chunked denoise at chunk boundaries.

        Accounting mirrors the claim/task_done path: the job leaves
        outstanding/ready/row counters so poll gating and the advertised
        queue_depth stay truthful, and an emptied group or board entry
        disappears entirely (its linger timer cancelled)."""
        job_id = str(job_id)

        def matches(job: dict) -> bool:
            return str(job.get("id")) == job_id

        for key, group in list(self._pending.items()):
            for job in group["jobs"]:
                if not matches(job):
                    continue
                group["jobs"].remove(job)
                group["rows"] -= job_rows(job)
                # recompute the distinct-adapter slot accounting (an
                # adapter may be shared by surviving members): a stale
                # set would flush future same-key groups on reason
                # "slots" for adapters no surviving job carries
                group["adapters"] = {
                    a for a in map(adapter_ref, group["jobs"])
                    if a is not None}
                self._outstanding -= 1
                if not group["jobs"]:
                    group["timer"].cancel()
                    del self._pending[key]
                logger.info("cancelled lingering job %s before dispatch",
                            job_id)
                return True
        for entry in list(self._board):
            for job in entry["jobs"]:
                if not matches(job):
                    continue
                rows = job_rows(job)
                entry["jobs"].remove(job)
                entry["rows"] -= rows
                self._ready_jobs -= 1
                self._ready_rows -= rows
                self._outstanding -= 1
                if not entry["jobs"]:
                    self._board.remove(entry)
                logger.info("cancelled board job %s before a slice "
                            "claimed it", job_id)
                return True
        return False

    def flush_all(self) -> None:
        """Release every lingering group immediately (shutdown/tests)."""
        for key in list(self._pending):
            self._flush(key, reason="shutdown")

    def close(self) -> None:
        """Drain mode (worker stop(drain=True)): release every lingering
        group now and dispatch any straggler put() immediately — no job
        may sit in a linger window while the process is trying to exit."""
        self._closed = True
        self.flush_all()
