"""Pipeline registry with weight residency — the heart of the TPU redesign.

The reference resolves diffusers class names from job JSON by reflection
(swarm/type_helpers.py:9-22) and calls `from_pretrained` on EVERY job
(swarm/diffusion/diffusion_func.py:103) — disk -> VRAM per job is its #1
perf loss (SURVEY §2.2). Here:

- job `pipeline_type` strings map to registered `PipelineFactory` entries
  (a fixed table, no reflection / no arbitrary imports);
- built pipelines are cached by (model_name, pipeline_type, variant): Flax
  params are loaded once, transferred to the job's mesh, and stay resident;
  jitted programs are cached by XLA per (shape bucket, step count) on top;
- an LRU bound keeps HBM use sane when a worker serves many models.
"""

from __future__ import annotations

import logging
import threading
from collections import OrderedDict
from typing import Callable

logger = logging.getLogger(__name__)

# wire-name -> family; the table covers every pipeline_type string the
# reference hive can send (SURVEY §2.7) so legacy jobs resolve.
PIPELINE_FAMILIES: dict[str, str] = {
    "DiffusionPipeline": "sd",
    "StableDiffusionPipeline": "sd",
    "StableDiffusionImg2ImgPipeline": "sd",
    "StableDiffusionInpaintPipeline": "sd",
    "StableDiffusionControlNetPipeline": "sd",
    "StableDiffusionControlNetImg2ImgPipeline": "sd",
    "StableDiffusionControlNetInpaintPipeline": "sd",
    "StableDiffusionXLPipeline": "sdxl",
    "StableDiffusionXLImg2ImgPipeline": "sdxl",
    "StableDiffusionXLInpaintPipeline": "sdxl",
    "StableDiffusionXLControlNetPipeline": "sdxl",
    "StableDiffusionXLControlNetImg2ImgPipeline": "sdxl",
    "StableDiffusionXLControlNetInpaintPipeline": "sdxl",
    "StableDiffusionInstructPix2PixPipeline": "sd",
    "StableDiffusionXLInstructPix2PixPipeline": "sdxl",
    "StableDiffusionLatentUpscalePipeline": "sd_upscale",
    "KandinskyPipeline": "kandinsky",
    "KandinskyImg2ImgPipeline": "kandinsky",
    "KandinskyV22Pipeline": "kandinsky",
    "KandinskyV22Img2ImgPipeline": "kandinsky",
    "KandinskyV22ControlnetPipeline": "kandinsky",
    "KandinskyV22ControlnetImg2ImgPipeline": "kandinsky",
    "KandinskyV22PriorPipeline": "kandinsky_prior",
    "KandinskyV22PriorEmb2EmbPipeline": "kandinsky_prior",
    "Kandinsky3Pipeline": "kandinsky3",
    "Kandinsky3Img2ImgPipeline": "kandinsky3",
    "AutoPipelineForText2Image": "sd",
    "StableCascadeDecoderPipeline": "cascade",
    "StableCascadePriorPipeline": "cascade_prior",
    "StableCascadeCombinedPipeline": "cascade",
    "FluxPipeline": "flux",
    "IFPipeline": "deepfloyd_if",
    "IFSuperResolutionPipeline": "deepfloyd_if",
    "AudioLDMPipeline": "audioldm",
    "AudioLDM2Pipeline": "audioldm2",
    "BarkPipeline": "bark",
    "AnimateDiffPipeline": "animatediff",
    "TextToVideoSDPipeline": "animatediff",
    "VideoToVideoSDPipeline": "animatediff",
    "I2VGenXLPipeline": "i2vgenxl",
    "StableVideoDiffusionPipeline": "svd",
    "BlipForConditionalGeneration": "blip",
    "BlipForQuestionAnswering": "blip",
}

# family -> factory(model_name, chipset, **variant) -> pipeline bundle.
# A bundle holds ONE resident param set per (model, family) and serves every
# pipeline_type of that family: run() dispatches txt2img/img2img/inpaint from
# the kwargs it receives (image/mask_image presence), so the txt2img and
# inpaint wire names share weights instead of loading twice.
_FACTORIES: dict[str, Callable] = {}

_CACHE_LOCK = threading.Lock()
_CACHE: OrderedDict[tuple, object] = OrderedDict()
_BUILD_LOCKS: dict[tuple, threading.Lock] = {}
MAX_RESIDENT_PIPELINES = 4


def register_family(family: str):
    def deco(factory: Callable):
        _FACTORIES[family] = factory
        return factory

    return deco


def family_of(pipeline_type: str) -> str:
    try:
        return PIPELINE_FAMILIES[pipeline_type]
    except KeyError:
        raise ValueError(f"Unknown pipeline type: {pipeline_type}") from None


def _auto_family(model_name: str) -> str:
    """Generic wire names (AutoPipelineFor*, DiffusionPipeline) resolve by
    MODEL name, the way diffusers' AutoPipeline does — the reference hive
    sends e.g. Kandinsky jobs as AutoPipelineForText2Image
    (swarm/test.py:96,144)."""
    name = model_name.lower()
    if "kandinsky-3" in name or "kandinsky3" in name:
        return "kandinsky3"
    if "kandinsky" in name:
        return "kandinsky_prior" if "prior" in name else "kandinsky"
    if "cascade" in name:
        return "cascade_prior" if "prior" in name else "cascade"
    if "flux" in name:
        return "flux"
    if name.startswith("deepfloyd/") or "tiny-if" in name:
        return "deepfloyd_if"
    if "latent-upscaler" in name or "tiny-upscaler" in name:
        return "sd_upscale"
    from .models.configs import model_family

    return "sdxl" if "xl" in model_family(model_name) else "sd"


def get_pipeline(model_name: str, pipeline_type: str, chipset=None, **variant):
    """Resolve (and cache) a resident pipeline for this model on this mesh."""
    _ensure_builtin_families()
    if pipeline_type.startswith("AutoPipeline") or pipeline_type == "DiffusionPipeline":
        family = _auto_family(model_name)
    else:
        family = family_of(pipeline_type)
    factory = _FACTORIES.get(family)
    if factory is None:
        raise ValueError(
            f"Pipeline family '{family}' ({pipeline_type}) is not available on "
            "this worker."
        )

    slice_id = getattr(chipset, "slice_id", 0)
    key = (model_name, family, slice_id, tuple(sorted(variant.items())))
    with _CACHE_LOCK:
        if key in _CACHE:
            _CACHE.move_to_end(key)
            pipeline = _CACHE[key]
            hit = True
        else:
            build_lock = _BUILD_LOCKS.setdefault(key, threading.Lock())
            hit = False
    if hit:
        # a cache hit is a residency signal too: this slice serves the
        # model right now, so the dispatch board should keep routing
        # same-model groups here (recency refresh)
        if chipset is not None:
            _note_resident(model_name, slice_id)
        return pipeline

    # build outside the cache lock (weight load/convert can take seconds) but
    # serialized per key so concurrent slices don't double-load weights
    with build_lock:
        with _CACHE_LOCK:
            if key in _CACHE:
                _CACHE.move_to_end(key)
                pipeline = _CACHE[key]
                hit = True
        if hit:
            if chipset is not None:
                _note_resident(model_name, slice_id)
            return pipeline
        logger.info("building pipeline %s/%s", model_name, family)
        pipeline = factory(model_name, chipset, **variant)
        if chipset is not None:
            # the load event feeding the placement layer: this model is
            # now warm on this slice, so the dispatch board routes the
            # next same-model group here (chips/allocator residency map)
            _note_resident(model_name, slice_id)

        with _CACHE_LOCK:
            _CACHE[key] = pipeline
            while len(_CACHE) > MAX_RESIDENT_PIPELINES:
                evicted_key, evicted = _CACHE.popitem(last=False)
                logger.info("evicting resident pipeline %s", evicted_key)
                _clear_resident(evicted_key[0], evicted_key[2])
                release = getattr(evicted, "release", None)
                if release:
                    release()
    return pipeline


def _note_resident(model_name: str, slice_id: int) -> None:
    try:
        from .chips.allocator import note_resident

        note_resident(model_name, slice_id)
    except Exception:  # placement is advisory; never fail a build over it
        logger.debug("residency note failed", exc_info=True)


def _clear_resident(model_name: str, slice_id: int) -> None:
    try:
        from .chips.allocator import clear_resident

        clear_resident(model_name, slice_id)
    except Exception:
        logger.debug("residency clear failed", exc_info=True)


def clear_cache() -> None:
    with _CACHE_LOCK:
        _CACHE.clear()


def resident_models(slice_id: int | None = None) -> list[str]:
    """Model names currently resident in HBM (telemetry /healthz).

    With `slice_id`, only models resident on THAT slice — pipelines and
    their jitted programs are per-slice, so a process-wide answer would
    deny a stolen group its first-compile watchdog allowance on the
    slice that actually has to compile."""
    with _CACHE_LOCK:
        return sorted({
            key[0] for key in _CACHE
            if slice_id is None or key[2] == slice_id
        })


_BUILTINS_LOADED = False


def _ensure_builtin_families() -> None:
    """Import pipeline modules lazily so the registry is importable without jax."""
    global _BUILTINS_LOADED
    if _BUILTINS_LOADED:
        return
    _BUILTINS_LOADED = True
    for module in ("stable_diffusion", "video", "svd", "i2vgen", "audio",
                   "audioldm2",
                   "captioning", "flux", "kandinsky", "kandinsky3", "cascade",
                   "upscale", "deepfloyd", "bark"):
        try:
            __import__(f"{__package__}.pipelines.{module}")
        except Exception as e:
            logger.warning("pipeline family module %s unavailable: %s", module, e)
