"""Coalesce compatibility: the shared, jax-free vocabulary of batching.

`coalesce_key(job)` decides whether two raw hive jobs may share ONE
padded jitted denoise+decode invocation. Until ISSUE 9 that decision
lived inside the worker's batching layer, so the only place compatible
jobs could meet was a 50 ms linger window on one worker — batchmates
that landed in different polls (or on different workers) ran solo by
bad luck. The hive now gang-schedules: `hive_server/queue.py` keeps a
secondary index from this exact key to queued jobs, and
`hive_server/dispatch.py` hands same-key jobs out as ONE pre-batched
/work reply. For that to be sound, both sides MUST agree on the key —
hence this module: imported by the worker's BatchScheduler, the hive's
queue/dispatcher, and the test fake alike, with no jax dependency so a
chip-less coordinator can import it.

Everything here operates on plain wire-format job dicts:

- `coalesce_key(job)` -> tuple | None: the compatibility bucket; None
  means "not batchable, single-job path".
- `job_rows(job)`: images the job contributes to a coalesced batch.
- `is_interactive(job)`: the latency-sensitive marker both the hive's
  priority classes and the worker's linger fast-path read.
- `placement_model(job)`: the model name residency maps know the job
  by (the tiny stand-in when `test_tiny_model` is set).
"""

from __future__ import annotations

# wire pipeline_type strings whose txt2img semantics the batched program
# reproduces exactly (plain prompt-conditioned CFG denoise + decode)
_BATCHABLE_PIPELINE_TYPES = {
    None,
    "DiffusionPipeline",
    "StableDiffusionPipeline",
    "StableDiffusionXLPipeline",
    "AutoPipelineForText2Image",
}

# img2img wire names the stacked-init-latent program variant serves
_BATCHABLE_I2I_PIPELINE_TYPES = {
    None,
    "DiffusionPipeline",
    "StableDiffusionImg2ImgPipeline",
    "StableDiffusionXLImg2ImgPipeline",
    "AutoPipelineForImage2Image",
}

# families with a run_batched entry (pipelines/stable_diffusion.py)
_BATCHABLE_FAMILIES = {"sd", "sdxl"}

# job-level keys that mean per-job structure the padded batch can't carry
# (start_image_uri and strength are handled per-workflow: txt2img refuses
# them, img2img REQUIRES the start image and keys on the strength)
_UNBATCHABLE_JOB_KEYS = (
    "mask_image_uri",
    "lora",
    "refiner",
    "upscale",
    "textual_inversion",
    "vae",
)

# the only `parameters` keys a batchable job may carry; anything else
# (controlnet, scheduler_args, aesthetic_score, ...) is per-job behavior
# we refuse to guess at — the job falls through to the single path
_SAFE_PARAMETER_KEYS = frozenset({
    "test_tiny_model",
    "pipeline_type",
    "scheduler_type",
    "num_inference_steps",
    "guidance_scale",
    "num_images_per_prompt",
    "large_model",
    "use_karras_sigmas",
    "default_height",
    "default_width",
})

DEFAULT_STEPS = 30
DEFAULT_GUIDANCE = 7.5
DEFAULT_SCHEDULER = "DPMSolverMultistepScheduler"
DEFAULT_STRENGTH = 0.75


def is_interactive(job: dict) -> bool:
    """Latency-sensitive marker (ROADMAP "priority-aware batching", minimal
    slice): a job carrying `priority: "interactive"` (or the legacy
    `sdaas_priority` spelling) must not sit in a linger window."""
    return "interactive" in (
        str(job.get("priority", "")).lower(),
        str(job.get("sdaas_priority", "")).lower(),
    )


def job_rows(job: dict) -> int:
    """Images this job contributes to a coalesced batch."""
    params = job.get("parameters") or {}
    try:
        n = int(params.get("num_images_per_prompt",
                           job.get("num_images_per_prompt", 1)) or 1)
    except (TypeError, ValueError):
        return 1
    return max(n, 1)


def placement_model(job: dict) -> str | None:
    """The model name the residency map will know this job by — the tiny
    stand-in when `test_tiny_model` is set (that is the name the registry
    loads and therefore the name load events record)."""
    model = job.get("model_name")
    if not isinstance(model, str) or not model:
        return None
    params = job.get("parameters")
    tiny = bool(job.get("test_tiny_model"))
    if isinstance(params, dict):
        tiny = tiny or bool(params.get("test_tiny_model"))
    if tiny:
        try:
            from .workflows.diffusion import _tiny_stand_in

            return _tiny_stand_in(model)
        except Exception:  # placement is advisory; never fail a job over it
            return model
    return model


def coalesce_key(job: dict) -> tuple | None:
    """Compatibility bucket for one raw hive job; None = not batchable.

    Two jobs with equal keys produce identical results whether they run
    alone or coalesced: everything the jitted program closes over or
    shares across the batch (model, canvas, step count, scheduler,
    guidance scale, workflow, img2img strength) is in the key;
    everything per-row (prompt, negative, seed, start image, image
    count) rides outside it.
    """
    try:
        workflow = job.get("workflow")
        if workflow not in ("txt2img", "img2img"):
            return None
        model = job.get("model_name")
        if not isinstance(model, str) or not model:
            return None
        if any(k in job for k in _UNBATCHABLE_JOB_KEYS):
            return None
        params = job.get("parameters") or {}
        if not isinstance(params, dict):
            return None
        if not set(params) <= _SAFE_PARAMETER_KEYS:
            return None

        from .registry import _auto_family

        family = _auto_family(model)
        if family not in _BATCHABLE_FAMILIES:
            return None

        # canvas: explicit dims, else the model-pinned default the
        # formatter would apply; jobs relying on the family default share
        # the None bucket (they all resolve to the same canvas)
        height = job.get("height", params.get("default_height"))
        width = job.get("width", params.get("default_width"))
        if (height is None) != (width is None):
            return None
        if height is not None:
            height, width = int(height), int(width)

        strength = None
        if workflow == "txt2img":
            # a txt2img job carrying img2img-shaped fields is something
            # the formatter may interpret per-job — single path
            if "start_image_uri" in job or "strength" in job:
                return None
            if params.get("pipeline_type") not in _BATCHABLE_PIPELINE_TYPES:
                return None
        else:  # img2img: per-request start images -> stacked init latents
            if not job.get("start_image_uri"):
                return None
            # without an explicit canvas the solo path sizes the pass to
            # each start image — a group can't share a program over
            # unknown per-image canvases, so explicit dims are required
            if height is None:
                return None
            if params.get("pipeline_type") not in _BATCHABLE_I2I_PIPELINE_TYPES:
                return None
            name = model.lower()
            # edit/inpaint architectures condition on the channel dim —
            # different program semantics, out of the batched variant
            if any(s in name for s in ("pix2pix", "ip2p", "inpaint")):
                return None
            strength = round(float(job.get("strength", DEFAULT_STRENGTH)), 4)

        steps = int(params.get("num_inference_steps",
                               job.get("num_inference_steps", DEFAULT_STEPS)))
        guidance = round(float(params.get(
            "guidance_scale", job.get("guidance_scale", DEFAULT_GUIDANCE))), 4)
        scheduler = str(params.get("scheduler_type", DEFAULT_SCHEDULER))
        karras = bool(params.get("use_karras_sigmas", False))
        # the tiny flag rides at either level on the wire (formatters copy
        # the whole job); both must split the bucket or a real job could
        # coalesce behind a tiny-flagged one and run on the stand-in model
        tiny = bool(params.get("test_tiny_model", False)) \
            or bool(job.get("test_tiny_model", False))
        # large_model flips the SD-vs-SDXL default pipeline class
        large = bool(params.get("large_model", False))
        return (model, family, height, width, steps, scheduler, guidance,
                karras, tiny, large, workflow, strength)
    except (TypeError, ValueError):
        # hive-controlled values that don't parse: let the single-job
        # path produce its usual fatal envelope for them
        return None
