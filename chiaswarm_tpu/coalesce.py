"""Coalesce compatibility: the shared, jax-free vocabulary of batching.

`coalesce_key(job)` decides whether two raw hive jobs may share ONE
padded jitted denoise+decode invocation. Until ISSUE 9 that decision
lived inside the worker's batching layer, so the only place compatible
jobs could meet was a 50 ms linger window on one worker — batchmates
that landed in different polls (or on different workers) ran solo by
bad luck. The hive now gang-schedules: `hive_server/queue.py` keeps a
secondary index from this exact key to queued jobs, and
`hive_server/dispatch.py` hands same-key jobs out as ONE pre-batched
/work reply. For that to be sound, both sides MUST agree on the key —
hence this module: imported by the worker's BatchScheduler, the hive's
queue/dispatcher, and the test fake alike, with no jax dependency so a
chip-less coordinator can import it.

Everything here operates on plain wire-format job dicts:

- `coalesce_key(job)` -> tuple | None: the compatibility bucket; None
  means "not batchable, single-job path".
- `job_rows(job)`: images the job contributes to a coalesced batch.
- `is_interactive(job)`: the latency-sensitive marker both the hive's
  priority classes and the worker's linger fast-path read.
- `placement_model(job)`: the model name residency maps know the job
  by (the tiny stand-in when `test_tiny_model` is set).
"""

from __future__ import annotations

import os

# wire pipeline_type strings whose txt2img semantics the batched program
# reproduces exactly (plain prompt-conditioned CFG denoise + decode)
_BATCHABLE_PIPELINE_TYPES = {
    None,
    "DiffusionPipeline",
    "StableDiffusionPipeline",
    "StableDiffusionXLPipeline",
    "AutoPipelineForText2Image",
}

# img2img wire names the stacked-init-latent program variant serves
_BATCHABLE_I2I_PIPELINE_TYPES = {
    None,
    "DiffusionPipeline",
    "StableDiffusionImg2ImgPipeline",
    "StableDiffusionXLImg2ImgPipeline",
    "AutoPipelineForImage2Image",
}

# families with a run_batched entry (pipelines/stable_diffusion.py for
# the UNet families; pipelines/flux.py since ISSUE 20)
_BATCHABLE_FAMILIES = {"sd", "sdxl", "flux"}

# txt2img wire names the coalesced flux pass reproduces exactly (plain
# prompt-conditioned rectified-flow denoise + decode; no CFG doubling)
_BATCHABLE_FLUX_PIPELINE_TYPES = {
    None,
    "DiffusionPipeline",
    "FluxPipeline",
    "AutoPipelineForText2Image",
}

# job-level keys that mean per-job structure the padded batch can't carry
# (start_image_uri and strength are handled per-workflow: txt2img refuses
# them, img2img REQUIRES the start image and keys on the strength; `lora`
# left this list in ISSUE 13 — adapters now ride PER ROW as runtime
# low-rank deltas, so adapter identity no longer splits the bucket)
_UNBATCHABLE_JOB_KEYS = (
    "mask_image_uri",
    "refiner",
    "upscale",
    "textual_inversion",
    "vae",
)

# the only `parameters` keys a batchable job may carry; anything else
# (scheduler_args, aesthetic_score, ...) is per-job behavior we refuse
# to guess at — the job falls through to the single path. `controlnet`
# is handled explicitly (the shared-ControlNet component below), and
# cross_attention_kwargs / lora_rank ride with the per-row adapter.
_SAFE_PARAMETER_KEYS = frozenset({
    "test_tiny_model",
    "pipeline_type",
    "scheduler_type",
    "num_inference_steps",
    "guidance_scale",
    "num_images_per_prompt",
    "large_model",
    "use_karras_sigmas",
    "default_height",
    "default_width",
    "controlnet",
    "cross_attention_kwargs",
    "lora_rank",
})

# txt2img-ControlNet wire names whose batched semantics the shared-
# ControlNet group reproduces (one control image conditions every row)
_BATCHABLE_CN_PIPELINE_TYPES = {
    None,
    "StableDiffusionControlNetPipeline",
    "StableDiffusionXLControlNetPipeline",
}

DEFAULT_STEPS = 30
DEFAULT_GUIDANCE = 7.5
DEFAULT_SCHEDULER = "DPMSolverMultistepScheduler"
DEFAULT_STRENGTH = 0.75

# --- stage-graph vocabulary (ISSUE 20) -------------------------------
# Stage-typed placement needs one spelling of stage names on BOTH sides
# of the wire: the hive's dispatcher gates hand-outs on the stages a
# worker advertises, and the worker derives its advertisement (and its
# local routing — chip slice vs. the jax-free stage executor) from the
# same sets. Chip stages run accelerator programs; CPU stages are
# jax-free host work (prompt/conditioning prep, NSFW check + packaging)
# that can land on a chip-less host.

CHIP_STAGES = frozenset({
    "denoise", "upscale", "svd", "i2vgen", "txt2vid", "vid2vid", "audio",
})
CPU_STAGES = frozenset({
    "encode", "decode", "postprocess", "stitch", "caption",
})


def stage_of(job: dict) -> str | None:
    """The stage name a stage-job carries, or None for a monolithic job.
    The `stage` context dict is stamped by the hive's workflow expander
    (hive_server/dag.py); its absence IS the monolithic path."""
    stage = job.get("stage")
    if isinstance(stage, dict):
        name = stage.get("name")
        if isinstance(name, str) and name:
            return name
    return None


def is_interactive(job: dict) -> bool:
    """Latency-sensitive marker (ROADMAP "priority-aware batching", minimal
    slice): a job carrying `priority: "interactive"` (or the legacy
    `sdaas_priority` spelling) must not sit in a linger window."""
    return "interactive" in (
        str(job.get("priority", "")).lower(),
        str(job.get("sdaas_priority", "")).lower(),
    )


def job_rows(job: dict) -> int:
    """Images this job contributes to a coalesced batch."""
    params = job.get("parameters") or {}
    try:
        n = int(params.get("num_images_per_prompt",
                           job.get("num_images_per_prompt", 1)) or 1)
    except (TypeError, ValueError):
        return 1
    return max(n, 1)


def placement_model(job: dict) -> str | None:
    """The model name the residency map will know this job by — the tiny
    stand-in when `test_tiny_model` is set (that is the name the registry
    loads and therefore the name load events record)."""
    model = job.get("model_name")
    if not isinstance(model, str) or not model:
        return None
    params = job.get("parameters")
    tiny = bool(job.get("test_tiny_model"))
    if isinstance(params, dict):
        tiny = tiny or bool(params.get("test_tiny_model"))
    if tiny:
        try:
            from .workflows.diffusion import _tiny_stand_in

            return _tiny_stand_in(model)
        except Exception:  # placement is advisory; never fail a job over it
            return model
    return model


def adapter_ref(job: dict) -> str | None:
    """The adapter IDENTITY one job carries, or None — per-row data for
    the batched program, but the hive's gang dispatcher and the worker's
    scheduler both cap DISTINCT adapters per gang at `lora_slots_max`
    (the stacked-factor slot dimension), so both need one canonical
    spelling. Handles the raw wire string and the resolved
    {lora, weight_name, subfolder} dict alike."""
    lora = job.get("lora")
    if lora is None or lora == "":
        return None
    if isinstance(lora, dict):
        return "|".join(
            str(lora.get(k) or "")
            for k in ("lora", "weight_name", "subfolder"))
    return str(lora)


def wire_adapter_ref(ref, weight_name=None, subfolder=None) -> str:
    """Resolved adapter parts -> the WIRE spelling the submitting
    client used (loras.resolve_lora inverted). The worker's operand
    cache is keyed by the RESOLVED dict — its `lora` field holds the
    worker-local root dir for bare-name references — while the hive
    reads the raw job string, so cross-process identity (the /work
    resident-adapter advertisement, ISSUE 16) must reconstruct the
    form both started from:

      local root dir + weight file      -> the bare file name
      hub repo [+ subfolder] [+ file]   -> "pub/repo[/sub...][/file]"

    A worker-local root dir is configuration, not adapter identity —
    two workers with different `lora_root_dir` serving the same
    adapter must advertise the same ref."""
    ref = str(ref or "")
    name = str(weight_name or "")
    sub = str(subfolder or "")
    if name and os.path.isabs(os.path.expanduser(ref)):
        return "/".join(p for p in (sub, name) if p)
    return "/".join(p for p in (ref, sub, name) if p)


def canonical_adapter_ref(job: dict) -> str | None:
    """adapter_ref normalized for CROSS-PROCESS identity (the /work
    resident-adapter advertisement, ISSUE 16): the resolved dict
    spelling and the raw wire string collapse to one form via
    wire_adapter_ref, so a worker whose operand cache was fed by
    resolved-dict jobs still matches a string-form job's adapters."""
    lora = job.get("lora")
    if lora is None or lora == "":
        return None
    if isinstance(lora, dict):
        return wire_adapter_ref(
            lora.get("lora"), lora.get("weight_name"),
            lora.get("subfolder"))
    # legacy pipe-joined string spellings ("style-a||") still collapse
    return str(lora).rstrip("|")


# smallest padded factor rank the batched program compiles
# (lora_runtime.MIN_RANK imports this): declared ranks below it all run
# as the same rank-4-padded program, so they must share one bucket here
LORA_MIN_RANK = 4


def _pow2_bucket(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


# _runtime_delta_on memo: (env spelling, settings-file mtime_ns) -> flag.
# coalesce_key runs per job on the hive submit and worker enqueue hot
# paths; a full settings read+parse per adapter job would add disk I/O
# there, but the flag only changes when the env var or the file does —
# one getenv + one stat re-validates it.
_DELTA_FLAG: tuple[tuple, bool] | None = None


def _runtime_delta_on() -> bool:
    """Settings.lora_runtime_delta at call time — jax-free. The kill
    switch restores the pre-ISSUE-13 serving shape end to end: with
    deltas off, run_batched refuses adapter groups, so admitting them
    here would only buy a doomed coalesced attempt + a noisy solo
    fallback per group."""
    global _DELTA_FLAG
    try:
        import os

        from .settings import get_settings_dir, load_settings

        # get_settings_full_path() mkdirs the settings dir as a side
        # effect — derive the path without it, one stat only
        root = os.getenv("SDAAS_ROOT")
        try:
            mtime = os.stat(
                get_settings_dir() / "settings.json").st_mtime_ns
        except OSError:
            mtime = None
        fingerprint = (os.getenv("CHIASWARM_LORA_RUNTIME_DELTA"), root,
                       mtime)
        if _DELTA_FLAG is not None and _DELTA_FLAG[0] == fingerprint:
            return _DELTA_FLAG[1]
        flag = bool(getattr(load_settings(), "lora_runtime_delta", True))
        _DELTA_FLAG = (fingerprint, flag)
        return flag
    except Exception:  # settings trouble must never unbatch plain jobs
        return True


def _adapter_component(job: dict, params: dict) -> tuple | None:
    """The coalesce key's adapter-slot dimension (ISSUE 13): jobs
    carrying an adapter coalesce with each other AND with adapter-free
    jobs on the same base model (adapter-free rows ride slot 0 of the
    stacked factors with an exact zero delta), so adapter PRESENCE never
    splits the bucket — identity rides per row. Only a submitter-
    declared `lora_rank` splits, by power-of-two RANK BUCKET: a gang's
    stacked factors share one padded rank, and an explicit hint keeps a
    rank-4 fleet from padding to a declared rank-128 outlier. Undeclared
    ranks coalesce with everything; zero-padding keeps any mix exact
    either way."""
    if adapter_ref(job) is None:
        return None
    try:
        rank = int(params.get("lora_rank", job.get("lora_rank", 0)) or 0)
    except (TypeError, ValueError):
        rank = 0
    if rank <= 0:
        return None  # same bucket as adapter-free jobs
    return ("lora", _pow2_bucket(max(rank, LORA_MIN_RANK)))


def _controlnet_component(job: dict, params: dict,
                          workflow: str) -> tuple | None | bool:
    """The shared-ControlNet dimension (ISSUE 13 second rung): jobs
    conditioned by ONE identical ControlNet branch + control image
    coalesce, with the control residuals computed once per group. False
    = the job carries ControlNet structure the batched program cannot
    share (per-job start-image conditioning, QR prepipelines) -> single
    path; None = no ControlNet."""
    cn = params.get("controlnet")
    if cn is None:
        return None
    if not isinstance(cn, dict) or workflow != "txt2img":
        return False
    cn_params = cn.get("parameters") or {}
    if not isinstance(cn_params, dict):
        return False
    if cn_params.get("controlnet_prepipeline_type"):
        return False  # QR two-stage chains per job
    if cn.get("qr_code_contents"):
        return False  # generated control images are per-job content
    uri = cn.get("control_image_uri")
    if not uri:
        return False
    if params.get("pipeline_type") not in _BATCHABLE_CN_PIPELINE_TYPES:
        return False
    return (
        str(cn.get("controlnet_model_name",
                   "lllyasviel/control_v11p_sd15_canny")),
        str(uri),
        str(cn.get("preprocessor") or ""),
        round(float(cn.get("controlnet_conditioning_scale", 1.0)), 4),
        round(float(cn.get("control_guidance_start", 0.0)), 4),
        round(float(cn.get("control_guidance_end", 1.0)), 4),
    )


def coalesce_key(job: dict) -> tuple | None:
    """Compatibility bucket for one raw hive job; None = not batchable.

    Two jobs with equal keys produce identical results whether they run
    alone or coalesced: everything the jitted program closes over or
    shares across the batch (model, canvas, step count, scheduler,
    guidance scale, workflow, img2img strength, the SHARED ControlNet
    branch + control image) is in the key; everything per-row (prompt,
    negative, seed, start image, image count, ADAPTER identity + scale)
    rides outside it. The adapter-slot element splits only by rank
    bucket — same base model + compatible rank coalesce, thousands of
    adapters over one resident tree (ISSUE 13).
    """
    try:
        workflow = job.get("workflow")
        if workflow not in ("txt2img", "img2img"):
            return None
        # stage-jobs (ISSUE 20): only the denoise stage is the padded
        # jitted program; it coalesces with OTHER denoise stages but
        # never with monolithic jobs (the envelopes differ — a denoise
        # stage hands off raw rows instead of packaged outputs), so the
        # stage name is a key dimension. Every other stage is host work
        # on the single path.
        stage = stage_of(job)
        if stage is not None and stage != "denoise":
            return None
        model = job.get("model_name")
        if not isinstance(model, str) or not model:
            return None
        if any(k in job for k in _UNBATCHABLE_JOB_KEYS):
            return None
        params = job.get("parameters") or {}
        if not isinstance(params, dict):
            return None
        if not set(params) <= _SAFE_PARAMETER_KEYS:
            return None

        adapter = _adapter_component(job, params)
        if adapter_ref(job) is not None and not _runtime_delta_on():
            # lora_runtime_delta=0: adapters serve via merged trees on
            # the single path — adapter jobs are uncoalesceable again
            return None
        cn = _controlnet_component(job, params, workflow)
        if cn is False:
            return None
        if cn is not None and adapter_ref(job) is not None:
            # each is batchable alone; the combination stays on the
            # single path (the delta interceptor is scoped to the UNet,
            # but the grouping matrix stays small and tested)
            return None

        from .registry import _auto_family

        family = _auto_family(model)
        if family not in _BATCHABLE_FAMILIES:
            return None
        if family == "flux":
            # flow-matching txt2img only: no CFG pair, no adapter delta
            # path, no ControlNet branch in the MMDiT program. Steps and
            # guidance must be EXPLICIT — the solo path's defaults are
            # model-variant-dependent (schnell distills to 4 steps,
            # guidance 3.5 vs the UNet families' 7.5), which this
            # jax-free key cannot reproduce without guessing.
            if workflow != "txt2img" or cn is not None \
                    or adapter_ref(job) is not None:
                return None
            if params.get("pipeline_type") \
                    not in _BATCHABLE_FLUX_PIPELINE_TYPES:
                return None
            if params.get("num_inference_steps",
                          job.get("num_inference_steps")) is None:
                return None
            if params.get("guidance_scale",
                          job.get("guidance_scale")) is None:
                return None

        # canvas: explicit dims, else the model-pinned default the
        # formatter would apply; jobs relying on the family default share
        # the None bucket (they all resolve to the same canvas)
        height = job.get("height", params.get("default_height"))
        width = job.get("width", params.get("default_width"))
        if (height is None) != (width is None):
            return None
        if height is not None:
            height, width = int(height), int(width)

        strength = None
        if workflow == "txt2img":
            # a txt2img job carrying img2img-shaped fields is something
            # the formatter may interpret per-job — single path
            if "start_image_uri" in job or "strength" in job:
                return None
            # the shared-ControlNet component validated its own pipeline
            # types, and the flux branch above validated flux wire
            # names; a plain txt2img job keeps the original gate
            if cn is None and family != "flux" and (
                    params.get("pipeline_type")
                    not in _BATCHABLE_PIPELINE_TYPES):
                return None
        else:  # img2img: per-request start images -> stacked init latents
            if not job.get("start_image_uri"):
                return None
            # without an explicit canvas the solo path sizes the pass to
            # each start image — a group can't share a program over
            # unknown per-image canvases, so explicit dims are required
            if height is None:
                return None
            if params.get("pipeline_type") not in _BATCHABLE_I2I_PIPELINE_TYPES:
                return None
            name = model.lower()
            # edit/inpaint architectures condition on the channel dim —
            # different program semantics, out of the batched variant
            if any(s in name for s in ("pix2pix", "ip2p", "inpaint")):
                return None
            strength = round(float(job.get("strength", DEFAULT_STRENGTH)), 4)

        steps = int(params.get("num_inference_steps",
                               job.get("num_inference_steps", DEFAULT_STEPS)))
        guidance = round(float(params.get(
            "guidance_scale", job.get("guidance_scale", DEFAULT_GUIDANCE))), 4)
        scheduler = str(params.get("scheduler_type", DEFAULT_SCHEDULER))
        karras = bool(params.get("use_karras_sigmas", False))
        # the tiny flag rides at either level on the wire (formatters copy
        # the whole job); both must split the bucket or a real job could
        # coalesce behind a tiny-flagged one and run on the stand-in model
        tiny = bool(params.get("test_tiny_model", False)) \
            or bool(job.get("test_tiny_model", False))
        # large_model flips the SD-vs-SDXL default pipeline class
        large = bool(params.get("large_model", False))
        return (model, family, height, width, steps, scheduler, guidance,
                karras, tiny, large, workflow, strength, adapter, cn,
                stage)
    except (TypeError, ValueError):
        # hive-controlled values that don't parse: let the single-job
        # path produce its usual fatal envelope for them
        return None
