"""Job JSON -> (workload callback, normalized kwargs).

Dispatch parity with reference swarm/job_arguments.py:24-397: same workflow
keys (txt2img / img2img / inpaint / txt2vid / img2vid / vid2vid / txt2audio /
img2txt / stitch), same defaults (30 SD steps, 25 video, 20 audio; 1024 size
cap; SD-vs-SDXL pipeline selection via `large_model`; instruct-pix2pix
strength -> image_guidance_scale x5), same ControlNet argument extraction.

Differences by design:
- `parameters.pipeline_type` / `scheduler_type` stay *strings* resolved
  against our pipeline registry (`registry.py`) at execution time — no
  `__import__` reflection over class names (reference swarm/type_helpers.py:
  9-22), which was both a supply-chain hazard and a torch-ism.
- The reference's inpaint bug (swarm/job_arguments.py:234 passes
  device_identifier as `size`) is fixed: size flows through properly.
- Workload callbacks are imported lazily so formatting is testable without
  pulling in model code.
"""

from __future__ import annotations

import asyncio

from .external_resources import (
    download_images,
    get_image,
    get_qrcode_image,
    is_not_blank,
    max_size,
)
from .loras import Loras
from .pre_processors.image_utils import center_crop_resize, resize_square

# Default wire names (reference defaults at swarm/job_arguments.py:83-118,206-210)
DEFAULT_SCHEDULER = "DPMSolverMultistepScheduler"

# models whose strength parameter maps to image_guidance_scale (x5)
_PIX2PIX_MODELS = {"timbrooks/instruct-pix2pix", "diffusers/sdxl-instructpix2pix-768"}
_SIZE_LOCKED_MODELS = {
    "diffusers/sdxl-instructpix2pix-768",
    "kandinsky-community/kandinsky-2-2-controlnet-depth",
}


async def format_args(job: dict, settings, device_identifier: str):
    args = prepare_args(job, settings)
    stage = args.pop("stage", None)
    workflow = args.pop("workflow", None)

    if isinstance(stage, dict) and stage.get("name"):
        # stage-graph jobs (ISSUE 20): host stages (encode/decode) route
        # to their own callbacks; chip stages fall through to the classic
        # dispatch below with the graph metadata (emit_raw handoff,
        # injected start image) already applied to `args`
        from .workflows.stages import format_stage_args

        routed = await format_stage_args(
            stage, workflow, args, settings, device_identifier)
        if routed is not None:
            return routed

    if workflow == "echo":
        from .workflows.echo import echo_callback

        return echo_callback, args

    if workflow == "txt2audio":
        if args["model_name"] == "suno/bark":
            from .workflows.audio import bark_callback

            return bark_callback, args
        return format_txt2audio_args(args)

    if workflow == "stitch":
        return await format_stitch_args(args)

    if workflow == "img2txt":
        return await format_img2txt_args(args)

    if workflow == "vid2vid":
        from .workflows.video import vid2vid_callback

        return vid2vid_callback, args

    if workflow == "txt2vid":
        return format_txt2vid_args(args)

    if workflow == "img2vid":
        return await format_img2vid_args(args)

    if args["model_name"].startswith("DeepFloyd/"):
        from .workflows.diffusion import deepfloyd_if_callback

        return deepfloyd_if_callback, args

    return await format_stable_diffusion_args(args, workflow, device_identifier)


def prepare_args(job: dict, settings) -> dict:
    args = dict(job)
    if "lora" in args:
        args["lora"] = Loras(settings.lora_root_dir).resolve_lora(args["lora"])
    return args


# --- non-diffusion workflows ---


async def format_stitch_args(args: dict):
    from .workflows.stitch import stitch_callback

    image_urls = [j["resultUri"] for j in args["jobs"]]
    args["images"] = await download_images(image_urls)
    return stitch_callback, args


async def format_img2txt_args(args: dict):
    from .workflows.captioning import caption_callback

    if "start_image_uri" in args:
        args["image"] = await get_image(args.pop("start_image_uri"), None)
    return caption_callback, args


def format_txt2audio_args(args: dict):
    from .workflows.audio import txt2audio_callback

    parameters = args.pop("parameters", {})
    args.setdefault("prompt", "")
    args.setdefault("num_inference_steps", 20)
    args["pipeline_type"] = parameters.pop("pipeline_type", "AudioLDMPipeline")
    args["scheduler_type"] = parameters.pop("scheduler_type", DEFAULT_SCHEDULER)
    _drop_unsupported(args, parameters)
    # remaining special parameters (test_tiny_model, audio_length_in_s, ...)
    # pass through WITHOUT overwriting already-formatted top-level args —
    # a hive-controlled parameters dict must not rewrite model_name/prompt
    _merge_passthrough(args, parameters)
    return txt2audio_callback, args


def format_txt2vid_args(args: dict):
    from .workflows.video import txt2vid_callback

    parameters = args.pop("parameters", {})
    args.setdefault("prompt", "")
    args.setdefault("num_inference_steps", 25)
    args.pop("num_images_per_prompt", None)

    args["pipeline_type"] = parameters.pop("pipeline_type", "DiffusionPipeline")

    # model-pinned scheduler args trump user settings (reference :109-119)
    if "scheduler_args" in parameters:
        scheduler_args = parameters["scheduler_args"]
        args["scheduler_type"] = scheduler_args.pop("scheduler_type", "LCMScheduler")
        args["scheduler_args"] = scheduler_args
    else:
        args["scheduler_type"] = parameters.pop("scheduler_type", DEFAULT_SCHEDULER)

    if "motion_adapter" in parameters:
        args["motion_adapter"] = parameters["motion_adapter"]
    if "lora" in parameters:
        args["lora"] = parameters["lora"]

    _drop_unsupported(args, parameters)
    _merge_passthrough(args, parameters)
    return txt2vid_callback, args


async def format_img2vid_args(args: dict):
    from .workflows.video import img2vid_callback

    parameters = args.pop("parameters", {})
    args.setdefault("prompt", "")
    args.setdefault("num_inference_steps", 25)
    args.pop("num_images_per_prompt", None)

    args["pipeline_type"] = parameters.pop("pipeline_type", "I2VGenXLPipeline")
    args["scheduler_type"] = parameters.pop("scheduler_type", DEFAULT_SCHEDULER)

    if "start_image_uri" in args:
        args["image"] = await get_image(args.pop("start_image_uri"), None)

    _drop_unsupported(args, parameters)
    _merge_passthrough(args, parameters)
    return img2vid_callback, args


# --- stable-diffusion family ---


async def format_stable_diffusion_args(args: dict, workflow, device_identifier: str):
    from .workflows.diffusion import diffusion_callback

    size = None
    if "height" in args and "width" in args:
        if args["height"] > max_size or args["width"] > max_size:
            raise Exception(
                f"The max image size is ({max_size}, {max_size}); "
                f"got ({args['height']}, {args['width']})."
            )
        # PIL (width, height) convention throughout the input path
        size = (args["width"], args["height"])

    args.setdefault("prompt", "")
    parameters = args.pop("parameters", {})

    if workflow == "img2img":
        await format_img2img_args(args, parameters, size, device_identifier)
    elif workflow == "inpaint" or "mask_image_uri" in args:
        await format_inpaint_args(args, parameters, size, device_identifier)
    elif workflow == "txt2img":
        await format_txt2img_args(args, parameters, size, device_identifier)

    args.setdefault("num_inference_steps", 30)

    if "pipeline_prior_type" in parameters:
        args["pipeline_prior_type"] = parameters.pop("pipeline_prior_type")
    if "prior_timesteps" in parameters:
        args["prior_timesteps"] = parameters.pop("prior_timesteps")

    args["pipeline_type"] = parameters.pop("pipeline_type", "DiffusionPipeline")
    args["scheduler_type"] = parameters.pop("scheduler_type", DEFAULT_SCHEDULER)

    # model-specified default canvas (reference :213-219)
    default_height = parameters.pop("default_height", None)
    default_width = parameters.pop("default_width", None)
    if default_height is not None and "height" not in args:
        args["height"] = default_height
    if default_width is not None and "width" not in args:
        args["width"] = default_width

    _drop_unsupported(args, parameters)
    # remaining special parameters pass straight through to the pipeline
    # (protected identity keys excepted — same rule as the other formatters)
    _merge_passthrough(args, parameters)

    return diffusion_callback, args


async def format_txt2img_args(args, parameters, size, device_identifier):
    if "controlnet" in parameters:
        parameters.setdefault(
            "pipeline_type",
            "StableDiffusionXLControlNetPipeline"
            if parameters.get("large_model", False)
            else "StableDiffusionControlNetPipeline",
        )
        await format_controlnet_args(args, parameters, None, size, device_identifier)


async def format_inpaint_args(args, parameters, size, device_identifier):
    # pick the inpaint pipeline class BEFORE delegating to img2img setup so
    # img2img's own default doesn't claim the slot (the reference effectively
    # dispatched bare inpaint jobs to the img2img class, :234+290)
    large = parameters.get("large_model", False)
    if "controlnet" in parameters:
        parameters.setdefault(
            "pipeline_type",
            "StableDiffusionXLControlNetInpaintPipeline"
            if large
            else "StableDiffusionControlNetInpaintPipeline",
        )
    else:
        parameters.setdefault(
            "pipeline_type",
            "StableDiffusionXLInpaintPipeline"
            if large
            else "StableDiffusionInpaintPipeline",
        )

    # inpaint inherits img2img setup since it has a start image
    # (size is threaded through properly — reference :234 dropped it)
    await format_img2img_args(args, parameters, size, device_identifier)
    args["mask_image"] = await get_image(args.pop("mask_image_uri"), size)
    args.pop("height", None)
    args.pop("width", None)

    if "controlnet" in parameters:
        await format_controlnet_args(args, parameters, None, size, device_identifier)


async def format_img2img_args(args, parameters, size, device_identifier):
    start_image = await get_image(args.pop("start_image_uri", None), size)

    if size is None and start_image is not None:
        size = start_image.size

    if "controlnet" in parameters:
        await format_controlnet_args(
            args, parameters, start_image, size, device_identifier
        )
        parameters.setdefault(
            "pipeline_type",
            "StableDiffusionXLControlNetImg2ImgPipeline"
            if parameters.get("large_model", False)
            else "StableDiffusionControlNetImg2ImgPipeline",
        )
    elif "pipeline_type" not in parameters:
        parameters["pipeline_type"] = (
            "StableDiffusionXLImg2ImgPipeline"
            if parameters.get("large_model", False)
            else "StableDiffusionImg2ImgPipeline"
        )
        args.pop("height", None)
        args.pop("width", None)

    if args["model_name"] in _PIX2PIX_MODELS:
        # pix2pix uses image_guidance_scale (range 1-5) instead of strength (0-1)
        args["image_guidance_scale"] = args.pop("strength", 0.6) * 5

    if start_image is None and args.get("control_image") is not None:
        start_image = args["control_image"]
    if start_image is None:
        raise ValueError("Workflow requires an input image. None provided")

    if args["model_name"] in _SIZE_LOCKED_MODELS and not parameters.get(
        "test_tiny_model"
    ):
        # these checkpoints error off their native 768 canvas (reference
        # :314-321); tiny-model test jobs keep their small canvas
        start_image = resize_square(start_image).resize((768, 768))
        args["height"] = start_image.height
        args["width"] = start_image.width

    if "control_image" in args:
        start_image = center_crop_resize(start_image, args["control_image"].size)

    args["image"] = start_image


def _flag_degraded(args: dict, preprocessor: str) -> None:
    """Surface classical-CV annotator stand-ins in the result envelope
    (VERDICT r03 weak #5): the hive/user must be able to see that the
    conditioning image came from an approximation, not the learned
    detector the reference runs."""
    from .pre_processors.controlnet import is_degraded_preprocessor

    if is_degraded_preprocessor(preprocessor):
        args.setdefault("degraded_preprocessors", []).append(preprocessor)


async def _preprocess_off_loop(image, preprocessor: str, device_identifier: str):
    """Model-backed preprocessors (depth etc.) load weights and jit-compile;
    run them in the default executor so the poll/upload loops keep breathing
    (the same boundary do_work uses for pipeline execution)."""
    from .pre_processors.controlnet import preprocess_image

    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(
        None, preprocess_image, image, preprocessor, device_identifier
    )


async def format_controlnet_args(args, parameters, start_image, size, device_identifier):
    controlnet = parameters.pop("controlnet")
    control_image = await get_image(controlnet.get("control_image_uri"), size)
    args["save_preprocessed_input"] = True

    if is_not_blank(controlnet.get("qr_code_contents")):
        # a QR code overrides any provided control image
        control_image = await get_qrcode_image(controlnet["qr_code_contents"], size)
        if start_image is None:
            start_image = control_image
    elif start_image is not None and is_not_blank(controlnet.get("preprocessor")):
        control_image = await _preprocess_off_loop(
            start_image, controlnet["preprocessor"], device_identifier
        )
        _flag_degraded(args, controlnet["preprocessor"])
    elif control_image is not None and is_not_blank(controlnet.get("preprocessor")):
        control_image = await _preprocess_off_loop(
            control_image, controlnet["preprocessor"], device_identifier
        )
        _flag_degraded(args, controlnet["preprocessor"])
    elif control_image is None:
        control_image = start_image

    if control_image is None:
        raise ValueError("Controlnet specified but no control image provided")

    controlnet_parameters = controlnet.get("parameters", {})
    args["controlnet_model_type"] = controlnet_parameters.get(
        "controlnet_model_type", "ControlNetModel"
    )
    if "controlnet_prepipeline_type" in controlnet_parameters:
        args["controlnet_prepipeline_type"] = controlnet_parameters[
            "controlnet_prepipeline_type"
        ]
    args["controlnet_model_name"] = controlnet.get(
        "controlnet_model_name", "lllyasviel/control_v11p_sd15_canny"
    )
    args["controlnet_conditioning_scale"] = float(
        controlnet.get("controlnet_conditioning_scale", 1.0)
    )
    args["control_guidance_start"] = float(controlnet.get("control_guidance_start", 0.0))
    args["control_guidance_end"] = float(controlnet.get("control_guidance_end", 1.0))

    if args["model_name"] == "kandinsky-community/kandinsky-2-2-controlnet-depth":
        # kandinsky controlnet takes a depth "hint" instead of "image"
        from .pre_processors.depth_estimator import make_hint

        loop = asyncio.get_running_loop()
        args["hint"] = await loop.run_in_executor(None, make_hint, control_image)
    elif parameters.get("pipeline_type") in (
        "StableDiffusionControlNetPipeline",
        "StableDiffusionXLControlNetPipeline",
    ):
        args["image"] = control_image
    else:
        args["control_image"] = control_image


def _drop_unsupported(args: dict, parameters: dict) -> None:
    for arg in parameters.pop("unsupported_pipeline_arguments", []):
        args.pop(arg, None)


# identity / payload keys a hive-controlled parameters dict may FILL but
# never rewrite (pipeline_type/scheduler_type are popped explicitly by each
# formatter before the merge, so they never reach it)
_PROTECTED_ARGS = frozenset({
    "model_name", "prompt", "negative_prompt", "image", "mask_image",
    "control_image", "workflow", "id", "rng", "chipset",
})


def _merge_passthrough(args: dict, parameters: dict) -> None:
    """Passthrough with reference precedence — parameters win (model-pinned
    steps/scheduler knobs must override formatter defaults) — EXCEPT the
    protected identity keys, which parameters may fill but never rewrite.
    A formatter's neutral default (None/"", e.g. setdefault('prompt',''))
    counts as fillable, not as a value to protect."""
    for k, v in parameters.items():
        if k in _PROTECTED_ARGS and args.get(k) not in (None, ""):
            continue
        args[k] = v
