"""Persistent XLA compilation cache plumbing (one knob, two consumers).

A cold slice pays the full XLA trace+compile on its first pass of every
shape bucket (~12 s of the ~17.7 s tiny-smoke warmup on CPU, 369 s for
the SDXL flagship on a v5e chip — BENCH_r02/r05). The compiled
executables are deterministic per (HLO, backend), so JAX's persistent
compilation cache can carry the compile half across process restarts:
a rolling worker restart then pays only trace + cache deserialization.

`Settings.compile_cache_dir` / `CHIASWARM_COMPILE_CACHE_DIR` picks the
directory: a relative value resolves under `$SDAAS_ROOT` (default
`xla_cache` -> `$SDAAS_ROOT/xla_cache`), `~` expands, and an empty
value (or "0"/"off") disables the cache entirely — the disabled path
never imports jax or touches its config, so opting out is 0-cost. An
unwritable directory degrades to a warning + disabled cache, never a
worker failure (the cache is an optimization).

Consumers: worker.startup() (min_compile_time 1.0 s, so thousands of
trivial sub-programs don't spam the spool) and bench.py (the
warm-restart probe uses 0.0 so the whole tiny pipeline caches).
"""

from __future__ import annotations

import logging
import os
from pathlib import Path

logger = logging.getLogger(__name__)

_DISABLED_VALUES = {"", "0", "off", "none", "disabled"}


def resolve_cache_dir(settings=None) -> Path | None:
    """The configured cache directory, or None when disabled. Pure path
    logic — no filesystem writes, no jax."""
    if settings is None:
        from .settings import load_settings

        settings = load_settings()
    raw = str(getattr(settings, "compile_cache_dir", "") or "").strip()
    if raw.lower() in _DISABLED_VALUES:
        return None
    path = Path(os.path.expanduser(raw))
    if not path.is_absolute():
        from .settings import get_settings_dir

        path = get_settings_dir() / path
    return path


def enable_compile_cache(settings=None,
                         min_compile_time_s: float = 1.0) -> Path | None:
    """Point jax's persistent compilation cache at the configured
    directory. Returns the active path, or None when disabled or the
    directory can't be created/written (logged as a warning — the worker
    keeps serving, it just recompiles on restart)."""
    path = resolve_cache_dir(settings)
    if path is None:
        return None
    try:
        path.mkdir(parents=True, exist_ok=True)
        probe = path / ".write_probe"
        probe.write_text("ok")
        probe.unlink()
    except OSError as e:
        logger.warning(
            "compile cache dir %s is not writable (%s); persistent "
            "compilation cache disabled for this run", path, e)
        return None
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", str(path))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_s))
    except Exception as e:  # cache is an optimization, never fatal
        logger.warning("persistent compilation cache unavailable: %s", e)
        return None
    return path
