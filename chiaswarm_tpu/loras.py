"""LoRA reference resolution.

Parses the job's `lora` string into {lora, weight_name, subfolder} the way the
reference intends (swarm/loras.py:8-39) — including the ≥4-segment case that
the reference gets wrong (`parts[parts[2:-1]]` at swarm/loras.py:37 raises
TypeError; here deep subfolder paths are joined correctly).

Forms accepted:
  "name"                                  -> local file under lora_root_dir
  "publisher/repo"                        -> hub repo, default weights
  "publisher/repo/file.safetensors"       -> hub repo + weight file
  "publisher/repo/sub/dirs/file.st"       -> hub repo + nested subfolder + file
"""

from __future__ import annotations

import os
from dataclasses import dataclass


@dataclass(frozen=True)
class LoraRef:
    lora: str
    weight_name: str | None = None
    subfolder: str | None = None

    def as_dict(self) -> dict:
        return {
            "lora": self.lora,
            "weight_name": self.weight_name,
            "subfolder": self.subfolder,
        }


def resolve_lora(lora: str, lora_root_dir: str) -> dict:
    parts = [p for p in lora.split("/") if p]
    if len(parts) == 1:
        # bare local name under lora_root_dir
        return LoraRef(
            lora=os.path.expanduser(lora_root_dir), weight_name=parts[0]
        ).as_dict()
    if len(parts) == 2:
        return LoraRef(lora=f"{parts[0]}/{parts[1]}").as_dict()
    if len(parts) == 3:
        return LoraRef(lora=f"{parts[0]}/{parts[1]}", weight_name=parts[2]).as_dict()
    # publisher/repo/<subfolder...>/file
    return LoraRef(
        lora=f"{parts[0]}/{parts[1]}",
        weight_name=parts[-1],
        subfolder="/".join(parts[2:-1]),
    ).as_dict()


class Loras:
    """Reference-compatible wrapper (swarm/loras.py class shape)."""

    def __init__(self, lora_root_dir: str):
        self.lora_root_dir = lora_root_dir

    def resolve_lora(self, lora: str) -> dict:
        return resolve_lora(lora, self.lora_root_dir)
