"""Deterministic fault injection for the worker runtime.

The robustness layer (outbox redelivery, slice watchdog, graceful drain)
only earns trust if its failure paths run in CI, not just in outages.
This module is the switchboard: named injection points compiled into the
runtime that are free when disarmed (one dict lookup) and deterministic
when armed — no random fault roulette, a test or the chaos smoke harness
(tools/chaos_smoke.py) arms exactly the failure it wants, N times.

Spec grammar (``Settings.fault_injection`` / ``CHIASWARM_FAULTS``):

    "drop_submit=3,hang_denoise=1"

arms each named point for its first N hits. Special key ``hang_timeout``
(seconds, float) bounds how long a hang blocks when nobody calls
``release_hangs()``.

Injection points wired today (site -> effect):

- ``drop_submit``    hive._submit_once raises a connection error before
                     the POST leaves the worker (submit drop xN)
- ``hang_denoise``   ChipSet execution blocks under the slice busy lock
                     until ``release_hangs()`` / hang_timeout (hung
                     compile/denoise; exercises the watchdog)
- ``oom_batched``    ChipSet.run_batched raises RESOURCE_EXHAUSTED before
                     the coalesced pass runs (exercises the per-job
                     fallback)
- ``kill_before_ack`` worker result delivery raises FaultInjected AFTER
                     the hive ack, BEFORE the outbox unlink (simulated
                     crash; exercises redelivery-on-restart)
- ``hang_after_checkpoint`` the chunk-boundary checkpoint shipper blocks
                     right after handing a checkpoint upload to the
                     event loop — the worker 'dies' mid-denoise past a
                     durable checkpoint (exercises resume-on-redelivery,
                     ISSUE 18)
- ``kill_before_journal_sync`` (hive-side) the coordinator dies between
                     an in-memory state mutation and the WAL append —
                     the in-flight HTTP response errors and the journal
                     misses the transition; recovery must tolerate it
- ``crash_after_lease`` (hive-side) the coordinator dies after leasing +
                     journaling jobs on a /work poll but before the
                     reply leaves — the worker never sees the jobs, and
                     WAL replay + lease expiry must redeliver them
- ``drop_replication`` (hive-side) a standby's replication stream fetch
                     dies mid-flight (network partition / primary
                     mid-crash); the next sync must resume from the
                     same position without losing or doubling events

Sites call ``faults.fire(point)`` / ``faults.hang(point)`` by name;
unknown names simply never fire, so new points cost one line at the site.
"""

from __future__ import annotations

import logging
import os
import threading

logger = logging.getLogger(__name__)

DEFAULT_HANG_TIMEOUT_S = 600.0


class FaultInjected(Exception):
    """An armed injection point fired (the default exception when the
    site didn't supply a more realistic one)."""


class FaultPlan:
    """One parsed fault spec: armed counts per point, fired counts for
    assertions, and the shared hang latch. Thread-safe — sites fire from
    slice executor threads while the asyncio loop reads counters."""

    def __init__(self, spec: str = "",
                 hang_timeout_s: float = DEFAULT_HANG_TIMEOUT_S):
        self.hang_timeout_s = float(hang_timeout_s)
        self._lock = threading.Lock()
        self._armed: dict[str, int] = {}
        self._fired: dict[str, int] = {}
        self._release = threading.Event()
        self._hanging = 0
        for part in (spec or "").replace(";", ",").split(","):
            part = part.strip()
            if not part:
                continue
            point, _, count = part.partition("=")
            point = point.strip()
            try:
                value = float(count) if count else 1.0
            except ValueError:
                logger.warning("unparseable fault spec entry %r ignored", part)
                continue
            if point == "hang_timeout":
                self.hang_timeout_s = value
            else:
                self._armed[point] = int(value)

    # --- introspection (tests / chaos harness) ---

    def active(self, point: str) -> bool:
        with self._lock:
            return self._armed.get(point, 0) > 0

    def fired(self, point: str) -> int:
        with self._lock:
            return self._fired.get(point, 0)

    @property
    def hanging(self) -> int:
        """Threads currently blocked inside a hang point."""
        with self._lock:
            return self._hanging

    # --- injection sites ---

    def _consume(self, point: str) -> bool:
        with self._lock:
            if self._armed.get(point, 0) <= 0:
                return False
            self._armed[point] -= 1
            self._fired[point] = self._fired.get(point, 0) + 1
            return True

    def fire(self, point: str, exc: Exception | None = None) -> None:
        """Raise at an armed point (consuming one charge); no-op otherwise.

        `exc` lets the site raise the exception class its real failure
        would produce (e.g. an aiohttp connection error) so downstream
        classification paths run unmodified.
        """
        if not self._consume(point):
            return
        logger.warning("fault injected: %s", point)
        raise exc if exc is not None else FaultInjected(point)

    def hang(self, point: str) -> None:
        """Block the calling thread at an armed point until
        ``release_hangs()`` or hang_timeout; no-op otherwise."""
        if not self._consume(point):
            return
        logger.warning("fault injected: %s (hanging, timeout %.0fs)",
                       point, self.hang_timeout_s)
        with self._lock:
            self._hanging += 1
        try:
            self._release.wait(self.hang_timeout_s)
        finally:
            with self._lock:
                self._hanging -= 1

    def release_hangs(self) -> None:
        """Unblock every current and future hang point (the 'hang clears'
        half of a watchdog-recovery scenario)."""
        self._release.set()


_plan = FaultPlan(os.environ.get("CHIASWARM_FAULTS", ""))


def configure(spec: str = "",
              hang_timeout_s: float = DEFAULT_HANG_TIMEOUT_S) -> FaultPlan:
    """Install (and return) a fresh process-wide plan. ``configure("")``
    disarms everything — call it in test teardown."""
    global _plan
    # a replaced plan must not strand threads blocked in its hang points
    _plan.release_hangs()
    _plan = FaultPlan(spec, hang_timeout_s)
    return _plan


def get_plan() -> FaultPlan:
    return _plan


def active(point: str) -> bool:
    return _plan.active(point)


def fire(point: str, exc: Exception | None = None) -> None:
    _plan.fire(point, exc)


def hang(point: str) -> None:
    _plan.hang(point)
