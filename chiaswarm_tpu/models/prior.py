"""Diffusion prior transformer: text -> CLIP image embedding (Kandinsky 2.x).

Reference behavior replaced: KandinskyV22PriorPipeline loaded fresh per job
and run before the main pipeline (swarm/diffusion/pipeline_steps.py:7-38,
including the split-embeds mode where `prior_timesteps` rides the job). The
prior denoises in CLIP *embedding* space: a transformer over
[text tokens | text embed | timestep | noisy image embed | learned query]
predicts the clean image embedding each step.

This is an original flax formulation (the reference imported diffusers'
PriorTransformer); tiny configs exercise the same graph hermetically.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp

from .flux import timestep_embedding


@dataclasses.dataclass(frozen=True)
class PriorConfig:
    embed_dim: int = 1280  # CLIP image-embedding width (ViT-bigG)
    hidden_size: int = 2048
    num_layers: int = 10
    num_heads: int = 32
    text_seq: int = 77
    text_dim: int = 1280  # text-encoder hidden width


TINY_PRIOR = PriorConfig(
    embed_dim=32, hidden_size=64, num_layers=2, num_heads=4, text_seq=77,
    text_dim=32,
)


class PriorBlock(nn.Module):
    config: PriorConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        h = cfg.num_heads
        hd = cfg.hidden_size // h
        b, s, _ = x.shape
        y = nn.LayerNorm(dtype=self.dtype, name="norm1")(x)
        qkv = nn.Dense(3 * cfg.hidden_size, dtype=self.dtype, name="qkv")(y)
        q, k, v = jnp.split(qkv.reshape(b, s, 3, h, hd), 3, axis=2)
        q, k, v = (t[:, :, 0] for t in (q, k, v))
        from ..ops import dot_product_attention

        attn = dot_product_attention(q, k, v).reshape(b, s, cfg.hidden_size)
        x = x + nn.Dense(cfg.hidden_size, dtype=self.dtype, name="proj")(attn)
        y = nn.LayerNorm(dtype=self.dtype, name="norm2")(x)
        y = nn.Dense(4 * cfg.hidden_size, dtype=self.dtype, name="fc1")(y)
        y = nn.gelu(y, approximate=True)
        return x + nn.Dense(cfg.hidden_size, dtype=self.dtype, name="fc2")(y)


class DiffusionPrior(nn.Module):
    config: PriorConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, noisy_embed, timesteps, text_hiddens, text_embed):
        """noisy_embed [B, E], timesteps [B], text_hiddens [B, S, Dt],
        text_embed [B, Dt] -> predicted clean image embed [B, E]."""
        cfg = self.config
        b = noisy_embed.shape[0]
        tokens = [
            nn.Dense(cfg.hidden_size, dtype=self.dtype, name="text_proj")(
                text_hiddens.astype(self.dtype)
            ),
            nn.Dense(cfg.hidden_size, dtype=self.dtype, name="embed_proj")(
                text_embed.astype(self.dtype)
            )[:, None],
            nn.Dense(cfg.hidden_size, dtype=self.dtype, name="time_proj")(
                timestep_embedding(timesteps, 256, time_factor=1.0).astype(
                    self.dtype
                )
            )[:, None],
            nn.Dense(cfg.hidden_size, dtype=self.dtype, name="sample_proj")(
                noisy_embed.astype(self.dtype)
            )[:, None],
            jnp.broadcast_to(
                self.param(
                    "query_embedding", nn.initializers.normal(0.02),
                    (1, 1, cfg.hidden_size),
                ).astype(self.dtype),
                (b, 1, cfg.hidden_size),
            ),
        ]
        x = jnp.concatenate(tokens, axis=1)
        pos = self.param(
            "positional_embedding", nn.initializers.normal(0.02),
            (1, cfg.text_seq + 4, cfg.hidden_size),
        ).astype(self.dtype)
        x = x + pos
        for i in range(cfg.num_layers):
            x = PriorBlock(cfg, dtype=self.dtype, name=f"blocks_{i}")(x)
        x = nn.LayerNorm(dtype=self.dtype, name="norm_out")(x)
        # the learned query token carries the prediction
        return nn.Dense(cfg.embed_dim, dtype=self.dtype, name="to_embed")(
            x[:, -1]
        )
