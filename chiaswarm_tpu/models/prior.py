"""Diffusion prior transformer: text -> CLIP image embedding (Kandinsky 2.x).

Reference behavior replaced: KandinskyV22PriorPipeline loaded fresh per job
and run before the main pipeline (swarm/diffusion/pipeline_steps.py:7-38,
including the split-embeds mode where `prior_timesteps` rides the job). The
prior denoises in CLIP *embedding* space: a transformer over
[text tokens | text embed | timestep | noisy image embed | learned query]
predicts the clean image embedding each step.

The graph matches diffusers' `PriorTransformer` (the module the K2.2 prior
checkpoint ships) parameter-for-parameter so conversion is mechanical:
sinusoidal time features at the INNER width -> 2-layer MLP, per-input
projections, learned positional + prd embeddings, pre-LN blocks with
biased qkv and exact-gelu FF, final LayerNorm + projection read from the
last (prd) token. When `attention_mask` is provided the blocks run CAUSAL
attention with padded text masked — PriorTransformer's behavior whenever
the pipeline passes the text mask.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp

from .layers import TimestepEmbedding, timestep_embedding


@dataclasses.dataclass(frozen=True)
class PriorConfig:
    embed_dim: int = 1280  # CLIP image-embedding width (ViT-bigG)
    num_heads: int = 32
    head_dim: int = 64  # inner width = heads * head_dim = 2048
    num_layers: int = 20  # kandinsky-2-2-prior geometry
    text_seq: int = 77
    text_dim: int = 1280  # text-encoder hidden width
    additional_tokens: int = 4  # [text embed, time, sample, prd]

    @property
    def hidden_size(self) -> int:
        return self.num_heads * self.head_dim


TINY_PRIOR = PriorConfig(
    embed_dim=32, num_heads=4, head_dim=16, num_layers=2, text_seq=77,
    text_dim=32,
)


class PriorBlock(nn.Module):
    """Pre-LN transformer block matching PriorTransformer's
    BasicTransformerBlock(attention_bias=True, activation_fn='gelu'):
    norm1 -> biased multihead self-attention -> norm3 -> exact-gelu FF."""

    config: PriorConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, mask=None):
        cfg = self.config
        h, hd = cfg.num_heads, cfg.head_dim
        inner = cfg.hidden_size
        b, s, _ = x.shape
        y = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="norm1")(x)
        proj = lambda name: nn.Dense(inner, dtype=self.dtype, name=name)(
            y
        ).reshape(b, s, h, hd)
        q, k, v = proj("to_q"), proj("to_k"), proj("to_v")
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * hd**-0.5
        if mask is not None:
            logits = logits + mask
        w = nn.softmax(logits.astype(jnp.float32), axis=-1).astype(self.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", w, v).reshape(b, s, inner)
        x = x + nn.Dense(inner, dtype=self.dtype, name="to_out_0")(attn)
        y = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="norm3")(x)
        y = nn.Dense(4 * inner, dtype=self.dtype, name="ff_proj")(y)
        y = nn.gelu(y, approximate=False)
        return x + nn.Dense(inner, dtype=self.dtype, name="ff_out")(y)


class DiffusionPrior(nn.Module):
    config: PriorConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, noisy_embed, timesteps, text_hiddens, text_embed,
                 attention_mask=None):
        """noisy_embed [B, E], timesteps [B], text_hiddens [B, S, Dt],
        text_embed [B, Dt], attention_mask [B, S] keep-mask or None ->
        predicted clean image embed [B, E]."""
        cfg = self.config
        inner = cfg.hidden_size
        b = noisy_embed.shape[0]
        t_feat = timestep_embedding(
            timesteps, inner, dtype=self.dtype
        )
        time_tok = TimestepEmbedding(inner, dtype=self.dtype,
                                     name="time_embedding")(t_feat)
        tokens = [
            nn.Dense(inner, dtype=self.dtype,
                     name="encoder_hidden_states_proj")(
                text_hiddens.astype(self.dtype)
            ),
            nn.Dense(inner, dtype=self.dtype, name="embed_proj")(
                text_embed.astype(self.dtype)
            )[:, None],
            time_tok[:, None],
            nn.Dense(inner, dtype=self.dtype, name="proj_in")(
                noisy_embed.astype(self.dtype)
            )[:, None],
            jnp.broadcast_to(
                self.param(
                    "prd_embedding", nn.initializers.normal(0.02),
                    (1, 1, inner),
                ).astype(self.dtype),
                (b, 1, inner),
            ),
        ]
        x = jnp.concatenate(tokens, axis=1)
        seq = cfg.text_seq + cfg.additional_tokens
        pos = self.param(
            "positional_embedding", nn.initializers.normal(0.02),
            (1, seq, inner),
        ).astype(self.dtype)
        x = x + pos

        mask = None
        if attention_mask is not None:
            # PriorTransformer: pad mask over the text tokens (additional
            # tokens always attended) PLUS a causal triangle
            pad = (1.0 - attention_mask.astype(jnp.float32)) * -1e4
            pad = jnp.pad(pad, ((0, 0), (0, cfg.additional_tokens)))
            causal = jnp.triu(jnp.full((seq, seq), -1e4, jnp.float32), k=1)
            mask = (pad[:, None, :] + causal[None]).astype(self.dtype)[
                :, None, :, :
            ]

        for i in range(cfg.num_layers):
            x = PriorBlock(cfg, dtype=self.dtype,
                           name=f"transformer_blocks_{i}")(x, mask)
        x = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="norm_out")(x)
        # the learned prd token carries the prediction
        return nn.Dense(cfg.embed_dim, dtype=self.dtype,
                        name="proj_to_clip_embeddings")(x[:, -1])
