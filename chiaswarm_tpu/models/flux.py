"""Flux MMDiT transformer, flax.linen — the rectified-flow flagship family.

Reference context: Flux dev/schnell jobs ride `FluxPipeline` wire names
with bf16 + sequential CPU offload on CUDA (reference swarm/test.py:
244-290, swarm/job_arguments.py large-model branches). TPU rebuild: the
whole transformer is one XLA program — no offload; memory scaling comes
from sharding (parallel/tensor.py) instead.

Architecture (Black Forest Labs Flux):
- 2x2-patchified 16-channel latents -> `img_in` linear; T5 context ->
  `txt_in` linear; sinusoidal timestep (+ guidance for dev) and CLIP
  pooled vector feed MLPs summed into the modulation vector `vec`.
- `depth_double` double-stream blocks: separate img/txt streams, each with
  adaLN modulation from `vec`, joint attention over the concatenated
  token sequence, per-head RMS qk-norm, 3D RoPE (text ids zero, image ids
  (y, x)).
- `depth_single` single-stream blocks over the fused sequence: one fused
  linear producing qkv + MLP-in, attention + gelu-MLP combined, one
  output linear.
- final adaLN + linear back to patch channels.

Module names follow the BFL checkpoint graph (double_blocks.N.img_attn.*)
so conversion is mechanical (models/conversion.py convert_flux).
"""

from __future__ import annotations

import dataclasses
import math

import flax.linen as nn
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class FluxConfig:
    in_channels: int = 64  # 16 latent channels x 2x2 patch
    hidden_size: int = 3072
    num_heads: int = 24
    depth_double: int = 19
    depth_single: int = 38
    mlp_ratio: float = 4.0
    context_dim: int = 4096  # T5-XXL d_model
    pooled_dim: int = 768  # CLIP-L pooled
    guidance_embed: bool = True  # flux-dev distilled guidance; schnell: False
    axes_dims_rope: tuple[int, ...] = (16, 56, 56)
    theta: int = 10_000

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


TINY_FLUX = FluxConfig(
    in_channels=16,  # 4 latent channels x 2x2 patch (tiny VAE)
    hidden_size=32,
    num_heads=2,
    depth_double=1,
    depth_single=1,
    context_dim=32,
    pooled_dim=32,
    guidance_embed=True,
    axes_dims_rope=(4, 6, 6),
)


def timestep_embedding(t, dim: int, max_period: float = 10_000.0,
                       time_factor: float = 1000.0):
    """Sinusoidal features of (scaled) flow time t in [0, 1] -> [B, dim]."""
    t = t * time_factor
    half = dim // 2
    freqs = jnp.exp(
        -math.log(max_period) * jnp.arange(half, dtype=jnp.float32) / half
    )
    args = t[:, None].astype(jnp.float32) * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


def rope_frequencies(ids, axes_dims: tuple[int, ...], theta: int):
    """[B, S, n_axes] integer positions -> complex-as-pair rotations
    [B, S, head_dim/2, 2] laid out axis-by-axis (Flux 3D RoPE)."""
    components = []
    for axis, dim in enumerate(axes_dims):
        pos = ids[..., axis].astype(jnp.float32)  # [B, S]
        scale = jnp.arange(0, dim, 2, dtype=jnp.float32) / dim
        omega = 1.0 / (theta**scale)  # [dim/2]
        angles = pos[..., None] * omega  # [B, S, dim/2]
        components.append(angles)
    angles = jnp.concatenate(components, axis=-1)  # [B, S, head_dim/2]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x, cos, sin):
    """x [B, S, H, D] with rotation pairs on the last dim."""
    x1 = x[..., 0::2]
    x2 = x[..., 1::2]
    cos = cos[:, :, None, :]
    sin = sin[:, :, None, :]
    out1 = x1 * cos - x2 * sin
    out2 = x1 * sin + x2 * cos
    return jnp.stack([out1, out2], axis=-1).reshape(x.shape)


class QKNorm(nn.Module):
    """Per-head RMS normalization of q and k (Flux stabilization)."""

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, q, k):
        def rms(x, name):
            scale = self.param(name, nn.initializers.ones, (x.shape[-1],))
            var = jnp.mean(
                jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True
            )
            return ((x * (var + 1e-6) ** -0.5) * scale).astype(self.dtype)

        return rms(q, "query_scale"), rms(k, "key_scale")


class MLPEmbedder(nn.Module):
    hidden: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(self.hidden, dtype=self.dtype, name="in_layer")(x)
        x = nn.silu(x)
        return nn.Dense(self.hidden, dtype=self.dtype, name="out_layer")(x)


class Modulation(nn.Module):
    """vec -> (shift, scale, gate) x n chunks."""

    hidden: int
    n: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, vec):
        out = nn.Dense(self.n * self.hidden, dtype=self.dtype, name="lin")(
            nn.silu(vec)
        )
        return jnp.split(out[:, None, :], self.n, axis=-1)


def _attention(q, k, v, cos, sin):
    """Joint attention with RoPE; [B, S, H, D] -> [B, S, H*D]."""
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    from ..ops import dot_product_attention

    out = dot_product_attention(q, k, v)
    b, s, h, d = out.shape
    return out.reshape(b, s, h * d)


class DoubleStreamBlock(nn.Module):
    config: FluxConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, img, txt, vec, cos, sin):
        cfg = self.config
        h, hd = cfg.num_heads, cfg.head_dim
        mlp_dim = int(cfg.hidden_size * cfg.mlp_ratio)

        def stream(name):
            mod = Modulation(cfg.hidden_size, 6, dtype=self.dtype,
                             name=f"{name}_mod")
            return mod

        img_mod = stream("img")(vec)
        txt_mod = stream("txt")(vec)

        def norm(x):
            return nn.LayerNorm(
                use_bias=False, use_scale=False, epsilon=1e-6, dtype=self.dtype
            )(x)

        def qkv(x, name):
            b, s, _ = x.shape
            out = nn.Dense(3 * h * hd, dtype=self.dtype, name=f"{name}_attn_qkv")(x)
            q, k, v = jnp.split(out.reshape(b, s, 3, h, hd), 3, axis=2)
            q, k, v = (t[:, :, 0] for t in (q, k, v))
            q, k = QKNorm(dtype=self.dtype, name=f"{name}_attn_norm")(q, k)
            return q, k, v

        # modulated pre-norm + qkv per stream
        img_n = norm(img) * (1 + img_mod[1]) + img_mod[0]
        txt_n = norm(txt) * (1 + txt_mod[1]) + txt_mod[0]
        iq, ik, iv = qkv(img_n, "img")
        tq, tk, tv = qkv(txt_n, "txt")

        # joint attention: text tokens first (matches ids layout)
        q = jnp.concatenate([tq, iq], axis=1)
        k = jnp.concatenate([tk, ik], axis=1)
        v = jnp.concatenate([tv, iv], axis=1)
        attn = _attention(q, k, v, cos, sin)
        txt_len = txt.shape[1]
        txt_attn, img_attn = attn[:, :txt_len], attn[:, txt_len:]

        img = img + img_mod[2] * nn.Dense(
            cfg.hidden_size, dtype=self.dtype, name="img_attn_proj"
        )(img_attn)
        txt = txt + txt_mod[2] * nn.Dense(
            cfg.hidden_size, dtype=self.dtype, name="txt_attn_proj"
        )(txt_attn)

        def mlp(x, mod_shift, mod_scale, mod_gate, name):
            y = norm(x) * (1 + mod_scale) + mod_shift
            y = nn.Dense(mlp_dim, dtype=self.dtype, name=f"{name}_mlp_0")(y)
            y = nn.gelu(y, approximate=True)
            y = nn.Dense(cfg.hidden_size, dtype=self.dtype, name=f"{name}_mlp_2")(y)
            return x + mod_gate * y

        img = mlp(img, img_mod[3], img_mod[4], img_mod[5], "img")
        txt = mlp(txt, txt_mod[3], txt_mod[4], txt_mod[5], "txt")
        return img, txt


class SingleStreamBlock(nn.Module):
    config: FluxConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, vec, cos, sin):
        cfg = self.config
        h, hd = cfg.num_heads, cfg.head_dim
        mlp_dim = int(cfg.hidden_size * cfg.mlp_ratio)
        shift, scale, gate = Modulation(
            cfg.hidden_size, 3, dtype=self.dtype, name="modulation"
        )(vec)
        y = nn.LayerNorm(
            use_bias=False, use_scale=False, epsilon=1e-6, dtype=self.dtype
        )(x)
        y = y * (1 + scale) + shift
        b, s, _ = y.shape
        fused = nn.Dense(
            3 * h * hd + mlp_dim, dtype=self.dtype, name="linear1"
        )(y)
        qkv_part, mlp_part = jnp.split(fused, [3 * h * hd], axis=-1)
        q, k, v = jnp.split(qkv_part.reshape(b, s, 3, h, hd), 3, axis=2)
        q, k, v = (t[:, :, 0] for t in (q, k, v))
        q, k = QKNorm(dtype=self.dtype, name="norm")(q, k)
        attn = _attention(q, k, v, cos, sin)
        out = nn.Dense(cfg.hidden_size, dtype=self.dtype, name="linear2")(
            jnp.concatenate([attn, nn.gelu(mlp_part, approximate=True)], axis=-1)
        )
        return x + gate * out


class FluxTransformer(nn.Module):
    config: FluxConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, img, img_ids, txt, txt_ids, timesteps, pooled,
                 guidance=None):
        """img [B, S_img, in_channels] patchified latents; txt [B, S_txt,
        context_dim]; ids [B, S, 3]; -> [B, S_img, in_channels]."""
        cfg = self.config
        img = nn.Dense(cfg.hidden_size, dtype=self.dtype, name="img_in")(img)
        txt = nn.Dense(cfg.hidden_size, dtype=self.dtype, name="txt_in")(txt)

        vec = MLPEmbedder(cfg.hidden_size, dtype=self.dtype, name="time_in")(
            timestep_embedding(timesteps, 256).astype(self.dtype)
        )
        if cfg.guidance_embed:
            g = guidance if guidance is not None else jnp.ones_like(timesteps)
            vec = vec + MLPEmbedder(
                cfg.hidden_size, dtype=self.dtype, name="guidance_in"
            )(timestep_embedding(g, 256).astype(self.dtype))
        vec = vec + MLPEmbedder(
            cfg.hidden_size, dtype=self.dtype, name="vector_in"
        )(pooled.astype(self.dtype))

        ids = jnp.concatenate([txt_ids, img_ids], axis=1)
        cos, sin = rope_frequencies(ids, cfg.axes_dims_rope, cfg.theta)
        cos = cos.astype(self.dtype)
        sin = sin.astype(self.dtype)

        for i in range(cfg.depth_double):
            img, txt = DoubleStreamBlock(
                cfg, dtype=self.dtype, name=f"double_blocks_{i}"
            )(img, txt, vec, cos, sin)

        x = jnp.concatenate([txt, img], axis=1)
        for i in range(cfg.depth_single):
            x = SingleStreamBlock(
                cfg, dtype=self.dtype, name=f"single_blocks_{i}"
            )(x, vec, cos, sin)
        x = x[:, txt.shape[1]:]

        shift, scale = jnp.split(
            nn.Dense(2 * cfg.hidden_size, dtype=self.dtype,
                     name="final_layer_mod")(nn.silu(vec))[:, None, :],
            2, axis=-1,
        )
        x = nn.LayerNorm(
            use_bias=False, use_scale=False, epsilon=1e-6, dtype=self.dtype
        )(x)
        x = x * (1 + scale) + shift
        return nn.Dense(
            cfg.in_channels, dtype=self.dtype, name="final_layer_linear"
        )(x)


class FluxHead(nn.Module):
    """The pre-block section of FluxTransformer as a standalone module.

    Param names (img_in/txt_in/time_in/guidance_in/vector_in) match the
    monolith exactly, so the weight-streaming runner applies it against
    the SAME converted tree (a subset of params['flux']) — parity between
    the streamed and resident paths is asserted in tests/test_flux_stream.
    """

    config: FluxConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, img, txt, timesteps, pooled, guidance=None):
        cfg = self.config
        img = nn.Dense(cfg.hidden_size, dtype=self.dtype, name="img_in")(img)
        txt = nn.Dense(cfg.hidden_size, dtype=self.dtype, name="txt_in")(txt)
        vec = MLPEmbedder(cfg.hidden_size, dtype=self.dtype, name="time_in")(
            timestep_embedding(timesteps, 256).astype(self.dtype)
        )
        if cfg.guidance_embed:
            g = guidance if guidance is not None else jnp.ones_like(timesteps)
            vec = vec + MLPEmbedder(
                cfg.hidden_size, dtype=self.dtype, name="guidance_in"
            )(timestep_embedding(g, 256).astype(self.dtype))
        vec = vec + MLPEmbedder(
            cfg.hidden_size, dtype=self.dtype, name="vector_in"
        )(pooled.astype(self.dtype))
        return img, txt, vec


class FluxFinal(nn.Module):
    """The post-block section of FluxTransformer (modulated output proj),
    standalone for the streaming runner; names match the monolith."""

    config: FluxConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, vec):
        cfg = self.config
        shift, scale = jnp.split(
            nn.Dense(2 * cfg.hidden_size, dtype=self.dtype,
                     name="final_layer_mod")(nn.silu(vec))[:, None, :],
            2, axis=-1,
        )
        x = nn.LayerNorm(
            use_bias=False, use_scale=False, epsilon=1e-6, dtype=self.dtype
        )(x)
        x = x * (1 + scale) + shift
        return nn.Dense(
            cfg.in_channels, dtype=self.dtype, name="final_layer_linear"
        )(x)


# params['flux'] keys consumed by FluxHead / FluxFinal (the rest are the
# double_blocks_i / single_blocks_i trees the streaming runner pages in)
HEAD_KEYS = ("img_in", "txt_in", "time_in", "guidance_in", "vector_in")
FINAL_KEYS = ("final_layer_mod", "final_layer_linear")


def patchify(latents):
    """[B, H, W, C] -> ([B, H/2*W/2, 4C], ids [B, S, 3])."""
    b, h, w, c = latents.shape
    x = latents.reshape(b, h // 2, 2, w // 2, 2, c)
    x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, (h // 2) * (w // 2), 4 * c)
    ys, xs = jnp.meshgrid(
        jnp.arange(h // 2), jnp.arange(w // 2), indexing="ij"
    )
    ids = jnp.stack(
        [jnp.zeros_like(ys), ys, xs], axis=-1
    ).reshape(1, -1, 3)
    return x, jnp.broadcast_to(ids, (b, ids.shape[1], 3)).astype(jnp.int32)


def unpatchify(x, h: int, w: int):
    """[B, H/2*W/2, 4C] -> [B, H, W, C]."""
    b, s, c4 = x.shape
    c = c4 // 4
    x = x.reshape(b, h // 2, w // 2, 2, 2, c)
    x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, h, w, c)
    return x
