"""DPT monocular depth estimator (ViT backbone + reassemble/fusion head).

Reference behavior replaced: swarm/pre_processors/controlnet.py:94-119 runs
transformers' DPT pipeline on CUDA for the `depth` preprocessor, and
swarm/pre_processors/depth_estimator.py:8-24 feeds Kandinsky's depth hint.
TPU rebuild: one flax module, jitted end-to-end; module naming tracks the
HF DPTForDepthEstimation graph so conversion (convert_dpt) is mechanical.

Structure (DPT-Large geometry by default):
- ViT backbone (pre-LN), features tapped at 4 intermediate layers;
- reassemble: readout-projected tokens -> spatial maps at /4, /8, /16, /32
  of the input resolution (convtranspose / identity / strided conv);
- RefineNet-style fusion: deepest-first residual conv units, 2x upsample
  per stage; 3-conv head -> one depth channel.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class DPTConfig:
    image_size: int = 384
    patch_size: int = 16
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    taps: tuple[int, ...] = (5, 11, 17, 23)  # tapped encoder layers
    reassemble_channels: tuple[int, ...] = (256, 512, 1024, 1024)
    fusion_dim: int = 256
    head_dim: int = 32


# patch 16 is load-bearing: the reassemble factors (4x, 2x, 1x, 0.5x) are
# tuned for a /16 token grid so the fused map lands at /2 of the input
TINY_DPT = DPTConfig(
    image_size=64, patch_size=16, hidden_size=32, num_layers=4, num_heads=4,
    taps=(0, 1, 2, 3), reassemble_channels=(16, 24, 32, 32), fusion_dim=16,
    head_dim=8,
)


class _ViTBlock(nn.Module):
    hidden: int
    heads: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, s, _ = x.shape
        hd = self.hidden // self.heads
        y = nn.LayerNorm(dtype=self.dtype, name="ln1")(x)
        q = nn.Dense(self.hidden, dtype=self.dtype, name="q")(y)
        k = nn.Dense(self.hidden, dtype=self.dtype, name="k")(y)
        v = nn.Dense(self.hidden, dtype=self.dtype, name="v")(y)
        q, k, v = (t.reshape(b, s, self.heads, hd) for t in (q, k, v))
        from ..ops import dot_product_attention

        attn = dot_product_attention(q, k, v).reshape(b, s, self.hidden)
        x = x + nn.Dense(self.hidden, dtype=self.dtype, name="out")(attn)
        y = nn.LayerNorm(dtype=self.dtype, name="ln2")(x)
        y = nn.Dense(4 * self.hidden, dtype=self.dtype, name="fc1")(y)
        y = nn.gelu(y, approximate=False)
        return x + nn.Dense(self.hidden, dtype=self.dtype, name="fc2")(y)


class _ResidualConvUnit(nn.Module):
    channels: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        y = nn.relu(x)
        y = nn.Conv(self.channels, (3, 3), padding=((1, 1), (1, 1)),
                    dtype=self.dtype, name="conv1")(y)
        y = nn.relu(y)
        y = nn.Conv(self.channels, (3, 3), padding=((1, 1), (1, 1)),
                    dtype=self.dtype, name="conv2")(y)
        return x + y


def _resize2x(x):
    b, h, w, c = x.shape
    return jax.image.resize(x, (b, 2 * h, 2 * w, c), "bilinear")


class DPTDepthModel(nn.Module):
    config: DPTConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, pixels):
        """pixels [B, H, W, 3] normalized -> inverse depth [B, H, W]."""
        cfg = self.config
        x = nn.Conv(
            cfg.hidden_size, (cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size), dtype=self.dtype,
            name="patch_embed",
        )(pixels)
        b, gh, gw, _ = x.shape
        x = x.reshape(b, gh * gw, cfg.hidden_size)
        cls = self.param(
            "cls_token", nn.initializers.zeros, (1, 1, cfg.hidden_size)
        ).astype(self.dtype)
        x = jnp.concatenate([jnp.broadcast_to(cls, (b, 1, cfg.hidden_size)), x],
                            axis=1)
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02),
            (1, gh * gw + 1, cfg.hidden_size),
        ).astype(self.dtype)
        x = x + pos

        taps = {}
        for i in range(cfg.num_layers):
            x = _ViTBlock(cfg.hidden_size, cfg.num_heads, dtype=self.dtype,
                          name=f"layer_{i}")(x)
            if i in cfg.taps:
                taps[i] = x

        features = []
        for k, layer_idx in enumerate(cfg.taps):
            t = taps[layer_idx]
            tokens, cls_tok = t[:, 1:], t[:, :1]
            # readout "project": concat cls onto every token, project back
            readout = jnp.concatenate(
                [tokens, jnp.broadcast_to(cls_tok, tokens.shape)], axis=-1
            )
            tokens = nn.gelu(
                nn.Dense(cfg.hidden_size, dtype=self.dtype,
                         name=f"reassemble_{k}_readout")(readout),
                approximate=False,
            )
            fmap = tokens.reshape(b, gh, gw, cfg.hidden_size)
            ch = cfg.reassemble_channels[k]
            fmap = nn.Conv(ch, (1, 1), dtype=self.dtype,
                           name=f"reassemble_{k}_project")(fmap)
            if k == 0:  # /16 -> /4
                fmap = nn.ConvTranspose(
                    ch, (4, 4), strides=(4, 4), dtype=self.dtype,
                    name="reassemble_0_resize",
                )(fmap)
            elif k == 1:  # /16 -> /8
                fmap = nn.ConvTranspose(
                    ch, (2, 2), strides=(2, 2), dtype=self.dtype,
                    name="reassemble_1_resize",
                )(fmap)
            elif k == 3:  # /16 -> /32
                fmap = nn.Conv(
                    ch, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)),
                    dtype=self.dtype, name="reassemble_3_resize",
                )(fmap)
            fmap = nn.Conv(
                cfg.fusion_dim, (3, 3), padding=((1, 1), (1, 1)),
                use_bias=False, dtype=self.dtype, name=f"conv_{k}",
            )(fmap)
            features.append(fmap)

        # RefineNet fusion, deepest first: residual_layer1 transforms the
        # LATERAL feature joining the fused stream (HF DPTFeatureFusionLayer:
        # fused = fused + rcu1(lateral); rcu2 on the sum). The 2x upsample
        # here is half-pixel bilinear vs HF's align_corners=True — a
        # boundary-pixel-level divergence only.
        fused = None
        for k in reversed(range(len(features))):
            lateral = features[k]
            if fused is None:
                hidden = lateral
            else:
                hidden = fused + _ResidualConvUnit(
                    cfg.fusion_dim, dtype=self.dtype, name=f"fusion_{k}_rcu1"
                )(lateral)
            hidden = _ResidualConvUnit(
                cfg.fusion_dim, dtype=self.dtype, name=f"fusion_{k}_rcu2"
            )(hidden)
            hidden = _resize2x(hidden)
            fused = nn.Conv(
                cfg.fusion_dim, (1, 1), dtype=self.dtype,
                name=f"fusion_{k}_project",
            )(hidden)

        y = nn.Conv(cfg.fusion_dim // 2, (3, 3), padding=((1, 1), (1, 1)),
                    dtype=self.dtype, name="head_conv1")(fused)
        y = _resize2x(y)
        y = nn.Conv(cfg.head_dim, (3, 3), padding=((1, 1), (1, 1)),
                    dtype=self.dtype, name="head_conv2")(y)
        y = nn.relu(y)
        y = nn.Conv(1, (1, 1), dtype=self.dtype, name="head_conv3")(y)
        y = nn.relu(y)
        return y[..., 0]
