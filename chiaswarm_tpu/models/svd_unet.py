"""Stable Video Diffusion UNet (UNetSpatioTemporalConditionModel) — the
TRUE architecture, NHWC flax.

The reference serves img2vid with this model via
`StableVideoDiffusionPipeline.from_pretrained`
(/root/reference/swarm/video/img2vid.py:16-31). Structure per the diffusers
graph so checkpoints convert mechanically:

- every resnet is a SpatioTemporalResBlock: a spatial ResnetBlock2D
  followed by a temporal ResnetBlock (3D convs over (frame,1,1) windows),
  blended by a learned AlphaBlender mix factor;
- every attention stage is a TransformerSpatioTemporalModel: a spatial
  BasicTransformerBlock (cross-attending the 1-token CLIP image embed)
  paired with a TemporalBasicTransformerBlock that attends across frames
  per spatial position (with its own GEGLU `ff_in` and a sinusoidal
  frame-position embedding), blended by another AlphaBlender;
- micro-conditioning: (fps, motion_bucket_id, noise_aug_strength) each get
  a 256-d fourier embedding -> `add_embedding` MLP summed into the time
  embedding (SDXL-style).

The video batch is laid out [B*F, H, W, C] with a STATIC num_frames so the
whole denoise scan jits once per (frames, size) bucket; frame-axis
reshapes are free layout changes under XLA.

Conversion: conversion.py::convert_svd_unet / infer_svd_unet_config;
parity vs an exact-key torch mirror in tests/test_svd_conversion.py.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp

from .layers import (
    BasicTransformerBlock,
    Downsample2D,
    FusedGroupNorm,
    TimestepEmbedding,
    Upsample2D,
    timestep_embedding,
)


@dataclasses.dataclass(frozen=True)
class SVDUNetConfig:
    in_channels: int = 8  # 4 noise + 4 conditioning-frame latents
    out_channels: int = 4
    block_out_channels: tuple[int, ...] = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    # per level: spatio-temporal transformer stages present
    attention: tuple[bool, ...] = (True, True, True, False)
    num_attention_heads: tuple[int, ...] = (5, 10, 20, 20)
    cross_attention_dim: int = 1024
    transformer_layers_per_block: int = 1
    addition_time_embed_dim: int = 256
    projection_class_embeddings_input_dim: int = 768  # 3 ids x 256


TINY_SVD_UNET = SVDUNetConfig(
    in_channels=8,
    out_channels=4,
    block_out_channels=(32, 64),  # GroupNorm(32) floors the tiny width
    layers_per_block=1,
    attention=(True, False),
    num_attention_heads=(4, 4),
    cross_attention_dim=24,
    addition_time_embed_dim=8,
    projection_class_embeddings_input_dim=24,
)


class AlphaBlender(nn.Module):
    """Learned spatial/temporal mix: alpha = sigmoid(mix_factor); frames
    flagged image-only take the spatial branch outright."""

    switch_spatial_to_temporal_mix: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x_spatial, x_temporal, image_only_indicator=None):
        mix = self.param("mix_factor", nn.initializers.constant(0.5), (1,))
        alpha = nn.sigmoid(mix.astype(jnp.float32))[0]
        if image_only_indicator is not None:
            # [B, F] bool -> broadcast over the trailing feature axes
            flags = image_only_indicator.astype(bool)
            while flags.ndim < x_spatial.ndim:
                flags = flags[..., None]
            alpha = jnp.where(flags, 1.0, alpha)
        alpha = jnp.asarray(alpha, x_spatial.dtype)
        if self.switch_spatial_to_temporal_mix:
            alpha = 1.0 - alpha
        return alpha * x_spatial + (1.0 - alpha) * x_temporal


class TemporalResnetBlock(nn.Module):
    """ResNet over the frame axis: 3D convs with (3,1,1) kernels on
    [B, F, H, W, C]."""

    out_channels: int
    eps: float = 1e-6
    has_temb: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, temb=None):
        residual = x
        h = FusedGroupNorm(32, epsilon=self.eps, dtype=self.dtype,
                           act="silu", name="norm1")(x)
        h = nn.Conv(
            self.out_channels,
            (3, 1, 1),
            padding=((1, 1), (0, 0), (0, 0)),
            dtype=self.dtype,
            name="conv1",
        )(h)
        if self.has_temb and temb is not None:
            # temb [B, F, C_t] -> per-frame shift
            proj = nn.Dense(
                self.out_channels, dtype=self.dtype, name="time_emb_proj"
            )(nn.silu(temb))
            h = h + proj[:, :, None, None, :]
        h = FusedGroupNorm(32, epsilon=self.eps, dtype=self.dtype,
                           act="silu", name="norm2")(h)
        h = nn.Conv(
            self.out_channels,
            (3, 1, 1),
            padding=((1, 1), (0, 0), (0, 0)),
            dtype=self.dtype,
            name="conv2",
        )(h)
        if residual.shape[-1] != self.out_channels:
            residual = nn.Conv(
                self.out_channels, (1, 1, 1), dtype=self.dtype,
                name="conv_shortcut",
            )(residual)
        return h + residual


class SpatioTemporalResBlock(nn.Module):
    """Spatial ResnetBlock2D + TemporalResnetBlock + AlphaBlender.

    Submodule names mirror the diffusers keys (spatial_res_block /
    temporal_res_block / time_mixer)."""

    out_channels: int
    eps: float = 1e-5
    temporal_eps: float | None = None
    has_temb: bool = True
    switch_spatial_to_temporal_mix: bool = False
    # "learned_with_images" (UNet) respects image_only_indicator;
    # "learned" (temporal VAE decoder) is a pure sigmoid blend
    merge_strategy: str = "learned_with_images"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, temb, num_frames: int, image_only_indicator=None):
        from .layers import ResnetBlock2D

        h = ResnetBlock2D(
            self.out_channels, eps=self.eps, dtype=self.dtype,
            name="spatial_res_block",
        )(x, temb)
        bf, hh, ww, c = h.shape
        b = bf // num_frames
        h5 = h.reshape(b, num_frames, hh, ww, c)
        temb5 = (
            temb.reshape(b, num_frames, -1) if temb is not None else None
        )
        ht = TemporalResnetBlock(
            self.out_channels,
            eps=self.temporal_eps if self.temporal_eps is not None else self.eps,
            has_temb=self.has_temb,
            dtype=self.dtype,
            name="temporal_res_block",
        )(h5, temb5)
        mixed = AlphaBlender(
            switch_spatial_to_temporal_mix=self.switch_spatial_to_temporal_mix,
            dtype=self.dtype,
            name="time_mixer",
        )(
            h5,
            ht,
            image_only_indicator
            if self.merge_strategy == "learned_with_images"
            else None,
        )
        return mixed.reshape(bf, hh, ww, c)


class TemporalBasicTransformerBlock(nn.Module):
    """Attention across frames per spatial position, with an input GEGLU
    projection (ff_in) and optional cross-attention to the first frame's
    conditioning tokens."""

    dim: int
    num_heads: int
    head_dim: int
    cross: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, hidden, num_frames: int, context=None):
        from .layers import Attention, FeedForward

        bf, s, c = hidden.shape
        b = bf // num_frames
        # [B*F, S, C] -> [B*S, F, C]
        hidden = hidden.reshape(b, num_frames, s, c).transpose(0, 2, 1, 3)
        hidden = hidden.reshape(b * s, num_frames, c)

        residual = hidden
        h = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="norm_in")(hidden)
        h = FeedForward(self.dim, dtype=self.dtype, name="ff_in")(h)
        hidden = h + residual  # is_res: dim == time_mix_inner_dim in SVD

        attn = Attention(
            self.num_heads, self.head_dim, self.dim, dtype=self.dtype,
            name="attn1",
        )
        hidden = hidden + attn(
            nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="norm1")(hidden)
        )
        if self.cross:
            cross_attn = Attention(
                self.num_heads, self.head_dim, self.dim, dtype=self.dtype,
                name="attn2",
            )
            hidden = hidden + cross_attn(
                nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="norm2")(
                    hidden
                ),
                context,
            )
        hidden = hidden + FeedForward(self.dim, dtype=self.dtype, name="ff")(
            nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="norm3")(hidden)
        )
        hidden = hidden.reshape(b, s, num_frames, c).transpose(0, 2, 1, 3)
        return hidden.reshape(bf, s, c)


class TransformerSpatioTemporal(nn.Module):
    """Spatial transformer + frame-axis transformer pair with a learned
    blend; conditioning context is the 1-token CLIP image embed (the
    temporal blocks see the FIRST frame's context per diffusers)."""

    num_heads: int
    head_dim: int
    num_layers: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, context, num_frames: int, image_only_indicator=None):
        bf, hh, ww, c = x.shape
        b = bf // num_frames
        inner = self.num_heads * self.head_dim
        residual = x

        hidden = FusedGroupNorm(32, epsilon=1e-6, dtype=self.dtype,
                                name="norm")(x)
        hidden = hidden.reshape(bf, hh * ww, c)
        hidden = nn.Dense(inner, dtype=self.dtype, name="proj_in")(hidden)

        # frame-position embedding added before each temporal block
        frame_ids = jnp.tile(jnp.arange(num_frames), (b,))
        t_feat = timestep_embedding(frame_ids, c, dtype=self.dtype)
        emb = _time_pos_embed(t_feat, c, self.dtype)[:, None, :]

        # temporal cross-attention context: first frame's tokens, repeated
        # per spatial position -> [B*S, 1, C_ctx]
        ctx_first = context.reshape(b, num_frames, -1, context.shape[-1])[:, 0]
        time_context = jnp.broadcast_to(
            ctx_first[:, None],
            (b, hh * ww, ctx_first.shape[1], ctx_first.shape[2]),
        ).reshape(b * hh * ww, ctx_first.shape[1], ctx_first.shape[2])

        # ONE blender shared by all layers (diffusers has a single
        # time_mixer on the transformer, reused per layer)
        blender = AlphaBlender(dtype=self.dtype, name="time_mixer")

        for i in range(self.num_layers):
            hidden = BasicTransformerBlock(
                inner,
                self.num_heads,
                self.head_dim,
                dtype=self.dtype,
                name=f"transformer_blocks_{i}",
            )(hidden, context)
            mix = hidden + emb.astype(hidden.dtype)
            mix = TemporalBasicTransformerBlock(
                inner,
                self.num_heads,
                self.head_dim,
                dtype=self.dtype,
                name=f"temporal_transformer_blocks_{i}",
            )(mix, num_frames, time_context)
            hidden = _blend_tokens(
                blender, hidden, mix, image_only_indicator, b, num_frames
            )
        hidden = nn.Dense(c, dtype=self.dtype, name="proj_out")(hidden)
        return hidden.reshape(bf, hh, ww, c) + residual


def _blend_tokens(blender, spatial, temporal, image_only_indicator, b, f):
    """AlphaBlender over [B*F, S, C] token tensors (indicator per frame)."""
    if image_only_indicator is not None:
        s, c = spatial.shape[1], spatial.shape[2]
        sp = spatial.reshape(b, f, s, c)
        tp = temporal.reshape(b, f, s, c)
        out = blender(sp, tp, image_only_indicator)
        return out.reshape(b * f, s, c)
    return blender(spatial, temporal, None)


def _time_pos_embed(t_feat, in_channels, dtype):
    """diffusers TimestepEmbedding(in_channels, in_channels*4,
    out_dim=in_channels): asymmetric in/out widths, so inline Denses."""
    h = nn.Dense(in_channels * 4, dtype=dtype, name="time_pos_embed_linear_1")(
        t_feat
    )
    h = nn.silu(h)
    return nn.Dense(in_channels, dtype=dtype, name="time_pos_embed_linear_2")(h)


class UNetSpatioTemporalConditionModel(nn.Module):
    config: SVDUNetConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        sample,  # [B, F, H, W, C_in] (noise latents ++ cond-frame latents)
        timesteps,  # [B] or scalar
        encoder_hidden_states,  # [B, 1, cross] CLIP image embed tokens
        added_time_ids,  # [B, 3] (fps, motion_bucket_id, noise_aug)
        image_only_indicator=None,  # [B, F]; zeros for video generation
    ):
        cfg = self.config
        b, num_frames = sample.shape[0], sample.shape[1]
        if jnp.ndim(timesteps) == 0:
            timesteps = jnp.broadcast_to(timesteps, (b,))
        if image_only_indicator is None:
            image_only_indicator = jnp.zeros((b, num_frames), jnp.float32)

        temb_dim = cfg.block_out_channels[0] * 4
        t_feat = timestep_embedding(
            timesteps, cfg.block_out_channels[0], dtype=self.dtype
        )
        temb = TimestepEmbedding(temb_dim, dtype=self.dtype, name="time_embedding")(
            t_feat
        )
        tid_feat = timestep_embedding(
            added_time_ids.reshape(-1),
            cfg.addition_time_embed_dim,
            dtype=self.dtype,
        ).reshape(b, -1)
        temb = temb + TimestepEmbedding(
            temb_dim, dtype=self.dtype, name="add_embedding"
        )(tid_feat)

        # flatten frames into the batch; conditioning repeats per frame
        x = sample.reshape(
            b * num_frames, sample.shape[2], sample.shape[3], sample.shape[4]
        )
        temb = jnp.repeat(temb, num_frames, axis=0)
        context = jnp.repeat(
            encoder_hidden_states.astype(self.dtype), num_frames, axis=0
        )

        x = nn.Conv(
            cfg.block_out_channels[0], (3, 3), padding=((1, 1), (1, 1)),
            dtype=self.dtype, name="conv_in",
        )(x.astype(self.dtype))

        def res_block(prefix, j, out_ch, h):
            return SpatioTemporalResBlock(
                out_ch, dtype=self.dtype, name=f"{prefix}_resnets_{j}"
            )(h, temb, num_frames, image_only_indicator)

        def attn_block(prefix, j, level, h):
            return TransformerSpatioTemporal(
                cfg.num_attention_heads[level],
                cfg.block_out_channels[level] // cfg.num_attention_heads[level],
                cfg.transformer_layers_per_block,
                dtype=self.dtype,
                name=f"{prefix}_attentions_{j}",
            )(h, context, num_frames, image_only_indicator)

        levels = len(cfg.block_out_channels)
        skips = [x]
        for i, out_ch in enumerate(cfg.block_out_channels):
            prefix = f"down_blocks_{i}"
            for j in range(cfg.layers_per_block):
                x = res_block(prefix, j, out_ch, x)
                if cfg.attention[i]:
                    x = attn_block(prefix, j, i, x)
                skips.append(x)
            if i != levels - 1:
                x = Downsample2D(
                    out_ch, dtype=self.dtype, name=f"{prefix}_downsamplers_0"
                )(x)
                skips.append(x)

        x = res_block("mid_block", 0, cfg.block_out_channels[-1], x)
        x = attn_block("mid_block", 0, levels - 1, x)
        x = res_block("mid_block", 1, cfg.block_out_channels[-1], x)

        for bi, out_ch in enumerate(reversed(cfg.block_out_channels)):
            rev = levels - 1 - bi
            prefix = f"up_blocks_{bi}"
            for j in range(cfg.layers_per_block + 1):
                x = jnp.concatenate([x, skips.pop()], axis=-1)
                x = res_block(prefix, j, out_ch, x)
                if cfg.attention[rev]:
                    x = attn_block(prefix, j, rev, x)
            if bi != levels - 1:
                x = Upsample2D(
                    out_ch, dtype=self.dtype, name=f"{prefix}_upsamplers_0"
                )(x)

        x = FusedGroupNorm(32, epsilon=1e-5, dtype=self.dtype, act="silu",
                           name="conv_norm_out")(x)
        x = nn.Conv(
            cfg.out_channels, (3, 3), padding=((1, 1), (1, 1)), dtype=self.dtype,
            name="conv_out",
        )(x)
        return x.reshape(
            b, num_frames, x.shape[1], x.shape[2], cfg.out_channels
        )
