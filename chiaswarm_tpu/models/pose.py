"""Heatmap pose estimation network for the openpose preprocessor.

Reference behavior replaced: swarm/pre_processors/controlnet.py:46-47
(`OpenposeDetector.from_pretrained("lllyasviel/ControlNet")` — a torch
body-pose network run per job). TPU redesign: a compact fully-conv
heatmap network in flax (strided conv encoder -> residual trunk -> 18
COCO-keypoint heatmaps at 1/8 resolution), resident and jitted once per
canvas bucket; keypoints read out as per-channel argmax + confidence.
Weights follow weights.py policy: tiny/test names random-init, real names
fail loudly until pose-weight conversion lands.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp

# COCO-18 keypoint scheme (the openpose body model's output order)
N_KEYPOINTS = 18
# limb connectivity for skeleton rendering (keypoint index pairs)
LIMBS = (
    (0, 1), (1, 2), (2, 3), (3, 4), (1, 5), (5, 6), (6, 7), (1, 8),
    (8, 9), (9, 10), (1, 11), (11, 12), (12, 13), (0, 14), (14, 16),
    (0, 15), (15, 17),
)


@dataclasses.dataclass(frozen=True)
class PoseConfig:
    image_size: int = 368  # openpose canonical input canvas
    widths: tuple[int, ...] = (64, 128, 256)
    trunk_blocks: int = 4
    n_keypoints: int = N_KEYPOINTS


TINY_POSE = PoseConfig(image_size=64, widths=(8, 16), trunk_blocks=1)


class _ResBlock(nn.Module):
    width: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.relu(nn.Conv(self.width, (3, 3), dtype=self.dtype)(x))
        h = nn.Conv(self.width, (3, 3), dtype=self.dtype)(h)
        return nn.relu(x + h)


class PoseNet(nn.Module):
    config: PoseConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, pixels):
        """[B, S, S, 3] in [-1, 1] -> heatmaps [B, S/2^len(widths), ..,
        n_keypoints] (sigmoid confidence per cell)."""
        x = pixels
        for w in self.config.widths:
            x = nn.relu(
                nn.Conv(w, (3, 3), strides=(2, 2), dtype=self.dtype)(x)
            )
        for _ in range(self.config.trunk_blocks):
            x = _ResBlock(self.config.widths[-1], dtype=self.dtype)(x)
        heat = nn.Conv(
            self.config.n_keypoints, (1, 1), dtype=self.dtype, name="heatmaps"
        )(x)
        return nn.sigmoid(heat)
