"""Heatmap pose estimation network for the openpose preprocessor.

Reference behavior replaced: swarm/pre_processors/controlnet.py:46-47
(`OpenposeDetector.from_pretrained("lllyasviel/ControlNet")` — a torch
body-pose network run per job). TPU redesign: a compact fully-conv
heatmap network in flax (strided conv encoder -> residual trunk -> 18
COCO-keypoint heatmaps at 1/8 resolution), resident and jitted once per
canvas bucket; keypoints read out as per-channel argmax + confidence.
Weights follow weights.py policy: tiny/test names random-init, real names
fail loudly until pose-weight conversion lands.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp

# COCO-18 keypoint scheme (the openpose body model's output order)
N_KEYPOINTS = 18
# limb connectivity for skeleton rendering (keypoint index pairs)
LIMBS = (
    (0, 1), (1, 2), (2, 3), (3, 4), (1, 5), (5, 6), (6, 7), (1, 8),
    (8, 9), (9, 10), (1, 11), (11, 12), (12, 13), (0, 14), (14, 16),
    (0, 15), (15, 17),
)


@dataclasses.dataclass(frozen=True)
class PoseConfig:
    image_size: int = 368  # openpose canonical input canvas
    widths: tuple[int, ...] = (64, 128, 256)
    trunk_blocks: int = 4
    n_keypoints: int = N_KEYPOINTS


TINY_POSE = PoseConfig(image_size=64, widths=(8, 16), trunk_blocks=1)


class _ResBlock(nn.Module):
    width: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.relu(nn.Conv(self.width, (3, 3), dtype=self.dtype)(x))
        h = nn.Conv(self.width, (3, 3), dtype=self.dtype)(h)
        return nn.relu(x + h)


class PoseNet(nn.Module):
    config: PoseConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, pixels):
        """[B, S, S, 3] in [-1, 1] -> heatmaps [B, S/2^len(widths), ..,
        n_keypoints] (sigmoid confidence per cell)."""
        x = pixels
        for w in self.config.widths:
            x = nn.relu(
                nn.Conv(w, (3, 3), strides=(2, 2), dtype=self.dtype)(x)
            )
        for _ in range(self.config.trunk_blocks):
            x = _ResBlock(self.config.widths[-1], dtype=self.dtype)(x)
        heat = nn.Conv(
            self.config.n_keypoints, (1, 1), dtype=self.dtype, name="heatmaps"
        )(x)
        return nn.sigmoid(heat)


# --- real CMU body-pose network (lllyasviel/ControlNet body_pose_model) ---

# COCO limb pairs and their PAF channel pairs, the standard openpose
# grouping tables (1-based keypoint ids in the original; stored 0-based)
LIMB_SEQ = (
    (1, 2), (1, 5), (2, 3), (3, 4), (5, 6), (6, 7), (1, 8), (8, 9),
    (9, 10), (1, 11), (11, 12), (12, 13), (1, 0), (0, 14), (14, 16),
    (0, 15), (15, 17), (2, 16), (5, 17),
)
PAF_IDX = (
    (12, 13), (20, 21), (14, 15), (16, 17), (22, 23), (24, 25), (0, 1),
    (2, 3), (4, 5), (6, 7), (8, 9), (10, 11), (28, 29), (30, 31),
    (34, 35), (32, 33), (36, 37), (18, 19), (26, 27),
)


class OpenposeBody(nn.Module):
    """CMU 6-stage CPM body network (VGG-19 feature trunk + per-stage
    PAF/heatmap branches), flax/NHWC, module names mirroring the
    pytorch-openpose state dict (`model0.conv1_1`,
    `model1_1.conv5_1_CPM_L1`, `model2_1.Mconv1_stage2_L1`, ...) so
    conversion.convert_openpose_body is mechanical.

    Replaces the compact stand-in PoseNet for real
    `lllyasviel/ControlNet` annotator weights (reference
    swarm/pre_processors/controlnet.py:46-47). Returns (paf [B,H/8,W/8,38],
    heatmap [B,H/8,W/8,19])."""

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, pixels):
        import functools

        relu = nn.relu
        pool = functools.partial(
            nn.max_pool, window_shape=(2, 2), strides=(2, 2)
        )

        class _Scope(nn.Module):
            """Named sub-scope so params nest as model0/conv1_1/..."""

            layers: tuple
            dtype: jnp.dtype

            @nn.compact
            def __call__(self, x):
                outer = self.layers
                for kind, args in outer:
                    if kind == "conv":
                        name, ch, k = args
                        x = nn.Conv(
                            ch, (k, k),
                            padding=((k // 2, k // 2), (k // 2, k // 2)),
                            dtype=self.dtype, name=name,
                        )(x)
                    elif kind == "relu":
                        x = relu(x)
                    else:  # pool
                        x = pool(x)
                return x

        vgg = []
        for name, ch in (
            ("conv1_1", 64), ("conv1_2", 64),
        ):
            vgg += [("conv", (name, ch, 3)), ("relu", None)]
        vgg += [("pool", None)]
        for name, ch in (("conv2_1", 128), ("conv2_2", 128)):
            vgg += [("conv", (name, ch, 3)), ("relu", None)]
        vgg += [("pool", None)]
        for name, ch in (
            ("conv3_1", 256), ("conv3_2", 256), ("conv3_3", 256),
            ("conv3_4", 256),
        ):
            vgg += [("conv", (name, ch, 3)), ("relu", None)]
        vgg += [("pool", None)]
        for name, ch in (
            ("conv4_1", 512), ("conv4_2", 512), ("conv4_3_CPM", 256),
            ("conv4_4_CPM", 128),
        ):
            vgg += [("conv", (name, ch, 3)), ("relu", None)]
        feats = _Scope(tuple(vgg), self.dtype, name="model0")(pixels)

        def stage1(branch, out_ch):
            layers = []
            for i in (1, 2, 3):
                layers += [
                    ("conv", (f"conv5_{i}_CPM_L{branch}", 128, 3)),
                    ("relu", None),
                ]
            layers += [
                ("conv", (f"conv5_4_CPM_L{branch}", 512, 1)), ("relu", None),
                ("conv", (f"conv5_5_CPM_L{branch}", out_ch, 1)),
            ]
            return tuple(layers)

        def stage_t(t, branch, out_ch):
            layers = []
            for i in (1, 2, 3, 4, 5):
                layers += [
                    ("conv", (f"Mconv{i}_stage{t}_L{branch}", 128, 7)),
                    ("relu", None),
                ]
            layers += [
                ("conv", (f"Mconv6_stage{t}_L{branch}", 128, 1)),
                ("relu", None),
                ("conv", (f"Mconv7_stage{t}_L{branch}", out_ch, 1)),
            ]
            return tuple(layers)

        paf = _Scope(stage1(1, 38), self.dtype, name="model1_1")(feats)
        heat = _Scope(stage1(2, 19), self.dtype, name="model1_2")(feats)
        for t in range(2, 7):
            x = jnp.concatenate([paf, heat, feats], axis=-1)
            paf = _Scope(stage_t(t, 1, 38), self.dtype, name=f"model{t}_1")(x)
            heat = _Scope(stage_t(t, 2, 19), self.dtype, name=f"model{t}_2")(x)
        return paf, heat
