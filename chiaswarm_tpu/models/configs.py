"""Canonical model configurations for the supported model families.

Maps hive model names (SURVEY §2.7) to architecture configs. `TINY_*`
configs are scaled-down versions of the same graphs for hermetic CPU tests
and the `test_tiny_model` job parameter (SURVEY §4 test strategy).
"""

from __future__ import annotations

from .clip import CLIPTextConfig
from .unet2d import UNet2DConfig
from .vae import VAEConfig

# --- Stable Diffusion 1.x (512 base) ---
SD15_UNET = UNet2DConfig(
    block_out_channels=(320, 640, 1280, 1280),
    transformer_layers=(1, 1, 1, 0),
    num_attention_heads=8,  # head dim 40/80/160/160
    cross_attention_dim=768,
)
SD15_CLIP = CLIPTextConfig(
    hidden_size=768, num_layers=12, num_heads=12, hidden_act="quick_gelu"
)

# --- Stable Diffusion 2.1 ---
SD21_UNET = UNet2DConfig(
    block_out_channels=(320, 640, 1280, 1280),
    transformer_layers=(1, 1, 1, 0),
    num_attention_heads=(5, 10, 20, 20),  # head dim 64 throughout
    cross_attention_dim=1024,
)
SD21_CLIP = CLIPTextConfig(
    hidden_size=1024, num_layers=23, num_heads=16, hidden_act="gelu"
)

# --- SDXL base ---
SDXL_UNET = UNet2DConfig(
    block_out_channels=(320, 640, 1280),
    transformer_layers=(0, 2, 10),
    mid_transformer_layers=10,
    num_attention_heads=(5, 10, 20),  # head dim 64 throughout
    cross_attention_dim=2048,
    addition_embed_dim=2816,  # 1280 pooled + 6*256 time ids
)
SDXL_CLIP_1 = CLIPTextConfig(
    hidden_size=768,
    num_layers=12,
    num_heads=12,
    hidden_act="quick_gelu",
    hidden_state_index=-2,
)
SDXL_CLIP_2 = CLIPTextConfig(
    hidden_size=1280,
    num_layers=32,
    num_heads=20,
    hidden_act="gelu",
    hidden_state_index=-2,
    projection_dim=1280,
)

# --- SDXL refiner (single 1280 encoder, 2560 context) ---
SDXL_REFINER_UNET = UNet2DConfig(
    block_out_channels=(384, 768, 1536, 1536),
    transformer_layers=(0, 4, 4, 0),
    mid_transformer_layers=4,
    num_attention_heads=(6, 12, 24, 24),  # head dim 64 throughout
    cross_attention_dim=1280,
    addition_embed_dim=2560,
)

SD_VAE = VAEConfig()
SDXL_VAE = VAEConfig(scaling_factor=0.13025)
# Flux: 16-channel latents, shifted+scaled, no 1x1 quant convs
# (black-forest-labs/FLUX.1-* AutoencoderKL config)
FLUX_VAE = VAEConfig(
    latent_channels=16, scaling_factor=0.3611, shift_factor=0.1159,
    use_quant_conv=False,
)

# --- tiny configs for hermetic tests / test_tiny_model jobs ---
TINY_UNET = UNet2DConfig(
    block_out_channels=(32, 64),
    transformer_layers=(1, 1),
    mid_transformer_layers=1,
    layers_per_block=1,
    num_attention_heads=4,
    cross_attention_dim=32,
)
TINY_XL_UNET = UNet2DConfig(
    block_out_channels=(32, 64),
    transformer_layers=(1, 1),
    mid_transformer_layers=1,
    layers_per_block=1,
    num_attention_heads=4,
    cross_attention_dim=64,
    addition_embed_dim=128,  # 32 pooled + 6*16 time-id features
    addition_time_embed_dim=16,
)
TINY_CLIP = CLIPTextConfig(
    vocab_size=1000, hidden_size=32, num_layers=2, num_heads=4, max_positions=77
)
TINY_CLIP_2 = CLIPTextConfig(
    vocab_size=1000,
    hidden_size=32,
    num_layers=2,
    num_heads=4,
    max_positions=77,
    projection_dim=32,
    hidden_state_index=-2,
)
TINY_VAE = VAEConfig(block_out_channels=(32, 32), layers_per_block=1)


def model_family(model_name: str) -> str:
    """Classify a hive model name into an architecture family."""
    name = model_name.lower()
    if "xl" in name and "refiner" in name:
        return "sdxl_refiner"
    if "xl" in name or "playground" in name:
        return "sdxl"
    if "stable-diffusion-2" in name or name.endswith("-v2-1") or "768" in name:
        return "sd21"
    return "sd15"
