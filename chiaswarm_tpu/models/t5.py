"""T5 text encoder (encoder-only), flax.linen — Flux's context encoder.

Reference context: Flux pipelines carry `max_sequence_length` 256/512 T5
tokens (reference swarm/test.py:259,283); the reference loads the encoder
through diffusers. This is the architecture rebuilt for XLA: pre-RMSNorm
blocks, relative-position-bucket attention bias computed once and shared
across layers (T5 semantics: only layer 0 owns the embedding table), and
gated-GELU FFN. Module names mirror the HF graph section-for-section so
conversion is a mechanical rename (models/conversion.py convert_t5).
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    d_model: int = 4096
    d_kv: int = 64
    num_heads: int = 64
    d_ff: int = 10240
    num_layers: int = 24
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    layer_norm_epsilon: float = 1e-6


# Flux uses T5-XXL; the tiny config keeps every structural feature
TINY_T5 = T5Config(
    vocab_size=1000, d_model=32, d_kv=8, num_heads=4, d_ff=64, num_layers=2
)


def t5_config_from_json(cj: dict | None) -> T5Config:
    """Geometry from a transformers T5/UL2 config.json (Kandinsky 3 rides
    FLAN-UL2's encoder: d_model 4096, 32 layers, d_ff 16384, 16x256
    heads — different from Flux's XXL defaults)."""
    cj = cj or {}
    base = T5Config()
    return T5Config(
        vocab_size=int(cj.get("vocab_size", base.vocab_size)),
        d_model=int(cj.get("d_model", base.d_model)),
        d_kv=int(cj.get("d_kv", base.d_kv)),
        num_heads=int(cj.get("num_heads", base.num_heads)),
        d_ff=int(cj.get("d_ff", base.d_ff)),
        num_layers=int(cj.get("num_layers", base.num_layers)),
        relative_attention_num_buckets=int(
            cj.get("relative_attention_num_buckets",
                   base.relative_attention_num_buckets)
        ),
        relative_attention_max_distance=int(
            cj.get("relative_attention_max_distance",
                   base.relative_attention_max_distance)
        ),
        layer_norm_epsilon=float(
            cj.get("layer_norm_epsilon", base.layer_norm_epsilon)
        ),
    )


class RMSNorm(nn.Module):
    epsilon: float = 1e-6
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
        x = x * (var + self.epsilon) ** -0.5
        return (x * scale).astype(self.dtype)


def relative_position_buckets(qlen: int, klen: int, num_buckets: int,
                              max_distance: int) -> np.ndarray:
    """T5's log-bucketed relative positions (bidirectional encoder form).

    Computed host-side with numpy — it depends only on static lengths, so
    it constant-folds into the compiled program.
    """
    context = np.arange(qlen)[:, None]
    memory = np.arange(klen)[None, :]
    rel = memory - context
    buckets = np.zeros_like(rel)
    half = num_buckets // 2
    buckets += (rel > 0).astype(np.int64) * half
    rel = np.abs(rel)
    max_exact = half // 2
    is_small = rel < max_exact
    large = max_exact + (
        np.log(np.maximum(rel, 1) / max_exact)
        / np.log(max_distance / max_exact)
        * (half - max_exact)
    ).astype(np.int64)
    large = np.minimum(large, half - 1)
    buckets += np.where(is_small, rel, large)
    return buckets


class T5Attention(nn.Module):
    config: T5Config
    dtype: jnp.dtype = jnp.float32
    has_relative_bias: bool = False

    @nn.compact
    def __call__(self, x, position_bias=None, attention_mask=None):
        cfg = self.config
        b, s, _ = x.shape
        inner = cfg.num_heads * cfg.d_kv
        # T5 projections carry no bias and no 1/sqrt(d) scaling (folded into
        # the stored weights at training time)
        q = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="q")(x)
        k = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="k")(x)
        v = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="v")(x)
        q = q.reshape(b, s, cfg.num_heads, cfg.d_kv)
        k = k.reshape(b, s, cfg.num_heads, cfg.d_kv)
        v = v.reshape(b, s, cfg.num_heads, cfg.d_kv)

        if self.has_relative_bias:
            table = self.param(
                "relative_attention_bias",
                nn.initializers.normal(1.0),
                (cfg.relative_attention_num_buckets, cfg.num_heads),
            )
            buckets = relative_position_buckets(
                s, s, cfg.relative_attention_num_buckets,
                cfg.relative_attention_max_distance,
            )
            position_bias = jnp.transpose(
                jnp.asarray(table)[jnp.asarray(buckets)], (2, 0, 1)
            )[None]  # [1, H, S, S]

        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        if position_bias is not None:
            logits = logits + position_bias.astype(jnp.float32)
        if attention_mask is not None:
            # [B, S] 1-keep mask over keys (transformers' extended-mask
            # additive form: masked keys get a large negative)
            logits = jnp.where(
                attention_mask[:, None, None, :].astype(bool),
                logits,
                jnp.asarray(-1e9, jnp.float32),
            )
        weights = nn.softmax(logits, axis=-1).astype(self.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", weights, v).reshape(b, s, inner)
        return nn.Dense(
            cfg.d_model, use_bias=False, dtype=self.dtype, name="o"
        )(out), position_bias


class T5Block(nn.Module):
    config: T5Config
    dtype: jnp.dtype = jnp.float32
    has_relative_bias: bool = False

    @nn.compact
    def __call__(self, x, position_bias=None, attention_mask=None):
        cfg = self.config
        y = RMSNorm(cfg.layer_norm_epsilon, dtype=self.dtype, name="attn_norm")(x)
        y, position_bias = T5Attention(
            cfg, dtype=self.dtype, has_relative_bias=self.has_relative_bias,
            name="attention",
        )(y, position_bias, attention_mask)
        x = x + y
        y = RMSNorm(cfg.layer_norm_epsilon, dtype=self.dtype, name="ff_norm")(x)
        # gated-GELU FFN (T5 v1.1 / XXL): gelu(wi_0(x)) * wi_1(x) -> wo
        gate = nn.Dense(cfg.d_ff, use_bias=False, dtype=self.dtype, name="wi_0")(y)
        value = nn.Dense(cfg.d_ff, use_bias=False, dtype=self.dtype, name="wi_1")(y)
        y = nn.gelu(gate, approximate=True) * value
        y = nn.Dense(cfg.d_model, use_bias=False, dtype=self.dtype, name="wo")(y)
        return x + y, position_bias


class T5Encoder(nn.Module):
    config: T5Config
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, input_ids, attention_mask=None):
        """[B, S] int32 (+ [B, S] 1-keep mask) -> [B, S, d_model]."""
        cfg = self.config
        x = nn.Embed(
            cfg.vocab_size, cfg.d_model, dtype=self.dtype, name="token_embedding"
        )(input_ids)
        position_bias = None
        for i in range(cfg.num_layers):
            x, position_bias = T5Block(
                cfg, dtype=self.dtype, has_relative_bias=(i == 0),
                name=f"block_{i}",
            )(x, position_bias, attention_mask)
        return RMSNorm(cfg.layer_norm_epsilon, dtype=self.dtype,
                       name="final_norm")(x)
