"""AutoencoderKLTemporalDecoder — the SVD video VAE, NHWC flax.

The encoder is the standard AutoencoderKL encoder (reused from vae.py: the
conditioning frame is encoded per-image), but the DECODER interleaves
temporal ResNets with the spatial ones (SpatioTemporalResBlock with a
"learned" alpha blend and switched mix) and finishes with a (3,1,1) conv
over the frame axis, which is what removes SVD's frame flicker. Matches
the diffusers graph so `convert_svd_vae` (conversion.py) maps checkpoints
mechanically; there is a `quant_conv` but NO post-quant conv.

Serving: StableVideoDiffusionPipeline decode (pipelines/video.py), where
the reference calls `pipe.decode_latents` with VAE slicing enabled
(/root/reference/swarm/video/img2vid.py:26-31) — here the whole
frame-batched decode is one jitted program.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp

from .layers import FusedGroupNorm, Upsample2D
from .svd_unet import SpatioTemporalResBlock
from .vae import Encoder, VAEAttention, VAEConfig


@dataclasses.dataclass(frozen=True)
class SVDVAEConfig:
    in_channels: int = 3
    latent_channels: int = 4
    block_out_channels: tuple[int, ...] = (128, 256, 512, 512)
    layers_per_block: int = 2
    scaling_factor: float = 0.18215

    def encoder_config(self) -> VAEConfig:
        return VAEConfig(
            in_channels=self.in_channels,
            latent_channels=self.latent_channels,
            block_out_channels=self.block_out_channels,
            layers_per_block=self.layers_per_block,
            scaling_factor=self.scaling_factor,
        )


TINY_SVD_VAE = SVDVAEConfig(
    block_out_channels=(32, 32), layers_per_block=1
)


class TemporalDecoder(nn.Module):
    config: SVDVAEConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, latents, num_frames: int):
        """[B*F, h, w, latent] (unscaled) -> [B*F, 8h, 8w, 3]."""
        cfg = self.config
        mid_ch = cfg.block_out_channels[-1]

        def st_block(name, out_ch, h):
            return SpatioTemporalResBlock(
                out_ch,
                eps=1e-6,
                temporal_eps=1e-5,
                has_temb=False,
                merge_strategy="learned",
                switch_spatial_to_temporal_mix=True,
                dtype=self.dtype,
                name=name,
            )(h, None, num_frames)

        x = nn.Conv(
            mid_ch, (3, 3), padding=((1, 1), (1, 1)), dtype=self.dtype,
            name="conv_in",
        )(latents)

        x = st_block("mid_block_resnets_0", mid_ch, x)
        x = VAEAttention(mid_ch, dtype=self.dtype, name="mid_block_attentions_0")(x)
        x = st_block("mid_block_resnets_1", mid_ch, x)

        for b, out_ch in enumerate(reversed(cfg.block_out_channels)):
            for i in range(cfg.layers_per_block + 1):
                x = st_block(f"up_blocks_{b}_resnets_{i}", out_ch, x)
            if b != len(cfg.block_out_channels) - 1:
                x = Upsample2D(
                    out_ch, dtype=self.dtype, name=f"up_blocks_{b}_upsamplers_0"
                )(x)

        x = FusedGroupNorm(32, epsilon=1e-6, dtype=self.dtype, act="silu",
                           name="conv_norm_out")(x)
        x = nn.Conv(
            cfg.in_channels, (3, 3), padding=((1, 1), (1, 1)), dtype=self.dtype,
            name="conv_out",
        )(x)
        # final temporal smoothing conv over the frame axis
        bf, hh, ww, c = x.shape
        x = x.reshape(bf // num_frames, num_frames, hh, ww, c)
        x = nn.Conv(
            cfg.in_channels,
            (3, 1, 1),
            padding=((1, 1), (0, 0), (0, 0)),
            dtype=self.dtype,
            name="time_conv_out",
        )(x)
        return x.reshape(bf, hh, ww, c)


class AutoencoderKLTemporalDecoder(nn.Module):
    config: SVDVAEConfig
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        self.encoder = Encoder(self.config.encoder_config(), dtype=self.dtype)
        self.decoder = TemporalDecoder(self.config, dtype=self.dtype)
        self.quant_conv = nn.Conv(
            2 * self.config.latent_channels, (1, 1), dtype=self.dtype
        )
        # NB: no post_quant_conv in this family

    def encode(self, pixels, rng=None):
        """pixels [B,H,W,3] in [-1,1] -> UNSCALED latent mean [B,h,w,C]
        (SVD conditions on the raw mean; denoise latents get the
        scaling_factor at the pipeline level)."""
        moments = self.quant_conv(self.encoder(pixels))
        mean, logvar = jnp.split(moments, 2, axis=-1)
        if rng is not None:
            import jax

            std = jnp.exp(0.5 * jnp.clip(logvar, -30.0, 20.0))
            mean = mean + std * jax.random.normal(rng, mean.shape, mean.dtype)
        return mean

    def decode(self, latents, num_frames: int):
        """SCALED latents [B*F,h,w,C] -> pixels [-1,1]."""
        latents = latents / self.config.scaling_factor
        return self.decoder(latents, num_frames)

    def __call__(self, pixels, num_frames: int = 1):
        lat = self.encode(pixels) * self.config.scaling_factor
        return self.decode(lat, num_frames)
