"""Conditional UNet2D covering the SD family (SD1.x/2.x, SDXL, inpaint
variants) as configs of one flax module.

Replaces the reference's per-job diffusers UNet loads
(swarm/diffusion/diffusion_func.py:103). Architecture matches the HF
`UNet2DConditionModel` graph so weights convert mechanically, but execution
is NHWC with attention routed through the TPU kernel path. SDXL's extra
conditioning (pooled text embeds + time ids) is the `addition_embed`
branch.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp

from .layers import (
    BasicTransformerBlock,
    Downsample2D,
    FusedGroupNorm,
    ResnetBlock2D,
    TimestepEmbedding,
    Transformer2DModel,
    Upsample2D,
    timestep_embedding,
)


@dataclasses.dataclass(frozen=True)
class UNet2DConfig:
    in_channels: int = 4
    out_channels: int = 4
    block_out_channels: tuple[int, ...] = (320, 640, 1280, 1280)
    # per down block: number of transformer layers; 0 = plain ResNet block
    transformer_layers: tuple[int, ...] = (1, 1, 1, 0)
    mid_transformer_layers: int = 1
    layers_per_block: int = 2
    # per-block head COUNT. NB: HF SD configs store this in a field misnamed
    # `attention_head_dim` — for the SD family diffusers reads it as the
    # number of heads (SD1.5: 8 heads of dim 40; SD2.1/XL: (5,10,20) heads
    # of dim 64). Keep the semantics, fix the name.
    num_attention_heads: int | tuple[int, ...] = 8
    # SDXL additional conditioning: projection dim of pooled text embeds
    addition_embed_dim: int = 0  # 0 = disabled
    addition_time_embed_dim: int = 256
    # AudioLDM-style FiLM conditioning: a `simple_projection` class
    # embedding (Linear from e.g. the 512-d CLAP joint space into temb),
    # concatenated to — not summed with — the time embedding when
    # `class_embeddings_concat` (diffusers UNet2DConditionModel semantics;
    # the resnet time projections then see 2x temb width)
    class_embed_dim: int = 0  # 0 = disabled
    class_embeddings_concat: bool = False
    # 0 = the transformer blocks self-attend (encoder_hidden_states=None,
    # AudioLDM's layout) instead of cross-attending to a text sequence
    cross_attention_dim: int = 768
    flip_sin_to_cos: bool = True
    freq_shift: float = 0.0

    def heads_per_block(self) -> tuple[int, ...]:
        if isinstance(self.num_attention_heads, int):
            return (self.num_attention_heads,) * len(self.block_out_channels)
        return tuple(self.num_attention_heads)


class CrossAttnDownBlock(nn.Module):
    config: UNet2DConfig
    out_channels: int
    n_transformer: int
    num_heads: int
    add_downsample: bool
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, temb, context):
        skips = []
        for i in range(self.config.layers_per_block):
            x = ResnetBlock2D(self.out_channels, dtype=self.dtype, name=f"resnets_{i}")(
                x, temb
            )
            if self.n_transformer > 0:
                x = Transformer2DModel(
                    self.num_heads,
                    self.out_channels // self.num_heads,
                    self.n_transformer,
                    dtype=self.dtype,
                    name=f"attentions_{i}",
                )(x, context)
            skips.append(x)
        if self.add_downsample:
            x = Downsample2D(self.out_channels, dtype=self.dtype, name="downsamplers_0")(x)
            skips.append(x)
        return x, skips


class CrossAttnUpBlock(nn.Module):
    config: UNet2DConfig
    out_channels: int
    n_transformer: int
    num_heads: int
    add_upsample: bool
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, skips, temb, context):
        for i in range(self.config.layers_per_block + 1):
            x = jnp.concatenate([x, skips.pop()], axis=-1)
            x = ResnetBlock2D(self.out_channels, dtype=self.dtype, name=f"resnets_{i}")(
                x, temb
            )
            if self.n_transformer > 0:
                x = Transformer2DModel(
                    self.num_heads,
                    self.out_channels // self.num_heads,
                    self.n_transformer,
                    dtype=self.dtype,
                    name=f"attentions_{i}",
                )(x, context)
        if self.add_upsample:
            x = Upsample2D(self.out_channels, dtype=self.dtype, name="upsamplers_0")(x)
        return x


class UNetMidBlock(nn.Module):
    config: UNet2DConfig
    channels: int
    n_transformer: int
    num_heads: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, temb, context):
        x = ResnetBlock2D(self.channels, dtype=self.dtype, name="resnets_0")(x, temb)
        x = Transformer2DModel(
            self.num_heads,
            self.channels // self.num_heads,
            self.n_transformer,
            dtype=self.dtype,
            name="attentions_0",
        )(x, context)
        return ResnetBlock2D(self.channels, dtype=self.dtype, name="resnets_1")(x, temb)


class UNet2DConditionModel(nn.Module):
    config: UNet2DConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        sample,  # [B, H, W, C_in] latents
        timesteps,  # [B] or scalar
        encoder_hidden_states,  # [B, S, cross_attention_dim]
        added_cond: dict | None = None,  # SDXL: {"text_embeds": [B,D], "time_ids": [B,6]}
        down_residuals: tuple | None = None,  # ControlNet per-skip residuals
        mid_residual=None,  # ControlNet mid-block residual
        class_labels=None,  # AudioLDM: [B, class_embed_dim] CLAP embedding
    ):
        cfg = self.config
        if jnp.ndim(timesteps) == 0:
            timesteps = jnp.broadcast_to(timesteps, (sample.shape[0],))

        temb_dim = cfg.block_out_channels[0] * 4
        t_feat = timestep_embedding(
            timesteps,
            cfg.block_out_channels[0],
            flip_sin_to_cos=cfg.flip_sin_to_cos,
            downscale_freq_shift=cfg.freq_shift,
            dtype=self.dtype,
        )
        temb = TimestepEmbedding(temb_dim, dtype=self.dtype, name="time_embedding")(t_feat)

        if cfg.addition_embed_dim:
            # SDXL micro-conditioning (size/crop time ids + pooled text embeds)
            add = added_cond or {}
            time_ids = add["time_ids"]
            text_embeds = add["text_embeds"]
            tid_feat = timestep_embedding(
                time_ids.reshape(-1),
                cfg.addition_time_embed_dim,
                flip_sin_to_cos=cfg.flip_sin_to_cos,
                downscale_freq_shift=cfg.freq_shift,
                dtype=self.dtype,
            ).reshape(sample.shape[0], -1)
            add_feat = jnp.concatenate([text_embeds, tid_feat], axis=-1)
            temb = temb + TimestepEmbedding(
                temb_dim, dtype=self.dtype, name="add_embedding"
            )(add_feat)

        if cfg.class_embed_dim:
            class_emb = nn.Dense(
                temb_dim, dtype=self.dtype, name="class_embedding"
            )(class_labels.astype(self.dtype))
            if cfg.class_embeddings_concat:
                temb = jnp.concatenate([temb, class_emb], axis=-1)
            else:
                temb = temb + class_emb

        x = nn.Conv(
            cfg.block_out_channels[0], (3, 3), padding=((1, 1), (1, 1)),
            dtype=self.dtype, name="conv_in",
        )(sample)

        heads = cfg.heads_per_block()
        skips = [x]
        for b, out_ch in enumerate(cfg.block_out_channels):
            last = b == len(cfg.block_out_channels) - 1
            x, block_skips = CrossAttnDownBlock(
                cfg,
                out_ch,
                cfg.transformer_layers[b],
                heads[b],
                add_downsample=not last,
                dtype=self.dtype,
                name=f"down_blocks_{b}",
            )(x, temb, encoder_hidden_states)
            skips.extend(block_skips)

        if down_residuals is not None:
            skips = [s + r for s, r in zip(skips, down_residuals)]

        x = UNetMidBlock(
            cfg,
            cfg.block_out_channels[-1],
            cfg.mid_transformer_layers,
            heads[-1],
            dtype=self.dtype,
            name="mid_block",
        )(x, temb, encoder_hidden_states)

        if mid_residual is not None:
            x = x + mid_residual

        for b, out_ch in enumerate(reversed(cfg.block_out_channels)):
            rev = len(cfg.block_out_channels) - 1 - b
            last = b == len(cfg.block_out_channels) - 1
            x = CrossAttnUpBlock(
                cfg,
                out_ch,
                cfg.transformer_layers[rev],
                heads[rev],
                add_upsample=not last,
                dtype=self.dtype,
                name=f"up_blocks_{b}",
            )(x, skips, temb, encoder_hidden_states)

        x = FusedGroupNorm(32, epsilon=1e-5, dtype=self.dtype, act="silu",
                           name="conv_norm_out")(x)
        return nn.Conv(
            cfg.out_channels, (3, 3), padding=((1, 1), (1, 1)), dtype=self.dtype,
            name="conv_out",
        )(x)
