"""Bark-style TTS transformer stack: causal GPTs + codec decoder.

The reference delegates Bark entirely to the `bark` package
(swarm/audio/bark.py:16-21: preload_models + generate_audio). This module
rebuilds the architecture TPU-first as three flax transformers over the
suno/bark token scheme — text -> semantic tokens (causal AR), semantic ->
coarse acoustic codebooks (causal AR, 2 codebooks interleaved), coarse ->
fine codebooks (non-causal, per-codebook refinement) — plus a SEANet-style
transposed-conv codec decoder from quantized codebooks to waveform.

TPU design notes: autoregressive decoding runs as ONE `lax.scan` over a
static token budget with an explicit KV cache in the scan carry (cache
writes via `dynamic_update_slice`, attention masked to `pos`) — no
Python-loop decoding, no dynamic shapes, one compiled program per (prompt
budget, generation budget). The fine stage and the codec are plain batched
forward passes that ride the MXU.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class BarkGPTConfig:
    input_vocab: int
    output_vocab: int
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    block_size: int = 1024
    causal: bool = True
    # fine stage (transformers BarkFineModel): one embedding table per
    # codebook (summed over books 0..codebook_idx) and one LM head per
    # predicted book. 0 = single-table causal stages (semantic/coarse).
    n_codes_total: int = 0
    n_codes_given: int = 1


# suno/bark token scheme constants (semantic rate ~50 Hz, EnCodec 75 Hz)
SEMANTIC_VOCAB = 10_000
CODEBOOK_SIZE = 1024
N_COARSE_BOOKS = 2
N_FINE_BOOKS = 8
SEMANTIC_RATE = 50
CODEC_RATE = 75


def bark_small(stage: str) -> BarkGPTConfig:
    """suno/bark vocab structure (transformers Bark*Config); real serving
    reads the per-stage config.json from the checkpoint instead."""
    if stage == "semantic":
        return BarkGPTConfig(
            input_vocab=129_600,  # text ids at 10_048.., specials at top
            output_vocab=10_048,
        )
    if stage == "coarse":
        # coarse codes live at 10_000 + book*1024 INSIDE the shared vocab
        return BarkGPTConfig(input_vocab=12_096, output_vocab=12_096)
    return BarkGPTConfig(  # fine: per-book tables, pad id = CODEBOOK_SIZE
        input_vocab=1056,
        output_vocab=1056,
        causal=False,
        n_codes_total=N_FINE_BOOKS,
    )


def bark_tiny(stage: str) -> BarkGPTConfig:
    """Same vocab STRUCTURE as the real scheme at test scale
    (pipelines.bark.TINY_SCHEME): semantic ids 0..999, text above 1048,
    coarse codes at 1000 + book*64 in a shared in/out vocab."""
    kw = dict(n_layer=2, n_head=2, d_model=32, block_size=128)
    if stage == "semantic":
        return BarkGPTConfig(input_vocab=1200, output_vocab=1000, **kw)
    if stage == "coarse":
        return BarkGPTConfig(input_vocab=1136, output_vocab=1136, **kw)
    return BarkGPTConfig(
        input_vocab=64 + 1, output_vocab=64, causal=False,
        n_codes_total=N_FINE_BOOKS, **kw
    )


class _Block(nn.Module):
    config: BarkGPTConfig
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        cfg = self.config
        self.ln1 = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype)
        self.ln2 = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype)
        self.qkv = nn.Dense(3 * cfg.d_model, dtype=self.dtype)
        self.proj = nn.Dense(cfg.d_model, dtype=self.dtype)
        self.fc = nn.Dense(4 * cfg.d_model, dtype=self.dtype)
        self.fc_out = nn.Dense(cfg.d_model, dtype=self.dtype)

    def _heads(self, x):
        b = x.shape[0]
        h = self.config.n_head
        return x.reshape(b, -1, h, self.config.d_model // h)

    def _mlp(self, x):
        # transformers BarkMLP uses exact (erf) GELU, not the tanh approx
        return self.fc_out(nn.gelu(self.fc(x), approximate=False))

    def __call__(self, x, mask=None):
        """Full-sequence pass. x [B,T,D]; mask [T,T] additive or None."""
        h = self.ln1(x)
        q, k, v = jnp.split(self.qkv(h), 3, axis=-1)
        q, k, v = (self._heads(t) for t in (q, k, v))
        scale = (q.shape[-1]) ** -0.5
        att = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        if mask is not None:
            att = att + mask
        att = jax.nn.softmax(att.astype(jnp.float32), axis=-1).astype(q.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", att, v)
        x = x + self.proj(out.reshape(x.shape))
        return x + self._mlp(self.ln2(x))

    def step(self, x, pos, cache_k, cache_v):
        """One decode step. x [B,D]; caches [B,T_max,H,dh]; pos scalar.
        -> (x, cache_k, cache_v)."""
        h = self.ln1(x)
        q, k, v = jnp.split(self.qkv(h), 3, axis=-1)
        b = x.shape[0]
        hd = self.config.d_model // self.config.n_head
        q = q.reshape(b, self.config.n_head, hd)
        k = k.reshape(b, 1, self.config.n_head, hd)
        v = v.reshape(b, 1, self.config.n_head, hd)
        cache_k = jax.lax.dynamic_update_slice(cache_k, k, (0, pos, 0, 0))
        cache_v = jax.lax.dynamic_update_slice(cache_v, v, (0, pos, 0, 0))
        scale = hd**-0.5
        att = jnp.einsum("bhd,bkhd->bhk", q, cache_k) * scale
        t_max = cache_k.shape[1]
        valid = jnp.arange(t_max) <= pos
        att = jnp.where(valid[None, None, :], att, -1e9)
        att = jax.nn.softmax(att.astype(jnp.float32), axis=-1).astype(q.dtype)
        out = jnp.einsum("bhk,bkhd->bhd", att, cache_v).reshape(b, -1)
        x = x + self.proj(out)
        return x + self._mlp(self.ln2(x)), cache_k, cache_v


class BarkGPT(nn.Module):
    """Causal (or bidirectional) transformer with an explicit-KV decode
    path for scan-based AR generation."""

    config: BarkGPTConfig
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        cfg = self.config
        if cfg.n_codes_total:
            self.tok_embeds = [
                nn.Embed(cfg.input_vocab, cfg.d_model, dtype=self.dtype,
                         name=f"tok_embed_{i}")
                for i in range(cfg.n_codes_total)
            ]
            self.heads = [
                nn.Dense(cfg.output_vocab, use_bias=False, dtype=self.dtype,
                         name=f"head_{i}")
                for i in range(cfg.n_codes_total - cfg.n_codes_given)
            ]
        else:
            self.tok_embed = nn.Embed(
                cfg.input_vocab, cfg.d_model, dtype=self.dtype
            )
            self.head = nn.Dense(
                cfg.output_vocab, use_bias=False, dtype=self.dtype
            )
        self.pos_embed = nn.Embed(cfg.block_size, cfg.d_model, dtype=self.dtype)
        self.blocks = [
            _Block(cfg, dtype=self.dtype, name=f"block_{i}")
            for i in range(cfg.n_layer)
        ]
        self.ln_f = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype)

    def _trunk(self, x):
        t = x.shape[1]
        x = x + self.pos_embed(jnp.arange(t))[None]
        mask = None
        if self.config.causal:
            mask = jnp.where(
                jnp.tril(jnp.ones((t, t), bool)), 0.0, -1e9
            ).astype(self.dtype)
        for block in self.blocks:
            x = block(x, mask)
        return self.ln_f(x)

    def __call__(self, tokens, codebook_idx: int | None = None):
        """Single-table stages: [B,T] -> logits [B,T,output_vocab] (causal
        iff config.causal). Fine stage (n_codes_total): tokens [B,K,T],
        `codebook_idx` a static int — embeddings sum over books
        0..codebook_idx (transformers BarkFineModel semantics; unpredicted
        books carry the pad id = codebook size) and the logits come from
        that book's own head."""
        if self.config.n_codes_total:
            assert codebook_idx is not None, "fine stage needs codebook_idx"
            x = sum(
                self.tok_embeds[i](tokens[:, i])
                for i in range(codebook_idx + 1)
            )
            x = self._trunk(x)
            return self.heads[codebook_idx - self.config.n_codes_given](x)
        x = self.tok_embed(tokens)
        return self.head(self._trunk(x))

    def init_all(self, tokens):
        """Init-only entry touching every per-book table and head so a
        single `init` materialises the full fine-stage parameter tree."""
        cfg = self.config
        if not cfg.n_codes_total:
            return self(tokens)
        x = sum(emb(tokens[:, i]) for i, emb in enumerate(self.tok_embeds))
        x = self._trunk(x)
        return sum(head(x) for head in self.heads)

    def embed_step(self, token, pos):
        """[B] int32, pos scalar -> [B,D] (decode-path embedding)."""
        return self.tok_embed(token) + self.pos_embed(jnp.asarray(pos))[None]

    def step(self, token, pos, caches):
        """One AR step. caches: list of (k, v) [B,T_max,H,dh] per layer.
        -> (logits [B,V], caches)."""
        x = self.embed_step(token, pos)
        new = []
        for block, (ck, cv) in zip(self.blocks, caches):
            x, ck, cv = block.step(x, pos, ck, cv)
            new.append((ck, cv))
        return self.head(self.ln_f(x)), new

    def init_cache(self, batch: int, t_max: int):
        cfg = self.config
        hd = cfg.d_model // cfg.n_head
        z = jnp.zeros((batch, t_max, cfg.n_head, hd), self.dtype)
        return [(z, z) for _ in range(cfg.n_layer)]


def generate(model: BarkGPT, params, prompt, n_new: int, rng,
             temperature: float = 0.7, top_k: int = 50,
             input_offset: int = 0, range_fn=None):
    """Scan-based AR sampling: one compiled loop over prompt+generation.

    prompt [B, Tp] int32 feeds teacher-forced; then n_new tokens sample
    from top-k at `temperature`. `range_fn(gen_idx) -> (lo, hi)` (jax-
    traceable) restricts sampling to a logit slice per generated index
    (codebook parity constraints). Sampled ids live in the OUTPUT vocab;
    `input_offset` maps them back into the input embedding space when fed
    as the next token (e.g. coarse ids ride above the semantic ids).
    Returns [B, n_new] sampled OUTPUT-vocab ids.
    """
    b, t_prompt = prompt.shape
    total = t_prompt + n_new
    caches = model.init_cache(b, total)
    k = min(top_k, model.config.output_vocab)

    def sample(logits, key, gen_idx):
        logits = logits.astype(jnp.float32)
        if range_fn is not None:
            lo, hi = range_fn(gen_idx)
            idx = jnp.arange(logits.shape[-1])
            logits = jnp.where((idx >= lo) & (idx < hi), logits, -1e9)
        top, _ = jax.lax.top_k(logits, k)
        logits = jnp.where(logits < top[..., -1:], -1e9, logits)
        # temperature may be a traced scalar (kept out of jit cache keys)
        temp = jnp.maximum(jnp.asarray(temperature, jnp.float32), 1e-4)
        return jax.random.categorical(key, logits / temp)

    def body(carry, i):
        token, caches = carry
        logits, caches = model.apply(
            {"params": params}, token, i, caches, method=BarkGPT.step
        )
        sampled = sample(logits, jax.random.fold_in(rng, i), i - (t_prompt - 1))
        next_prompt = prompt[:, jnp.minimum(i + 1, t_prompt - 1)]
        token = jnp.where(
            i + 1 < t_prompt, next_prompt, sampled + input_offset
        ).astype(prompt.dtype)
        return (token, caches), sampled

    (_, _), out = jax.lax.scan(
        body, (prompt[:, 0], caches), jnp.arange(total - 1)
    )
    # out[i] is the sample made AFTER consuming position i; generation
    # begins once the prompt is exhausted
    return jnp.moveaxis(out, 0, 1)[:, t_prompt - 1:]
