"""Kandinsky 3 UNet: the diffusers `Kandinsky3UNet` graph rebuilt as one
flax module in NHWC.

Reference behavior replaced: swarm/test.py:130-147 schedules
kandinsky-community/kandinsky-3 through AutoPipeline; diffusers serves it
with Kandinsky3UNet — a distinct block family from every other UNet in the
inventory: every norm is a *conditional* group norm (affine-free GroupNorm
whose scale/shift come from a zero-init MLP of the time embedding), res
blocks are 4-sub-block bottlenecks (1-3-3-1 kernels at `max(in,out)//2`
hidden width) with up/down-sampling threaded through specific sub-block
positions, and attention blocks are token-space (flattened h*w) with
conv1x1 feed-forwards. Text conditioning is FLAN-UL2 T5 states projected
by a bias-free Linear, entering both through cross-attention at the three
lower resolutions and through an attention pooling added to the time
embedding.

Module names line up with the flattened diffusers state-dict names so
conversion (models/conversion.py convert_kandinsky3_unet) is a mechanical
rename; the two ConvTranspose kernels per up-path resnet are the only
layout special-cases (IOHW, unlike conv's OIHW).
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp

from .layers import FusedGroupNorm, TimestepEmbedding, timestep_embedding


@dataclasses.dataclass(frozen=True)
class K3UNetConfig:
    in_channels: int = 4
    time_embedding_dim: int = 1536
    groups: int = 32
    attention_head_dim: int = 64
    layers_per_block: int = 3
    block_out_channels: tuple[int, ...] = (384, 768, 1536, 3072)
    cross_attention_dim: int = 4096
    encoder_hid_dim: int = 4096
    add_cross_attention: tuple[bool, ...] = (False, True, True, True)
    add_self_attention: tuple[bool, ...] = (False, True, True, True)
    expansion_ratio: int = 4
    compression_ratio: int = 2

    @property
    def init_channels(self) -> int:
        return self.block_out_channels[0] // 2


# layers_per_block >= 2: the up-block channel plan
# [(in+cat, in)] + [(in, in)]*(n-2) + [(in, out)] degenerates below that
TINY_K3_UNET = K3UNetConfig(
    time_embedding_dim=32,
    groups=4,
    attention_head_dim=8,
    layers_per_block=2,
    block_out_channels=(16, 32),
    cross_attention_dim=32,
    encoder_hid_dim=32,
    add_cross_attention=(False, True),
    add_self_attention=(False, True),
)


class ConditionalGroupNorm(nn.Module):
    """Affine-free GroupNorm modulated by a zero-init MLP of the time
    embedding: x_norm * (scale(temb) + 1) + shift(temb)."""

    groups: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, temb):
        c = x.shape[-1]
        ctx = nn.Dense(2 * c, dtype=self.dtype, name="context_mlp_1")(
            nn.silu(temb)
        )
        scale, shift = jnp.split(ctx[:, None, None, :], 2, axis=-1)
        x = nn.GroupNorm(
            self.groups, epsilon=1e-5, use_bias=False, use_scale=False,
            dtype=self.dtype,
        )(x)
        return x * (scale + 1.0) + shift


class ConvTranspose2x2(nn.Module):
    """torch ConvTranspose2d(kernel=2, stride=2): stride equals kernel so
    every input pixel maps to a disjoint 2x2 output block — an einsum, not
    a real transposed convolution. Kernel layout (2, 2, in, out)."""

    features: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (2, 2, c, self.features),
        )
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        y = jnp.einsum(
            "bhwi,klio->bhkwlo", x, jnp.asarray(kernel, self.dtype)
        )
        y = y.reshape(b, 2 * h, 2 * w, self.features)
        return y + jnp.asarray(bias, self.dtype)


class K3Attention(nn.Module):
    """Bias-free attention (to_q/to_k/to_v/to_out_0), softmax in fp32.
    `inner` is both the query width and the output width; K/V project from
    whatever width the context carries."""

    inner: int
    head_dim: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, q_in, kv_in, mask=None):
        heads = max(1, self.inner // self.head_dim)
        dim = self.inner // heads
        b, n, _ = q_in.shape
        s = kv_in.shape[1]
        q = nn.Dense(self.inner, use_bias=False, dtype=self.dtype,
                     name="to_q")(q_in)
        k = nn.Dense(self.inner, use_bias=False, dtype=self.dtype,
                     name="to_k")(kv_in)
        v = nn.Dense(self.inner, use_bias=False, dtype=self.dtype,
                     name="to_v")(kv_in)
        q = q.reshape(b, n, heads, dim)
        k = k.reshape(b, s, heads, dim)
        v = v.reshape(b, s, heads, dim)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        logits = logits * (dim ** -0.5)
        if mask is not None:
            big_neg = jnp.asarray(-1e9, jnp.float32)
            logits = jnp.where(
                mask[:, None, None, :].astype(bool), logits, big_neg
            )
        weights = nn.softmax(logits, axis=-1).astype(self.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", weights, v).reshape(
            b, n, self.inner
        )
        return nn.Dense(
            self.inner, use_bias=False, dtype=self.dtype, name="to_out_0"
        )(out)


class K3EncoderProj(nn.Module):
    """diffusers Kandinsky3EncoderProj: bias-free Linear + LayerNorm over
    the T5 states before they condition anything."""

    cross_attention_dim: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(
            self.cross_attention_dim, use_bias=False, dtype=self.dtype,
            name="projection_linear",
        )(x)
        return nn.LayerNorm(
            epsilon=1e-5, dtype=self.dtype, name="projection_norm"
        )(x)


class K3AttentionPooling(nn.Module):
    """Mean-of-context query attends over the context; the pooled vector
    adds onto the time embedding (diffusers Kandinsky3AttentionPooling)."""

    num_channels: int
    head_dim: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, temb, context, mask=None):
        pooled = K3Attention(
            self.num_channels, self.head_dim, dtype=self.dtype,
            name="attention",
        )(jnp.mean(context, axis=1, keepdims=True), context, mask)
        return temb + pooled[:, 0, :]


class K3Block(nn.Module):
    """norm -> silu -> (up) -> conv -> (down): one bottleneck sub-block.
    `up_resolution` None keeps resolution, True transposed-up-2x BEFORE
    the conv, False strided-down-2x AFTER it."""

    out_channels: int
    kernel_size: int = 3
    up_resolution: bool | None = None
    groups: int = 32
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, temb):
        x = ConditionalGroupNorm(
            self.groups, dtype=self.dtype, name="group_norm"
        )(x, temb)
        x = nn.silu(x)
        if self.up_resolution is True:
            x = ConvTranspose2x2(
                x.shape[-1], dtype=self.dtype, name="up_sample"
            )(x)
        pad = "SAME" if self.kernel_size > 1 else "VALID"
        x = nn.Conv(
            self.out_channels,
            (self.kernel_size, self.kernel_size),
            padding=pad,
            dtype=self.dtype,
            name="projection",
        )(x)
        if self.up_resolution is False:
            x = nn.Conv(
                self.out_channels, (2, 2), strides=(2, 2), padding="VALID",
                dtype=self.dtype, name="down_sample",
            )(x)
        return x


class K3ResNetBlock(nn.Module):
    """Four-sub-block bottleneck (kernels 1-3-3-1 at max(in,out)//ratio
    width) with a shortcut that mirrors any resolution change."""

    out_channels: int
    compression_ratio: int = 2
    up_resolutions: tuple[bool | None, ...] = (None, None, None, None)
    groups: int = 32
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, temb):
        in_channels = x.shape[-1]
        kernel_sizes = (1, 3, 3, 1)
        hidden = max(in_channels, self.out_channels) // self.compression_ratio
        widths = [hidden, hidden, hidden, self.out_channels]
        out = x
        for idx, (w, ks, up) in enumerate(
            zip(widths, kernel_sizes, self.up_resolutions)
        ):
            out = K3Block(
                w, kernel_size=ks, up_resolution=up, groups=self.groups,
                dtype=self.dtype, name=f"resnet_blocks_{idx}",
            )(out, temb)
        if True in self.up_resolutions:
            x = ConvTranspose2x2(
                in_channels, dtype=self.dtype, name="shortcut_up_sample"
            )(x)
        if in_channels != self.out_channels:
            x = nn.Conv(
                self.out_channels, (1, 1), dtype=self.dtype,
                name="shortcut_projection",
            )(x)
        if False in self.up_resolutions:
            x = nn.Conv(
                self.out_channels, (2, 2), strides=(2, 2), padding="VALID",
                dtype=self.dtype, name="shortcut_down_sample",
            )(x)
        return x + out


class K3AttentionBlock(nn.Module):
    """Token-space attention over the flattened feature map (self when no
    context, cross otherwise) + conv1x1 feed-forward, both residual and
    both entered through conditional group norms."""

    head_dim: int
    expansion_ratio: int = 4
    groups: int = 32
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, temb, context=None, context_mask=None):
        b, h, w, c = x.shape
        out = ConditionalGroupNorm(
            self.groups, dtype=self.dtype, name="in_norm"
        )(x, temb)
        tokens = out.reshape(b, h * w, c)
        kv = context if context is not None else tokens
        mask = context_mask if context is not None else None
        attn = K3Attention(
            c, self.head_dim, dtype=self.dtype, name="attention"
        )(tokens, kv, mask)
        x = x + attn.reshape(b, h, w, c)
        out = ConditionalGroupNorm(
            self.groups, dtype=self.dtype, name="out_norm"
        )(x, temb)
        ff = nn.Conv(
            self.expansion_ratio * c, (1, 1), use_bias=False,
            dtype=self.dtype, name="feed_forward_0",
        )(out)
        ff = nn.Conv(
            c, (1, 1), use_bias=False, dtype=self.dtype,
            name="feed_forward_2",
        )(nn.silu(ff))
        return x + ff


class K3DownBlock(nn.Module):
    """[self-attn] then layers_per_block x (resnet_in -> [cross-attn] ->
    resnet_out); the last resnet_out's third sub-block strided-downsamples
    when this level downsamples."""

    config: K3UNetConfig
    out_channels: int
    cross: bool
    self_attention: bool
    down_sample: bool
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, temb, context=None, context_mask=None):
        cfg = self.config
        if self.self_attention:
            x = K3AttentionBlock(
                cfg.attention_head_dim, cfg.expansion_ratio, cfg.groups,
                dtype=self.dtype, name="attentions_0",
            )(x, temb)
        nb = cfg.layers_per_block
        for j in range(nb):
            x = K3ResNetBlock(
                self.out_channels, cfg.compression_ratio,
                groups=cfg.groups, dtype=self.dtype,
                name=f"resnets_in_{j}",
            )(x, temb)
            if self.cross:
                x = K3AttentionBlock(
                    cfg.attention_head_dim, cfg.expansion_ratio, cfg.groups,
                    dtype=self.dtype, name=f"attentions_{j + 1}",
                )(x, temb, context, context_mask)
            last = j == nb - 1
            up_res = (
                (None, None, False, None)
                if (last and self.down_sample)
                else (None, None, None, None)
            )
            x = K3ResNetBlock(
                self.out_channels, cfg.compression_ratio,
                up_resolutions=up_res, groups=cfg.groups, dtype=self.dtype,
                name=f"resnets_out_{j}",
            )(x, temb)
        return x


class K3UpBlock(nn.Module):
    """layers_per_block x (resnet_in -> [cross-attn] -> resnet_out) then
    [self-attn]; the first resnet_in's second sub-block transposed-
    upsamples when this level upsamples. Channel plan
    [(in+cat, in)] + [(in, in)]*(n-2) + [(in, out)], where resnet_in keeps
    the pair's input width and resnet_out moves to the pair's output."""

    config: K3UNetConfig
    in_channels: int  # the level's base width; the skip concat adds cat_dim
    out_channels: int
    cross: bool
    self_attention: bool
    up_sample: bool
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, temb, context=None, context_mask=None):
        cfg = self.config
        nb = cfg.layers_per_block
        base = self.in_channels
        pairs = (
            [(x.shape[-1], base)]
            + [(base, base)] * (nb - 2)
            + [(base, self.out_channels)]
        )
        for j, (ic, oc) in enumerate(pairs):
            up_res = (
                (None, True, None, None)
                if (j == 0 and self.up_sample)
                else (None, None, None, None)
            )
            x = K3ResNetBlock(
                ic, cfg.compression_ratio, up_resolutions=up_res,
                groups=cfg.groups, dtype=self.dtype, name=f"resnets_in_{j}",
            )(x, temb)
            if self.cross:
                x = K3AttentionBlock(
                    cfg.attention_head_dim, cfg.expansion_ratio, cfg.groups,
                    dtype=self.dtype, name=f"attentions_{j + 1}",
                )(x, temb, context, context_mask)
            x = K3ResNetBlock(
                oc, cfg.compression_ratio, groups=cfg.groups,
                dtype=self.dtype, name=f"resnets_out_{j}",
            )(x, temb)
        if self.self_attention:
            x = K3AttentionBlock(
                cfg.attention_head_dim, cfg.expansion_ratio, cfg.groups,
                dtype=self.dtype, name="attentions_0",
            )(x, temb)
        return x


class Kandinsky3UNet(nn.Module):
    """[B,H,W,4] latents + [B] timesteps + [B,S,encoder_hid_dim] T5 states
    (+ [B,S] 0/1 mask) -> [B,H,W,4] noise prediction."""

    config: K3UNetConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, sample, timesteps, encoder_hidden_states,
                 encoder_attention_mask=None):
        cfg = self.config
        n = len(cfg.block_out_channels)
        init_ch = cfg.init_channels

        temb_in = timestep_embedding(
            timesteps, init_ch, flip_sin_to_cos=False,
            downscale_freq_shift=1.0, dtype=self.dtype,
        )
        temb = TimestepEmbedding(
            cfg.time_embedding_dim, dtype=self.dtype, name="time_embedding"
        )(temb_in)

        context = K3EncoderProj(
            cfg.cross_attention_dim, dtype=self.dtype,
            name="encoder_hid_proj",
        )(jnp.asarray(encoder_hidden_states, self.dtype))
        temb = K3AttentionPooling(
            cfg.time_embedding_dim, cfg.attention_head_dim,
            dtype=self.dtype, name="add_time_condition",
        )(temb, context, encoder_attention_mask)

        x = nn.Conv(
            init_ch, (3, 3), dtype=self.dtype, name="conv_in"
        )(jnp.asarray(sample, self.dtype))

        hidden_dims = (init_ch,) + tuple(cfg.block_out_channels)
        skips = []
        for i in range(n):
            x = K3DownBlock(
                cfg,
                cfg.block_out_channels[i],
                cross=cfg.add_cross_attention[i],
                self_attention=cfg.add_self_attention[i],
                down_sample=i != n - 1,
                dtype=self.dtype,
                name=f"down_blocks_{i}",
            )(x, temb, context, encoder_attention_mask)
            if i != n - 1:
                skips.append(x)

        for lvl in range(n):
            i = n - 1 - lvl  # source level this up block mirrors
            if lvl != 0:
                x = jnp.concatenate([x, skips.pop()], axis=-1)
            x = K3UpBlock(
                cfg,
                in_channels=cfg.block_out_channels[i],
                out_channels=hidden_dims[i],
                cross=cfg.add_cross_attention[i],
                self_attention=cfg.add_self_attention[i],
                up_sample=lvl != 0,
                dtype=self.dtype,
                name=f"up_blocks_{lvl}",
            )(x, temb, context, encoder_attention_mask)

        x = FusedGroupNorm(
            cfg.groups, epsilon=1e-5, dtype=self.dtype, act="silu",
            name="conv_norm_out",
        )(x)
        return nn.Conv(
            cfg.in_channels, (3, 3), dtype=self.dtype, name="conv_out"
        )(x)
