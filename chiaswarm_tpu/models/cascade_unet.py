"""Stable Cascade (Wuerstchen v3) UNet — the TRUE architecture, NHWC flax.

One module class covers both released parameterisations, exactly as the
diffusers `StableCascadeUNet` does for the checkpoints the reference serves
via `StableCascadeDecoderPipeline` (/root/reference/swarm/diffusion/
pipeline_steps.py:70-90):

- stage C ("prior"): patch_size 1, two 2048-wide levels that never change
  spatial resolution (`switch_level=[False]` makes the down/upscalers plain
  1x1 convs), every layer = ResBlock + TimestepBlock + AttnBlock, text
  conditioning from pooled+sequence CLIP-bigG plus an (optional) image
  embed.
- stage B ("decoder"): patch_size 2, four levels (320/640/1280/1280) with
  strided-conv downscalers and transposed-conv upscalers, attention only in
  the two deep levels, conditioned on the stage-C latent through
  `effnet_mapper` and on pooled text only.

Blocks are ConvNeXt-style (depthwise conv -> LayerNorm -> wide GELU MLP
with a GlobalResponseNorm), NOT the SD ResNet/Transformer stack — which is
why this family gets its own module instead of UNet2DConditionModel.

Weight conversion + geometry inference live in models/conversion.py
(`convert_cascade_unet` / `infer_cascade_unet_config`); numeric parity vs
an exact-key torch mirror is tested in tests/test_cascade_conversion.py.
"""

from __future__ import annotations

import dataclasses
import math

import flax.linen as nn
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class CascadeUNetConfig:
    in_channels: int = 16
    out_channels: int = 16
    patch_size: int = 1
    timestep_ratio_embedding_dim: int = 64
    conditioning_dim: int = 2048
    block_out_channels: tuple[int, ...] = (2048, 2048)
    num_attention_heads: tuple[int, ...] = (32, 32)
    down_num_layers_per_block: tuple[int, ...] = (8, 24)
    up_num_layers_per_block: tuple[int, ...] = (24, 8)
    down_blocks_repeat_mappers: tuple[int, ...] = (1, 1)
    up_blocks_repeat_mappers: tuple[int, ...] = (1, 1)
    # per level: does each layer carry an AttnBlock (block types are always
    # ResBlock + TimestepBlock [+ AttnBlock] in the released configs)
    attention: tuple[bool, ...] = (True, True)
    clip_text_pooled_in_channels: int = 1280
    clip_text_in_channels: int = 0  # 0 = absent (stage B)
    clip_image_in_channels: int = 0  # 0 = absent (stage B)
    clip_seq: int = 4
    effnet_in_channels: int = 0  # stage B: 16 (the stage-C latent space)
    pixel_mapper_in_channels: int = 0  # stage B: 3 (semantic pixels, zeros)
    kernel_size: int = 3
    self_attn: bool = True
    timestep_conditioning_type: tuple[str, ...] = ("sca", "crp")
    # None -> strided-conv scalers (stage B); a tuple -> 1x1-conv scalers
    # with optional bilinear re-scale per boundary (stage C: (False,))
    switch_level: tuple[bool, ...] | None = None

    @property
    def t_embed_total(self) -> int:
        return self.timestep_ratio_embedding_dim * (
            1 + len(self.timestep_conditioning_type)
        )


# tiny hermetic-test parameterisations of both stages
TINY_CASCADE_C = CascadeUNetConfig(
    in_channels=16,
    out_channels=16,
    patch_size=1,
    timestep_ratio_embedding_dim=8,
    conditioning_dim=32,
    block_out_channels=(32, 32),
    num_attention_heads=(4, 4),
    down_num_layers_per_block=(1, 2),
    up_num_layers_per_block=(2, 1),
    down_blocks_repeat_mappers=(1, 2),
    up_blocks_repeat_mappers=(2, 1),
    attention=(True, True),
    clip_text_pooled_in_channels=16,
    clip_text_in_channels=16,
    clip_image_in_channels=12,
    clip_seq=2,
    timestep_conditioning_type=("sca", "crp"),
    switch_level=(False,),
)
TINY_CASCADE_B = CascadeUNetConfig(
    in_channels=4,
    out_channels=4,
    patch_size=2,
    timestep_ratio_embedding_dim=8,
    conditioning_dim=16,
    block_out_channels=(16, 32),
    num_attention_heads=(0, 4),
    down_num_layers_per_block=(1, 2),
    up_num_layers_per_block=(2, 1),
    down_blocks_repeat_mappers=(1, 1),
    up_blocks_repeat_mappers=(2, 1),
    attention=(False, True),
    clip_text_pooled_in_channels=16,
    effnet_in_channels=16,
    pixel_mapper_in_channels=3,
    clip_seq=2,
    timestep_conditioning_type=("sca",),
    switch_level=None,
)


def timestep_ratio_embedding(r, dim: int, max_positions: float = 10000.0):
    """Sinusoidal embedding of a [0,1] timestep RATIO (r * 1e4 positions)."""
    r = jnp.asarray(r, jnp.float32) * max_positions
    half = dim // 2
    emb = math.log(max_positions) / (half - 1)
    emb = jnp.exp(jnp.arange(half, dtype=jnp.float32) * -emb)
    emb = r[:, None] * emb[None, :]
    emb = jnp.concatenate([jnp.sin(emb), jnp.cos(emb)], axis=1)
    if dim % 2 == 1:
        emb = jnp.pad(emb, ((0, 0), (0, 1)))
    return emb


def _ln(x, dtype):
    """The family's LayerNorm: last-axis, no affine, eps 1e-6."""
    return nn.LayerNorm(
        epsilon=1e-6, use_scale=False, use_bias=False, dtype=dtype
    )(x)


def pixel_unshuffle(x, p: int):
    """NHWC space-to-depth with torch PixelUnshuffle channel order."""
    if p == 1:
        return x
    b, h, w, c = x.shape
    x = x.reshape(b, h // p, p, w // p, p, c)
    x = x.transpose(0, 1, 3, 5, 2, 4)  # [b, h/p, w/p, c, dy, dx]
    return x.reshape(b, h // p, w // p, c * p * p)


def pixel_shuffle(x, p: int):
    if p == 1:
        return x
    b, h, w, cpp = x.shape
    c = cpp // (p * p)
    x = x.reshape(b, h, w, c, p, p)
    x = x.transpose(0, 1, 4, 2, 5, 3)  # [b, h, dy, w, dx, c]
    return x.reshape(b, h * p, w * p, c)


def interpolate_bilinear_align_corners(x, out_h: int, out_w: int):
    """Bilinear resize with torch align_corners=True semantics (used for
    the effnet/pixels maps and the switch-level skip rescale; jax.image
    only offers half-pixel sampling)."""
    b, h, w, c = x.shape
    if h == out_h and w == out_w:
        return x

    def axis_weights(n_in, n_out):
        if n_out == 1 or n_in == 1:
            pos = jnp.zeros((n_out,), jnp.float32)
        else:
            pos = jnp.linspace(0.0, n_in - 1.0, n_out)
        lo = jnp.clip(jnp.floor(pos).astype(jnp.int32), 0, n_in - 1)
        hi = jnp.clip(lo + 1, 0, n_in - 1)
        frac = pos - lo.astype(jnp.float32)
        return lo, hi, frac

    ylo, yhi, yf = axis_weights(h, out_h)
    xlo, xhi, xf = axis_weights(w, out_w)
    top = x[:, ylo][:, :, xlo] * (1 - xf)[None, None, :, None] + x[:, ylo][
        :, :, xhi
    ] * xf[None, None, :, None]
    bot = x[:, yhi][:, :, xlo] * (1 - xf)[None, None, :, None] + x[:, yhi][
        :, :, xhi
    ] * xf[None, None, :, None]
    return top * (1 - yf)[None, :, None, None] + bot * yf[None, :, None, None]


class GlobalResponseNorm(nn.Module):
    """ConvNeXt-v2 GRN over NHWC (spatial L2 per channel, mean-normalised)."""

    dim: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        gamma = self.param("gamma", nn.initializers.zeros, (1, 1, 1, self.dim))
        beta = self.param("beta", nn.initializers.zeros, (1, 1, 1, self.dim))
        agg = jnp.sqrt(
            jnp.sum(jnp.square(x.astype(jnp.float32)), axis=(1, 2), keepdims=True)
        )
        stand = agg / (jnp.mean(agg, axis=-1, keepdims=True) + 1e-6)
        stand = stand.astype(x.dtype)
        return gamma.astype(x.dtype) * (x * stand) + beta.astype(x.dtype) + x


class CascadeResBlock(nn.Module):
    """depthwise conv -> LN -> [skip concat] -> Dense(4c) GELU GRN Dense."""

    channels: int
    kernel_size: int = 3
    c_skip: int = 0
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, x_skip=None):
        res = x
        k = self.kernel_size
        h = nn.Conv(
            self.channels,
            (k, k),
            padding=((k // 2, k // 2), (k // 2, k // 2)),
            feature_group_count=self.channels,
            dtype=self.dtype,
            name="depthwise",
        )(x)
        h = _ln(h, self.dtype)
        if x_skip is not None:
            h = jnp.concatenate([h, x_skip.astype(h.dtype)], axis=-1)
        h = nn.Dense(self.channels * 4, dtype=self.dtype, name="channelwise_0")(h)
        h = nn.gelu(h, approximate=False)
        h = GlobalResponseNorm(
            self.channels * 4, dtype=self.dtype, name="channelwise_2"
        )(h)
        h = nn.Dense(self.channels, dtype=self.dtype, name="channelwise_4")(h)
        return h + res


class CascadeTimestepBlock(nn.Module):
    """AdaLN-style scale/shift from the (chunked) timestep-ratio embedding."""

    channels: int
    conds: tuple[str, ...]
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, t_embed):
        chunks = jnp.split(t_embed, 1 + len(self.conds), axis=1)
        ab = nn.Dense(self.channels * 2, dtype=self.dtype, name="mapper")(chunks[0])
        a, b = jnp.split(ab, 2, axis=1)
        for i, cname in enumerate(self.conds):
            abc = nn.Dense(
                self.channels * 2, dtype=self.dtype, name=f"mapper_{cname}"
            )(chunks[i + 1])
            ac, bc = jnp.split(abc, 2, axis=1)
            a, b = a + ac, b + bc
        return x * (1 + a[:, None, None, :]) + b[:, None, None, :]


class CascadeAttnBlock(nn.Module):
    """LN -> attention where K/V = [image tokens (if self_attn)] + mapped
    conditioning tokens; biased q/k/v projections (diffusers Attention
    with bias=True)."""

    channels: int
    num_heads: int
    self_attn: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, kv):
        from ..ops import dot_product_attention

        b, h, w, c = x.shape
        kvm = nn.Dense(c, dtype=self.dtype, name="kv_mapper_1")(nn.silu(kv))
        nx = _ln(x, self.dtype).reshape(b, h * w, c)
        kv_full = jnp.concatenate([nx, kvm], axis=1) if self.self_attn else kvm

        head_dim = c // self.num_heads
        q = nn.Dense(c, dtype=self.dtype, name="attention_to_q")(nx)
        k = nn.Dense(c, dtype=self.dtype, name="attention_to_k")(kv_full)
        v = nn.Dense(c, dtype=self.dtype, name="attention_to_v")(kv_full)
        sk = kv_full.shape[1]
        out = dot_product_attention(
            q.reshape(b, h * w, self.num_heads, head_dim),
            k.reshape(b, sk, self.num_heads, head_dim),
            v.reshape(b, sk, self.num_heads, head_dim),
        ).reshape(b, h * w, c)
        out = nn.Dense(c, dtype=self.dtype, name="attention_to_out_0")(out)
        return x + out.reshape(b, h, w, c)


class ConvTransposed2D(nn.Module):
    """torch ConvTranspose2d equivalent (kernel k, stride s, padding p) via
    an input-dilated forward convolution. The kernel param is stored
    ALREADY flipped/transposed to [kh, kw, in, out] forward-conv layout
    (conversion.py does the flip), so apply is a plain conv."""

    features: int
    kernel_size: int
    stride: int
    padding: int = 0
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        import jax

        k, s, p = self.kernel_size, self.stride, self.padding
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (k, k, x.shape[-1], self.features),
        )
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        pad = k - 1 - p
        out = jax.lax.conv_general_dilated(
            x.astype(self.dtype),
            kernel.astype(self.dtype),
            window_strides=(1, 1),
            padding=((pad, pad), (pad, pad)),
            lhs_dilation=(s, s),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        return out + bias.astype(out.dtype)


class StableCascadeUNet(nn.Module):
    config: CascadeUNetConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        sample,  # [B, H, W, in_channels]
        timestep_ratio,  # [B] in [0, 1]
        clip_text_pooled,  # [B, S_p, pooled_in] (S_p usually 1)
        clip_text=None,  # [B, S, text_in] (stage C)
        clip_img=None,  # [B, S_i, img_in] (stage C)
        effnet=None,  # [B, ch, cw, effnet_in] stage-C latent (stage B)
        pixels=None,  # [B, 8, 8, 3] semantic pixels (stage B, zeros)
    ):
        cfg = self.config
        b = sample.shape[0]
        levels = len(cfg.block_out_channels)

        # --- timestep-ratio embedding (main + one chunk per conditioning) ---
        t_embed = timestep_ratio_embedding(
            timestep_ratio, cfg.timestep_ratio_embedding_dim
        )
        zero_cond = timestep_ratio_embedding(
            jnp.zeros_like(jnp.asarray(timestep_ratio, jnp.float32)),
            cfg.timestep_ratio_embedding_dim,
        )
        for _ in cfg.timestep_conditioning_type:
            t_embed = jnp.concatenate([t_embed, zero_cond], axis=1)
        t_embed = t_embed.astype(self.dtype)

        # --- CLIP conditioning tokens: [text, image, pooled] order ---
        ctp = nn.Dense(
            cfg.conditioning_dim * cfg.clip_seq,
            dtype=self.dtype,
            name="clip_txt_pooled_mapper",
        )(clip_text_pooled.astype(self.dtype))
        ctp = ctp.reshape(b, -1, cfg.conditioning_dim)
        if cfg.clip_text_in_channels and clip_text is not None:
            pieces = [
                nn.Dense(
                    cfg.conditioning_dim, dtype=self.dtype, name="clip_txt_mapper"
                )(clip_text.astype(self.dtype))
            ]
            if cfg.clip_image_in_channels:
                if clip_img is None:
                    clip_img = jnp.zeros(
                        (b, 1, cfg.clip_image_in_channels), self.dtype
                    )
                ci = nn.Dense(
                    cfg.conditioning_dim * cfg.clip_seq,
                    dtype=self.dtype,
                    name="clip_img_mapper",
                )(clip_img.astype(self.dtype))
                pieces.append(ci.reshape(b, -1, cfg.conditioning_dim))
            clip = jnp.concatenate(pieces + [ctp], axis=1)
        else:
            clip = ctp
        clip = _ln(clip, self.dtype)

        # --- input embedding: pixel-unshuffle + 1x1 conv + LN ---
        x = pixel_unshuffle(sample.astype(self.dtype), cfg.patch_size)
        x = nn.Conv(
            cfg.block_out_channels[0], (1, 1), dtype=self.dtype, name="embedding_1"
        )(x)
        x = _ln(x, self.dtype)

        if cfg.effnet_in_channels and effnet is not None:
            e = nn.Conv(
                cfg.block_out_channels[0] * 4,
                (1, 1),
                dtype=self.dtype,
                name="effnet_mapper_0",
            )(
                interpolate_bilinear_align_corners(
                    effnet.astype(self.dtype), x.shape[1], x.shape[2]
                )
            )
            e = nn.gelu(e, approximate=False)
            e = nn.Conv(
                cfg.block_out_channels[0],
                (1, 1),
                dtype=self.dtype,
                name="effnet_mapper_2",
            )(e)
            x = x + _ln(e, self.dtype)
        if cfg.pixel_mapper_in_channels:
            if pixels is None:
                pixels = jnp.zeros((b, 8, 8, cfg.pixel_mapper_in_channels))
            p = nn.Conv(
                cfg.block_out_channels[0] * 4,
                (1, 1),
                dtype=self.dtype,
                name="pixels_mapper_0",
            )(pixels.astype(self.dtype))
            p = nn.gelu(p, approximate=False)
            p = nn.Conv(
                cfg.block_out_channels[0],
                (1, 1),
                dtype=self.dtype,
                name="pixels_mapper_2",
            )(p)
            x = x + interpolate_bilinear_align_corners(
                _ln(p, self.dtype), x.shape[1], x.shape[2]
            )

        def level_blocks(prefix, level, n_layers, c_skip_first):
            """Build the flattened per-level block list (matching the torch
            ModuleList flattening) as (kind, module) pairs."""
            ch = cfg.block_out_channels[level]
            blocks = []
            idx = 0
            for layer in range(n_layers):
                c_skip = c_skip_first if layer == 0 else 0
                blocks.append(
                    (
                        "res",
                        CascadeResBlock(
                            ch,
                            cfg.kernel_size,
                            c_skip=c_skip,
                            dtype=self.dtype,
                            name=f"{prefix}_{idx}",
                        ),
                    )
                )
                idx += 1
                blocks.append(
                    (
                        "time",
                        CascadeTimestepBlock(
                            ch,
                            cfg.timestep_conditioning_type,
                            dtype=self.dtype,
                            name=f"{prefix}_{idx}",
                        ),
                    )
                )
                idx += 1
                if cfg.attention[level]:
                    blocks.append(
                        (
                            "attn",
                            CascadeAttnBlock(
                                ch,
                                cfg.num_attention_heads[level],
                                self_attn=cfg.self_attn,
                                dtype=self.dtype,
                                name=f"{prefix}_{idx}",
                            ),
                        )
                    )
                    idx += 1
            return blocks

        def run_blocks(blocks, x, skip=None):
            first = True
            for kind, mod in blocks:
                if kind == "res":
                    s = skip if first else None
                    if s is not None and (
                        x.shape[1] != s.shape[1] or x.shape[2] != s.shape[2]
                    ):
                        x = interpolate_bilinear_align_corners(
                            x, s.shape[1], s.shape[2]
                        )
                    x = mod(x, s)
                    first = False
                elif kind == "time":
                    x = mod(x, t_embed)
                else:
                    x = mod(x, clip)
            return x

        # --- down path ---
        level_outputs = []
        for i in range(levels):
            if i > 0:
                x = _ln(x, self.dtype)
                if cfg.switch_level is not None:
                    # 1x1 mapping conv, then optional bilinear downscale
                    x = nn.Conv(
                        cfg.block_out_channels[i],
                        (1, 1),
                        dtype=self.dtype,
                        name=f"down_downscalers_{i}_1",
                    )(x)
                    if cfg.switch_level[i - 1]:
                        x = interpolate_bilinear_align_corners(
                            x, x.shape[1] // 2, x.shape[2] // 2
                        )
                else:
                    # torch Conv2d(k=2, s=2) has padding=0: VALID, so odd
                    # grids floor (flax SAME would zero-pad and diverge)
                    x = nn.Conv(
                        cfg.block_out_channels[i],
                        (2, 2),
                        strides=(2, 2),
                        padding="VALID",
                        dtype=self.dtype,
                        name=f"down_downscalers_{i}_1",
                    )(x)
            blocks = level_blocks(
                f"down_blocks_{i}", i, cfg.down_num_layers_per_block[i], 0
            )
            n_rep = cfg.down_blocks_repeat_mappers[i]
            for r in range(n_rep):
                x = run_blocks(blocks, x)
                if r < n_rep - 1:
                    x = nn.Conv(
                        cfg.block_out_channels[i],
                        (1, 1),
                        dtype=self.dtype,
                        name=f"down_repeat_mappers_{i}_{r}",
                    )(x)
            level_outputs.insert(0, x)

        # --- up path (enumeration 0 = deepest level) ---
        x = level_outputs[0]
        for j in range(levels):
            i = levels - 1 - j  # original level index
            c_skip = cfg.block_out_channels[i] if j > 0 else 0
            blocks = level_blocks(
                f"up_blocks_{j}", i, cfg.up_num_layers_per_block[j], c_skip
            )
            skip = level_outputs[j] if j > 0 else None
            n_rep = cfg.up_blocks_repeat_mappers[j]
            for r in range(n_rep):
                x = run_blocks(blocks, x, skip=skip)
                if r < n_rep - 1:
                    x = nn.Conv(
                        cfg.block_out_channels[i],
                        (1, 1),
                        dtype=self.dtype,
                        name=f"up_repeat_mappers_{j}_{r}",
                    )(x)
            if i > 0:
                x = _ln(x, self.dtype)
                if cfg.switch_level is not None:
                    if cfg.switch_level[i - 1]:
                        x = interpolate_bilinear_align_corners(
                            x, x.shape[1] * 2, x.shape[2] * 2
                        )
                    x = nn.Conv(
                        cfg.block_out_channels[i - 1],
                        (1, 1),
                        dtype=self.dtype,
                        name=f"up_upscalers_{j}_1",
                    )(x)
                else:
                    x = ConvTransposed2D(
                        cfg.block_out_channels[i - 1],
                        kernel_size=2,
                        stride=2,
                        dtype=self.dtype,
                        name=f"up_upscalers_{j}_1",
                    )(x)

        # --- classifier head: LN + 1x1 conv + pixel-shuffle ---
        x = _ln(x, self.dtype)
        x = nn.Conv(
            cfg.out_channels * cfg.patch_size**2,
            (1, 1),
            dtype=self.dtype,
            name="clf_1",
        )(x)
        return pixel_shuffle(x, cfg.patch_size)
