"""ControlNet (Zhang et al.) as a flax module over the shared UNet blocks.

Replaces the reference's per-job `ControlNetModel.from_pretrained`
(swarm/diffusion/diffusion_func.py:52-73). The control branch copies the
UNet's down/mid path, embeds the conditioning image through a small conv
stack, and emits zero-initialized 1x1-conv residuals that are added to the
main UNet's skip connections — so an unconverted (random/zero) ControlNet
is exactly a no-op on the base model, which the tests rely on.

Weight layout mirrors HF `ControlNetModel` for mechanical conversion.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from .layers import TimestepEmbedding, timestep_embedding
from .unet2d import CrossAttnDownBlock, UNet2DConfig, UNetMidBlock


class ControlNetConditioningEmbedding(nn.Module):
    """Control image [B, H, W, 3] -> feature map at latent resolution.

    `downscale` must equal the VAE's spatial factor (8 for SD-family, where
    the channel ramp 16->32->96->256 matches HF; smaller for tiny test VAEs,
    where the ramp truncates).
    """

    out_channels: int
    downscale: int = 8
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, cond):
        n_down = max((self.downscale - 1).bit_length(), 1)  # log2, >= 1
        block_channels = ((16, 32, 96, 256) * 2)[: n_down + 1]
        x = nn.Conv(
            block_channels[0], (3, 3), padding=((1, 1), (1, 1)),
            dtype=self.dtype, name="conv_in",
        )(cond)
        x = nn.silu(x)
        for i in range(len(block_channels) - 1):
            x = nn.Conv(
                block_channels[i], (3, 3), padding=((1, 1), (1, 1)),
                dtype=self.dtype, name=f"blocks_{2 * i}",
            )(x)
            x = nn.silu(x)
            x = nn.Conv(
                block_channels[i + 1], (3, 3), strides=(2, 2),
                padding=((1, 1), (1, 1)), dtype=self.dtype,
                name=f"blocks_{2 * i + 1}",
            )(x)
            x = nn.silu(x)
        # zero conv: starts as identity-off
        return nn.Conv(
            self.out_channels, (3, 3), padding=((1, 1), (1, 1)),
            kernel_init=nn.initializers.zeros, bias_init=nn.initializers.zeros,
            dtype=self.dtype, name="conv_out",
        )(x)


def _zero_conv(channels, dtype, name):
    return nn.Conv(
        channels, (1, 1), kernel_init=nn.initializers.zeros,
        bias_init=nn.initializers.zeros, dtype=dtype, name=name,
    )


class ControlNetModel(nn.Module):
    """Down+mid copy of the UNet emitting per-skip residuals.

    __call__(sample, timesteps, encoder_hidden_states, controlnet_cond,
    conditioning_scale) -> (down_residuals tuple, mid_residual).
    """

    config: UNet2DConfig
    cond_downscale: int = 8  # = the paired VAE's spatial latent factor
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, sample, timesteps, encoder_hidden_states, controlnet_cond,
                 conditioning_scale=1.0, added_cond=None):
        cfg = self.config
        if jnp.ndim(timesteps) == 0:
            timesteps = jnp.broadcast_to(timesteps, (sample.shape[0],))

        temb_dim = cfg.block_out_channels[0] * 4
        t_feat = timestep_embedding(
            timesteps,
            cfg.block_out_channels[0],
            flip_sin_to_cos=cfg.flip_sin_to_cos,
            downscale_freq_shift=cfg.freq_shift,
            dtype=self.dtype,
        )
        temb = TimestepEmbedding(temb_dim, dtype=self.dtype, name="time_embedding")(
            t_feat
        )

        if cfg.addition_embed_dim and added_cond is not None:
            tid_feat = timestep_embedding(
                added_cond["time_ids"].reshape(-1),
                cfg.addition_time_embed_dim,
                flip_sin_to_cos=cfg.flip_sin_to_cos,
                downscale_freq_shift=cfg.freq_shift,
                dtype=self.dtype,
            ).reshape(sample.shape[0], -1)
            add_feat = jnp.concatenate([added_cond["text_embeds"], tid_feat], axis=-1)
            temb = temb + TimestepEmbedding(
                temb_dim, dtype=self.dtype, name="add_embedding"
            )(add_feat)

        x = nn.Conv(
            cfg.block_out_channels[0], (3, 3), padding=((1, 1), (1, 1)),
            dtype=self.dtype, name="conv_in",
        )(sample)
        x = x + ControlNetConditioningEmbedding(
            cfg.block_out_channels[0], downscale=self.cond_downscale,
            dtype=self.dtype, name="controlnet_cond_embedding",
        )(controlnet_cond)

        heads = cfg.heads_per_block()
        skips = [x]
        for b, out_ch in enumerate(cfg.block_out_channels):
            last = b == len(cfg.block_out_channels) - 1
            x, block_skips = CrossAttnDownBlock(
                cfg,
                out_ch,
                cfg.transformer_layers[b],
                heads[b],
                add_downsample=not last,
                dtype=self.dtype,
                name=f"down_blocks_{b}",
            )(x, temb, encoder_hidden_states)
            skips.extend(block_skips)

        x = UNetMidBlock(
            cfg,
            cfg.block_out_channels[-1],
            cfg.mid_transformer_layers,
            heads[-1],
            dtype=self.dtype,
            name="mid_block",
        )(x, temb, encoder_hidden_states)

        down_res = tuple(
            _zero_conv(s.shape[-1], self.dtype, f"controlnet_down_blocks_{i}")(s)
            * conditioning_scale
            for i, s in enumerate(skips)
        )
        mid_res = (
            _zero_conv(x.shape[-1], self.dtype, "controlnet_mid_block")(x)
            * conditioning_scale
        )
        return down_res, mid_res
