"""Stable-Diffusion safety checker: CLIP vision tower + concept embeddings.

Reference behavior replaced: the diffusers pipelines' bundled
StableDiffusionSafetyChecker whose `nsfw_content_detected` the reference
propagates into the result envelope (swarm/post_processors/
output_processor.py:174-192, swarm/worker.py:166). Round 1 shipped the
envelope flag but no detector (VERDICT weak #9).

Structure: CLIP ViT image encoder (pre-LN, quick-gelu MLPs) -> visual
projection -> cosine scores against fixed concept / special-care
embeddings with per-concept thresholds; special-care hits tighten the
concept thresholds (the checkpoint's semantics).
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp

from .clip import _act


@dataclasses.dataclass(frozen=True)
class SafetyConfig:
    image_size: int = 224
    patch_size: int = 14
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    projection_dim: int = 768
    num_concepts: int = 17
    num_special: int = 3
    # ViT-L towers (safety checker) use quick_gelu; ViT-H (SVD's image
    # encoder, which reuses this tower standalone) uses erf gelu
    hidden_act: str = "quick_gelu"


TINY_SAFETY = SafetyConfig(
    image_size=32, patch_size=8, hidden_size=32, num_layers=2, num_heads=4,
    projection_dim=16, num_concepts=4, num_special=2,
)


class CLIPVisionEncoder(nn.Module):
    config: SafetyConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, pixels):
        """[B, H, W, 3] normalized -> projected image embeds [B, P]."""
        cfg = self.config
        x = nn.Conv(
            cfg.hidden_size, (cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size), use_bias=False,
            dtype=self.dtype, name="patch_embed",
        )(pixels)
        b, gh, gw, _ = x.shape
        x = x.reshape(b, gh * gw, cfg.hidden_size)
        cls = self.param(
            "cls_embed", nn.initializers.normal(0.02), (cfg.hidden_size,)
        ).astype(self.dtype)
        x = jnp.concatenate(
            [jnp.broadcast_to(cls[None, None], (b, 1, cfg.hidden_size)), x],
            axis=1,
        )
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02),
            (gh * gw + 1, cfg.hidden_size),
        ).astype(self.dtype)
        x = x + pos[None]
        x = nn.LayerNorm(dtype=self.dtype, name="pre_ln")(x)
        hd = cfg.hidden_size // cfg.num_heads
        for i in range(cfg.num_layers):
            blk = f"layer_{i}"
            y = nn.LayerNorm(dtype=self.dtype, name=f"{blk}_ln1")(x)
            q = nn.Dense(cfg.hidden_size, dtype=self.dtype, name=f"{blk}_q")(y)
            k = nn.Dense(cfg.hidden_size, dtype=self.dtype, name=f"{blk}_k")(y)
            v = nn.Dense(cfg.hidden_size, dtype=self.dtype, name=f"{blk}_v")(y)
            s = y.shape[1]
            q, k, v = (t.reshape(b, s, cfg.num_heads, hd) for t in (q, k, v))
            from ..ops import dot_product_attention

            attn = dot_product_attention(q, k, v).reshape(b, s, cfg.hidden_size)
            x = x + nn.Dense(
                cfg.hidden_size, dtype=self.dtype, name=f"{blk}_out"
            )(attn)
            y = nn.LayerNorm(dtype=self.dtype, name=f"{blk}_ln2")(x)
            y = nn.Dense(4 * cfg.hidden_size, dtype=self.dtype,
                         name=f"{blk}_fc1")(y)
            y = _act(cfg.hidden_act)(y)
            x = x + nn.Dense(cfg.hidden_size, dtype=self.dtype,
                             name=f"{blk}_fc2")(y)
        pooled = nn.LayerNorm(dtype=self.dtype, name="post_ln")(x[:, 0])
        return nn.Dense(
            cfg.projection_dim, use_bias=False, dtype=self.dtype,
            name="projection",
        )(pooled)


class SafetyChecker(nn.Module):
    """Full checker: vision embed -> per-image NSFW boolean."""

    config: SafetyConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, pixels):
        cfg = self.config
        embeds = CLIPVisionEncoder(cfg, dtype=self.dtype, name="vision")(pixels)
        concept = self.param(
            "concept_embeds", nn.initializers.normal(1.0),
            (cfg.num_concepts, cfg.projection_dim),
        )
        special = self.param(
            "special_care_embeds", nn.initializers.normal(1.0),
            (cfg.num_special, cfg.projection_dim),
        )
        concept_w = self.param(
            "concept_embeds_weights", nn.initializers.constant(0.5),
            (cfg.num_concepts,),
        )
        special_w = self.param(
            "special_care_embeds_weights", nn.initializers.constant(0.5),
            (cfg.num_special,),
        )

        def cos(a, b):
            a = a / (jnp.linalg.norm(a, axis=-1, keepdims=True) + 1e-8)
            b = b / (jnp.linalg.norm(b, axis=-1, keepdims=True) + 1e-8)
            return a @ b.T

        e = embeds.astype(jnp.float32)
        special_scores = cos(e, special.astype(jnp.float32)) - special_w
        # a special-care hit tightens every concept threshold by 0.01
        # (checkpoint semantics; diffusers' `adjustment`)
        adjustment = jnp.where(
            jnp.any(special_scores > 0, axis=-1, keepdims=True), 0.01, 0.0
        )
        concept_scores = (
            cos(e, concept.astype(jnp.float32)) - concept_w + adjustment
        )
        return jnp.any(concept_scores > 0, axis=-1)
