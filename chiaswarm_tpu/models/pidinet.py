"""PiDiNet soft-edge detector (table5_pidinet, 'carv4' config) — the
learned annotator behind the `softedge` preprocessor.

Reference behavior replaced: swarm/pre_processors/controlnet.py:56-57
(controlnet_aux PidiNetDetector fetched per call). The graph is four
stages of pixel-difference-convolution blocks (depthwise 3x3/5x5 +
pointwise, residual, maxpool+1x1-shortcut on stride), each stage refined
by a compact dilation module (CDCM: 4 parallel dilated 3x3) and spatial
attention (CSAM), reduced to a 1-channel edge logit, bilinearly upsampled
to the input canvas, and fused by a 1x1 classifier; every map exits
through a sigmoid.

The checkpoint stores RAW pixel-difference kernels; conversion
(models/conversion.py convert_pidinet) re-parameterizes cd/ad/rd kernels
into equivalent vanilla convs (the authors' published convert_pdc math),
so this flax graph is plain convs.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

# the released table5_pidinet config: (cd, ad, rd, cv) per stage
CARV4 = ("cd", "ad", "rd", "cv") * 4
STAGE_PLANES = (60, 120, 240, 240)
DIL = 24


class _PDCBlock(nn.Module):
    """Converted PDC block: [maxpool + 1x1 shortcut on stride] depthwise
    conv (5x5 for rd, 3x3 otherwise) -> relu -> pointwise, residual."""

    pdc: str
    out_channels: int
    stride: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        in_ch = x.shape[-1]
        if self.stride > 1:
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        k = 5 if self.pdc == "rd" else 3
        p = k // 2
        y = nn.Conv(
            in_ch, (k, k), padding=((p, p), (p, p)),
            feature_group_count=in_ch, use_bias=False, dtype=self.dtype,
            name="conv1",
        )(x)
        y = nn.relu(y)
        y = nn.Conv(
            self.out_channels, (1, 1), use_bias=False, dtype=self.dtype,
            name="conv2",
        )(y)
        if self.stride > 1:
            x = nn.Conv(
                self.out_channels, (1, 1), dtype=self.dtype, name="shortcut"
            )(x)
        return y + x


class _CDCM(nn.Module):
    """Compact dilation module: 1x1 then four parallel dilated 3x3
    (dilations 5/7/9/11), summed."""

    out_channels: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = nn.relu(x)
        x = nn.Conv(self.out_channels, (1, 1), dtype=self.dtype,
                    name="conv1")(x)
        out = 0
        for i, d in enumerate((5, 7, 9, 11)):
            out = out + nn.Conv(
                self.out_channels, (3, 3), padding=((d, d), (d, d)),
                kernel_dilation=(d, d), use_bias=False, dtype=self.dtype,
                name=f"conv2_{i + 1}",
            )(x)
        return out


class _CSAM(nn.Module):
    """Compact spatial attention: 1x1 -> 3x3 -> sigmoid gate."""

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        y = nn.relu(x)
        y = nn.Conv(4, (1, 1), dtype=self.dtype, name="conv1")(y)
        y = nn.Conv(1, (3, 3), padding=((1, 1), (1, 1)), use_bias=False,
                    dtype=self.dtype, name="conv2")(y)
        return x * nn.sigmoid(y)


class PiDiNet(nn.Module):
    """[B, H, W, 3] in [0, 1] -> [B, H, W, 1] fused edge probability
    (the last of upstream's five sigmoid outputs)."""

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, h, w, _ = x.shape
        x = nn.Conv(
            STAGE_PLANES[0], (3, 3), padding=((1, 1), (1, 1)),
            use_bias=False, dtype=self.dtype, name="init_block",
        )(jnp.asarray(x, self.dtype))
        stage_outs = []
        for s in range(4):
            n_blocks = 3 if s == 0 else 4
            for j in range(n_blocks):
                # stage 0's first pdc layer IS the init conv above, so its
                # blocks cover pdc layers 1..3; stage s>0 covers 4s..4s+3
                # and starts with a strided block
                layer = j + 1 if s == 0 else s * 4 + j
                x = _PDCBlock(
                    CARV4[layer], STAGE_PLANES[s],
                    stride=2 if (s > 0 and j == 0) else 1,
                    dtype=self.dtype,
                    name=f"block{s + 1}_{j + 1}",
                )(x)
            stage_outs.append(x)

        logits = []
        for i, xi in enumerate(stage_outs):
            y = _CDCM(DIL, dtype=self.dtype, name=f"dilations_{i}")(xi)
            y = _CSAM(dtype=self.dtype, name=f"attentions_{i}")(y)
            y = nn.Conv(1, (1, 1), dtype=self.dtype,
                        name=f"conv_reduces_{i}")(y)
            logits.append(
                jax.image.resize(
                    y.astype(jnp.float32), (b, h, w, 1), "bilinear"
                )
            )
        fused = nn.Conv(1, (1, 1), dtype=self.dtype, name="classifier")(
            jnp.concatenate(logits, axis=-1).astype(self.dtype)
        )
        return nn.sigmoid(fused.astype(jnp.float32))
