"""LineArt generator (informative-drawings `Generator`) — the learned
annotator behind the `lineart` preprocessor.

Reference behavior replaced: swarm/pre_processors/controlnet.py:43
(controlnet_aux LineartDetector, sk_model.pth / sk_model2.pth coarse).
The graph is a compact image-to-sketch translator: reflect-padded 7x7
stem, two stride-2 downsamples, three residual blocks, two transposed-
conv upsamples, a 7x7 head with sigmoid — every norm an InstanceNorm
(affine-free, so the checkpoint carries only conv weights).

The two ConvTranspose2d(3, stride 2, padding 1, output_padding 1) layers
convert at load into equivalent input-dilated convs (kernel flipped,
asymmetric (1,2) padding), so the flax graph is pure convs
(models/conversion.py convert_lineart owns the mapping).
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class LineartConfig:
    base_channels: int = 64
    n_residual_blocks: int = 3


TINY_LINEART = LineartConfig(base_channels=8, n_residual_blocks=1)


def instance_norm(x, eps: float = 1e-5):
    """torch InstanceNorm2d(affine=False): per-sample per-channel spatial
    standardization (biased variance, matching torch)."""
    mean = jnp.mean(x, axis=(1, 2), keepdims=True)
    var = jnp.var(x, axis=(1, 2), keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps)


def _reflect_conv(x, features, kernel, pad, dtype, name):
    x = jnp.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)), mode="reflect")
    return nn.Conv(features, (kernel, kernel), padding="VALID",
                   dtype=dtype, name=name)(x)


class _UpConv(nn.Module):
    """ConvTranspose2d(3, stride=2, padding=1, output_padding=1) as an
    input-dilated conv; the kernel arrives pre-flipped from conversion."""

    features: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (3, 3, x.shape[-1], self.features),
        )
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        y = jax.lax.conv_general_dilated(
            x.astype(self.dtype), jnp.asarray(kernel, self.dtype),
            (1, 1), ((1, 2), (1, 2)), lhs_dilation=(2, 2),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        return y + jnp.asarray(bias, self.dtype)


class LineartGenerator(nn.Module):
    """[B, H, W, 3] in [0, 1] -> [B, H, W, 1] sketch probability (dark
    strokes near 0 on a white ~1 page, before the caller inverts)."""

    config: LineartConfig = LineartConfig()
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        c = cfg.base_channels
        x = jnp.asarray(x, self.dtype)
        x = _reflect_conv(x, c, 7, 3, self.dtype, "model0_conv")
        x = nn.relu(instance_norm(x))
        x = nn.Conv(2 * c, (3, 3), strides=(2, 2),
                    padding=((1, 1), (1, 1)), dtype=self.dtype,
                    name="model1_conv0")(x)
        x = nn.relu(instance_norm(x))
        x = nn.Conv(4 * c, (3, 3), strides=(2, 2),
                    padding=((1, 1), (1, 1)), dtype=self.dtype,
                    name="model1_conv1")(x)
        x = nn.relu(instance_norm(x))
        for i in range(cfg.n_residual_blocks):
            h = _reflect_conv(x, 4 * c, 3, 1, self.dtype,
                              f"res_{i}_conv0")
            h = nn.relu(instance_norm(h))
            h = _reflect_conv(h, 4 * c, 3, 1, self.dtype,
                              f"res_{i}_conv1")
            x = x + instance_norm(h)
        x = _UpConv(2 * c, dtype=self.dtype, name="model3_conv0")(x)
        x = nn.relu(instance_norm(x))
        x = _UpConv(c, dtype=self.dtype, name="model3_conv1")(x)
        x = nn.relu(instance_norm(x))
        x = _reflect_conv(x, 1, 7, 3, self.dtype, "model4_conv")
        return nn.sigmoid(x)
