"""UperNet semantic segmentation (ConvNeXt backbone), flax/NHWC.

The reference's `segmentation` ControlNet annotator runs
UperNetForSemanticSegmentation (openmmlab/upernet-convnext-small) over
ADE20K (reference swarm/pre_processors/controlnet.py:122-141). This is
the real graph rebuilt TPU-first: ConvNeXt stages (depthwise 7x7 +
channels-last LN + pointwise MLP + layer scale — all MXU/VPU friendly in
NHWC), PSP pyramid pooling, FPN top-down fusion, pixel classifier.

BatchNorms in the UperNet conv modules fold into the conv kernels at
conversion time (conversion.convert_upernet), so runtime is conv+ReLU.
Numeric parity vs transformers' UperNetForSemanticSegmentation is
asserted in tests/test_segmentation_conversion.py.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class UperNetConfig:
    depths: tuple[int, ...] = (3, 3, 27, 3)  # convnext-small
    hidden_sizes: tuple[int, ...] = (96, 192, 384, 768)
    hidden_size: int = 512  # decode head channels
    num_labels: int = 150  # ADE20K
    pool_scales: tuple[int, ...] = (1, 2, 3, 6)
    layer_norm_eps: float = 1e-6


TINY_UPERNET = UperNetConfig(
    depths=(1, 1, 1, 1), hidden_sizes=(8, 16, 24, 32), hidden_size=16,
    num_labels=5,
)


def upernet_config_from_json(config_json: dict | None) -> UperNetConfig:
    """The ONE config.json parse shared by the resident Segmenter and
    `initialize --check`, so verify and serving cannot drift."""
    cfg = UperNetConfig()
    cj = config_json or {}
    bb = cj.get("backbone_config", {})
    return UperNetConfig(
        depths=tuple(bb.get("depths", cfg.depths)),
        hidden_sizes=tuple(bb.get("hidden_sizes", cfg.hidden_sizes)),
        hidden_size=int(cj.get("hidden_size", cfg.hidden_size)),
        num_labels=int(cj.get("num_labels", cfg.num_labels)),
        pool_scales=tuple(cj.get("pool_scales", cfg.pool_scales)),
    )


def _ln(x, scale, bias, eps):
    mean = x.mean(axis=-1, keepdims=True)
    var = ((x - mean) ** 2).mean(axis=-1, keepdims=True)
    return (x - mean) / jnp.sqrt(var + eps) * scale + bias


class _ChannelsLN(nn.Module):
    """LayerNorm over the channel axis of an NHWC map (torch's
    ConvNextLayerNorm data_format=channels_first, transposed)."""

    eps: float = 1e-6
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (c,))
        bias = self.param("bias", nn.initializers.zeros, (c,))
        return _ln(
            x, jnp.asarray(scale, x.dtype), jnp.asarray(bias, x.dtype),
            self.eps,
        )


class _ConvNextLayer(nn.Module):
    dim: int
    eps: float
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.Conv(
            self.dim, (7, 7), padding=((3, 3), (3, 3)),
            feature_group_count=self.dim, dtype=self.dtype, name="dwconv",
        )(x)
        h = _ChannelsLN(self.eps, dtype=self.dtype, name="norm")(h)
        h = nn.Dense(4 * self.dim, dtype=self.dtype, name="pwconv1")(h)
        h = nn.gelu(h, approximate=False)
        h = nn.Dense(self.dim, dtype=self.dtype, name="pwconv2")(h)
        gamma = self.param(
            "layer_scale", nn.initializers.ones, (self.dim,)
        )
        return x + h * jnp.asarray(gamma, h.dtype)


class _ConvRelu(nn.Module):
    """UperNetConvModule with the BatchNorm folded into the conv."""

    channels: int
    kernel: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        p = self.kernel // 2
        return nn.relu(
            nn.Conv(
                self.channels, (self.kernel, self.kernel),
                padding=((p, p), (p, p)), dtype=self.dtype, name="conv",
            )(x)
        )


def _adaptive_avg_pool(x, out: int):
    """torch AdaptiveAvgPool2d semantics: per-cell windows
    [floor(i*H/out), ceil((i+1)*H/out))."""
    b, h, w, c = x.shape
    rows = []
    for i in range(out):
        h0, h1 = (i * h) // out, -(-((i + 1) * h) // out)
        cols = []
        for j in range(out):
            w0, w1 = (j * w) // out, -(-((j + 1) * w) // out)
            cols.append(x[:, h0:h1, w0:w1].mean(axis=(1, 2)))
        rows.append(jnp.stack(cols, axis=1))
    return jnp.stack(rows, axis=1)  # [B, out, out, C]


def _resize(x, hw):
    return jax.image.resize(
        x, (x.shape[0], hw[0], hw[1], x.shape[-1]), "bilinear"
    )


class UperNetSegmenter(nn.Module):
    config: UperNetConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, pixels):
        """[B, H, W, 3] (normalized) -> logits [B, H, W, num_labels]."""
        cfg = self.config
        eps = cfg.layer_norm_eps

        x = nn.Conv(
            cfg.hidden_sizes[0], (4, 4), strides=(4, 4), dtype=self.dtype,
            name="patch_embeddings",
        )(pixels)
        x = _ChannelsLN(eps, dtype=self.dtype, name="embeddings_norm")(x)

        feats = []
        for s, (depth, dim) in enumerate(zip(cfg.depths, cfg.hidden_sizes)):
            if s > 0:
                x = _ChannelsLN(
                    eps, dtype=self.dtype, name=f"downsample_norm_{s}"
                )(x)
                x = nn.Conv(
                    dim, (2, 2), strides=(2, 2), dtype=self.dtype,
                    name=f"downsample_conv_{s}",
                )(x)
            for j in range(depth):
                x = _ConvNextLayer(
                    dim, eps, dtype=self.dtype, name=f"stage_{s}_layer_{j}"
                )(x)
            feats.append(
                _ChannelsLN(eps, dtype=self.dtype, name=f"feature_norm_{s}")(x)
            )

        # PSP over the top feature
        top = feats[-1]
        hw = top.shape[1:3]
        psp = [top]
        for k, scale in enumerate(cfg.pool_scales):
            pooled = _adaptive_avg_pool(top, scale)
            pooled = _ConvRelu(
                cfg.hidden_size, 1, dtype=self.dtype, name=f"psp_{k}"
            )(pooled)
            psp.append(_resize(pooled, hw))
        psp_out = _ConvRelu(
            cfg.hidden_size, 3, dtype=self.dtype, name="bottleneck"
        )(jnp.concatenate(psp, axis=-1))

        # FPN top-down
        laterals = [
            _ConvRelu(cfg.hidden_size, 1, dtype=self.dtype, name=f"lateral_{i}")(
                feats[i]
            )
            for i in range(len(feats) - 1)
        ] + [psp_out]
        for i in range(len(laterals) - 1, 0, -1):
            laterals[i - 1] = laterals[i - 1] + _resize(
                laterals[i], laterals[i - 1].shape[1:3]
            )
        outs = [
            _ConvRelu(cfg.hidden_size, 3, dtype=self.dtype, name=f"fpn_{i}")(
                laterals[i]
            )
            for i in range(len(laterals) - 1)
        ] + [laterals[-1]]
        size0 = outs[0].shape[1:3]
        outs = [outs[0]] + [_resize(o, size0) for o in outs[1:]]
        fused = _ConvRelu(
            cfg.hidden_size, 3, dtype=self.dtype, name="fpn_bottleneck"
        )(jnp.concatenate(outs, axis=-1))
        logits = nn.Conv(
            cfg.num_labels, (1, 1), dtype=self.dtype, name="classifier"
        )(fused)
        return _resize(
            logits.astype(jnp.float32), pixels.shape[1:3]
        )
