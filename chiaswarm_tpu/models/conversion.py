"""HF torch checkpoint -> Flax param tree conversion.

The #1 hard part per SURVEY §7: diffusers/transformers safetensors state
dicts (NCHW convs, [out,in] linears, dotted names) map onto the NHWC flax
modules in this package. Module naming in unet2d/vae/clip deliberately
mirrors the HF graph so the mapping is mechanical:

  torch `down_blocks.0.resnets.1.conv1.weight` [O,I,kh,kw]
    -> flax params["down_blocks_0"]["resnets_1"]["conv1"]["kernel"] [kh,kw,I,O]

Rules:
- conv weight (4d): transpose OIHW -> HWIO
- linear weight (2d): transpose [O,I] -> [I,O]
- norm weight/bias: -> scale/bias
- embeddings: kept as-is ([V, D])
- flax GroupNorm/LayerNorm: weight -> scale

Works from a flat `{name: np.ndarray}` dict, so the source can be
safetensors files, torch .bin (via torch.load), or a synthetic test dict.
"""

from __future__ import annotations

import logging
from pathlib import Path

import numpy as np

logger = logging.getLogger(__name__)


def load_torch_state_dict(model_dir: str | Path, subfolder: str = "") -> dict:
    """Flat numpy state dict from safetensors file(s) under model_dir."""
    from safetensors import safe_open

    root = Path(model_dir) / subfolder
    files = sorted(root.glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"no safetensors under {root}")
    state = {}
    for f in files:
        with safe_open(str(f), framework="np") as sf:
            for key in sf.keys():
                state[key] = sf.get_tensor(key)
    return state


def _assign(tree: dict, path: list[str], value) -> None:
    node = tree
    for p in path[:-1]:
        node = node.setdefault(p, {})
    node[path[-1]] = value


def torch_name_to_flax_path(name: str) -> tuple[list[str], str]:
    """'down_blocks.0.resnets.1.conv1.weight' ->
    (['down_blocks_0','resnets_1','conv1'], 'weight')"""
    parts = name.split(".")
    leaf = parts[-1]
    merged: list[str] = []
    for p in parts[:-1]:
        if p.isdigit() and merged:
            merged[-1] = f"{merged[-1]}_{p}"
        else:
            merged.append(p)
    return merged, leaf


def convert_tensor(path: list[str], leaf: str, tensor: np.ndarray):
    """Apply layout + naming rules for one parameter."""
    if leaf == "weight":
        if tensor.ndim == 4:
            if path and path[-1] in ("proj_in", "proj_out") and tensor.shape[2:] == (1, 1):
                # SD1.x Transformer2D proj convs are 1x1; our module is Dense
                return "kernel", tensor[:, :, 0, 0].T
            return "kernel", tensor.transpose(2, 3, 1, 0)  # conv OIHW -> HWIO
        if tensor.ndim == 2:
            # exact module names only: a substring match turns Denses that
            # merely MENTION embeddings (embedding_proj,
            # proj_to_clip_embeddings) into untransposed tables
            if path[-1] in (
                "token_embedding", "word_embeddings", "position_embeddings",
                "token_type_embeddings", "embed_tokens",
            ):
                return "embedding", tensor
            return "kernel", tensor.T
        if tensor.ndim == 5:
            # Conv3d (O, I, kt, kh, kw) -> flax (kt, kh, kw, I, O)
            return "kernel", tensor.transpose(2, 3, 4, 1, 0)
        if tensor.ndim == 1:  # norm scale
            return "scale", tensor
    if leaf == "bias":
        return "bias", tensor
    if leaf in ("position_ids",):
        return None, None  # buffer, not a param
    # verbatim leaves (e.g. logit_scale, position_embedding as param)
    return leaf, tensor


def convert_state_dict(state: dict, rename=None) -> dict:
    """Flat torch state dict -> nested flax params dict (numpy leaves).

    `rename`: optional callable mapping torch names to this package's module
    names (model-specific quirks, e.g. CLIP's text_model prefix).
    """
    params: dict = {}
    for name, tensor in state.items():
        if rename is not None:
            name = rename(name)
            if name is None:
                continue
        path, leaf = torch_name_to_flax_path(name)
        new_leaf, value = convert_tensor(path, leaf, np.asarray(tensor))
        if new_leaf is None:
            continue
        _assign(params, path + [new_leaf], value)
    return params


# --- model-specific torch-name normalizers ---


def clip_rename(name: str) -> str | None:
    """transformers CLIPTextModel names -> models.clip module names."""
    if name.startswith("text_model."):
        name = name[len("text_model.") :]
    name = name.replace("encoder.layers.", "layers.")
    name = name.replace("embeddings.token_embedding", "token_embedding")
    name = name.replace("mlp.fc1", "fc1").replace("mlp.fc2", "fc2")
    if "embeddings.position_ids" in name:
        return None
    if "embeddings.position_embedding.weight" in name:
        # stored as a bare param (not nn.Embed) in CLIPTextEncoder
        return "position_embedding"
    return name


def vae_rename(name: str) -> str | None:
    """diffusers AutoencoderKL names -> models.vae module names (the flax
    modules flatten mid/up/down block interiors into single-level names)."""
    name = name.replace("mid_block.resnets.", "mid_block_resnets.")
    name = name.replace("mid_block.attentions.", "mid_block_attentions.")
    for kind in ("down_blocks", "up_blocks"):
        # down_blocks.0.resnets.1.x -> down_blocks_0_resnets.1.x
        import re

        name = re.sub(rf"{kind}\.(\d+)\.resnets\.", rf"{kind}_\1_resnets.", name)
        name = re.sub(
            rf"{kind}\.(\d+)\.downsamplers\.", rf"{kind}_\1_downsamplers.", name
        )
        name = re.sub(rf"{kind}\.(\d+)\.upsamplers\.", rf"{kind}_\1_upsamplers.", name)
    # legacy attention naming (diffusers <0.18): query/key/value/proj_attn
    name = name.replace(".query.", ".to_q.")
    name = name.replace(".key.", ".to_k.")
    name = name.replace(".value.", ".to_v.")
    name = name.replace(".proj_attn.", ".to_out.0.")
    name = name.replace(".to_out.0.", ".to_out_0.")
    return name


def unet_rename(name: str) -> str | None:
    """diffusers UNet2DConditionModel names -> models.unet2d module names."""
    name = name.replace(".to_out.0.", ".to_out_0.")
    name = name.replace(".ff.net.0.", ".ff.net_0.")
    name = name.replace(".ff.net.2.", ".ff.net_2.")
    return name


def convert_clip(state: dict) -> dict:
    return convert_state_dict(state, clip_rename)


def convert_vae(state: dict) -> dict:
    return convert_state_dict(state, vae_rename)


def convert_unet(state: dict) -> dict:
    return convert_state_dict(state, unet_rename)


def convert_t5(state: dict) -> dict:
    """transformers T5EncoderModel names -> models/t5.py module names."""
    params: dict = {}

    def put(path: list[str], leaf: str, value):
        _assign(params, path + [leaf], value)

    import re

    for name, v in state.items():
        v = np.asarray(v)
        if name in ("shared.weight", "encoder.embed_tokens.weight"):
            put(["token_embedding"], "embedding", v)
            continue
        if name == "encoder.final_layer_norm.weight":
            put(["final_norm"], "scale", v)
            continue
        m = re.match(r"encoder\.block\.(\d+)\.layer\.(\d)\.(.+)\.weight$", name)
        if not m:
            continue
        i, sub_idx, sub = m.group(1), m.group(2), m.group(3)
        block = f"block_{i}"
        if sub_idx == "0":  # attention sublayer
            if sub == "layer_norm":
                put([block, "attn_norm"], "scale", v)
            elif sub == "SelfAttention.relative_attention_bias":
                put([block, "attention"], "relative_attention_bias", v)
            elif sub.startswith("SelfAttention."):
                proj = sub.rsplit(".", 1)[1]  # q|k|v|o
                put([block, "attention", proj], "kernel",
                    np.ascontiguousarray(v.T))
        else:  # feed-forward sublayer
            if sub == "layer_norm":
                put([block, "ff_norm"], "scale", v)
            elif sub.startswith("DenseReluDense."):
                proj = sub.rsplit(".", 1)[1]  # wi_0|wi_1|wo
                put([block, proj], "kernel", np.ascontiguousarray(v.T))
    return params


def convert_flux(state: dict) -> dict:
    """diffusers FluxTransformer2DModel names -> models/flux.py module names.

    Non-mechanical steps: diffusers keeps separate to_q/to_k/to_v (and
    add_*_proj for the text stream) which fuse into this module's
    `*_attn_qkv` Dense; the single-block to_q/k/v + proj_mlp fuse into
    `linear1`; and AdaLayerNormContinuous's (scale, shift) chunk order
    swaps into this module's (shift, scale).
    """
    import re

    params: dict = {}

    def put(path: list[str], leaf: str, value):
        _assign(params, path + [leaf], np.ascontiguousarray(value))

    def dense(path: list[str], leaf: str, v):
        if leaf == "weight":
            put(path, "kernel", v.T)
        else:
            put(path, "bias", v)

    # gather per-block q/k/v pieces for fusing
    fused: dict[tuple, dict] = {}

    top = {
        "x_embedder": ["img_in"],
        "context_embedder": ["txt_in"],
        "time_text_embed.timestep_embedder.linear_1": ["time_in", "in_layer"],
        "time_text_embed.timestep_embedder.linear_2": ["time_in", "out_layer"],
        "time_text_embed.text_embedder.linear_1": ["vector_in", "in_layer"],
        "time_text_embed.text_embedder.linear_2": ["vector_in", "out_layer"],
        "time_text_embed.guidance_embedder.linear_1": ["guidance_in", "in_layer"],
        "time_text_embed.guidance_embedder.linear_2": ["guidance_in", "out_layer"],
        "proj_out": ["final_layer_linear"],
    }

    for name, v in state.items():
        v = np.asarray(v)
        base, leaf = name.rsplit(".", 1)
        if base in top:
            dense(top[base], leaf, v)
            continue
        if base == "norm_out.linear":
            # (scale, shift) -> (shift, scale): swap output halves
            half = v.shape[0] // 2
            swapped = np.concatenate([v[half:], v[:half]], axis=0)
            dense(["final_layer_mod"], leaf, swapped)
            continue
        m = re.match(r"transformer_blocks\.(\d+)\.(.+)$", base)
        if m:
            i, sub = m.group(1), m.group(2)
            blk = f"double_blocks_{i}"
            table = {
                "norm1.linear": [blk, "img_mod", "lin"],
                "norm1_context.linear": [blk, "txt_mod", "lin"],
                "attn.to_out.0": [blk, "img_attn_proj"],
                "attn.to_add_out": [blk, "txt_attn_proj"],
                "ff.net.0.proj": [blk, "img_mlp_0"],
                "ff.net.2": [blk, "img_mlp_2"],
                "ff_context.net.0.proj": [blk, "txt_mlp_0"],
                "ff_context.net.2": [blk, "txt_mlp_2"],
            }
            if sub in table:
                dense(table[sub], leaf, v)
            qk = {
                "attn.norm_q": ([blk, "img_attn_norm"], "query_scale"),
                "attn.norm_k": ([blk, "img_attn_norm"], "key_scale"),
                "attn.norm_added_q": ([blk, "txt_attn_norm"], "query_scale"),
                "attn.norm_added_k": ([blk, "txt_attn_norm"], "key_scale"),
            }
            if sub in qk and leaf == "weight":
                path, pname = qk[sub]
                put(path, pname, v)
            fuse = {
                "attn.to_q": ("img", 0), "attn.to_k": ("img", 1),
                "attn.to_v": ("img", 2),
                "attn.add_q_proj": ("txt", 0), "attn.add_k_proj": ("txt", 1),
                "attn.add_v_proj": ("txt", 2),
            }
            if sub in fuse:
                stream, slot = fuse[sub]
                fused.setdefault((blk, stream), {})[(slot, leaf)] = v
            continue
        m = re.match(r"single_transformer_blocks\.(\d+)\.(.+)$", base)
        if m:
            i, sub = m.group(1), m.group(2)
            blk = f"single_blocks_{i}"
            if sub == "norm.linear":
                dense([blk, "modulation", "lin"], leaf, v)
            elif sub == "proj_out":
                dense([blk, "linear2"], leaf, v)
            elif sub == "attn.norm_q" and leaf == "weight":
                put([blk, "norm"], "query_scale", v)
            elif sub == "attn.norm_k" and leaf == "weight":
                put([blk, "norm"], "key_scale", v)
            elif sub in ("attn.to_q", "attn.to_k", "attn.to_v", "proj_mlp"):
                slot = {"attn.to_q": 0, "attn.to_k": 1, "attn.to_v": 2,
                        "proj_mlp": 3}[sub]
                fused.setdefault((blk, "single"), {})[(slot, leaf)] = v

    for (blk, stream), pieces in fused.items():
        n_slots = 4 if stream == "single" else 3
        for leaf in ("weight", "bias"):
            parts = [pieces.get((s, leaf)) for s in range(n_slots)]
            if any(p is None for p in parts):
                continue
            cat = np.concatenate(parts, axis=0)  # torch out-dim
            if stream == "single":
                dense([blk, "linear1"], leaf, cat)
            else:
                dense([blk, f"{stream}_attn_qkv"], leaf, cat)
    return params


def convert_dpt(state: dict) -> dict:
    """transformers DPTForDepthEstimation names -> models/depth.py names.

    Notable remaps: fusion_stage layer order is reversed (HF layer 0 fuses
    the DEEPEST feature; this module indexes fusion_k by feature k), and
    ConvTranspose weights are [in, out, kh, kw] (vs Conv's [out, in, ...]).
    """
    import re

    params: dict = {}

    def put(path: str, leaf: str, value):
        _assign(params, path.split("/") + [leaf], np.ascontiguousarray(value))

    def dense(path, leaf, v):
        put(path, "kernel" if leaf == "weight" else "bias",
            v.T if leaf == "weight" else v)

    def conv(path, leaf, v):
        put(path, "kernel" if leaf == "weight" else "bias",
            v.transpose(2, 3, 1, 0) if leaf == "weight" else v)

    def convT(path, leaf, v):
        put(path, "kernel" if leaf == "weight" else "bias",
            v.transpose(2, 3, 0, 1) if leaf == "weight" else v)

    def norm(path, leaf, v):
        put(path, "scale" if leaf == "weight" else "bias", v)

    n_taps = 4
    for name, v in state.items():
        v = np.asarray(v)
        base, leaf = name.rsplit(".", 1)
        if name == "dpt.embeddings.cls_token":
            params["cls_token"] = v
        elif name == "dpt.embeddings.position_embeddings":
            params["pos_embed"] = v
        elif base == "dpt.embeddings.patch_embeddings.projection":
            conv("patch_embed", leaf, v)
        elif base.startswith("dpt.encoder.layer."):
            m = re.match(r"dpt\.encoder\.layer\.(\d+)\.(.+)$", base)
            i, sub = m.group(1), m.group(2)
            blk = f"layer_{i}"
            table = {
                "attention.attention.query": (dense, f"{blk}/q"),
                "attention.attention.key": (dense, f"{blk}/k"),
                "attention.attention.value": (dense, f"{blk}/v"),
                "attention.output.dense": (dense, f"{blk}/out"),
                "intermediate.dense": (dense, f"{blk}/fc1"),
                "output.dense": (dense, f"{blk}/fc2"),
                "layernorm_before": (norm, f"{blk}/ln1"),
                "layernorm_after": (norm, f"{blk}/ln2"),
            }
            if sub in table:
                fn, path = table[sub]
                fn(path, leaf, v)
        elif base.startswith("neck.reassemble_stage.readout_projects."):
            # stage-level ModuleList: readout_projects.{k}.0 is the Linear
            m = re.match(
                r"neck\.reassemble_stage\.readout_projects\.(\d+)\.0$", base
            )
            if m:
                dense(f"reassemble_{m.group(1)}_readout", leaf, v)
        elif base.startswith("neck.reassemble_stage.layers."):
            m = re.match(
                r"neck\.reassemble_stage\.layers\.(\d+)\.(.+)$", base
            )
            k, sub = m.group(1), m.group(2)
            if sub == "projection":
                conv(f"reassemble_{k}_project", leaf, v)
            elif sub == "resize":
                (convT if int(k) < 2 else conv)(
                    f"reassemble_{k}_resize", leaf, v
                )
        elif base.startswith("neck.convs."):
            k = base.rsplit(".", 1)[1]
            conv(f"conv_{k}", leaf, v)
        elif base.startswith("neck.fusion_stage.layers."):
            m = re.match(
                r"neck\.fusion_stage\.layers\.(\d+)\.(.+)$", base
            )
            j, sub = int(m.group(1)), m.group(2)
            k = n_taps - 1 - j  # HF fuses deepest-first; we index by feature
            if j == 0 and sub.startswith("residual_layer1."):
                continue  # unused on the deepest stage; our module omits it
            table = {
                "residual_layer1.convolution1": f"fusion_{k}_rcu1/conv1",
                "residual_layer1.convolution2": f"fusion_{k}_rcu1/conv2",
                "residual_layer2.convolution1": f"fusion_{k}_rcu2/conv1",
                "residual_layer2.convolution2": f"fusion_{k}_rcu2/conv2",
                "projection": f"fusion_{k}_project",
            }
            if sub in table:
                conv(table[sub], leaf, v)
        elif base.startswith("head.head."):
            idx = base.rsplit(".", 1)[1]
            conv({"0": "head_conv1", "2": "head_conv2", "4": "head_conv3"}[idx],
                 leaf, v)
    return params


def convert_safety_checker(state: dict) -> dict:
    """transformers StableDiffusionSafetyChecker -> models/safety.py names."""
    import re

    params: dict = {"vision": {}}

    def put(tree, path, leaf, value):
        node = tree
        for p in path.split("/"):
            if p:
                node = node.setdefault(p, {})
        node[leaf] = np.ascontiguousarray(value)

    v_tree = params["vision"]
    for name, t in state.items():
        t = np.asarray(t)
        if name in ("concept_embeds", "special_care_embeds",
                    "concept_embeds_weights", "special_care_embeds_weights"):
            params[name] = t
            continue
        if name == "visual_projection.weight":
            put(v_tree, "projection", "kernel", t.T)
            continue
        prefix = "vision_model.vision_model."
        if not name.startswith(prefix):
            continue
        n = name[len(prefix):]
        if n == "embeddings.class_embedding":
            v_tree["cls_embed"] = t
        elif n == "embeddings.position_embedding.weight":
            v_tree["pos_embed"] = t
        elif n == "embeddings.patch_embedding.weight":
            put(v_tree, "patch_embed", "kernel", t.transpose(2, 3, 1, 0))
        elif n.startswith("pre_layrnorm."):  # (sic) HF's typo'd name
            put(v_tree, "pre_ln", "scale" if n.endswith("weight") else "bias", t)
        elif n.startswith("post_layernorm."):
            put(v_tree, "post_ln", "scale" if n.endswith("weight") else "bias", t)
        else:
            m = re.match(r"encoder\.layers\.(\d+)\.(.+)\.(weight|bias)$", n)
            if not m:
                continue
            i, sub, leaf = m.group(1), m.group(2), m.group(3)
            blk = f"layer_{i}"
            dense = {
                "self_attn.q_proj": f"{blk}_q",
                "self_attn.k_proj": f"{blk}_k",
                "self_attn.v_proj": f"{blk}_v",
                "self_attn.out_proj": f"{blk}_out",
                "mlp.fc1": f"{blk}_fc1",
                "mlp.fc2": f"{blk}_fc2",
            }
            norm = {"layer_norm1": f"{blk}_ln1", "layer_norm2": f"{blk}_ln2"}
            if sub in dense:
                put(v_tree, dense[sub], "kernel" if leaf == "weight" else "bias",
                    t.T if leaf == "weight" else t)
            elif sub in norm:
                put(v_tree, norm[sub], "scale" if leaf == "weight" else "bias", t)
    return params


def convert_blip(state: dict) -> dict:
    """HF BlipForConditionalGeneration / BlipForQuestionAnswering state dict
    -> {"vision","text","qenc"} trees matching models/blip.py ("qenc" is the
    VQA question encoder, `text_encoder.*` in the HF layout; empty for
    caption-only checkpoints). Two non-mechanical steps: the vision tower's
    fused qkv projection splits into our separate q/k/v Denses, and BERT's
    dotted layer names flatten onto the decoder's per-layer module names.
    Reference behavior replaced: swarm/captioning/caption_image.py:12-17
    (transformers classes resolved by name per job)."""
    vision: dict = {}
    text: dict = {}
    qenc: dict = {}

    def put(tree: dict, path: str, leaf: str, value):
        node = tree
        for p in path.split("/"):
            node = node.setdefault(p, {})
        node[leaf] = value

    def dense(tree, path, leaf, v):
        # torch Linear [out, in] -> flax kernel [in, out]; bias verbatim
        if leaf == "weight":
            put(tree, path, "kernel", np.ascontiguousarray(v.T))
        else:
            put(tree, path, "bias", v)

    def norm(tree, path, leaf, v):
        put(tree, path, "scale" if leaf == "weight" else "bias", v)

    import re

    def bert_text(tree: dict, n: str, v) -> None:
        """One BlipTextModel-relative key (embeddings.* / encoder.layer.*)
        into a models/blip.py text tree — shared by the answer decoder
        (under text_decoder.bert.) and the question encoder (text_encoder.,
        no bert. prefix, no cls head)."""
        if n == "embeddings.word_embeddings.weight":
            put(tree, "word_embeddings", "embedding", v)
        elif n == "embeddings.position_embeddings.weight":
            tree["position_embeddings"] = v
        elif n.startswith("embeddings.LayerNorm."):
            norm(tree, "embed_ln", n.rsplit(".", 1)[1], v)
        else:
            m = re.match(r"encoder\.layer\.(\d+)\.(.+)\.(weight|bias)$", n)
            if not m:
                return
            i, sub, leaf = m.group(1), m.group(2), m.group(3)
            table = {
                "attention.self.query": ("dense", f"self_{i}/q"),
                "attention.self.key": ("dense", f"self_{i}/k"),
                "attention.self.value": ("dense", f"self_{i}/v"),
                "attention.output.dense": ("dense", f"self_{i}/out"),
                "attention.output.LayerNorm": ("norm", f"self_ln_{i}"),
                "crossattention.self.query": ("dense", f"cross_{i}/q"),
                "crossattention.self.key": ("dense", f"cross_{i}/k"),
                "crossattention.self.value": ("dense", f"cross_{i}/v"),
                "crossattention.output.dense": ("dense", f"cross_{i}/out"),
                "crossattention.output.LayerNorm": ("norm", f"cross_ln_{i}"),
                "intermediate.dense": ("dense", f"fc1_{i}"),
                "output.dense": ("dense", f"fc2_{i}"),
                "output.LayerNorm": ("norm", f"ffn_ln_{i}"),
            }
            entry = table.get(sub)
            if entry is None:
                return
            kind, path = entry
            (dense if kind == "dense" else norm)(tree, path, leaf, v)

    for name, v in state.items():
        v = np.asarray(v)
        if name.startswith("vision_model."):
            n = name[len("vision_model."):]
            if n == "embeddings.class_embedding":
                vision["cls_token"] = v.reshape(1, 1, -1)
            elif n == "embeddings.position_embedding":
                vision["pos_embed"] = v.reshape(1, v.shape[-2], v.shape[-1])
            elif n.startswith("embeddings.patch_embedding."):
                leaf = n.rsplit(".", 1)[1]
                if leaf == "weight":
                    put(vision, "patch_embed", "kernel", v.transpose(2, 3, 1, 0))
                else:
                    put(vision, "patch_embed", "bias", v)
            elif n.startswith("post_layernorm."):
                norm(vision, "ln_post", n.rsplit(".", 1)[1], v)
            else:
                m = re.match(r"encoder\.layers\.(\d+)\.(.+)\.(weight|bias)$", n)
                if not m:
                    continue
                i, sub, leaf = m.group(1), m.group(2), m.group(3)
                if sub == "self_attn.qkv":
                    # fused [3D, D] rows (or [3D] bias) -> separate q/k/v
                    for part, chunk in zip("qkv", np.split(v, 3, axis=0)):
                        dense(vision, f"attn_{i}/{part}", leaf, chunk)
                elif sub == "self_attn.projection":
                    dense(vision, f"attn_{i}/out", leaf, v)
                elif sub == "layer_norm1":
                    norm(vision, f"ln1_{i}", leaf, v)
                elif sub == "layer_norm2":
                    norm(vision, f"ln2_{i}", leaf, v)
                elif sub == "mlp.fc1":
                    dense(vision, f"fc1_{i}", leaf, v)
                elif sub == "mlp.fc2":
                    dense(vision, f"fc2_{i}", leaf, v)
        elif name.startswith("text_decoder."):
            n = name[len("text_decoder."):]
            if n.startswith("cls.predictions.transform.dense."):
                dense(text, "head_dense", n.rsplit(".", 1)[1], v)
            elif n.startswith("cls.predictions.transform.LayerNorm."):
                norm(text, "head_ln", n.rsplit(".", 1)[1], v)
            elif n.startswith("cls.predictions.decoder."):
                dense(text, "lm_head", n.rsplit(".", 1)[1], v)
            elif n == "cls.predictions.bias":
                # tied duplicate of decoder.bias in HF checkpoints
                text.setdefault("lm_head", {}).setdefault("bias", v)
            elif n.startswith("bert."):
                bert_text(text, n[len("bert."):], v)
        elif name.startswith("text_encoder."):
            # VQA question encoder: BlipTextModel without pooler or cls head
            bert_text(qenc, name[len("text_encoder."):], v)
    return {"vision": vision, "text": text, "qenc": qenc}


def assert_tree_shapes_match(converted: dict, initialized: dict, prefix=""):
    """Structural check: every initialized param has a converted twin of the
    same shape. Raises with the full list of mismatches."""
    problems: list[str] = []

    def shape_of(x):
        # works for arrays AND jax.eval_shape's ShapeDtypeStructs, so the
        # check can run without materializing a full-size init
        return tuple(getattr(x, "shape", None) or np.shape(x))

    def walk(c, i, path):
        if isinstance(i, dict):
            for k, v in i.items():
                if not isinstance(c, dict) or k not in c:
                    problems.append(f"missing {path}/{k}")
                else:
                    walk(c[k], v, f"{path}/{k}")
        else:
            if shape_of(c) != shape_of(i):
                problems.append(f"shape {path}: {shape_of(c)} != {shape_of(i)}")

    walk(converted, initialized, prefix)
    if problems:
        raise ValueError("conversion mismatches:\n" + "\n".join(problems[:40]))


# --- CLAP text encoder (AudioLDM conditioning; models/clap.py) ---


def clap_rename(name: str) -> str | None:
    """transformers ClapTextModelWithProjection names -> models.clap names."""
    if name.startswith("text_model."):
        name = name[len("text_model."):]
    if "position_ids" in name:
        return None
    name = name.replace("embeddings.word_embeddings", "word_embeddings")
    name = name.replace("embeddings.position_embeddings", "position_embeddings")
    name = name.replace("embeddings.token_type_embeddings",
                        "token_type_embeddings")
    name = name.replace("embeddings.LayerNorm", "embed_norm")
    name = name.replace("encoder.layer.", "layers.")
    name = name.replace("attention.self.", "self_attn.")
    name = name.replace("attention.output.dense", "attn_out")
    name = name.replace("attention.output.LayerNorm", "attn_norm")
    name = name.replace("intermediate.dense", "intermediate")
    name = name.replace("output.dense", "output")
    name = name.replace("output.LayerNorm", "output_norm")
    name = name.replace("pooler.dense", "pooler")
    name = name.replace("text_projection.linear1", "proj_1")
    name = name.replace("text_projection.linear2", "proj_2")
    return name


def convert_clap(state: dict) -> dict:
    params = convert_state_dict(state, rename=clap_rename)
    # the self-attn q/k/v ended up under layers_N.self_attn already; the
    # flax module names are query/key/value — convert_state_dict keeps them
    return params


# --- HiFi-GAN vocoder (AudioLDM mel->waveform; models/hifigan.py) ---


def convert_hifigan(state: dict) -> dict:
    """transformers SpeechT5HifiGan state dict -> models.hifigan params.

    Conv1d weights are [O, I, K] -> flax Conv kernel [K, I, O];
    ConvTranspose1d weights are [I, O, K] -> flax ConvTranspose [K, I, O].
    The normalize-before `mean`/`scale` buffers ride along as params.
    """
    params: dict = {}
    for name, tensor in state.items():
        tensor = np.asarray(tensor)
        # strip weight-norm decomposition if present (g * v/|v|)
        if name.endswith(".weight_g") or name.endswith(".weight_v"):
            base = name.rsplit(".", 1)[0]
            g_name, v_name = base + ".weight_g", base + ".weight_v"
            if g_name not in state or v_name not in state:
                continue
            if not name.endswith(".weight_g"):
                continue  # handle the pair once, on the _g entry
            g = np.asarray(state[g_name])
            v = np.asarray(state[v_name])
            norm = np.sqrt((v**2).sum(axis=(1, 2), keepdims=True))
            tensor = g * v / np.maximum(norm, 1e-12)
            name = base + ".weight"
        # resblocks.N.convs1.M.weight -> resblocks_N.convs1_M.kernel
        path, leaf = torch_name_to_flax_path(name)
        if leaf == "weight" and tensor.ndim == 3:
            if path and path[-1].startswith("upsampler"):
                value = tensor.transpose(2, 0, 1)  # IOK -> KIO
            else:
                value = tensor.transpose(2, 1, 0)  # OIK -> KIO
            _assign(params, path + ["kernel"], value)
        elif leaf in ("mean", "scale") and not path:
            _assign(params, [leaf], tensor)
        elif leaf == "bias":
            _assign(params, path + ["bias"], tensor)
        else:
            _assign(params, path + [leaf], tensor)
    return params


# --- Kandinsky 2.2 family (models/unet_kandinsky.py, movq.py, prior.py) ---


def k22_unet_rename(name: str, text_image: bool = False) -> str | None:
    """diffusers K2.x / DeepFloyd IF UNet2DConditionModel names ->
    models.unet_kandinsky module names (the same block family serves all
    three: image-conditioned K2.2, text_image-conditioned K2.1,
    text-conditioned IF)."""
    name = name.replace(".to_out.0.", ".to_out_0.")
    if text_image:
        # K2.1: TextImageTimeEmbedding + TextImageProjection — the SAME
        # torch names (`add_embedding.image_proj`,
        # `encoder_hid_proj.image_embeds`) mean different flax modules
        # than in K2.2's single-modality embeddings, so the mode is an
        # argument, decided by infer_k22_unet_config from the checkpoint
        name = name.replace("add_embedding.text_proj.", "aug_emb_text_proj.")
        name = name.replace("add_embedding.text_norm.", "aug_emb_text_norm.")
        name = name.replace("add_embedding.image_proj.",
                            "aug_emb_image_proj.")
        name = name.replace("encoder_hid_proj.image_embeds.",
                            "hid_proj_image.")
        name = name.replace("encoder_hid_proj.text_proj.", "hid_proj_text.")
    # Kandinsky: ImageTimeEmbedding + ImageProjection
    name = name.replace("add_embedding.image_proj.", "aug_emb_proj.")
    name = name.replace("add_embedding.image_norm.", "aug_emb_norm.")
    name = name.replace("encoder_hid_proj.image_embeds.", "hid_proj.")
    name = name.replace("encoder_hid_proj.norm.", "hid_proj_norm.")
    # IF: TextTimeEmbedding (LN -> attention pool -> proj -> LN) + Linear
    name = name.replace("add_embedding.norm1.", "aug_emb_norm1.")
    name = name.replace("add_embedding.pool.", "aug_emb_pool.")
    name = name.replace("add_embedding.proj.", "aug_emb_proj.")
    name = name.replace("add_embedding.norm2.", "aug_emb_norm2.")
    name = name.replace("encoder_hid_proj.weight", "hid_proj.weight")
    name = name.replace("encoder_hid_proj.bias", "hid_proj.bias")
    name = name.replace("mid_block.resnets.", "mid_block_resnets.")
    name = name.replace("mid_block.attentions.", "mid_block_attentions.")
    return name


def infer_k22_unet_config(state: dict, config_json: dict | None = None):
    """Derive the UNet geometry from the checkpoint itself (block channels,
    layers, attention placement, cross/image dims, ImageProjection token
    count) — hardcoding those invites silent drift from the real weights.
    `attention_head_dim` is the one field shapes cannot reveal (q/k/v are
    fused over heads); it comes from the shipped config.json, default 64."""
    import re

    from .unet_kandinsky import K22UNetConfig

    blocks: dict[int, int] = {}
    attn_blocks: set[int] = set()
    layers = 1
    for k in state:
        m = re.match(r"down_blocks\.(\d+)\.resnets\.(\d+)\.conv1\.weight", k)
        if m:
            blocks[int(m.group(1))] = np.asarray(state[k]).shape[0]
            layers = max(layers, int(m.group(2)) + 1)
        m = re.match(r"down_blocks\.(\d+)\.attentions\.0\.to_q\.weight", k)
        if m:
            attn_blocks.add(int(m.group(1)))
    n = max(blocks) + 1
    block_out = tuple(blocks[i] for i in range(n))
    first_attn = min(attn_blocks)
    cross = np.asarray(
        state[f"down_blocks.{first_attn}.attentions.0.add_k_proj.weight"]
    ).shape[1]
    cfg_json = config_json or {}
    head_dim = int(cfg_json.get("attention_head_dim", 64))
    groups = int(cfg_json.get("norm_num_groups", 32))
    text_image_mode = "encoder_hid_proj.text_proj.weight" in state
    image_mode = (
        "encoder_hid_proj.image_embeds.weight" in state
        and not text_image_mode
    )
    image_embed_dim = 768
    if text_image_mode:
        # K2.1 TextImageProjection: text_proj gives the text hidden width,
        # image_embeds gives the prior embedding width + token count
        hid_dim = np.asarray(
            state["encoder_hid_proj.text_proj.weight"]
        ).shape[1]
        img_w = np.asarray(state["encoder_hid_proj.image_embeds.weight"])
        image_embed_dim = img_w.shape[1]
        tokens = img_w.shape[0] // cross
    elif image_mode:
        proj_w = np.asarray(state["encoder_hid_proj.image_embeds.weight"])
        hid_dim = proj_w.shape[1]
        tokens = proj_w.shape[0] // cross
    else:
        # IF: plain Linear T5-state projection
        proj_w = np.asarray(state["encoder_hid_proj.weight"])
        hid_dim = proj_w.shape[1]
        tokens = 0
    return K22UNetConfig(
        in_channels=np.asarray(state["conv_in.weight"]).shape[1],
        out_channels=np.asarray(state["conv_out.weight"]).shape[0],
        block_out_channels=block_out,
        layers_per_block=layers,
        attention_head_dim=head_dim,
        cross_attention_dim=cross,
        encoder_hid_dim=hid_dim,
        image_proj_tokens=tokens,
        image_embed_dim=image_embed_dim,
        down_attention=tuple(i in attn_blocks for i in range(n)),
        norm_num_groups=groups,
        conditioning=(
            "text_image" if text_image_mode
            else "image" if image_mode else "text"
        ),
        act=str(cfg_json.get("act_fn",
                             "gelu" if not (image_mode or text_image_mode)
                             else "silu")),
        class_embed_timestep=any(
            k.startswith("class_embedding.") for k in state
        ),
        addition_embed_heads=int(
            cfg_json.get("addition_embed_type_num_heads", 64)
        ),
    )


def convert_kandinsky_unet(state: dict, config_json: dict | None = None):
    """-> (K22UNetConfig, params)."""
    import functools

    cfg = infer_k22_unet_config(state, config_json)
    rename = functools.partial(
        k22_unet_rename, text_image=cfg.conditioning == "text_image"
    )
    return cfg, convert_state_dict(state, rename)


def movq_rename(name: str) -> str | None:
    """diffusers VQModel (norm_type=spatial) names -> models.movq names."""
    import re

    if name.startswith("quantize."):
        return None  # codebook: dead weight for continuous-latent serving
    name = name.replace(".to_out.0.", ".to_out_0.")
    for pre in ("encoder", "decoder"):
        name = name.replace(f"{pre}.mid_block.resnets.",
                            f"{pre}.mid_block_resnets.")
        name = name.replace(f"{pre}.mid_block.attentions.",
                            f"{pre}.mid_block_attentions.")
        name = re.sub(
            rf"{pre}\.(down_blocks|up_blocks)\.(\d+)\.(resnets|downsamplers|upsamplers)\.",
            rf"{pre}.\1_\2_\3.",
            name,
        )
    # samplers are a bare conv: flatten onto the module's single name
    # (after the block flatten above the shape is "..._downsamplers.0.conv.")
    name = re.sub(r"_(downsamplers|upsamplers)\.0\.conv\.",
                  r"_\1_0_conv.", name)
    # legacy attention naming (q/k/v/proj_attn) in older exports
    name = name.replace(".query.", ".to_q.")
    name = name.replace(".key.", ".to_k.")
    name = name.replace(".value.", ".to_v.")
    name = name.replace(".proj_attn.", ".to_out_0.")
    return name


def convert_movq(state: dict) -> dict:
    """diffusers VQModel state dict -> models.movq params. Checkpoints
    whose SpatialNorm group-norm is non-affine get identity scale/bias
    filled in (our module keeps them as real params)."""
    params = convert_state_dict(state, movq_rename)

    def fill(tree: dict):
        for v in tree.values():
            if isinstance(v, dict):
                if "conv_y" in v and "norm_layer" not in v:
                    ch = np.asarray(v["conv_y"]["kernel"]).shape[-1]
                    v["norm_layer"] = {
                        "scale": np.ones((ch,), np.float32),
                        "bias": np.zeros((ch,), np.float32),
                    }
                else:
                    fill(v)

    fill(params)
    return params


def prior_rename(name: str) -> str | None:
    """diffusers PriorTransformer names -> models.prior names."""
    if name in ("clip_mean", "clip_std"):
        return None  # extracted separately (embedding-space whitening stats)
    name = name.replace("embedding_proj.", "embed_proj.")
    name = name.replace(".attn1.to_out.0.", ".to_out_0.")
    name = name.replace(".attn1.", ".")
    name = name.replace(".ff.net.0.proj.", ".ff_proj.")
    name = name.replace(".ff.net.2.", ".ff_out.")
    return name


def convert_prior(state: dict):
    """-> (params, clip_stats or None). clip_stats = {"mean","std"} [E] —
    PriorTransformer.post_process_latents un-whitens the predicted
    embedding before the decoder consumes it."""
    params = convert_state_dict(state, prior_rename)
    stats = None
    if "clip_mean" in state and "clip_std" in state:
        stats = {
            "mean": np.asarray(state["clip_mean"]).reshape(-1),
            "std": np.asarray(state["clip_std"]).reshape(-1),
        }
    return params, stats


# --- AnimateDiff video family (models/video_unet.py) ---


def motion_adapter_rename(name: str) -> str | None:
    """diffusers MotionAdapter names -> models.video_unet motion-module
    names (the temporal_transformer wrapper level flattens away)."""
    import re

    name = re.sub(
        r"down_blocks\.(\d+)\.motion_modules\.(\d+)\.temporal_transformer\.",
        r"down_\1_motion_modules_\2.", name,
    )
    name = re.sub(
        r"up_blocks\.(\d+)\.motion_modules\.(\d+)\.temporal_transformer\.",
        r"up_\1_motion_modules_\2.", name,
    )
    name = re.sub(
        r"mid_block\.motion_modules\.(\d+)\.temporal_transformer\.",
        r"mid_motion_modules_\1.", name,
    )
    name = name.replace(".to_out.0.", ".to_out_0.")
    name = name.replace(".ff.net.0.", ".ff.net_0.")
    name = name.replace(".ff.net.2.", ".ff.net_2.")
    return name


def convert_motion_adapter(state: dict) -> dict:
    """MotionAdapter checkpoint -> motion-module subtrees, ready to overlay
    onto a VideoUNet param tree (same top-level names)."""
    return convert_state_dict(state, motion_adapter_rename)


def video_unet_rename(name: str) -> str | None:
    """diffusers SD UNet2DConditionModel names -> models.video_unet SPATIAL
    module names (VideoUNet flattens the block level: down_blocks.0.resnets.1
    -> down_0_resnets_1; motion modules come from the adapter)."""
    import re

    name = re.sub(r"down_blocks\.(\d+)\.(resnets|attentions)\.",
                  r"down_\1_\2.", name)
    name = re.sub(r"down_blocks\.(\d+)\.downsamplers\.0\.conv\.",
                  r"down_\1_downsample.conv.", name)
    name = re.sub(r"up_blocks\.(\d+)\.(resnets|attentions)\.",
                  r"up_\1_\2.", name)
    name = re.sub(r"up_blocks\.(\d+)\.upsamplers\.0\.conv\.",
                  r"up_\1_upsample.conv.", name)
    name = name.replace("mid_block.resnets.", "mid_resnets.")
    name = name.replace("mid_block.attentions.", "mid_attentions.")
    name = name.replace(".to_out.0.", ".to_out_0.")
    name = name.replace(".ff.net.0.", ".ff.net_0.")
    name = name.replace(".ff.net.2.", ".ff.net_2.")
    return name


def convert_video_unet(spatial_state: dict, motion_state: dict) -> dict:
    """SD1.5-family UNet checkpoint + MotionAdapter checkpoint -> one
    VideoUNet param tree (AnimateDiff's composition: frozen spatial weights
    with temporal modules threaded between them)."""
    params = convert_state_dict(spatial_state, video_unet_rename)
    for key, sub in convert_motion_adapter(motion_state).items():
        params[key] = sub
    return params


# --- HED edge annotator (models/hed.py) ---


def convert_hed(state: dict) -> dict:
    """lllyasviel ControlNetHED state dict (norm, blockN.convs.M,
    blockN.projection) -> models.hed params; the generic merge handles the
    dotted indices, and `norm` rides verbatim in its NCHW shape."""
    return convert_state_dict(state)


def checked_converted(module, example_args, converted, prefix, rng,
                      example_kwargs: dict | None = None):
    """Shape-check a converted tree against a flax module via eval_shape
    (no materialized random init) and return it; geometry mismatches
    surface as MissingWeightsError naming the component. The shared
    loader-side twin of assert_tree_shapes_match, used by every pipeline
    family that loads converted weights. Static call arguments (e.g.
    num_frames) must ride `example_kwargs` — eval_shape abstracts every
    positional argument."""
    import functools

    import jax

    from ..weights import MissingWeightsError

    init = (
        functools.partial(module.init, **example_kwargs)
        if example_kwargs
        else module.init
    )
    expected = jax.eval_shape(init, rng, *example_args)["params"]
    try:
        assert_tree_shapes_match(converted, expected, prefix=prefix)
    except ValueError as e:
        raise MissingWeightsError(
            f"converted checkpoint does not match the {prefix} "
            f"architecture: {e}"
        ) from None
    return converted


# --- generic UNet2DConditionModel / AutoencoderKL geometry inference ---
# (AudioLDM and other families whose checkpoints reuse the standard SD
# layouts with different dims; reference loads them via from_pretrained,
# swarm/audio/audioldm.py:19)


def infer_unet2d_config(state: dict, config_json: dict | None = None):
    """Derive a UNet2DConfig from a diffusers UNet2DConditionModel state
    dict. Every geometric field comes from tensor shapes; only the head
    COUNT (invisible in fused qkv shapes) reads config.json, defaulting
    to the SD convention of reading `attention_head_dim` as head count."""
    import re

    from .unet2d import UNet2DConfig

    blocks: dict[int, int] = {}
    layers = 1
    tlayers: dict[int, int] = {}
    mid_layers = 0
    for k in state:
        m = re.match(r"down_blocks\.(\d+)\.resnets\.(\d+)\.conv1\.weight", k)
        if m:
            blocks[int(m.group(1))] = np.asarray(state[k]).shape[0]
            layers = max(layers, int(m.group(2)) + 1)
        m = re.match(
            r"down_blocks\.(\d+)\.attentions\.0\.transformer_blocks\.(\d+)\.", k
        )
        if m:
            b, t = int(m.group(1)), int(m.group(2)) + 1
            tlayers[b] = max(tlayers.get(b, 0), t)
        m = re.match(r"mid_block\.attentions\.0\.transformer_blocks\.(\d+)\.", k)
        if m:
            mid_layers = max(mid_layers, int(m.group(1)) + 1)
    n = max(blocks) + 1
    block_out = tuple(blocks[i] for i in range(n))
    temb_dim = np.asarray(state["time_embedding.linear_2.weight"]).shape[0]

    # cross-attention dim: attn2's kv input width; when it equals the
    # block's inner dim the blocks self-attend (AudioLDM passes
    # encoder_hidden_states=None) unless config.json says otherwise
    cross = 0
    for b in sorted(tlayers):
        kw = f"down_blocks.{b}.attentions.0.transformer_blocks.0.attn2.to_k.weight"
        if kw in state:
            kv_in = np.asarray(state[kw]).shape[1]
            cross = 0 if kv_in == block_out[b] else kv_in
            break
    cfg_json = config_json or {}
    json_cross = cfg_json.get("cross_attention_dim")
    if isinstance(json_cross, (list, tuple)):
        # AudioLDM2-style per-block lists are not supported by this
        # uniform-config family; fall back to the shape-derived value
        json_cross = None
    if json_cross is not None:
        cross = int(json_cross)

    class_dim = 0
    concat = False
    if "class_embedding.weight" in state:
        class_dim = np.asarray(state["class_embedding.weight"]).shape[1]
        proj_in = np.asarray(
            state["down_blocks.0.resnets.0.time_emb_proj.weight"]
        ).shape[1]
        concat = proj_in == 2 * temb_dim

    heads = cfg_json.get("attention_head_dim", 8)
    if isinstance(heads, (list, tuple)):
        heads = tuple(int(h) for h in heads)
    else:
        heads = int(heads)
    return UNet2DConfig(
        in_channels=np.asarray(state["conv_in.weight"]).shape[1],
        out_channels=np.asarray(state["conv_out.weight"]).shape[0],
        block_out_channels=block_out,
        transformer_layers=tuple(tlayers.get(i, 0) for i in range(n)),
        mid_transformer_layers=mid_layers,
        layers_per_block=layers,
        num_attention_heads=heads,
        cross_attention_dim=cross,
        class_embed_dim=class_dim,
        class_embeddings_concat=concat,
    )


def infer_vae_config(state: dict, config_json: dict | None = None):
    """Derive a VAEConfig from a diffusers AutoencoderKL state dict.
    scaling_factor is training metadata invisible in shapes — it must
    come from config.json (diffusers defaults to 0.18215)."""
    import re

    from .vae import VAEConfig

    blocks: dict[int, int] = {}
    layers = 1
    for k in state:
        m = re.match(r"encoder\.down_blocks\.(\d+)\.resnets\.(\d+)\.conv1\.weight", k)
        if m:
            blocks[int(m.group(1))] = np.asarray(state[k]).shape[0]
            layers = max(layers, int(m.group(2)) + 1)
    block_out = tuple(blocks[i] for i in range(max(blocks) + 1))
    cfg_json = config_json or {}
    return VAEConfig(
        in_channels=np.asarray(state["encoder.conv_in.weight"]).shape[1],
        latent_channels=np.asarray(state["decoder.conv_in.weight"]).shape[1],
        block_out_channels=block_out,
        layers_per_block=layers,
        scaling_factor=float(cfg_json.get("scaling_factor", 0.18215)),
        use_quant_conv="quant_conv.weight" in state,
    )


# --- Bark (transformers BarkSemanticModel/BarkCoarseModel/BarkFineModel) ---


def bark_gpt_rename(name: str) -> str | None:
    """transformers Bark*Model names -> models.bark.BarkGPT names."""
    if name.endswith("attn.bias") or name.endswith("attn.masked_bias"):
        return None  # causal-mask buffers
    name = name.replace("input_embeds_layers.", "tok_embed_")
    name = name.replace("input_embeds_layer.", "tok_embed.")
    name = name.replace("position_embeds_layer.", "pos_embed.")
    name = name.replace("layers.", "block_")
    name = name.replace("layernorm_1.", "ln1.")
    name = name.replace("layernorm_2.", "ln2.")
    name = name.replace("layernorm_final.", "ln_f.")
    name = name.replace("attn.att_proj.", "qkv.")
    name = name.replace("attn.out_proj.", "proj.")
    name = name.replace("mlp.in_proj.", "fc.")
    name = name.replace("mlp.out_proj.", "fc_out.")
    name = name.replace("lm_heads.", "head_")
    name = name.replace("lm_head.", "head.")
    return name


def convert_bark_gpt(state: dict) -> dict:
    params = convert_state_dict(state, bark_gpt_rename)

    # nn.Embed tables need `embedding` (untransposed), not `kernel`
    def fix(tree: dict):
        for key, v in list(tree.items()):
            if isinstance(v, dict):
                if key.startswith(("tok_embed", "pos_embed")) and "kernel" in v:
                    v["embedding"] = np.ascontiguousarray(v.pop("kernel").T)
                else:
                    fix(v)

    fix(params)
    return params


def split_bark_state(state: dict) -> dict:
    """The HF suno/bark repo ships ONE state dict holding every stage;
    split by prefix -> {"semantic"|"coarse"|"fine"|"codec": substate}."""
    prefixes = {
        "semantic.": "semantic",
        "coarse_acoustics.": "coarse",
        "fine_acoustics.": "fine",
        "codec_model.": "codec",
    }
    out: dict[str, dict] = {}
    for k, v in state.items():
        for pre, stage in prefixes.items():
            if k.startswith(pre):
                out.setdefault(stage, {})[k[len(pre):]] = v
                break
    return out


def infer_bark_gpt_config(stage_cfg: dict, stage: str):
    """Per-stage geometry from the repo config.json's nested stage config
    (keys: semantic_config / coarse_acoustics_config /
    fine_acoustics_config)."""
    from .bark import BarkGPTConfig

    fine = stage == "fine"
    return BarkGPTConfig(
        input_vocab=int(stage_cfg.get("input_vocab_size", 10_048)),
        output_vocab=int(stage_cfg.get("output_vocab_size", 10_048)),
        n_layer=int(stage_cfg.get("num_layers", 12)),
        n_head=int(stage_cfg.get("num_heads", 12)),
        d_model=int(stage_cfg.get("hidden_size", 768)),
        block_size=int(stage_cfg.get("block_size", 1024)),
        causal=not fine,
        n_codes_total=int(stage_cfg.get("n_codes_total", 8)) if fine else 0,
        n_codes_given=int(stage_cfg.get("n_codes_given", 1)),
    )


# --- EnCodec decoder (transformers EncodecModel) ---


def _fold_weight_norm(g: np.ndarray, v: np.ndarray) -> np.ndarray:
    """weight_norm: w = g * v / ||v|| with the norm over all dims but 0."""
    flat = v.reshape(v.shape[0], -1)
    norm = np.linalg.norm(flat, axis=1).reshape((-1,) + (1,) * (v.ndim - 1))
    return g * v / np.maximum(norm, 1e-12)


def infer_encodec_config(config_json: dict | None = None):
    from .encodec import EncodecConfig

    cfg = config_json or {}
    base = EncodecConfig()
    return EncodecConfig(
        hidden_size=int(cfg.get("hidden_size", base.hidden_size)),
        num_filters=int(cfg.get("num_filters", base.num_filters)),
        upsampling_ratios=tuple(
            cfg.get("upsampling_ratios", base.upsampling_ratios)
        ),
        kernel_size=int(cfg.get("kernel_size", base.kernel_size)),
        last_kernel_size=int(
            cfg.get("last_kernel_size", base.last_kernel_size)
        ),
        residual_kernel_size=int(
            cfg.get("residual_kernel_size", base.residual_kernel_size)
        ),
        dilation_growth_rate=int(
            cfg.get("dilation_growth_rate", base.dilation_growth_rate)
        ),
        num_residual_layers=int(
            cfg.get("num_residual_layers", base.num_residual_layers)
        ),
        num_lstm_layers=int(cfg.get("num_lstm_layers", base.num_lstm_layers)),
        compress=int(cfg.get("compress", base.compress)),
        codebook_size=int(cfg.get("codebook_size", base.codebook_size)),
        audio_channels=int(cfg.get("audio_channels", base.audio_channels)),
        pad_mode=str(cfg.get("pad_mode", base.pad_mode)),
        use_conv_shortcut=bool(
            cfg.get("use_conv_shortcut", base.use_conv_shortcut)
        ),
    )


def convert_encodec_decoder(state: dict, max_codebooks: int | None = None) -> dict:
    """transformers EncodecModel state (decoder.* + quantizer.*) ->
    models.encodec.EncodecDecoderModel params. Weight-norm pairs
    (parametrizations.weight.original0/1) fold into plain kernels;
    Conv1d kernels go OIK->KIO, ConvTranspose1d IOK->K,out,in (flax
    `transpose_kernel=True` layout, verified numerically in tests).
    `max_codebooks` drops RVQ layers beyond the serving depth (the 24 kHz
    checkpoint carries 32 codebooks; Bark uses 8)."""
    import re

    # pair up the weight-norm halves first
    groups: dict[str, dict] = {}
    loose: dict[str, np.ndarray] = {}
    for k, v in state.items():
        m = re.match(r"(.*)\.parametrizations\.weight\.original([01])$", k)
        if m:
            groups.setdefault(m.group(1), {})[m.group(2)] = np.asarray(v)
        else:
            loose[k] = np.asarray(v)

    params: dict = {}

    def assign(torch_name: str, leaf: str, value):
        path, _ = torch_name_to_flax_path(torch_name + ".x")
        _assign(params, path + [leaf], value)

    for base, halves in groups.items():
        if not base.startswith("decoder."):
            continue
        w = _fold_weight_norm(halves["0"], halves["1"])
        # One permutation serves both conv kinds: torch Conv1d [out,in,k]
        # -> flax Conv [k,in,out], and torch ConvTranspose1d [in,out,k] ->
        # the flax transpose_kernel=True layout [k,out,in] (measured exact
        # vs torch, maxerr 0.0) — both are axis reversal.
        assign(base, "kernel", np.ascontiguousarray(w.transpose(2, 1, 0)))
    for k, v in loose.items():
        if k.startswith("decoder.") and k.endswith(".bias"):
            assign(k[: -len(".bias")], "bias", v)
        elif k.startswith("decoder.") and ".lstm." in k:
            mod, leaf = k.rsplit(".lstm.", 1)
            assign(mod, leaf, v)
        elif re.match(r"quantizer\.layers\.\d+\.codebook\.embed$", k):
            idx = int(k.split(".")[2])
            if max_codebooks is None or idx < max_codebooks:
                params[f"codebook_{idx}"] = v
    return params


def mclip_rename(name: str) -> str | None:
    """Kandinsky 2.1 MultilingualCLIP names (XLM-R under a `transformer.`
    prefix + `LinearTransformation`) -> models.mclip names, reusing the
    RoBERTa-trunk renames CLAP established."""
    if name.startswith("transformer."):
        name = name[len("transformer."):]
    if name.startswith("pooler."):
        return None  # XLM-R CLS pooler: unused by MCLIP's mean pooling
    if name.startswith("LinearTransformation."):
        return name.replace("LinearTransformation.", "transformation.")
    return clap_rename(name)


def convert_mclip(state: dict) -> dict:
    return convert_state_dict(state, mclip_rename)


def convert_openpose_body(state: dict) -> dict:
    """pytorch-openpose bodypose_model weights (the lllyasviel/ControlNet
    `body_pose_model.pth` annotator) -> models.pose.OpenposeBody params.

    The distributed .pth stores FLAT caffe-style keys (`conv1_1.weight`,
    `Mconv1_stage2_L1.weight` — pytorch-openpose re-prefixes them at load
    time via its `transfer()` helper); a module-prefixed dict
    (`model0.conv1_1.weight`) passes through unchanged. Flat names are
    unique per block, so the prefix derives from the name itself."""
    import re

    def prefix(name: str) -> str:
        m = re.match(r"Mconv\d+_stage(\d+)_L([12])\.", name)
        if m:
            return f"model{m.group(1)}_{m.group(2)}."
        m = re.match(r"conv5_\d+_CPM_L([12])\.", name)
        if m:
            return f"model1_{m.group(1)}."
        return "model0."

    if not any(k.startswith("model") for k in state):
        state = {prefix(k) + k: v for k, v in state.items()}
    return convert_state_dict(state)


def convert_upernet(state: dict) -> dict:
    """transformers UperNetForSemanticSegmentation (ConvNeXt backbone) ->
    models.segmentation.UperNetSegmenter params. BatchNorms fold into
    their conv kernels (eval-mode running stats), the auxiliary FCN head
    (training-only deep supervision) is dropped."""
    import re

    params: dict = {}

    def put(module: str, leaf: str, value):
        params.setdefault(module, {})[leaf] = value

    # group conv+bn pairs of the decode head for folding
    convs: dict[str, dict] = {}
    for k, v in state.items():
        v = np.asarray(v)
        if k.startswith("auxiliary_head."):
            continue
        m = re.match(
            r"decode_head\.(.+)\.(conv|batch_norm)\.(weight|bias|"
            r"running_mean|running_var)$", k,
        )
        if m:
            convs.setdefault(m.group(1), {})[
                f"{m.group(2)}.{m.group(3)}"
            ] = v
            continue
        if k == "decode_head.classifier.weight":
            put("classifier", "kernel", v.transpose(2, 3, 1, 0))
        elif k == "decode_head.classifier.bias":
            put("classifier", "bias", v)
        elif k == "backbone.embeddings.patch_embeddings.weight":
            put("patch_embeddings", "kernel", v.transpose(2, 3, 1, 0))
        elif k == "backbone.embeddings.patch_embeddings.bias":
            put("patch_embeddings", "bias", v)
        elif k == "backbone.embeddings.layernorm.weight":
            put("embeddings_norm", "scale", v)
        elif k == "backbone.embeddings.layernorm.bias":
            put("embeddings_norm", "bias", v)
        else:
            m = re.match(
                r"backbone\.encoder\.stages\.(\d+)\.downsampling_layer\."
                r"([01])\.(weight|bias)$", k,
            )
            if m:
                s, which, leaf = int(m.group(1)), m.group(2), m.group(3)
                if which == "0":
                    put(f"downsample_norm_{s}",
                        "scale" if leaf == "weight" else "bias", v)
                else:
                    put(f"downsample_conv_{s}",
                        "kernel" if leaf == "weight" else "bias",
                        v.transpose(2, 3, 1, 0) if leaf == "weight" else v)
                continue
            m = re.match(
                r"backbone\.encoder\.stages\.(\d+)\.layers\.(\d+)\.(.+)$", k
            )
            if m:
                s, j, rest = int(m.group(1)), int(m.group(2)), m.group(3)
                mod = f"stage_{s}_layer_{j}"
                if rest == "layer_scale_parameter":
                    put(mod, "layer_scale", v)
                elif rest == "dwconv.weight":
                    _assign(params, [mod, "dwconv", "kernel"],
                            v.transpose(2, 3, 1, 0))
                elif rest == "dwconv.bias":
                    _assign(params, [mod, "dwconv", "bias"], v)
                elif rest.startswith("layernorm."):
                    leaf = "scale" if rest.endswith("weight") else "bias"
                    _assign(params, [mod, "norm", leaf], v)
                elif rest.startswith("pwconv"):
                    which = rest.split(".")[0]
                    leaf = "kernel" if rest.endswith("weight") else "bias"
                    _assign(params, [mod, which, leaf],
                            v.T if leaf == "kernel" else v)
                continue
            m = re.match(
                r"backbone\.hidden_states_norms\.stage(\d)\.(weight|bias)$", k
            )
            if m:
                s = int(m.group(1)) - 1
                put(f"feature_norm_{s}",
                    "scale" if m.group(2) == "weight" else "bias", v)
                continue

    # fold BN into the decode-head convs; rename to the flax module names
    rename = {}
    for i in range(8):
        rename[f"psp_modules.{i}.1"] = f"psp_{i}"
        rename[f"lateral_convs.{i}"] = f"lateral_{i}"
        rename[f"fpn_convs.{i}"] = f"fpn_{i}"
    rename["bottleneck"] = "bottleneck"
    rename["fpn_bottleneck"] = "fpn_bottleneck"
    for torch_mod, tensors in convs.items():
        target = rename.get(torch_mod)
        if target is None:
            continue
        w = tensors["conv.weight"]  # [O, I, kh, kw], no conv bias
        gamma = tensors["batch_norm.weight"]
        beta = tensors["batch_norm.bias"]
        mean = tensors["batch_norm.running_mean"]
        var = tensors["batch_norm.running_var"]
        scale = gamma / np.sqrt(var + 1e-5)
        w = w * scale[:, None, None, None]
        b = beta - mean * scale
        _assign(params, [target, "conv", "kernel"], w.transpose(2, 3, 1, 0))
        _assign(params, [target, "conv", "bias"], b)
    return params


def unet3d_rename(name: str) -> str:
    """diffusers UNet3DConditionModel names -> models.unet3d names."""
    import re

    name = name.replace(".to_out.0.", ".to_out_0.")
    name = name.replace(".ff.net.0.", ".ff.net_0.")
    name = name.replace(".ff.net.2.", ".ff.net_2.")
    # TemporalConvLayer Sequentials: conv1 = [GN, SiLU, Conv] (conv idx 2),
    # conv2..4 = [GN, SiLU, Dropout, Conv] (conv idx 3)
    name = re.sub(r"\.conv1\.0\.", ".conv1_norm.", name)
    name = re.sub(r"\.conv1\.2\.", ".conv1_conv.", name)
    name = re.sub(r"\.conv([234])\.0\.", r".conv\1_norm.", name)
    name = re.sub(r"\.conv([234])\.3\.", r".conv\1_conv.", name)
    name = re.sub(
        r"^down_blocks\.(\d+)\.(resnets|attentions|temp_attentions|"
        r"temp_convs)\.", r"down_\1_\2.", name,
    )
    name = re.sub(
        r"^up_blocks\.(\d+)\.(resnets|attentions|temp_attentions|"
        r"temp_convs)\.", r"up_\1_\2.", name,
    )
    name = re.sub(r"^down_blocks\.(\d+)\.downsamplers\.0\.",
                  r"down_\1_downsample.", name)
    name = re.sub(r"^up_blocks\.(\d+)\.upsamplers\.0\.",
                  r"up_\1_upsample.", name)
    name = re.sub(r"^mid_block\.(resnets|attentions|temp_attentions|"
                  r"temp_convs)\.", r"mid_\1_", name)
    return name


def convert_unet3d(state: dict) -> dict:
    """diffusers UNet3DConditionModel state dict -> models.unet3d params
    (temporal Conv3d kernels ride convert_tensor's generic 5d rule)."""
    return convert_state_dict(state, unet3d_rename)


def infer_unet3d_config(state: dict, config_json: dict | None = None):
    """UNet3DConfig from the checkpoint shapes + config.json head dim."""
    import re

    from .unet3d import UNet3DConfig

    blocks: dict[int, int] = {}
    attn: set[int] = set()
    layers = 1
    for k in state:
        m = re.match(r"down_blocks\.(\d+)\.resnets\.(\d+)\.conv1\.weight", k)
        if m:
            blocks[int(m.group(1))] = np.asarray(state[k]).shape[0]
            layers = max(layers, int(m.group(2)) + 1)
        m = re.match(r"down_blocks\.(\d+)\.attentions\.", k)
        if m:
            attn.add(int(m.group(1)))
    n = max(blocks) + 1
    cross = 1024
    for k in state:
        m = re.match(
            r"down_blocks\.\d+\.attentions\.0\.transformer_blocks\.0\."
            r"attn2\.to_k\.weight", k,
        )
        if m:
            cross = np.asarray(state[k]).shape[1]
            break
    cfg_json = config_json or {}
    return UNet3DConfig(
        in_channels=np.asarray(state["conv_in.weight"]).shape[1],
        out_channels=np.asarray(state["conv_out.weight"]).shape[0],
        block_out_channels=tuple(blocks[i] for i in range(n)),
        layers_per_block=layers,
        attention=tuple(i in attn for i in range(n)),
        attention_head_dim=int(cfg_json.get("attention_head_dim", 64)),
        cross_attention_dim=cross,
        norm_num_groups=int(cfg_json.get("norm_num_groups", 32)),
    )


# --- Stable Cascade (Wuerstchen v3) family ---


def cascade_unet_rename(name: str) -> str | None:
    """diffusers StableCascadeUNet names -> models.cascade_unet names.

    The switch-level UpDownBlock2d wraps its mapping conv in `.blocks.{m}`
    (the interpolation sibling is parameterless), which collapses onto the
    same flax module as the plain strided conv; diffusers' Attention
    submodule flattens onto this package's single-module attn block."""
    import re

    name = re.sub(r"(down_downscalers\.\d+\.1)\.blocks\.\d+\.", r"\1.", name)
    name = re.sub(r"(up_upscalers\.\d+\.1)\.blocks\.\d+\.", r"\1.", name)
    name = name.replace(".attention.to_out.0.", ".attention_to_out_0.")
    name = name.replace(".attention.to_", ".attention_to_")
    name = name.replace(".kv_mapper.1.", ".kv_mapper_1.")
    return name


def infer_cascade_unet_config(state: dict, config_json: dict | None = None):
    """CascadeUNetConfig from checkpoint shapes; config.json only supplies
    what shapes cannot (patch size, head counts, clip_seq, conditioning
    order) with released-config defaults."""
    import re

    from .cascade_unet import CascadeUNetConfig

    cj = config_json or {}
    levels = 1 + max(
        int(m.group(1))
        for k in state
        for m in [re.match(r"down_blocks\.(\d+)\.", k)]
        if m
    )
    block_out, down_layers, up_layers, attention = [], [], [], []
    for i in range(levels):
        res_idx = sorted(
            int(m.group(1))
            for k in state
            for m in [
                re.match(rf"down_blocks\.{i}\.(\d+)\.depthwise\.weight$", k)
            ]
            if m
        )
        block_out.append(
            int(np.asarray(
                state[f"down_blocks.{i}.{res_idx[0]}.depthwise.weight"]
            ).shape[0])
        )
        down_layers.append(len(res_idx))
        up_layers.append(
            len([
                k for k in state
                if re.match(
                    rf"up_blocks\.{levels - 1 - i}\.\d+\.depthwise\.weight$", k
                )
            ])
        )
        attention.append(
            any(
                re.match(rf"down_blocks\.{i}\.\d+\.attention\.to_q\.", k)
                for k in state
            )
        )

    def repeat_counts(prefix):
        counts = []
        for i in range(levels):
            reps = {
                int(m.group(1))
                for k in state
                for m in [re.match(rf"{prefix}\.{i}\.(\d+)\.weight$", k)]
                if m
            }
            counts.append(len(reps) + 1)
        return tuple(counts)

    t_dim = None
    for k in state:
        if k.endswith(".mapper.weight"):
            t_dim = int(np.asarray(state[k]).shape[1])
            break
    conds = tuple(
        cj.get(
            "timestep_conditioning_type",
            [
                c for c in ("sca", "crp")
                if any(k.endswith(f".mapper_{c}.weight") for k in state)
            ],
        )
    )
    clip_seq = int(cj.get("clip_seq") or 4)
    ctp_w = np.asarray(state["clip_txt_pooled_mapper.weight"])
    conditioning_dim = ctp_w.shape[0] // clip_seq
    patch = int(cj.get("patch_size") or 1)
    emb_w = np.asarray(state["embedding.1.weight"])
    heads_cj = cj.get("num_attention_heads")
    if heads_cj is None:
        heads = tuple(
            (c // 64 if a else 0) for c, a in zip(block_out, attention)
        )
    elif isinstance(heads_cj, int):
        heads = (heads_cj,) * levels
    else:
        heads = tuple(int(h or 0) for h in heads_cj)
    self_attn = cj.get("self_attn", True)
    if isinstance(self_attn, (list, tuple)):
        self_attn = bool(self_attn[0])
    switch = None
    if any(
        ".blocks." in k
        for k in state
        if k.startswith(("down_downscalers", "up_upscalers"))
    ):
        switch = tuple(cj.get("switch_level") or [False] * (levels - 1))
    dw_key = next(k for k in state if k.endswith(".depthwise.weight"))
    return CascadeUNetConfig(
        in_channels=int(emb_w.shape[1] // patch**2),
        out_channels=int(
            np.asarray(state["clf.1.weight"]).shape[0] // patch**2
        ),
        patch_size=patch,
        timestep_ratio_embedding_dim=t_dim or 64,
        conditioning_dim=int(conditioning_dim),
        block_out_channels=tuple(block_out),
        num_attention_heads=heads,
        down_num_layers_per_block=tuple(down_layers),
        up_num_layers_per_block=tuple(reversed(up_layers)),
        down_blocks_repeat_mappers=repeat_counts("down_repeat_mappers"),
        up_blocks_repeat_mappers=repeat_counts("up_repeat_mappers"),
        attention=tuple(attention),
        clip_text_pooled_in_channels=int(ctp_w.shape[1]),
        clip_text_in_channels=int(
            np.asarray(state["clip_txt_mapper.weight"]).shape[1]
        ) if "clip_txt_mapper.weight" in state else 0,
        clip_image_in_channels=int(
            np.asarray(state["clip_img_mapper.weight"]).shape[1]
        ) if "clip_img_mapper.weight" in state else 0,
        clip_seq=clip_seq,
        effnet_in_channels=int(
            np.asarray(state["effnet_mapper.0.weight"]).shape[1]
        ) if "effnet_mapper.0.weight" in state else 0,
        pixel_mapper_in_channels=int(
            np.asarray(state["pixels_mapper.0.weight"]).shape[1]
        ) if "pixels_mapper.0.weight" in state else 0,
        kernel_size=int(np.asarray(state[dw_key]).shape[-1]),
        self_attn=bool(self_attn),
        timestep_conditioning_type=conds,
        switch_level=switch,
    )


def _conv_transpose_kernel(w: np.ndarray) -> np.ndarray:
    """torch ConvTranspose2d [in, out, kh, kw] -> the equivalent forward
    (input-dilated) conv kernel [kh, kw, in, out] (spatially flipped)."""
    return np.ascontiguousarray(np.flip(w, (2, 3)).transpose(2, 3, 0, 1))


def convert_cascade_unet(state: dict, config_json: dict | None = None):
    """diffusers StableCascadeUNet state dict -> (config, flax params)."""
    cfg = infer_cascade_unet_config(state, config_json)
    state = dict(state)
    specials = []
    if cfg.switch_level is None:
        for j in range(len(cfg.block_out_channels) - 1):
            wkey = f"up_upscalers.{j}.1.weight"
            if wkey in state:
                specials.append((
                    [f"up_upscalers_{j}_1", "kernel"],
                    _conv_transpose_kernel(np.asarray(state.pop(wkey))),
                ))
                specials.append((
                    [f"up_upscalers_{j}_1", "bias"],
                    np.asarray(state.pop(f"up_upscalers.{j}.1.bias")),
                ))
    params = convert_state_dict(state, rename=cascade_unet_rename)
    for path, value in specials:
        _assign(params, path, value)
    return cfg, params


def infer_paella_vq_config(state: dict, config_json: dict | None = None):
    """PaellaVQConfig (decode path) from `up_blocks.*`/`out_block.*` keys."""
    import re

    from .paella_vq import PaellaVQConfig

    cj = config_json or {}
    in_w = np.asarray(state["up_blocks.0.0.weight"])
    ct_idx = sorted(
        int(m.group(1))
        for k in state
        for m in [re.match(r"up_blocks\.(\d+)\.weight$", k)]
        if m
    )
    mix_idx = sorted(
        int(m.group(1))
        for k in state
        for m in [re.match(r"up_blocks\.(\d+)\.gammas$", k)]
        if m
    )
    first_ct = ct_idx[0] if ct_idx else 1 + (mix_idx[-1] if mix_idx else 0)
    factor = int(cj.get("up_down_scale_factor") or 2)
    return PaellaVQConfig(
        out_channels=int(
            np.asarray(state["out_block.0.weight"]).shape[0] // factor**2
        ),
        up_down_scale_factor=factor,
        levels=len(ct_idx) + 1,
        bottleneck_blocks=len([i for i in mix_idx if i < first_ct]),
        embed_dim=int(in_w.shape[0]),
        latent_channels=int(in_w.shape[1]),
        scale_factor=float(cj.get("scale_factor") or 0.3764),
    )


def convert_paella_vq(state: dict, config_json: dict | None = None):
    """PaellaVQModel state dict -> (config, decoder params). Encoder +
    quantizer keys (in_block/down_blocks/vquantizer) are dropped — the
    serving path only decodes (pipeline_steps.py:70-90 semantics)."""
    import re

    cfg = infer_paella_vq_config(state, config_json)
    decode_state = {
        k: v
        for k, v in state.items()
        if k.startswith(("up_blocks.", "out_block."))
    }
    specials = []
    for k in list(decode_state):
        m = re.match(r"up_blocks\.(\d+)\.(weight|bias)$", k)
        if not m:
            continue
        idx, leaf = m.group(1), m.group(2)
        v = np.asarray(decode_state.pop(k))
        specials.append((
            [f"up_blocks_{idx}", "kernel" if leaf == "weight" else "bias"],
            _conv_transpose_kernel(v) if leaf == "weight" else v,
        ))
    params = convert_state_dict(decode_state)
    for path, value in specials:
        _assign(params, path, value)
    return cfg, params


# --- Stable Video Diffusion family ---


def svd_unet_rename(name: str) -> str:
    """diffusers UNetSpatioTemporalConditionModel names -> models.svd_unet
    names (flatten per-level block lists; GEGLU nets; flat time_pos_embed)."""
    import re

    name = re.sub(
        r"(down_blocks|up_blocks)\.(\d+)\."
        r"(resnets|attentions|downsamplers|upsamplers)\.",
        r"\1_\2_\3.",
        name,
    )
    name = name.replace("mid_block.resnets.", "mid_block_resnets.")
    name = name.replace("mid_block.attentions.", "mid_block_attentions.")
    name = name.replace(".to_out.0.", ".to_out_0.")
    name = re.sub(r"\.(ff|ff_in)\.net\.0\.", r".\1.net_0.", name)
    name = re.sub(r"\.(ff|ff_in)\.net\.2\.", r".\1.net_2.", name)
    name = name.replace(".time_pos_embed.linear_", ".time_pos_embed_linear_")
    return name


def convert_svd_unet(state: dict) -> dict:
    return convert_state_dict(state, svd_unet_rename)


def infer_svd_unet_config(state: dict, config_json: dict | None = None):
    """SVDUNetConfig from checkpoint shapes (head counts from config.json,
    falling back to head-dim-64 like the released checkpoints)."""
    import re

    from .svd_unet import SVDUNetConfig

    cj = config_json or {}
    blocks: dict[int, int] = {}
    attn: set[int] = set()
    layers = 1
    for k in state:
        m = re.match(
            r"down_blocks\.(\d+)\.resnets\.(\d+)\."
            r"spatial_res_block\.conv1\.weight",
            k,
        )
        if m:
            blocks[int(m.group(1))] = int(np.asarray(state[k]).shape[0])
            layers = max(layers, int(m.group(2)) + 1)
        m = re.match(r"down_blocks\.(\d+)\.attentions\.", k)
        if m:
            attn.add(int(m.group(1)))
    n = max(blocks) + 1
    cross = 1024
    tlayers = 1
    for k in state:
        m = re.match(
            r"down_blocks\.\d+\.attentions\.0\.transformer_blocks\."
            r"(\d+)\.attn2\.to_k\.weight",
            k,
        )
        if m:
            cross = int(np.asarray(state[k]).shape[1])
            tlayers = max(tlayers, int(m.group(1)) + 1)
    proj_in_dim = int(np.asarray(state["add_embedding.linear_1.weight"]).shape[1])
    heads_cj = cj.get("num_attention_heads")
    if heads_cj is None:
        heads = tuple(max(1, blocks[i] // 64) for i in range(n))
    elif isinstance(heads_cj, int):
        heads = (heads_cj,) * n
    else:
        heads = tuple(int(h) for h in heads_cj)
    return SVDUNetConfig(
        in_channels=int(np.asarray(state["conv_in.weight"]).shape[1]),
        out_channels=int(np.asarray(state["conv_out.weight"]).shape[0]),
        block_out_channels=tuple(blocks[i] for i in range(n)),
        layers_per_block=layers,
        attention=tuple(i in attn for i in range(n)),
        num_attention_heads=heads,
        cross_attention_dim=cross,
        transformer_layers_per_block=tlayers,
        addition_time_embed_dim=proj_in_dim // 3,
        projection_class_embeddings_input_dim=proj_in_dim,
    )


def convert_svd_vae(state: dict) -> dict:
    """AutoencoderKLTemporalDecoder -> models.svd_vae params: the standard
    VAE rename covers both sides (the temporal decoder's level names
    flatten identically; its spatio-temporal res-block children pass
    through unchanged)."""
    return convert_state_dict(state, vae_rename)


def infer_svd_vae_config(state: dict, config_json: dict | None = None):
    import re

    from .svd_vae import SVDVAEConfig

    cj = config_json or {}
    blocks: dict[int, int] = {}
    layers = 1
    for k in state:
        m = re.match(
            r"encoder\.down_blocks\.(\d+)\.resnets\.(\d+)\.conv1\.weight", k
        )
        if m:
            blocks[int(m.group(1))] = int(np.asarray(state[k]).shape[0])
            layers = max(layers, int(m.group(2)) + 1)
    n = max(blocks) + 1
    return SVDVAEConfig(
        in_channels=int(np.asarray(state["encoder.conv_in.weight"]).shape[1]),
        latent_channels=int(
            np.asarray(state["quant_conv.weight"]).shape[0] // 2
        ),
        block_out_channels=tuple(blocks[i] for i in range(n)),
        layers_per_block=layers,
        scaling_factor=float(cj.get("scaling_factor") or 0.18215),
    )


def convert_clip_vision(state: dict) -> dict:
    """transformers CLIPVisionModelWithProjection -> the standalone vision
    tower (models/safety.py::CLIPVisionEncoder param names). Reuses the
    safety-checker converter by aliasing the key prefix."""
    aliased = {}
    for k, v in state.items():
        if k.startswith("vision_model."):
            aliased["vision_model." + k] = v
        elif k == "visual_projection.weight":
            aliased[k] = v
    return convert_safety_checker(aliased)["vision"]


def infer_clip_vision_config(config_json: dict | None = None):
    """SafetyConfig (the vision-tower geometry carrier) from a
    CLIPVisionModelWithProjection config.json."""
    from .safety import SafetyConfig

    cj = config_json or {}
    return SafetyConfig(
        image_size=int(cj.get("image_size", 224)),
        patch_size=int(cj.get("patch_size", 14)),
        hidden_size=int(cj.get("hidden_size", 1280)),
        num_layers=int(cj.get("num_hidden_layers", 32)),
        num_heads=int(cj.get("num_attention_heads", 16)),
        projection_dim=int(cj.get("projection_dim", 1024)),
        hidden_act=str(cj.get("hidden_act", "gelu")),
    )


# --- Kandinsky 3 (models/unet_kandinsky3.py) ---


def infer_k3_unet_config(state: dict, config_json: dict | None = None):
    """K3UNetConfig from the checkpoint itself. Shapes reveal everything
    except attention_head_dim and groups (fused projections), which come
    from the shipped config.json (defaults 64/32, the released values)."""
    import re

    from .unet_kandinsky3 import K3UNetConfig

    cj = config_json or {}
    blocks: dict[int, int] = {}
    layers = 1
    self_attn: set[int] = set()
    cross_attn: set[int] = set()
    for k in state:
        m = re.match(
            r"down_blocks\.(\d+)\.resnets_in\.(\d+)\.resnet_blocks\.3\."
            r"projection\.weight",
            k,
        )
        if m:
            blocks[int(m.group(1))] = int(np.asarray(state[k]).shape[0])
            layers = max(layers, int(m.group(2)) + 1)
        m = re.match(r"down_blocks\.(\d+)\.attentions\.(\d+)\.attention\.", k)
        if m:
            (self_attn if m.group(2) == "0" else cross_attn).add(
                int(m.group(1))
            )
    n = max(blocks) + 1
    block_out = tuple(blocks[i] for i in range(n))
    hid_w = np.asarray(state["encoder_hid_proj.projection_linear.weight"])
    # hidden bottleneck width of down level 0's first resnet reveals the
    # compression ratio: hidden = max(in, out) // ratio
    h0 = int(
        np.asarray(
            state["down_blocks.0.resnets_in.0.resnet_blocks.0.projection.weight"]
        ).shape[0]
    )
    first_attn = min(self_attn | cross_attn) if (self_attn or cross_attn) else 0
    ff0 = state.get(
        f"down_blocks.{first_attn}.attentions.0.feed_forward.0.weight",
        state.get(
            f"down_blocks.{first_attn}.attentions.1.feed_forward.0.weight"
        ),
    )
    expansion = 4
    if ff0 is not None:
        ff0 = np.asarray(ff0)
        expansion = int(ff0.shape[0] // ff0.shape[1])
    return K3UNetConfig(
        in_channels=int(np.asarray(state["conv_in.weight"]).shape[1]),
        time_embedding_dim=int(
            np.asarray(state["time_embedding.linear_2.weight"]).shape[0]
        ),
        groups=int(cj.get("groups", 32)),
        attention_head_dim=int(cj.get("attention_head_dim", 64)),
        layers_per_block=layers,
        block_out_channels=block_out,
        cross_attention_dim=int(hid_w.shape[0]),
        encoder_hid_dim=int(hid_w.shape[1]),
        add_cross_attention=tuple(i in cross_attn for i in range(n)),
        add_self_attention=tuple(i in self_attn for i in range(n)),
        expansion_ratio=expansion,
        compression_ratio=max(1, block_out[0] // h0),
    )


def convert_kandinsky3_unet(state: dict, config_json: dict | None = None):
    """-> (K3UNetConfig, params). The flattened diffusers names map by the
    generic digit-merge rename; the ConvTranspose2d kernels
    ((shortcut_)up_sample.weight, layout IOHW not OIHW) are the one
    special case."""
    cfg = infer_k3_unet_config(state, config_json)
    specials = []
    rest = {}
    for k, v in state.items():
        arr = np.asarray(v)
        if (
            (k.endswith("up_sample.weight"))
            and arr.ndim == 4
            and arr.shape[2:] == (2, 2)
        ):
            path, _ = torch_name_to_flax_path(k)
            specials.append((path + ["kernel"], arr.transpose(2, 3, 0, 1)))
        else:
            rest[k] = v
    params = convert_state_dict(rest)
    for path, value in specials:
        _assign(params, path, value)
    return cfg, params


# --- SD-x2 latent upscaler (models/k_upscaler.py) ---


def k_upscaler_rename(name: str) -> str:
    """diffusers K-UNet names -> models.k_upscaler names. The digit-merge
    of torch_name_to_flax_path flattens the block lists; only the flat
    time-embedding names and the frozen fourier weight need mapping."""
    import re

    if name == "time_proj.weight":
        return "time_proj_weight"
    name = name.replace("time_embedding.linear_1.", "time_embedding_linear_1.")
    name = name.replace("time_embedding.linear_2.", "time_embedding_linear_2.")
    name = name.replace("time_embedding.cond_proj.", "time_embedding_cond_proj.")
    name = re.sub(
        r"(down_blocks|up_blocks)\.(\d+)\.(resnets|attentions)\.(\d+)\.",
        r"\1_\2_\3_\4.",
        name,
    )
    return name


def infer_k_upscaler_config(state: dict, config_json: dict | None = None):
    """KUpscalerConfig from the checkpoint itself (self/cross attention
    placement from attn1/attn2 key presence, q/k/v bias from bias keys;
    head dim and group size from config.json, defaults 64/32)."""
    import re

    from .k_upscaler import KUpscalerConfig

    cj = config_json or {}
    blocks: dict[int, int] = {}
    layers = 1
    cross: set[int] = set()
    down_self: set[int] = set()
    up_self: set[int] = set()
    for k in state:
        m = re.match(r"down_blocks\.(\d+)\.resnets\.(\d+)\.conv1\.weight", k)
        if m:
            blocks[int(m.group(1))] = int(np.asarray(state[k]).shape[0])
            layers = max(layers, int(m.group(2)) + 1)
        m = re.match(r"down_blocks\.(\d+)\.attentions\.0\.attn2\.to_q\.", k)
        if m:
            cross.add(int(m.group(1)))
        m = re.match(r"down_blocks\.(\d+)\.attentions\.0\.attn1\.to_q\.", k)
        if m:
            down_self.add(int(m.group(1)))
        m = re.match(r"up_blocks\.(\d+)\.attentions\.0\.attn1\.to_q\.", k)
        if m:
            up_self.add(int(m.group(1)))
    n = max(blocks) + 1
    first = min(cross) if cross else 1
    cross_dim = int(
        np.asarray(
            state[f"down_blocks.{first}.attentions.0.attn2.to_k.weight"]
        ).shape[1]
    )
    group_size = int(
        cj.get("resnet_group_size") or cj.get("norm_num_groups") or 32
    )
    return KUpscalerConfig(
        in_channels=int(np.asarray(state["conv_in.weight"]).shape[1]),
        out_channels=int(np.asarray(state["conv_out.weight"]).shape[0]),
        block_out_channels=tuple(blocks[i] for i in range(n)),
        layers_per_block=layers,
        cross_attention_dim=cross_dim,
        attention_head_dim=int(cj.get("attention_head_dim", 64)),
        resnet_group_size=group_size,
        time_cond_proj_dim=int(
            np.asarray(state["time_embedding.cond_proj.weight"]).shape[1]
        ),
        cross_attention=tuple(i in cross for i in range(n)),
        down_self_attention=tuple(i in down_self for i in range(n)),
        up_self_attention=tuple(i in up_self for i in range(n)),
        attention_bias=any(
            k.endswith("attn2.to_q.bias") for k in state
        ),
    )


def convert_k_upscaler(state: dict, config_json: dict | None = None):
    """-> (KUpscalerConfig, params)."""
    cfg = infer_k_upscaler_config(state, config_json)
    return cfg, convert_state_dict(state, k_upscaler_rename)


# --- LineArt generator (models/lineart.py) ---


def infer_lineart_config(state: dict):
    import re

    from .lineart import LineartConfig

    n_res = 0
    for k in state:
        m = re.match(r"model2\.(\d+)\.conv_block\.1\.weight", k)
        if m:
            n_res = max(n_res, int(m.group(1)) + 1)
    return LineartConfig(
        base_channels=int(np.asarray(state["model0.1.weight"]).shape[0]),
        n_residual_blocks=n_res,
    )


def convert_lineart(state: dict):
    """informative-drawings Generator state dict -> (LineartConfig,
    models.lineart params). InstanceNorms are affine-free (no params);
    the two ConvTranspose kernels flip+transpose into the input-dilated
    conv layout _UpConv runs."""
    import re

    cfg = infer_lineart_config(state)
    params: dict = {}

    def put_conv(target, w, b):
        _assign(params, [target, "kernel"], w.transpose(2, 3, 1, 0))
        _assign(params, [target, "bias"], b)

    def put_convt(target, w, b):
        # torch convT (in, out, kh, kw) -> flipped conv (kh, kw, in, out)
        _assign(
            params, [target, "kernel"],
            np.ascontiguousarray(np.flip(w, (2, 3)).transpose(2, 3, 0, 1)),
        )
        _assign(params, [target, "bias"], b)

    arr = {k: np.asarray(v) for k, v in state.items()}
    put_conv("model0_conv", arr["model0.1.weight"], arr["model0.1.bias"])
    put_conv("model1_conv0", arr["model1.0.weight"], arr["model1.0.bias"])
    put_conv("model1_conv1", arr["model1.3.weight"], arr["model1.3.bias"])
    for i in range(cfg.n_residual_blocks):
        put_conv(f"res_{i}_conv0", arr[f"model2.{i}.conv_block.1.weight"],
                 arr[f"model2.{i}.conv_block.1.bias"])
        put_conv(f"res_{i}_conv1", arr[f"model2.{i}.conv_block.5.weight"],
                 arr[f"model2.{i}.conv_block.5.bias"])
    put_convt("model3_conv0", arr["model3.0.weight"], arr["model3.0.bias"])
    put_convt("model3_conv1", arr["model3.3.weight"], arr["model3.3.bias"])
    put_conv("model4_conv", arr["model4.1.weight"], arr["model4.1.bias"])
    return cfg, params


# --- M-LSD line detector (models/mlsd.py) ---


def _fold_bn(w, b, bn_w, bn_b, bn_mean, bn_var, eps=1e-5):
    """Fold BatchNorm into the preceding conv: returns (w', b')."""
    scale = bn_w / np.sqrt(bn_var + eps)
    w = w * scale[:, None, None, None]
    if b is None:
        b = np.zeros_like(bn_b)
    return w, bn_b + (b - bn_mean) * scale


def convert_mlsd(state: dict):
    """MobileV2_MLSD_Large state dict -> models.mlsd params, every
    BatchNorm folded into its conv. Accepts DataParallel 'module.'
    prefixes."""
    from .mlsd import MBV2_SETTING

    arr = {}
    for k, v in state.items():
        if k.startswith("module."):
            k = k[len("module."):]
        arr[k] = np.asarray(v)
    params: dict = {}

    def fold_into(target, conv_key, bn_key):
        w = arr[f"{conv_key}.weight"]
        b = arr.get(f"{conv_key}.bias")
        w, b = _fold_bn(
            w, b, arr[f"{bn_key}.weight"], arr[f"{bn_key}.bias"],
            arr[f"{bn_key}.running_mean"], arr[f"{bn_key}.running_var"],
        )
        path = target.split("/")
        _assign(params, path + ["kernel"], w.transpose(2, 3, 1, 0))
        _assign(params, path + ["bias"], b)

    fold_into("features_0/conv", "backbone.features.0.0",
              "backbone.features.0.1")
    idx = 1
    for t, c, n, s in MBV2_SETTING:
        for _ in range(n):
            pre = f"backbone.features.{idx}.conv"
            if t == 1:
                fold_into(f"features_{idx}/depthwise/conv",
                          f"{pre}.0.0", f"{pre}.0.1")
                fold_into(f"features_{idx}/project", f"{pre}.1", f"{pre}.2")
            else:
                fold_into(f"features_{idx}/expand/conv",
                          f"{pre}.0.0", f"{pre}.0.1")
                fold_into(f"features_{idx}/depthwise/conv",
                          f"{pre}.1.0", f"{pre}.1.1")
                fold_into(f"features_{idx}/project", f"{pre}.2", f"{pre}.3")
            idx += 1
    for blk in range(15, 23):
        for conv in ("conv1", "conv2"):
            fold_into(f"block{blk}/{conv}", f"block{blk}.{conv}.0",
                      f"block{blk}.{conv}.1")
    fold_into("block23/conv1", "block23.conv1.0", "block23.conv1.1")
    fold_into("block23/conv2", "block23.conv2.0", "block23.conv2.1")
    _assign(params, ["block23", "conv3", "kernel"],
            arr["block23.conv3.weight"].transpose(2, 3, 1, 0))
    _assign(params, ["block23", "conv3", "bias"], arr["block23.conv3.bias"])
    return params


# --- PiDiNet soft-edge detector (models/pidinet.py) ---


def _convert_pdc(op: str, w: np.ndarray) -> np.ndarray:
    """Re-parameterize a pixel-difference conv kernel into a vanilla conv
    kernel (the pidinet authors' convert_pdc math). cd/ad stay 3x3; rd
    expands to 5x5."""
    if op == "cv":
        return w
    o, i = w.shape[:2]
    flat = w.reshape(o, i, -1).copy()
    if op == "cd":
        flat[:, :, 4] = flat[:, :, 4] - w.sum(axis=(2, 3))
        return flat.reshape(w.shape)
    if op == "ad":
        return (flat - flat[:, :, [3, 0, 1, 6, 4, 2, 7, 8, 5]]).reshape(
            w.shape
        )
    if op == "rd":
        buffer = np.zeros((o, i, 25), w.dtype)
        buffer[:, :, [0, 2, 4, 10, 14, 20, 22, 24]] = flat[:, :, 1:]
        buffer[:, :, [6, 7, 8, 11, 13, 16, 17, 18]] = -flat[:, :, 1:]
        return buffer.reshape(o, i, 5, 5)
    raise ValueError(f"unknown pdc op {op}")


def convert_pidinet(state: dict):
    """table5_pidinet checkpoint (raw pixel-difference kernels, carv4
    config) -> models.pidinet params. Accepts the {'state_dict': ...}
    wrapper and DataParallel 'module.' prefixes."""
    from .pidinet import CARV4

    if "state_dict" in state and not any(
        k.startswith(("init_block", "block")) for k in state
    ):
        state = state["state_dict"]
    arr = {}
    for k, v in state.items():
        if k.startswith("module."):
            k = k[len("module."):]
        arr[k] = np.asarray(v)

    params: dict = {}

    def put(path, leaf, value):
        _assign(params, list(path) + [leaf], value)

    put(["init_block"], "kernel",
        _convert_pdc(CARV4[0], arr["init_block.weight"]).transpose(2, 3, 1, 0))
    for s in range(4):
        n_blocks = 3 if s == 0 else 4
        for j in range(n_blocks):
            layer = j + 1 if s == 0 else s * 4 + j
            name = f"block{s + 1}_{j + 1}"
            w = _convert_pdc(CARV4[layer], arr[f"{name}.conv1.weight"])
            put([name, "conv1"], "kernel", w.transpose(2, 3, 1, 0))
            put([name, "conv2"], "kernel",
                arr[f"{name}.conv2.weight"].transpose(2, 3, 1, 0))
            if f"{name}.shortcut.weight" in arr:
                put([name, "shortcut"], "kernel",
                    arr[f"{name}.shortcut.weight"].transpose(2, 3, 1, 0))
                put([name, "shortcut"], "bias", arr[f"{name}.shortcut.bias"])
    for i in range(4):
        put([f"dilations_{i}", "conv1"], "kernel",
            arr[f"dilations.{i}.conv1.weight"].transpose(2, 3, 1, 0))
        put([f"dilations_{i}", "conv1"], "bias",
            arr[f"dilations.{i}.conv1.bias"])
        for d in range(1, 5):
            put([f"dilations_{i}", f"conv2_{d}"], "kernel",
                arr[f"dilations.{i}.conv2_{d}.weight"].transpose(2, 3, 1, 0))
        put([f"attentions_{i}", "conv1"], "kernel",
            arr[f"attentions.{i}.conv1.weight"].transpose(2, 3, 1, 0))
        put([f"attentions_{i}", "conv1"], "bias",
            arr[f"attentions.{i}.conv1.bias"])
        put([f"attentions_{i}", "conv2"], "kernel",
            arr[f"attentions.{i}.conv2.weight"].transpose(2, 3, 1, 0))
        put([f"conv_reduces_{i}"], "kernel",
            arr[f"conv_reduces.{i}.conv.weight"].transpose(2, 3, 1, 0))
        put([f"conv_reduces_{i}"], "bias", arr[f"conv_reduces.{i}.conv.bias"])
    put(["classifier"], "kernel",
        arr["classifier.weight"].transpose(2, 3, 1, 0))
    put(["classifier"], "bias", arr["classifier.bias"])
    return params


# --- I2VGenXL (models/i2vgen.py) ---


def i2vgen_rename(name: str) -> str:
    """diffusers I2VGenXLUNet names -> models.i2vgen names: flatten the
    temporal-encoder internals, then the shared unet3d trunk rename. The
    Sequential conditioning stacks flatten by the generic digit-merge."""
    if name.startswith("image_latents_temporal_encoder."):
        name = name.replace(".attn1.to_q.", ".attn1_to_q.")
        name = name.replace(".attn1.to_k.", ".attn1_to_k.")
        name = name.replace(".attn1.to_v.", ".attn1_to_v.")
        name = name.replace(".attn1.to_out.0.", ".attn1_to_out_0.")
        name = name.replace(".ff.net.0.proj.", ".ff_net_0_proj.")
        name = name.replace(".ff.net.2.", ".ff_net_2.")
        return name
    return unet3d_rename(name)


def convert_i2vgen_unet(state: dict) -> dict:
    return convert_state_dict(state, i2vgen_rename)


def infer_i2vgen_config(state: dict, config_json: dict | None = None):
    """I2VGenConfig from checkpoint shapes: the trunk geometry via
    infer_unet3d_config; conv_in sees 2*in_channels (noise + projected
    image latents)."""
    from .i2vgen import I2VGenConfig

    base = infer_unet3d_config(state, config_json)
    return I2VGenConfig(
        in_channels=base.in_channels // 2,
        out_channels=base.out_channels,
        block_out_channels=base.block_out_channels,
        layers_per_block=base.layers_per_block,
        attention=base.attention,
        attention_head_dim=base.attention_head_dim,
        cross_attention_dim=base.cross_attention_dim,
        norm_num_groups=base.norm_num_groups,
    )


# --- GPT-2 trunk (models/gpt2.py — AudioLDM2's language model) ---


def gpt2_config_from_json(cj: dict | None):
    from .gpt2 import GPT2Config

    cj = cj or {}
    base = GPT2Config()
    return GPT2Config(
        hidden_size=int(cj.get("n_embd", base.hidden_size)),
        num_layers=int(cj.get("n_layer", base.num_layers)),
        num_heads=int(cj.get("n_head", base.num_heads)),
        n_positions=int(cj.get("n_positions", base.n_positions)),
        layer_norm_epsilon=float(
            cj.get("layer_norm_epsilon", base.layer_norm_epsilon)
        ),
    )


def convert_gpt2(state: dict) -> dict:
    """transformers GPT2Model names -> models.gpt2 params. Conv1D weights
    are already (in, out) = flax Dense layout, so they copy UNtransposed;
    wte and the causal-mask buffers are dead weight for embeds-in
    serving."""
    import re

    params: dict = {}
    for name, v in state.items():
        if name.startswith("transformer."):
            name = name[len("transformer."):]
        if name == "wte.weight" or name.endswith((".attn.bias",
                                                  ".attn.masked_bias")):
            continue
        v = np.asarray(v)
        if name == "wpe.weight":
            _assign(params, ["wpe"], v)
            continue
        if name in ("ln_f.weight", "ln_f.bias"):
            leaf = "scale" if name.endswith("weight") else "bias"
            _assign(params, ["ln_f", leaf], v)
            continue
        m = re.match(r"h\.(\d+)\.(.+)$", name)
        if not m:
            continue
        block = f"h_{m.group(1)}"
        sub = m.group(2)
        leaf = "bias" if sub.endswith(".bias") else "weight"
        target = {
            "ln_1": ["ln_1"],
            "ln_2": ["ln_2"],
            "attn.c_attn": ["c_attn"],
            "attn.c_proj": ["c_proj"],
            "mlp.c_fc": ["c_fc"],
            "mlp.c_proj": ["mlp_c_proj"],
        }.get(sub.rsplit(".", 1)[0])
        if target is None:
            continue
        if target[0].startswith("ln"):
            new_leaf = "scale" if leaf == "weight" else "bias"
        else:
            new_leaf = "kernel" if leaf == "weight" else "bias"
        _assign(params, [block] + target + [new_leaf], v)
    return params


# --- AudioLDM2 UNet + projection (models/audioldm2_unet.py) ---


def audioldm2_unet_rename(name: str) -> str:
    """diffusers AudioLDM2UNet2DConditionModel names ->
    models.audioldm2_unet names (flatten block lists and the single
    transformer block's internals)."""
    import re

    name = name.replace(".transformer_blocks.0.attn1.",
                        ".transformer_blocks_0_attn1_")
    name = name.replace(".transformer_blocks.0.attn2.",
                        ".transformer_blocks_0_attn2_")
    name = re.sub(r"\.transformer_blocks\.0\.norm([123])\.",
                  r".transformer_blocks_0_norm\1.", name)
    name = name.replace(".transformer_blocks.0.ff.",
                        ".transformer_blocks_0_ff.")
    name = name.replace("_to_out.0.", "_to_out_0.")
    name = re.sub(
        r"^down_blocks\.(\d+)\.(resnets|attentions)\.", r"down_\1_\2.", name
    )
    name = re.sub(
        r"^up_blocks\.(\d+)\.(resnets|attentions)\.", r"up_\1_\2.", name
    )
    name = re.sub(r"^down_blocks\.(\d+)\.downsamplers\.0\.conv\.",
                  r"down_\1_downsample.", name)
    name = re.sub(r"^up_blocks\.(\d+)\.upsamplers\.0\.conv\.",
                  r"up_\1_upsample.", name)
    name = re.sub(r"^mid_block\.(resnets|attentions)\.", r"mid_\1_", name)
    return name


def convert_audioldm2_unet(state: dict) -> dict:
    return convert_state_dict(state, audioldm2_unet_rename)


def infer_audioldm2_unet_config(state: dict, config_json: dict | None = None):
    """AudioLDM2UNetConfig from the checkpoint shapes: per-slot cross
    widths from the paired attn2 projections; head dim from config.json
    (fused projections hide it)."""
    import re

    from .audioldm2_unet import AudioLDM2UNetConfig

    cj = config_json or {}
    blocks: dict[int, int] = {}
    attn: set[int] = set()
    layers = 1
    for k in state:
        m = re.match(r"down_blocks\.(\d+)\.resnets\.(\d+)\.conv1\.weight", k)
        if m:
            blocks[int(m.group(1))] = int(np.asarray(state[k]).shape[0])
            layers = max(layers, int(m.group(2)) + 1)
        m = re.match(r"down_blocks\.(\d+)\.attentions\.", k)
        if m:
            attn.add(int(m.group(1)))
    n = max(blocks) + 1
    first = min(attn)
    cross = []
    for idx in (0, 1):
        key = (f"down_blocks.{first}.attentions.{idx}"
               ".transformer_blocks.0.attn2.to_k.weight")
        cross.append(int(np.asarray(state[key]).shape[1]))
    head_dim = int(cj.get("attention_head_dim", 8))
    return AudioLDM2UNetConfig(
        in_channels=int(np.asarray(state["conv_in.weight"]).shape[1]),
        out_channels=int(np.asarray(state["conv_out.weight"]).shape[0]),
        block_out_channels=tuple(blocks[i] for i in range(n)),
        layers_per_block=layers,
        attention=tuple(i in attn for i in range(n)),
        attention_head_dim=head_dim,
        cross_attention_dims=tuple(cross),
        norm_num_groups=int(cj.get("norm_num_groups", 32)),
    )


def convert_audioldm2_projection(state: dict) -> dict:
    """AudioLDM2ProjectionModel state dict -> models.audioldm2_unet
    AudioLDM2Projection params."""
    params: dict = {}
    for name, v in state.items():
        v = np.asarray(v)
        if name in ("sos_embed", "eos_embed", "sos_embed_1", "eos_embed_1"):
            _assign(params, [name], v)
        elif name.endswith(".weight"):
            _assign(params, [name[: -len(".weight")], "kernel"],
                    np.ascontiguousarray(v.T))
        elif name.endswith(".bias"):
            _assign(params, [name[: -len(".bias")], "bias"], v)
    return params


# --- ZoeDepth (models/zoedepth.py) ---


def zoedepth_rename(name: str) -> str | None:
    """transformers ZoeDepthForDepthEstimation names -> models.zoedepth
    names (digit-merge covers the lists; the readout Sequential index and
    the per-layer bias table need explicit mapping)."""
    import re

    if name.endswith("relative_position_index"):
        return None  # computed, not a weight
    name = name.replace(
        ".relative_position_bias.relative_position_bias_table",
        ".relative_position_bias",
    )
    name = re.sub(r"\.readout_projects\.(\d+)\.0\.",
                  r".readout_projects.\1.proj.", name)
    return name


def convert_zoedepth(state: dict, config_json: dict | None = None):
    """-> (ZoeConfig, params). The two transposed-conv reassemble resizes
    (factors > 1) are the only layout special-cases (IOHW, stride ==
    kernel)."""
    from .zoedepth import ZoeConfig

    cj = config_json or {}
    bj = cj.get("backbone_config", {})
    bins = (cj.get("bin_configurations") or [{}])[0]
    cfg = ZoeConfig(
        image_size=int(bj.get("image_size", 384)),
        patch_size=int(bj.get("patch_size", 16)),
        hidden_size=int(bj.get("hidden_size", 1024)),
        num_layers=int(bj.get("num_hidden_layers", 24)),
        num_heads=int(bj.get("num_attention_heads", 16)),
        intermediate_size=int(bj.get("intermediate_size", 4096)),
        layer_norm_eps=float(bj.get("layer_norm_eps", 1e-12)),
        out_indices=tuple(bj.get("out_indices", (6, 12, 18, 24))),
        reassemble_factors=tuple(
            cj.get("reassemble_factors", (4, 2, 1, 0.5))
        ),
        neck_hidden_sizes=tuple(
            cj.get("neck_hidden_sizes", (96, 192, 384, 768))
        ),
        fusion_hidden_size=int(cj.get("fusion_hidden_size", 256)),
        bottleneck_features=int(cj.get("bottleneck_features", 256)),
        num_relative_features=int(cj.get("num_relative_features", 32)),
        num_attractors=tuple(cj.get("num_attractors", (16, 8, 4, 1))),
        bin_embedding_dim=int(cj.get("bin_embedding_dim", 128)),
        n_bins=int(bins.get("n_bins", 64)),
        min_depth=float(bins.get("min_depth", 1e-3)),
        max_depth=float(bins.get("max_depth", 10.0)),
        min_temp=float(cj.get("min_temp", 0.0212)),
        max_temp=float(cj.get("max_temp", 50.0)),
    )
    if len(cj.get("bin_configurations", [{}])) > 1:
        raise ValueError(
            "multi-domain (NK) ZoeDepth heads are not supported; use a "
            "single-configuration checkpoint (ZoeD_N)"
        )
    specials = []
    rest = {}
    convt = {
        f"neck.reassemble_stage.layers.{i}.resize.weight"
        for i, f in enumerate(cfg.reassemble_factors) if f > 1
    }
    for k, v in state.items():
        if k in convt:
            arr = np.asarray(v)
            path, _ = torch_name_to_flax_path(k)
            specials.append((path + ["kernel"], arr.transpose(2, 3, 0, 1)))
        else:
            rest[k] = v
    params = convert_state_dict(rest, zoedepth_rename)
    for path, value in specials:
        _assign(params, path, value)
    return cfg, params
