"""M-LSD line-segment detector (MobileV2_MLSD_Large) — the learned
annotator behind the `mlsd` preprocessor.

Reference behavior replaced: swarm/pre_processors/controlnet.py:31
(controlnet_aux MLSDdetector fetched per call). The graph is a 4-channel
MobileNetV2 trunk (first 14 feature blocks, ReLU6, inverted residuals)
whose five FPN taps feed a chain of A/B/C fusion blocks (1x1 fuse +
align-corners 2x upsampling, 3x3 residual refine, dilated head) emitting
a 16-channel map at input/2; channels 7..16 carry the TP-map (center
heat + start/end displacements) that the host decodes into line
segments.

Every BatchNorm folds into its preceding conv at conversion
(models/conversion.py convert_mlsd), so the flax graph is pure
conv+relu6. Module names are this package's own (the torch checkpoint's
Sequential indices don't survive folding); conversion owns the mapping.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp


# MobileNetV2 inverted-residual plan the MLSD trunk uses: (t, c, n, s)
MBV2_SETTING = ((1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2),
                (6, 64, 4, 2), (6, 96, 3, 1))
FPN_TAPS = (1, 3, 6, 10, 13)


@dataclasses.dataclass(frozen=True)
class MLSDConfig:
    in_channels: int = 4  # RGB + constant alpha plane
    stem_channels: int = 32
    head_channels: int = 64
    out_channels: int = 16


def relu6(x):
    return jnp.clip(x, 0.0, 6.0)


def resize_align_corners_2x(x):
    """F.interpolate(scale_factor=2, mode='bilinear', align_corners=True)
    — the shared cascade_unet helper carries the align-corners math."""
    from .cascade_unet import interpolate_bilinear_align_corners

    b, h, w, c = x.shape
    return interpolate_bilinear_align_corners(x, 2 * h, 2 * w)


class _ConvRelu6(nn.Module):
    features: int
    kernel: int = 3
    stride: int = 1
    groups: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        pad = (self.kernel - 1) // 2
        x = nn.Conv(
            self.features, (self.kernel, self.kernel),
            strides=(self.stride, self.stride),
            padding=((pad, pad), (pad, pad)),
            feature_group_count=self.groups,
            dtype=self.dtype, name="conv",
        )(x)
        return relu6(x)


class _InvertedResidual(nn.Module):
    out_channels: int
    stride: int
    expand_ratio: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        in_ch = x.shape[-1]
        hidden = round(in_ch * self.expand_ratio)
        h = x
        if self.expand_ratio != 1:
            h = _ConvRelu6(hidden, kernel=1, dtype=self.dtype,
                           name="expand")(h)
        h = _ConvRelu6(
            hidden, kernel=3, stride=self.stride, groups=hidden,
            dtype=self.dtype, name="depthwise",
        )(h)
        h = nn.Conv(self.out_channels, (1, 1), dtype=self.dtype,
                    name="project")(h)
        if self.stride == 1 and in_ch == self.out_channels:
            h = x + h
        return h


class _BlockA(nn.Module):
    """1x1 fuse of a lateral tap and the carried feature map (optionally
    align-corners 2x upsampled), concatenated."""

    out_channels: int
    upscale: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, lateral, carried):
        b = nn.Conv(self.out_channels, (1, 1), dtype=self.dtype,
                    name="conv1")(carried)
        b = nn.relu(b)
        a = nn.Conv(self.out_channels, (1, 1), dtype=self.dtype,
                    name="conv2")(lateral)
        a = nn.relu(a)
        if self.upscale:
            b = resize_align_corners_2x(b)
        return jnp.concatenate([a, b], axis=-1)


class _BlockB(nn.Module):
    """3x3 residual refine then 3x3 reduce."""

    out_channels: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.Conv(x.shape[-1], (3, 3), padding=((1, 1), (1, 1)),
                    dtype=self.dtype, name="conv1")(x)
        x = nn.relu(h) + x
        x = nn.Conv(self.out_channels, (3, 3), padding=((1, 1), (1, 1)),
                    dtype=self.dtype, name="conv2")(x)
        return nn.relu(x)


class _BlockC(nn.Module):
    """Dilated 3x3 -> 3x3 -> 1x1 head."""

    out_channels: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        x = nn.Conv(c, (3, 3), padding=((5, 5), (5, 5)),
                    kernel_dilation=(5, 5), dtype=self.dtype,
                    name="conv1")(x)
        x = nn.relu(x)
        x = nn.Conv(c, (3, 3), padding=((1, 1), (1, 1)), dtype=self.dtype,
                    name="conv2")(x)
        x = nn.relu(x)
        return nn.Conv(self.out_channels, (1, 1), dtype=self.dtype,
                       name="conv3")(x)


class MLSDNet(nn.Module):
    """[B, H, W, 4] in [-1, 1] -> [B, H/2, W/2, 9] TP map
    (channel 0 = center logit, 1..4 = start/end displacements)."""

    config: MLSDConfig = MLSDConfig()
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        x = _ConvRelu6(cfg.stem_channels, kernel=3, stride=2,
                       dtype=self.dtype, name="features_0")(x)
        taps = {}
        idx = 1
        for t, c, n, s in MBV2_SETTING:
            for i in range(n):
                x = _InvertedResidual(
                    c, s if i == 0 else 1, t, dtype=self.dtype,
                    name=f"features_{idx}",
                )(x)
                if idx in FPN_TAPS:
                    taps[idx] = x
                idx += 1
        c1, c2, c3, c4, c5 = (taps[i] for i in FPN_TAPS)

        hc = cfg.head_channels
        x = _BlockA(hc, upscale=False, dtype=self.dtype, name="block15")(
            c4, c5
        )
        x = _BlockB(hc, dtype=self.dtype, name="block16")(x)
        x = _BlockA(hc, dtype=self.dtype, name="block17")(c3, x)
        x = _BlockB(hc, dtype=self.dtype, name="block18")(x)
        x = _BlockA(hc, dtype=self.dtype, name="block19")(c2, x)
        x = _BlockB(hc, dtype=self.dtype, name="block20")(x)
        x = _BlockA(hc, dtype=self.dtype, name="block21")(c1, x)
        x = _BlockB(hc, dtype=self.dtype, name="block22")(x)
        x = _BlockC(cfg.out_channels, dtype=self.dtype, name="block23")(x)
        # the TP map is the trailing 9 channels (7 auxiliary training
        # channels are dropped exactly as upstream does)
        return x[..., 7:]
