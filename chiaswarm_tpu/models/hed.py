"""HED edge detector (lllyasviel's ControlNetHED, Apache-2.0 weights) —
the learned annotator behind the `scribble` and `softedge` preprocessors.

Reference behavior replaced: swarm/pre_processors/controlnet.py:51-57
(controlnet_aux HEDdetector fetched per call). The graph is a VGG-style
backbone with 5 stages; each stage emits a 1-channel edge logit map via a
1x1 projection, the host resizes all 5 to the input canvas and sigmoids
their mean. Module/param names line up with the checkpoint's state dict
(norm, blockN.convs.M, blockN.projection) so conversion is mechanical.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class HEDConfig:
    channels: tuple[int, ...] = (64, 128, 256, 512, 512)
    layers: tuple[int, ...] = (2, 2, 3, 3, 3)


TINY_HED = HEDConfig(channels=(8, 16), layers=(1, 1))


class _Block(nn.Module):
    out_channels: int
    n_convs: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        for i in range(self.n_convs):
            x = nn.Conv(self.out_channels, (3, 3), padding=((1, 1), (1, 1)),
                        dtype=self.dtype, name=f"convs_{i}")(x)
            x = nn.relu(x)
        proj = nn.Conv(1, (1, 1), dtype=self.dtype, name="projection")(x)
        return x, proj


class HEDNet(nn.Module):
    """[B, H, W, 3] raw RGB in 0..255 -> list of per-stage edge logit maps
    (each [B, H/2^i, W/2^i, 1])."""

    config: HEDConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, pixels):
        cfg = self.config
        # learned input normalization, stored in the checkpoint's NCHW
        # layout [1, 3, 1, 1]
        norm = self.param(
            "norm", nn.initializers.zeros, (1, 3, 1, 1)
        ).astype(self.dtype)
        x = pixels.astype(self.dtype) - norm.transpose(0, 2, 3, 1)
        projections = []
        for i, (ch, n) in enumerate(zip(cfg.channels, cfg.layers)):
            if i > 0:
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            x, proj = _Block(ch, n, dtype=self.dtype, name=f"block{i + 1}")(x)
            projections.append(proj)
        return projections
