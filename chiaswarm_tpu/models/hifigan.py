"""HiFi-GAN vocoder generator (the AudioLDM mel->waveform stage).

Reference behavior replaced: the reference's AudioLDMPipeline carries a
`SpeechT5HifiGan` vocoder inside diffusers (swarm/audio/audioldm.py:23-29
just calls the pipeline). This flax module mirrors the transformers
`SpeechT5HifiGan` graph — conv_pre -> N ConvTranspose upsample stages,
each fused with multi-receptive-field residual blocks (kernels 3/7/11,
dilations 1/3/5) -> conv_post -> tanh — so checkpoints convert
mechanically (conversion.convert_hifigan). NWC layout; the whole vocoder
is one fused conv program on the MXU.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class HifiGanConfig:
    model_in_dim: int = 64  # mel bins
    upsample_initial_channel: int = 512
    upsample_rates: tuple[int, ...] = (5, 4, 2, 2, 2)
    upsample_kernel_sizes: tuple[int, ...] = (16, 16, 8, 4, 4)
    resblock_kernel_sizes: tuple[int, ...] = (3, 7, 11)
    resblock_dilation_sizes: tuple[tuple[int, ...], ...] = (
        (1, 3, 5), (1, 3, 5), (1, 3, 5),
    )
    leaky_relu_slope: float = 0.1
    normalize_before: bool = True


TINY_HIFIGAN = HifiGanConfig(
    model_in_dim=8,
    upsample_initial_channel=16,
    upsample_rates=(4, 2),
    upsample_kernel_sizes=(8, 4),
    resblock_kernel_sizes=(3,),
    resblock_dilation_sizes=((1, 3),),
)


class _ResBlock(nn.Module):
    """HifiGanResidualBlock: dilated conv pairs with leaky-relu."""

    channels: int
    kernel_size: int
    dilations: tuple[int, ...]
    slope: float
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        for i, d in enumerate(self.dilations):
            h = nn.leaky_relu(x, self.slope)
            h = nn.Conv(
                self.channels, (self.kernel_size,), kernel_dilation=(d,),
                dtype=self.dtype, name=f"convs1_{i}",
            )(h)
            h = nn.leaky_relu(h, self.slope)
            h = nn.Conv(
                self.channels, (self.kernel_size,), dtype=self.dtype,
                name=f"convs2_{i}",
            )(h)
            x = x + h
        return x


class HifiGanGenerator(nn.Module):
    config: HifiGanConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, mel):
        """log-mel [B, T, n_mels] -> waveform [B, T * prod(rates)]."""
        cfg = self.config
        if cfg.normalize_before:
            mean = self.param(
                "mean", nn.initializers.zeros, (cfg.model_in_dim,)
            )
            scale = self.param(
                "scale", nn.initializers.ones, (cfg.model_in_dim,)
            )
            mel = (mel - mean) / scale
        x = nn.Conv(
            cfg.upsample_initial_channel, (7,), dtype=self.dtype,
            name="conv_pre",
        )(mel.astype(self.dtype))
        n_kernels = len(cfg.resblock_kernel_sizes)
        for i, (rate, k) in enumerate(
            zip(cfg.upsample_rates, cfg.upsample_kernel_sizes)
        ):
            x = nn.leaky_relu(x, cfg.leaky_relu_slope)
            ch = cfg.upsample_initial_channel // (2 ** (i + 1))
            # torch ConvTranspose1d(pad=(k-rate)//2) == full (VALID)
            # transpose conv cropped by that pad on both ends; SAME only
            # coincides when k-rate is even, and the real AudioLDM vocoder
            # hits an odd case (kernel 16, rate 5)
            x = nn.ConvTranspose(
                ch, (k,), strides=(rate,), padding="VALID",
                dtype=self.dtype, name=f"upsampler_{i}",
            )(x)
            pad = (k - rate) // 2
            if pad:
                x = x[:, pad:-pad]
            # multi-receptive-field fusion: mean of the per-kernel resblocks
            acc = None
            for j, (rk, dil) in enumerate(
                zip(cfg.resblock_kernel_sizes, cfg.resblock_dilation_sizes)
            ):
                r = _ResBlock(
                    ch, rk, tuple(dil), cfg.leaky_relu_slope,
                    dtype=self.dtype, name=f"resblocks_{i * n_kernels + j}",
                )(x)
                acc = r if acc is None else acc + r
            x = acc / n_kernels
        x = nn.leaky_relu(x, cfg.leaky_relu_slope)
        x = nn.Conv(1, (7,), dtype=self.dtype, name="conv_post")(x)
        return jnp.tanh(x)[..., 0]
