"""CLIP BPE tokenizer (self-contained, offline).

The reference gets tokenization implicitly through diffusers pipelines; a
TPU worker must not depend on hub downloads at job time, so the byte-pair
encoder is implemented here and reads `vocab.json` + `merges.txt` from the
local model root. When no vocab ships with a model (hermetic tests, tiny
models), a deterministic hash tokenizer keeps the full text->ids->embedding
path exercised with the same padding/BOS/EOS layout.
"""

from __future__ import annotations

import functools
import hashlib
import json
import re
from pathlib import Path

import numpy as np


@functools.lru_cache()
def bytes_to_unicode() -> dict[int, str]:
    """GPT-2/CLIP reversible byte -> unicode mapping."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("\xa1"), ord("\xac") + 1))
        + list(range(ord("\xae"), ord("\xff") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, map(chr, cs)))


# CLIP's word pattern; \p{L}/\p{N} classes approximated with str.isalpha-
# compatible ASCII ranges plus a catch-all (stdlib `re` has no \p support)
_WORD_PATTERN = re.compile(
    r"""<\|startoftext\|>|<\|endoftext\|>|'s|'t|'re|'ve|'m|'ll|'d"""
    r"""|[a-zA-Z]+|[0-9]|[^\sa-zA-Z0-9]+""",
    re.IGNORECASE,
)


def _clean(text: str) -> str:
    return re.sub(r"\s+", " ", text.strip()).lower()


class CLIPTokenizer:
    """Byte-pair encoding with </w> word terminals, CLIP layout:
    [BOS, tokens..., EOS, pad(EOS or 0)...] to max_length."""

    def __init__(self, vocab: dict[str, int], merges: list[tuple[str, str]],
                 max_length: int = 77):
        self.vocab = vocab
        self.ranks = {m: i for i, m in enumerate(merges)}
        self.byte_encoder = bytes_to_unicode()
        self.max_length = max_length
        self.bos = vocab.get("<|startoftext|>", len(vocab) - 2)
        self.eos = vocab.get("<|endoftext|>", len(vocab) - 1)
        # per-token BPE memo (HF tokenizers keep the same memo
        # unbounded): entries are a few hundred bytes and the key space
        # is natural-language vocabulary, not request volume
        self._cache: dict[str, list[str]] = {}  # swarmlint: disable=SW007

    @classmethod
    def from_dir(cls, path: str | Path, max_length: int = 77) -> "CLIPTokenizer":
        path = Path(path)
        vocab = json.loads((path / "vocab.json").read_text())
        merges = []
        for line in (path / "merges.txt").read_text().splitlines():
            if line.startswith("#") or not line.strip():
                continue
            a, b = line.split()
            merges.append((a, b))
        return cls(vocab, merges, max_length)

    def bpe(self, token: str) -> list[str]:
        if token in self._cache:
            return self._cache[token]
        word = [self.byte_encoder[b] for b in token.encode("utf-8")]
        if not word:
            return []
        word[-1] = word[-1] + "</w>"

        while len(word) > 1:
            pairs = [(word[i], word[i + 1]) for i in range(len(word) - 1)]
            best = min(pairs, key=lambda p: self.ranks.get(p, float("inf")))
            if best not in self.ranks:
                break
            merged = []
            i = 0
            while i < len(word):
                if i < len(word) - 1 and (word[i], word[i + 1]) == best:
                    merged.append(word[i] + word[i + 1])
                    i += 2
                else:
                    merged.append(word[i])
                    i += 1
            word = merged

        self._cache[token] = word
        return word

    def encode(self, text: str) -> list[int]:
        ids = []
        for token in _WORD_PATTERN.findall(_clean(text)):
            for piece in self.bpe(token):
                ids.append(self.vocab.get(piece, self.eos))
        return ids

    def __call__(self, texts: str | list[str]) -> np.ndarray:
        """-> int32 [B, max_length] with BOS/EOS and EOS padding."""
        if isinstance(texts, str):
            texts = [texts]
        batch = np.full((len(texts), self.max_length), self.eos, dtype=np.int32)
        for row, text in enumerate(texts):
            ids = self.encode(text)[: self.max_length - 2]
            batch[row, 0] = self.bos
            batch[row, 1 : 1 + len(ids)] = ids
            batch[row, 1 + len(ids)] = self.eos
        return batch


class HashTokenizer:
    """Deterministic fallback: word -> stable hash id. Keeps the BOS/EOS/pad
    layout of CLIPTokenizer so models see realistic id patterns in tests."""

    def __init__(self, vocab_size: int = 1000, max_length: int = 77):
        self.vocab_size = vocab_size
        self.max_length = max_length
        self.bos = vocab_size - 2
        self.eos = vocab_size - 1

    def encode(self, text: str) -> list[int]:
        words = _clean(text).split()
        ids = []
        for w in words:
            digest = hashlib.sha256(w.encode()).digest()
            ids.append(int.from_bytes(digest[:4], "little") % (self.vocab_size - 2))
        return ids

    def __call__(self, texts: str | list[str]) -> np.ndarray:
        if isinstance(texts, str):
            texts = [texts]
        batch = np.full((len(texts), self.max_length), self.eos, dtype=np.int32)
        for row, text in enumerate(texts):
            ids = self.encode(text)[: self.max_length - 2]
            batch[row, 0] = self.bos
            batch[row, 1 : 1 + len(ids)] = ids
            batch[row, 1 + len(ids)] = self.eos
        return batch


class PlaceholderTokenizer:
    """Base tokenizer extended with textual-inversion placeholder tokens.

    Each placeholder string maps to a fixed run of ids past the base vocab
    (the pipeline appends matching rows to the token-embedding table).
    Splitting happens before BPE so multi-word or bracketed placeholders
    like `<gta5-artwork>` survive intact.
    """

    def __init__(self, base, placeholders: dict[str, list[int]]):
        self.base = base
        self.placeholders = dict(placeholders)
        self.max_length = base.max_length
        self.bos = base.bos
        self.eos = base.eos
        if self.placeholders:
            import re as _re

            pattern = "|".join(
                _re.escape(p)
                for p in sorted(self.placeholders, key=len, reverse=True)
            )
            self._splitter = _re.compile(f"({pattern})")
        else:
            self._splitter = None

    def encode(self, text: str) -> list[int]:
        if self._splitter is None:
            return self.base.encode(text)
        ids: list[int] = []
        for part in self._splitter.split(text):
            if not part:
                continue
            if part in self.placeholders:
                ids.extend(self.placeholders[part])
            else:
                ids.extend(self.base.encode(part))
        return ids

    def __call__(self, texts: str | list[str]) -> np.ndarray:
        if isinstance(texts, str):
            texts = [texts]
        batch = np.full((len(texts), self.max_length), self.eos, dtype=np.int32)
        for row, text in enumerate(texts):
            ids = self.encode(text)[: self.max_length - 2]
            batch[row, 0] = self.bos
            batch[row, 1 : 1 + len(ids)] = ids
            batch[row, 1 + len(ids)] = self.eos
        return batch


def load_tokenizer(model_dir: str | Path | None, vocab_size: int = 49408,
                   max_length: int = 77):
    """CLIPTokenizer when vocab files exist under the model dir, else hash."""
    if model_dir is not None:
        tok_dir = Path(model_dir) / "tokenizer"
        if (tok_dir / "vocab.json").is_file() and (tok_dir / "merges.txt").is_file():
            return CLIPTokenizer.from_dir(tok_dir, max_length)
    return HashTokenizer(vocab_size, max_length)
