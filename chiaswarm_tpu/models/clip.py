"""CLIP text encoders (SD1.x ViT-L, SD2.x OpenCLIP-H, SDXL dual encoders).

Config-driven flax transformer with causal masking; supports returning the
penultimate hidden state (SD2/SDXL use clip-skip style conditioning) and a
final text projection (SDXL's second encoder pools + projects).
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class CLIPTextConfig:
    vocab_size: int = 49408
    hidden_size: int = 1024
    num_layers: int = 23
    num_heads: int = 16
    max_positions: int = 77
    intermediate_mult: int = 4
    hidden_act: str = "gelu"  # gelu (SD2/XL) | quick_gelu (SD1.x ViT-L)
    # output selection: -1 = final layer norm output; -2 = penultimate layer
    hidden_state_index: int = -1
    # False + index -1: the LAST layer's output BEFORE the final LayerNorm
    # (HF `hidden_states[-1]` — Stable Cascade's prior/decoder conditioning)
    apply_final_norm: bool = True
    projection_dim: int = 0  # >0: emit pooled projection (SDXL encoder 2)


def _act(name: str):
    if name == "quick_gelu":
        return lambda x: x * nn.sigmoid(1.702 * x)
    # exact erf gelu (transformers "gelu"); flax defaults to tanh approx
    return lambda x: nn.gelu(x, approximate=False)


class CLIPAttention(nn.Module):
    config: CLIPTextConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, hidden, mask):
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_heads
        b, s, _ = hidden.shape

        def heads(name):
            return nn.Dense(cfg.hidden_size, dtype=self.dtype, name=name)(
                hidden
            ).reshape(b, s, cfg.num_heads, head_dim)

        q, k, v = heads("q_proj"), heads("k_proj"), heads("v_proj")
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * head_dim**-0.5
        logits = logits + mask
        weights = nn.softmax(logits.astype(jnp.float32), axis=-1).astype(self.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", weights, v).reshape(b, s, cfg.hidden_size)
        return nn.Dense(cfg.hidden_size, dtype=self.dtype, name="out_proj")(out)


class CLIPLayer(nn.Module):
    config: CLIPTextConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, hidden, mask):
        cfg = self.config
        hidden = hidden + CLIPAttention(cfg, dtype=self.dtype, name="self_attn")(
            nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="layer_norm1")(hidden),
            mask,
        )
        mlp_in = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="layer_norm2")(hidden)
        h = nn.Dense(
            cfg.hidden_size * cfg.intermediate_mult, dtype=self.dtype, name="fc1"
        )(mlp_in)
        h = _act(cfg.hidden_act)(h)
        return hidden + nn.Dense(cfg.hidden_size, dtype=self.dtype, name="fc2")(h)


class CLIPTextEncoder(nn.Module):
    config: CLIPTextConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, input_ids, extra_embeddings=None, attention_mask=None):
        """input_ids [B, 77] -> dict with:
        - hidden_states: [B, 77, D] conditioning sequence (per config index)
        - pooled: [B, D or projection_dim] EOS-token pooled output

        `attention_mask` [B, S] (1 = attend) composes with the causal mask
        — Stable Cascade's pipelines mask padding (most SD-family callers
        don't pass one, matching diffusers).

        `extra_embeddings` [K, D] carries textual-inversion placeholder
        vectors: ids >= vocab_size index into it (id - vocab_size). Passed
        as data rather than grafted into the Embed table so the resident
        param tree (and its flax shape contract) never changes per job.
        """
        cfg = self.config
        b, s = input_ids.shape

        tok = nn.Embed(
            cfg.vocab_size, cfg.hidden_size, dtype=self.dtype, name="token_embedding"
        )(jnp.minimum(input_ids, cfg.vocab_size - 1))
        if extra_embeddings is not None:
            is_extra = input_ids >= cfg.vocab_size
            extra_idx = jnp.clip(
                input_ids - cfg.vocab_size, 0, extra_embeddings.shape[0] - 1
            )
            tok = jnp.where(
                is_extra[..., None],
                extra_embeddings.astype(tok.dtype)[extra_idx],
                tok,
            )
        pos = self.param(
            "position_embedding",
            nn.initializers.normal(0.01),
            (cfg.max_positions, cfg.hidden_size),
        ).astype(self.dtype)
        hidden = tok + pos[None, :s, :]

        causal = jnp.triu(jnp.full((s, s), -1e9, self.dtype), k=1)[None, None]
        if attention_mask is not None:
            pad = jnp.where(
                attention_mask[:, None, None, :].astype(bool), 0.0, -1e9
            ).astype(self.dtype)
            causal = causal + pad

        collected = []
        for i in range(cfg.num_layers):
            collected.append(hidden)
            hidden = CLIPLayer(cfg, dtype=self.dtype, name=f"layers_{i}")(hidden, causal)
        pre_ln = hidden
        final = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="final_layer_norm")(
            hidden
        )
        collected.append(final)  # index -1

        # hidden_state_index -2 = input of the last layer (diffusers clip-skip)
        if cfg.hidden_state_index == -1:
            out_hidden = final if cfg.apply_final_norm else pre_ln
        else:
            out_hidden = collected[cfg.hidden_state_index]

        # pooled = final-LN state at each sequence's first EOS. EOS is the
        # highest id in the BASE vocab (both tokenizers), but textual-
        # inversion placeholder ids sit past it — match the id exactly
        # instead of argmax-ing raw ids
        eos_idx = jnp.argmax(
            (input_ids == cfg.vocab_size - 1).astype(jnp.int32), axis=-1
        )
        pooled = final[jnp.arange(b), eos_idx]
        if cfg.projection_dim:
            pooled = nn.Dense(
                cfg.projection_dim, use_bias=False, dtype=self.dtype,
                name="text_projection",
            )(pooled)

        return {"hidden_states": out_hidden, "pooled": pooled}
