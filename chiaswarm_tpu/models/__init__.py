"""Flax model zoo: UNet2D (SD/SDXL), AutoencoderKL, CLIP text encoders.

Written NHWC-first for TPU (XLA tiles NHWC convs onto the MXU directly);
HF torch checkpoints are converted by `conversion.py`. Architecture parity
targets the models the reference serves via diffusers (SURVEY §2.7).
"""

from .clip import CLIPTextConfig, CLIPTextEncoder
from .unet2d import UNet2DConfig, UNet2DConditionModel
from .vae import AutoencoderKL, VAEConfig

__all__ = [
    "CLIPTextConfig",
    "CLIPTextEncoder",
    "UNet2DConfig",
    "UNet2DConditionModel",
    "AutoencoderKL",
    "VAEConfig",
]
