"""Kandinsky 2.2 decoder UNet: the diffusers `UNet2DConditionModel`
instance kandinsky-community/kandinsky-2-2-decoder ships (reference loads
it per job via KandinskyV22Pipeline, swarm/diffusion/pipeline_steps.py:7-38)
— rebuilt as one flax module in NHWC with attention on the TPU kernel path.

Architecture facts this module encodes (from the checkpoint's unet
config.json): ResnetDownsample/SimpleCrossAttn down blocks, SimpleCrossAttn
mid/up blocks, `scale_shift` AdaGN resnets, resnet-based down/upsamplers,
added-KV attention (image-projection tokens concatenated with the spatial
self-attention KV), image conditioning through BOTH the additive time-embed
branch (ImageTimeEmbedding) and the cross-attention tokens (ImageProjection)
— no text cross-attention at all; the prior's CLIP image embedding is the
only conditioning.

Module names line up with the merged diffusers state-dict names so
conversion (models/conversion.py convert_kandinsky_unet) is mechanical.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp

from .layers import FusedGroupNorm, TimestepEmbedding, timestep_embedding


@dataclasses.dataclass(frozen=True)
class K22UNetConfig:
    in_channels: int = 4
    out_channels: int = 8  # learned variance: pipeline keeps channels [:4]
    block_out_channels: tuple[int, ...] = (384, 768, 1280, 1280)
    layers_per_block: int = 3
    attention_head_dim: int = 64
    cross_attention_dim: int = 768
    encoder_hid_dim: int = 1280  # CLIP image-embedding width
    # ImageProjection token count; conversion infers the real value from
    # `encoder_hid_proj.image_embeds.weight`'s output width
    image_proj_tokens: int = 32
    # which down blocks carry attention (block 0 is pure resnet)
    down_attention: tuple[bool, ...] = (False, True, True, True)
    norm_num_groups: int = 32
    # "image": K2.2 — a single CLIP image embedding feeds BOTH the additive
    #   time branch (ImageTimeEmbedding) and the ImageProjection tokens.
    # "text": DeepFloyd IF — T5 states feed an attention-pooled
    #   TextTimeEmbedding and a Linear encoder_hid projection.
    # "text_image": K2.1 — MCLIP text states + pooled text embed + prior
    #   image embed feed TextImageTimeEmbedding (additive) and
    #   TextImageProjection (image tokens prepended to projected text).
    conditioning: str = "image"
    # K2.1: width of the prior image embedding entering the text_image
    # projections (encoder_hid_dim is the TEXT hidden width there)
    image_embed_dim: int = 768
    act: str = "silu"  # resnet/out nonlinearity ("gelu" for IF)
    # IF super-resolution stages carry a second timestep conditioning (the
    # aug/noise level) through a class embedding
    class_embed_timestep: bool = False
    addition_embed_heads: int = 64  # TextTimeEmbedding pool heads


TINY_K22_UNET = K22UNetConfig(
    block_out_channels=(32, 64),
    layers_per_block=1,
    attention_head_dim=8,
    cross_attention_dim=16,
    encoder_hid_dim=32,
    image_proj_tokens=2,
    down_attention=(False, True),
    norm_num_groups=8,
)

# DeepFloyd IF-I (pixel-space base stage) real geometry analog; conversion
# re-derives the true numbers from the checkpoint
IF_UNET = K22UNetConfig(
    in_channels=3,
    out_channels=6,  # pixels + learned variance
    block_out_channels=(704, 1408, 2112, 2816),
    layers_per_block=3,
    attention_head_dim=64,
    cross_attention_dim=2048,
    encoder_hid_dim=4096,  # T5-XXL hidden width
    image_proj_tokens=0,  # text mode: no ImageProjection tokens
    down_attention=(False, True, True, True),
    conditioning="text",
    act="gelu",
    addition_embed_heads=64,
)

TINY_IF_UNET = K22UNetConfig(
    in_channels=3,
    out_channels=3,
    block_out_channels=(32, 64),
    layers_per_block=1,
    attention_head_dim=8,
    cross_attention_dim=16,
    encoder_hid_dim=32,
    image_proj_tokens=0,  # text mode: no ImageProjection tokens
    down_attention=(False, True),
    norm_num_groups=8,
    conditioning="text",
    act="gelu",
    addition_embed_heads=4,
)

TINY_IF_SR_UNET = dataclasses.replace(
    TINY_IF_UNET, in_channels=6, class_embed_timestep=True
)


def _act(name: str):
    if name == "gelu":
        # erf gelu, diffusers parity (approximate=True would silently
        # diverge from converted IF checkpoints)
        return lambda x: nn.gelu(x, approximate=False)
    return nn.silu


class KResnetBlock(nn.Module):
    """diffusers ResnetBlock2D with time_embedding_norm='scale_shift' and
    optional resnet-internal down/up sampling (avg-pool / nearest-2x applied
    to both branches BEFORE conv1, matching Downsample2D/Upsample2D with
    use_conv=False)."""

    out_channels: int
    groups: int = 32
    down: bool = False
    up: bool = False
    act: str = "silu"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, temb):
        act = _act(self.act)
        h = FusedGroupNorm(self.groups, epsilon=1e-5, dtype=self.dtype,
                           name="norm1")(x)
        h = act(h)
        if self.down:
            x = nn.avg_pool(x, (2, 2), strides=(2, 2))
            h = nn.avg_pool(h, (2, 2), strides=(2, 2))
        elif self.up:
            x = jnp.repeat(jnp.repeat(x, 2, axis=1), 2, axis=2)
            h = jnp.repeat(jnp.repeat(h, 2, axis=1), 2, axis=2)
        h = nn.Conv(self.out_channels, (3, 3), padding=((1, 1), (1, 1)),
                    dtype=self.dtype, name="conv1")(h)
        # scale_shift AdaGN: the projection emits [scale | shift]; the temb
        # nonlinearity is the BLOCK's act (diffusers ResnetBlock2D applies
        # self.nonlinearity to temb, so IF uses gelu here too)
        t = nn.Dense(2 * self.out_channels, dtype=self.dtype,
                     name="time_emb_proj")(act(temb))
        scale, shift = jnp.split(t[:, None, None, :], 2, axis=-1)
        h = FusedGroupNorm(self.groups, epsilon=1e-5, dtype=self.dtype,
                           name="norm2")(h)
        h = h * (1.0 + scale) + shift
        h = act(h)
        h = nn.Conv(self.out_channels, (3, 3), padding=((1, 1), (1, 1)),
                    dtype=self.dtype, name="conv2")(h)
        if x.shape[-1] != self.out_channels:
            x = nn.Conv(self.out_channels, (1, 1), dtype=self.dtype,
                        name="conv_shortcut")(x)
        return x + h


class KAttention(nn.Module):
    """diffusers Attention with AttnAddedKVProcessor: token-space group norm,
    self KV concatenated AFTER the added (image-projection) KV, residual
    over the spatial map."""

    heads: int
    head_dim: int
    channels: int
    groups: int = 32
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, context):
        """x [B, H, W, C]; context [B, N, cross_dim] -> [B, H, W, C]."""
        b, h, w, c = x.shape
        tokens = x.reshape(b, h * w, c)
        # torch GroupNorm over [B, C, S]: stats over (group channels, S) —
        # flax GroupNorm on [B, S, C] reduces identically
        norm = FusedGroupNorm(self.groups, epsilon=1e-5, dtype=self.dtype,
                              name="group_norm")(tokens)
        inner = self.heads * self.head_dim
        q = nn.Dense(inner, dtype=self.dtype, name="to_q")(norm)
        k_self = nn.Dense(inner, dtype=self.dtype, name="to_k")(norm)
        v_self = nn.Dense(inner, dtype=self.dtype, name="to_v")(norm)
        k_add = nn.Dense(inner, dtype=self.dtype, name="add_k_proj")(
            context.astype(self.dtype)
        )
        v_add = nn.Dense(inner, dtype=self.dtype, name="add_v_proj")(
            context.astype(self.dtype)
        )
        k = jnp.concatenate([k_add, k_self], axis=1)
        v = jnp.concatenate([v_add, v_self], axis=1)
        shape4 = lambda t: t.reshape(b, t.shape[1], self.heads, self.head_dim)
        from ..ops import dot_product_attention

        out = dot_product_attention(shape4(q), shape4(k), shape4(v))
        out = out.reshape(b, h * w, inner)
        out = nn.Dense(self.channels, dtype=self.dtype, name="to_out_0")(out)
        return x + out.reshape(b, h, w, self.channels)


class KDownBlock(nn.Module):
    """ResnetDownsampleBlock2D / SimpleCrossAttnDownBlock2D: `layers`
    resnets (each followed by attention when `attend`), then a resnet
    downsampler. Skips collected after every resnet(+attn) and after the
    downsampler — identical skip cadence to the SD UNet."""

    config: K22UNetConfig
    out_channels: int
    attend: bool
    add_downsample: bool
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, temb, context):
        cfg = self.config
        skips = []
        for i in range(cfg.layers_per_block):
            x = KResnetBlock(self.out_channels, groups=cfg.norm_num_groups,
                             act=cfg.act, dtype=self.dtype,
                             name=f"resnets_{i}")(x, temb)
            if self.attend:
                x = KAttention(
                    self.out_channels // cfg.attention_head_dim,
                    cfg.attention_head_dim, self.out_channels,
                    groups=cfg.norm_num_groups, dtype=self.dtype,
                    name=f"attentions_{i}",
                )(x, context)
            skips.append(x)
        if self.add_downsample:
            x = KResnetBlock(self.out_channels, groups=cfg.norm_num_groups,
                             down=True, act=cfg.act, dtype=self.dtype,
                             name="downsamplers_0")(x, temb)
            skips.append(x)
        return x, skips


class KUpBlock(nn.Module):
    """SimpleCrossAttnUpBlock2D / ResnetUpsampleBlock2D with the resnet
    upsampler."""

    config: K22UNetConfig
    out_channels: int
    attend: bool
    add_upsample: bool
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, skips, temb, context):
        cfg = self.config
        for i in range(cfg.layers_per_block + 1):
            x = jnp.concatenate([x, skips.pop()], axis=-1)
            x = KResnetBlock(self.out_channels, groups=cfg.norm_num_groups,
                             act=cfg.act, dtype=self.dtype,
                             name=f"resnets_{i}")(x, temb)
            if self.attend:
                x = KAttention(
                    self.out_channels // cfg.attention_head_dim,
                    cfg.attention_head_dim, self.out_channels,
                    groups=cfg.norm_num_groups, dtype=self.dtype,
                    name=f"attentions_{i}",
                )(x, context)
        if self.add_upsample:
            x = KResnetBlock(self.out_channels, groups=cfg.norm_num_groups,
                             up=True, act=cfg.act, dtype=self.dtype,
                             name="upsamplers_0")(x, temb)
        return x


class AttentionPooling(nn.Module):
    """diffusers AttentionPooling (IF's TextTimeEmbedding pool): a mean+
    positional class token attends the sequence; its attention output is
    the pooled vector."""

    num_heads: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, s, width = x.shape
        pos = self.param(
            "positional_embedding", nn.initializers.normal(width**-0.5),
            (1, width),
        ).astype(self.dtype)
        cls = jnp.mean(x, axis=1, keepdims=True) + pos[None]
        seq = jnp.concatenate([cls, x], axis=1)
        hd = width // self.num_heads
        shape = lambda t: t.reshape(b, t.shape[1], self.num_heads, hd)
        q = shape(nn.Dense(width, dtype=self.dtype, name="q_proj")(cls))
        k = shape(nn.Dense(width, dtype=self.dtype, name="k_proj")(seq))
        v = shape(nn.Dense(width, dtype=self.dtype, name="v_proj")(seq))
        scale = hd**-0.5
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        w = nn.softmax(logits.astype(jnp.float32), axis=-1).astype(self.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", w, v).reshape(b, 1, width)
        return out[:, 0]


class K22UNet(nn.Module):
    config: K22UNetConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, sample, timesteps, cond, class_labels=None):
        """sample [B, H, W, C_in], timesteps [B] -> [B, H, W, C_out].

        `cond` is the image embedding [B, E] (conditioning="image") or the
        T5 states [B, S, E] (conditioning="text"). `class_labels` [B] is
        the IF super-res aug/noise level (class_embed_timestep)."""
        cfg = self.config
        if jnp.ndim(timesteps) == 0:
            timesteps = jnp.broadcast_to(timesteps, (sample.shape[0],))

        temb_dim = cfg.block_out_channels[0] * 4
        t_feat = timestep_embedding(
            timesteps, cfg.block_out_channels[0], dtype=self.dtype
        )
        temb = TimestepEmbedding(temb_dim, dtype=self.dtype,
                                 name="time_embedding")(t_feat)
        if not isinstance(cond, dict):
            cond = cond.astype(self.dtype)
        if cfg.conditioning == "image":
            # addition_embed_type="image" (ImageTimeEmbedding): the image
            # embed joins the timestep embedding additively
            aug = nn.Dense(temb_dim, dtype=self.dtype, name="aug_emb_proj")(cond)
            aug = nn.LayerNorm(dtype=self.dtype, name="aug_emb_norm")(aug)
            temb = temb + aug
            # encoder_hid_dim_type="image_proj" (ImageProjection): the image
            # embed also becomes the cross-attention token sequence
            ctx = nn.Dense(
                cfg.image_proj_tokens * cfg.cross_attention_dim,
                dtype=self.dtype, name="hid_proj",
            )(cond).reshape(-1, cfg.image_proj_tokens, cfg.cross_attention_dim)
            ctx = nn.LayerNorm(dtype=self.dtype, name="hid_proj_norm")(ctx)
        elif cfg.conditioning == "text_image":
            # K2.1: `cond` is a dict {"text_states" [B,S,Dt], "text_embeds"
            # [B,Dt'], "image_embeds" [B,Di]}.
            # addition_embed_type="text_image" (TextImageTimeEmbedding):
            # LN(text_proj(pooled text)) + image_proj(image embed)
            text_states = cond["text_states"].astype(self.dtype)
            text_embeds = cond["text_embeds"].astype(self.dtype)
            image_embeds = cond["image_embeds"].astype(self.dtype)
            aug_text = nn.LayerNorm(dtype=self.dtype, name="aug_emb_text_norm")(
                nn.Dense(temb_dim, dtype=self.dtype,
                         name="aug_emb_text_proj")(text_embeds)
            )
            aug_img = nn.Dense(temb_dim, dtype=self.dtype,
                               name="aug_emb_image_proj")(image_embeds)
            temb = temb + aug_text + aug_img
            # encoder_hid_dim_type="text_image_proj" (TextImageProjection):
            # image tokens prepended to the projected text sequence (no LN)
            img_tokens = nn.Dense(
                cfg.image_proj_tokens * cfg.cross_attention_dim,
                dtype=self.dtype, name="hid_proj_image",
            )(image_embeds).reshape(
                -1, cfg.image_proj_tokens, cfg.cross_attention_dim
            )
            txt_tokens = nn.Dense(cfg.cross_attention_dim, dtype=self.dtype,
                                  name="hid_proj_text")(text_states)
            ctx = jnp.concatenate([img_tokens, txt_tokens], axis=1)
        else:
            # IF: addition_embed_type="text" (TextTimeEmbedding = LN ->
            # attention pool -> proj -> LN), encoder_hid_dim_type="text_proj"
            aug = nn.LayerNorm(dtype=self.dtype, name="aug_emb_norm1")(cond)
            aug = AttentionPooling(cfg.addition_embed_heads, dtype=self.dtype,
                                   name="aug_emb_pool")(aug)
            aug = nn.Dense(temb_dim, dtype=self.dtype, name="aug_emb_proj")(aug)
            aug = nn.LayerNorm(dtype=self.dtype, name="aug_emb_norm2")(aug)
            temb = temb + aug
            ctx = nn.Dense(cfg.cross_attention_dim, dtype=self.dtype,
                           name="hid_proj")(cond)
        if cfg.class_embed_timestep:
            # IF-II: the SR noise level rides a second timestep embedding
            if class_labels is None:
                class_labels = jnp.zeros_like(timesteps)
            c_feat = timestep_embedding(
                class_labels, cfg.block_out_channels[0], dtype=self.dtype
            )
            temb = temb + TimestepEmbedding(
                temb_dim, dtype=self.dtype, name="class_embedding"
            )(c_feat)

        x = nn.Conv(cfg.block_out_channels[0], (3, 3),
                    padding=((1, 1), (1, 1)), dtype=self.dtype,
                    name="conv_in")(sample)

        skips = [x]
        for b, out_ch in enumerate(cfg.block_out_channels):
            last = b == len(cfg.block_out_channels) - 1
            x, block_skips = KDownBlock(
                cfg, out_ch, attend=cfg.down_attention[b],
                add_downsample=not last, dtype=self.dtype,
                name=f"down_blocks_{b}",
            )(x, temb, ctx)
            skips.extend(block_skips)

        mid_ch = cfg.block_out_channels[-1]
        x = KResnetBlock(mid_ch, groups=cfg.norm_num_groups, act=cfg.act,
                         dtype=self.dtype, name="mid_block_resnets_0")(x, temb)
        x = KAttention(
            mid_ch // cfg.attention_head_dim, cfg.attention_head_dim, mid_ch,
            groups=cfg.norm_num_groups, dtype=self.dtype,
            name="mid_block_attentions_0",
        )(x, ctx)
        x = KResnetBlock(mid_ch, groups=cfg.norm_num_groups, act=cfg.act,
                         dtype=self.dtype, name="mid_block_resnets_1")(x, temb)

        for b, out_ch in enumerate(reversed(cfg.block_out_channels)):
            rev = len(cfg.block_out_channels) - 1 - b
            last = b == len(cfg.block_out_channels) - 1
            x = KUpBlock(
                cfg, out_ch, attend=cfg.down_attention[rev],
                add_upsample=not last, dtype=self.dtype,
                name=f"up_blocks_{b}",
            )(x, skips, temb, ctx)

        x = FusedGroupNorm(cfg.norm_num_groups, epsilon=1e-5,
                           dtype=self.dtype, act="silu",
                           name="conv_norm_out")(x)
        return nn.Conv(cfg.out_channels, (3, 3), padding=((1, 1), (1, 1)),
                       dtype=self.dtype, name="conv_out")(x)
