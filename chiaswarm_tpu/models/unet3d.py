"""UNet3DConditionModel — the modelscope/zeroscope text-to-video graph.

Reference behavior replaced: swarm/video/tx2vid.py loads
cerspense/zeroscope_v2_576w / damo-vilab text-to-video (diffusers
UNet3DConditionModel) per job. TPU rebuild: frames ride the batch axis
([B*F, H, W, C]) so every spatial op stays a large MXU-friendly 2D conv /
attention; the temporal pieces — factorized (3,1,1) conv stacks and
frame-axis transformers — reshape locally and never materialize NCFHW.

Per-layer graph (diffusers unet_3d_blocks): resnet -> TemporalConvLayer
-> Transformer2D (text cross-attention) -> TransformerTemporal
(frame self-attention, double_self_attention=True, no positional
embeddings) with a TransformerTemporal at conv_in (`transformer_in`).
Module names mirror the merged diffusers state-dict names so
conversion.convert_unet3d is mechanical; numeric parity vs an exact-key
torch mirror is asserted in tests/test_unet3d_conversion.py.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp

from .layers import (
    BasicTransformerBlock,
    Downsample2D,
    FusedGroupNorm,
    ResnetBlock2D,
    TimestepEmbedding,
    Transformer2DModel,
    Upsample2D,
    timestep_embedding,
)


@dataclasses.dataclass(frozen=True)
class UNet3DConfig:
    in_channels: int = 4
    out_channels: int = 4
    block_out_channels: tuple[int, ...] = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    # per down block: spatial+temporal attention present? (last block is
    # plain DownBlock3D in the reference geometry)
    attention: tuple[bool, ...] = (True, True, True, False)
    attention_head_dim: int = 64
    cross_attention_dim: int = 1024
    norm_num_groups: int = 32


TINY_UNET3D = UNet3DConfig(
    block_out_channels=(32, 64),
    layers_per_block=1,
    attention=(True, False),
    attention_head_dim=8,
    cross_attention_dim=16,
    norm_num_groups=8,
)


class TemporalConvLayer(nn.Module):
    """diffusers TemporalConvLayer: four GroupNorm->SiLU->(3,1,1)-conv
    stages with an identity residual (conv4 is zero-initialized so an
    unconverted layer is a no-op on the spatial model)."""

    channels: int
    groups: int = 32
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, num_frames: int):
        bf, h, w, c = x.shape
        b = bf // num_frames
        hidden = x.reshape(b, num_frames, h, w, c)
        identity = hidden
        for i in range(1, 5):
            hidden = FusedGroupNorm(
                self.groups, epsilon=1e-5, dtype=self.dtype, act="silu",
                name=f"conv{i}_norm",
            )(hidden)
            hidden = nn.Conv(
                self.channels, (3, 1, 1),
                padding=((1, 1), (0, 0), (0, 0)),
                kernel_init=(
                    nn.initializers.zeros if i == 4
                    else nn.initializers.lecun_normal()
                ),
                dtype=self.dtype, name=f"conv{i}_conv",
            )(hidden)
        return (identity + hidden).reshape(bf, h, w, c)


class TransformerTemporal(nn.Module):
    """diffusers TransformerTemporalModel (double_self_attention=True, no
    positional embeddings): frame-axis transformer at fixed spatial
    positions, residual."""

    num_heads: int
    head_dim: int
    num_layers: int = 1
    groups: int = 32
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, num_frames: int):
        bf, h, w, c = x.shape
        b = bf // num_frames
        # inner width = heads * head_dim, which differs from the channel
        # count at `transformer_in` (diffusers builds it with 8 heads of
        # attention_head_dim regardless of block width)
        inner = self.num_heads * self.head_dim
        residual = x
        hidden = FusedGroupNorm(
            self.groups, epsilon=1e-6, dtype=self.dtype, name="norm"
        )(x)
        hidden = hidden.reshape(b, num_frames, h * w, c)
        hidden = hidden.transpose(0, 2, 1, 3).reshape(
            b * h * w, num_frames, c
        )
        hidden = nn.Dense(inner, dtype=self.dtype, name="proj_in")(hidden)
        for i in range(self.num_layers):
            hidden = BasicTransformerBlock(
                inner, self.num_heads, self.head_dim, dtype=self.dtype,
                name=f"transformer_blocks_{i}",
            )(hidden, None)
        hidden = nn.Dense(c, dtype=self.dtype, name="proj_out")(hidden)
        hidden = hidden.reshape(b, h * w, num_frames, c).transpose(0, 2, 1, 3)
        return hidden.reshape(bf, h, w, c) + residual


def unet3d_backbone(cfg: UNet3DConfig, dtype, sample, temb, ctx,
                    num_frames: int):
    """conv_in -> transformer_in -> down/mid/up -> out head, with the
    module names conversion.unet3d_rename maps. Must be called inside a
    parent module's compact `__call__` (inline submodules register on the
    caller) — shared by UNet3DConditionModel and the I2VGenXL variant,
    which differ only in the conditioning assembled around this trunk."""
    g = cfg.norm_num_groups
    heads_of = lambda ch: ch // cfg.attention_head_dim
    x = nn.Conv(
        cfg.block_out_channels[0], (3, 3), padding=((1, 1), (1, 1)),
        dtype=dtype, name="conv_in",
    )(sample)
    # diffusers builds transformer_in with 8 heads of
    # attention_head_dim regardless of the block width
    x = TransformerTemporal(
        8, cfg.attention_head_dim, groups=g, dtype=dtype,
        name="transformer_in",
    )(x, num_frames)

    skips = [x]
    for bidx, out_ch in enumerate(cfg.block_out_channels):
        last = bidx == len(cfg.block_out_channels) - 1
        for i in range(cfg.layers_per_block):
            x = ResnetBlock2D(
                out_ch, dtype=dtype,
                name=f"down_{bidx}_resnets_{i}",
            )(x, temb)
            x = TemporalConvLayer(
                out_ch, groups=g, dtype=dtype,
                name=f"down_{bidx}_temp_convs_{i}",
            )(x, num_frames)
            if cfg.attention[bidx]:
                x = Transformer2DModel(
                    heads_of(out_ch), cfg.attention_head_dim, 1,
                    dtype=dtype,
                    name=f"down_{bidx}_attentions_{i}",
                )(x, ctx)
                x = TransformerTemporal(
                    heads_of(out_ch), cfg.attention_head_dim, groups=g,
                    dtype=dtype,
                    name=f"down_{bidx}_temp_attentions_{i}",
                )(x, num_frames)
            skips.append(x)
        if not last:
            x = Downsample2D(
                out_ch, dtype=dtype, name=f"down_{bidx}_downsample"
            )(x)
            skips.append(x)

    mid_ch = cfg.block_out_channels[-1]
    x = ResnetBlock2D(mid_ch, dtype=dtype, name="mid_resnets_0")(
        x, temb
    )
    x = TemporalConvLayer(
        mid_ch, groups=g, dtype=dtype, name="mid_temp_convs_0"
    )(x, num_frames)
    x = Transformer2DModel(
        heads_of(mid_ch), cfg.attention_head_dim, 1, dtype=dtype,
        name="mid_attentions_0",
    )(x, ctx)
    x = TransformerTemporal(
        heads_of(mid_ch), cfg.attention_head_dim, groups=g,
        dtype=dtype, name="mid_temp_attentions_0",
    )(x, num_frames)
    x = ResnetBlock2D(mid_ch, dtype=dtype, name="mid_resnets_1")(
        x, temb
    )
    x = TemporalConvLayer(
        mid_ch, groups=g, dtype=dtype, name="mid_temp_convs_1"
    )(x, num_frames)

    for bidx, out_ch in enumerate(reversed(cfg.block_out_channels)):
        rev = len(cfg.block_out_channels) - 1 - bidx
        last = bidx == len(cfg.block_out_channels) - 1
        for i in range(cfg.layers_per_block + 1):
            x = jnp.concatenate([x, skips.pop()], axis=-1)
            x = ResnetBlock2D(
                out_ch, dtype=dtype, name=f"up_{bidx}_resnets_{i}"
            )(x, temb)
            x = TemporalConvLayer(
                out_ch, groups=g, dtype=dtype,
                name=f"up_{bidx}_temp_convs_{i}",
            )(x, num_frames)
            if cfg.attention[rev]:
                x = Transformer2DModel(
                    heads_of(out_ch), cfg.attention_head_dim, 1,
                    dtype=dtype,
                    name=f"up_{bidx}_attentions_{i}",
                )(x, ctx)
                x = TransformerTemporal(
                    heads_of(out_ch), cfg.attention_head_dim, groups=g,
                    dtype=dtype,
                    name=f"up_{bidx}_temp_attentions_{i}",
                )(x, num_frames)
        if not last:
            x = Upsample2D(
                out_ch, dtype=dtype, name=f"up_{bidx}_upsample"
            )(x)

    x = FusedGroupNorm(g, epsilon=1e-5, dtype=dtype, act="silu",
                       name="conv_norm_out")(x)
    return nn.Conv(
        cfg.out_channels, (3, 3), padding=((1, 1), (1, 1)),
        dtype=dtype, name="conv_out",
    )(x)


class UNet3DConditionModel(nn.Module):
    config: UNet3DConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, sample, timesteps, encoder_hidden_states,
                 num_frames: int):
        """sample [B*F, H, W, C_in]; timesteps [B*F]; encoder_hidden_states
        [B*F, S, D] (text states repeated per frame) -> [B*F, H, W, C_out].
        """
        cfg = self.config
        if jnp.ndim(timesteps) == 0:
            timesteps = jnp.broadcast_to(timesteps, (sample.shape[0],))

        temb_dim = cfg.block_out_channels[0] * 4
        t_feat = timestep_embedding(
            timesteps, cfg.block_out_channels[0], dtype=self.dtype
        )
        temb = TimestepEmbedding(
            temb_dim, dtype=self.dtype, name="time_embedding"
        )(t_feat)
        ctx = encoder_hidden_states.astype(self.dtype)
        return unet3d_backbone(
            cfg, self.dtype, sample, temb, ctx, num_frames
        )
