"""Analytic FLOP counts for the UNet forward pass, for MFU reporting.

The reference has no performance accounting at all (SURVEY §5 'tracing:
absent'); here each bench run reports model FLOPs utilisation so perf
regressions are visible as a fraction of peak, not just wall-clock.

Counts mirror `unet2d.py`/`layers.py` exactly (convs as 2*K*K*Cin*Cout*H*W,
matmuls as 2*M*N*K, attention as 2*S*S_kv*inner twice). Elementwise/norm
work is omitted — on TPU it is fused and bandwidth-bound, not FLOP-bound.
"""

from __future__ import annotations

from .unet2d import UNet2DConfig


def _resnet(cin: int, cout: int, s: int, temb_dim: int) -> float:
    f = 2 * 9 * cin * cout * s  # conv1
    f += 2 * 9 * cout * cout * s  # conv2
    f += 2 * temb_dim * cout  # time_emb_proj (per batch row, no spatial)
    if cin != cout:
        f += 2 * cin * cout * s  # 1x1 shortcut
    return f


def _transformer(ch: int, n_layers: int, s: int, ctx_len: int,
                 cross_dim: int) -> float:
    f = 2 * 2 * ch * ch * s  # proj_in + proj_out
    per_layer = 0.0
    # self-attention: q,k,v,out projections + scores + weighted sum
    per_layer += 4 * 2 * ch * ch * s
    per_layer += 2 * 2 * s * s * ch
    # cross-attention: q,out on ch; k,v on cross_dim; attn over ctx_len
    per_layer += 2 * 2 * ch * ch * s
    per_layer += 2 * 2 * cross_dim * ch * ctx_len
    per_layer += 2 * 2 * s * ctx_len * ch
    # GEGLU MLP: proj to 2*4ch, gate, project back
    per_layer += 2 * ch * (8 * ch) * s + 2 * (4 * ch) * ch * s
    return f + n_layers * per_layer


def unet_call_flops(cfg: UNet2DConfig, lh: int, lw: int, batch: int,
                    ctx_len: int = 77) -> float:
    """FLOPs of ONE UNet2DConditionModel.__call__ on [batch, lh, lw, C]."""
    chans = cfg.block_out_channels
    temb_dim = chans[0] * 4
    s0 = lh * lw
    f = 2 * 9 * cfg.in_channels * chans[0] * s0  # conv_in

    # down path: level b runs at spatial s0 / 4^b
    skip_specs = [(chans[0], 0)]  # (channels, level) for each skip tensor
    in_ch = chans[0]
    for b, out_ch in enumerate(chans):
        s = s0 // (4 ** b)
        for _ in range(cfg.layers_per_block):
            f += _resnet(in_ch, out_ch, s, temb_dim)
            if cfg.transformer_layers[b] > 0:
                f += _transformer(out_ch, cfg.transformer_layers[b], s,
                                  ctx_len, cfg.cross_attention_dim)
            in_ch = out_ch
            skip_specs.append((out_ch, b))
        if b != len(chans) - 1:
            f += 2 * 9 * out_ch * out_ch * (s // 4)  # strided downsample conv
            skip_specs.append((out_ch, b + 1))

    # mid block at the deepest level
    s_mid = s0 // (4 ** (len(chans) - 1))
    mid_ch = chans[-1]
    f += 2 * _resnet(mid_ch, mid_ch, s_mid, temb_dim)
    f += _transformer(mid_ch, cfg.mid_transformer_layers, s_mid, ctx_len,
                      cfg.cross_attention_dim)

    # up path: concatenated skips make the resnet input wider
    x_ch = mid_ch
    for b, out_ch in enumerate(reversed(chans)):
        rev = len(chans) - 1 - b
        for _ in range(cfg.layers_per_block + 1):
            skip_ch, skip_level = skip_specs.pop()
            s = s0 // (4 ** skip_level)
            f += _resnet(x_ch + skip_ch, out_ch, s, temb_dim)
            if cfg.transformer_layers[rev] > 0:
                f += _transformer(out_ch, cfg.transformer_layers[rev], s,
                                  ctx_len, cfg.cross_attention_dim)
            x_ch = out_ch
        if b != len(chans) - 1:
            s_up = s0 // (4 ** (rev - 1))
            f += 2 * 9 * out_ch * out_ch * s_up  # post-resize conv

    f += 2 * 9 * chans[0] * cfg.out_channels * s0  # conv_out
    return float(f) * batch


def denoise_flops(cfg: UNet2DConfig, lh: int, lw: int, n_images: int,
                  steps: int, ctx_len: int = 77, cfg_rows: int = 2) -> float:
    """FLOPs of a full CFG denoise loop (batch is cfg_rows*N per step;
    2 for standard CFG, 3 for instruct-pix2pix dual guidance)."""
    return unet_call_flops(cfg, lh, lw, cfg_rows * n_images, ctx_len) * steps
