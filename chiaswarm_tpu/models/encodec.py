"""EnCodec neural audio codec — decoder path, flax/NLC.

Bark's waveform stage: the reference's `generate_audio` decodes the 8-book
EnCodec tokens through facebook/encodec_24khz (reference
swarm/audio/bark.py:16-21 via suno's codec). This is the decode-only
rebuild: RVQ codebook-sum -> SEANet decoder (conv, 2-layer LSTM,
per-ratio transposed conv + residual blocks) -> waveform.

Layout is [B, T, C] (TPU-friendly channels-last; torch reference is
[B, C, T]). Weight-normalized conv weights fold into plain kernels at
conversion time (conversion.convert_encodec_decoder), so runtime is plain
convs. Causal padding follows transformers' EncodecConv1d exactly:
left-pad (k-1)*dilation in the configured pad mode ("reflect" for the
24 kHz model); transposed convs trim (k - stride) from the right
(trim_right_ratio=1). Numeric parity vs transformers EncodecModel.decode
is asserted in tests/test_bark_conversion.py.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class EncodecConfig:
    hidden_size: int = 128
    num_filters: int = 32
    upsampling_ratios: tuple[int, ...] = (8, 5, 4, 2)
    kernel_size: int = 7
    last_kernel_size: int = 7
    residual_kernel_size: int = 3
    dilation_growth_rate: int = 2
    num_residual_layers: int = 1
    num_lstm_layers: int = 2
    compress: int = 2
    codebook_size: int = 1024
    audio_channels: int = 1
    pad_mode: str = "reflect"
    use_conv_shortcut: bool = True


TINY_ENCODEC = EncodecConfig(
    hidden_size=16, num_filters=4, upsampling_ratios=(4, 2),
    kernel_size=7, last_kernel_size=7, residual_kernel_size=3,
    num_lstm_layers=1, codebook_size=64,
)


class _CausalConv(nn.Module):
    """EncodecConv1d, causal: left-pad (k-1)*dilation, stride 1."""

    out_channels: int
    kernel_size: int
    dilation: int = 1
    pad_mode: str = "reflect"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        pad = (self.kernel_size - 1) * self.dilation
        if pad:
            mode = "reflect" if self.pad_mode == "reflect" else "constant"
            # reflect needs T > pad; generated audio always has many frames
            x = jnp.pad(x, ((0, 0), (pad, 0), (0, 0)), mode=mode)
        return nn.Conv(
            self.out_channels, (self.kernel_size,),
            kernel_dilation=(self.dilation,), padding="VALID",
            dtype=self.dtype, name="conv",
        )(x)


class _CausalConvTranspose(nn.Module):
    """EncodecConvTranspose1d, causal: trim (k - stride) from the right."""

    out_channels: int
    kernel_size: int
    stride: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        y = nn.ConvTranspose(
            self.out_channels, (self.kernel_size,), strides=(self.stride,),
            padding="VALID", transpose_kernel=True,
            dtype=self.dtype, name="conv",
        )(x)
        trim = self.kernel_size - self.stride
        return y[:, : y.shape[1] - trim] if trim else y


class _LSTM(nn.Module):
    """torch-layout LSTM stack with residual (EncodecLSTM semantics).

    Parameters keep the torch names/shapes (weight_ih_l0 [4H, H], gate
    order i,f,g,o) so conversion is a verbatim copy; the recurrence is a
    lax.scan over time.
    """

    dim: int
    num_layers: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):  # [B, T, C]
        residual = x
        h0 = jnp.zeros((x.shape[0], self.dim), x.dtype)
        for layer in range(self.num_layers):
            w_ih = self.param(
                f"weight_ih_l{layer}", nn.initializers.zeros,
                (4 * self.dim, self.dim),
            )
            w_hh = self.param(
                f"weight_hh_l{layer}", nn.initializers.zeros,
                (4 * self.dim, self.dim),
            )
            b_ih = self.param(
                f"bias_ih_l{layer}", nn.initializers.zeros, (4 * self.dim,)
            )
            b_hh = self.param(
                f"bias_hh_l{layer}", nn.initializers.zeros, (4 * self.dim,)
            )
            # hoist the input projection out of the scan: one big matmul
            gates_x = x @ w_ih.T + b_ih + b_hh

            def step(carry, gx, w_hh=w_hh):
                h, c = carry
                gates = gx + h @ w_hh.T
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                c = nn.sigmoid(f) * c + nn.sigmoid(i) * jnp.tanh(g)
                h = nn.sigmoid(o) * jnp.tanh(c)
                return (h, c), h

            _, hs = jax.lax.scan(
                step, (h0, h0), jnp.moveaxis(gates_x, 0, 1)
            )
            x = jnp.moveaxis(hs, 0, 1)
        return x + residual


class _ResnetBlock(nn.Module):
    config: EncodecConfig
    dim: int
    dilations: tuple[int, ...]
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        hidden = self.dim // cfg.compress
        kernel_sizes = (cfg.residual_kernel_size, 1)
        h = x
        # block indices interleave ELU modules like the torch ModuleList
        # (block.0 = ELU, block.1 = conv, block.2 = ELU, block.3 = conv)
        for i, (k, dil) in enumerate(zip(kernel_sizes, self.dilations)):
            h = nn.elu(h)
            out_ch = self.dim if i == len(kernel_sizes) - 1 else hidden
            h = _CausalConv(
                out_ch, k, dilation=dil, pad_mode=cfg.pad_mode,
                dtype=self.dtype, name=f"block_{2 * i + 1}",
            )(h)
        if cfg.use_conv_shortcut:
            x = _CausalConv(
                self.dim, 1, pad_mode=cfg.pad_mode, dtype=self.dtype,
                name="shortcut",
            )(x)
        return x + h


class _Decoder(nn.Module):
    config: EncodecConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        scaling = 2 ** len(cfg.upsampling_ratios)
        idx = 0
        x = _CausalConv(
            scaling * cfg.num_filters, cfg.kernel_size,
            pad_mode=cfg.pad_mode, dtype=self.dtype, name=f"layers_{idx}",
        )(x)
        idx += 1
        x = _LSTM(
            scaling * cfg.num_filters, cfg.num_lstm_layers,
            dtype=self.dtype, name=f"layers_{idx}",
        )(x)
        idx += 1
        for ratio in cfg.upsampling_ratios:
            current = scaling * cfg.num_filters
            x = nn.elu(x)
            idx += 1  # the ELU occupies a ModuleList slot in torch
            x = _CausalConvTranspose(
                current // 2, ratio * 2, ratio, dtype=self.dtype,
                name=f"layers_{idx}",
            )(x)
            idx += 1
            for j in range(cfg.num_residual_layers):
                x = _ResnetBlock(
                    cfg, current // 2,
                    (cfg.dilation_growth_rate ** j, 1),
                    dtype=self.dtype, name=f"layers_{idx}",
                )(x)
                idx += 1
            scaling //= 2
        x = nn.elu(x)
        idx += 1
        return _CausalConv(
            cfg.audio_channels, cfg.last_kernel_size,
            pad_mode=cfg.pad_mode, dtype=self.dtype, name=f"layers_{idx}",
        )(x)


class EncodecDecoderModel(nn.Module):
    """RVQ codes [B, K, T] -> waveform [B, T * hop] (hop = prod(ratios))."""

    config: EncodecConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, codes):
        cfg = self.config
        b, k, t = codes.shape
        quantized = jnp.zeros((b, t, cfg.hidden_size), self.dtype)
        for i in range(k):
            embed = self.param(
                f"codebook_{i}", nn.initializers.normal(0.02),
                (cfg.codebook_size, cfg.hidden_size),
            )
            quantized = quantized + jnp.asarray(embed, self.dtype)[codes[:, i]]
        wav = _Decoder(cfg, dtype=self.dtype, name="decoder")(quantized)
        return wav[..., 0] if cfg.audio_channels == 1 else wav

    @property
    def hop(self) -> int:
        out = 1
        for r in self.config.upsampling_ratios:
            out *= r
        return out
