"""AudioLDM2 UNet + projection model — the dual-conditioned mel
diffusion graph behind `AudioLDM2Pipeline`.

Reference behavior replaced: the reference resolves any diffusers
pipeline class by name for txt2audio jobs
(swarm/job_arguments.py get_type + swarm/audio/audioldm.py:12-21), so a
`parameters.pipeline_type = "AudioLDM2Pipeline"` job runs AudioLDM2.

The UNet is the standard 2D block plan (resnet + transformer per layer,
mid with a resnet sandwich) with ONE structural twist: every attention
slot is a PAIR of sequential single-block transformers, the first
cross-attending the GPT-2 generated sequence (language-model width), the
second the T5 states (its own width), both with key-padding masks. The
projection model is four learned SOS/EOS vectors plus one Linear per
text tower, assembling the joint GPT-2 input sequence.

Module names line up with the diffusers state-dict names so conversion
(models/conversion.py convert_audioldm2_unet) is a mechanical rename.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp

from .layers import (
    FeedForward,
    FusedGroupNorm,
    ResnetBlock2D,
    TimestepEmbedding,
    timestep_embedding,
)


@dataclasses.dataclass(frozen=True)
class AudioLDM2UNetConfig:
    in_channels: int = 8
    out_channels: int = 8
    block_out_channels: tuple[int, ...] = (128, 256, 384, 640)
    layers_per_block: int = 2
    attention: tuple[bool, ...] = (True, True, True, True)
    # diffusers quirk: UNet2DConditionModel reads `attention_head_dim`
    # as the HEAD COUNT (num_attention_heads = ... or attention_head_dim)
    attention_head_dim: int = 8
    # one entry per per-layer attention slot: (generated/GPT-2 width,
    # text/T5 width)
    cross_attention_dims: tuple[int, ...] = (768, 1024)
    norm_num_groups: int = 32


TINY_AUDIOLDM2_UNET = AudioLDM2UNetConfig(
    block_out_channels=(32, 64),
    layers_per_block=1,
    attention=(True, True),
    attention_head_dim=8,
    # widths match TINY_GPT2.hidden_size and a narrowed TINY_T5
    cross_attention_dims=(32, 16),
    norm_num_groups=8,
)


class MaskedTransformer2D(nn.Module):
    """Single-block Transformer2DModel with key-padding-masked cross
    attention (diffusers audioldm2 semantics; keys norm/proj_in/
    transformer_blocks.0.{norm1,attn1,norm2,attn2,norm3,ff}/proj_out)."""

    num_heads: int
    head_dim: int
    groups: int = 32
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, context, context_mask=None):
        b, h, w, c = x.shape
        residual = x
        hidden = FusedGroupNorm(
            self.groups, epsilon=1e-6, dtype=self.dtype, name="norm"
        )(x)
        hidden = hidden.reshape(b, h * w, c)
        hidden = nn.Dense(c, dtype=self.dtype, name="proj_in")(hidden)

        def attention(q_in, kv_in, mask, name):
            inner = self.num_heads * self.head_dim
            q = nn.Dense(inner, use_bias=False, dtype=self.dtype,
                         name=f"{name}_to_q")(q_in)
            k = nn.Dense(inner, use_bias=False, dtype=self.dtype,
                         name=f"{name}_to_k")(kv_in)
            v = nn.Dense(inner, use_bias=False, dtype=self.dtype,
                         name=f"{name}_to_v")(kv_in)
            n, s = q.shape[1], k.shape[1]
            q = q.reshape(b, n, self.num_heads, self.head_dim)
            k = k.reshape(b, s, self.num_heads, self.head_dim)
            v = v.reshape(b, s, self.num_heads, self.head_dim)
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
            logits = logits * (self.head_dim ** -0.5)
            if mask is not None:
                logits = jnp.where(
                    mask[:, None, None, :].astype(bool), logits, -1e9
                )
            weights = nn.softmax(logits, axis=-1).astype(self.dtype)
            out = jnp.einsum("bhqk,bkhd->bqhd", weights, v).reshape(
                b, n, inner
            )
            return nn.Dense(c, dtype=self.dtype, name=f"{name}_to_out_0")(
                out
            )

        blk = "transformer_blocks_0"
        normed = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype,
                              name=f"{blk}_norm1")(hidden)
        hidden = hidden + attention(normed, normed, None, f"{blk}_attn1")
        hidden = hidden + attention(
            nn.LayerNorm(epsilon=1e-5, dtype=self.dtype,
                         name=f"{blk}_norm2")(hidden),
            jnp.asarray(context, self.dtype), context_mask,
            f"{blk}_attn2",
        )
        h2 = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype,
                          name=f"{blk}_norm3")(hidden)
        hidden = hidden + FeedForward(
            c, dtype=self.dtype, name=f"{blk}_ff"
        )(h2)
        hidden = nn.Dense(c, dtype=self.dtype, name="proj_out")(hidden)
        return hidden.reshape(b, h, w, c) + residual


class AudioLDM2UNet(nn.Module):
    """[B, T, F, C] mel latents + [B] timesteps + the two context
    sequences (+ masks) -> [B, T, F, C] noise prediction."""

    config: AudioLDM2UNetConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, sample, timesteps, ctx0, mask0, ctx1, mask1):
        cfg = self.config
        g = cfg.norm_num_groups
        heads = cfg.attention_head_dim  # head COUNT (diffusers quirk)
        dim_of = lambda ch: max(1, ch // heads)
        ctxs = ((ctx0, mask0), (ctx1, mask1))

        temb = TimestepEmbedding(
            cfg.block_out_channels[0] * 4, dtype=self.dtype,
            name="time_embedding",
        )(timestep_embedding(timesteps, cfg.block_out_channels[0],
                             dtype=self.dtype))

        x = nn.Conv(
            cfg.block_out_channels[0], (3, 3), padding=((1, 1), (1, 1)),
            dtype=self.dtype, name="conv_in",
        )(jnp.asarray(sample, self.dtype))

        n = len(cfg.block_out_channels)
        n_ctx = len(ctxs)
        skips = [x]
        for bidx, out_ch in enumerate(cfg.block_out_channels):
            for i in range(cfg.layers_per_block):
                x = ResnetBlock2D(
                    out_ch, dtype=self.dtype,
                    name=f"down_{bidx}_resnets_{i}",
                )(x, temb)
                if cfg.attention[bidx]:
                    # attention slot indices: attentions_{i*n_ctx + idx}
                    for idx, (ctx, mask) in enumerate(ctxs):
                        x = MaskedTransformer2D(
                            heads, dim_of(out_ch),
                            groups=g, dtype=self.dtype,
                            name=f"down_{bidx}_attentions_{i * n_ctx + idx}",
                        )(x, ctx, mask)
                skips.append(x)
            if bidx != n - 1:
                x = nn.Conv(
                    out_ch, (3, 3), strides=(2, 2),
                    padding=((1, 1), (1, 1)), dtype=self.dtype,
                    name=f"down_{bidx}_downsample",
                )(x)
                skips.append(x)

        mid_ch = cfg.block_out_channels[-1]
        x = ResnetBlock2D(mid_ch, dtype=self.dtype, name="mid_resnets_0")(
            x, temb
        )
        for idx, (ctx, mask) in enumerate(ctxs):
            x = MaskedTransformer2D(
                heads, dim_of(mid_ch), groups=g,
                dtype=self.dtype, name=f"mid_attentions_{idx}",
            )(x, ctx, mask)
        x = ResnetBlock2D(mid_ch, dtype=self.dtype, name="mid_resnets_1")(
            x, temb
        )

        for bidx, out_ch in enumerate(reversed(cfg.block_out_channels)):
            rev = n - 1 - bidx
            for i in range(cfg.layers_per_block + 1):
                x = jnp.concatenate([x, skips.pop()], axis=-1)
                x = ResnetBlock2D(
                    out_ch, dtype=self.dtype, name=f"up_{bidx}_resnets_{i}"
                )(x, temb)
                if cfg.attention[rev]:
                    for idx, (ctx, mask) in enumerate(ctxs):
                        x = MaskedTransformer2D(
                            heads, dim_of(out_ch),
                            groups=g, dtype=self.dtype,
                            name=f"up_{bidx}_attentions_{i * n_ctx + idx}",
                        )(x, ctx, mask)
            if bidx != n - 1:
                x = jnp.repeat(jnp.repeat(x, 2, axis=1), 2, axis=2)
                x = nn.Conv(
                    out_ch, (3, 3), padding=((1, 1), (1, 1)),
                    dtype=self.dtype, name=f"up_{bidx}_upsample",
                )(x)

        x = FusedGroupNorm(g, epsilon=1e-5, dtype=self.dtype, act="silu",
                           name="conv_norm_out")(x)
        return nn.Conv(
            cfg.out_channels, (3, 3), padding=((1, 1), (1, 1)),
            dtype=self.dtype, name="conv_out",
        )(x)


class AudioLDM2Projection(nn.Module):
    """diffusers AudioLDM2ProjectionModel: per-tower Linear into the
    language-model width plus learned SOS/EOS vectors; output is the
    joint [sos|clap|eos|sos_1|t5|eos_1] GPT-2 input sequence + mask."""

    language_model_dim: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, h0, m0, h1, m1):
        lm = self.language_model_dim
        b = h0.shape[0]
        h0 = nn.Dense(lm, dtype=self.dtype, name="projection")(
            jnp.asarray(h0, self.dtype)
        )
        h1 = nn.Dense(lm, dtype=self.dtype, name="projection_1")(
            jnp.asarray(h1, self.dtype)
        )

        def specials(name0, name1):
            sos = self.param(name0, nn.initializers.ones, (lm,))
            eos = self.param(name1, nn.initializers.ones, (lm,))
            return (
                jnp.broadcast_to(jnp.asarray(sos, self.dtype), (b, 1, lm)),
                jnp.broadcast_to(jnp.asarray(eos, self.dtype), (b, 1, lm)),
            )

        sos0, eos0 = specials("sos_embed", "eos_embed")
        sos1, eos1 = specials("sos_embed_1", "eos_embed_1")
        ones = jnp.ones((b, 1), m0.dtype)
        seq = jnp.concatenate([sos0, h0, eos0, sos1, h1, eos1], axis=1)
        mask = jnp.concatenate([ones, m0, ones, ones, m1, ones], axis=-1)
        return seq, mask
