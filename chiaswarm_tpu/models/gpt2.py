"""GPT-2 trunk (transformers GPT2Model) — AudioLDM2's "language model".

Reference behavior replaced: the reference can serve AudioLDM2 jobs via
`parameters.pipeline_type = "AudioLDM2Pipeline"` (swarm/job_arguments.py
get_type resolves any diffusers class; the shipped callback is the same
txt2audio path, swarm/audio/audioldm.py:12-21). AudioLDM2 uses GPT-2
purely as an embedding-space sequence model: the projected CLAP+T5
sequence goes in as `inputs_embeds`, and generation appends the LAST
HIDDEN STATE eight times (no sampling, no vocabulary) — so this module
carries no token embedding at all (wte is dead weight for serving, like
the MoVQ codebook).

transformers stores the attention/MLP projections as Conv1D with (in,
out)-shaped weights — exactly flax Dense's kernel layout, so conversion
(models/conversion.py convert_gpt2) copies them UNtransposed.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    n_positions: int = 1024
    layer_norm_epsilon: float = 1e-5


TINY_GPT2 = GPT2Config(hidden_size=32, num_layers=2, num_heads=4,
                       n_positions=64)


class _Block(nn.Module):
    config: GPT2Config
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, bias):
        cfg = self.config
        b, s, d = x.shape
        heads = cfg.num_heads
        hd = d // heads
        h = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=self.dtype,
                         name="ln_1")(x)
        qkv = nn.Dense(3 * d, dtype=self.dtype, name="c_attn")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, heads, hd)
        k = k.reshape(b, s, heads, hd)
        v = v.reshape(b, s, heads, hd)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        logits = logits * (hd ** -0.5) + bias
        weights = nn.softmax(logits, axis=-1).astype(self.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", weights, v).reshape(b, s, d)
        x = x + nn.Dense(d, dtype=self.dtype, name="c_proj")(attn)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=self.dtype,
                         name="ln_2")(x)
        h = nn.Dense(4 * d, dtype=self.dtype, name="c_fc")(h)
        h = nn.gelu(h, approximate=True)  # gelu_new
        return x + nn.Dense(d, dtype=self.dtype, name="mlp_c_proj")(h)


class GPT2Model(nn.Module):
    """[B, S, hidden] input embeddings (+ optional [B, S] 1-keep padding
    mask) -> [B, S, hidden] final hidden states (causal)."""

    config: GPT2Config
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs_embeds, attention_mask=None):
        cfg = self.config
        b, s, d = inputs_embeds.shape
        wpe = self.param(
            "wpe", nn.initializers.normal(0.02), (cfg.n_positions, d)
        )
        x = jnp.asarray(inputs_embeds, self.dtype) + jnp.asarray(
            wpe[:s], self.dtype
        )
        causal = jnp.tril(jnp.ones((s, s), bool))
        bias = jnp.where(causal[None, None], 0.0, -1e9)
        if attention_mask is not None:
            bias = bias + jnp.where(
                attention_mask[:, None, None, :].astype(bool), 0.0, -1e9
            )
        for i in range(cfg.num_layers):
            x = _Block(cfg, dtype=self.dtype, name=f"h_{i}")(x, bias)
        return nn.LayerNorm(
            epsilon=cfg.layer_norm_epsilon, dtype=self.dtype, name="ln_f"
        )(x)
