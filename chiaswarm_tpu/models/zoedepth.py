"""ZoeDepth metric depth (transformers ZoeDepthForDepthEstimation,
BEiT-large backbone, ZoeD_N single-configuration head) — the learned
model behind the `zoe depth` preprocessor.

Reference behavior replaced: swarm/pre_processors/zoe_depth.py:8-13
(torch-hub ZoeDepth invoked per call). The graph, ported from the
installed transformers modeling source as ground truth:
- BEiT: patch conv + CLS token, 24 pre-LN blocks with per-layer 2D
  relative-position-bias tables (bias-free key projection, layer-scale
  lambdas), four tap points (after layers 6/12/18/24) that keep the CLS
  token for the DPT readout;
- DPT-style neck: readout-projected reassemble to four resolutions
  (transposed-conv x4/x2, identity, strided conv x0.5), 3x3 projections,
  top-down fusion with pre-activation residual units and align-corners
  2x upsampling;
- relative-depth head (3 convs) whose 32-feature activation conditions
- the metric-bins head: seed bin regressor (softplus, unnormed),
  four attractor layers (inv-attractor contraction with the upstream
  default alpha=300/gamma=2 — the config fields are unused upstream),
  projector MLPs over the fused pyramid, and a conditional log-binomial
  softmax (Stirling log-binom) over bin centers.

Serving runs a FIXED square canvas equal to the trained window (the
relative-position tables then index directly, no bilinear table
interpolation). Module names line up with the transformers state-dict
names so conversion (models/conversion.py convert_zoedepth) is a
mechanical rename.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from .cascade_unet import interpolate_bilinear_align_corners


@dataclasses.dataclass(frozen=True)
class ZoeConfig:
    # BEiT backbone
    image_size: int = 384
    patch_size: int = 16
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    intermediate_size: int = 4096
    layer_norm_eps: float = 1e-12
    out_indices: tuple[int, ...] = (6, 12, 18, 24)
    # neck + heads
    reassemble_factors: tuple[float, ...] = (4, 2, 1, 0.5)
    neck_hidden_sizes: tuple[int, ...] = (96, 192, 384, 768)
    fusion_hidden_size: int = 256
    bottleneck_features: int = 256
    num_relative_features: int = 32
    num_attractors: tuple[int, ...] = (16, 8, 4, 1)
    bin_embedding_dim: int = 128
    n_bins: int = 64
    min_depth: float = 1e-3
    max_depth: float = 10.0
    min_temp: float = 0.0212
    max_temp: float = 50.0
    # transformers single-head defaults (NOT scaled from bin_embedding_dim
    # — the multi-head variant does that, the single head does not)
    seed_mlp_dim: int = 256
    projector_mlp_dim: int = 128

    @property
    def window(self) -> int:
        return self.image_size // self.patch_size


TINY_ZOE = ZoeConfig(
    image_size=64,
    patch_size=16,
    hidden_size=32,
    num_layers=4,
    num_heads=4,
    intermediate_size=64,
    out_indices=(1, 2, 3, 4),
    neck_hidden_sizes=(8, 16, 24, 32),
    fusion_hidden_size=16,
    bottleneck_features=16,
    num_relative_features=8,
    num_attractors=(4, 2, 2, 1),
    bin_embedding_dim=16,
    n_bins=8,
)


def beit_relative_position_index(window: int) -> np.ndarray:
    """(W^2+1)^2 index into the (2W-1)^2+3 bias table (CLS rows use the
    trailing three special entries) — transformers BeitRelativePositionBias
    semantics at the trained window."""
    num_rel = (2 * window - 1) ** 2 + 3
    coords = np.stack(np.meshgrid(np.arange(window), np.arange(window),
                                  indexing="ij"))
    flat = coords.reshape(2, -1)
    rel = flat[:, :, None] - flat[:, None, :]
    rel = rel.transpose(1, 2, 0).copy()
    rel[:, :, 0] += window - 1
    rel[:, :, 1] += window - 1
    rel[:, :, 0] *= 2 * window - 1
    area = window * window
    index = np.zeros((area + 1, area + 1), np.int32)
    index[1:, 1:] = rel.sum(-1)
    index[0, 0:] = num_rel - 3
    index[0:, 0] = num_rel - 2
    index[0, 0] = num_rel - 1
    return index


class _BeitSelfAttention(nn.Module):
    config: ZoeConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        b, s, d = x.shape
        heads = cfg.num_heads
        hd = d // heads
        q = nn.Dense(d, dtype=self.dtype, name="query")(x)
        k = nn.Dense(d, use_bias=False, dtype=self.dtype, name="key")(x)
        v = nn.Dense(d, dtype=self.dtype, name="value")(x)
        q = q.reshape(b, s, heads, hd)
        k = k.reshape(b, s, heads, hd)
        v = v.reshape(b, s, heads, hd)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        logits = logits * (hd ** -0.5)
        table = self.param(
            "relative_position_bias",
            nn.initializers.zeros,
            ((2 * cfg.window - 1) ** 2 + 3, heads),
        )
        index = beit_relative_position_index(cfg.window)
        bias = jnp.asarray(table)[jnp.asarray(index.reshape(-1))]
        bias = bias.reshape(s, s, heads).transpose(2, 0, 1)
        logits = logits + bias[None].astype(jnp.float32)
        weights = nn.softmax(logits, axis=-1).astype(self.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", weights, v).reshape(b, s, d)


class _BeitAttention(nn.Module):
    """transformers BeitAttention: self-attention + output dense (the
    nested `attention.attention` / `attention.output` key shape)."""

    config: ZoeConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        y = _BeitSelfAttention(self.config, dtype=self.dtype,
                               name="attention")(x)

        class _Out(nn.Module):
            dtype: jnp.dtype = jnp.float32

            @nn.compact
            def __call__(self, h):
                return nn.Dense(h.shape[-1], dtype=self.dtype,
                                name="dense")(h)

        return _Out(dtype=self.dtype, name="output")(y)


class _BeitLayer(nn.Module):
    config: ZoeConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        d = cfg.hidden_size
        attn = _BeitAttention(cfg, dtype=self.dtype, name="attention")(
            nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=self.dtype,
                         name="layernorm_before")(x)
        )
        lambda_1 = self.param("lambda_1", nn.initializers.ones, (d,))
        x = x + attn * jnp.asarray(lambda_1, self.dtype)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=self.dtype,
                         name="layernorm_after")(x)

        class _Mid(nn.Module):
            width: int
            dtype: jnp.dtype = jnp.float32

            @nn.compact
            def __call__(self, z):
                return nn.gelu(
                    nn.Dense(self.width, dtype=self.dtype, name="dense")(z),
                    approximate=False,
                )

        class _Out(nn.Module):
            width: int
            dtype: jnp.dtype = jnp.float32

            @nn.compact
            def __call__(self, z):
                return nn.Dense(self.width, dtype=self.dtype,
                                name="dense")(z)

        h = _Mid(cfg.intermediate_size, dtype=self.dtype,
                 name="intermediate")(h)
        h = _Out(d, dtype=self.dtype, name="output")(h)
        lambda_2 = self.param("lambda_2", nn.initializers.ones, (d,))
        return x + h * jnp.asarray(lambda_2, self.dtype)


class BeitBackbone(nn.Module):
    """[B, H, W, 3] (H = W = image_size) -> four [B, S+1, hidden] taps
    (CLS kept for the DPT readout)."""

    config: ZoeConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, pixels):
        cfg = self.config
        b = pixels.shape[0]
        p = cfg.patch_size

        class _Embeddings(nn.Module):
            dtype: jnp.dtype = jnp.float32

            @nn.compact
            def __call__(self, px):
                class _Patch(nn.Module):
                    dtype: jnp.dtype = jnp.float32

                    @nn.compact
                    def __call__(self, z):
                        return nn.Conv(
                            cfg.hidden_size, (p, p), strides=(p, p),
                            padding="VALID", dtype=self.dtype,
                            name="projection",
                        )(z)

                tokens = _Patch(dtype=self.dtype, name="patch_embeddings")(px)
                tokens = tokens.reshape(b, -1, cfg.hidden_size)
                cls = self.param(
                    "cls_token", nn.initializers.zeros,
                    (1, 1, cfg.hidden_size),
                )
                cls = jnp.broadcast_to(
                    jnp.asarray(cls, self.dtype), (b, 1, cfg.hidden_size)
                )
                return jnp.concatenate([cls, tokens], axis=1)

        x = _Embeddings(dtype=self.dtype, name="embeddings")(
            jnp.asarray(pixels, self.dtype)
        )

        class _Encoder(nn.Module):
            dtype: jnp.dtype = jnp.float32

            @nn.compact
            def __call__(self, h):
                taps = []
                for i in range(cfg.num_layers):
                    h = _BeitLayer(cfg, dtype=self.dtype,
                                   name=f"layer_{i}")(h)
                    if (i + 1) in cfg.out_indices:
                        taps.append(h)
                return taps

        return _Encoder(dtype=self.dtype, name="encoder")(x)


class _ConvTransposeSame(nn.Module):
    """torch ConvTranspose2d(kernel=k, stride=k): disjoint k x k output
    blocks — an einsum. Kernel layout (k, k, in, out)."""

    features: int
    k: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (self.k, self.k, c, self.features),
        )
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        y = jnp.einsum("bhwi,klio->bhkwlo", x,
                       jnp.asarray(kernel, self.dtype))
        y = y.reshape(b, self.k * h, self.k * w, self.features)
        return y + jnp.asarray(bias, self.dtype)


class _PreActResidual(nn.Module):
    width: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.relu(x)
        h = nn.Conv(self.width, (3, 3), padding=((1, 1), (1, 1)),
                    dtype=self.dtype, name="convolution1")(h)
        h = nn.relu(h)
        h = nn.Conv(self.width, (3, 3), padding=((1, 1), (1, 1)),
                    dtype=self.dtype, name="convolution2")(h)
        return x + h


class _FusionLayer(nn.Module):
    width: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, residual=None):
        if residual is not None:
            if residual.shape != x.shape:
                residual = jax.image.resize(
                    residual, x.shape, "bilinear"
                ).astype(residual.dtype)
            x = x + _PreActResidual(self.width, dtype=self.dtype,
                                    name="residual_layer1")(residual)
        x = _PreActResidual(self.width, dtype=self.dtype,
                            name="residual_layer2")(x)
        b, h, w, c = x.shape
        x = interpolate_bilinear_align_corners(x, 2 * h, 2 * w)
        return nn.Conv(self.width, (1, 1), dtype=self.dtype,
                       name="projection")(x)


def _log_binom(n, k, eps=1e-7):
    n = n + eps
    k = k + eps
    return n * jnp.log(n) - k * jnp.log(k) - (n - k) * jnp.log(n - k + eps)


class _ConditionalLogBinomial(nn.Module):
    """mlp.0 (1x1) -> gelu -> mlp.2 (1x1, 4ch) -> softplus, split into a
    binomial probability and temperature, then the Stirling log-binomial
    softmax over n_bins classes."""

    config: ZoeConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, main, condition):
        cfg = self.config
        x = jnp.concatenate([main, condition], axis=-1)
        bottleneck = x.shape[-1] // 2
        x = nn.Conv(bottleneck, (1, 1), dtype=self.dtype, name="mlp_0")(x)
        x = nn.gelu(x, approximate=False)
        x = nn.Conv(4, (1, 1), dtype=self.dtype, name="mlp_2")(x)
        x = nn.softplus(x.astype(jnp.float32))
        eps = 1e-4
        prob = x[..., :2] + eps
        prob = prob[..., 0] / (prob[..., 0] + prob[..., 1])
        temp = x[..., 2:] + eps
        temp = temp[..., 0] / (temp[..., 0] + temp[..., 1])
        temp = (cfg.max_temp - cfg.min_temp) * temp + cfg.min_temp
        prob = jnp.clip(prob, eps, 1.0)[..., None]
        one_minus = jnp.clip(1.0 - prob, eps, 1.0)
        k_idx = jnp.arange(cfg.n_bins, dtype=jnp.float32)
        k_minus_1 = jnp.float32(cfg.n_bins - 1)
        y = (
            _log_binom(k_minus_1, k_idx)
            + k_idx * jnp.log(prob)
            + (k_minus_1 - k_idx) * jnp.log(one_minus)
        )
        return nn.softmax(y / temp[..., None], axis=-1)


class _Mlp1x1(nn.Module):
    """conv1 -> relu -> conv2 (+ optional trailing activation)."""

    mid: int
    out: int
    trailing: str | None = None
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.mid, (1, 1), dtype=self.dtype, name="conv1")(x)
        x = nn.relu(x)
        x = nn.Conv(self.out, (1, 1), dtype=self.dtype, name="conv2")(x)
        if self.trailing == "softplus":
            x = nn.softplus(x.astype(jnp.float32)).astype(x.dtype)
        return x


class ZoeDepthModel(nn.Module):
    """[B, S, S, 3] normalized pixels (S = config.image_size) ->
    [B, S, S] metric depth (meters)."""

    config: ZoeConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, pixels):
        cfg = self.config
        b = pixels.shape[0]
        win = cfg.window
        taps = BeitBackbone(cfg, dtype=self.dtype, name="backbone")(pixels)

        # --- neck: reassemble ---
        class _Reassemble(nn.Module):
            dtype: jnp.dtype = jnp.float32

            @nn.compact
            def __call__(self, taps):
                out = []
                for i, (tap, ch, factor) in enumerate(zip(
                    taps, cfg.neck_hidden_sizes, cfg.reassemble_factors
                )):
                    cls, tokens = tap[:, 0], tap[:, 1:]
                    readout = jnp.broadcast_to(
                        cls[:, None, :], tokens.shape
                    )

                    class _Readout(nn.Module):
                        dtype: jnp.dtype = jnp.float32

                        @nn.compact
                        def __call__(self, z):
                            # torch key readout_projects.N.0 -> "proj"
                            # (a bare digit child would collide with the
                            # digit-merge rename)
                            return nn.gelu(
                                nn.Dense(cfg.hidden_size, dtype=self.dtype,
                                         name="proj")(z),
                                approximate=False,
                            )

                    h = _Readout(dtype=self.dtype, name=f"readout_projects_{i}")(
                        jnp.concatenate([tokens, readout], axis=-1)
                    )
                    h = h.reshape(b, win, win, cfg.hidden_size)

                    class _Layer(nn.Module):
                        dtype: jnp.dtype = jnp.float32

                        @nn.compact
                        def __call__(self, z):
                            z = nn.Conv(ch, (1, 1), dtype=self.dtype,
                                        name="projection")(z)
                            if factor > 1:
                                z = _ConvTransposeSame(
                                    ch, int(factor), dtype=self.dtype,
                                    name="resize",
                                )(z)
                            elif factor < 1:
                                s = int(1 / factor)
                                z = nn.Conv(
                                    ch, (3, 3), strides=(s, s),
                                    padding=((1, 1), (1, 1)),
                                    dtype=self.dtype, name="resize",
                                )(z)
                            return z

                    out.append(_Layer(dtype=self.dtype,
                                      name=f"layers_{i}")(h))
                return out

        class _Neck(nn.Module):
            dtype: jnp.dtype = jnp.float32

            @nn.compact
            def __call__(self, taps):
                feats = _Reassemble(dtype=self.dtype,
                                    name="reassemble_stage")(taps)
                feats = [
                    nn.Conv(cfg.fusion_hidden_size, (3, 3),
                            padding=((1, 1), (1, 1)), use_bias=False,
                            dtype=self.dtype, name=f"convs_{i}")(f)
                    for i, f in enumerate(feats)
                ]

                class _Fusion(nn.Module):
                    dtype: jnp.dtype = jnp.float32

                    @nn.compact
                    def __call__(self, feats):
                        fused_states = []
                        fused = None
                        for j, f in enumerate(feats[::-1]):
                            layer = _FusionLayer(
                                cfg.fusion_hidden_size, dtype=self.dtype,
                                name=f"layers_{j}",
                            )
                            fused = layer(f) if fused is None else layer(
                                fused, f
                            )
                            fused_states.append(fused)
                        return fused_states

                fused = _Fusion(dtype=self.dtype, name="fusion_stage")(feats)
                return fused, feats[-1]

        fused_states, bottleneck = _Neck(dtype=self.dtype, name="neck")(taps)

        # --- relative head ---
        class _RelativeHead(nn.Module):
            dtype: jnp.dtype = jnp.float32

            @nn.compact
            def __call__(self, h):
                h = nn.Conv(cfg.fusion_hidden_size // 2, (3, 3),
                            padding=((1, 1), (1, 1)), dtype=self.dtype,
                            name="conv1")(h)
                bb, hh, ww, _ = h.shape
                h = interpolate_bilinear_align_corners(h, 2 * hh, 2 * ww)
                h = nn.Conv(cfg.num_relative_features, (3, 3),
                            padding=((1, 1), (1, 1)), dtype=self.dtype,
                            name="conv2")(h)
                h = nn.relu(h)
                features = h
                h = nn.Conv(1, (1, 1), dtype=self.dtype, name="conv3")(h)
                h = nn.relu(h)
                return h[..., 0], features

        relative_depth, rel_features = _RelativeHead(
            dtype=self.dtype, name="relative_head"
        )(fused_states[-1])

        # --- metric head (single bin configuration, softplus centers) ---
        class _MetricHead(nn.Module):
            dtype: jnp.dtype = jnp.float32

            @nn.compact
            def __call__(self, outconv, bottleneck, feature_blocks,
                         relative_depth):
                x = nn.Conv(cfg.bottleneck_features, (1, 1),
                            dtype=self.dtype, name="conv2")(bottleneck)
                seed = _Mlp1x1(
                    cfg.seed_mlp_dim, cfg.n_bins,
                    trailing="softplus", dtype=self.dtype,
                    name="seed_bin_regressor",
                )(x)
                prev_bin = seed  # softplus/unnormed: centers ARE the bins
                prev_embedding = _Mlp1x1(
                    cfg.projector_mlp_dim, cfg.bin_embedding_dim,
                    dtype=self.dtype, name="seed_projector",
                )(x)
                bin_centers = prev_bin
                for i, feature in enumerate(feature_blocks):
                    embedding = _Mlp1x1(
                        cfg.projector_mlp_dim, cfg.bin_embedding_dim,
                        dtype=self.dtype, name=f"projectors_{i}",
                    )(feature)

                    class _Attractor(nn.Module):
                        n_attr: int
                        dtype: jnp.dtype = jnp.float32

                        @nn.compact
                        def __call__(self, emb, prev_bin, prev_emb):
                            bb, hh, ww, _ = emb.shape
                            prev_emb = interpolate_bilinear_align_corners(
                                prev_emb, hh, ww
                            )
                            z = emb + prev_emb
                            z = nn.Conv(cfg.bin_embedding_dim, (1, 1),
                                        dtype=self.dtype, name="conv1")(z)
                            z = nn.relu(z)
                            z = nn.Conv(self.n_attr, (1, 1),
                                        dtype=self.dtype, name="conv2")(z)
                            attractors = nn.softplus(
                                z.astype(jnp.float32)
                            )
                            centers = interpolate_bilinear_align_corners(
                                prev_bin.astype(jnp.float32), hh, ww
                            )
                            # upstream calls inv_attractor with its
                            # DEFAULTS (alpha=300, gamma=2) — the config
                            # fields are unused there
                            dx = (attractors[..., None] -
                                  centers[..., None, :])
                            # attractor_kind "mean": average the per-
                            # attractor contractions
                            delta = jnp.mean(
                                dx / (1.0 + 300.0 * dx * dx), axis=-2
                            )
                            new_centers = centers + delta
                            return new_centers, new_centers

                    prev_bin, bin_centers = _Attractor(
                        cfg.num_attractors[i], dtype=self.dtype,
                        name=f"attractors_{i}",
                    )(embedding, prev_bin, prev_embedding)
                    prev_embedding = embedding

                rel = relative_depth[..., None]
                bb, hh, ww, _ = outconv.shape
                rel = interpolate_bilinear_align_corners(rel, hh, ww)
                last = jnp.concatenate([outconv, rel.astype(outconv.dtype)],
                                       axis=-1)
                embedding = interpolate_bilinear_align_corners(
                    prev_embedding, hh, ww
                )
                probs = _ConditionalLogBinomial(
                    cfg, dtype=self.dtype, name="conditional_log_binomial"
                )(last, embedding)
                centers = interpolate_bilinear_align_corners(
                    bin_centers, hh, ww
                )
                return jnp.sum(probs * centers, axis=-1)

        return _MetricHead(dtype=self.dtype, name="metric_head")(
            rel_features, bottleneck, fused_states, relative_depth
        )
