"""Multilingual CLIP text tower (Kandinsky 2.1's encoder).

diffusers' `MultilingualCLIP` = an XLM-RoBERTa-Large trunk + attention-
mask mean pooling + one Linear into the 768-d CLIP space; the decoder
UNet cross-attends to the raw 1024-wide hidden states while the pooled
projection feeds the additive TextImageTimeEmbedding branch (reference
serves it through KandinskyPipeline, swarm/test.py:85-107).

XLM-R is architecturally RoBERTa, which models/clap.py already implements
(same post-LN layers, pad-offset position ids), so the trunk reuses those
blocks and the conversion reuses clap_rename; only the head differs
(mean-pool + `LinearTransformation` instead of CLS-pool + 2-layer MLP).
Numeric parity vs transformers XLMRobertaModel is asserted in
tests/test_kandinsky_conversion.py.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from .clap import ClapTextConfig, _Layer

# xlm-roberta-large geometry; serving reads the checkpoint config.json
MCLIP_XLMR_LARGE = ClapTextConfig(
    vocab_size=250_002,
    hidden_size=1024,
    num_layers=24,
    num_heads=16,
    intermediate_size=4096,
    max_positions=514,
    projection_dim=768,
    layer_norm_eps=1e-5,
)

TINY_MCLIP = ClapTextConfig(
    vocab_size=1000, hidden_size=32, num_layers=2, num_heads=4,
    intermediate_size=64, max_positions=80, projection_dim=16,
    layer_norm_eps=1e-5,
)


class MCLIPTextEncoder(nn.Module):
    config: ClapTextConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, input_ids, attention_mask=None):
        """[B, S] int32 -> {"hidden_states" [B,S,D], "pooled_proj" [B,P]}.

        `pooled_proj` = LinearTransformation(mean over non-pad tokens) —
        what the K2.1 UNet's text_embeds branch consumes; the hidden
        states cross-attend through the UNet's text_proj."""
        cfg = self.config
        if attention_mask is None:
            attention_mask = (input_ids != cfg.pad_token_id).astype(
                jnp.float32
            )
        positions = (
            jnp.cumsum(attention_mask.astype(jnp.int32), axis=1)
            * attention_mask.astype(jnp.int32)
            + cfg.pad_token_id
        )
        x = (
            nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=self.dtype,
                     name="word_embeddings")(input_ids)
            + nn.Embed(cfg.max_positions, cfg.hidden_size, dtype=self.dtype,
                       name="position_embeddings")(positions)
            + nn.Embed(cfg.type_vocab_size, cfg.hidden_size, dtype=self.dtype,
                       name="token_type_embeddings")(
                jnp.zeros_like(input_ids))
        )
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=self.dtype,
                         name="embed_norm")(x)
        for i in range(cfg.num_layers):
            x = _Layer(cfg, dtype=self.dtype, name=f"layers_{i}")(
                x, attention_mask
            )
        denom = jnp.maximum(attention_mask.sum(axis=1, keepdims=True), 1.0)
        pooled = (x * attention_mask[..., None]).sum(axis=1) / denom.astype(
            x.dtype
        )
        proj = nn.Dense(cfg.projection_dim, dtype=self.dtype,
                        name="transformation")(pooled)
        return {"hidden_states": x, "pooled_proj": proj}
