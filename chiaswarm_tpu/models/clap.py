"""CLAP text encoder (the AudioLDM prompt-conditioning tower).

Reference behavior replaced: diffusers' AudioLDMPipeline embeds prompts
with `ClapTextModelWithProjection` (the reference just calls the pipeline,
swarm/audio/audioldm.py:23-29). This flax module mirrors the transformers
graph — a RoBERTa-style post-LN encoder (learned positions offset past the
padding id, token-type embeddings), a tanh pooler over the CLS token, and
the two-layer CLAP projection into the 512-d joint audio-text space — so
checkpoints convert mechanically (conversion.convert_clap).
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ClapTextConfig:
    vocab_size: int = 50265  # roberta-base vocabulary
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_positions: int = 514
    type_vocab_size: int = 1
    pad_token_id: int = 1
    projection_dim: int = 512
    layer_norm_eps: float = 1e-12


TINY_CLAP = ClapTextConfig(
    vocab_size=1000, hidden_size=32, num_layers=2, num_heads=4,
    intermediate_size=64, max_positions=80, projection_dim=32,
)


class _SelfAttention(nn.Module):
    config: ClapTextConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, mask):
        cfg = self.config
        h = cfg.num_heads
        d = cfg.hidden_size // h

        def heads(t):
            return t.reshape(t.shape[0], t.shape[1], h, d)

        q = heads(nn.Dense(cfg.hidden_size, dtype=self.dtype, name="query")(x))
        k = heads(nn.Dense(cfg.hidden_size, dtype=self.dtype, name="key")(x))
        v = heads(nn.Dense(cfg.hidden_size, dtype=self.dtype, name="value")(x))
        att = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (d**-0.5)
        att = att + (1.0 - mask[:, None, None, :]) * -1e9
        att = jax.nn.softmax(att.astype(jnp.float32), axis=-1).astype(q.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", att, v)
        return out.reshape(x.shape)


class _Layer(nn.Module):
    """Post-LN transformer layer (BERT/RoBERTa convention)."""

    config: ClapTextConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, mask):
        cfg = self.config
        att = _SelfAttention(cfg, dtype=self.dtype, name="self_attn")(x, mask)
        att = nn.Dense(cfg.hidden_size, dtype=self.dtype, name="attn_out")(att)
        x = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="attn_norm"
        )(x + att)
        h = nn.Dense(
            cfg.intermediate_size, dtype=self.dtype, name="intermediate"
        )(x)
        h = nn.gelu(h, approximate=False)
        h = nn.Dense(cfg.hidden_size, dtype=self.dtype, name="output")(h)
        return nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="output_norm"
        )(x + h)


class ClapTextEncoder(nn.Module):
    config: ClapTextConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, input_ids, attention_mask=None):
        """[B, S] int32 -> {"hidden_states": [B,S,D], "pooled": [B,P]}.

        `pooled` is the CLAP text embedding (tanh pooler -> 2-layer
        projection), the conditioning vector AudioLDM's UNet consumes.
        """
        cfg = self.config
        if attention_mask is None:
            attention_mask = (input_ids != cfg.pad_token_id).astype(jnp.float32)
        # RoBERTa position ids: cumulative index over non-pad tokens,
        # offset past the padding id
        positions = (
            jnp.cumsum(attention_mask.astype(jnp.int32), axis=1)
            * attention_mask.astype(jnp.int32)
            + cfg.pad_token_id
        )
        x = (
            nn.Embed(
                cfg.vocab_size, cfg.hidden_size, dtype=self.dtype,
                name="word_embeddings",
            )(input_ids)
            + nn.Embed(
                cfg.max_positions, cfg.hidden_size, dtype=self.dtype,
                name="position_embeddings",
            )(positions)
            + nn.Embed(
                cfg.type_vocab_size, cfg.hidden_size, dtype=self.dtype,
                name="token_type_embeddings",
            )(jnp.zeros_like(input_ids))
        )
        x = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="embed_norm"
        )(x)
        for i in range(cfg.num_layers):
            x = _Layer(cfg, dtype=self.dtype, name=f"layers_{i}")(
                x, attention_mask
            )
        pooled = jnp.tanh(
            nn.Dense(cfg.hidden_size, dtype=self.dtype, name="pooler")(x[:, 0])
        )
        # ClapProjectionLayer: linear -> relu -> linear
        p = nn.Dense(cfg.projection_dim, dtype=self.dtype, name="proj_1")(pooled)
        p = nn.relu(p)
        p = nn.Dense(cfg.projection_dim, dtype=self.dtype, name="proj_2")(p)
        return {"hidden_states": x, "pooled": p}
