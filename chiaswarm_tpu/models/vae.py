"""AutoencoderKL (the SD latent VAE), flax.linen, NHWC.

Reference behavior being replaced: diffusers VAE with slicing/tiling memory
knobs (swarm/diffusion/diffusion_func.py:134-146). On TPU the decode runs
as one fused program; for batches, decode is shard_mapped over the mesh's
data axis instead of sliced sequentially (pipelines/stable_diffusion.py).
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp

from .layers import Downsample2D, FusedGroupNorm, ResnetBlock2D, Upsample2D
from ..ops import dot_product_attention


@dataclasses.dataclass(frozen=True)
class VAEConfig:
    in_channels: int = 3
    latent_channels: int = 4
    block_out_channels: tuple[int, ...] = (128, 256, 512, 512)
    layers_per_block: int = 2
    scaling_factor: float = 0.18215  # 0.13025 for SDXL, 0.3611 for Flux
    shift_factor: float = 0.0  # Flux: 0.1159 (latents are shifted, then scaled)
    # Flux VAE checkpoints ship without the 1x1 (post_)quant convs
    use_quant_conv: bool = True


class VAEAttention(nn.Module):
    """Single-head spatial self-attention used in the VAE mid blocks."""

    channels: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        residual = x
        hidden = FusedGroupNorm(32, epsilon=1e-6, dtype=self.dtype,
                                name="group_norm")(x)
        hidden = hidden.reshape(b, h * w, c)
        q = nn.Dense(c, dtype=self.dtype, name="to_q")(hidden)
        k = nn.Dense(c, dtype=self.dtype, name="to_k")(hidden)
        v = nn.Dense(c, dtype=self.dtype, name="to_v")(hidden)
        out = dot_product_attention(
            q[:, :, None, :], k[:, :, None, :], v[:, :, None, :]
        )[:, :, 0, :]
        out = nn.Dense(c, dtype=self.dtype, name="to_out_0")(out)
        return out.reshape(b, h, w, c) + residual


class Encoder(nn.Module):
    config: VAEConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, pixels):
        cfg = self.config
        x = nn.Conv(
            cfg.block_out_channels[0], (3, 3), padding=((1, 1), (1, 1)),
            dtype=self.dtype, name="conv_in",
        )(pixels)

        for b, out_ch in enumerate(cfg.block_out_channels):
            for i in range(cfg.layers_per_block):
                x = ResnetBlock2D(out_ch, eps=1e-6, dtype=self.dtype, name=f"down_blocks_{b}_resnets_{i}"
                )(x)
            if b != len(cfg.block_out_channels) - 1:
                x = Downsample2D(
                    out_ch,
                    asymmetric_pad=True,
                    dtype=self.dtype,
                    name=f"down_blocks_{b}_downsamplers_0",
                )(x)

        mid_ch = cfg.block_out_channels[-1]
        x = ResnetBlock2D(mid_ch, eps=1e-6, dtype=self.dtype, name="mid_block_resnets_0")(x)
        x = VAEAttention(mid_ch, dtype=self.dtype, name="mid_block_attentions_0")(x)
        x = ResnetBlock2D(mid_ch, eps=1e-6, dtype=self.dtype, name="mid_block_resnets_1")(x)

        x = FusedGroupNorm(32, epsilon=1e-6, dtype=self.dtype, act="silu",
                           name="conv_norm_out")(x)
        # moments: mean + logvar
        return nn.Conv(
            2 * cfg.latent_channels, (3, 3), padding=((1, 1), (1, 1)),
            dtype=self.dtype, name="conv_out",
        )(x)


class Decoder(nn.Module):
    config: VAEConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, latents):
        cfg = self.config
        mid_ch = cfg.block_out_channels[-1]
        x = nn.Conv(
            mid_ch, (3, 3), padding=((1, 1), (1, 1)), dtype=self.dtype, name="conv_in"
        )(latents)

        x = ResnetBlock2D(mid_ch, eps=1e-6, dtype=self.dtype, name="mid_block_resnets_0")(x)
        x = VAEAttention(mid_ch, dtype=self.dtype, name="mid_block_attentions_0")(x)
        x = ResnetBlock2D(mid_ch, eps=1e-6, dtype=self.dtype, name="mid_block_resnets_1")(x)

        for b, out_ch in enumerate(reversed(cfg.block_out_channels)):
            for i in range(cfg.layers_per_block + 1):
                x = ResnetBlock2D(out_ch, eps=1e-6, dtype=self.dtype, name=f"up_blocks_{b}_resnets_{i}"
                )(x)
            if b != len(cfg.block_out_channels) - 1:
                x = Upsample2D(
                    out_ch, dtype=self.dtype, name=f"up_blocks_{b}_upsamplers_0"
                )(x)

        x = FusedGroupNorm(32, epsilon=1e-6, dtype=self.dtype, act="silu",
                           name="conv_norm_out")(x)
        return nn.Conv(
            cfg.in_channels, (3, 3), padding=((1, 1), (1, 1)), dtype=self.dtype,
            name="conv_out",
        )(x)


class AutoencoderKL(nn.Module):
    config: VAEConfig
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        self.encoder = Encoder(self.config, dtype=self.dtype)
        self.decoder = Decoder(self.config, dtype=self.dtype)
        if self.config.use_quant_conv:
            self.quant_conv = nn.Conv(
                2 * self.config.latent_channels, (1, 1), dtype=self.dtype
            )
            self.post_quant_conv = nn.Conv(
                self.config.latent_channels, (1, 1), dtype=self.dtype
            )
        else:  # Flux layout: encoder/decoder connect directly to the latents
            self.quant_conv = lambda x: x
            self.post_quant_conv = lambda x: x

    def encode(self, pixels, rng=None):
        """pixels [B,H,W,3] in [-1,1] -> scaled latents [B,H/8,W/8,C]."""
        moments = self.quant_conv(self.encoder(pixels))
        mean, logvar = jnp.split(moments, 2, axis=-1)
        if rng is not None:
            import jax

            std = jnp.exp(0.5 * jnp.clip(logvar, -30.0, 20.0))
            mean = mean + std * jax.random.normal(rng, mean.shape, mean.dtype)
        return (mean - self.config.shift_factor) * self.config.scaling_factor

    def decode(self, latents):
        """scaled latents -> pixels [B,H,W,3] in [-1,1]."""
        latents = latents / self.config.scaling_factor + self.config.shift_factor
        return self.decoder(self.post_quant_conv(latents))

    def __call__(self, pixels):
        return self.decode(self.encode(pixels))
