"""SD-x2 latent upscaler UNet: the K-diffusion upscaler graph diffusers
serves as `UNet2DConditionModel` with K-blocks — rebuilt as one flax
module in NHWC.

Reference behavior replaced: swarm/post_processors/upscale.py:5-36 loads
`StableDiffusionLatentUpscalePipeline` per upscale job; its UNet is a
distinct family from every other UNet in the inventory: Gaussian-Fourier
time features with a 896-d conditioning projection folded INTO the
timestep embedding (cat of a fixed 128-d noise-level embed and the CLIP
pooler output), AdaGroupNorm everywhere (affine-free GroupNorm whose
scale/shift are a plain Linear of the time embedding), gelu resnets with
bias-free shortcuts, fixed (non-learned) blur kernels for down/up
sampling, K-attention blocks with layer-normed cross states, a 1x1
conv-in over 8 channels (noise + conditioning latents), no mid block, no
output norm, and a 5-channel 1x1 conv-out whose last channel is dropped.

Skip wiring (channel shapes pin it): each down level contributes its
pre-downsample output; the deepest up block concatenates the bottom
hidden with itself (the K-UNet's symmetric 2x-width entry), shallower up
blocks concatenate the mirrored down output after upsampling.

Module names line up with the diffusers state-dict names so conversion
(models/conversion.py convert_k_upscaler) is a mechanical rename.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class KUpscalerConfig:
    in_channels: int = 8
    out_channels: int = 5
    block_out_channels: tuple[int, ...] = (384, 768, 1280, 1280)
    layers_per_block: int = 4
    cross_attention_dim: int = 768
    attention_head_dim: int = 64
    resnet_group_size: int = 32
    time_cond_proj_dim: int = 896
    cross_attention: tuple[bool, ...] = (False, True, True, True)
    # self-attention lives at the bottom of the U (deepest down + deepest
    # up); conversion infers the real placement from attn1 key presence
    down_self_attention: tuple[bool, ...] = (False, False, False, True)
    up_self_attention: tuple[bool, ...] = (True, False, False, False)
    attention_bias: bool = True


TINY_K_UPSCALER = KUpscalerConfig(
    block_out_channels=(32, 64),
    layers_per_block=2,
    cross_attention_dim=32,
    attention_head_dim=8,
    resnet_group_size=16,
    # tiny CLIP pools 32-wide + a 16-wide fixed noise embed (the real
    # model is 768 + 128 = 896)
    time_cond_proj_dim=48,
    cross_attention=(False, True),
    down_self_attention=(False, True),
    up_self_attention=(True, False),
)


def _blur_kernel(scale: float) -> np.ndarray:
    k1 = np.asarray([1.0, 3.0, 3.0, 1.0], np.float32) / 8.0 * scale
    return np.outer(k1, k1)


class KDownsample2D(nn.Module):
    """Fixed depthwise 4x4 blur, stride 2, reflect pad 1 — no params."""

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        x = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)), mode="reflect")
        kernel = jnp.asarray(_blur_kernel(1.0), self.dtype)
        kernel = jnp.tile(kernel[:, :, None, None], (1, 1, 1, c))
        return jax.lax.conv_general_dilated(
            x.astype(self.dtype), kernel, (2, 2), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=c,
        )


class KUpsample2D(nn.Module):
    """Fixed depthwise transposed 4x4 blur, stride 2 (torch
    conv_transpose2d(stride=2, padding=3) on a reflect-pad-1 input ==
    input dilation 2 + VALID conv with the symmetric kernel) — no
    params."""

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        x = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)), mode="reflect")
        kernel = jnp.asarray(_blur_kernel(2.0), self.dtype)
        kernel = jnp.tile(kernel[:, :, None, None], (1, 1, 1, c))
        return jax.lax.conv_general_dilated(
            x.astype(self.dtype), kernel, (1, 1), ((0, 0), (0, 0)),
            lhs_dilation=(2, 2),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=c,
        )


class AdaGroupNorm(nn.Module):
    """Affine-free GroupNorm; scale/shift from a Linear of the time
    embedding (no activation): x_norm * (1 + scale) + shift."""

    groups: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, temb):
        c = x.shape[-1]
        emb = nn.Dense(2 * c, dtype=self.dtype, name="linear")(temb)
        scale, shift = jnp.split(emb[:, None, None, :], 2, axis=-1)
        x = nn.GroupNorm(
            self.groups, epsilon=1e-5, use_bias=False, use_scale=False,
            dtype=self.dtype,
        )(x)
        return x * (1.0 + scale) + shift


class KResnetBlock(nn.Module):
    """diffusers ResnetBlockCondNorm2D (ada_group): AdaGN -> gelu -> conv,
    twice; bias-free 1x1 shortcut on width change."""

    out_channels: int
    group_size: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, temb):
        in_ch = x.shape[-1]
        h = AdaGroupNorm(
            max(1, in_ch // self.group_size), dtype=self.dtype, name="norm1"
        )(x, temb)
        h = nn.gelu(h, approximate=False)
        h = nn.Conv(
            self.out_channels, (3, 3), dtype=self.dtype, name="conv1"
        )(h)
        h = AdaGroupNorm(
            max(1, self.out_channels // self.group_size), dtype=self.dtype,
            name="norm2",
        )(h, temb)
        h = nn.gelu(h, approximate=False)
        h = nn.Conv(
            self.out_channels, (3, 3), dtype=self.dtype, name="conv2"
        )(h)
        if in_ch != self.out_channels:
            x = nn.Conv(
                self.out_channels, (1, 1), use_bias=False, dtype=self.dtype,
                name="conv_shortcut",
            )(x)
        return x + h


class KAttention(nn.Module):
    """diffusers Attention as the K blocks build it: optional q/k/v bias,
    to_out.0 with bias, layer-normed cross states (norm_cross)."""

    inner: int
    head_dim: int
    use_bias: bool = True
    cross_norm: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, q_in, kv_in):
        heads = max(1, self.inner // self.head_dim)
        dim = self.inner // heads
        b, n, _ = q_in.shape
        if self.cross_norm:
            kv_in = nn.LayerNorm(
                epsilon=1e-5, dtype=self.dtype, name="norm_cross"
            )(kv_in)
        s = kv_in.shape[1]
        q = nn.Dense(self.inner, use_bias=self.use_bias, dtype=self.dtype,
                     name="to_q")(q_in)
        k = nn.Dense(self.inner, use_bias=self.use_bias, dtype=self.dtype,
                     name="to_k")(kv_in)
        v = nn.Dense(self.inner, use_bias=self.use_bias, dtype=self.dtype,
                     name="to_v")(kv_in)
        q = q.reshape(b, n, heads, dim)
        k = k.reshape(b, s, heads, dim)
        v = v.reshape(b, s, heads, dim)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        weights = nn.softmax(logits * (dim ** -0.5), axis=-1).astype(
            self.dtype
        )
        out = jnp.einsum("bhqk,bkhd->bqhd", weights, v).reshape(
            b, n, self.inner
        )
        return nn.Dense(self.inner, dtype=self.dtype, name="to_out_0")(out)


class KAttentionBlock(nn.Module):
    """AdaGN-normed token-space attention: optional self (attn1) then
    cross (attn2) over layer-normed encoder states, both residual."""

    head_dim: int
    group_size: int
    self_attention: bool = False
    attention_bias: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, temb, context):
        b, h, w, c = x.shape
        groups = max(1, c // self.group_size)
        if self.self_attention:
            norm = AdaGroupNorm(groups, dtype=self.dtype, name="norm1")(
                x, temb
            )
            tokens = norm.reshape(b, h * w, c)
            attn = KAttention(
                c, self.head_dim, use_bias=self.attention_bias,
                dtype=self.dtype, name="attn1",
            )(tokens, tokens)
            x = x + attn.reshape(b, h, w, c)
        norm = AdaGroupNorm(groups, dtype=self.dtype, name="norm2")(x, temb)
        tokens = norm.reshape(b, h * w, c)
        attn = KAttention(
            c, self.head_dim, use_bias=self.attention_bias, cross_norm=True,
            dtype=self.dtype, name="attn2",
        )(tokens, context)
        return x + attn.reshape(b, h, w, c)


class KUpscalerUNet(nn.Module):
    """[B,H,W,8] (noise latents + conditioning latents) + [B] continuous
    timesteps (log(sigma)/4) + [B,S,cross] CLIP states + [B,896]
    timestep_cond -> [B,H,W,out_channels]."""

    config: KUpscalerConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, sample, timesteps, encoder_hidden_states,
                 timestep_cond):
        cfg = self.config
        n = len(cfg.block_out_channels)
        c0 = cfg.block_out_channels[0]

        # GaussianFourierProjection(log=False, flip_sin_to_cos=True):
        # cat(cos, sin) of 2*pi*w*t with a frozen random weight vector
        w = self.param(
            "time_proj_weight", nn.initializers.normal(16.0), (c0,)
        )
        args = (
            jnp.asarray(timesteps, jnp.float32)[:, None]
            * jax.lax.stop_gradient(jnp.asarray(w, jnp.float32))[None, :]
            * (2.0 * np.pi)
        )
        t_emb = jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)
        t_emb = t_emb.astype(self.dtype)
        # TimestepEmbedding with cond_proj + gelu act AND post-act
        t_emb = t_emb + nn.Dense(
            2 * c0, use_bias=False, dtype=self.dtype,
            name="time_embedding_cond_proj",
        )(jnp.asarray(timestep_cond, self.dtype))
        t_emb = nn.Dense(
            2 * c0, dtype=self.dtype, name="time_embedding_linear_1"
        )(t_emb)
        t_emb = nn.gelu(t_emb, approximate=False)
        t_emb = nn.Dense(
            2 * c0, dtype=self.dtype, name="time_embedding_linear_2"
        )(t_emb)
        temb = nn.gelu(t_emb, approximate=False)

        context = jnp.asarray(encoder_hidden_states, self.dtype)
        x = nn.Conv(
            c0, (1, 1), dtype=self.dtype, name="conv_in"
        )(jnp.asarray(sample, self.dtype))

        skips = []
        for i in range(n):
            out_ch = cfg.block_out_channels[i]
            for j in range(cfg.layers_per_block):
                x = KResnetBlock(
                    out_ch, cfg.resnet_group_size, dtype=self.dtype,
                    name=f"down_blocks_{i}_resnets_{j}",
                )(x, temb)
                if cfg.cross_attention[i]:
                    x = KAttentionBlock(
                        cfg.attention_head_dim, cfg.resnet_group_size,
                        self_attention=cfg.down_self_attention[i],
                        attention_bias=cfg.attention_bias,
                        dtype=self.dtype,
                        name=f"down_blocks_{i}_attentions_{j}",
                    )(x, temb, context)
            skips.append(x)
            if i != n - 1:
                x = KDownsample2D(dtype=self.dtype)(x)

        rev = tuple(reversed(cfg.block_out_channels))
        for lvl in range(n):
            i = n - 1 - lvl
            out_ch = rev[lvl]
            k_out = rev[min(lvl + 1, n - 1)]
            x = jnp.concatenate([x, skips.pop()], axis=-1)
            nb = cfg.layers_per_block
            for j in range(nb):
                width = k_out if j == nb - 1 else out_ch
                x = KResnetBlock(
                    width, cfg.resnet_group_size, dtype=self.dtype,
                    name=f"up_blocks_{lvl}_resnets_{j}",
                )(x, temb)
                if cfg.cross_attention[i]:
                    x = KAttentionBlock(
                        cfg.attention_head_dim, cfg.resnet_group_size,
                        self_attention=cfg.up_self_attention[lvl],
                        attention_bias=cfg.attention_bias,
                        dtype=self.dtype,
                        name=f"up_blocks_{lvl}_attentions_{j}",
                    )(x, temb, context)
            if lvl != n - 1:
                x = KUpsample2D(dtype=self.dtype)(x)

        return nn.Conv(
            cfg.out_channels, (1, 1), dtype=self.dtype, name="conv_out"
        )(x)
