"""Temporal video UNet: the SD block stack + motion (temporal-attention)
modules, covering AnimateDiff-style txt2vid and SVD/I2VGenXL-style img2vid.

Reference behavior replaced: swarm/video/tx2vid.py:26-48 (AnimateDiff motion
adapters loaded per job onto a torch UNet) and swarm/video/img2vid.py
(StableVideoDiffusion). TPU-first inversions:

- frames ride the batch dim for all spatial ops ([B*F, H, W, C] — keeps the
  MXU fed with large convs/matmuls), and temporal mixing happens in compact
  [B*H*W, F, C] self-attention blocks after each spatial stage, matching the
  AnimateDiff motion-module graph for weight conversion;
- the whole clip denoises as ONE scan program — no per-frame Python loop
  (the reference's vid2vid runs up to 100 sequential pipeline invocations,
  swarm/video/pix2pix.py:47-68);
- img2vid conditions by concatenating the encoded conditioning frame onto
  every frame's latent channels (SVD layout: in_channels 8).
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp

from .layers import (
    Attention,
    Downsample2D,
    FeedForward,
    FusedGroupNorm,
    ResnetBlock2D,
    TimestepEmbedding,
    Transformer2DModel,
    Upsample2D,
    timestep_embedding,
)
from .unet2d import UNet2DConfig


@dataclasses.dataclass(frozen=True)
class VideoUNetConfig:
    base: UNet2DConfig = UNet2DConfig()
    num_frames: int = 16
    temporal_pos_max: int = 32  # max frames the positional table supports


def _sinusoidal_pe(n: int, dim: int, dtype) -> jnp.ndarray:
    """diffusers SinusoidalPositionalEmbedding layout: sin/cos INTERLEAVED
    (pe[:, 0::2]=sin, pe[:, 1::2]=cos) — converted attention weights were
    trained against this layout, so the concatenated variant would silently
    scramble positions."""
    position = jnp.arange(n, dtype=jnp.float32)[:, None]
    div = jnp.exp(
        jnp.arange(0, dim, 2, dtype=jnp.float32) * (-jnp.log(10000.0) / dim)
    )
    args = position * div[None]
    pe = jnp.zeros((n, dim), jnp.float32)
    pe = pe.at[:, 0::2].set(jnp.sin(args))
    pe = pe.at[:, 1::2].set(jnp.cos(args))
    return pe.astype(dtype)


class _TemporalBlock(nn.Module):
    """diffusers motion BasicTransformerBlock: two temporal SELF-attentions
    and a GEGLU FF, with the sinusoidal positions applied to the NORMED
    input of each attention (positional_embeddings='sinusoidal')."""

    channels: int
    num_heads: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, hidden, pos):
        c = self.channels
        hd = c // self.num_heads
        y = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="norm1")(hidden)
        hidden = hidden + Attention(
            self.num_heads, hd, c, dtype=self.dtype, name="attn1"
        )(y + pos[None])
        y = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="norm2")(hidden)
        hidden = hidden + Attention(
            self.num_heads, hd, c, dtype=self.dtype, name="attn2"
        )(y + pos[None])
        y = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="norm3")(hidden)
        return hidden + FeedForward(c, dtype=self.dtype, name="ff")(y)


class TemporalTransformer(nn.Module):
    """Self-attention over the frame axis at fixed spatial positions.

    Input [BF, H, W, C]; `num_frames` is the RUNTIME clip length (static at
    trace time), passed per call because jobs may request fewer frames than
    the configured maximum — deriving it from config would fold the CFG
    uncond/cond halves into one clip.

    The graph IS diffusers' AnimateDiff motion module (group norm ->
    proj_in -> temporal transformer blocks -> zero-init proj_out ->
    residual), parameter-for-parameter, so real motion-adapter checkpoints
    convert mechanically (conversion.py convert_motion_adapter).
    """

    channels: int
    num_heads: int = 8
    num_layers: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, num_frames: int):
        bf, h, w, c = x.shape
        if bf % num_frames:
            raise ValueError(
                f"batch*frames {bf} not divisible by num_frames {num_frames}"
            )
        b = bf // num_frames
        residual = x
        hidden = FusedGroupNorm(32, epsilon=1e-6, dtype=self.dtype,
                                name="norm")(x)
        # [B, F, H, W, C] -> [B*H*W, F, C]
        hidden = hidden.reshape(b, num_frames, h, w, c)
        hidden = hidden.transpose(0, 2, 3, 1, 4).reshape(b * h * w, num_frames, c)
        hidden = nn.Dense(c, dtype=self.dtype, name="proj_in")(hidden)

        pos = _sinusoidal_pe(num_frames, c, self.dtype)
        heads = self.num_heads if c % self.num_heads == 0 else max(
            1, min(self.num_heads, c // 8)
        )
        for i in range(self.num_layers):
            hidden = _TemporalBlock(
                c, heads, dtype=self.dtype, name=f"transformer_blocks_{i}"
            )(hidden, pos)

        # zero-init output projection: an unconverted motion module is a
        # no-op on the spatial model (AnimateDiff init convention)
        hidden = nn.Dense(
            c, kernel_init=nn.initializers.zeros, dtype=self.dtype,
            name="proj_out",
        )(hidden)
        hidden = hidden.reshape(b, h, w, num_frames, c)
        hidden = hidden.transpose(0, 3, 1, 2, 4).reshape(bf, h, w, c)
        return residual + hidden


class VideoUNet(nn.Module):
    """[B*F, H, W, C] latents -> noise prediction, temporally mixed."""

    config: VideoUNetConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, sample, timesteps, encoder_hidden_states, num_frames=None):
        cfg = self.config.base
        # runtime clip length (static per compile); defaults to the config
        # maximum for single-clip calls like param init
        frames = int(num_frames) if num_frames is not None else self.config.num_frames
        if frames > self.config.temporal_pos_max:
            raise ValueError(
                f"num_frames {frames} exceeds temporal_pos_max "
                f"{self.config.temporal_pos_max}"
            )
        if jnp.ndim(timesteps) == 0:
            timesteps = jnp.broadcast_to(timesteps, (sample.shape[0],))

        temb_dim = cfg.block_out_channels[0] * 4
        t_feat = timestep_embedding(
            timesteps, cfg.block_out_channels[0],
            flip_sin_to_cos=cfg.flip_sin_to_cos,
            downscale_freq_shift=cfg.freq_shift, dtype=self.dtype,
        )
        temb = TimestepEmbedding(temb_dim, dtype=self.dtype, name="time_embedding")(
            t_feat
        )

        x = nn.Conv(
            cfg.block_out_channels[0], (3, 3), padding=((1, 1), (1, 1)),
            dtype=self.dtype, name="conv_in",
        )(sample)

        heads = cfg.heads_per_block()
        skips = [x]
        for bidx, out_ch in enumerate(cfg.block_out_channels):
            last = bidx == len(cfg.block_out_channels) - 1
            for i in range(cfg.layers_per_block):
                x = ResnetBlock2D(
                    out_ch, dtype=self.dtype, name=f"down_{bidx}_resnets_{i}"
                )(x, temb)
                if cfg.transformer_layers[bidx] > 0:
                    x = Transformer2DModel(
                        heads[bidx], out_ch // heads[bidx],
                        cfg.transformer_layers[bidx], dtype=self.dtype,
                        name=f"down_{bidx}_attentions_{i}",
                    )(x, encoder_hidden_states)
                x = TemporalTransformer(
                    out_ch, dtype=self.dtype,
                    name=f"down_{bidx}_motion_modules_{i}",
                )(x, frames)
                skips.append(x)
            if not last:
                x = Downsample2D(out_ch, dtype=self.dtype, name=f"down_{bidx}_downsample")(x)
                skips.append(x)

        mid_ch = cfg.block_out_channels[-1]
        x = ResnetBlock2D(mid_ch, dtype=self.dtype, name="mid_resnets_0")(x, temb)
        x = Transformer2DModel(
            heads[-1], mid_ch // heads[-1], cfg.mid_transformer_layers,
            dtype=self.dtype, name="mid_attentions_0",
        )(x, encoder_hidden_states)
        x = TemporalTransformer(
            mid_ch, dtype=self.dtype, name="mid_motion_modules_0"
        )(x, frames)
        x = ResnetBlock2D(mid_ch, dtype=self.dtype, name="mid_resnets_1")(x, temb)

        for bidx, out_ch in enumerate(reversed(cfg.block_out_channels)):
            rev = len(cfg.block_out_channels) - 1 - bidx
            last = bidx == len(cfg.block_out_channels) - 1
            for i in range(cfg.layers_per_block + 1):
                x = jnp.concatenate([x, skips.pop()], axis=-1)
                x = ResnetBlock2D(
                    out_ch, dtype=self.dtype, name=f"up_{bidx}_resnets_{i}"
                )(x, temb)
                if cfg.transformer_layers[rev] > 0:
                    x = Transformer2DModel(
                        heads[rev], out_ch // heads[rev],
                        cfg.transformer_layers[rev], dtype=self.dtype,
                        name=f"up_{bidx}_attentions_{i}",
                    )(x, encoder_hidden_states)
                x = TemporalTransformer(
                    out_ch, dtype=self.dtype,
                    name=f"up_{bidx}_motion_modules_{i}",
                )(x, frames)
            if not last:
                x = Upsample2D(out_ch, dtype=self.dtype, name=f"up_{bidx}_upsample")(x)

        x = FusedGroupNorm(32, epsilon=1e-5, dtype=self.dtype, act="silu",
                           name="conv_norm_out")(x)
        return nn.Conv(
            cfg.out_channels, (3, 3), padding=((1, 1), (1, 1)), dtype=self.dtype,
            name="conv_out",
        )(x)
