"""Shared diffusion building blocks (flax.linen, NHWC).

Block semantics match the SD/SDXL architecture family so HF checkpoints
convert 1:1 (conversion.py), but the code is organized TPU-first: tensors
stay NHWC, attention routes through ops.dot_product_attention (Pallas flash
on TPU), and everything traces to static shapes.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from ..ops import dot_product_attention
from ..ops.group_norm import group_norm


class FusedGroupNorm(nn.Module):
    """Drop-in nn.GroupNorm with an optionally fused SiLU epilogue.

    Param tree ("scale"/"bias", [C] f32) is identical to nn.GroupNorm, so
    checkpoint conversion is unchanged; compute routes through
    ops.group_norm — the single-pass Pallas kernel on TPU (1 HBM read +
    1 write vs the 2+1 of a separate norm + activation), the XLA-fused
    reference elsewhere (CHIASWARM_DISABLE_FUSED_GN=1 forces the latter
    for A/B). Numerics pinned by tests/test_group_norm.py.
    """

    num_groups: int = 32
    epsilon: float = 1e-5
    dtype: jnp.dtype = jnp.float32
    act: str | None = None

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        return group_norm(
            x, scale, bias, groups=self.num_groups, eps=self.epsilon,
            act=self.act, dtype=self.dtype,
        )


def timestep_embedding(
    timesteps,
    dim: int,
    *,
    max_period: float = 10000.0,
    flip_sin_to_cos: bool = True,
    downscale_freq_shift: float = 0.0,
    dtype=jnp.float32,
):
    """Sinusoidal timestep features [B] -> [B, dim] (SD convention: cos-first)."""
    half = dim // 2
    exponent = -jnp.log(max_period) * jnp.arange(half, dtype=jnp.float32)
    exponent = exponent / (half - downscale_freq_shift)
    freqs = jnp.exp(exponent)
    args = jnp.asarray(timesteps, jnp.float32)[:, None] * freqs[None, :]
    emb = jnp.concatenate([jnp.sin(args), jnp.cos(args)], axis=-1)
    if flip_sin_to_cos:
        emb = jnp.concatenate([emb[:, half:], emb[:, :half]], axis=-1)
    return emb.astype(dtype)


class TimestepEmbedding(nn.Module):
    """2-layer MLP lifting sinusoidal features to the UNet's temb width."""

    dim: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, sample):
        sample = nn.Dense(self.dim, dtype=self.dtype, name="linear_1")(sample)
        sample = nn.silu(sample)
        return nn.Dense(self.dim, dtype=self.dtype, name="linear_2")(sample)


class ResnetBlock2D(nn.Module):
    out_channels: int
    # diffusers: UNet resnets norm at 1e-5, VAE resnets at 1e-6
    eps: float = 1e-5
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, temb=None):
        residual = x
        h = FusedGroupNorm(32, epsilon=self.eps, dtype=self.dtype,
                           act="silu", name="norm1")(x)
        h = nn.Conv(
            self.out_channels, (3, 3), padding=((1, 1), (1, 1)), dtype=self.dtype,
            name="conv1",
        )(h)

        if temb is not None:
            temb_proj = nn.Dense(self.out_channels, dtype=self.dtype, name="time_emb_proj")(
                nn.silu(temb)
            )
            h = h + temb_proj[:, None, None, :]

        h = FusedGroupNorm(32, epsilon=self.eps, dtype=self.dtype,
                           act="silu", name="norm2")(h)
        h = nn.Conv(
            self.out_channels, (3, 3), padding=((1, 1), (1, 1)), dtype=self.dtype,
            name="conv2",
        )(h)

        if residual.shape[-1] != self.out_channels:
            residual = nn.Conv(
                self.out_channels, (1, 1), dtype=self.dtype, name="conv_shortcut"
            )(residual)
        return h + residual


class Attention(nn.Module):
    """Multi-head attention over [B, S, C] with optional cross context."""

    num_heads: int
    head_dim: int
    out_dim: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, hidden, context=None):
        context = hidden if context is None else context
        inner = self.num_heads * self.head_dim
        q = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="to_q")(hidden)
        k = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="to_k")(context)
        v = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="to_v")(context)

        b, sq, _ = q.shape
        sk = k.shape[1]
        q = q.reshape(b, sq, self.num_heads, self.head_dim)
        k = k.reshape(b, sk, self.num_heads, self.head_dim)
        v = v.reshape(b, sk, self.num_heads, self.head_dim)

        out = dot_product_attention(q, k, v)
        out = out.reshape(b, sq, inner)
        return nn.Dense(self.out_dim, dtype=self.dtype, name="to_out_0")(out)


class GEGLU(nn.Module):
    dim: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.dim * 2, dtype=self.dtype, name="proj")(x)
        h, gate = jnp.split(h, 2, axis=-1)
        return h * nn.gelu(gate, approximate=False)  # erf gelu, diffusers parity


class FeedForward(nn.Module):
    dim: int
    mult: int = 4
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = GEGLU(self.dim * self.mult, dtype=self.dtype, name="net_0")(x)
        return nn.Dense(self.dim, dtype=self.dtype, name="net_2")(x)


class BasicTransformerBlock(nn.Module):
    """self-attn -> cross-attn -> GEGLU MLP, pre-LN residual wiring."""

    dim: int
    num_heads: int
    head_dim: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, hidden, context):
        attn = Attention(
            self.num_heads, self.head_dim, self.dim, dtype=self.dtype, name="attn1"
        )
        hidden = hidden + attn(
            nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="norm1")(hidden)
        )
        cross = Attention(
            self.num_heads, self.head_dim, self.dim, dtype=self.dtype, name="attn2"
        )
        hidden = hidden + cross(
            nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="norm2")(hidden), context
        )
        ff = FeedForward(self.dim, dtype=self.dtype, name="ff")
        return hidden + ff(
            nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="norm3")(hidden)
        )


class Transformer2DModel(nn.Module):
    """Spatial transformer: NHWC -> tokens -> N blocks -> NHWC residual."""

    num_heads: int
    head_dim: int
    num_layers: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, context):
        b, h, w, c = x.shape
        residual = x
        hidden = FusedGroupNorm(32, epsilon=1e-6, dtype=self.dtype,
                                name="norm")(x)
        hidden = hidden.reshape(b, h * w, c)
        hidden = nn.Dense(c, dtype=self.dtype, name="proj_in")(hidden)
        for i in range(self.num_layers):
            hidden = BasicTransformerBlock(
                c,
                self.num_heads,
                self.head_dim,
                dtype=self.dtype,
                name=f"transformer_blocks_{i}",
            )(hidden, context)
        hidden = nn.Dense(c, dtype=self.dtype, name="proj_out")(hidden)
        return hidden.reshape(b, h, w, c) + residual


class Downsample2D(nn.Module):
    out_channels: int
    # VAE encoder uses asymmetric (0,1) padding (diffusers parity); UNet (1,1)
    asymmetric_pad: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        pad = ((0, 1), (0, 1)) if self.asymmetric_pad else ((1, 1), (1, 1))
        return nn.Conv(
            self.out_channels,
            (3, 3),
            strides=(2, 2),
            padding=pad,
            dtype=self.dtype,
            name="conv",
        )(x)


class Upsample2D(nn.Module):
    out_channels: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        x = jnp.repeat(jnp.repeat(x, 2, axis=1), 2, axis=2)  # nearest 2x
        return nn.Conv(
            self.out_channels, (3, 3), padding=((1, 1), (1, 1)), dtype=self.dtype,
            name="conv",
        )(x)
