"""Paella VQGAN (Stable Cascade stage A) — decode path, NHWC flax.

The reference's `StableCascadeDecoderPipeline` (swarm/diffusion/
pipeline_steps.py:70-90) finishes jobs by running the stage-B latents
through this model's `decode` (diffusers `PaellaVQModel.decode` with
`force_not_quantize` defaulting the quantizer away), so serving only needs
the up path: latent 1x1 in-conv -> MixingResidualBlock stack (12
bottleneck blocks at the deep level, 1 at the shallow) -> transposed-conv
2x -> 1x1 out conv + pixel-shuffle 2x == a 4x spatial decode overall.

Conversion (`convert_paella_vq` in conversion.py) maps the decode-side
keys (`up_blocks.*`, `out_block.*`) and ignores the encoder/quantizer
tables, which serving never touches.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp

from .cascade_unet import ConvTransposed2D, pixel_shuffle


@dataclasses.dataclass(frozen=True)
class PaellaVQConfig:
    out_channels: int = 3
    up_down_scale_factor: int = 2
    levels: int = 2
    bottleneck_blocks: int = 12
    embed_dim: int = 384
    latent_channels: int = 4
    scale_factor: float = 0.3764

    def c_levels(self) -> tuple[int, ...]:
        return tuple(
            self.embed_dim // (2**i) for i in reversed(range(self.levels))
        )


TINY_PAELLA_VQ = PaellaVQConfig(
    levels=2, bottleneck_blocks=2, embed_dim=32, latent_channels=4
)


class MixingResidualBlock(nn.Module):
    """LN-modulated depthwise (edge-padded 3x3) + channel MLP, with six
    learned per-block gammas gating each branch (Paella block)."""

    channels: int
    embed_dim: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        mods = self.param("gammas", nn.initializers.zeros, (6,)).astype(x.dtype)

        def ln(v):
            return nn.LayerNorm(
                epsilon=1e-6, use_scale=False, use_bias=False, dtype=self.dtype
            )(v)

        h = ln(x) * (1 + mods[0]) + mods[1]
        h = jnp.pad(h, ((0, 0), (1, 1), (1, 1), (0, 0)), mode="edge")
        h = nn.Conv(
            self.channels,
            (3, 3),
            padding="VALID",
            feature_group_count=self.channels,
            dtype=self.dtype,
            name="depthwise_1",
        )(h)
        x = x + h * mods[2]
        h = ln(x) * (1 + mods[3]) + mods[4]
        h = nn.Dense(self.embed_dim, dtype=self.dtype, name="channelwise_0")(h)
        h = nn.gelu(h, approximate=False)
        h = nn.Dense(self.channels, dtype=self.dtype, name="channelwise_2")(h)
        return x + h * mods[5]


class PaellaVQDecoder(nn.Module):
    config: PaellaVQConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, latents):
        """[B, h, w, latent_channels] (already divided by scale_factor at
        the call site, diffusers decode semantics) -> [B, 4h, 4w, 3]."""
        cfg = self.config
        c_levels = cfg.c_levels()
        x = latents.astype(self.dtype)
        idx = 0
        x = nn.Conv(
            c_levels[-1], (1, 1), dtype=self.dtype, name=f"up_blocks_{idx}_0"
        )(x)
        idx += 1
        for i in range(cfg.levels):
            ch = c_levels[cfg.levels - 1 - i]
            for _ in range(cfg.bottleneck_blocks if i == 0 else 1):
                x = MixingResidualBlock(
                    ch, ch * 4, dtype=self.dtype, name=f"up_blocks_{idx}"
                )(x)
                idx += 1
            if i < cfg.levels - 1:
                x = ConvTransposed2D(
                    c_levels[cfg.levels - 2 - i],
                    kernel_size=4,
                    stride=2,
                    padding=1,
                    dtype=self.dtype,
                    name=f"up_blocks_{idx}",
                )(x)
                idx += 1
        x = nn.Conv(
            cfg.out_channels * cfg.up_down_scale_factor**2,
            (1, 1),
            dtype=self.dtype,
            name="out_block_0",
        )(x)
        return pixel_shuffle(x, cfg.up_down_scale_factor)
