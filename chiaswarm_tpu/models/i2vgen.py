"""I2VGenXLUNet — the default image-to-video graph the reference serves
(swarm/job_arguments.py:143 resolves img2vid jobs to I2VGenXLPipeline).

The trunk is the UNet3DConditionModel block structure (models/unet3d.py
unet3d_backbone: resnet + temporal conv + spatial/temporal transformers,
frames riding the batch axis). Around it, I2VGenXL adds:
- an FPS embedding summed into the time embedding;
- a per-frame image-latents stream: 1x1/3x3 conv projection to latent
  width, a tiny frame-axis transformer encoder at every pixel, then
  channel-concat with the noisy latents into an 8-channel conv_in;
- context tokens assembled from THREE sources: the CLIP text states, an
  8x8 grid of first-frame latent features (conv stack + adaptive 32x32
  average pool + two stride-2 convs to cross width), and the CLIP image
  embedding lifted to `in_channels` extra tokens.

Module names line up with the diffusers state-dict names so conversion
(models/conversion.py convert_i2vgen_unet) is a mechanical rename over
unet3d_rename plus the flat conditioning-module names.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp

from .layers import TimestepEmbedding, timestep_embedding
from .unet3d import UNet3DConfig, unet3d_backbone


@dataclasses.dataclass(frozen=True)
class I2VGenConfig:
    in_channels: int = 4
    out_channels: int = 4
    block_out_channels: tuple[int, ...] = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    attention: tuple[bool, ...] = (True, True, True, False)
    attention_head_dim: int = 64
    cross_attention_dim: int = 1024
    norm_num_groups: int = 32

    def trunk(self) -> UNet3DConfig:
        return UNet3DConfig(
            in_channels=2 * self.in_channels,
            out_channels=self.out_channels,
            block_out_channels=self.block_out_channels,
            layers_per_block=self.layers_per_block,
            attention=self.attention,
            attention_head_dim=self.attention_head_dim,
            cross_attention_dim=self.cross_attention_dim,
            norm_num_groups=self.norm_num_groups,
        )


TINY_I2VGEN = I2VGenConfig(
    block_out_channels=(32, 64),
    layers_per_block=1,
    attention=(True, False),
    attention_head_dim=8,
    cross_attention_dim=16,
    norm_num_groups=8,
)


def adaptive_avg_pool(x, out_hw: int):
    """torch AdaptiveAvgPool2d semantics on NHWC (per-cell slice means
    with floor/ceil bin edges); shapes are static so the python loop
    traces away."""
    import math

    b, h, w, c = x.shape
    rows = jnp.stack(
        [
            jnp.mean(
                x[:, math.floor(i * h / out_hw): math.ceil((i + 1) * h / out_hw)],
                axis=1,
            )
            for i in range(out_hw)
        ],
        axis=1,
    )
    return jnp.stack(
        [
            jnp.mean(
                rows[:, :, math.floor(j * w / out_hw): math.ceil((j + 1) * w / out_hw)],
                axis=2,
            )
            for j in range(out_hw)
        ],
        axis=2,
    )


class _TemporalEncoder(nn.Module):
    """I2VGenXLTransformerTemporalEncoder: pre-LN self-attention + gelu
    feed-forward over the frame axis at each pixel (dim = latent width)."""

    dim: int
    heads: int = 2
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, tokens):
        b, f, d = tokens.shape
        head_dim = max(1, self.dim // self.heads)
        h = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="norm1")(tokens)
        q = nn.Dense(self.dim, use_bias=False, dtype=self.dtype,
                     name="attn1_to_q")(h)
        k = nn.Dense(self.dim, use_bias=False, dtype=self.dtype,
                     name="attn1_to_k")(h)
        v = nn.Dense(self.dim, use_bias=False, dtype=self.dtype,
                     name="attn1_to_v")(h)
        q = q.reshape(b, f, self.heads, head_dim)
        k = k.reshape(b, f, self.heads, head_dim)
        v = v.reshape(b, f, self.heads, head_dim)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        weights = nn.softmax(logits * (head_dim ** -0.5), axis=-1)
        attn = jnp.einsum(
            "bhqk,bkhd->bqhd", weights.astype(self.dtype), v
        ).reshape(b, f, self.dim)
        attn = nn.Dense(self.dim, dtype=self.dtype, name="attn1_to_out_0")(
            attn
        )
        tokens = tokens + attn
        ff = nn.Dense(4 * self.dim, dtype=self.dtype,
                      name="ff_net_0_proj")(tokens)
        ff = nn.gelu(ff, approximate=False)
        ff = nn.Dense(self.dim, dtype=self.dtype, name="ff_net_2")(ff)
        return tokens + ff


class I2VGenXLUNet(nn.Module):
    """sample [B*F, H, W, 4] + timesteps [B] + fps [B] +
    image_latents [B*F, H, W, 4] (frame 0 real, frames 1.. the pipeline's
    position-ramp maps) + image_embeddings [B, cross] +
    encoder_hidden_states [B, S, cross] -> [B*F, H, W, 4]."""

    config: I2VGenConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, sample, timesteps, fps, image_latents,
                 image_embeddings, encoder_hidden_states, num_frames: int):
        cfg = self.config
        c0 = cfg.block_out_channels[0]
        bf = sample.shape[0]
        b = bf // num_frames

        if jnp.ndim(timesteps) == 0:
            timesteps = jnp.broadcast_to(timesteps, (b,))
        if jnp.ndim(fps) == 0:
            fps = jnp.broadcast_to(fps, (b,))
        temb_dim = c0 * 4
        temb = TimestepEmbedding(
            temb_dim, dtype=self.dtype, name="time_embedding"
        )(timestep_embedding(timesteps, c0, dtype=self.dtype))
        temb = temb + TimestepEmbedding(
            temb_dim, dtype=self.dtype, name="fps_embedding"
        )(timestep_embedding(fps, c0, dtype=self.dtype))
        temb = jnp.repeat(temb, num_frames, axis=0)  # [B*F, temb]

        # context tokens: [text | first-frame latent grid | image embed]
        first = image_latents.reshape(
            b, num_frames, *image_latents.shape[1:]
        )[:, 0]
        y = nn.Conv(
            8 * cfg.in_channels, (3, 3), padding=((1, 1), (1, 1)),
            dtype=self.dtype, name="image_latents_context_embedding_0",
        )(jnp.asarray(first, self.dtype))
        y = adaptive_avg_pool(nn.silu(y), 32)
        y = nn.Conv(
            16 * cfg.in_channels, (3, 3), strides=(2, 2),
            padding=((1, 1), (1, 1)), dtype=self.dtype,
            name="image_latents_context_embedding_3",
        )(y)
        y = nn.Conv(
            cfg.cross_attention_dim, (3, 3), strides=(2, 2),
            padding=((1, 1), (1, 1)), dtype=self.dtype,
            name="image_latents_context_embedding_5",
        )(nn.silu(y))
        latent_tokens = y.reshape(b, -1, cfg.cross_attention_dim)

        img = nn.Dense(temb_dim, dtype=self.dtype,
                       name="context_embedding_0")(
            jnp.asarray(image_embeddings, self.dtype)
        )
        img = nn.Dense(
            cfg.in_channels * cfg.cross_attention_dim, dtype=self.dtype,
            name="context_embedding_2",
        )(nn.silu(img))
        img_tokens = img.reshape(b, cfg.in_channels, cfg.cross_attention_dim)

        ctx = jnp.concatenate(
            [
                jnp.asarray(encoder_hidden_states, self.dtype),
                latent_tokens,
                img_tokens,
            ],
            axis=1,
        )
        ctx = jnp.repeat(ctx, num_frames, axis=0)  # [B*F, S+HW/16+C, D]

        # per-frame image-latents stream -> channel concat with the noise
        il = nn.Conv(
            4 * cfg.in_channels, (1, 1), dtype=self.dtype,
            name="image_latents_proj_in_0",
        )(jnp.asarray(image_latents, self.dtype))
        il = nn.Conv(
            4 * cfg.in_channels, (3, 3), padding=((1, 1), (1, 1)),
            dtype=self.dtype, name="image_latents_proj_in_2",
        )(nn.silu(il))
        il = nn.Conv(
            cfg.in_channels, (3, 3), padding=((1, 1), (1, 1)),
            dtype=self.dtype, name="image_latents_proj_in_4",
        )(nn.silu(il))
        h, w = il.shape[1], il.shape[2]
        tokens = il.reshape(b, num_frames, h * w, cfg.in_channels)
        tokens = tokens.transpose(0, 2, 1, 3).reshape(
            b * h * w, num_frames, cfg.in_channels
        )
        tokens = _TemporalEncoder(
            cfg.in_channels, dtype=self.dtype,
            name="image_latents_temporal_encoder",
        )(tokens)
        il = tokens.reshape(b, h * w, num_frames, cfg.in_channels)
        il = il.transpose(0, 2, 1, 3).reshape(bf, h, w, cfg.in_channels)

        x = jnp.concatenate(
            [jnp.asarray(sample, self.dtype), il], axis=-1
        )
        return unet3d_backbone(
            cfg.trunk(), self.dtype, x, temb, ctx, num_frames
        )
