"""LoRA adapter loading: raw low-rank factors, plus the legacy merge.

The reference loads LoRA per job via diffusers `load_lora_weights` + fuse
(swarm/diffusion/diffusion_func.py:113-126) — a per-job torch graph edit.
On TPU the serving path (ISSUE 13) keeps ONE resident base UNet and
applies each adapter as a RUNTIME per-row delta inside the jitted
program: `W·x + scale·(alpha/r)·B·(A·x)` — see pipelines/lora_runtime.py.
This module owns the host side of that: loading a safetensors adapter
into raw `(A [r,in], B [out,r], alpha)` factors and matching them onto
the 2D Dense kernels of a UNet param tree.

`merge_lora` / `resolve_and_merge` (W += scale * (alpha/r) * B @ A into
a COPY of the base tree) remain as the fallback path for adapters the
runtime delta cannot express (non-Dense modules) and for pipelines that
have no delta path (video motion LoRAs).

Supports both common safetensors layouts:
- diffusers/PEFT: `unet.down_blocks.0...to_q.lora_A.weight` / `lora_B`
- kohya:          `lora_unet_down_blocks_0_..._to_q.lora_down.weight` / `lora_up`
  with optional per-module `.alpha` tensors.
"""

from __future__ import annotations

import logging
from pathlib import Path

import jax.numpy as jnp
import numpy as np

from ..telemetry import counter as _telemetry_counter

logger = logging.getLogger(__name__)

# adapter modules the Dense-only delta/merge paths cannot express
# (conv/LoCon layers, mismatched bases) — each skipped module counts
# here, while the log WARNING dedups to once per adapter ref so a
# 40-conv LoCon adapter in a hot gang doesn't firehose the worker log
CONV_SKIPPED = _telemetry_counter(
    "swarm_lora_conv_skipped_total",
    "Adapter modules skipped by the Dense-only LoRA paths "
    "(conv/LoCon layers or kernels the base tree cannot match)")

_WARNED_REFS: set[str] = set()
_WARNED_REFS_MAX = 4096  # dedup memory, not a cache: drop-all when full


def _warn_skipped(adapter_ref: str | None, message: str, *args) -> None:
    """Count every skipped module; WARN once per adapter ref (every
    time when the caller has no ref — the raw merge_lora entrypoint)."""
    CONV_SKIPPED.inc()
    if adapter_ref is not None:
        if adapter_ref in _WARNED_REFS:
            logger.debug(message, *args)
            return
        if len(_WARNED_REFS) >= _WARNED_REFS_MAX:
            _WARNED_REFS.clear()
        _WARNED_REFS.add(adapter_ref)
    logger.warning(message, *args)


def load_lora_state(path: str | Path, weight_name: str | None = None,
                    subfolder: str | None = None) -> dict:
    """Flat {name: np.ndarray} from a LoRA safetensors file."""
    from safetensors import safe_open

    root = Path(path).expanduser()
    if subfolder:
        root = root / subfolder
    if root.is_dir():
        candidates = (
            [root / weight_name]
            if weight_name
            else sorted(root.glob("*.safetensors"))
        )
        if not candidates or not candidates[0].is_file():
            raise FileNotFoundError(f"no LoRA weights under {root}")
        root = candidates[0]
    state = {}
    with safe_open(str(root), framework="np") as sf:
        for key in sf.keys():
            state[key] = sf.get_tensor(key)
    return state


def _module_path(name: str) -> tuple[str, str] | None:
    """LoRA tensor name -> ('/'-joined flax module path, 'A'|'B'|'alpha').

    Text-encoder tensors (kohya ``lora_te_``/``lora_te1_``/``lora_te2_``,
    diffusers ``text_encoder.``/``text_encoder_2.``) map into a
    ``te{i}:``-namespaced key (encoder index 0/1 in the pipeline's
    text-encoder LIST). ':' never appears in a flax module name, so the
    UNet matcher/interceptor can never cross-match a TE key — one flat
    factor dict carries both."""
    if name.endswith(".alpha"):
        base, kind = name[: -len(".alpha")], "alpha"
    elif name.endswith(".lora_A.weight") or name.endswith(".lora_down.weight"):
        base, kind = name.rsplit(".lora_", 1)[0], "A"
    elif name.endswith(".lora_B.weight") or name.endswith(".lora_up.weight"):
        base, kind = name.rsplit(".lora_", 1)[0], "B"
    else:
        return None

    # kohya: lora_unet_down_blocks_0_attentions_0_... (all underscores)
    if base.startswith("lora_unet_"):
        base = base[len("lora_unet_"):]
        return base, kind
    te_ns = None
    for prefix, ns in (("lora_te1_", "te0:"), ("lora_te2_", "te1:"),
                       ("lora_te_", "te0:")):
        if base.startswith(prefix):
            te_ns, base = ns, base[len(prefix):]
            break
    if te_ns is None:
        # diffusers: unet.down_blocks.0.attentions.0....processor?.to_q(_lora)?
        if base.startswith("unet."):
            base = base[len("unet."):]
        elif base.startswith("text_encoder_2."):
            te_ns, base = "te1:", base[len("text_encoder_2."):]
        elif base.startswith("text_encoder."):
            te_ns, base = "te0:", base[len("text_encoder."):]
        elif base.startswith("text_encoder"):
            return None
    base = (
        base.replace(".processor.", ".")
        .replace("_lora", "")
        .replace("to_out.0", "to_out_0")
    )
    base = base.replace(".", "_")
    if te_ns is not None:
        # the flax CLIP tree is rooted at the encoder module (clip.py):
        # HF's text_model.encoder. / text_model. wrapper levels vanish,
        # and fc1/fc2 sit directly in the layer (no `mlp` submodule)
        for strip in ("text_model_encoder_", "text_model_"):
            if base.startswith(strip):
                base = base[len(strip):]
                break
        base = base.replace("_mlp_fc", "_fc")
        return te_ns + base, kind
    return base, kind


def collect_lora_deltas(state: dict) -> dict[str, tuple]:
    """Group tensors -> {module_key: (A [r,in], B [out,r], alpha|None)}."""
    mods: dict[str, dict] = {}
    for name, tensor in state.items():
        parsed = _module_path(name)
        if parsed is None:
            continue
        base, kind = parsed
        mods.setdefault(base, {})[kind] = tensor
    out = {}
    for base, parts in mods.items():
        if "A" in parts and "B" in parts:
            out[base] = (
                parts["A"],
                parts["B"],
                float(parts["alpha"]) if "alpha" in parts else None,
            )
    return out


def _flat_params(tree, prefix=()):
    for k, v in tree.items():
        path = prefix + (k,)
        if isinstance(v, dict):
            yield from _flat_params(v, path)
        else:
            yield path, v


def factors_nbytes(factors: dict[str, tuple]) -> int:
    """Host bytes of one adapter's raw factors (the byte-capped factor
    cache's accounting unit — lora_cache.py)."""
    total = 0
    for a, b, _alpha in factors.values():
        total += int(np.asarray(a).nbytes) + int(np.asarray(b).nbytes)
    return total


def load_factors(lora: dict, model_name: str) -> dict[str, tuple]:
    """Load an adapter by job reference into raw factors
    {module_key: (A [r,in], B [out,r], alpha|None)}.

    Same candidate roots and failure contract as the merge path: the
    literal path, then `model_root_dir`/<ref>; load failures and
    zero-module adapters raise ValueError -> fatal job error (the
    reference's "incompatible lora" contract). The factors are
    scale-independent — one cache entry serves every lora_scale.
    """
    from ..settings import load_settings

    candidates = [Path(str(lora.get("lora"))).expanduser()]
    candidates.append(
        Path(load_settings().model_root_dir).expanduser() / str(lora.get("lora"))
    )
    state = None
    errors = []
    for root in candidates:
        try:
            state = load_lora_state(
                root, lora.get("weight_name"), lora.get("subfolder")
            )
            break
        except (FileNotFoundError, OSError) as e:
            errors.append(str(e))
    if state is None:
        raise ValueError(
            f"Could not load lora {lora}. It might be incompatible with "
            f"{model_name}: {'; '.join(errors)}"
        )
    factors = collect_lora_deltas(state)
    if not factors:
        raise ValueError(
            f"Could not load lora {lora}: no LoRA modules found in its "
            f"safetensors (incompatible with {model_name})"
        )
    return factors


def match_dense_factors(factors: dict[str, tuple], unet_params: dict
                        ) -> tuple[dict[str, tuple], int]:
    """Match raw factors onto a UNet tree's 2D Dense kernels.

    Returns ({'/'-joined module path: (A, B, alpha)}, unmatched_dense) —
    the operand layout pipelines/lora_runtime.py stacks per batch slot.
    `unmatched_dense` counts modules that matched a kernel by NAME but
    not by SHAPE, or no kernel at all: >0 means the adapter has content
    the runtime delta cannot express (conv/LoCon modules, a mismatched
    base), so the caller must fall back to the merged-tree path rather
    than silently drop part of the adapter. ``te{i}:``-namespaced keys
    are text-encoder content — not this tree's to match; they neither
    match nor count (match_te_dense_factors owns them).
    """
    index = _kernel_index(unet_params)
    matched: dict[str, tuple] = {}
    unmatched = 0
    for key, (a, b, alpha) in factors.items():
        if ":" in key:
            continue  # text-encoder namespace
        entry = _match_one(index, key, a, b, alpha)
        if entry is None:
            unmatched += 1
            continue
        matched[entry[0]] = entry[1]
    return matched, unmatched


def _kernel_index(params: dict) -> dict:
    index = {}
    for path, leaf in _flat_params(params):
        if path[-1] != "kernel":
            continue
        index["_".join(path[:-1])] = (path[:-1], getattr(leaf, "shape", None),
                                      getattr(leaf, "ndim", 0))
    return index


def _match_one(index: dict, key: str, a, b, alpha):
    """One factor against one kernel index: ('/'-path, (A, B, alpha))
    on a 2D shape-exact match, None otherwise."""
    hit = index.get(key)
    if hit is None:
        return None
    path, shape, ndim = hit
    a_arr, b_arr = np.asarray(a), np.asarray(b)
    # delta = (B @ A).T must land on a 2D [in, out] kernel
    if (ndim != 2 or a_arr.ndim != 2 or b_arr.ndim != 2
            or shape != (a_arr.shape[1], b_arr.shape[0])
            or a_arr.shape[0] != b_arr.shape[1]):
        return None
    return "/".join(path), (a_arr, b_arr,
                            float(alpha) if alpha is not None else None)


def match_te_dense_factors(factors: dict[str, tuple],
                           text_params_list: list[dict]
                           ) -> tuple[dict[str, tuple], int]:
    """Match ``te{i}:``-namespaced factors onto the pipeline's text
    encoder param trees (one per encoder, pipeline order).

    Returns ({'te{i}:' + '/'-joined path: (A, B, alpha)}, unmatched) —
    the same operand layout as match_dense_factors, keys kept under
    their namespace so the stacks ride ONE operand dict and the TE
    interceptor (make_te_interceptor) finds them by prefixed path.
    UNet keys (no ':') are ignored here. `unmatched` > 0 means TE
    content the delta cannot express — the caller falls back to the
    merged-tree path, exactly like the UNet side.
    """
    indexes = [_kernel_index(params) for params in text_params_list]
    matched: dict[str, tuple] = {}
    unmatched = 0
    for key, (a, b, alpha) in factors.items():
        ns, sep, rest = key.partition(":")
        if not sep:
            continue  # unet namespace
        enc = int(ns[2:]) if ns.startswith("te") and ns[2:].isdigit() else -1
        if not 0 <= enc < len(indexes):
            unmatched += 1
            continue
        entry = _match_one(indexes[enc], rest, a, b, alpha)
        if entry is None:
            unmatched += 1
            continue
        matched[f"{ns}:{entry[0]}"] = entry[1]
    return matched, unmatched


def merge_factors(params: dict, factors: dict[str, tuple],
                  scale: float = 1.0,
                  adapter_ref: str | None = None) -> tuple[dict, int]:
    """merge_lora over pre-collected factors (the factor-cache fallback
    path: the adapter was already loaded once; re-reading safetensors to
    merge would defeat the cache)."""
    return _merge_deltas(params, factors, scale, adapter_ref)


def merge_te_factors(text_params_list: list[dict], factors: dict[str, tuple],
                     scale: float = 1.0,
                     adapter_ref: str | None = None) -> tuple[list, int]:
    """Merge ``te{i}:``-namespaced factors into COPIES of the matching
    text-encoder trees (untouched encoders pass through by identity, so
    the prompt-embedding cache's identity check correctly bypasses).
    Returns (new text-params list, matched module count)."""
    merged_list = list(text_params_list)
    matched = 0
    for i, params in enumerate(text_params_list):
        prefix = f"te{i}:"
        sub = {key[len(prefix):]: val for key, val in factors.items()
               if key.startswith(prefix)}
        if not sub:
            continue
        merged, n = _merge_deltas(params, sub, scale, adapter_ref)
        if n:
            merged_list[i] = merged
        matched += n
    return merged_list, matched


def merge_lora(params: dict, lora_state: dict, scale: float = 1.0) -> tuple[dict, int]:
    """Return (new param tree with LoRA deltas merged, matched module count).

    `params` is a UNet param tree whose linear kernels are [in, out]; LoRA
    A/B are torch-layout [r, in] / [out, r], so delta_kernel = (B @ A).T.
    Unmatched LoRA modules are logged and skipped (reference behavior: LoRA
    incompatibility is a job error, not a crash — handled by caller).
    """
    deltas = collect_lora_deltas(lora_state)
    if not deltas:
        return params, 0
    return _merge_deltas(params, deltas, scale)


def _merge_deltas(params: dict, deltas: dict[str, tuple], scale: float,
                  adapter_ref: str | None = None) -> tuple[dict, int]:
    # index the param tree by normalized underscore path of the kernel's parent
    index = {}
    for path, leaf in _flat_params(params):
        if path[-1] != "kernel":
            continue
        index["_".join(path[:-1])] = path

    new_params = {k: v for k, v in params.items()}  # shallow copy, CoW below

    def set_leaf(path, value):
        node = new_params
        for p in path[:-1]:
            child = node[p]
            child = dict(child)
            node[p] = child
            node = child
        node[path[-1]] = value

    matched = 0
    for key, (a, b, alpha) in deltas.items():
        if ":" in key:
            continue  # text-encoder namespace: merge_te_factors owns it
        path = index.get(key)
        if path is None:
            _warn_skipped(adapter_ref,
                          "LoRA module %s not found in param tree", key)
            continue
        node = params
        for p in path:
            node = node[p]
        kernel = node
        rank = a.shape[0]
        eff = scale * ((alpha / rank) if alpha is not None else 1.0)
        delta = (np.asarray(b, np.float32) @ np.asarray(a, np.float32)).T
        if delta.shape != kernel.shape:
            _warn_skipped(
                adapter_ref,
                "LoRA %s shape %s incompatible with kernel %s",
                key, delta.shape, kernel.shape,
            )
            continue
        set_leaf(path, kernel + jnp.asarray(eff * delta, kernel.dtype))
        matched += 1
    return new_params, matched


def resolve_and_merge(base_unet_params: dict, lora: dict, scale: float,
                      model_name: str) -> dict:
    """Load a LoRA by job reference and merge it into a UNet param tree.

    One shared path for every pipeline family (SD and video motion-LoRAs):
    candidate roots are the literal path then `model_root_dir`/<ref>; load
    failures and zero-module matches raise ValueError -> fatal job error
    (the reference's "incompatible lora" contract,
    swarm/diffusion/diffusion_func.py:113-126). Returns the merged UNet
    tree (host-side); the caller places/casts and caches it.
    """
    factors = load_factors(lora, model_name)
    merged, matched = _merge_deltas(base_unet_params, factors, scale,
                                    str(lora.get("lora")))
    if matched == 0:
        raise ValueError(
            f"Could not load lora {lora}: no modules matched "
            f"{model_name}'s parameter tree"
        )
    logging.getLogger(__name__).info(
        "merged LoRA %s into %s (%d modules, scale %.2f)",
        lora.get("lora"), model_name, matched, scale,
    )
    return merged
