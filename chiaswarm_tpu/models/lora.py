"""LoRA adapters merged into Flax param trees at load time.

The reference loads LoRA per job via diffusers `load_lora_weights` + fuse
(swarm/diffusion/diffusion_func.py:113-126) — a per-job torch graph edit.
On TPU the jitted program's weights are just a pytree, so a LoRA is merged
arithmetically (W += scale * (alpha/r) * B @ A) into a COPY of the base
tree, and the merged tree is cached by (model, lora, scale) at the pipeline
layer — zero per-step cost, no graph surgery.

Supports both common safetensors layouts:
- diffusers/PEFT: `unet.down_blocks.0...to_q.lora_A.weight` / `lora_B`
- kohya:          `lora_unet_down_blocks_0_..._to_q.lora_down.weight` / `lora_up`
  with optional per-module `.alpha` tensors.
"""

from __future__ import annotations

import logging
from pathlib import Path

import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)


def load_lora_state(path: str | Path, weight_name: str | None = None,
                    subfolder: str | None = None) -> dict:
    """Flat {name: np.ndarray} from a LoRA safetensors file."""
    from safetensors import safe_open

    root = Path(path).expanduser()
    if subfolder:
        root = root / subfolder
    if root.is_dir():
        candidates = (
            [root / weight_name]
            if weight_name
            else sorted(root.glob("*.safetensors"))
        )
        if not candidates or not candidates[0].is_file():
            raise FileNotFoundError(f"no LoRA weights under {root}")
        root = candidates[0]
    state = {}
    with safe_open(str(root), framework="np") as sf:
        for key in sf.keys():
            state[key] = sf.get_tensor(key)
    return state


def _module_path(name: str) -> tuple[str, str] | None:
    """LoRA tensor name -> ('/'-joined flax module path, 'A'|'B'|'alpha')."""
    if name.endswith(".alpha"):
        base, kind = name[: -len(".alpha")], "alpha"
    elif name.endswith(".lora_A.weight") or name.endswith(".lora_down.weight"):
        base, kind = name.rsplit(".lora_", 1)[0], "A"
    elif name.endswith(".lora_B.weight") or name.endswith(".lora_up.weight"):
        base, kind = name.rsplit(".lora_", 1)[0], "B"
    else:
        return None

    # kohya: lora_unet_down_blocks_0_attentions_0_... (all underscores)
    if base.startswith("lora_unet_"):
        base = base[len("lora_unet_"):]
        return base, kind
    if base.startswith("lora_te_") or base.startswith("lora_te1_") or base.startswith(
        "lora_te2_"
    ):
        return None  # text-encoder LoRA: not merged yet
    # diffusers: unet.down_blocks.0.attentions.0....processor?.to_q(_lora)?
    if base.startswith("unet."):
        base = base[len("unet."):]
    elif base.startswith("text_encoder"):
        return None
    base = (
        base.replace(".processor.", ".")
        .replace("_lora", "")
        .replace("to_out.0", "to_out_0")
    )
    return base.replace(".", "_"), kind


def collect_lora_deltas(state: dict) -> dict[str, tuple]:
    """Group tensors -> {module_key: (A [r,in], B [out,r], alpha|None)}."""
    mods: dict[str, dict] = {}
    for name, tensor in state.items():
        parsed = _module_path(name)
        if parsed is None:
            continue
        base, kind = parsed
        mods.setdefault(base, {})[kind] = tensor
    out = {}
    for base, parts in mods.items():
        if "A" in parts and "B" in parts:
            out[base] = (
                parts["A"],
                parts["B"],
                float(parts["alpha"]) if "alpha" in parts else None,
            )
    return out


def _flat_params(tree, prefix=()):
    for k, v in tree.items():
        path = prefix + (k,)
        if isinstance(v, dict):
            yield from _flat_params(v, path)
        else:
            yield path, v


def merge_lora(params: dict, lora_state: dict, scale: float = 1.0) -> tuple[dict, int]:
    """Return (new param tree with LoRA deltas merged, matched module count).

    `params` is a UNet param tree whose linear kernels are [in, out]; LoRA
    A/B are torch-layout [r, in] / [out, r], so delta_kernel = (B @ A).T.
    Unmatched LoRA modules are logged and skipped (reference behavior: LoRA
    incompatibility is a job error, not a crash — handled by caller).
    """
    deltas = collect_lora_deltas(lora_state)
    if not deltas:
        return params, 0

    # index the param tree by normalized underscore path of the kernel's parent
    index = {}
    for path, leaf in _flat_params(params):
        if path[-1] != "kernel":
            continue
        index["_".join(path[:-1])] = path

    new_params = {k: v for k, v in params.items()}  # shallow copy, CoW below

    def set_leaf(path, value):
        node = new_params
        for p in path[:-1]:
            child = node[p]
            child = dict(child)
            node[p] = child
            node = child
        node[path[-1]] = value

    matched = 0
    for key, (a, b, alpha) in deltas.items():
        path = index.get(key)
        if path is None:
            logger.warning("LoRA module %s not found in param tree", key)
            continue
        node = params
        for p in path:
            node = node[p]
        kernel = node
        rank = a.shape[0]
        eff = scale * ((alpha / rank) if alpha is not None else 1.0)
        delta = (np.asarray(b, np.float32) @ np.asarray(a, np.float32)).T
        if delta.shape != kernel.shape:
            logger.warning(
                "LoRA %s shape %s incompatible with kernel %s",
                key, delta.shape, kernel.shape,
            )
            continue
        set_leaf(path, kernel + jnp.asarray(eff * delta, kernel.dtype))
        matched += 1
    return new_params, matched


def resolve_and_merge(base_unet_params: dict, lora: dict, scale: float,
                      model_name: str) -> dict:
    """Load a LoRA by job reference and merge it into a UNet param tree.

    One shared path for every pipeline family (SD and video motion-LoRAs):
    candidate roots are the literal path then `model_root_dir`/<ref>; load
    failures and zero-module matches raise ValueError -> fatal job error
    (the reference's "incompatible lora" contract,
    swarm/diffusion/diffusion_func.py:113-126). Returns the merged UNet
    tree (host-side); the caller places/casts and caches it.
    """
    from ..settings import load_settings

    candidates = [Path(str(lora.get("lora"))).expanduser()]
    candidates.append(
        Path(load_settings().model_root_dir).expanduser() / str(lora.get("lora"))
    )
    state = None
    errors = []
    for root in candidates:
        try:
            state = load_lora_state(
                root, lora.get("weight_name"), lora.get("subfolder")
            )
            break
        except (FileNotFoundError, OSError) as e:
            errors.append(str(e))
    if state is None:
        raise ValueError(
            f"Could not load lora {lora}. It might be incompatible with "
            f"{model_name}: {'; '.join(errors)}"
        )
    merged, matched = merge_lora(base_unet_params, state, scale)
    if matched == 0:
        raise ValueError(
            f"Could not load lora {lora}: no modules matched "
            f"{model_name}'s parameter tree"
        )
    logging.getLogger(__name__).info(
        "merged LoRA %s into %s (%d modules, scale %.2f)",
        lora.get("lora"), model_name, matched, scale,
    )
    return merged
