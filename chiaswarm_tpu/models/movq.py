"""MoVQ image codec: Kandinsky 2.x's pixel stage (diffusers `VQModel` with
`norm_type="spatial"`), replacing the AutoencoderKL stand-in the round-2
Kandinsky pipeline decoded through.

Reference behavior replaced: KandinskyV22Pipeline's `movq.decode(latents,
force_not_quantize=True)` and Img2Img's `movq.encode(image).latents`
(swarm/diffusion/pipeline_steps.py:7-38 loads them per job). Serving never
runs the vector quantizer: diffusion latents are continuous, so decode maps
latents -> post_quant_conv -> spatially-normalized decoder where every norm
is conditioned on the latents themselves (SpatialNorm: group-norm modulated
by 1x1 convs of the nearest-resized latent map).

Module names line up with the merged diffusers state-dict names
(models/conversion.py movq_rename flattens block interiors); the codebook
(`quantize.embedding`) is intentionally not part of the module — it is dead
weight for the continuous-latent serving path.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp

from .layers import FusedGroupNorm


@dataclasses.dataclass(frozen=True)
class MoVQConfig:
    in_channels: int = 3
    out_channels: int = 3
    latent_channels: int = 4
    vq_embed_dim: int = 4
    block_out_channels: tuple[int, ...] = (128, 256, 256, 512)
    layers_per_block: int = 2
    norm_num_groups: int = 32
    # K2.2's movq has no latent scaling (scaling_factor 1.0)
    scaling_factor: float = 1.0


TINY_MOVQ = MoVQConfig(
    block_out_channels=(16, 32), layers_per_block=1, norm_num_groups=8
)


def movq_config_from_json(cj: dict | None) -> MoVQConfig:
    """Geometry from a diffusers VQModel config.json (Kandinsky 3's movq
    differs from 2.2's only in fields this reads)."""
    cj = cj or {}
    base = MoVQConfig()
    return MoVQConfig(
        in_channels=int(cj.get("in_channels", base.in_channels)),
        out_channels=int(cj.get("out_channels", base.out_channels)),
        latent_channels=int(cj.get("latent_channels", base.latent_channels)),
        vq_embed_dim=int(cj.get("vq_embed_dim", base.vq_embed_dim)),
        block_out_channels=tuple(
            int(c) for c in cj.get("block_out_channels",
                                   base.block_out_channels)
        ),
        layers_per_block=int(
            cj.get("layers_per_block", base.layers_per_block)
        ),
        norm_num_groups=int(
            cj.get("norm_num_groups", base.norm_num_groups)
        ),
        scaling_factor=float(cj.get("scaling_factor", base.scaling_factor)),
    )


class SpatialNorm(nn.Module):
    """GroupNorm whose scale/shift are 1x1 convs of the (nearest-resized)
    latent map — the 'Mo' in MoVQ (modulated quantized vectors)."""

    channels: int
    groups: int = 32
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, f, zq):
        b, h, w, _ = f.shape
        zq = jax.image.resize(
            zq.astype(self.dtype), (b, h, w, zq.shape[-1]), "nearest"
        )
        norm = FusedGroupNorm(self.groups, epsilon=1e-6, dtype=self.dtype,
                              name="norm_layer")(f)
        y = nn.Conv(self.channels, (1, 1), dtype=self.dtype, name="conv_y")(zq)
        bb = nn.Conv(self.channels, (1, 1), dtype=self.dtype, name="conv_b")(zq)
        return norm * y + bb


class VQResnet(nn.Module):
    """VQ resnet (eps 1e-6, no temb); `spatial=True` swaps both norms for
    SpatialNorm conditioned on the latent map (decoder side)."""

    out_channels: int
    groups: int = 32
    spatial: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, zq=None):
        def norm(name, h):
            if self.spatial:
                return SpatialNorm(h.shape[-1], groups=self.groups,
                                   dtype=self.dtype, name=name)(h, zq)
            return FusedGroupNorm(self.groups, epsilon=1e-6, dtype=self.dtype,
                                  name=name)(h)

        h = nn.silu(norm("norm1", x))
        h = nn.Conv(self.out_channels, (3, 3), padding=((1, 1), (1, 1)),
                    dtype=self.dtype, name="conv1")(h)
        h = nn.silu(norm("norm2", h))
        h = nn.Conv(self.out_channels, (3, 3), padding=((1, 1), (1, 1)),
                    dtype=self.dtype, name="conv2")(h)
        if x.shape[-1] != self.out_channels:
            x = nn.Conv(self.out_channels, (1, 1), dtype=self.dtype,
                        name="conv_shortcut")(x)
        return x + h


class VQAttention(nn.Module):
    """Single-head VQ-GAN mid attention; spatial norm on the decoder side."""

    channels: int
    groups: int = 32
    spatial: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, zq=None):
        b, h, w, c = x.shape
        if self.spatial:
            norm = SpatialNorm(c, groups=self.groups, dtype=self.dtype,
                               name="spatial_norm")(x, zq)
        else:
            norm = FusedGroupNorm(self.groups, epsilon=1e-6, dtype=self.dtype,
                                  name="group_norm")(x)
        tokens = norm.reshape(b, h * w, c)
        q = nn.Dense(c, dtype=self.dtype, name="to_q")(tokens)
        k = nn.Dense(c, dtype=self.dtype, name="to_k")(tokens)
        v = nn.Dense(c, dtype=self.dtype, name="to_v")(tokens)
        from ..ops import dot_product_attention

        out = dot_product_attention(
            q[:, :, None, :], k[:, :, None, :], v[:, :, None, :]
        )[:, :, 0, :]
        out = nn.Dense(c, dtype=self.dtype, name="to_out_0")(out)
        return x + out.reshape(b, h, w, c)


class MoVQEncoder(nn.Module):
    config: MoVQConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, pixels):
        cfg = self.config
        g = cfg.norm_num_groups
        x = nn.Conv(cfg.block_out_channels[0], (3, 3),
                    padding=((1, 1), (1, 1)), dtype=self.dtype,
                    name="conv_in")(pixels)
        for b, out_ch in enumerate(cfg.block_out_channels):
            for i in range(cfg.layers_per_block):
                x = VQResnet(out_ch, groups=g, dtype=self.dtype,
                             name=f"down_blocks_{b}_resnets_{i}")(x)
            if b != len(cfg.block_out_channels) - 1:
                # Downsample2D(use_conv=True): asymmetric (0,1) pad, stride 2
                x = jnp.pad(x, ((0, 0), (0, 1), (0, 1), (0, 0)))
                x = nn.Conv(
                    out_ch, (3, 3), strides=(2, 2), padding="VALID",
                    dtype=self.dtype,
                    name=f"down_blocks_{b}_downsamplers_0_conv",
                )(x)
        ch = cfg.block_out_channels[-1]
        x = VQResnet(ch, groups=g, dtype=self.dtype,
                     name="mid_block_resnets_0")(x)
        x = VQAttention(ch, groups=g, dtype=self.dtype,
                        name="mid_block_attentions_0")(x)
        x = VQResnet(ch, groups=g, dtype=self.dtype,
                     name="mid_block_resnets_1")(x)
        x = FusedGroupNorm(g, epsilon=1e-6, dtype=self.dtype, act="silu",
                           name="conv_norm_out")(x)
        return nn.Conv(cfg.latent_channels, (3, 3), padding=((1, 1), (1, 1)),
                       dtype=self.dtype, name="conv_out")(x)


class MoVQDecoder(nn.Module):
    config: MoVQConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, zq):
        """x: post_quant_conv latents; zq: PRE-post_quant_conv latents (the
        spatial-norm conditioning, diffusers VQModel.decode)."""
        cfg = self.config
        g = cfg.norm_num_groups
        rev = tuple(reversed(cfg.block_out_channels))
        ch = rev[0]
        x = nn.Conv(ch, (3, 3), padding=((1, 1), (1, 1)), dtype=self.dtype,
                    name="conv_in")(x)
        x = VQResnet(ch, groups=g, spatial=True, dtype=self.dtype,
                     name="mid_block_resnets_0")(x, zq)
        x = VQAttention(ch, groups=g, spatial=True, dtype=self.dtype,
                        name="mid_block_attentions_0")(x, zq)
        x = VQResnet(ch, groups=g, spatial=True, dtype=self.dtype,
                     name="mid_block_resnets_1")(x, zq)
        for b, out_ch in enumerate(rev):
            for i in range(cfg.layers_per_block + 1):
                x = VQResnet(out_ch, groups=g, spatial=True, dtype=self.dtype,
                             name=f"up_blocks_{b}_resnets_{i}")(x, zq)
            if b != len(rev) - 1:
                x = jnp.repeat(jnp.repeat(x, 2, axis=1), 2, axis=2)
                x = nn.Conv(
                    out_ch, (3, 3), padding=((1, 1), (1, 1)),
                    dtype=self.dtype,
                    name=f"up_blocks_{b}_upsamplers_0_conv",
                )(x)
        x = SpatialNorm(rev[-1], groups=g, dtype=self.dtype,
                        name="conv_norm_out")(x, zq)
        x = nn.silu(x)
        return nn.Conv(cfg.out_channels, (3, 3), padding=((1, 1), (1, 1)),
                       dtype=self.dtype, name="conv_out")(x)


class MoVQ(nn.Module):
    """Encoder + decoder + the two 1x1 quant convs; `encode`/`decode` are
    the serving entry points (`__call__` exists so `init` touches every
    param once)."""

    config: MoVQConfig
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        self.encoder = MoVQEncoder(self.config, dtype=self.dtype)
        self.decoder = MoVQDecoder(self.config, dtype=self.dtype)
        self.quant_conv = nn.Conv(self.config.vq_embed_dim, (1, 1),
                                  dtype=self.dtype)
        self.post_quant_conv = nn.Conv(self.config.latent_channels, (1, 1),
                                       dtype=self.dtype)

    def __call__(self, pixels):
        return self.decode(self.encode(pixels))

    def encode(self, pixels):
        """[B, H, W, 3] in [-1, 1] -> continuous latents (VQ encoders are
        deterministic — no sampling, and serving skips quantization)."""
        return self.quant_conv(self.encoder(pixels))

    def decode(self, latents):
        return self.decoder(self.post_quant_conv(latents), latents)
