"""BLIP-style captioner: ViT image encoder + cross-attending text decoder.

Reference swarm/captioning/caption_image.py:12-40 loads transformers BLIP
classes named in the job JSON. TPU rebuild: one flax module pair, greedy
decode as a fixed-length `lax.scan` (static shapes — no dynamic stopping
inside jit; EOS handling happens on host after the scan).
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class BlipConfig:
    image_size: int = 384  # Salesforce/blip-image-captioning-* native size
    patch_size: int = 16
    vision_hidden: int = 768
    vision_layers: int = 12
    vision_heads: int = 12
    vocab_size: int = 30524  # bert-base vocab + [DEC]/[ENC] (BLIP's text side)
    text_hidden: int = 768
    text_layers: int = 12
    text_heads: int = 12
    max_positions: int = 512  # BERT absolute position table
    max_caption_len: int = 24
    bos_token_id: int = 30522  # [DEC]
    eos_token_id: int = 102  # bert [SEP]
    pad_token_id: int = 0


TINY_BLIP = BlipConfig(
    image_size=64, patch_size=16, vision_hidden=32, vision_layers=2,
    vision_heads=4, vocab_size=1000, text_hidden=32, text_layers=2,
    text_heads=4, max_positions=64, max_caption_len=8, bos_token_id=998,
    eos_token_id=999,
)


class _MHA(nn.Module):
    heads: int
    dim: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, q_in, kv_in, mask=None):
        head_dim = self.dim // self.heads
        b, sq, _ = q_in.shape
        sk = kv_in.shape[1]
        proj = lambda x, s, name: nn.Dense(self.dim, dtype=self.dtype, name=name)(
            x
        ).reshape(b, s, self.heads, head_dim)
        q, k, v = proj(q_in, sq, "q"), proj(kv_in, sk, "k"), proj(kv_in, sk, "v")
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * head_dim**-0.5
        if mask is not None:
            logits = logits + mask
        weights = nn.softmax(logits.astype(jnp.float32), axis=-1).astype(self.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", weights, v).reshape(b, sq, self.dim)
        return nn.Dense(self.dim, dtype=self.dtype, name="out")(out)


class VisionEncoder(nn.Module):
    """BLIP ViT (pre-LN). Module names line up with the HF checkpoint graph
    (vision_model.*) so convert_blip is a mechanical rename + qkv split."""

    config: BlipConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, pixels):
        """[B, H, W, 3] normalized -> [B, patches+1, D]."""
        cfg = self.config
        x = nn.Conv(
            cfg.vision_hidden, (cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size), dtype=self.dtype,
            name="patch_embed",
        )(pixels)
        b, h, w, c = x.shape
        x = x.reshape(b, h * w, c)
        cls = self.param(
            "cls_token", nn.initializers.normal(0.02), (1, 1, cfg.vision_hidden)
        ).astype(self.dtype)
        x = jnp.concatenate([jnp.broadcast_to(cls, (b, 1, c)), x], axis=1)
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02),
            (1, x.shape[1], cfg.vision_hidden),
        ).astype(self.dtype)
        x = x + pos
        eps = 1e-5  # HF BlipVisionConfig.layer_norm_eps
        for i in range(cfg.vision_layers):
            y = nn.LayerNorm(epsilon=eps, dtype=self.dtype, name=f"ln1_{i}")(x)
            x = x + _MHA(cfg.vision_heads, cfg.vision_hidden, dtype=self.dtype,
                         name=f"attn_{i}")(y, y)
            y = nn.LayerNorm(epsilon=eps, dtype=self.dtype, name=f"ln2_{i}")(x)
            y = nn.Dense(cfg.vision_hidden * 4, dtype=self.dtype, name=f"fc1_{i}")(y)
            y = nn.gelu(y, approximate=False)
            x = x + nn.Dense(cfg.vision_hidden, dtype=self.dtype, name=f"fc2_{i}")(y)
        return nn.LayerNorm(epsilon=eps, dtype=self.dtype, name="ln_post")(x)


def _embed_text(module, cfg: BlipConfig, input_ids, dtype):
    """Word + learned-position embeddings with BERT embedding LN (shared by
    the decoder and the VQA question encoder; identical param names)."""
    s = input_ids.shape[1]
    x = nn.Embed(
        cfg.vocab_size, cfg.text_hidden, dtype=dtype, name="word_embeddings"
    )(input_ids)
    pos = module.param(
        "position_embeddings", nn.initializers.normal(0.02),
        (cfg.max_positions, cfg.text_hidden),
    ).astype(dtype)
    x = x + pos[None, :s]
    return nn.LayerNorm(epsilon=1e-12, dtype=dtype, name="embed_ln")(x)


def _bert_layer(cfg: BlipConfig, dtype, i: int, x, context,
                self_mask=None, context_mask=None):
    """One post-LN BERT layer [self-attn + LN, cross-attn + LN, FFN + LN]
    — the block both TextDecoder and TextEncoder run, differing only in
    the masks. Must be called inside the owner's @nn.compact so the param
    names (self_{i}, cross_{i}, fc1_{i}, ...) land identically whichever
    module runs it."""
    eps = 1e-12  # BERT layer_norm_eps
    y = _MHA(cfg.text_heads, cfg.text_hidden, dtype=dtype,
             name=f"self_{i}")(x, x, self_mask)
    x = nn.LayerNorm(epsilon=eps, dtype=dtype, name=f"self_ln_{i}")(x + y)
    y = _MHA(cfg.text_heads, cfg.text_hidden, dtype=dtype,
             name=f"cross_{i}")(x, context, context_mask)
    x = nn.LayerNorm(epsilon=eps, dtype=dtype, name=f"cross_ln_{i}")(x + y)
    y = nn.Dense(cfg.text_hidden * 4, dtype=dtype, name=f"fc1_{i}")(x)
    y = nn.gelu(y, approximate=False)
    y = nn.Dense(cfg.text_hidden, dtype=dtype, name=f"fc2_{i}")(y)
    return nn.LayerNorm(epsilon=eps, dtype=dtype, name=f"ffn_ln_{i}")(x + y)


def _additive_mask(attention_mask, dtype):
    """[B, K] 1/0 keep-mask -> [B, 1, 1, K] additive logits mask."""
    return ((1.0 - attention_mask.astype(jnp.float32)) * -1e9).astype(dtype)[
        :, None, None, :
    ]


class TextDecoder(nn.Module):
    """BERT-style post-LN causal decoder mirroring HF BLIP's text_decoder
    (BlipTextLMHeadModel): embedding LN, per-layer [self-attn + LN,
    cross-attn over the context + LN, FFN + LN], prediction-head
    transform (dense -> gelu -> LN) before the vocab projection. Post-LN
    ordering and 1e-12 epsilons are load-bearing for converted weights.
    The cross-attention context is the vision embeds for captioning or the
    encoded question for VQA; `context_mask` [B, K] excludes padded
    context positions.
    """

    config: BlipConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, input_ids, image_embeds, context_mask=None):
        """[B, L] ids + [B, K, Dc] -> [B, L, vocab] logits (causal)."""
        cfg = self.config
        s = input_ids.shape[1]
        eps = 1e-12
        x = _embed_text(self, cfg, input_ids, self.dtype)
        causal = jnp.triu(jnp.full((s, s), -1e9, self.dtype), k=1)[None, None]
        ctx = image_embeds.astype(self.dtype)
        ctx_mask = (
            _additive_mask(context_mask, self.dtype)
            if context_mask is not None
            else None
        )
        for i in range(cfg.text_layers):
            x = _bert_layer(cfg, self.dtype, i, x, ctx, causal, ctx_mask)
        y = nn.Dense(cfg.text_hidden, dtype=self.dtype, name="head_dense")(x)
        y = nn.gelu(y, approximate=False)
        y = nn.LayerNorm(epsilon=eps, dtype=self.dtype, name="head_ln")(y)
        return nn.Dense(cfg.vocab_size, dtype=self.dtype, name="lm_head")(y)


def greedy_decode(decoder_apply, params, image_embeds, config: BlipConfig,
                  prefix_ids=None):
    """Fixed-length greedy decode under jit; returns [B, max_len] int32 ids.

    The buffer starts as [BOS, prefix..., EOS-pad]; each scan step writes
    the argmax for the next position. EOS truncation happens host-side.
    """
    b = image_embeds.shape[0]
    max_len = config.max_caption_len
    ids = jnp.full((b, max_len), config.eos_token_id, jnp.int32)
    ids = ids.at[:, 0].set(config.bos_token_id)
    start = 1
    if prefix_ids is not None:
        plen = prefix_ids.shape[1]
        ids = jax.lax.dynamic_update_slice(ids, prefix_ids.astype(jnp.int32), (0, 1))
        start = 1 + plen

    def body(ids, t):
        logits = decoder_apply(params, ids, image_embeds)  # [B, L, V]
        next_id = jnp.argmax(logits[:, t - 1, :], axis=-1).astype(jnp.int32)
        write = t >= start  # keep BOS/prefix intact
        current = jax.lax.dynamic_slice_in_dim(ids, t, 1, axis=1)[:, 0]
        next_id = jnp.where(write, next_id, current)
        ids = jax.lax.dynamic_update_slice_in_dim(
            ids, next_id[:, None], t, axis=1
        )
        return ids, ()

    ids, _ = jax.lax.scan(body, ids, jnp.arange(1, max_len))
    return ids


class TextEncoder(nn.Module):
    """BERT-style post-LN BIDIRECTIONAL encoder with cross-attention over
    vision embeds — HF BlipTextModel as BlipForQuestionAnswering uses it to
    encode the question against the image. Same block as TextDecoder
    (shared `_bert_layer`, identical param names) minus the causal mask
    and the LM head; returns hidden states for the answer decoder to
    cross-attend. `attention_mask` [B, L] excludes padded question
    positions from self-attention.

    Note on [ENC]: the original Salesforce BLIP swaps the question's
    leading [CLS] for a dedicated [ENC] token (id 30523); HF transformers'
    BlipForQuestionAnswering.generate — the stack the reference serves
    with — passes the tokenizer output ([CLS] q [SEP]) through UNCHANGED
    (verified against transformers 4.57). This encoder follows HF, and the
    torch-parity test in tests/test_captioning.py pins that choice."""

    config: BlipConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, input_ids, image_embeds, attention_mask=None):
        """[B, L] ids + [B, P, Dv] -> [B, L, D] question states."""
        cfg = self.config
        x = _embed_text(self, cfg, input_ids, self.dtype)
        img = image_embeds.astype(self.dtype)
        self_mask = (
            _additive_mask(attention_mask, self.dtype)
            if attention_mask is not None
            else None
        )
        for i in range(cfg.text_layers):
            x = _bert_layer(cfg, self.dtype, i, x, img, self_mask, None)
        return x
