"""Minimal BERT WordPiece tokenizer for the BLIP text side.

Replaces the reference's reflection-loaded `BlipProcessor`
(swarm/captioning/caption_image.py:12-17) with a dependency-free
implementation: lowercasing + punctuation-splitting pre-tokenizer and
greedy longest-match WordPiece over a bert-base `vocab.txt`. Decoding
re-joins `##` continuation pieces — enough for caption output, which is
plain lowercase English.

`HashBertTokenizer` is the hermetic stand-in for tiny/test models (same
role as models/tokenizer.py's HashTokenizer for CLIP).
"""

from __future__ import annotations

from pathlib import Path

_PUNCT = set("!\"#$%&'()*+,-./:;<=>?@[\\]^_`{|}~")


def _pre_tokenize(text: str) -> list[str]:
    """Lowercase, then split on whitespace and isolate punctuation."""
    words: list[str] = []
    current: list[str] = []
    for ch in text.lower():
        if ch.isspace():
            if current:
                words.append("".join(current))
                current = []
        elif ch in _PUNCT:
            if current:
                words.append("".join(current))
                current = []
            words.append(ch)
        else:
            current.append(ch)
    if current:
        words.append("".join(current))
    return words


class BertWordPieceTokenizer:
    unk_token = "[UNK]"

    def __init__(self, vocab: dict[str, int]):
        self.vocab = vocab
        self.inverse = {i: t for t, i in vocab.items()}
        self.unk_id = vocab.get(self.unk_token, 100)

    @classmethod
    def from_file(cls, vocab_path: str | Path) -> "BertWordPieceTokenizer":
        vocab = {}
        with open(vocab_path, encoding="utf-8") as f:
            # ids are line numbers including blanks, but CRLF endings and
            # empty trailing lines must not register as tokens
            for i, line in enumerate(f):
                token = line.rstrip("\r\n")
                if token:
                    vocab[token] = i
        return cls(vocab)

    def _wordpiece(self, word: str) -> list[int]:
        """Greedy longest-match-first, `##` continuation prefixes."""
        ids: list[int] = []
        start = 0
        while start < len(word):
            end = len(word)
            piece_id = None
            while end > start:
                piece = word[start:end]
                if start > 0:
                    piece = "##" + piece
                if piece in self.vocab:
                    piece_id = self.vocab[piece]
                    break
                end -= 1
            if piece_id is None:
                return [self.unk_id]  # whole word unknown
            ids.append(piece_id)
            start = end
        return ids

    def encode(self, text: str) -> list[int]:
        ids: list[int] = []
        for word in _pre_tokenize(text):
            ids.extend(self._wordpiece(word))
        return ids

    def decode(self, ids, skip_special: bool = True) -> str:
        pieces = []
        for i in ids:
            tok = self.inverse.get(int(i), self.unk_token)
            if skip_special and tok.startswith("[") and tok.endswith("]"):
                continue
            pieces.append(tok)
        out = ""
        for p in pieces:
            if p.startswith("##"):
                out += p[2:]
            elif out and p not in _PUNCT:
                out += " " + p
            else:
                out += p
        return out


class HashBertTokenizer:
    """Deterministic stand-in for tiny/test models: stable ids from token
    text, synthetic `t{id}` decode."""

    def __init__(self, vocab_size: int):
        self.vocab_size = vocab_size

    def encode(self, text: str) -> list[int]:
        import zlib

        # reserve the top ids for bos/eos of the tiny config
        span = max(self.vocab_size - 2, 1)
        return [zlib.crc32(w.encode()) % span for w in _pre_tokenize(text)]

    def decode(self, ids, skip_special: bool = True) -> str:
        return " ".join(f"t{int(i)}" for i in ids)


def load_bert_tokenizer(model_dir: str | Path | None, vocab_size: int):
    """Real WordPiece when a vocab ships with the model, else the hash
    stand-in (mirrors models/tokenizer.py's load_tokenizer contract)."""
    if model_dir is not None:
        for rel in ("vocab.txt", "tokenizer/vocab.txt"):
            path = Path(model_dir) / rel
            if path.is_file():
                return BertWordPieceTokenizer.from_file(path)
    return HashBertTokenizer(vocab_size)
