"""Layered configuration: JSON settings file + environment overrides.

Behavior parity with reference swarm/settings.py:7-76 — same file location
($SDAAS_ROOT or ~/.sdaas/settings.json), same field names, same env override
keys (SDAAS_TOKEN / SDAAS_URI / SDAAS_WORKERNAME) — plus TPU-specific fields
the reference has no analog for (mesh topology, compilation cache directory).
"""

from __future__ import annotations

import dataclasses
import json
import os
from pathlib import Path


@dataclasses.dataclass
class Settings:
    log_level: str = "WARN"
    log_filename: str = "log/generator.log"
    sdaas_token: str = ""
    sdaas_uri: str = "http://localhost:9511"
    worker_name: str = "worker"
    lora_root_dir: str = "~/lora"
    # --- TPU-native additions (no reference analog) ---
    # chips per job slice; 0 = use every local chip as one slice
    chips_per_job: int = 0
    # tensor-parallel degree within each slice (Megatron-style sharding of
    # attention/MLP kernels over the mesh's `tensor` axis); must divide the
    # slice's chip count
    tensor_parallelism: int = 1
    # sequence-parallel degree within each slice (ring attention over the
    # mesh's `seq` axis for long self-attention); tensor * seq must divide
    # the slice's chip count
    sequence_parallelism: int = 1
    # self-attention sequence length at which the ring route engages when a
    # seq axis is active (4096 tokens = a 1024^2 SDXL canvas's largest
    # attention level); configurable so tests and small-canvas deployments
    # exercise the exact production routing instead of monkey-patching
    ring_min_seq: int = 2048
    # persistent XLA compilation cache (the TPU analog of the HF model
    # cache): relative values resolve under $SDAAS_ROOT, "~" expands, ""
    # (or "0"/"off") disables at zero cost — compile_cache.py. Survives a
    # worker restart, so warm-restart warmup skips the XLA compile half.
    # (Legacy settings.json key `compilation_cache_dir` still loads.)
    compile_cache_dir: str = "xla_cache"
    # model weight root (converted Flax checkpoints / HF safetensors)
    model_root_dir: str = "~/.sdaas/models"
    # dtype policy for pipeline params: "bfloat16" | "float32"
    dtype: str = "bfloat16"
    # aux depth model serving the `depth` preprocessor + Kandinsky hint
    depth_model: str = "Intel/dpt-large"
    # aux pose model for the openpose preprocessor
    pose_model: str = "lllyasviel/ControlNet-openpose"
    # NSFW safety checker feeding the envelope flag ("" disables)
    safety_checker_model: str = "CompVis/stable-diffusion-safety-checker"
    # jax.profiler trace server port (0 = disabled)
    profiler_port: int = 0
    # arm the on-demand profiler capture hook on the worker metrics app
    # (POST /debug/profile?seconds=N writes a perfetto trace under
    # $SDAAS_ROOT/profiles/); off by default — profiling is an operator
    # action, not an always-on surface
    profiler_capture: bool = False
    # serve Flux on single-chip slices by paging transformer blocks from
    # host RAM (the TPU analog of the reference's sequential CPU offload);
    # False restores the round-4 behavior of refusing with flux_min_chips
    flux_streaming: bool = True
    # store the paged transformer blocks as per-channel int8 (halves the
    # per-step PCIe traffic — the streamed mode's bottleneck — at a small
    # bounded accuracy cost; dequantization happens on-chip)
    flux_stream_int8: bool = False
    # cross-job micro-batching (batching.py): how long a compatible txt2img
    # job waits for batchmates before its group dispatches to a slice. 0
    # disables the linger (every job dispatches alone, round-5 behavior)
    batch_linger_ms: float = 50.0
    # most jobs one coalesced group may hold; <= 1 disables coalescing
    max_coalesce: int = 8
    # text-encoder embedding cache (pipelines/, embed_cache.py): LRU
    # byte cap in MiB for encoded (model, prompt-text) rows, so gang
    # members and repeat prompts skip text_encode entirely; 0 disables
    embed_cache_mb: int = 64
    # --- multi-tenant add-on serving (ISSUE 13, pipelines/lora_runtime) ---
    # apply LoRA adapters as RUNTIME per-row low-rank deltas inside the
    # jitted program (one resident base UNet, adapters as stacked
    # factors) instead of merging each adapter into a full param-tree
    # copy. Off restores the merged-tree path everywhere (and makes
    # adapter jobs uncoalesceable again) — the A/B knob the lora_coalesce
    # bench flips for its solo-merged baseline
    lora_runtime_delta: bool = True
    # byte cap (MiB) for the process-wide raw adapter-factor LRU
    # (lora_cache.py); 0 disables caching (adapters reload per pass)
    lora_cache_mb: int = 256
    # byte cap (MiB) for the DEVICE-resident stacked-operand LRU
    # (lora_operands.py, ISSUE 16): already-assembled, already-uploaded
    # A/B stacks keyed by (model, adapter set, sig, dtype, geometry), so
    # a repeat gang of the same adapters uploads nothing. Coherent with
    # the factor LRU (factor eviction drops derived stacks); 0 disables
    # (every pass re-assembles + re-uploads, the PR 13 behavior)
    lora_operand_cache_mb: int = 512
    # most DISTINCT adapters one coalesced group/gang may carry. Shared
    # vocabulary: the hive's gang dispatcher, the worker's batch
    # scheduler, and run_batched all cap on it. The compiled slot
    # dimension is pow2(cap + 1) — one implicit zero slot for
    # adapter-free rows, padded to a power of two — so a FULL gang at
    # the default 8 compiles a 16-slot stack; set 7 to stay at 8 slots
    lora_slots_max: int = 8
    # adapters with rank beyond this serve via the merged-tree fallback
    # (their padded factor stacks would rival the activations they ride)
    lora_rank_max: int = 128
    # most compiled denoise-program variants (and assembled runners) one
    # pipeline keeps resident (pipelines/stable_diffusion.py). The
    # runtime-delta adapter path compiles one variant per (slot-bucket,
    # rank-bucket, targeted-module-path-set) and the path-set fan-out is
    # census-dependent, so a fleet-realistic worker bounds the cache:
    # past the cap the LRU entry is evicted WITH its compiled executable
    # (counted in swarm_program_cache_evicted_total). 0 = unbounded
    # (the pre-ISSUE-15 behavior)
    program_cache_max: int = 64
    # chunked denoise (pipelines/stable_diffusion.py): run the compiled
    # denoise loop in chunks of this many steps, probing the cancel
    # registry (cancel.py) at every chunk boundary so a cancelled job
    # frees its slice within one chunk instead of one full pass. 0 (the
    # default) keeps the single-pass compiled denoise at zero cost;
    # chunked and single-pass outputs are bitwise identical (pinned)
    denoise_chunk_steps: int = 0
    # --- preemption-tolerant denoise (ISSUE 18, checkpoint.py) ---
    # ship a durable mid-pass checkpoint (latents + scheduler state +
    # step index) to the hive every N chunk boundaries of a chunked
    # denoise, so a redelivered job resumes at step K instead of
    # recomputing the whole pass. Requires denoise_chunk_steps > 0.
    # 0 (the default) disables: the classic path stays byte-identical
    checkpoint_every_chunks: int = 0
    # largest checkpoint blob the worker will ship (bytes); a bigger
    # pack is skipped (counted), never truncated — losing a checkpoint
    # only costs recompute on redelivery
    checkpoint_max_bytes: int = 8388608
    # VAE-decode the intermediate latents every N chunk boundaries into
    # a progressive-preview artifact (spooled hive-side, surfaced as the
    # `partial` disposition on GET /api/jobs/{id}); 0 disables
    preview_every_chunks: int = 0
    # --- priority-aware multi-chip sharding (ISSUE 12) ---
    # run INTERACTIVE solo jobs as ONE sharded program over every chip of
    # their slice (attention heads + MLP inner dims on the mesh's tensor
    # axis, the CFG pair on data, optional ring-attention seq axis) so a
    # single job's latency scales with the slice instead of being bounded
    # by one chip. Batch/coalesced traffic keeps the data-parallel view
    # either way — the job class picks the geometry. Off by default: the
    # sharded view compiles its own program set per bucket, so an
    # operator turns it on per-fleet once the compile budget is warm
    shard_interactive: bool = False
    # tensor-parallel degree for sharded interactive passes; 0 = auto
    # (the largest power-of-two that still leaves a data axis >= the CFG
    # pair). Must divide the slice's chip count (with shard_seq)
    shard_tensor: int = 0
    # ring-attention sequence-parallel degree for sharded interactive
    # passes (long-canvas latents); 1 = off
    shard_seq: int = 1
    # --- observability (telemetry.py) ---
    # local /metrics + /healthz HTTP port; 0 disables the server (the
    # in-process instrumentation stays on either way — it is dict ops)
    metrics_port: int = 8061
    # bind address for the metrics server; loopback by default so worker
    # internals are not exposed off-host unless the operator opts in
    # (set 0.0.0.0 for a Prometheus scrape from another machine)
    metrics_host: str = "127.0.0.1"
    # log line format: "plain" (reference parity) | "json" (structured
    # lines carrying the active job_id — log_setup.JsonFormatter)
    log_format: str = "plain"
    # --- fault tolerance (outbox.py / worker watchdog / faults.py) ---
    # per-job execution deadline enforced by the slice watchdog; 0 disables.
    # On expiry the job returns the transient-error envelope and the slice
    # is quarantined until it passes a smoke probe
    job_deadline_s: float = 900.0
    # deadline multiplier when the job's model is not yet resident (first
    # compile of a big program legitimately takes minutes)
    job_deadline_compile_scale: float = 4.0
    # how long the quarantine probe waits for a wedged slice to come back
    # before writing it off (capacity stays shrunk if it never does)
    quarantine_probe_grace_s: float = 30.0
    # stop(drain=True)/SIGTERM: how long in-flight slices + the outbox get
    # to flush before the worker exits anyway (spooled envelopes survive)
    drain_deadline_s: float = 120.0
    # durable result spool directory (relative to $SDAAS_ROOT)
    outbox_dir: str = "outbox"
    # spooled-envelope count at which /healthz turns degraded (0 = never);
    # spooling itself never stops — saturation is a signal, not a limit
    outbox_max_entries: int = 512
    # deterministic fault-injection spec (faults.py), e.g.
    # "drop_submit=3,hang_denoise=1"; empty = no faults armed
    fault_injection: str = ""
    # --- embedded hive coordinator (hive_server/, tools/hive_serve.py) ---
    # bind address/port for the coordinator; the port default matches the
    # worker's sdaas_uri default, so `hive_serve` + a stock worker on one
    # host form a swarm with zero configuration (0 = ephemeral port)
    hive_host: str = "127.0.0.1"
    hive_port: int = 9511
    # how long a dispatched job may go without a result before its lease
    # expires and the job is re-queued for another worker
    hive_lease_deadline_s: float = 300.0
    # expired-lease redeliveries before the job parks as failed (a poison
    # job must not ping-pong around the swarm forever)
    hive_max_redeliveries: int = 3
    # total queued jobs past which POST /api/jobs answers 429 (admission
    # backpressure; 0 = unlimited)
    hive_queue_depth_limit: int = 256
    # how long a job waits for its model's WARM worker to poll before any
    # cold worker may steal it (residency-aware dispatch)
    hive_affinity_hold_s: float = 15.0
    # a worker unseen for this long stops counting as a live residency
    # holder (3-4 poll cadences; dead workers must not hold jobs hostage)
    hive_worker_ttl_s: float = 45.0
    # most jobs one /work poll may hand out (also capped by the worker's
    # advertised free capacity)
    hive_max_jobs_per_poll: int = 4
    # most jobs one gang-scheduled /work group may hold (hive-side
    # coalescing, ISSUE 9): same-model same-shape queued jobs leave in
    # ONE reply, pre-batched, sized to min(this, the worker's advertised
    # gang_rows appetite, hive_max_jobs_per_poll). <= 1 disables gang
    # scheduling and restores per-job dispatch
    hive_gang_max: int = 8
    # content-addressed artifact spool directory (relative to $SDAAS_ROOT)
    hive_spool_dir: str = "hive_spool"
    # finished (done/failed) job records kept in memory for
    # GET /api/jobs/{id}; older ones are forgotten so coordinator memory
    # is bounded by this, not by job history (0 = keep everything)
    hive_job_history_limit: int = 1000
    # admission-time job TTL: a job still QUEUED this many seconds after
    # submission is parked as `expired` instead of wasting a dispatch
    # (the submitter is presumed gone, or the answer stale). A per-job
    # `deadline_s` field on the submitted job dict overrides it; the
    # worker's slice watchdog also treats that per-job deadline as its
    # execution cap. 0 = no TTL (the pre-cancellation behavior)
    hive_job_ttl_s: float = 0.0
    # --- hive durability (hive_server/journal.py) ---
    # write-ahead journal directory (relative to $SDAAS_ROOT); every
    # queue/lease transition is appended so a crashed hive replays to its
    # pre-crash state on restart. "" disables (pure in-memory coordinator)
    hive_wal_dir: str = "hive_wal"
    # fsync each WAL append: flush-only (False) survives process death
    # incl. SIGKILL; fsync additionally survives power loss, at a
    # per-transition disk-sync cost
    hive_wal_fsync: bool = False
    # appends between WAL compactions (stream rewritten as a minimal
    # state snapshot); 0 = only compact on startup
    hive_wal_compact_every: int = 512
    # class-aware load shedding: per-class fractions of
    # hive_queue_depth_limit past which NEW submissions of that class
    # answer 429 — batch sheds first, interactive last
    hive_shed_watermarks: str = "interactive:1.0,default:0.85,batch:0.5"
    # artifact-spool retention sweep: total size / blob age bounds
    # (0 = keep everything); blobs referenced by a live job record are
    # never evicted
    hive_spool_max_bytes: int = 0
    hive_spool_max_age_s: float = 0.0
    # --- fleet observability plane (accounting.py / slo.py / fleet.py) ---
    # declarative per-class latency objectives, e.g.
    # "interactive:queue_wait_p95<2.0,e2e_p95<30;default:e2e_p95<120"
    # (classes split on ";", objectives on ","; metrics: queue_wait,
    # dispatch_to_settle, e2e). "" disables the SLO engine; GET /api/slo
    # still answers with enabled=false
    hive_slo: str = ""
    # sliding evaluation windows for compliance + burn rate: the fast
    # window drives /healthz degraded reasons, the slow one trend view
    hive_slo_fast_window_s: float = 60.0
    hive_slo_slow_window_s: float = 600.0
    # tenants named individually in the per-tenant usage gauges; the
    # rest fold into tenant="other" so cardinality stays bounded
    # (GET /api/usage always renders every tenant)
    hive_tenant_topk: int = 10
    # worker side: EWMA smoothing factor for the per-stage stats blob
    # piggybacked on /work polls (the hive's straggler detector input)
    hive_stats_ewma_alpha: float = 0.2
    # hive side: a worker is flagged a straggler when its per-stage EWMA
    # exceeds this multiple of the live peer median (plus an absolute
    # floor — fleet.py MIN_DELTA_S)
    hive_straggler_factor: float = 2.5
    # hive side: a worker whose leases expire this many CONSECUTIVE
    # times (no settle in between) stops receiving fresh seeds while a
    # healthy capable alternative is live — bounded by the affinity-hold
    # window exactly like straggler_hold, so a flapping worker is
    # preferred-against, never starved. 0 disables flap detection
    hive_flap_threshold: int = 3
    # --- hive replication & failover (hive_server/replication.py) ---
    # worker side: comma-separated hive site URIs in preference order
    # (primary first, standby after); the HiveClient pins to one and
    # fails over on consecutive transport errors or a not-primary 409.
    # Empty = the single sdaas_uri, the pre-replication behavior
    sdaas_uris: str = ""
    # hive side: set to the PRIMARY's site URI to run this hive as its
    # WAL-shipped standby (refuses work until promoted); "" = primary
    hive_standby_of: str = ""
    # how often the standby tails the primary's replication stream (and
    # therefore the failover-detection cadence)
    hive_replication_poll_s: float = 1.0
    # consecutive seconds of primary silence (no stream AND no /healthz
    # answer) before the standby promotes itself
    hive_failover_grace_s: float = 10.0
    # seconds without an APPLIED replication sync before a standby's
    # /healthz reports degraded (a silently stalled standby must be
    # visible before failover needs it); 0 disables the check
    hive_replication_lag_degraded_s: float = 30.0
    # worker side: consecutive transport errors on the pinned hive
    # endpoint before the client pins to the next one
    hive_failover_errors: int = 2
    # /healthz reports degraded when the worst device's free-HBM
    # fraction (memory_census.device_headroom) drops below this; 0
    # disables — some fleets legitimately run HBM near-full, so the
    # squeeze probe is an operator opt-in
    memory_headroom_degraded: float = 0.0
    # --- stage-graph serving (ISSUE 20, hive_server/dag.py) ---
    # worker side: which workflow stages this worker advertises on /work.
    # "auto" derives from hardware (chip hosts serve every stage; a
    # jax-free/CPU host serves only the host-path set — encode, decode,
    # postprocess, stitch, caption); "none" suppresses the advertisement
    # entirely (legacy poller: never sees stage-jobs); or an explicit
    # comma-separated stage list
    stage_roles: str = "auto"
    # worker side: concurrent host-path stage executions (encode/decode
    # jobs run beside the slice scheduler, so decode of pass N overlaps
    # denoise of pass N+1); 0 disables the side lane — CPU stages are
    # then refused by "auto" advertisement
    stage_workers: int = 2
    # hive side: terminal workflow graphs kept for GET /api/workflows
    # (running graphs never drop); bounds dag-table memory like
    # hive_job_history_limit bounds records
    hive_dag_history: int = 256

    @classmethod
    def field_names(cls) -> tuple[str, ...]:
        return tuple(f.name for f in dataclasses.fields(cls))


# env var -> settings attribute (reference swarm/settings.py:38-41).
# Every Settings field has exactly one override here (swarmlint SW004
# enforces it): SDAAS_* spellings are reference parity, CHIASWARM_*
# everything since.
_ENV_OVERRIDES = {
    "SDAAS_TOKEN": "sdaas_token",
    "CHIASWARM_LOG_LEVEL": "log_level",
    "CHIASWARM_LOG_FILENAME": "log_filename",
    "CHIASWARM_LORA_ROOT_DIR": "lora_root_dir",
    "CHIASWARM_MODEL_ROOT_DIR": "model_root_dir",
    "CHIASWARM_DEPTH_MODEL": "depth_model",
    "CHIASWARM_POSE_MODEL": "pose_model",
    "CHIASWARM_SAFETY_CHECKER_MODEL": "safety_checker_model",
    "CHIASWARM_PROFILER_PORT": "profiler_port",
    "CHIASWARM_JOB_DEADLINE_COMPILE_SCALE": "job_deadline_compile_scale",
    "CHIASWARM_QUARANTINE_PROBE_GRACE_S": "quarantine_probe_grace_s",
    "SDAAS_URI": "sdaas_uri",
    "SDAAS_WORKERNAME": "worker_name",
    "SDAAS_CHIPS_PER_JOB": "chips_per_job",
    "SDAAS_TENSOR_PARALLELISM": "tensor_parallelism",
    "SDAAS_SEQUENCE_PARALLELISM": "sequence_parallelism",
    "SDAAS_RING_MIN_SEQ": "ring_min_seq",
    "SDAAS_FLUX_STREAMING": "flux_streaming",
    "SDAAS_FLUX_STREAM_INT8": "flux_stream_int8",
    "SDAAS_DTYPE": "dtype",
    "SDAAS_BATCH_LINGER_MS": "batch_linger_ms",
    "SDAAS_MAX_COALESCE": "max_coalesce",
    "CHIASWARM_COMPILE_CACHE_DIR": "compile_cache_dir",
    "CHIASWARM_METRICS_PORT": "metrics_port",
    "CHIASWARM_METRICS_HOST": "metrics_host",
    "CHIASWARM_LOG_FORMAT": "log_format",
    "CHIASWARM_JOB_DEADLINE_S": "job_deadline_s",
    "CHIASWARM_DRAIN_DEADLINE_S": "drain_deadline_s",
    "CHIASWARM_OUTBOX_DIR": "outbox_dir",
    "CHIASWARM_OUTBOX_MAX_ENTRIES": "outbox_max_entries",
    "CHIASWARM_FAULTS": "fault_injection",
    "CHIASWARM_HIVE_HOST": "hive_host",
    "CHIASWARM_HIVE_PORT": "hive_port",
    "CHIASWARM_HIVE_LEASE_DEADLINE_S": "hive_lease_deadline_s",
    "CHIASWARM_HIVE_MAX_REDELIVERIES": "hive_max_redeliveries",
    "CHIASWARM_HIVE_QUEUE_DEPTH_LIMIT": "hive_queue_depth_limit",
    "CHIASWARM_HIVE_AFFINITY_HOLD_S": "hive_affinity_hold_s",
    "CHIASWARM_HIVE_WORKER_TTL_S": "hive_worker_ttl_s",
    "CHIASWARM_HIVE_MAX_JOBS_PER_POLL": "hive_max_jobs_per_poll",
    "CHIASWARM_HIVE_GANG_MAX": "hive_gang_max",
    "CHIASWARM_EMBED_CACHE_MB": "embed_cache_mb",
    "CHIASWARM_LORA_RUNTIME_DELTA": "lora_runtime_delta",
    "CHIASWARM_LORA_CACHE_MB": "lora_cache_mb",
    "CHIASWARM_LORA_OPERAND_CACHE_MB": "lora_operand_cache_mb",
    "CHIASWARM_LORA_SLOTS_MAX": "lora_slots_max",
    "CHIASWARM_LORA_RANK_MAX": "lora_rank_max",
    "CHIASWARM_PROGRAM_CACHE_MAX": "program_cache_max",
    "CHIASWARM_DENOISE_CHUNK_STEPS": "denoise_chunk_steps",
    "CHIASWARM_CHECKPOINT_EVERY_CHUNKS": "checkpoint_every_chunks",
    "CHIASWARM_CHECKPOINT_MAX_BYTES": "checkpoint_max_bytes",
    "CHIASWARM_PREVIEW_EVERY_CHUNKS": "preview_every_chunks",
    "CHIASWARM_SHARD_INTERACTIVE": "shard_interactive",
    "CHIASWARM_SHARD_TENSOR": "shard_tensor",
    "CHIASWARM_SHARD_SEQ": "shard_seq",
    "CHIASWARM_HIVE_JOB_TTL_S": "hive_job_ttl_s",
    "CHIASWARM_HIVE_SPOOL_DIR": "hive_spool_dir",
    "CHIASWARM_HIVE_JOB_HISTORY_LIMIT": "hive_job_history_limit",
    "CHIASWARM_HIVE_WAL_DIR": "hive_wal_dir",
    "CHIASWARM_HIVE_WAL_FSYNC": "hive_wal_fsync",
    "CHIASWARM_HIVE_WAL_COMPACT_EVERY": "hive_wal_compact_every",
    "CHIASWARM_HIVE_SHED_WATERMARKS": "hive_shed_watermarks",
    "CHIASWARM_HIVE_SPOOL_MAX_BYTES": "hive_spool_max_bytes",
    "CHIASWARM_HIVE_SPOOL_MAX_AGE_S": "hive_spool_max_age_s",
    "CHIASWARM_HIVE_SLO": "hive_slo",
    "CHIASWARM_HIVE_SLO_FAST_WINDOW_S": "hive_slo_fast_window_s",
    "CHIASWARM_HIVE_SLO_SLOW_WINDOW_S": "hive_slo_slow_window_s",
    "CHIASWARM_HIVE_TENANT_TOPK": "hive_tenant_topk",
    "CHIASWARM_HIVE_STATS_EWMA_ALPHA": "hive_stats_ewma_alpha",
    "CHIASWARM_HIVE_STRAGGLER_FACTOR": "hive_straggler_factor",
    "CHIASWARM_HIVE_FLAP_THRESHOLD": "hive_flap_threshold",
    "CHIASWARM_HIVE_URIS": "sdaas_uris",
    "CHIASWARM_HIVE_STANDBY_OF": "hive_standby_of",
    "CHIASWARM_HIVE_REPLICATION_POLL_S": "hive_replication_poll_s",
    "CHIASWARM_HIVE_FAILOVER_GRACE_S": "hive_failover_grace_s",
    "CHIASWARM_HIVE_FAILOVER_ERRORS": "hive_failover_errors",
    "CHIASWARM_HIVE_REPLICATION_LAG_DEGRADED_S":
        "hive_replication_lag_degraded_s",
    "CHIASWARM_PROFILER_CAPTURE": "profiler_capture",
    "CHIASWARM_MEMORY_HEADROOM_DEGRADED": "memory_headroom_degraded",
    "CHIASWARM_STAGE_ROLES": "stage_roles",
    "CHIASWARM_STAGE_WORKERS": "stage_workers",
    "CHIASWARM_HIVE_DAG_HISTORY": "hive_dag_history",
}


def get_settings_dir() -> Path:
    return Path(os.environ.get("SDAAS_ROOT") or "~/.sdaas/").expanduser()


def resolve_path(path: str | Path) -> Path:
    full_path = get_settings_dir() / path
    full_path.parent.mkdir(parents=True, exist_ok=True)
    return full_path


def get_settings_full_path() -> Path:
    return resolve_path("settings.json")


def settings_exist() -> bool:
    return get_settings_full_path().is_file()


def load_settings() -> Settings:
    try:
        raw = json.loads(get_settings_full_path().read_text())
    except FileNotFoundError:
        raw = {}
    except json.JSONDecodeError:
        raw = {}

    known = {k: v for k, v in raw.items() if k in Settings.field_names()}
    # pre-round-8 settings files spelled the cache knob compilation_cache_dir
    if "compilation_cache_dir" in raw and "compile_cache_dir" not in raw:
        known["compile_cache_dir"] = raw["compilation_cache_dir"]
    settings = Settings(**known)

    for env_key, attr in _ENV_OVERRIDES.items():
        value = os.getenv(env_key)
        if value is not None:
            field_type = type(getattr(settings, attr))
            if field_type is bool:
                # bool("0") is True — parse the usual spellings instead
                setattr(settings, attr,
                        value.strip().lower() in ("1", "true", "yes", "on"))
            else:
                setattr(settings, attr, field_type(value))

    return settings


def save_settings(settings: Settings) -> None:
    get_settings_full_path().write_text(
        json.dumps(dataclasses.asdict(settings), indent=2)
    )


def save_file(data, filename: str) -> None:
    resolve_path(filename).write_text(json.dumps(data, indent=2))
