"""The SW rules. Each rule is a small class with `code`, `title`, and
`check(project) -> list[Finding]`; the engine (core.run_lint) owns
suppression and baseline handling, so rules just report what they see.

Rules are deliberately shallow pattern matchers over the AST — a
tripwire, not a proof system. Where a rule cannot see through an
indirection (a blocking call hidden behind a helper, a cache bounded in
another module), it stays silent; the reviewer folklore it replaces had
the same blind spots, minus the consistency.
"""

from __future__ import annotations

import ast
import re

from . import config
from .core import Finding, Project, SourceFile


class Rule:
    code = "SW000"
    title = ""

    def check(self, project: Project) -> list[Finding]:
        raise NotImplementedError


# --- helpers ---------------------------------------------------------------


def _call_target(node: ast.Call) -> tuple[str | None, str | None]:
    """(owner, name) for a call: owner is the dotted-most base name for
    `owner.name(...)`, None for a bare `name(...)`."""
    func = node.func
    if isinstance(func, ast.Attribute):
        base = func.value
        if isinstance(base, ast.Name):
            return base.id, func.attr
        if isinstance(base, ast.Attribute) and isinstance(
                base.value, ast.Name):
            # e.g. urllib.request.urlopen -> owner "urllib"
            return base.value.id, func.attr
        return "", func.attr
    if isinstance(func, ast.Name):
        return None, func.id
    return "", None


def _iter_own_statements(fn: ast.AsyncFunctionDef):
    """Walk a coroutine's own body, never descending into nested
    function scopes (nested defs/lambdas usually run off-loop via
    run_in_executor; nested `async def`s get their own visit). A
    blocking call hidden behind an inline call to such a nested def is
    an acknowledged blind spot — tripwire, not proof."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _const_str(node, module_consts: dict[str, str]) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return module_consts.get(node.id)
    return None


def _module_str_consts(tree: ast.Module) -> dict[str, str]:
    out: dict[str, str] = {}
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            out[node.targets[0].id] = node.value.value
    return out


# --- SW001 -----------------------------------------------------------------


class JaxFreePurity(Rule):
    code = "SW001"
    title = ("accelerator import reachable from a declared jax-free "
             "module (module-level, transitive)")

    def _roots(self, project: Project) -> list[SourceFile]:
        roots: list[SourceFile] = []
        for spec in config.JAXFREE_ROOTS:
            if spec.endswith(".py"):
                sf = project.file(spec)
                if sf is not None:
                    roots.append(sf)
            else:
                prefix = spec.rstrip("/") + "/"
                roots.extend(sf for rel, sf in sorted(project.files.items())
                             if rel.startswith(prefix))
        return roots

    def check(self, project: Project) -> list[Finding]:
        # per-module direct facts, computed once
        direct_bad: dict[str, list[tuple[str, int]]] = {}
        deps: dict[str, dict[str, int]] = {}  # module -> dep -> line
        for mod, sf in project.modules.items():
            bad: dict[str, int] = {}
            dep_lines: dict[str, int] = {}
            for target, line in project.toplevel_imports(sf):
                if target.split(".")[0] in config.ACCELERATOR_PACKAGES:
                    bad.setdefault(target, line)
                fp = project.resolve_first_party(target)
                if fp is not None and fp != mod:
                    dep_lines.setdefault(fp, line)
            direct_bad[mod] = sorted(bad.items())
            deps[mod] = dep_lines

        findings: list[Finding] = []
        for sf in self._roots(project):
            root_mod = project.module_name(sf.rel)
            # BFS with parent pointers for chain reconstruction
            parent: dict[str, str] = {root_mod: ""}
            order = [root_mod]
            i = 0
            while i < len(order):
                mod = order[i]
                i += 1
                for dep in deps.get(mod, {}):
                    if dep not in parent:
                        parent[dep] = mod
                        order.append(dep)
            reported: set[str] = set()
            for mod in order:
                if not direct_bad.get(mod):
                    continue
                # rebuild the chain root -> ... -> mod
                chain = [mod]
                while parent[chain[-1]]:
                    chain.append(parent[chain[-1]])
                chain.reverse()
                if mod in reported:
                    continue
                reported.add(mod)
                # anchor at the root's own offending line: the import
                # starting the chain, or — for a direct violation — the
                # forbidden import itself (so per-line suppression and
                # baseline anchors land on the real statement)
                if len(chain) > 1:
                    line = deps[chain[0]].get(chain[1], 1)
                else:
                    line = direct_bad[mod][0][1]
                via = " -> ".join(chain)
                pkgs = ", ".join(name for name, _ in direct_bad[mod])
                findings.append(sf.finding(
                    self.code, line,
                    f"jax-free module reaches {pkgs} at module level "
                    f"via {via}; make the accelerator import lazy "
                    "(function-local) or drop the dependency"))
        return findings


# --- SW002 -----------------------------------------------------------------


class AsyncBlockingCalls(Rule):
    code = "SW002"
    title = "blocking call on the event loop (inside `async def`)"

    def check(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for sf in project.files.values():
            if sf.tree is None:
                continue
            for fn in ast.walk(sf.tree):
                if not isinstance(fn, ast.AsyncFunctionDef):
                    continue
                for node in _iter_own_statements(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    owner, name = _call_target(node)
                    hit = None
                    if owner is None and name in config.BLOCKING_NAME_CALLS:
                        hit = f"{name}()"
                    elif owner and (owner, name) in \
                            config.BLOCKING_MODULE_CALLS:
                        hit = f"{owner}.{name}()"
                    elif name in config.BLOCKING_METHOD_NAMES:
                        hit = f".{name}()"
                    if hit:
                        findings.append(sf.finding(
                            self.code, node.lineno,
                            f"{hit} blocks the event loop inside "
                            f"`async def {fn.name}`; route it through "
                            "run_in_executor / asyncio.to_thread"))
        return findings


# --- SW003 -----------------------------------------------------------------


class HiveClockDiscipline(Rule):
    code = "SW003"
    title = "direct wall/monotonic clock read in hive_server/ (use HiveClock)"

    def check(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        prefix = config.HIVE_SERVER_DIR.rstrip("/") + "/"
        for rel, sf in sorted(project.files.items()):
            if not rel.startswith(prefix) or rel == config.CLOCK_MODULE:
                continue
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                owner, name = _call_target(node)
                if owner and (owner, name) in config.CLOCK_CALLS:
                    findings.append(sf.finding(
                        self.code, node.lineno,
                        f"{owner}.{name}() in hive_server bypasses "
                        "HiveClock; use clock.mono() for intervals, "
                        "clock.wall() for persistence (clock.py)"))
        return findings


# --- SW004 -----------------------------------------------------------------


class SettingsKnobDrift(Rule):
    code = "SW004"
    title = "Settings knob drift (env override / README row / settings test)"

    def check(self, project: Project) -> list[Finding]:
        sf = project.file(config.SETTINGS_FILE)
        if sf is None or sf.tree is None:
            return []
        fields: dict[str, int] = {}
        env_by_field: dict[str, str] = {}
        overrides_line = 1
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef) and node.name == "Settings":
                for stmt in node.body:
                    if isinstance(stmt, ast.AnnAssign) and isinstance(
                            stmt.target, ast.Name):
                        fields[stmt.target.id] = stmt.lineno
            elif (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "_ENV_OVERRIDES"
                    and isinstance(node.value, ast.Dict)):
                overrides_line = node.lineno
                for k, v in zip(node.value.keys, node.value.values):
                    if (isinstance(k, ast.Constant)
                            and isinstance(v, ast.Constant)):
                        env_by_field[str(v.value)] = str(k.value)
        readme = project.read_text(config.README_FILE) or ""
        tests = project.read_text(config.SETTINGS_TEST_FILE) or ""

        findings: list[Finding] = []
        for field, line in fields.items():
            env = env_by_field.get(field)
            if env is None:
                findings.append(sf.finding(
                    self.code, line,
                    f"Settings.{field} has no env override in "
                    "_ENV_OVERRIDES (CHIASWARM_* / legacy SDAAS_*)"))
            if field not in readme:
                findings.append(sf.finding(
                    self.code, line,
                    f"Settings.{field} has no README knob-table row "
                    "(see \"Configuration reference\")"))
            elif env is not None and env not in readme:
                findings.append(sf.finding(
                    self.code, line,
                    f"env override {env} for Settings.{field} is not "
                    "documented in the README"))
            if field not in tests:
                findings.append(sf.finding(
                    self.code, line,
                    f"Settings.{field} is never referenced in "
                    f"{config.SETTINGS_TEST_FILE}"))
        for field, env in sorted(env_by_field.items()):
            if field not in fields:
                findings.append(sf.finding(
                    self.code, overrides_line,
                    f"env override {env} maps to nonexistent "
                    f"Settings.{field}"))
        return findings


# --- SW005 -----------------------------------------------------------------


_METRIC_METHODS = ("counter", "gauge", "histogram")
_DOC_NAME_RE = re.compile(r"swarm_[a-z0-9_]+")
_DOC_SUFFIX_RE = re.compile(r"`(_[a-z0-9_]+)`")


class MetricCatalogDrift(Rule):
    code = "SW005"
    title = "registered swarm_* metric missing/mismatched in README catalog"

    @staticmethod
    def _registrations(project: Project):
        """(name, labels, sf, line) for every metric registration in the
        package: a call to counter/gauge/histogram (any receiver) whose
        first argument resolves to a swarm_* string literal."""
        for rel, sf in sorted(project.files.items()):
            if not rel.startswith(config.METRICS_SCAN_PREFIX):
                continue
            if sf.tree is None:
                continue
            consts = _module_str_consts(sf.tree)
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                _, name = _call_target(node)
                if name not in _METRIC_METHODS or not node.args:
                    continue
                metric = _const_str(node.args[0], consts)
                if not metric or not metric.startswith(
                        config.METRIC_PREFIX):
                    continue
                labels: list[str] = []
                label_node = None
                if len(node.args) >= 3:
                    label_node = node.args[2]
                for kw in node.keywords:
                    if kw.arg == "labelnames":
                        label_node = kw.value
                if isinstance(label_node, (ast.Tuple, ast.List)):
                    labels = [e.value for e in label_node.elts
                              if isinstance(e, ast.Constant)]
                yield metric, labels, sf, node.lineno

    @staticmethod
    def _catalog(readme: str):
        """(catalog, rows): catalog maps each fully-spelled metric name
        to its concatenated labels-cell text; rows keeps every parsed
        (full names, suffix tokens, labels cell) triple so shorthand
        suffix forms (`swarm_outbox_spooled_total` / `_delivered_total`)
        can expand against a row's first full name."""
        rows: list[tuple[list[str], list[str], str]] = []
        for line in readme.splitlines():
            if not line.lstrip().startswith("|") or "swarm_" not in line:
                continue
            cells = [c.strip() for c in line.strip().strip("|").split("|")]
            metric_cell = cells[0] if cells else ""
            labels_cell = cells[2] if len(cells) >= 3 else ""
            full = _DOC_NAME_RE.findall(metric_cell)
            suffixes = _DOC_SUFFIX_RE.findall(metric_cell)
            if full:
                rows.append((full, suffixes, labels_cell))
        catalog: dict[str, str] = {}
        for full, _suffixes, labels_cell in rows:
            for name in full:
                catalog[name] = catalog.get(name, "") + " " + labels_cell
        return catalog, rows

    def check(self, project: Project) -> list[Finding]:
        readme = project.read_text(config.README_FILE) or ""
        catalog, rows = self._catalog(readme)
        findings: list[Finding] = []
        seen: set[str] = set()
        for metric, labels, sf, line in self._registrations(project):
            if metric in seen:
                continue
            seen.add(metric)
            labels_cell = catalog.get(metric)
            if labels_cell is None:
                # try the suffix shorthand: metric = prefix(first full
                # name of some row) + documented `_suffix`
                for full, suffixes, cell in rows:
                    anchor_name = full[0]
                    for sfx in suffixes:
                        if (metric.endswith(sfx) and anchor_name.startswith(
                                metric[: len(metric) - len(sfx)])):
                            labels_cell = cell
                            break
                    if labels_cell is not None:
                        break
            if labels_cell is None:
                findings.append(sf.finding(
                    self.code, line,
                    f"metric {metric} is registered but missing from the "
                    "README metric catalog"))
                continue
            for label in labels:
                if label not in labels_cell:
                    findings.append(sf.finding(
                        self.code, line,
                        f"metric {metric} label `{label}` is not in its "
                        "README catalog row's label column"))
        return findings


# --- SW006 -----------------------------------------------------------------


class WalEventExhaustiveness(Rule):
    code = "SW006"
    title = "ev_* journal event without replay/compaction/replication handling"

    def check(self, project: Project) -> list[Finding]:
        sf = project.file(config.JOURNAL_FILE)
        if sf is None or sf.tree is None:
            return []
        constructors: dict[str, tuple[str, int]] = {}  # fn -> (ev, line)
        apply_fn = snapshot_fn = None
        for node in sf.tree.body:
            if not isinstance(node, ast.FunctionDef):
                continue
            if node.name == "apply_events":
                apply_fn = node
            elif node.name == "snapshot_events":
                snapshot_fn = node
            elif node.name.startswith("ev_"):
                ev_type = None
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Dict):
                        for k, v in zip(sub.keys, sub.values):
                            if (isinstance(k, ast.Constant)
                                    and k.value == "ev"
                                    and isinstance(v, ast.Constant)):
                                ev_type = str(v.value)
                if ev_type:
                    constructors[node.name] = (ev_type, node.lineno)

        replayed: set[str] = set()
        if apply_fn is not None:
            known = {ev for ev, _ in constructors.values()}
            for sub in ast.walk(apply_fn):
                if isinstance(sub, ast.Constant) and sub.value in known:
                    replayed.add(sub.value)
        compacted: set[str] = set()
        if snapshot_fn is not None:
            for sub in ast.walk(snapshot_fn):
                if isinstance(sub, ast.Call):
                    _, name = _call_target(sub)
                    if name in constructors:
                        compacted.add(name)

        findings: list[Finding] = []
        for fn_name, (ev_type, line) in sorted(constructors.items()):
            if ev_type not in replayed:
                findings.append(sf.finding(
                    self.code, line,
                    f"journal event '{ev_type}' ({fn_name}) has no "
                    "replay branch in apply_events — a crash or standby "
                    "would silently drop this transition"))
            if fn_name not in compacted:
                findings.append(sf.finding(
                    self.code, line,
                    f"journal event '{ev_type}' ({fn_name}) is never "
                    "emitted by snapshot_events — compaction would "
                    "erase this transition from the stream"))
        # replication must ride the same apply path recovery uses
        repl = project.file(config.REPLICATION_FILE)
        if repl is not None and "apply_events" not in repl.text:
            findings.append(sf.finding(
                self.code, 1,
                "replication no longer applies the stream through "
                "journal.apply_events — the standby's correctness "
                "argument (same path as recovery) is broken"))
        return findings


# --- SW007 -----------------------------------------------------------------


_DICT_CTORS = {"dict", "OrderedDict", "defaultdict"}


class UnboundedCacheDict(Rule):
    code = "SW007"
    title = "cache dict with no eviction (byte/entry cap) in its module"

    @staticmethod
    def _target_name(node) -> str | None:
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute):
            return node.attr
        return None

    def check(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        sub = config.CACHE_NAME_SUBSTRING
        for rel, sf in sorted(project.files.items()):
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign) and node.value:
                    targets, value = [node.target], node.value
                else:
                    continue
                if isinstance(value, ast.Dict):
                    if value.keys:  # literal with entries: a lookup
                        continue    # table, not an accumulating cache
                elif isinstance(value, ast.Call):
                    _, ctor = _call_target(value)
                    if ctor not in _DICT_CTORS:
                        continue  # a cache class is presumed bounded
                else:
                    continue
                for target in targets:
                    name = self._target_name(target)
                    if name is None:
                        continue
                    if (sub not in name.lower()
                            and name not in config.CACHE_EXTRA_NAMES):
                        continue
                    if re.search(
                            rf"(?<![A-Za-z0-9_]){re.escape(name)}\s*\.\s*"
                            r"popitem", sf.text):
                        continue  # LRU eviction present in this module
                    findings.append(sf.finding(
                        self.code, node.lineno,
                        f"cache dict `{name}` has no eviction in "
                        f"{rel} — every growth axis needs a byte or "
                        "entry cap (popitem LRU) or an explicit "
                        "suppression arguing why it is bounded"))
        return findings


# --- SW008 -----------------------------------------------------------------


_SWALLOWABLE = {"BaseException", "CancelledError"}


class ExceptionHygiene(Rule):
    code = "SW008"
    title = "bare except / swallowed CancelledError in a coroutine"

    @staticmethod
    def _catches_swallowable(handler: ast.ExceptHandler) -> str | None:
        t = handler.type
        names = []
        if t is None:
            return "bare except"
        elts = t.elts if isinstance(t, ast.Tuple) else [t]
        for e in elts:
            if isinstance(e, ast.Name):
                names.append(e.id)
            elif isinstance(e, ast.Attribute):
                names.append(e.attr)
        hit = sorted(set(names) & _SWALLOWABLE)
        return f"except {hit[0]}" if hit else None

    @staticmethod
    def _reraises(handler: ast.ExceptHandler) -> bool:
        return any(isinstance(n, ast.Raise) for n in ast.walk(handler))

    def check(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for rel, sf in sorted(project.files.items()):
            if sf.tree is None:
                continue
            # every handler lexically inside an async def swallows
            # cancellation for the whole task tree above it
            async_handlers: set[int] = set()
            for fn in ast.walk(sf.tree):
                if isinstance(fn, ast.AsyncFunctionDef):
                    for n in ast.walk(fn):
                        if isinstance(n, ast.ExceptHandler):
                            async_handlers.add(id(n))
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if node.type is None:
                    findings.append(sf.finding(
                        self.code, node.lineno,
                        "bare `except:` catches SystemExit/"
                        "KeyboardInterrupt/CancelledError; catch "
                        "Exception (or narrower) instead"))
                    continue
                if id(node) not in async_handlers:
                    continue
                caught = self._catches_swallowable(node)
                if caught and not self._reraises(node):
                    findings.append(sf.finding(
                        self.code, node.lineno,
                        f"`{caught}` inside a coroutine swallows task "
                        "cancellation; re-raise CancelledError or "
                        "narrow the handler"))
        return findings


RULES: dict[str, Rule] = {
    r.code: r for r in (
        JaxFreePurity(), AsyncBlockingCalls(), HiveClockDiscipline(),
        SettingsKnobDrift(), MetricCatalogDrift(),
        WalEventExhaustiveness(), UnboundedCacheDict(), ExceptionHygiene(),
    )
}
