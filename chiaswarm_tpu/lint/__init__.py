"""swarmlint: repo-native static analysis for the swarm's load-bearing invariants.

Five of the last six PRs needed review-hardening passes for the same
recurring bug classes: blocking work on the asyncio event loop, wall-vs-
monotonic clock misuse outside HiveClock, jax imports leaking into
modules that must stay importable from chip-less hosts, and drift
between code, the conformance twin, and the README knob/metric
catalogs. Those invariants are the correctness substrate under every
ROADMAP scaling item, so this package encodes them as machine-checked
rules instead of reviewer folklore.

Usage:

    python -m chiaswarm_tpu.lint            # text report, exit 1 on findings
    python -m chiaswarm_tpu.lint --json     # machine-readable report

Per-line suppression (the flagged line itself):

    now = time.time()  # swarmlint: disable=SW003 -- wall clock needed: ...

Grandfathered findings live in ``chiaswarm_tpu/lint/baseline.json``;
the runner exits 0 while every finding is baselined, and
``tests/test_lint.py`` pins that the baseline only ever shrinks.

The package is deliberately stdlib-only (ast + tokenize + json): it must
run on the same chip-less hosts the hive coordinates from, and in CI
before any accelerator dependency is importable.

Rules:

==== =====================================================================
SW001 jax/flax/torch import purity for declared jax-free modules,
      checked transitively over the first-party MODULE-LEVEL import graph
SW002 blocking calls (time.sleep, sync file I/O, subprocess, file-handle
      json codec) inside ``async def`` bodies not routed through an executor
SW003 clock discipline: direct time.time()/time.monotonic() in
      hive_server/ outside clock.py (HiveClock is the one timebase)
SW004 Settings-knob drift: every Settings field needs an env override,
      a README knob-table row, and a tests/test_settings.py reference
SW005 metric-catalog drift: every registered swarm_* metric must appear
      in the README catalog with a consistent label set
SW006 WAL-event exhaustiveness: every ev_* journal event type needs
      replay (apply_events) and compaction (snapshot_events) handling;
      the replication stream rides the same apply path
SW007 unbounded cache dicts: a dict assigned to a cache-named target
      with no eviction (popitem) in the same file
SW008 bare ``except``; and handlers that swallow CancelledError /
      BaseException inside coroutines without re-raising
==== =====================================================================
"""

from .core import Baseline, Finding, LintResult, Project, run_lint
from .rules import RULES

__all__ = [
    "Baseline",
    "Finding",
    "LintResult",
    "Project",
    "RULES",
    "run_lint",
]
