"""`python -m chiaswarm_tpu.lint` — run swarmlint over the repo.

Exit codes: 0 clean (every finding suppressed or baselined), 1 findings
(or stale baseline entries — paid-off debt must be deleted), 2 bad
usage. `--json` emits the full machine-readable verdict for CI and the
chaos-smoke self-check.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .core import DEFAULT_BASELINE, Baseline, run_lint
from .rules import RULES


def _default_root() -> Path:
    # chiaswarm_tpu/lint/__main__.py -> repo root two packages up
    return Path(__file__).resolve().parents[2]


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m chiaswarm_tpu.lint",
        description="swarmlint: repo-native invariant checks (SW001-SW008)")
    parser.add_argument("--root", type=Path, default=None,
                        help="repo root to lint (default: this checkout)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable report on stdout")
    parser.add_argument("--baseline", type=Path, default=None,
                        help=f"baseline file (default: {DEFAULT_BASELINE})")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline (report everything)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule codes to run "
                             "(default: all)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for code in sorted(RULES):
            print(f"{code}  {RULES[code].title}")
        return 0

    selected = None
    if args.rules:
        wanted = {c.strip().upper() for c in args.rules.split(",") if c.strip()}
        unknown = wanted - set(RULES)
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        selected = {c: RULES[c] for c in wanted}

    root = args.root or _default_root()
    if not root.is_dir():
        print(f"not a directory: {root}", file=sys.stderr)
        return 2
    baseline = (Baseline() if args.no_baseline
                else Baseline.load(args.baseline or DEFAULT_BASELINE))
    result = run_lint(root, baseline=baseline, rules=selected)

    if args.as_json:
        print(json.dumps(result.as_dict(), indent=2))
    else:
        for f in result.parse_errors + result.findings:
            print(f.render())
        for key in result.stale_baseline:
            print(f"stale baseline entry (finding fixed — delete it): {key}")
        n = len(result.findings)
        print(f"swarmlint: {n} finding(s), "
              f"{len(result.baselined)} baselined, "
              f"{result.suppressed_count} suppressed, "
              f"{len(result.stale_baseline)} stale baseline entr(ies)")
    return 0 if result.clean and not result.stale_baseline else 1


if __name__ == "__main__":
    sys.exit(main())
