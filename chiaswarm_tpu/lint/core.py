"""swarmlint engine: file discovery, suppressions, import graph, baseline.

Stdlib-only by contract (see package docstring). The engine is rule-
agnostic: it loads every scanned file once (source + AST + suppression
map), exposes a first-party MODULE-LEVEL import graph, and applies the
suppression / baseline bookkeeping uniformly so every rule gets the
same workflow for free.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path

from . import config

# end-of-line suppression: `# swarmlint: disable=SW003` (comma-separated
# for several rules). An optional ` -- reason` tail is encouraged.
_SUPPRESS_RE = re.compile(r"#\s*swarmlint:\s*disable=([A-Z0-9,\s]+)")

_RULE_CODE_RE = re.compile(r"SW\d{3}")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative posix path
    line: int
    message: str
    anchor: str  # normalized source line: the baseline identity survives
    # line-number churn from unrelated edits

    @property
    def key(self) -> str:
        return f"{self.rule}|{self.path}|{self.anchor}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "key": self.key}


class SourceFile:
    """One scanned file: source text, parse tree (None on syntax error),
    and the per-line suppression map."""

    def __init__(self, abspath: Path, rel: str):
        self.abspath = abspath
        self.rel = rel
        self.text = abspath.read_text(encoding="utf-8", errors="replace")
        self.lines = self.text.splitlines()
        try:
            self.tree: ast.Module | None = ast.parse(self.text)
            self.parse_error: str | None = None
        except SyntaxError as e:
            self.tree = None
            self.parse_error = f"{e.msg} (line {e.lineno})"
        self.suppress: dict[int, set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                self.suppress[i] = set(_RULE_CODE_RE.findall(m.group(1)))

    def anchor(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            text = " ".join(self.lines[line - 1].split())
            if text:
                return text[:160]
        return f"L{line}"

    def finding(self, rule: str, line: int, message: str) -> Finding:
        return Finding(rule, self.rel, line, message, self.anchor(line))

    def suppressed(self, finding: Finding) -> bool:
        return finding.rule in self.suppress.get(finding.line, ())


class Project:
    """The scanned tree plus the first-party import graph.

    ``root`` is the repository root; rule fixtures point it at a temp
    tree mirroring the real layout, which is how every rule gets
    positive-case tests without planting findings in the real repo.
    """

    def __init__(self, root: str | Path, scan_paths=config.SCAN_PATHS):
        self.root = Path(root)
        self.files: dict[str, SourceFile] = {}
        for top in scan_paths:
            base = self.root / top
            if base.is_file() and base.suffix == ".py":
                self._add(base)
            elif base.is_dir():
                for p in sorted(base.rglob("*.py")):
                    if any(part in config.EXCLUDE_DIRS
                           for part in p.parts):
                        continue
                    self._add(p)
        # dotted module name -> SourceFile (tools/ scripts count as the
        # pseudo-package `tools` so relative chains resolve uniformly)
        self.modules: dict[str, SourceFile] = {}
        for rel, sf in self.files.items():
            self.modules[self.module_name(rel)] = sf

    def _add(self, p: Path) -> None:
        rel = p.relative_to(self.root).as_posix()
        self.files[rel] = SourceFile(p, rel)

    @staticmethod
    def module_name(rel: str) -> str:
        name = rel[:-3] if rel.endswith(".py") else rel
        return name.replace("/", ".")

    def file(self, rel: str) -> SourceFile | None:
        return self.files.get(rel)

    def read_text(self, rel: str) -> str | None:
        """Project-context text file (README, tests) — not scanned, not
        linted, but several drift rules compare against them."""
        p = self.root / rel
        try:
            return p.read_text(encoding="utf-8", errors="replace")
        except OSError:
            return None

    # --- first-party module-level import graph ---

    def toplevel_imports(self, sf: SourceFile) -> list[tuple[str, int]]:
        """(dotted target, line) for every MODULE-LEVEL import: module
        body, recursing through top-level if/try/class blocks (those
        execute at import time) but never into function bodies (lazy
        imports are the sanctioned worker-side escape hatch). Blocks
        guarded by ``if TYPE_CHECKING:`` never execute and are skipped.
        """
        if sf.tree is None:
            return []
        pkg = self.module_name(sf.rel).split(".")[:-1]
        out: list[tuple[str, int]] = []

        def visit(body) -> None:
            for node in body:
                if isinstance(node, ast.Import):
                    for alias in node.names:
                        out.append((alias.name, node.lineno))
                elif isinstance(node, ast.ImportFrom):
                    if node.level:
                        base = pkg[: len(pkg) - (node.level - 1)]
                        stem = ".".join(
                            base + ([node.module] if node.module else []))
                    else:
                        stem = node.module or ""
                    if not stem:
                        continue
                    out.append((stem, node.lineno))
                    # `from pkg import sub` may bind a submodule
                    for alias in node.names:
                        out.append((f"{stem}.{alias.name}", node.lineno))
                elif isinstance(node, ast.If):
                    if "TYPE_CHECKING" in ast.dump(node.test):
                        continue
                    visit(node.body)
                    visit(node.orelse)
                elif isinstance(node, ast.Try):
                    visit(node.body)
                    visit(node.orelse)
                    visit(node.finalbody)
                    for handler in node.handlers:
                        visit(handler.body)
                elif isinstance(node, ast.ClassDef):
                    visit(node.body)
        visit(sf.tree.body)
        return out

    def resolve_first_party(self, dotted: str) -> str | None:
        """Dotted import target -> module name in this project, or None
        for third-party / stdlib. `pkg.name` resolves to `pkg.name`,
        `pkg.name.__init__`, or (an attribute import) its parent."""
        for cand in (dotted, f"{dotted}.__init__"):
            if cand in self.modules:
                return cand
        if "." in dotted:
            parent = dotted.rsplit(".", 1)[0]
            for cand in (parent, f"{parent}.__init__"):
                if cand in self.modules:
                    return cand
        return None


class Baseline:
    """The checked-in grandfather file: a sorted list of finding keys.

    Keys use the normalized source line as identity (see Finding.anchor)
    so unrelated edits moving line numbers don't churn the file. The
    workflow is one-way by policy — tests/test_lint.py pins that this
    file only ever shrinks."""

    def __init__(self, keys=()):
        self.keys = set(keys)

    @classmethod
    def load(cls, path: str | Path) -> "Baseline":
        try:
            raw = json.loads(Path(path).read_text())
        except (OSError, json.JSONDecodeError):
            return cls()
        return cls(raw.get("findings", []))

    def save(self, path: str | Path) -> None:
        Path(path).write_text(json.dumps(
            {"findings": sorted(self.keys)}, indent=2) + "\n")

    def split(self, findings: list[Finding]
              ) -> tuple[list[Finding], list[Finding], list[str]]:
        """(new, grandfathered, stale-keys): stale keys are baseline
        entries matching no current finding — the debt was paid, so the
        entry must be deleted (the shrink-only test enforces it)."""
        new = [f for f in findings if f.key not in self.keys]
        old = [f for f in findings if f.key in self.keys]
        live = {f.key for f in findings}
        stale = sorted(k for k in self.keys if k not in live)
        return new, old, stale


DEFAULT_BASELINE = Path(__file__).with_name("baseline.json")


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]        # non-suppressed, non-baselined
    baselined: list[Finding]
    suppressed_count: int
    stale_baseline: list[str]
    parse_errors: list[Finding]

    @property
    def clean(self) -> bool:
        return not self.findings and not self.parse_errors

    def as_dict(self) -> dict:
        return {
            "clean": self.clean,
            "findings": [f.as_dict() for f in self.findings],
            "baselined": [f.as_dict() for f in self.baselined],
            "suppressed": self.suppressed_count,
            "stale_baseline": self.stale_baseline,
            "parse_errors": [f.as_dict() for f in self.parse_errors],
            "counts": _counts(self.findings),
        }


def _counts(findings: list[Finding]) -> dict[str, int]:
    out: dict[str, int] = {}
    for f in findings:
        out[f.rule] = out.get(f.rule, 0) + 1
    return out


def run_lint(root: str | Path, baseline: Baseline | None = None,
             rules: dict | None = None,
             scan_paths=config.SCAN_PATHS) -> LintResult:
    """Run every rule over the tree at ``root``; apply suppressions and
    the baseline; return the full verdict. Rule callables get the
    Project and return raw findings — everything workflow-shaped
    happens here, once."""
    from .rules import RULES

    project = Project(root, scan_paths=scan_paths)
    parse_errors = [
        sf.finding("SW000", 1, f"syntax error: {sf.parse_error}")
        for sf in project.files.values() if sf.parse_error
    ]
    raw: list[Finding] = []
    for code, rule in sorted((rules or RULES).items()):
        raw.extend(rule.check(project))
    raw.sort(key=lambda f: (f.path, f.line, f.rule))

    kept: list[Finding] = []
    suppressed = 0
    for f in raw:
        sf = project.file(f.path)
        if sf is not None and sf.suppressed(f):
            suppressed += 1
        else:
            kept.append(f)

    baseline = baseline or Baseline()
    new, old, stale = baseline.split(kept)
    # a narrowed run (--rules SW00x) cannot judge other rules' baseline
    # entries stale — only rules that actually ran produce findings
    ran = set((rules or RULES).keys())
    stale = [k for k in stale if k.split("|", 1)[0] in ran]
    return LintResult(new, old, suppressed, stale, parse_errors)
