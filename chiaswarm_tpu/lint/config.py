"""Repo-native lint configuration: the invariants, spelled as data.

swarmlint is not a general-purpose linter — every constant here names a
specific contract this repository's architecture depends on. Changing a
value below is changing an invariant; do it in the PR that changes the
architecture, with the reasoning in the commit.
"""

from __future__ import annotations

# directories (relative to the repo root) whose *.py files are linted;
# tests/ is deliberately excluded — fixtures embed rule-positive snippets
SCAN_PATHS = ("chiaswarm_tpu", "tools")

# directory names never descended into
EXCLUDE_DIRS = ("__pycache__",)

# --- SW001: jax purity ------------------------------------------------------

# top-level package names that must never be imported (at module level,
# transitively) from the jax-free roots: the hive coordinates from
# chip-less hosts, so its import closure must not pull an accelerator
# runtime. Function-local (lazy) imports are the sanctioned escape hatch
# and are NOT counted — they only execute on worker-side call paths.
ACCELERATOR_PACKAGES = ("jax", "jaxlib", "flax", "torch", "transformers",
                        "diffusers")

# modules / packages (repo-relative paths) declared jax-free. A path
# naming a directory covers every module under it.
JAXFREE_ROOTS = (
    "chiaswarm_tpu/hive_server",
    "chiaswarm_tpu/coalesce.py",
    "chiaswarm_tpu/telemetry.py",
    "chiaswarm_tpu/outbox.py",
    "chiaswarm_tpu/settings.py",
    "chiaswarm_tpu/faults.py",
    "chiaswarm_tpu/log_setup.py",
    "tools/swarm_top.py",
    "tools/hive_serve.py",
)

# --- SW002: event-loop blocking calls ---------------------------------------

# (module, attr) calls that block the calling thread; inside an
# ``async def`` body they stall every coroutine on the loop (heartbeats,
# cancel piggybacks, /metrics scrapes). Route them through
# run_in_executor / asyncio.to_thread instead.
BLOCKING_MODULE_CALLS = frozenset({
    ("time", "sleep"),
    ("subprocess", "run"),
    ("subprocess", "call"),
    ("subprocess", "check_call"),
    ("subprocess", "check_output"),
    ("subprocess", "Popen"),
    ("os", "fsync"),
    ("os", "system"),
    ("os", "popen"),
    # file-handle json codec: parsing a multi-MB result envelope on the
    # loop was the recurring bug SW002 exists for. The string variants
    # (loads/dumps) are left to review — small control payloads are fine
    # and the hive already routes big bodies through asyncio.to_thread.
    ("json", "load"),
    ("json", "dump"),
    ("socket", "create_connection"),
    ("urllib", "urlopen"),
    ("requests", "get"),
    ("requests", "post"),
})

# method names that are sync file I/O whatever the receiver (the pathlib
# idiom this repo uses everywhere)
BLOCKING_METHOD_NAMES = frozenset({
    "read_text", "read_bytes", "write_text", "write_bytes",
})

# bare-name calls that block (the builtin)
BLOCKING_NAME_CALLS = frozenset({"open"})

# --- SW003: clock discipline ------------------------------------------------

HIVE_SERVER_DIR = "chiaswarm_tpu/hive_server"
CLOCK_MODULE = "chiaswarm_tpu/hive_server/clock.py"
# the two faces HiveClock wraps; time.perf_counter for pure local
# durations is allowed (it never crosses a persistence or API boundary)
CLOCK_CALLS = frozenset({("time", "time"), ("time", "monotonic")})

# --- SW004 / SW005 / SW006: drift rules -------------------------------------

SETTINGS_FILE = "chiaswarm_tpu/settings.py"
README_FILE = "README.md"
SETTINGS_TEST_FILE = "tests/test_settings.py"
JOURNAL_FILE = "chiaswarm_tpu/hive_server/journal.py"
REPLICATION_FILE = "chiaswarm_tpu/hive_server/replication.py"

# metric registrations are collected from the package only — tools/ and
# tests/ READ exposition text and would contribute false names
METRICS_SCAN_PREFIX = "chiaswarm_tpu"
METRIC_PREFIX = "swarm_"

# --- SW007: unbounded caches ------------------------------------------------

# a dict/OrderedDict/defaultdict assigned to a target whose name matches
# this substring (case-insensitive) is presumed a cache and must show
# eviction (.popitem) somewhere in the same file
CACHE_NAME_SUBSTRING = "cache"
# cache dicts whose names don't say so (the PR 13 compiled-program
# variants that motivated this rule)
CACHE_EXTRA_NAMES = frozenset({"_programs"})
