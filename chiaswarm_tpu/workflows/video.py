"""Video workloads: txt2vid, img2vid, vid2vid (reference swarm/video/*).

txt2vid: AnimateDiff-style motion-module diffusion (swarm/video/tx2vid.py).
img2vid: image-conditioned video (swarm/video/img2vid.py).
vid2vid: per-frame instruct-pix2pix over a downloaded clip — on TPU the
frames are processed as one batched denoise instead of the reference's
sequential Python loop (swarm/video/pix2pix.py:47-68).
"""

from __future__ import annotations


def txt2vid_callback(device_identifier: str, model_name: str, **kwargs):
    from ..pipelines.video import run_txt2vid

    return run_txt2vid(device_identifier, model_name, **kwargs)


def img2vid_callback(device_identifier: str, model_name: str, **kwargs):
    from ..pipelines.video import run_img2vid

    return run_img2vid(device_identifier, model_name, **kwargs)


def vid2vid_callback(device_identifier: str, model_name: str, **kwargs):
    from ..pipelines.video import run_vid2vid

    return run_vid2vid(device_identifier, model_name, **kwargs)
