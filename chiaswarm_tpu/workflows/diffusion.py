"""Stable-diffusion-family workload callback.

The TPU rebuild of reference swarm/diffusion/diffusion_func.py:15-167. Where
the reference re-runs `from_pretrained` on every job, this callback resolves
(model, pipeline_type, shape bucket) against the residency registry
(`..registry`) and invokes an already-compiled jitted program; weights stay
on-chip between jobs.
"""

from __future__ import annotations

from ..post_processors.output_processor import OutputProcessor
from ..registry import get_pipeline


def diffusion_callback(device_identifier: str, model_name: str, **kwargs):
    content_type = kwargs.pop("content_type", "image/jpeg")
    outputs = kwargs.pop("outputs", ["primary"])
    # classical-stand-in annotators used for conditioning (job_arguments
    # _flag_degraded) surface in the result envelope, not just the logs
    degraded_preprocessors = kwargs.pop("degraded_preprocessors", None)

    if kwargs.pop("test_tiny_model", False):
        # hermetic test hook (SURVEY §4): serve the job with the tiny
        # random-weight stand-in of the requested architecture family
        from ..models.configs import model_family

        name = model_name.lower()
        if "pix2pix" in name or "ip2p" in name:
            model_name = "test/tiny-pix2pix"  # keep the 8-channel edit arch
        elif "flux" in name:
            model_name = (
                "test/tiny-flux-schnell" if "schnell" in name else "test/tiny-flux"
            )
        elif "kandinsky-3" in name or "kandinsky3" in name:
            model_name = "test/tiny-kandinsky3"
        elif "kandinsky" in name:
            if "controlnet" in name:
                model_name = "test/tiny-kandinsky-controlnet"
            elif "prior" in name:
                model_name = "test/tiny-kandinsky-prior"
            else:
                model_name = "test/tiny-kandinsky"
        elif "cascade" in name:
            model_name = (
                "test/tiny-cascade-prior" if "prior" in name
                else "test/tiny-cascade"
            )
        elif "xl" in model_family(model_name):
            model_name = "test/tiny-xl"
        else:
            model_name = "test/tiny-sd"

    pipeline_type = kwargs.pop("pipeline_type", "DiffusionPipeline")

    # capacity gate BEFORE residency: a model that cannot fit this slice is
    # a fatal job error naming the chip count it needs; a batch that does
    # not fit is capped (the TPU-native analog of the reference's
    # offload/slicing knobs — chips/requirements.py)
    from ..chips.requirements import check_capacity

    chipset = kwargs.get("chipset")
    requested_batch = int(kwargs.get("num_images_per_prompt", 1) or 1)
    # canvas: explicit dims, else the start image's (img2img/inpaint jobs
    # drop height/width during formatting), else the 1024 family default
    from ..chips.requirements import default_canvas

    height = kwargs.get("height")
    width = kwargs.get("width")
    image = kwargs.get("image")
    if (height is None or width is None) and image is not None:
        probe = image[0] if isinstance(image, list) else image
        if hasattr(probe, "size"):
            width, height = probe.size
    height = int(height or default_canvas(model_name))
    width = int(width or height)
    batch_capped = None
    if chipset is not None:
        allowed = check_capacity(
            chipset, model_name, requested_batch, height, width
        )
        if allowed < requested_batch:
            kwargs["num_images_per_prompt"] = allowed
            batch_capped = {"requested": requested_batch, "served": allowed}

    pipeline = get_pipeline(
        model_name, pipeline_type=pipeline_type, chipset=chipset
    )
    images, pipeline_config = pipeline.run(pipeline_type=pipeline_type, **kwargs)
    if batch_capped:
        pipeline_config["batch_capped"] = batch_capped
    if degraded_preprocessors:
        pipeline_config["degraded_preprocessors"] = degraded_preprocessors

    # real NSFW detection on the decoded pixels (reference envelope parity:
    # swarm/worker.py:166); auxiliary — never fails the job
    from ..pipelines.safety import flag_images

    nsfw, checked = flag_images(images)
    pipeline_config["nsfw"] = nsfw
    pipeline_config["nsfw_checked"] = checked

    processor = OutputProcessor(outputs, content_type)
    processor.add_outputs(images)
    return processor.get_results(), pipeline_config


def deepfloyd_if_callback(device_identifier: str, model_name: str, **kwargs):
    """DeepFloyd IF jobs dispatch early (job_arguments.py:78-81, mirroring
    reference :49-50), so the raw job `parameters` still ride in kwargs.
    The reference's own IF path (diffusion_func_if.py:13-69) shipped broken
    — random prompt embeds, NameError at :62; this cascade works."""
    parameters = kwargs.pop("parameters", {}) or {}
    content_type = kwargs.pop("content_type", "image/jpeg")
    outputs = kwargs.pop("outputs", ["primary"])
    if parameters.pop("test_tiny_model", False) or kwargs.pop(
        "test_tiny_model", False
    ):
        model_name = "test/tiny-if"
    pipeline_type = parameters.pop("pipeline_type", "IFPipeline")
    kwargs.update(parameters)
    kwargs.pop("start_image_uri", None)  # base stage is txt2img-only

    pipeline = get_pipeline(
        model_name, pipeline_type=pipeline_type, chipset=kwargs.get("chipset")
    )
    images, pipeline_config = pipeline.run(pipeline_type=pipeline_type, **kwargs)

    from ..pipelines.safety import flag_images

    nsfw, checked = flag_images(images)
    pipeline_config["nsfw"] = nsfw
    pipeline_config["nsfw_checked"] = checked

    processor = OutputProcessor(outputs, content_type)
    processor.add_outputs(images)
    return processor.get_results(), pipeline_config
