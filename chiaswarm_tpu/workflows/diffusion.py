"""Stable-diffusion-family workload callback.

The TPU rebuild of reference swarm/diffusion/diffusion_func.py:15-167. Where
the reference re-runs `from_pretrained` on every job, this callback resolves
(model, pipeline_type, shape bucket) against the residency registry
(`..registry`) and invokes an already-compiled jitted program; weights stay
on-chip between jobs.
"""

from __future__ import annotations

from ..post_processors.output_processor import OutputProcessor
from ..registry import get_pipeline
from ..telemetry import Span


def _tiny_stand_in(model_name: str) -> str:
    """hermetic test hook (SURVEY §4): the tiny random-weight stand-in of
    the requested architecture family (`test_tiny_model` job parameter)."""
    from ..models.configs import model_family

    name = model_name.lower()
    if "pix2pix" in name or "ip2p" in name:
        return "test/tiny-pix2pix"  # keep the 8-channel edit arch
    if "flux" in name:
        return "test/tiny-flux-schnell" if "schnell" in name else "test/tiny-flux"
    if "kandinsky-3" in name or "kandinsky3" in name:
        return "test/tiny-kandinsky3"
    if "kandinsky" in name:
        if "controlnet" in name:
            return "test/tiny-kandinsky-controlnet"
        if "prior" in name:
            return "test/tiny-kandinsky-prior"
        return "test/tiny-kandinsky"
    if "cascade" in name:
        return (
            "test/tiny-cascade-prior" if "prior" in name else "test/tiny-cascade"
        )
    if "xl" in model_family(model_name):
        return "test/tiny-xl"
    return "test/tiny-sd"


def diffusion_callback(device_identifier: str, model_name: str, **kwargs):
    content_type = kwargs.pop("content_type", "image/jpeg")
    outputs = kwargs.pop("outputs", ["primary"])
    # stage-graph handoff (ISSUE 20): a denoise stage-job skips the
    # host-side decode tail and emits raw rows for its successor stage
    emit_raw = bool(kwargs.pop("emit_raw", False))
    # classical-stand-in annotators used for conditioning (job_arguments
    # _flag_degraded) surface in the result envelope, not just the logs
    degraded_preprocessors = kwargs.pop("degraded_preprocessors", None)

    if kwargs.pop("test_tiny_model", False):
        model_name = _tiny_stand_in(model_name)

    pipeline_type = kwargs.pop("pipeline_type", "DiffusionPipeline")

    # capacity gate BEFORE residency: a model that cannot fit this slice is
    # a fatal job error naming the chip count it needs; a batch that does
    # not fit is capped (the TPU-native analog of the reference's
    # offload/slicing knobs — chips/requirements.py)
    from ..chips.requirements import check_capacity

    chipset = kwargs.get("chipset")
    requested_batch = int(kwargs.get("num_images_per_prompt", 1) or 1)
    # canvas: explicit dims, else the start image's (img2img/inpaint jobs
    # drop height/width during formatting), else the 1024 family default
    from ..chips.requirements import default_canvas

    height = kwargs.get("height")
    width = kwargs.get("width")
    image = kwargs.get("image")
    if (height is None or width is None) and image is not None:
        probe = image[0] if isinstance(image, list) else image
        if hasattr(probe, "size"):
            width, height = probe.size
    height = int(height or default_canvas(model_name))
    width = int(width or height)
    batch_capped = None
    if chipset is not None:
        allowed = check_capacity(
            chipset, model_name, requested_batch, height, width
        )
        if allowed < requested_batch:
            kwargs["num_images_per_prompt"] = allowed
            batch_capped = {"requested": requested_batch, "served": allowed}

    # class-aware slice geometry (ISSUE 12): the worker attaches these
    # for interactive solos on multi-chip slices; forwarded only to
    # pipelines that understand per-pass mesh views (SD family) so a
    # kandinsky/cascade job routed through this callback is unaffected
    geometry = kwargs.pop("geometry", None)
    reshard_probe = kwargs.pop("reshard_probe", None)

    # mid-pass durability seam (ISSUE 18): the worker attaches these for
    # checkpoint-armed solos; forwarded only to pipelines whose chunked
    # runner exposes the boundary (`supports_checkpoint`), so other
    # families routed through this callback run untouched
    ckpt_kwargs = {
        key: kwargs.pop(key)
        for key in ("checkpoint_every_chunks", "preview_every_chunks",
                    "checkpoint_cb", "preview_cb", "resume")
        if key in kwargs
    }

    pipeline = get_pipeline(
        model_name, pipeline_type=pipeline_type, chipset=chipset
    )
    if geometry is not None and hasattr(pipeline, "resolve_geometry"):
        kwargs["geometry"] = geometry
        if reshard_probe is not None:
            kwargs["reshard_probe"] = reshard_probe
    if ckpt_kwargs and getattr(pipeline, "supports_checkpoint", False):
        kwargs.update(ckpt_kwargs)
    images, pipeline_config = pipeline.run(pipeline_type=pipeline_type, **kwargs)
    if batch_capped:
        pipeline_config["batch_capped"] = batch_capped
    if degraded_preprocessors:
        pipeline_config["degraded_preprocessors"] = degraded_preprocessors

    if emit_raw:
        from .stages import pack_raw

        with Span("handoff", pipeline_config.setdefault("timings", {})):
            packaged = {"raw": pack_raw(images)}
        return packaged, pipeline_config

    # real NSFW detection on the decoded pixels (reference envelope parity:
    # swarm/worker.py:166); auxiliary — never fails the job
    from ..pipelines.safety import flag_images

    # stage "decode": host-side postprocess (NSFW check + grid composite +
    # encode) after the on-device decode that ends the denoise program
    with Span("decode", pipeline_config.setdefault("timings", {})):
        nsfw, checked = flag_images(images)
        pipeline_config["nsfw"] = nsfw
        pipeline_config["nsfw_checked"] = checked

        processor = OutputProcessor(outputs, content_type)
        processor.add_outputs(images)
        results = processor.get_results()
    return results, pipeline_config


def diffusion_batched_callback(device_identifier: str, requests: list[dict]):
    """Cross-job coalesced txt2img/img2img (batching.py design): every
    request in `requests` shares one coalesce key — same model, canvas,
    steps, scheduler, guidance, workflow (and strength for img2img) — and
    differs only per-row (prompt, negative, seed, start image, image
    count). Executes the group in as few padded jitted denoise+decode
    passes as capacity allows (usually one) and returns per-request
    (artifacts, pipeline_config) envelopes in order.

    Raising here (capacity, weights) is fine: the worker falls back to
    the single-job path, which reproduces the error per job with the
    existing fatal/transient attribution.
    """
    from ..chips.requirements import coalesced_fit, default_canvas
    from ..pipelines.common import chunk_by_rows
    from ..pipelines.safety import flag_images

    shared = requests[0]
    model_name = shared["model_name"]
    if shared.get("test_tiny_model", False):
        model_name = _tiny_stand_in(model_name)
    pipeline_type = shared.get("pipeline_type", "DiffusionPipeline")
    chipset = shared.get("chipset")
    # shared ControlNet (ISSUE 13 second rung): coalesce_key guarantees
    # every member carries the IDENTICAL branch + control image, so the
    # group conditions on ONE image — for txt2img-ControlNet wire names
    # the formatter delivered it as `image`, which therefore must not be
    # mistaken for an img2img start image
    cn_name = shared.get("controlnet_model_name")
    control_image = None
    if cn_name:
        control_image = (shared.get("control_image")
                         if shared.get("control_image") is not None
                         else shared.get("image"))
    # None flows through to run_batched, which defaults to the pipeline's
    # own default_size (or, for img2img, the shared start-image canvas) —
    # the same resolution the single path's run() does; the canvas below
    # is only the capacity gate's estimate
    height = shared.get("height")
    width = shared.get("width")
    i2i = shared.get("image") is not None and not cn_name
    if (height is None or width is None) and cn_name \
            and control_image is not None:
        est_w, est_h = control_image.size
    elif (height is None or width is None) and i2i:
        # img2img formatting pops height/width after resizing every start
        # image to the shared explicit canvas — read it back off the image
        est_w, est_h = shared["image"].size
    else:
        est_h = int(height or default_canvas(model_name))
        est_w = int(width or est_h)
    if cn_name and (height is None or width is None):
        # solo ControlNet passes size the canvas to the control image;
        # the shared group must reproduce that, not the family default
        height, width = est_h, est_w
    steps = int(shared.get("num_inference_steps", 30))
    guidance = float(shared.get("guidance_scale", 7.5))
    scheduler_type = shared.get("scheduler_type", "DPMSolverMultistepScheduler")
    karras = bool(shared.get("use_karras_sigmas", False))
    # NB `or`-defaulting would silently rewrite an explicit strength of
    # 0.0 and make the coalesced output diverge from the solo path's
    raw_strength = shared.get("strength")
    strength = 0.75 if raw_strength is None else float(raw_strength)

    # per-request envelope parameters + the run_batched row spec
    envelopes = []
    row_specs = []
    counts = []
    for r in requests:
        envelopes.append({
            "content_type": r.get("content_type", "image/jpeg"),
            "outputs": r.get("outputs", ["primary"]),
            # stage-graph denoise members (ISSUE 20) hand off raw rows;
            # the coalesce key's stage element keeps them from mixing
            # with monolithic jobs, so a group is all-raw or all-packaged
            "emit_raw": bool(r.get("emit_raw")),
        })
        n = max(int(r.get("num_images_per_prompt", 1) or 1), 1)
        counts.append(n)
        xattn = r.get("cross_attention_kwargs") or {}
        row_specs.append({
            "prompt": r.get("prompt", ""),
            "negative_prompt": r.get("negative_prompt", ""),
            "rng": r.get("rng"),
            "num_images_per_prompt": n,
            "image": None if cn_name else r.get("image"),
            # per-row adapter (ISSUE 13): the resolved reference becomes
            # a slot in the batched program's stacked low-rank factors;
            # scale rides per row like the reference's
            # cross_attention_kwargs.scale
            "lora": r.get("lora"),
            "lora_scale": float(r.get("lora_scale",
                                      xattn.get("scale", 1.0)) or 0.0),
            # per-row cancel token key (ISSUE 10): run_batched probes the
            # cancel registry for this id at denoise chunk boundaries
            "job_id": r.get("id"),
        })

    # capacity admits the COALESCED batch, capping rather than rejecting:
    # a group bigger than one pass splits into passes that fit (the
    # batching scheduler already sized groups with coalesce_rows_limit,
    # so more than one chunk means the estimate moved under us)
    max_rows = sum(counts)
    if chipset is not None:
        max_rows = coalesced_fit(chipset, model_name, max_rows, est_h, est_w)
    # per-request cap, mirroring the single path's check_capacity clamp:
    # a request bigger than one pass serves the batch that fits, recorded
    # in its envelope, never silently
    capped: dict[int, dict] = {}
    for i, n in enumerate(counts):
        if n > max_rows:
            capped[i] = {"requested": n, "served": max_rows}
            counts[i] = max_rows
            row_specs[i]["num_images_per_prompt"] = max_rows

    pipeline = get_pipeline(
        model_name, pipeline_type=pipeline_type, chipset=chipset
    )
    cn_kwargs = {}
    if cn_name:
        cn_kwargs = {
            "controlnet_model_name": cn_name,
            "control_image": control_image,
            "controlnet_conditioning_scale": float(
                shared.get("controlnet_conditioning_scale", 1.0)),
            "control_guidance_start": float(
                shared.get("control_guidance_start", 0.0)),
            "control_guidance_end": float(
                shared.get("control_guidance_end", 1.0)),
        }
    results = []
    chunks = list(chunk_by_rows(counts, max_rows))
    if len(chunks) > 1:
        # a group split across passes surfaces every adapter refusal
        # up front: a LATER chunk's refusal would discard earlier
        # chunks' finished denoise work and re-count their row metrics
        # on the worker's re-batch
        prescan = getattr(pipeline, "prescan_adapter_chunks", None)
        if prescan is not None:
            prescan([row_specs[s:e] for s, e in chunks])
    for start, end in chunks:
        results.extend(pipeline.run_batched(
            row_specs[start:end],
            height=height,
            width=width,
            num_inference_steps=steps,
            guidance_scale=guidance,
            scheduler_type=scheduler_type,
            use_karras_sigmas=karras,
            pipeline_type=pipeline_type,
            strength=strength,
            **cn_kwargs,
        ))

    out = []
    for i, ((images, pipeline_config), env) in enumerate(zip(results, envelopes)):
        # classical-CV annotator stand-ins surface per envelope exactly
        # like the solo path (the conditioning image is an approximation)
        if requests[i].get("degraded_preprocessors"):
            pipeline_config["degraded_preprocessors"] = \
                requests[i]["degraded_preprocessors"]
        if pipeline_config.get("cancelled"):
            # hive-revoked mid-denoise: no safety pass, no packaging —
            # the worker drops this slot (no envelope is ever delivered)
            out.append((None, pipeline_config))
            continue
        if env["emit_raw"]:
            from .stages import pack_raw

            with Span("handoff", pipeline_config.setdefault("timings", {})):
                packaged = {"raw": pack_raw(images)}
            pipeline_config["batched_with"] = len(requests)
            if i in capped:
                pipeline_config["batch_capped"] = capped[i]
            out.append((packaged, pipeline_config))
            continue
        with Span("decode", pipeline_config.setdefault("timings", {})):
            nsfw, checked = flag_images(images)
            pipeline_config["nsfw"] = nsfw
            pipeline_config["nsfw_checked"] = checked
            pipeline_config["batched_with"] = len(requests)
            if i in capped:
                pipeline_config["batch_capped"] = capped[i]
            processor = OutputProcessor(env["outputs"], env["content_type"])
            processor.add_outputs(images)
            packaged = processor.get_results()
        out.append((packaged, pipeline_config))
    return out


def deepfloyd_if_callback(device_identifier: str, model_name: str, **kwargs):
    """DeepFloyd IF jobs dispatch early (job_arguments.py:78-81, mirroring
    reference :49-50), so the raw job `parameters` still ride in kwargs.
    The reference's own IF path (diffusion_func_if.py:13-69) shipped broken
    — random prompt embeds, NameError at :62; this cascade works."""
    parameters = kwargs.pop("parameters", {}) or {}
    content_type = kwargs.pop("content_type", "image/jpeg")
    outputs = kwargs.pop("outputs", ["primary"])
    if parameters.pop("test_tiny_model", False) or kwargs.pop(
        "test_tiny_model", False
    ):
        model_name = "test/tiny-if"
    pipeline_type = parameters.pop("pipeline_type", "IFPipeline")
    kwargs.update(parameters)
    kwargs.pop("start_image_uri", None)  # base stage is txt2img-only

    pipeline = get_pipeline(
        model_name, pipeline_type=pipeline_type, chipset=kwargs.get("chipset")
    )
    images, pipeline_config = pipeline.run(pipeline_type=pipeline_type, **kwargs)

    from ..pipelines.safety import flag_images

    nsfw, checked = flag_images(images)
    pipeline_config["nsfw"] = nsfw
    pipeline_config["nsfw_checked"] = checked

    processor = OutputProcessor(outputs, content_type)
    processor.add_outputs(images)
    return processor.get_results(), pipeline_config
