"""Image captioning / VQA workload (reference swarm/captioning/caption_image.py).

BLIP-style: unconditional captioning, or question-conditioned when the job
carries a prompt; result is a JSON text artifact.
"""

from __future__ import annotations

from ..post_processors.output_processor import make_text_result


def caption_callback(device_identifier: str, model_name: str, **kwargs):
    from ..pipelines.captioning import get_caption_pipeline

    image = kwargs.get("image")
    if image is None:
        raise ValueError("img2txt requires an input image. None provided")

    prompt = kwargs.get("prompt") or None
    parameters = kwargs.get("parameters", {})
    if parameters.get("test_tiny_model"):
        is_vqa = (
            "vqa" in model_name.lower()
            or parameters.get("model_type") == "BlipForQuestionAnswering"
        )
        model_name = "test/tiny-blip-vqa" if is_vqa else "test/tiny-blip"
    pipe = get_caption_pipeline(
        model_name,
        chipset=kwargs.get("chipset"),
        model_type=parameters.get("model_type"),
    )
    text, config = pipe.run(image, prompt=prompt)
    return {"primary": make_text_result(text)}, {**config, "caption": text}
