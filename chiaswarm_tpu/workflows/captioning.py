"""Image captioning / VQA workload (reference swarm/captioning/caption_image.py).

BLIP-style: unconditional captioning, or question-conditioned when the job
carries a prompt; result is a JSON text artifact.
"""

from __future__ import annotations

from ..post_processors.output_processor import make_text_result


def caption_callback(device_identifier: str, model_name: str, **kwargs):
    from ..pipelines.captioning import caption_image

    image = kwargs.get("image")
    if image is None:
        raise ValueError("img2txt requires an input image. None provided")

    prompt = kwargs.get("prompt") or None
    parameters = kwargs.get("parameters", {})
    text = caption_image(
        image,
        model_name=model_name,
        prompt=prompt,
        processor_type=parameters.get("processor_type"),
        model_type=parameters.get("model_type"),
    )
    return {"primary": make_text_result(text)}, {"caption": text}
