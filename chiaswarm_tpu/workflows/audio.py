"""Audio workloads: AudioLDM-style txt2audio and Bark TTS.

Reference: swarm/audio/audioldm.py:23-34 (AudioLDM -> wav 16 kHz -> mp3) and
swarm/audio/bark.py:16-21. Artifacts default to content_type "audio/mpeg"
(the reference's default) via the built-in MPEG Layer I encoder
(toolbox/mpeg_audio.py — no pydub/ffmpeg dependency); WAV only on explicit
request or encode failure, with the content type saying so.
"""

from __future__ import annotations


def txt2audio_callback(device_identifier: str, model_name: str, **kwargs):
    from ..pipelines.audio import run_audioldm

    return run_audioldm(device_identifier, model_name, **kwargs)


def bark_callback(device_identifier: str, model_name: str, **kwargs):
    from ..pipelines.bark import run_bark

    return run_bark(device_identifier, model_name, **kwargs)
