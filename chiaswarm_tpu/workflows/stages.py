"""Worker-side stage-job callbacks (ISSUE 20 stage-graph serving).

The hive's workflow expander (hive_server/dag.py) splits a diffusion
request into encode / denoise [/ upscale] / decode stage-jobs. The chip
stages (denoise, upscale) ride the classic diffusion path with the
`emit_raw` handoff flag; the host stages (encode, decode, postprocess)
format to the callbacks here and run on the worker's jax-free stage
lane — so a chip-less host can serve them, and a chip host can overlap
them with the next pass's denoise.

Raw handoff format (`application/x-swarm-raw+json`): a JSON container
of losslessly PNG-encoded rows. Lossless matters — the decode stage
must package pixels identical to what the monolithic path would have
packaged, so the final envelope differs from a single-lease run only
in which host did the work.
"""

from __future__ import annotations

import base64
import hashlib
import io
import json

from PIL import Image

from ..post_processors.output_processor import OutputProcessor
from ..telemetry import Span

RAW_CONTENT_TYPE = "application/x-swarm-raw+json"

# wire workflows whose stage names the dag templates own; explicit-chain
# stages keep their native workflow dispatch (an echo "postprocess"
# stage runs echo, not the diffusion decode)
_DIFFUSION_WORKFLOWS = (None, "txt2img", "img2img")

# workflows that consume a start image, for the handoff="image" seam
_IMAGE_CONSUMERS = ("img2img", "img2vid", "vid2vid", "img2txt")


def pack_raw(images) -> dict:
    """Denoised rows -> ONE raw-handoff artifact (the producing stage's
    whole output travels as a single content-addressed spool blob)."""
    rows = []
    for image in images:
        buffer = io.BytesIO()
        image.save(buffer, format="PNG")
        rows.append(base64.b64encode(buffer.getvalue()).decode("ascii"))
    payload = json.dumps({"format": "png", "images": rows}).encode("utf-8")
    return {
        "blob": base64.b64encode(payload).decode("ascii"),
        "content_type": RAW_CONTENT_TYPE,
        "sha256_hash": hashlib.sha256(payload).hexdigest(),
        "rows": len(rows),
    }


def unpack_raw(payload: bytes) -> list[Image.Image]:
    doc = json.loads(payload.decode("utf-8"))
    return [
        Image.open(io.BytesIO(base64.b64decode(row))).convert("RGB")
        for row in doc.get("images", [])
    ]


def input_blob(inputs, key: str | None = None) -> bytes | None:
    """The newest predecessor artifact blob (optionally by artifact key)
    from a stage-job's hydrated inputs — the worker's poll loop fetched
    each spool href and stamped the bytes back as `blob`."""
    for entry in reversed(list(inputs or [])):
        artifacts = entry.get("artifacts") if isinstance(entry, dict) else None
        if not isinstance(artifacts, dict):
            continue
        for name, art in artifacts.items():
            if key is not None and name != key:
                continue
            blob = art.get("blob") if isinstance(art, dict) else None
            if isinstance(blob, str) and blob:
                try:
                    return base64.b64decode(blob)
                except (ValueError, TypeError):
                    continue
    return None


def stage_images(inputs) -> list[Image.Image]:
    """The image rows a consuming stage works from: the predecessor's
    raw handoff when present, else its packaged primary artifact."""
    payload = input_blob(inputs, key="raw")
    if payload is not None:
        return unpack_raw(payload)
    payload = input_blob(inputs, key="primary")
    if payload is not None:
        return [Image.open(io.BytesIO(payload)).convert("RGB")]
    raise ValueError(
        "stage-job has no input artifacts to work from (predecessor "
        "handoff missing or not yet hydrated)")


async def format_stage_args(stage: dict, workflow, args: dict, settings,
                            device_identifier: str):
    """Route one stage-job. Returns (callback, kwargs) for the host
    stages this module owns, or None to fall through to the classic
    dispatch — with the graph metadata (emit_raw / injected start
    image) already applied to `args`."""
    name = str(stage.get("name") or "")
    inputs = stage.get("inputs") or []
    if workflow in _DIFFUSION_WORKFLOWS:
        if name == "encode":
            args.setdefault("prompt", "")
            args.setdefault("negative_prompt", "")
            return encode_callback, args
        if name in ("decode", "postprocess"):
            args["stage_inputs"] = inputs
            return decode_callback, args
        if name == "upscale":
            args["stage_inputs"] = inputs
            return upscale_stage_callback, args
        if name == "denoise" and stage.get("handoff") == "raw":
            # classic dispatch, raw handoff: the pass skips the host-side
            # packaging and emits rows for the successor stage
            args["emit_raw"] = True
            return None
    if stage.get("handoff") == "image" and inputs \
            and workflow in _IMAGE_CONSUMERS \
            and "start_image_uri" not in args and args.get("image") is None:
        payload = input_blob(inputs, key="primary")
        if payload is not None:
            args["image"] = Image.open(io.BytesIO(payload)).convert("RGB")
    return None


def encode_callback(device_identifier: str, model_name: str, **kwargs):
    """Text-encode stage: jax-free conditioning prep. The CPU-serving
    half of prompt handling — tokenize-and-fingerprint the prompts so
    the denoise stage (and the hive's dedup/cache layers) can key on the
    conditioning identity without re-reading free text. Runs fine on a
    host advertising no chips."""
    prompt = str(kwargs.get("prompt", ""))
    negative = str(kwargs.get("negative_prompt", ""))
    pipeline_config = {"stage": "encode", "model": model_name,
                       "device": device_identifier}
    with Span("encode", pipeline_config.setdefault("timings", {})):
        doc = {
            "model_name": model_name,
            "prompt": prompt,
            "negative_prompt": negative,
            "prompt_sha256": hashlib.sha256(
                prompt.encode("utf-8")).hexdigest(),
            "negative_sha256": hashlib.sha256(
                negative.encode("utf-8")).hexdigest(),
            # whitespace tokens: an honest size hint, not a model vocab
            "tokens_estimate": len(prompt.split()),
        }
        payload = json.dumps(doc, sort_keys=True).encode("utf-8")
    artifacts = {
        "conditioning": {
            "blob": base64.b64encode(payload).decode("ascii"),
            "content_type": "application/json",
            "sha256_hash": hashlib.sha256(payload).hexdigest(),
        }
    }
    return artifacts, pipeline_config


def decode_callback(device_identifier: str, model_name: str, **kwargs):
    """Decode/postprocess stage: the host-side tail of the monolithic
    diffusion callback — NSFW check, grid composite, encode — applied to
    the predecessor's raw rows. Package-identical to what the single
    path produces from the same pixels."""
    content_type = kwargs.pop("content_type", "image/jpeg")
    outputs = kwargs.pop("outputs", ["primary"])
    inputs = kwargs.pop("stage_inputs", [])
    images = stage_images(inputs)
    pipeline_config = {"stage": "decode", "model": model_name,
                       "device": device_identifier, "rows": len(images)}
    from ..pipelines.safety import flag_images

    with Span("decode", pipeline_config.setdefault("timings", {})):
        nsfw, checked = flag_images(images)
        pipeline_config["nsfw"] = nsfw
        pipeline_config["nsfw_checked"] = checked
        processor = OutputProcessor(outputs, content_type)
        processor.add_outputs(images)
        results = processor.get_results()
    return results, pipeline_config


def upscale_stage_callback(device_identifier: str, model_name: str, **kwargs):
    """Upscale stage: the learned sd-x2 latent upscaler as its own
    leased chip stage (the monolithic path chains it inside one pass).
    Consumes the denoise stage's raw rows, emits raw rows for decode.
    Missing upscaler weights degrade to a recorded 2x resize — parity
    with the monolithic path's fallback policy: never fail a job over
    an auxiliary stage."""
    inputs = kwargs.pop("stage_inputs", [])
    rng = kwargs.pop("rng", None)
    chipset = kwargs.pop("chipset", None)
    params = kwargs.get("parameters") or {}
    tiny = bool(kwargs.pop("test_tiny_model", False)
                or (isinstance(params, dict)
                    and params.get("test_tiny_model")))
    if tiny:
        from .diffusion import _tiny_stand_in

        model_name = _tiny_stand_in(model_name)
    images = stage_images(inputs)
    pipeline_config = {"stage": "upscale", "model": model_name,
                       "rows": len(images)}
    timings = pipeline_config.setdefault("timings", {})
    upscaler = None
    try:
        from ..registry import get_pipeline
        from ..pipelines.upscale import upscaler_name_for
        from ..weights import MissingWeightsError

        try:
            upscaler = get_pipeline(
                upscaler_name_for(model_name),
                pipeline_type="StableDiffusionLatentUpscalePipeline",
                chipset=chipset,
            )
        except MissingWeightsError:
            upscaler = None
    except Exception:  # registry trouble: the resize fallback still serves
        upscaler = None
    with Span("upscale", timings):
        if upscaler is not None:
            images = upscaler.upscale(
                list(images),
                prompt=str(kwargs.get("prompt", "")),
                negative_prompt=str(kwargs.get("negative_prompt", "")),
                rng=rng,
            )
        else:
            images = [
                im.resize((im.width * 2, im.height * 2),
                          Image.Resampling.LANCZOS)
                for im in images
            ]
            pipeline_config["upscaler"] = "resize-fallback"
    pipeline_config["upscaled"] = True
    return {"raw": pack_raw(images)}, pipeline_config
