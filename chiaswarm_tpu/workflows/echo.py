"""Echo workload: renders the prompt as an image artifact.

A hermetic diagnostic workflow with no model dependency — used by the
integration tests to exercise the full worker loop (hive poll -> dispatch ->
chip slice -> artifact -> result upload) and usable in production as a
liveness probe. No reference analog (the reference is only testable against
a live hive + GPU; SURVEY §4).
"""

from __future__ import annotations

from ..post_processors.output_processor import OutputProcessor, image_from_text


def echo_callback(device_identifier: str, model_name: str, **kwargs):
    prompt = kwargs.get("prompt", "")
    content_type = kwargs.get("content_type", "image/jpeg")
    size = (kwargs.get("width", 512), kwargs.get("height", 512))

    processor = OutputProcessor(
        kwargs.get("outputs", ["primary"]), content_type
    )
    processor.add_outputs([image_from_text(f"echo: {prompt}", size)])

    pipeline_config = {"echo": True, "device": device_identifier, "model": model_name}
    return processor.get_results(), pipeline_config
