"""Stitch workload: composite prior jobs' images into a mosaic + image map.

Behavior parity with reference swarm/toolbox/stitch.py:12-100: lays out the
input images in a near-square grid of uniform tiles, returns the mosaic as
the primary artifact plus an HTML-image-map style metadata list locating each
source job's tile, so the hive UI can make regions clickable.
"""

from __future__ import annotations

import math

from PIL import Image

from ..post_processors.output_processor import OutputProcessor

TILE = 256


def stitch_callback(device_identifier: str, model_name: str, **kwargs):
    images: list[Image.Image] = kwargs["images"]
    jobs: list[dict] = kwargs.get("jobs", [])
    content_type = kwargs.get("content_type", "image/jpeg")

    if not images:
        raise ValueError("stitch requires at least one input image")

    cols = math.ceil(math.sqrt(len(images)))
    rows = math.ceil(len(images) / cols)

    mosaic = Image.new("RGB", (cols * TILE, rows * TILE))
    image_map = []
    for i, image in enumerate(images):
        tile = image.convert("RGB").copy()
        tile.thumbnail((TILE, TILE), Image.Resampling.LANCZOS)
        x, y = (i % cols) * TILE, (i // cols) * TILE
        # center the tile in its cell
        mosaic.paste(tile, (x + (TILE - tile.width) // 2, y + (TILE - tile.height) // 2))
        region = {
            "coords": [x, y, x + TILE, y + TILE],
            "shape": "rect",
        }
        if i < len(jobs):
            region["job_id"] = jobs[i].get("id")
            region["href"] = jobs[i].get("resultUri")
        image_map.append(region)

    processor = OutputProcessor(kwargs.get("outputs", ["primary"]), content_type)
    processor.add_outputs([mosaic])
    return processor.get_results(), {
        "image_map": image_map,
        "rows": rows,
        "cols": cols,
    }
