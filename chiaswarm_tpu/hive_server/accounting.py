"""Per-tenant usage accounting: who consumed which chip-seconds.

The ROADMAP's planet-scale front-door item says it plainly: at
millions-of-users traffic "per-tenant accounting becomes load-bearing"
— weighted-fair queueing, quota-aware shedding, and LoRA-aware dispatch
(SwiftDiffusion, arXiv 2407.02031: per-user add-on modules dominate
serving cost) all presuppose the hive can ATTRIBUTE cost per submitter.
This module is that attribution.

Design: the ledger is **pure derived state**. Every settled job's record
already carries, WAL-journaled verbatim with its settle event, the raw
material attribution needs — the job dict (tenant field), the result
envelope (``pipeline_config.timings`` stage spans, ``embed_cache``
counters, spooled artifact byte counts) and the wall-stamped timeline
(dispatch/settle instants, gang size). So usage is computed *from the
records*, never separately persisted: crash recovery, WAL compaction,
and standby replication all reproduce the ledger for free because they
reproduce the records — the same trick the trace endpoint uses. The
totals are therefore crash-consistent bit for bit (integer micro-units
internally, so summation order across live-vs-replay cannot perturb a
single bit; pinned by the ``usage_survives_restart`` chaos scenario).

Attribution per settled job:

- ``tenant``       the job's ``tenant`` field (default ``"anon"``);
- ``chip_seconds`` the worker's whole-pass ``job_s`` stage span (the
                   authoritative chip occupancy, stamped by ChipSet
                   around the pass), else the sum of per-stage spans,
                   else — the **fallback** — the wall-clock delta from
                   the last dispatch to the settle in the timeline,
                   counted in ``swarm_hive_usage_fallback_total`` so a
                   legacy worker's envelopes are never silently dropped
                   from the tenant's bill;
- ``rows``         image rows (coalesce.job_rows);
- ``coalesce_saved_seconds`` the chip time sharing a pass saved:
                   chip_s * (group-1)/group, group = coalesced batch
                   size from the envelope trace (``coalesced_with``) or
                   the dispatch gang size;
- ``embed_cache_hits`` prompt-embedding rows served from cache during
                   the job's pass (stamped by the pipeline);
- ``artifact_bytes`` decoded artifact payload bytes (spool refs carry
                   exact counts; inline blobs are estimated from the
                   base64 length);
- ``flops``        the job's own analytic UNet FLOPs from the envelope's
                   ``pipeline_config.cost`` stamp (ISSUE 17) — already
                   an integer at the source, so per-tenant sums equal
                   the sum of envelope stamps exactly; surfaced on the
                   wire as both ``flops`` and ``petaflops``
                   ("petaflops served" next to chip-seconds).

Served at ``GET /api/usage`` and ``GET /api/tenants/{id}/usage``, and
exported as ``swarm_hive_tenant_chip_seconds_total{tenant}`` /
``swarm_hive_tenant_rows_total{tenant}`` gauges with the top-K tenants
by chip-seconds named and the rest folded into ``other``
(``hive_tenant_topk``) so tenant cardinality can never blow up the
metrics surface.
"""

from __future__ import annotations

from .. import telemetry
from ..coalesce import job_rows

TENANT_DEFAULT = "anon"
# the fold bucket for tenants past the top-K gauge cut; a real tenant
# named "other" folds into it too (documented, bounded > perfect)
TENANT_OTHER = "other"

# timings keys that are waiting, not chip work
_NON_CHIP_KEYS = frozenset({"queue_wait_s", "submit_s"})

_FALLBACK = telemetry.counter(
    "swarm_hive_usage_fallback_total",
    "Settled jobs attributed by wall-clock dispatch-to-settle because "
    "the envelope carried no pipeline_config.timings (older worker, or "
    "a parked-then-requeued outbox envelope) — billed approximately "
    "instead of silently dropped from the tenant ledger",
)
_TENANT_CHIP_S = telemetry.gauge(
    "swarm_hive_tenant_chip_seconds_total",
    "Chip-seconds attributed to each tenant's settled jobs (top-K by "
    "cost; the rest fold into tenant=\"other\")",
    ("tenant",),
)
_TENANT_ROWS = telemetry.gauge(
    "swarm_hive_tenant_rows_total",
    "Image rows attributed to each tenant's settled jobs (top-K by "
    "chip-seconds; the rest fold into tenant=\"other\")",
    ("tenant",),
)
_TENANT_FLOPS = telemetry.gauge(
    "swarm_hive_tenant_flops_total",
    "Analytic UNet FLOPs attributed to each tenant's settled jobs from "
    "the envelopes' pipeline_config.cost stamps (top-K by chip-seconds; "
    "the rest fold into tenant=\"other\")",
    ("tenant",),
)

# label values currently exported, so a tenant dropping out of the
# top-K retires its series instead of freezing at its last value
_exported_tenants: set[str] = set()


def tenant_of(job: dict) -> str:
    """The submitter a job bills to: its `tenant` field (set from the
    submit body; a missing/blank/non-string value is the shared
    anonymous tenant). Reading the job dict — which rides the WAL admit
    event verbatim — is what makes attribution replay- and
    replication-safe with no extra persistence."""
    if not isinstance(job, dict):
        return TENANT_DEFAULT
    tenant = job.get("tenant")
    if isinstance(tenant, str) and tenant.strip():
        return tenant.strip()
    return TENANT_DEFAULT


def _as_float(value) -> float | None:
    try:
        v = float(value)
    except (TypeError, ValueError):
        return None
    return v if v >= 0 else None


def chip_seconds_of(timings) -> float | None:
    """Chip occupancy from a settled envelope's stage timings: ``job_s``
    (the ChipSet's whole-pass wall clock, which every per-stage span
    nests inside) when present, else the per-stage sum excluding the
    waiting stages. None = no usable timings (the caller falls back to
    hive wall clock and counts it)."""
    if not isinstance(timings, dict):
        return None
    job_s = _as_float(timings.get("job_s"))
    if job_s is not None:
        return job_s
    total = 0.0
    seen = False
    for key, value in timings.items():
        if not (isinstance(key, str) and key.endswith("_s")):
            continue
        if key in _NON_CHIP_KEYS:
            continue
        v = _as_float(value)
        if v is not None:
            total += v
            seen = True
    return total if seen else None


def _pipeline_config(result) -> dict:
    if isinstance(result, dict) and isinstance(
            result.get("pipeline_config"), dict):
        return result["pipeline_config"]
    return {}


def _coalesce_group(record) -> int:
    """How many jobs shared the pass that served this record: the
    worker-echoed ``coalesced_with`` count from the envelope trace when
    present (the worker knows what actually coalesced, linger merges
    included), else the dispatch-time gang size from the timeline."""
    trace = _pipeline_config(record.result).get("trace")
    if isinstance(trace, dict):
        mates = trace.get("coalesced_with")
        if isinstance(mates, int) and mates >= 0:
            return mates + 1
    for event in reversed(getattr(record, "timeline", ()) or ()):
        if isinstance(event, dict) and event.get("event") == "dispatch":
            size = event.get("gang_size")
            if isinstance(size, int) and size >= 1:
                return size
            break
    return 1


def _fallback_wall_s(record) -> float:
    """Wall-clock dispatch-to-settle from the journaled timeline — the
    approximation a timings-free envelope is billed at."""
    dispatched = settled = None
    for event in getattr(record, "timeline", ()) or ():
        if not isinstance(event, dict):
            continue
        if event.get("event") == "dispatch":
            dispatched = _as_float(event.get("wall"))
        elif event.get("event") == "settle":
            settled = _as_float(event.get("wall"))
    if dispatched is None or settled is None:
        return 0.0
    return max(settled - dispatched, 0.0)


def _artifact_bytes(result) -> int:
    total = 0
    artifacts = result.get("artifacts") if isinstance(result, dict) else None
    if not isinstance(artifacts, dict):
        return 0
    for art in artifacts.values():
        if not isinstance(art, dict):
            continue
        if isinstance(art.get("bytes"), int):
            total += max(art["bytes"], 0)
        elif isinstance(art.get("blob"), str):
            # inline base64 (spool disabled or failed): decoded size
            total += len(art["blob"]) * 3 // 4
    return total


def job_usage(record) -> dict | None:
    """One settled record's attribution, in integer micro-units (so
    per-tenant sums are independent of summation order — live settle
    order vs WAL-replay record order must produce bit-identical
    totals). None for anything not settled `done` with a result."""
    if getattr(record, "state", None) != "done":
        return None
    if not isinstance(record.result, dict):
        return None
    cfg = _pipeline_config(record.result)
    chip_s = chip_seconds_of(cfg.get("timings"))
    fallback = chip_s is None
    if fallback:
        chip_s = _fallback_wall_s(record)
    chip_us = int(round(chip_s * 1e6))
    group = _coalesce_group(record)
    embed = cfg.get("embed_cache")
    hits = 0
    if isinstance(embed, dict) and isinstance(embed.get("hits"), int):
        hits = max(embed["hits"], 0)
    # adapter-operand residency (ISSUE 16): device bytes the worker did
    # NOT re-upload because the job's stacked LoRA operands were already
    # resident. Pass-level like embed_cache: a coalesced group's
    # envelopes each carry the shared pass figure.
    operand = cfg.get("operand_cache")
    operand_saved = 0
    if isinstance(operand, dict) and isinstance(
            operand.get("bytes_saved"), int):
        operand_saved = max(operand["bytes_saved"], 0)
    # serving-path cost stamp (ISSUE 17): the job's OWN integer FLOPs —
    # per-job at the source even for coalesced passes, so tenant sums
    # and envelope sums agree exactly. An old envelope with no stamp
    # bills 0 FLOPs (chip-seconds still cover it).
    cost = cfg.get("cost")
    flops = 0
    if isinstance(cost, dict) and isinstance(cost.get("flops"), int):
        flops = max(cost["flops"], 0)
    return {
        "tenant": tenant_of(record.job),
        "chip_us": chip_us,
        "rows": job_rows(record.job),
        "coalesced": group > 1,
        "saved_us": chip_us * (group - 1) // max(group, 1),
        "embed_cache_hits": hits,
        "artifact_bytes": _artifact_bytes(record.result),
        "operand_saved_bytes": operand_saved,
        "flops": flops,
        "fallback": fallback,
    }


_FIELDS = ("jobs", "chip_us", "rows", "coalesced_jobs", "saved_us",
           "embed_cache_hits", "artifact_bytes",
           "operand_upload_bytes_saved", "flops", "fallback_jobs")


def zero_bucket() -> dict:
    return {field: 0 for field in _FIELDS}


def usage_summary(records) -> dict:
    """Aggregate every settled record into per-tenant + total buckets
    (integer micro-units; `render_usage` turns them wire-ready). Pure —
    derived state, recomputed on demand from whatever records the
    process holds (history pruning bounds the window, exactly as it
    bounds GET /api/jobs/{id})."""
    tenants: dict[str, dict] = {}
    totals = zero_bucket()
    for record in records:
        usage = job_usage(record)
        if usage is None:
            continue
        bucket = tenants.setdefault(usage["tenant"], zero_bucket())
        for dst in (bucket, totals):
            dst["jobs"] += 1
            dst["chip_us"] += usage["chip_us"]
            dst["rows"] += usage["rows"]
            dst["coalesced_jobs"] += 1 if usage["coalesced"] else 0
            dst["saved_us"] += usage["saved_us"]
            dst["embed_cache_hits"] += usage["embed_cache_hits"]
            dst["artifact_bytes"] += usage["artifact_bytes"]
            dst["operand_upload_bytes_saved"] += usage["operand_saved_bytes"]
            dst["flops"] += usage["flops"]
            dst["fallback_jobs"] += 1 if usage["fallback"] else 0
    return {"tenants": tenants, "totals": totals}


def render_bucket(bucket: dict) -> dict:
    """One tenant's (or the totals') wire shape: micro-units become
    rounded seconds, counters stay integers. Field set pinned by the
    protocol-conformance suite."""
    return {
        "jobs": bucket["jobs"],
        "chip_seconds": round(bucket["chip_us"] / 1e6, 3),
        "rows": bucket["rows"],
        "coalesced_jobs": bucket["coalesced_jobs"],
        "coalesce_saved_seconds": round(bucket["saved_us"] / 1e6, 3),
        "embed_cache_hits": bucket["embed_cache_hits"],
        "artifact_bytes": bucket["artifact_bytes"],
        "operand_upload_bytes_saved": bucket["operand_upload_bytes_saved"],
        # FLOPs stay the exact integer (envelope-sum reconciliation);
        # petaflops is the human-scale twin for billing surfaces
        "flops": bucket["flops"],
        "petaflops": round(bucket["flops"] / 1e15, 6),
        "fallback_jobs": bucket["fallback_jobs"],
    }


def render_usage(summary: dict, topk: int = 0) -> dict:
    """The GET /api/usage payload: every tenant rendered (the JSON
    surface is for operators and billing — it is not cardinality-bound
    the way the metrics are), sorted by chip-seconds, plus the grand
    totals and the top-K cut the gauges use. The one assembly both the
    real hive and the test fake serve, so the conformance-pinned reply
    shape has a single source of truth."""
    tenants = summary["tenants"]
    ordered = sorted(tenants.items(),
                     key=lambda kv: (-kv[1]["chip_us"], kv[0]))
    return {
        "tenants": {t: render_bucket(b) for t, b in ordered},
        "totals": render_bucket(summary["totals"]),
        "top": [t for t, _ in ordered[:topk]] if topk > 0
               else [t for t, _ in ordered],
        "settled_jobs": summary["totals"]["jobs"],
        "topk": topk,
    }


def render_tenant_reply(summary: dict, tenant: str) -> dict:
    """The GET /api/tenants/{id}/usage payload (shared by the real hive
    and the test fake): one tenant's bucket, zeroed when the retained
    history holds nothing for it."""
    bucket = summary["tenants"].get(tenant)
    return {
        "tenant": tenant,
        "known": bucket is not None,
        "usage": render_bucket(
            bucket if bucket is not None else zero_bucket()),
    }


def refresh_tenant_metrics(summary: dict, topk: int) -> None:
    """Re-export the per-tenant gauges from a fresh summary: the top-K
    tenants by chip-seconds keep their own label value, everything else
    folds into ``other``, and label values that dropped out of the cut
    are REMOVED (a gauge is a statement about now, and a stale tenant
    series would misreport forever)."""
    global _exported_tenants
    ordered = sorted(summary["tenants"].items(),
                     key=lambda kv: (-kv[1]["chip_us"], kv[0]))
    topk = max(int(topk), 1)
    named = ordered[:topk]
    folded = ordered[topk:]
    exported: set[str] = set()
    for tenant, bucket in named:
        label = TENANT_OTHER if tenant == TENANT_OTHER else tenant
        _TENANT_CHIP_S.set(round(bucket["chip_us"] / 1e6, 3), tenant=label)
        _TENANT_ROWS.set(bucket["rows"], tenant=label)
        _TENANT_FLOPS.set(bucket["flops"], tenant=label)
        exported.add(label)
    if folded or TENANT_OTHER in exported:
        chip_us = sum(b["chip_us"] for _, b in folded)
        rows = sum(b["rows"] for _, b in folded)
        flops = sum(b["flops"] for _, b in folded)
        if TENANT_OTHER in exported:
            # a literal "other" tenant merged with the fold bucket
            chip_us += sum(b["chip_us"] for t, b in named
                           if t == TENANT_OTHER)
            rows += sum(b["rows"] for t, b in named if t == TENANT_OTHER)
            flops += sum(b["flops"] for t, b in named if t == TENANT_OTHER)
        _TENANT_CHIP_S.set(round(chip_us / 1e6, 3), tenant=TENANT_OTHER)
        _TENANT_ROWS.set(rows, tenant=TENANT_OTHER)
        _TENANT_FLOPS.set(flops, tenant=TENANT_OTHER)
        exported.add(TENANT_OTHER)
    for stale in _exported_tenants - exported:
        _TENANT_CHIP_S.remove(tenant=stale)
        _TENANT_ROWS.remove(tenant=stale)
        _TENANT_FLOPS.remove(tenant=stale)
    _exported_tenants = exported


def note_fallback() -> None:
    """Count one live fallback attribution (never called on replay —
    the counter, like every hive counter, measures this process's own
    observations, not reconstructed history)."""
    _FALLBACK.inc()
