"""One clock, two faces: monotonic for intervals, wall for persistence.

The hive measures every interval — queue wait, lease deadlines, affinity
hold windows, worker liveness — with ``time.monotonic()``, which is the
right tool exactly until a value has to survive the process: a monotonic
reading is an offset from an arbitrary per-process origin, so a
persisted ``submitted_at`` or ``expires_at`` is meaningless after a
restart. The pre-WAL code had this bug latent (nothing persisted yet,
so nothing broke); the journal makes it load-bearing.

``HiveClock`` pins the convention in one place:

- **intervals** are always monotonic arithmetic (``mono()``), immune to
  NTP steps and operator ``date`` changes;
- **persistence** always goes through ``wall_from_mono`` on the way to
  disk and ``mono_from_wall`` on the way back, which re-anchors a stored
  wall-clock instant into the *current* process's monotonic timebase so
  interval arithmetic keeps working across the restart (to within
  wall-clock accuracy — the only timebase two processes share).

The two source functions are injectable so tests can simulate a restart
(new monotonic origin, continuous wall clock) without sleeping or
monkey-patching the ``time`` module.
"""

from __future__ import annotations

import time
from typing import Callable


class HiveClock:
    __slots__ = ("_mono", "_wall")

    def __init__(self, mono: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time):
        self._mono = mono
        self._wall = wall

    def mono(self) -> float:
        """Now, in the process-local monotonic timebase (intervals)."""
        return self._mono()

    def wall(self) -> float:
        """Now, as a wall-clock epoch instant (persistence)."""
        return self._wall()

    def wall_from_mono(self, mono_instant: float) -> float:
        """Translate a monotonic instant into a wall-clock epoch value
        fit for persistence."""
        return self._wall() - (self._mono() - mono_instant)

    def mono_from_wall(self, wall_instant: float) -> float:
        """Re-anchor a persisted wall-clock instant into this process's
        monotonic timebase. The result can be negative (an instant before
        this process's monotonic origin) — it is an arithmetic anchor,
        never a value to sleep until."""
        return self._mono() - (self._wall() - wall_instant)


# the process-default clock every hive component shares unless a test
# injects its own; sharing matters — mixing two monotonic origins in one
# interval subtraction is exactly the bug this module exists to prevent
CLOCK = HiveClock()
